"""Device-mesh construction for the sharded sweep.

The reference's only parallelism is a numba ``prange`` thread pool over DM
trials (``pulsarutils/dedispersion.py:174-181``).  The TPU-native design
maps that onto a 2-D ``jax.sharding.Mesh``:

* ``"dm"`` axis — embarrassingly-parallel trial sharding (the prange
  equivalent; no communication);
* ``"chan"`` axis — channel sharding of the input filterbank, with a
  ``psum`` over partial dedispersed sums (the "tensor-parallel" analogue,
  collective rides ICI);
* a separate ``"time"`` axis mesh drives the ring-halo streaming path
  (:mod:`.stream`) — the sequence-parallel analogue for 1M+-sample chunks.

Multi-host note: all construction goes through ``jax.devices()``, so under
``jax.distributed`` initialisation the same code lays the mesh over every
host's local devices and the collectives ride ICI/DCN as laid out by XLA.
"""

from __future__ import annotations

import numpy as np


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across JAX versions — ONE call site owns the API
    drift so every mesh kernel builder stays version-agnostic:

    * new API (``jax.shard_map``, ``check_vma=``) when present;
    * else the long-stable ``jax.experimental.shard_map.shard_map``
      (``check_rep=`` — the same lint under its older name).
    """
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map

    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=check_vma)


def make_mesh(shape=None, axis_names=("dm", "chan"), devices=None):
    """Build a ``Mesh`` over the available devices.

    ``shape=None`` puts every device on the first axis.  ``shape`` entries
    may include ``-1`` (inferred).  Total must divide the device count; the
    mesh uses the first ``prod(shape)`` devices.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(jax.devices()) if devices is None else list(devices)
    ndev = len(devices)
    if shape is None:
        shape = (ndev,) + (1,) * (len(axis_names) - 1)
    shape = list(shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = ndev // known
    total = int(np.prod(shape))
    if total > ndev:
        raise ValueError(f"mesh shape {tuple(shape)} needs {total} devices, "
                         f"have {ndev}")
    grid = np.array(devices[:total]).reshape(shape)
    return Mesh(grid, tuple(axis_names))


def balanced_2d_mesh(n_devices=None):
    """A (dm, chan) mesh that puts most parallelism on the free ``dm`` axis
    but keeps a non-trivial ``chan`` dimension when enough devices exist
    (so the channel-psum path is actually exercised)."""
    import jax

    ndev = n_devices if n_devices is not None else len(jax.devices())
    chan = 2 if ndev % 2 == 0 and ndev >= 4 else 1
    return make_mesh((ndev // chan, chan), ("dm", "chan"))


def pad_to_multiple(array, axis, multiple, mode="edge"):
    """Pad ``array`` along ``axis`` so its length is a multiple.

    Returns ``(padded, original_length)``.  Used to make trial/channel
    counts divisible by the mesh axis sizes (padded trials are duplicates,
    padded channels are zeros — both exact no-ops for the search result
    after slicing back).
    """
    n = array.shape[axis]
    pad = (-n) % multiple
    if pad == 0:
        return array, n
    widths = [(0, 0)] * array.ndim
    widths[axis] = (0, pad)
    kwargs = {} if mode != "constant" else {"constant_values": 0}
    return np.pad(array, widths, mode=mode, **kwargs), n


def fetch_global(arr):
    """Global (possibly multi-process-sharded) jax array -> host numpy.

    On a multi-process cluster a globally-sharded array spans devices
    the local process cannot address and plain ``np.asarray`` raises —
    found live by ``tools/multihost_live.py`` (round 5).
    ``process_allgather`` assembles the full value on every host;
    single-process keeps the zero-copy fetch.  Safe on plain
    numpy/host inputs.
    """
    import numpy as np

    import jax

    if isinstance(arr, jax.Array) and jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr,
                                                            tiled=True))
    return np.asarray(arr)
