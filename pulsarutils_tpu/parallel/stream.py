"""Streaming long-series search: overlap-save chunking and the
time-sharded ring dedispersion step.

The reference's "long-context" mechanism is a host-side 50%-overlap chunk
loop sized by the physics — chunk length = band-crossing delay at ``dmmax``,
hop = half the chunk (reference ``pulsarutils/clean.py:296-301,318``) — so
every pulse is fully contained, un-wrapped, in at least one chunk.  This
module keeps that overlap-save logic but makes it device-resident:

* :func:`plan_chunks` — the physics-driven chunk/hop/resample sizing rule;
* :func:`stream_search` — jit-once, stream-many driver: every chunk reuses
  one compiled search executable; JAX's async dispatch overlaps the
  host->device copy of chunk ``k+1`` with the compute of chunk ``k``
  (double buffering for free);
* :func:`ring_dedisperse` — the sequence-parallel analogue: the time axis
  is sharded over a ``"time"`` mesh axis and each device pulls a halo of
  ``max_offset`` samples from its right neighbour with ONE
  ``lax.ppermute`` per step, reproducing the exact global circular-shift
  semantics of :func:`~pulsarutils_tpu.ops.dedisperse.dedisperse` on a
  sequence no single device could hold.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from ..ops.plan import delta_delay, dm_broadening
from ..ops.search import dedispersion_search
from ..tuning.geometry import PLAN_CACHE_SIZE, counted_plan_cache
from ..utils.logging_utils import budget_bucket


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """Physics-driven streaming geometry (reference ``clean.py:296-316``)."""
    step: int            # samples per chunk
    hop: int             # chunk advance (step // 2 -> 50% overlap)
    resample: int        # time-rebin factor applied to each chunk
    sample_time: float   # post-resample sample time


def plan_chunks(nsamples, sample_time, dmmin, dmmax, start_freq, stop_freq,
                foff, chunk_length=None, new_sample_time=None, min_step=128):
    """Choose chunk size / hop / resampling from the search physics.

    * chunk length defaults to the band-crossing delay at ``dmmax`` and the
      chunk holds twice that, so a pulse entering at any phase of the hop
      is fully contained once (reference ``clean.py:296-301``);
    * data are resampled so the new sample time is ~1/10 of the minimum
      intra-channel DM smearing (reference ``clean.py:304-316``).
    """
    if chunk_length is None:
        chunk_length = delta_delay(dmmax, start_freq, stop_freq)
    step = max(int(chunk_length / sample_time) * 2, min_step)

    dm_dt = dm_broadening(dmmin, start_freq, abs(foff))
    if new_sample_time is None:
        new_sample_time = max(dm_dt / 10, sample_time)
    ratio = new_sample_time / sample_time
    resample = int(np.rint(ratio)) if ratio >= 2 else 1

    if step >= 1024 * resample:
        # round the chunk up so the POST-RESAMPLE time axis is a
        # multiple of the FDMT/Pallas tile size: a non-tile-divisible
        # searched axis forces the TPU transform to zero-pad (slower,
        # and it disables the hybrid's noise certificate — the pad
        # breaks the circular-gather model its soundness bound
        # assumes).  A slightly larger chunk keeps the physics
        # guarantee (chunk >= 2x the band-crossing delay).
        quantum = 1024 * resample
        step = -(-step // quantum) * quantum
    return ChunkPlan(step=step, hop=step // 2, resample=resample,
                     sample_time=resample * sample_time)


def iter_chunk_starts(nsamples, plan, tmin=0, sample_time=None):
    """Chunk start indices with 50% overlap, skipping a final fragment
    shorter than half a chunk (reference ``clean.py:318-325``) — and,
    round 5, a final fragment *wholly contained* in the previous chunk
    (``istart - hop + step >= nsamples``): it re-reads data the previous
    full-length chunk already searched with MORE context (the short time
    axis only worsens circular-wrap artifacts) while costing a complete
    extra compile set for the odd shape (~minutes on the 1M-sample
    configs — measured in the round-5 survey rehearsal)."""
    prev = None
    for istart in range(0, nsamples, plan.hop):
        if sample_time is not None and istart * sample_time < tmin:
            continue
        if min(plan.step, nsamples - istart) < plan.hop:
            continue
        if (prev is not None and istart - plan.hop == prev
                and prev + plan.step >= nsamples):
            continue
        prev = istart
        yield istart


def _iter_lookahead(chunks):
    """Pull-lazy iteration with exactly ONE chunk of lookahead.

    ``stream_search`` must consume its producer as a true iterator
    (ISSUE 19: a live feed cannot hold an observation in RAM), but a
    strict lock-step pull would serialize chunk production behind the
    device search.  Pre-pulling a single item keeps the classic
    double-buffer overlap — the producer builds chunk ``k+1`` while
    chunk ``k`` computes — with bounded memory by construction: at most
    two produced-but-unconsumed chunks exist at any moment (the pending
    slot plus the producer's in-flight ``next``).  A list producer
    degrades gracefully (iteration order and results are identical).
    """
    it = iter(chunks)
    try:
        pending = next(it)
    except StopIteration:
        return
    for item in it:
        yield pending
        pending = item
    yield pending


def stream_search(chunks, dmmin, dmmax, start_freq, bandwidth, sample_time,
                  *, backend="jax", snr_threshold=6.0, trial_dms=None,
                  dm_block=None, chan_block=None, budget=None, mesh=None,
                  kernel="auto", dispatch_timeout=None, dispatch_retries=0,
                  skip_failed=False, health=None, http_port=None,
                  http_host="127.0.0.1", canary=None,
                  plane_consumer=None, lineage=None, push=None):
    """Search an iterable of ``(istart, (nchan, step))`` chunks.

    ``chunks`` is consumed as a true lazy iterator with one chunk of
    lookahead (ISSUE 19): a generator producer — a file reader or the
    live-ingest assembler — is pulled at most one chunk ahead of the
    chunk being searched, so memory stays bounded by two chunks no
    matter how long the observation runs, while production still
    overlaps compute.  Lists keep working unchanged (and still
    provide the progress total via ``len``).

    One compiled executable serves every distinct chunk shape; interior
    chunks share one shape by construction, so at most one extra compile
    happens for a ragged final chunk (which the reference also processes,
    ``clean.py:319-325``).  Returns a list of per-chunk hits:
    ``(istart, table, best_row)`` for chunks whose best S/N clears
    ``snr_threshold`` (the reference's candidate criterion,
    ``clean.py:349``), plus the full tables for diagnostics.

    ``mesh`` (with ``backend="jax"``) routes every chunk through the
    sharded multi-device searches, the same routing rule as the full
    pipeline driver (``kernel="hybrid"`` -> the fused
    :func:`~.sharded_fdmt.sharded_hybrid_search` — one ``shard_map``
    dispatch per typical hit chunk, round 6 — ``"fdmt"`` -> the
    DM-sliced tree, anything else -> the ``(dm, chan)`` exact sweep).
    The sharded searches re-derive the chunk-geometry plan from a
    per-geometry cache, so interior chunks share one compiled program
    AND one host-side offset table.

    ``budget`` (a
    :class:`~pulsarutils_tpu.utils.logging_utils.BudgetAccountant`)
    opens one chunk budget per chunk: the search's dispatch/readback
    buckets land per chunk — on the mesh route too, attributed by the
    sharded searches exactly as single-device — and a compile observed
    on any chunk after the first is flagged as a retrace (the
    one-executable contract above is *checked*, not assumed — round 6).

    Robustness (ISSUE 4 — defaults reproduce the pre-hardening path):
    ``dispatch_timeout`` bounds each chunk's search on a watchdog
    thread (a wedged dispatch was an infinite stall),
    ``dispatch_retries`` re-attempts a failed/timed-out chunk, and
    ``skip_failed=True`` drops a chunk that still fails (logged +
    ``putpu_stream_chunks_failed_total``) instead of killing the whole
    stream.  ValueError/TypeError always propagate, even under
    ``skip_failed`` — they are treated as configuration errors (which
    would fail identically on every chunk), so a producer feeding
    malformed per-chunk arrays must validate shapes upstream rather
    than rely on containment.

    Live surface (ISSUE 5, same contract as ``search_by_chunks``):
    ``http_port`` serves ``/metrics`` / ``/healthz`` / ``/progress``
    for the duration of the stream (``http_host`` picks the bind
    address — loopback by default, ``"0.0.0.0"`` to let a remote
    Prometheus scrape job or fleet probe reach it); ``health`` accepts
    a caller-owned
    :class:`~pulsarutils_tpu.obs.health.HealthEngine` (created
    internally when ``http_port`` is set), updated per chunk with wall
    time, candidate rate and containment events; ``canary`` (a
    :class:`~pulsarutils_tpu.obs.canary.CanaryController` or a bare
    rate float) injects synthetic pulses into selected chunks before
    the search and matches them against the emitted tables — canary
    best rows are excluded from the returned ``hits``, and when the
    canary outranks a genuine weaker pulse in the same chunk that
    pulse's row is promoted as the chunk's ``best_row`` instead.  All
    are ``None``-gated: off means the pre-PR code path,
    byte-identical.

    Packed low-bit chunks (ISSUE 11): a chunk may be a
    :class:`~pulsarutils_tpu.io.lowbit.PackedFrames` instead of a float
    block — the RAW 1/2/4-bit bytes ship to the device and the
    bit-unpack runs inside the search jit (integer sweep accumulation
    where exact), cutting host->device traffic 8-16x with candidates
    byte-identical to the host-unpacked run (bench config 15 gates the
    identity and the ``putpu_bytes_uploaded_total`` ratio).  Canaries
    are quantized into the packed codes on the same seam
    (:meth:`~pulsarutils_tpu.obs.canary.CanaryController.
    maybe_inject_packed`), so recall is measured on packed runs too.

    ``plane_consumer`` (ISSUE 13, same contract as
    ``search_by_chunks``): a ``fn(istart, plane, table)`` callable
    that forces plane capture on every chunk's search and receives the
    dedispersed plane (device array, or a sharded handle on the mesh
    route) before it is dropped — the periodicity accumulation seam.
    ``None`` (default) keeps the pre-seam code path byte-identical.

    ``lineage`` / ``push`` (ISSUE 18, same contract as
    ``search_by_chunks``): lineage stamps each hit with monotone stage
    timestamps and feeds the candidate latency histograms — a stream
    has no persist store, so the hit-emit point is its "persist
    complete" stage and no ``.lineage.json`` doc is written; ``push``
    (an :class:`~pulsarutils_tpu.obs.push.AlertBroker` or subscriber
    specs) fans hits out to webhook subscribers on a bounded queue
    that can never block this loop.  Canary best rows are excluded
    before the publish site.  Both ``None``-gated, byte-identical off.
    """
    import contextlib
    import json as _json
    import time as _time

    from ..faults import inject as fault_inject
    from ..faults.policy import call_with_deadline
    from ..io.lowbit import PackedFrames
    from ..obs import metrics as _metrics
    from ..obs.canary import CanaryController
    from ..obs.health import HealthEngine
    from ..obs.lineage import LineageRecorder
    from ..obs.push import AlertBroker
    from ..obs.server import start_obs_server
    from ..obs.trace import set_track, span
    from ..resilience import ladder as _ladder
    from ..utils.logging_utils import logger

    # each stream session starts undegraded (OOM descents within the
    # stream are sticky — a measured slowdown; ISSUE 12)
    _ladder.reset()

    @contextlib.contextmanager
    def traced_chunk(istart):
        # budget-less analogue of BudgetAccountant.chunk's tracing: the
        # chunk span AND its nested spans (search, kernel buckets) land
        # on this chunk's own Perfetto track
        with set_track(f"chunk {istart}"):
            with span("chunk", chunk=istart):
                yield

    if budget is not None:
        budget.begin_stream()

    # the plane-consumer seam forces capture; the kwarg is only passed
    # when armed so the seam-off dispatch signature (and its compiled
    # programs) stays byte-identical to the pre-seam driver
    capture_kw = {"capture_plane": True} if plane_consumer is not None \
        else {}

    def run_one(istart, chunk):
        fault_inject.fire("dispatch", chunk=istart, backend=backend)
        if mesh is not None and backend == "jax":
            if kernel == "hybrid":
                from .sharded_fdmt import sharded_hybrid_search

                return sharded_hybrid_search(
                    chunk, dmmin, dmmax, start_freq, bandwidth,
                    sample_time, mesh=mesh, **capture_kw)
            if kernel == "fdmt":
                from .sharded_fdmt import sharded_fdmt_search

                return sharded_fdmt_search(
                    chunk, dmmin, dmmax, start_freq, bandwidth,
                    sample_time, mesh=mesh, **capture_kw)
            from .sharded import sharded_dedispersion_search

            return sharded_dedispersion_search(
                chunk, dmmin, dmmax, start_freq, bandwidth, sample_time,
                mesh=mesh, trial_dms=trial_dms, chan_block=chan_block,
                # the documented consumer contract: a DM-sharded
                # device-resident handle, never an eagerly-gathered
                # host plane (search_by_chunks' mesh seam rule)
                **(dict(capture_kw, plane_handle=True) if capture_kw
                   else {}))
        return dedispersion_search(
            chunk, dmmin, dmmax, start_freq, bandwidth, sample_time,
            backend=backend, trial_dms=trial_dms, dm_block=dm_block,
            chan_block=chan_block, **capture_kw,
            **({} if kernel == "auto" else {"kernel": kernel}))

    def run_guarded(istart, chunk):
        last = None
        attempt = 0
        oom_descents = 0
        budget_attempts = max(int(dispatch_retries), 0) + 1
        while attempt < budget_attempts:
            try:
                return call_with_deadline(lambda: run_one(istart, chunk),
                                          dispatch_timeout)
            except (ValueError, TypeError):
                raise  # deterministic configuration error
            except Exception as exc:  # jax errors share no base class
                last = exc
                if _ladder.is_resource_exhausted(exc) \
                        and oom_descents < 2 * len(_ladder.STEPS):
                    # RESOURCE_EXHAUSTED is not a transient dispatch
                    # fault (ISSUE 12): descend the degradation ladder
                    # — the re-dispatch runs smaller (split trial
                    # passes; unfused mesh hybrid) and byte-identical —
                    # without burning the transient retry budget
                    _ladder.oom_event("stream")
                    _ladder.descend("unfuse" if kernel == "hybrid"
                                    else "split_dm")
                    oom_descents += 1
                    logger.warning(
                        "stream chunk %s hit RESOURCE_EXHAUSTED (%r); "
                        "ladder level %d, re-dispatching smaller",
                        istart, exc, _ladder.level())
                    continue
                attempt += 1
                if attempt < budget_attempts:
                    _metrics.counter("putpu_dispatch_retries_total").inc()
                logger.warning("stream chunk %s search failed (%r); "
                               "%s", istart, exc,
                               "retrying" if attempt < budget_attempts
                               else "giving up")
        raise last

    if canary is not None and not isinstance(canary, CanaryController):
        canary = CanaryController(rate=float(canary))
    if canary is not None and canary.rate <= 0.0:
        canary = None
    if http_port is not None and health is None:
        health = HealthEngine()
    if lineage is True:
        lineage = LineageRecorder(source="stream_search")
    elif not lineage:
        lineage = None          # accept False/0/"" as "off" (CLI flag)
    push_owned = False
    if not push:
        push = None
    elif not isinstance(push, AlertBroker):
        push = AlertBroker(push, health=health)
        push_owned = True

    results = []
    hits = []
    total = len(chunks) if hasattr(chunks, "__len__") else None
    t_run0 = _time.time()

    def _progress_snapshot():
        done = len(results)
        elapsed = _time.time() - t_run0
        rate = done / elapsed if elapsed > 0 and done else None
        doc = {"chunks_done": done, "chunks_total": total,
               "elapsed_s": round(elapsed, 1),
               "eta_s": (round((total - done) / rate, 1)
                         if rate and total is not None else None),
               "hits": len(hits)}
        if canary is not None:
            doc["canary"] = canary.summary()
        return doc

    obs_server = (start_obs_server(http_port, health=health,
                                   progress_fn=_progress_snapshot,
                                   host=http_host, push=push)
                  if http_port is not None else None)

    def _oom_events_total():
        return sum(m.get("value", 0)
                   for m in _metrics.REGISTRY.snapshot()
                   if m.get("name") == "putpu_oom_events_total")

    health_oom_base = [_oom_events_total()] if health is not None else None

    def _health_update(istart, wall_s, candidates=None, contained=False):
        if health is not None:
            oom_now = _oom_events_total()
            oom_delta = oom_now - health_oom_base[0]
            health_oom_base[0] = oom_now
            health.update(istart, wall_s=wall_s, candidates=candidates,
                          quarantined=contained, oom_events=oom_delta,
                          canary=canary.summary()
                          if canary is not None else None)

    def _emit_candidate(istart, chunk, best):
        """Lineage + push at a hit-append site (ISSUE 18; canary best
        rows are tagged/promoted before this point and never reach
        it).  A stream has no persist store, so the emit point doubles
        as the "persist complete" stage: the hit is durable in the
        caller's hands and the end-to-end latency histogram closes
        here."""
        if lineage is None and push is None:
            return
        dm = float(best["DM"])
        snr = float(best["snr"])
        width = float(best["rebin"]) * float(sample_time)
        iend = istart + int(chunk.shape[1])
        cl = None
        if lineage is not None:
            cl = lineage.candidate(istart, iend, dm=dm, snr=snr,
                                   width=width)
            lineage.persisted(cl, writer=None)
        if push is not None:
            push.publish(
                {"schema_version": 1, "kind": "candidate",
                 "source": "stream_search", "chunk": int(istart),
                 "iend": int(iend), "dm": dm, "snr": snr,
                 "width_s": width},
                on_delivered=(None if cl is None else
                              lambda sub, _lat, _cl=cl:
                              lineage.delivered(_cl, sub)))

    try:
      for istart, chunk in _iter_lookahead(chunks):
        # with a budget, the chunk/search spans come from the accountant
        # itself (one timing primitive); without one, emit them directly
        # so a trace-only stream still renders per-chunk tracks
        ctx = (budget.chunk(istart) if budget is not None
               else traced_chunk(istart))
        with ctx:
            t_chunk = _time.perf_counter()
            is_packed = isinstance(chunk, PackedFrames)
            if lineage is not None:
                # a stream has no reader thread: chunk receipt is its
                # "read" seam
                lineage.mark(istart, "read")
            if canary is not None:
                if not canary._bound:
                    canary.bind(nchan=chunk.shape[0],
                                start_freq=start_freq,
                                bandwidth=bandwidth, tsamp=sample_time,
                                dmmin=dmmin, dmmax=dmmax)
                if is_packed:
                    # quantized into the low-bit codes, re-packed on
                    # this thread: the device signature is exact and
                    # recall is measured on packed runs too (ISSUE 11)
                    chunk = PackedFrames(
                        canary.maybe_inject_packed(
                            chunk.frames, istart, nbits=chunk.nbits,
                            nchan=chunk.nchan,
                            band_descending=chunk.band_descending),
                        chunk.nbits, chunk.nchan,
                        band_descending=chunk.band_descending)
                else:
                    chunk = canary.maybe_inject(chunk, istart)
            if backend == "jax":
                # bytes shipped for this chunk's search: the packed
                # fast path's 8-16x link win is a METRIC, not a claim
                # (bench config 15 gates the ratio).  The float arm
                # counts the float32 bytes the search actually uploads
                # (not the host array's nbytes — a float64 producer
                # would over-report 2x and inflate the ratio)
                _metrics.counter("putpu_bytes_uploaded_total").inc(
                    int(chunk.nbytes) if is_packed
                    else 4 * int(np.prod(np.shape(chunk))))
                if is_packed:
                    _metrics.counter(
                        "putpu_lowbit_packed_chunks_total").inc()
                    _metrics.counter(
                        "putpu_lowbit_bytes_saved_total").inc(
                        chunk.float_nbytes - chunk.nbytes)
            if lineage is not None:
                lineage.mark(istart, "dispatch")
            try:
                with (budget.bucket("search") if budget is not None
                      else span("search")):
                    result = run_guarded(istart, chunk)
                if plane_consumer is not None:
                    table, _plane = result
                    plane_consumer(istart, _plane, table)
                else:
                    table = result
            except (ValueError, TypeError):
                raise
            except Exception:
                if not skip_failed:
                    raise
                # containment: one broken chunk must not kill a long
                # stream — counted, logged above, and absent from the
                # results (callers see exactly which chunks made it)
                _metrics.counter("putpu_stream_chunks_failed_total").inc()
                if canary is not None:
                    canary.discard(istart)
                if lineage is not None:
                    lineage.discard(istart)
                _health_update(istart,
                               wall_s=_time.perf_counter() - t_chunk,
                               contained=True)
                continue
            if lineage is not None:
                lineage.mark(istart, "ready")
            canary_obs = (canary.observe(istart, table, snr_threshold)
                          if canary is not None else None)
            results.append((istart, table))
            best = table.best_row()
            _metrics.counter("putpu_stream_chunks_total").inc()
            if best["snr"] > snr_threshold:
                if canary_obs is not None \
                        and canary_obs["best_is_canary"]:
                    # the chunk's best row is the injected canary:
                    # excluded from the science hits.  A genuine weaker
                    # pulse in the same chunk is promoted in its place
                    # — the hit list must match the canary-off run's
                    canary.tag_hit(istart)
                    sci_idx = canary_obs["science_idx"]
                    sci_snr = canary_obs["science_snr"]
                    if sci_idx is not None \
                            and sci_snr > float(snr_threshold):
                        # same contract as search_by_chunks: the
                        # promoted hit's table has the canary-lit rows
                        # masked out, so consumers sifting/persisting
                        # stream hits never ingest synthetic rows
                        keep = ~canary_obs["canary_rows"]
                        sci_table = type(table)(
                            {name: table[name][keep]
                             for name in table.colnames},
                            meta=table.meta)
                        best = {name: table[name][sci_idx]
                                for name in table.colnames}
                        hits.append((istart, sci_table, best))
                        _metrics.counter(
                            "putpu_stream_hits_total").inc()
                        _metrics.counter(
                            "putpu_canary_promoted_hits_total").inc()
                        _emit_candidate(istart, chunk, best)
                else:
                    if canary_obs is not None \
                            and canary_obs["recovered"]:
                        # a real pulse outranked this chunk's canary:
                        # the hit is genuine but its table still holds
                        # the canary-lit rows — counted + logged, as in
                        # search_by_chunks
                        _metrics.counter(
                            "putpu_canary_contaminated_tables_total").inc()
                        logger.info(
                            "stream chunk %d: real hit persisted "
                            "alongside a recovered canary — trial rows "
                            "near DM %.1f include synthetic signal",
                            istart, canary.dm)
                    hits.append((istart, table, best))
                    _metrics.counter("putpu_stream_hits_total").inc()
                    _emit_candidate(istart, chunk, best)
            if health is not None:
                ncand = int(np.count_nonzero(
                    np.asarray(table["snr"], dtype=np.float64)
                    > float(snr_threshold)))
                if canary_obs is not None:
                    # canary-lit rows are excluded from the storm signal
                    ncand = max(ncand - canary_obs["n_above_near"], 0)
                _health_update(istart,
                               wall_s=_time.perf_counter() - t_chunk,
                               candidates=ncand)
            if lineage is not None:
                # hit lineage froze at the sift verdict; dropping the
                # chunk marks bounds the recorder's memory
                lineage.discard(istart)
    finally:
        if push is not None and push_owned:
            # bounded drain — a wedged subscriber cannot stall the
            # stream's exit (undelivered alerts are counted)
            logger.info("PUSH_JSON %s", _json.dumps(push.close()))
        if obs_server is not None:
            obs_server.close()
    return results, hits


# ---------------------------------------------------------------------------
# Time-sharded ring dedispersion (sequence parallelism)
# ---------------------------------------------------------------------------

@counted_plan_cache("_ring_kernel", maxsize=PLAN_CACHE_SIZE)
def _ring_kernel(mesh, n_hops, rotation):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n_time = mesh.shape["time"]
    # each device receives its RIGHT neighbour's block (ring, wraps)
    perm = [(i, (i - 1) % n_time) for i in range(n_time)]

    def local_step(data_local, offsets):
        # data_local (C, T_loc): this device's contiguous time slice.
        # offsets (D, C): rebased gather offsets in [0, n_hops * T_loc).
        t_loc = data_local.shape[1]
        ndm = offsets.shape[0]
        tidx = jnp.arange(t_loc, dtype=jnp.int32)

        def hop(h, carry):
            acc, cur, nxt = carry
            # out[d, t] += sum_{c : off in window h} ext[c, t + off - base]
            ext = jnp.concatenate([cur, nxt], axis=1)
            rel = offsets - h * t_loc
            valid = (rel >= 0) & (rel < t_loc)
            relc = jnp.clip(rel, 0, t_loc)
            idx = tidx[None, None, :] + relc[:, :, None]  # < 2 * t_loc
            gathered = jnp.take_along_axis(
                jnp.broadcast_to(ext[None], (ndm,) + ext.shape), idx, axis=2)
            acc = acc + jnp.where(valid[:, :, None], gathered, 0.0).sum(axis=1)
            # rotate the ring: this device's view advances one block right
            return acc, nxt, jax.lax.ppermute(nxt, "time", perm=perm)

        acc0 = jnp.zeros((ndm, t_loc), dtype=data_local.dtype)
        if hasattr(jax.lax, "pcast"):
            # newer jax tracks varying-mesh-axes: a zeros-constant carry
            # is UNVARYING while the body's sum varies over the mesh,
            # and fori_loop rejects the carry-type mismatch
            acc0 = jax.lax.pcast(acc0, "time", to="varying")
        nxt0 = jax.lax.ppermute(data_local, "time", perm=perm)
        acc, _, _ = jax.lax.fori_loop(0, n_hops, hop,
                                      (acc0, data_local, nxt0))
        return acc

    from .mesh import shard_map_compat

    fn = shard_map_compat(
        local_step,
        mesh=mesh,
        in_specs=(P(None, "time"), P(None, None)),
        out_specs=P(None, "time"),
    )

    @jax.jit
    def run(data, offsets):
        plane = fn(data, offsets)
        # undo the constant global rotation introduced by offset rebasing:
        # ring_result[d, t] = dedisp[d, (t - base) mod T], so rolling by
        # rotation = (-base) mod T restores dedisp
        return jnp.roll(plane, rotation, axis=1)

    return run


def ring_dedisperse(data, trial_dms, start_freq, bandwidth, sample_time,
                    mesh):
    """Globally-circular dedispersion of a time-sharded sequence.

    The sequence-parallel path (ring-attention-style): ``data`` is
    ``(nchan, T)`` with ``T`` divisible by the ``"time"`` mesh axis size and
    each device holds a contiguous slice.  Fixed-size blocks rotate around
    the ring (one ``ppermute`` per hop); every device accumulates, for its
    own output slice, the channels whose delay lands in the currently-held
    window.  Raw per-channel shifts are rebased by the global minimum so
    gather offsets sit in ``[0, span]`` (span = intra-band delay range at
    ``dmmax``), and the resulting constant time rotation is undone at the
    end — the output equals the single-device
    :func:`~pulsarutils_tpu.ops.dedisperse.dedisperse_batch_numpy` plane up
    to float32 summation order, for ANY shift magnitude (the ring wraps).

    Hop count = ``ceil(span / (T / n_time))``; total gather work equals the
    single-device kernel — it is only distributed, with one ICI block
    transfer per hop overlapping the local gather.
    """
    import jax.numpy as jnp

    # host normalisation of the input: for a device-resident array this
    # is a full-chunk readback — attribute it instead of letting it land
    # in the unattributed residual (putpu-lint device-trip)
    with budget_bucket("search/readback"):
        data = np.asarray(data)
    nchan, nsamples = data.shape
    n_time = mesh.shape["time"]
    if nsamples % n_time:
        raise ValueError(f"T={nsamples} not divisible by time axis {n_time}")
    t_loc = nsamples // n_time

    trial_dms = np.asarray(  # putpu-lint: disable=device-trip — host DM plan list
        trial_dms, dtype=np.float64)
    from ..ops.plan import dedispersion_shifts_batch
    shifts = np.rint(dedispersion_shifts_batch(
        trial_dms, nchan, start_freq, bandwidth,
        sample_time)).astype(np.int64)
    base = int(shifts.min()) if shifts.size else 0
    offsets = (shifts - base).astype(np.int32)
    span = int(offsets.max()) if offsets.size else 0
    if span >= nsamples:
        raise ValueError(
            f"intra-band delay span {span} exceeds the sequence length "
            f"{nsamples}; enlarge the chunk (plan_chunks sizes it correctly)")
    n_hops = max(1, -(-(span + 1) // t_loc))
    # rotation: out[d, tau] = ring_result[d, (tau - base) mod T]
    rotation = (-base) % nsamples

    kernel = _ring_kernel(mesh, n_hops, rotation)
    return kernel(jnp.asarray(data, dtype=jnp.float32),
                  jnp.asarray(offsets))
