"""Sharded dedispersion sweep over a (dm, chan) device mesh.

The TPU-native replacement for the reference's numba ``prange`` sweep
(``pulsarutils/dedispersion.py:174-202``), scaled out with
``jax.shard_map``:

* the input filterbank ``(nchan, T)`` is sharded over the ``chan`` mesh
  axis (each device holds a frequency sub-band — HBM per device drops by
  the chan factor);
* the gather-offset table ``(ndm, nchan)`` is sharded over both axes;
* each device dedisperses its (trial-shard x channel-shard) block — a
  purely local batched gather — then a single ``psum`` over ``chan``
  reduces the partial channel sums into full dedispersed series;
* scoring runs on the ``dm``-sharded full series; outputs come back
  ``dm``-sharded (concatenated by the out-spec).

Communication: ONE psum of ``(ndm/dm_size, T)`` per block over ICI — the
collective-per-byte cost is amortised over the whole trial block.  With
``chan=1`` the program contains no collectives at all and is the pure
embarrassingly-parallel layout.
"""

from __future__ import annotations

import functools

import numpy as np

from ..ops.dedisperse import dedisperse_block_chunked_jax
from ..ops.plan import dedispersion_plan
from ..ops.search import (
    _offsets_for,
    auto_chan_block,
    score_profiles_stacked,
    unstack_scores,
)
from ..tuning.geometry import PLAN_CACHE_SIZE, counted_plan_cache
from ..utils.logging_utils import budget_bucket, budget_count
from ..utils.table import ResultTable
from .mesh import pad_to_multiple


@counted_plan_cache("_sharded_kernel", maxsize=PLAN_CACHE_SIZE)
def _sharded_kernel(mesh, capture_plane, chan_block, kernel="gather",
                    max_off=0, policy=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    def local_search(data_local, off_local, roll_k):
        # data_local (C_loc, T); off_local (D_loc, C_loc); roll_k scalar
        if kernel == "pallas":
            from ..ops.pallas_dedisperse import dedisperse_plane_pallas_traced

            partial = dedisperse_plane_pallas_traced(data_local, off_local,
                                                     max_off)
        else:
            partial = dedisperse_block_chunked_jax(data_local, off_local,
                                                   chan_block, policy=policy)
        dedisp = jax.lax.psum(partial, "chan")
        if kernel == "pallas":
            # undo the host-side offset rebase (see rebase_offsets); the
            # rotation is a traced operand so plans whose rebase constant
            # differs still share this compiled program
            dedisp = jnp.roll(dedisp, -roll_k, axis=1)
        # ONE stacked (5, D_loc) score array -> one host readback (each
        # fetched array costs a full round trip on tunnelled platforms)
        stacked = score_profiles_stacked(dedisp, xp=jnp)
        if capture_plane:
            return stacked, dedisp
        return stacked

    out_scores = P(None, "dm")
    out_specs = ((out_scores, P("dm", None)) if capture_plane
                 else out_scores)

    from .mesh import shard_map_compat

    fn = shard_map_compat(
        local_search,
        mesh=mesh,
        in_specs=(P("chan", None), P("dm", "chan"), P()),
        out_specs=out_specs,
        # pallas_call outputs carry no varying-mesh-axes metadata, which
        # trips shard_map's vma lint; the collective structure here is a
        # single explicit psum, so the check adds nothing
        check_vma=(kernel != "pallas"),
    )
    return jax.jit(fn)


def sharded_dedispersion_search(data, dmmin, dmmax, start_freq, bandwidth,
                                sample_time, mesh, *, trial_dms=None,
                                capture_plane=False, chan_block=None,
                                dtype=None, kernel="auto",
                                plane_handle=False, offsets=None,
                                pallas_max_off=None, precision=None):
    """Run the full DM sweep sharded over ``mesh`` axes ``("dm", "chan")``.

    Same result contract as
    :func:`pulsarutils_tpu.ops.search.dedispersion_search` (same plan, same
    host-side float64 offsets, same scorer) — only the execution layout
    differs.  Works on any mesh built by :mod:`.mesh`, including the
    8-virtual-device CPU mesh used in tests.

    ``kernel``: ``"auto"`` (measured per-(backend, geometry, mesh-shape)
    selection via the plan-level autotuner — see
    :mod:`pulsarutils_tpu.tuning`; the static rule, per-shard Pallas on
    all-TPU float32 meshes and XLA gather elsewhere, remains the
    zero-measurement fallback and the ``PUTPU_AUTOTUNE=off`` escape
    hatch), ``"pallas"``, or ``"gather"``.

    ``plane_handle`` (with ``capture_plane``) keeps the captured plane
    DM-sharded and device-resident, returned as a
    :class:`~.sharded_plane.ShardedPlane` instead of a host gather (the
    mesh streaming diagnostics path).

    ``offsets`` (with an explicit ``trial_dms``) supplies the precomputed
    int32 gather-offset rows for those trials, so a caller cycling many
    small trial subsets over one chunk geometry (the sharded hybrid's
    rescore buckets) slices ONE cached table instead of re-deriving the
    plan shifts host-side per call.  ``pallas_max_off`` pins the Pallas
    kernel's static halo bound to a caller-chosen value covering every
    subset (e.g. the full table's rebased bound, power-of-two rounded):
    without it each subset's own bound keys the compiled-program cache,
    and a subset spanning a different offset range silently retraces —
    the retrace detector (``BudgetAccountant``) flags exactly that.

    ``precision`` names a :mod:`~pulsarutils_tpu.precision` accumulation
    strategy for the per-shard channel partial sums (the cross-shard
    ``psum`` stays plain f32 — it adds at most ``chan_size`` partials).
    ``"auto"`` degrades to the static ``f32`` on the mesh path (the
    policy tuner measures the single-device programs), and the Pallas
    per-shard kernel only supports plain f32.
    """
    import jax
    import jax.numpy as jnp

    from ..io.lowbit import PackedFrames

    if isinstance(data, PackedFrames):
        # packed low-bit chunk (ISSUE 11): upload the RAW bytes and
        # decode through the cached device-unpack program — the chan
        # sharding below cannot split packed frames (byte boundaries
        # straddle channel shards), so the unpack is its own dispatch
        # and the sharded sweep consumes the HBM-resident float block;
        # the link still carries only the packed bytes
        data = data.to_device()
    dtype = dtype or jnp.float32
    nchan, nsamples = np.shape(data)
    if trial_dms is None:
        trial_dms = dedispersion_plan(nchan, dmmin, dmmax, start_freq,
                                      bandwidth, sample_time)
    trial_dms = np.asarray(  # putpu-lint: disable=device-trip — host DM plan list
        trial_dms, dtype=np.float64)
    ndm = len(trial_dms)

    if offsets is None:
        # per-call host plan math — hoist it with offsets= when calling
        # repeatedly at one geometry (the counter makes a hot-loop
        # rebuild visible in the chunk budget)
        budget_count("offset_tables")
        offsets = _offsets_for(trial_dms, nchan, start_freq, bandwidth,
                               sample_time, nsamples)
    else:
        offsets = np.asarray(  # putpu-lint: disable=device-trip — host offset table
            offsets, dtype=np.int32)
        if offsets.shape != (ndm, nchan):
            raise ValueError(f"offsets shape {offsets.shape} does not "
                             f"match ({ndm}, {nchan})")

    dm_size = mesh.shape["dm"]
    chan_size = mesh.shape["chan"]
    # pad trials (duplicates of the last trial) and channels (zeros — exact
    # no-ops for the channel sum)
    offsets, _ = pad_to_multiple(offsets, 0, dm_size, mode="edge")
    offsets, _ = pad_to_multiple(offsets, 1, chan_size, mode="constant")
    if nchan % chan_size:
        # a device-resident input bounces through the host on this
        # misaligned-channel path — attribute the trip (putpu-lint
        # device-trip); the aligned branch below keeps it on-device
        with budget_bucket("search/plan"):
            data_padded, _ = pad_to_multiple(np.asarray(data), 0,
                                             chan_size, mode="constant")
    else:
        # already aligned: keep the caller's array — a device-resident
        # input (e.g. the sharded hybrid's repeated rescore calls) must
        # not bounce through the host on every call
        data_padded = data

    if chan_block is None:
        chan_block = auto_chan_block(data_padded.shape[0] // chan_size,
                                     nsamples, offsets.shape[0] // dm_size)

    if kernel == "auto":
        # measured per-(backend, geometry, mesh-shape) selection with the
        # persistent tune cache; the static rule (per-shard Pallas on
        # all-TPU float32 meshes, gather elsewhere) stays as the
        # zero-measurement fallback and the PUTPU_AUTOTUNE=off hatch.
        # Off-TPU meshes have a single applicable variant and resolve
        # statically at zero cost.
        from ..tuning.autotune import resolve_mesh_kernel

        kernel = resolve_mesh_kernel(mesh, nchan, nsamples, ndm,
                                     start_freq, bandwidth, sample_time,
                                     trial_dms, dtype=dtype)
    # rebase wrapped offsets to the band-crossing span (see rebase_offsets)
    # so the pallas halo stays small; max_off is rounded up to a power of
    # two so small plan changes reuse the compiled kernel (the gather
    # kernel does not depend on either — keep its cache key constant)
    roll_k = 0
    if kernel == "pallas":
        from ..ops.pallas_dedisperse import rebase_offsets

        offsets, roll_k, max_off = rebase_offsets(offsets, nsamples)
        if pallas_max_off is not None:
            # caller-pinned static halo bound: one compiled program per
            # bucket shape across every trial subset (no silent retrace)
            if pallas_max_off < max_off:
                raise ValueError(f"pallas_max_off={pallas_max_off} does "
                                 f"not cover the subset bound {max_off}")
            max_off = int(pallas_max_off)
        else:
            if max_off > 0:
                max_off = 1 << int(np.ceil(np.log2(max_off + 1)))
            max_off = max(max_off, 256)
    else:
        max_off = 0

    from ..precision import engage, resolve_policy

    eff_policy = resolve_policy(precision)
    if eff_policy == "auto":
        # the policy tuner measures the single-device programs; on the
        # mesh path the static f32 default stands
        eff_policy = "f32"
    if eff_policy != "f32" and kernel == "pallas":
        raise ValueError("precision policies other than 'f32' need the "
                         "gather mesh kernel (the per-shard Pallas "
                         "kernel accumulates plain f32)")
    policy_arg = None if eff_policy == "f32" else eff_policy
    if policy_arg is not None:
        engage(policy_arg)

    compiled = _sharded_kernel(mesh, capture_plane, chan_block, kernel,
                               max_off, policy_arg)
    from ..obs import roofline

    roof = roofline.begin()
    with budget_bucket("search/dispatch"):
        # host->device conversions stay INSIDE the bucket: on CPU the
        # asarray of a full chunk copies synchronously, and those
        # seconds must stay attributed (round-6 contract)
        sweep_args = (jnp.asarray(data_padded, dtype=dtype),
                      jnp.asarray(offsets), jnp.int32(roll_k))
        out = compiled(*sweep_args)
        budget_count("dispatches")

    from .mesh import fetch_global as fetch

    if capture_plane:
        stacked, plane = out
        if plane_handle:
            from .sharded_plane import ShardedPlane

            plane = ShardedPlane(plane, mesh, "dm", np.arange(ndm))
        else:
            with budget_bucket("search/readback"):
                plane = fetch(plane)[:ndm]
                budget_count("readbacks")
    else:
        stacked, plane = out, None
    with budget_bucket("search/readback"):
        stacked_host = fetch(stacked)[:, :ndm]
        budget_count("readbacks")
    roofline.end(roof, "sharded_sweep", compiled, sweep_args)
    maxvalues, stds, best_snrs, best_windows, best_peaks = unstack_scores(
        stacked_host)

    table = ResultTable({
        "DM": trial_dms,
        "max": maxvalues,
        "std": stds,
        "snr": best_snrs,
        "rebin": best_windows,
        "peak": best_peaks,
    })
    if capture_plane:
        return table, plane
    return table
