"""DM-sliced sharded FDMT: the fast tree kernel scaled over a device mesh.

:mod:`.sharded` scales the *direct* sweep (the bit-exact kernel) over a
``(dm, chan)`` mesh; this module scales the *FDMT* — the throughput
kernel behind ``kernel="fdmt"`` and the hybrid — over the ``dm`` axis:

* the trial-delay range ``[n_lo, n_hi]`` splits into one contiguous
  slice per device;
* each device runs the **delay-range-pruned** transform
  (:class:`~pulsarutils_tpu.ops.fdmt.FdmtPlan` with its slice as
  ``[min_delay, max_delay]``) — rows outside its slice are never built,
  so per-device work for the deep (delay-dominated) iterations scales
  ~1/D while only the shallow channel-dominated iterations are
  replicated;
* the per-device merge schedules differ (different delay windows), but
  ``shard_map`` compiles ONE program: the tables are padded to common
  shapes and shipped as **sharded runtime operands** riding the merge
  kernel's scalar-prefetch inputs
  (:func:`~pulsarutils_tpu.ops.fdmt.merge_rows_traced`);
* scores come back ``dm``-sharded; each device's leading ``hi - lo + 1``
  rows are its delay slice and the padded remainder is dropped when the
  host stitches the global table.

Input data is replicated across the ``dm`` axis (each device needs the
whole band to dedisperse any trial — same trade the reference's
shared-memory ``prange`` sweep makes, ``pulsarutils/dedispersion.py:174``).
Communication: none at all inside the transform (the slices are
independent), so the layout scales over DCN as well as ICI.
"""

from __future__ import annotations

import functools

import numpy as np

from ..ops.fdmt import (
    MERGE_ROW_BLOCK,
    _pick_fdmt_tile,
    fdmt_plan,
    fdmt_trial_dms,
)
from ..tuning.geometry import PLAN_CACHE_SIZE, counted_plan_cache
from ..utils.logging_utils import budget_bucket, budget_count, logger
from ..utils.table import ResultTable
from .mesh import fetch_global, pad_to_multiple

__all__ = ["sharded_fdmt_search", "sharded_hybrid_search",
           "slice_delay_range"]


def slice_delay_range(n_lo, n_hi, n_slices):
    """Split ``[n_lo, n_hi]`` (inclusive) into contiguous near-equal
    slices; returns a list of ``(lo, hi)`` pairs.  Requires at least one
    trial per slice."""
    total = n_hi - n_lo + 1
    if total < n_slices:
        raise ValueError(f"{total} trials cannot fill {n_slices} devices; "
                         "use a smaller mesh or a wider DM range")
    edges = [n_lo + (total * i) // n_slices for i in range(n_slices + 1)]
    return [(edges[i], edges[i + 1] - 1) for i in range(n_slices)]


def _pad_rows(a, rows):
    """Pad a 1-D table to ``rows`` by repeating its last entry."""
    return np.concatenate([a, a[-1:].repeat(rows - len(a))])


def _stacked_tables(plans, t_tile):
    """Per-iteration tables stacked over devices + static kernel bounds.

    Returns a list of dicts with ``idx_low/idx_high/shift/shift_high``
    as ``(D, rows_max)`` int32 arrays (device-shardable) and the static
    ``k_tiles``/``k_tiles_h``/``rows_max`` the one compiled program
    needs (maxima over devices).
    """
    n_iter = len(plans[0].iterations)
    assert all(len(p.iterations) == n_iter for p in plans)
    L = t_tile // 8
    out = []
    for i in range(n_iter):
        its = [p.iterations[i] for p in plans]
        rows_max = max(len(it["idx_low"]) for it in its)
        rows_max += (-rows_max) % min(MERGE_ROW_BLOCK, rows_max)
        idx_low = np.stack([_pad_rows(it["idx_low"], rows_max)
                            for it in its])
        idx_high = np.stack([_pad_rows(it["idx_high"], rows_max)
                             for it in its])
        shift = np.stack([_pad_rows(it["shift"], rows_max) for it in its])
        max_shift = int(shift.max(initial=0))
        k_tiles = (max_shift // L + 23) // 8
        if its[0]["shift_high"] is not None:
            shift_high = np.stack([_pad_rows(it["shift_high"], rows_max)
                                   for it in its])
            k_tiles_h = (int(shift_high.max(initial=0)) // L + 23) // 8
        else:
            shift_high = np.zeros_like(shift)
            k_tiles_h = 0
        out.append({
            "idx_low": idx_low.astype(np.int32),
            "idx_high": idx_high.astype(np.int32),
            "shift": shift.astype(np.int32),
            "shift_high": shift_high.astype(np.int32),
            "k_tiles": k_tiles,
            "k_tiles_h": k_tiles_h,
            "rows_max": rows_max,
        })
    return out


@counted_plan_cache("_build_sharded_fdmt", maxsize=PLAN_CACHE_SIZE)
def _build_sharded_fdmt(mesh, axis, nchan, nchan_padded, t, t_tile,
                        use_pallas, interpret, plan_key, t_orig,
                        with_cert=False, with_plane=False,
                        packed_meta=None):
    """Compile the SPMD transform+score program for one mesh/geometry.

    ``plan_key`` carries the static per-iteration bounds (k_tiles,
    rows_max, ...) so the cache key captures the schedule shapes; the
    table *values* are runtime operands.  ``t`` is the (possibly padded)
    run length; scores are computed over the first ``t_orig`` samples.
    ``with_plane`` additionally emits the final transform state — the
    dedispersed plane, DM-sharded ``P(axis, None)`` and device-resident
    (the mesh plane-products path, :mod:`.sharded_plane`).
    ``packed_meta`` (a :meth:`~pulsarutils_tpu.io.lowbit.PackedFrames.
    meta` tuple) makes ``data`` the RAW packed ``(T, bytes_per_frame)``
    uint8 frames, replicated like the float block was: each device's
    shard_map body starts with the bit-unpack, so the host->device
    link carries 1/8-1/16th the bytes (ISSUE 11).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.fdmt import _merge_xla, merge_rows_traced
    from ..ops.search import score_profiles_chunked

    iter_meta = plan_key  # tuple of (k_tiles, k_tiles_h, rows_max)

    def local_fn(data, *tables):
        # data (nchan, T) replicated — or the raw packed frames,
        # unpacked here INSIDE the one shard_map program; tables: 4
        # arrays per iteration, each (1, rows_max) — this device's
        # merge schedule
        if packed_meta is not None:
            from ..io.lowbit import unpack_from_meta

            data = unpack_from_meta(data, packed_meta, jnp)
        state = data
        if nchan < nchan_padded:
            state = jnp.concatenate(
                [state, jnp.zeros((nchan_padded - nchan, t), state.dtype)])
        for i, (k_tiles, k_tiles_h, rows_max) in enumerate(iter_meta):
            il, ih, sh, shh = (tables[4 * i + j][0] for j in range(4))
            if use_pallas:
                state = merge_rows_traced(
                    state, il, ih, sh,
                    shh if k_tiles_h else jnp.zeros_like(sh),
                    k_tiles=k_tiles, k_tiles_h=k_tiles_h, t_tile=t_tile,
                    interpret=interpret)
            else:
                state = _merge_xla(state, il, ih, sh,
                                   shh if k_tiles_h else None)
        if t_orig != t:
            state = state[:, :t_orig]
        # score every (padded) row; junk rows are dropped host-side
        scores = score_profiles_chunked(state, jnp,
                                        with_cert=with_cert)[None]
        return (scores, state) if with_plane else scores

    from .mesh import shard_map_compat

    in_specs = [P()] + [P(axis)] * (4 * len(iter_meta))
    out_specs = (P(axis), P(axis, None)) if with_plane else P(axis)
    fn = jax.jit(shard_map_compat(
        local_fn, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_specs,
        # pallas_call outputs carry no varying-mesh-axes metadata, which
        # trips shard_map's vma lint; there are no collectives at all in
        # this program, so the check adds nothing
        check_vma=not use_pallas))
    return fn


def sharded_fdmt_search(data, dmmin, dmmax, start_freq, bandwidth,
                        sample_time, mesh, axis="dm", use_pallas=None,
                        with_cert=False, capture_plane=False):
    """FDMT sweep with the trial-DM axis sharded over ``mesh[axis]``.

    Same scientific contract as ``dedispersion_search(kernel="fdmt")``
    (integer band-delay trial grid, within-one-trial hit agreement with
    the exact kernels), with per-device HBM for the output plane/state
    cut ~1/D and the deep tree iterations parallelised over devices.
    ``use_pallas`` forces the Pallas (True, interpret mode off-TPU — for
    testing the traced-table kernel path) or XLA (False) merge; default
    auto: Pallas on TPU.

    ``data`` may be a :class:`~pulsarutils_tpu.io.lowbit.PackedFrames`
    (ISSUE 11): the raw 1/2/4-bit bytes ship to the devices and each
    shard_map body unpacks them in-program — 1/8-1/16th the link
    traffic, scores byte-identical to the float-block run.

    Returns a :class:`~pulsarutils_tpu.utils.table.ResultTable` with the
    usual ``DM, max, std, snr, rebin, peak`` columns over the full grid.
    With ``capture_plane`` returns ``(table, plane)`` where ``plane`` is
    a :class:`~pulsarutils_tpu.parallel.sharded_plane.ShardedPlane` —
    the dedispersed plane left DM-sharded and device-resident, with
    shard-local per-row products (the mesh diagnostics/period-search
    path; the single-device path's host-gathered plane never exists).
    """
    import jax
    import jax.numpy as jnp

    from ..io.lowbit import PackedFrames
    from ..ops.search import unstack_scores

    packed = data if isinstance(data, PackedFrames) else None
    nchan, t = np.shape(data)  # PackedFrames reports its logical shape
    n_dev = mesh.shape[axis]
    trial_dms, n_lo, n_hi = fdmt_trial_dms(nchan, dmmin, dmmax, start_freq,
                                           bandwidth, sample_time)
    slices = slice_delay_range(n_lo, n_hi, n_dev)

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    interpret = jax.default_backend() != "tpu"
    packed_meta = packed.meta() if packed is not None else None
    # packed input: the RAW bytes are the program operand — the unpack
    # runs inside the shard_map body (_build_sharded_fdmt)
    data = (jnp.asarray(packed.frames) if packed is not None
            else jnp.asarray(data, jnp.float32))
    t_run = t
    t_tile = _pick_fdmt_tile(t)
    if use_pallas and t_tile == 0:
        # same zero-pad rule as the single-device path
        # (ops/fdmt.py:_transform_setup): the XLA merge's per-row rolls
        # scalarise on TPU, so padding to a tile multiple and slicing
        # the scores back is far cheaper than falling off Pallas
        t_run = -(-t // 1024) * 1024
        if packed is not None:
            # frames are time-major: pad whole zero FRAMES — a zero
            # byte decodes to zero codes, so the unpacked pad equals
            # the float path's zero-sample pad exactly
            data = jnp.pad(data, ((0, t_run - t), (0, 0)))
        else:
            data = jnp.pad(data, ((0, 0), (0, t_run - t)))
        t_tile = _pick_fdmt_tile(t_run)
    elif t_tile == 0:
        t_tile = 1024  # unused by the XLA merge path

    plans = [fdmt_plan(nchan, float(start_freq), float(bandwidth), hi, lo)
             for lo, hi in slices]
    tables = _stacked_tables(plans, t_tile)
    plan_key = tuple((it["k_tiles"], it["k_tiles_h"], it["rows_max"])
                     for it in tables)

    fn = _build_sharded_fdmt(mesh, axis, nchan, plans[0].nchan_padded,
                             t_run, t_tile, use_pallas, interpret,
                             plan_key, t, with_cert, capture_plane,
                             packed_meta)
    flat = []
    for it in tables:
        flat += [jnp.asarray(it[k]) for k in
                 ("idx_low", "idx_high", "shift", "shift_high")]
    plane_handle = None
    if capture_plane:
        from .sharded_plane import ShardedPlane

        with budget_bucket("search/coarse"):
            out, plane = fn(data, *flat)
            budget_count("dispatches")
        with budget_bucket("search/coarse_readback"):
            out = fetch_global(out)
            budget_count("readbacks")
        # device d's padded shard starts at d * rows_max in the global
        # concatenated plane; its first (hi-lo+1) rows are its slice
        rows_max = plane.shape[0] // n_dev
        row_index = np.concatenate(
            [d * rows_max + np.arange(hi - lo + 1)
             for d, (lo, hi) in enumerate(slices)])
        plane_handle = ShardedPlane(plane, mesh, axis, row_index)
    else:
        with budget_bucket("search/coarse"):
            out_dev = fn(data, *flat)
            budget_count("dispatches")
        with budget_bucket("search/coarse_readback"):
            out = fetch_global(out_dev)
            budget_count("readbacks")

    # stitch the dm-sharded scores: device d's first (hi-lo+1) rows are
    # its delay slice; the rest is padding junk
    cols = []
    for d, (lo, hi) in enumerate(slices):
        stacked = out[d]  # (5|6, rows_max_final)
        cols.append(stacked[:, :hi - lo + 1])
    scores = unstack_scores(np.concatenate(cols, axis=1))
    maxvalues, stds, snrs, wins, peaks = scores[:5]
    columns = {
        "DM": trial_dms,
        "max": maxvalues,
        "std": stds,
        "snr": snrs,
        "rebin": wins,
        "peak": peaks,
    }
    if with_cert:
        columns["cert"] = scores[5]
    table = ResultTable(columns)
    return (table, plane_handle) if capture_plane else table


@counted_plan_cache("_plan_offsets", maxsize=PLAN_CACHE_SIZE)
def _plan_offsets(nchan, dmmin, dmmax, start_freq, bandwidth, sample_time,
                  nsamples):
    """Chunk-geometry plan grid + full int32 offset table, cached.

    The sharded hybrid used to re-enter ``dedispersion_plan`` +
    ``_offsets_for`` host-side on EVERY rescore bucket (and on every
    streaming chunk of identical geometry); one cached table is sliced
    per bucket instead.  Returned arrays are shared cache objects —
    callers slice, never mutate.  Size and hit/miss counters come from
    :mod:`..tuning.geometry` — one documented policy for every
    geometry-keyed plan cache (this one sat at 8 while its sibling
    program caches sat at 16, so tuner-induced geometry churn could
    thrash the table while the programs survived).
    """
    from ..ops.plan import dedispersion_plan
    from ..ops.search import _offsets_for

    trial_dms = np.asarray(
        dedispersion_plan(nchan, dmmin, dmmax, start_freq, bandwidth,
                          sample_time), dtype=np.float64)
    offsets = _offsets_for(trial_dms, nchan, start_freq, bandwidth,
                           sample_time, nsamples)
    trial_dms.setflags(write=False)  # shared cache objects: fail loudly
    offsets.setflags(write=False)    # on accidental mutation
    return trial_dms, offsets


@counted_plan_cache("_build_fused_sharded_hybrid", maxsize=PLAN_CACHE_SIZE)
def _build_fused_sharded_hybrid(mesh, nchan, nchan_padded, t, t_tile,
                                use_pallas, interpret, plan_key, ndm_plan,
                                bucket, bucket2, rescore_kernel, chan_block,
                                max_off, nchan_rs, packed_meta=None):
    """ONE ``shard_map`` program for the mesh hybrid's first round:

    DM-sliced coarse FDMT (each dm shard runs its delay-range-pruned
    transform, replicated over ``chan``) -> one small all-gather of the
    per-shard score packs so every device holds the global plan-grid
    coarse table -> the guarantee loop's OWN seed rule evaluated
    device-side (plausible-best + floor rows, grown +/-1 neighbours,
    selected via :func:`~..ops.search.fused_masked_topk`) -> exact
    rescore of the seed bucket sharded over the full ``(dm, chan)`` mesh
    (same per-shard kernel, channel split and psum order as
    :func:`~.sharded.sharded_dedispersion_search`, so the scores are
    bit-identical to the unfused escape hatch) -> the need stage
    (:func:`~..ops.search.fused_need_stage`, shared with the
    single-device fused kernel) rescored the same way -> everything
    packed into one replicated float32 vector
    (:func:`~..ops.search.unpack_fused_hybrid` layout).

    A typical hit chunk's guarantee loop therefore completes in ONE
    dispatch instead of one coarse ``shard_map`` program plus one per
    rescore bucket.  The seed rule deliberately differs from the
    single-device kernel's blind top-k: computing the loop's own mask
    makes the fused path's rescored set — and hence the ``exact``
    column — provably identical to the unfused path whenever the mask
    fits the bucket (the host tops up or falls back otherwise, see
    ``sharded_hybrid_search``), up to one caveat: the device evaluates
    the masks in float32 where the host loop uses float64, so a row
    within one float32 ulp of a criterion threshold can be flagged by
    one and not the other — a measure-zero tie whose members are
    score-equivalent either way (the exact-argbest contract is
    unaffected; the parity tests use decisive data).

    ``check_vma`` is off: the collective structure is three explicit
    collectives (coarse all-gather, rescore psum + all-gather) and the
    outputs are replicated by construction, which the vma lint cannot
    express across the pallas/cond paths.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.dedisperse import dedisperse_block_chunked_jax
    from ..ops.fdmt import _merge_xla, merge_rows_traced
    from ..ops.search import (
        fused_masked_topk,
        fused_need_stage,
        score_profiles_chunked,
        score_profiles_stacked,
    )

    iter_meta = plan_key  # tuple of (k_tiles, k_tiles_h, rows_max)
    dm_size = mesh.shape["dm"]
    chan_size = mesh.shape["chan"]
    c_loc = nchan_rs // chan_size

    def local_fn(data, idx_map, offsets_rs, cert_params, roll_k, *tables):
        # packed low-bit input (ISSUE 11): the operand is the RAW
        # (T, bytes_per_frame) uint8 frames and the bit-unpack is the
        # first op of this ONE shard_map program — coarse transform,
        # seed/need rescore and packing all read the unpacked block
        # from HBM while the link only ever carried the packed bytes
        if packed_meta is not None:
            from ..io.lowbit import unpack_from_meta

            data = unpack_from_meta(data, packed_meta, jnp)
        # ---- coarse: this dm shard's delay-sliced transform (chan
        # replicated) — identical math to _build_sharded_fdmt.local_fn
        state = data
        if nchan < nchan_padded:
            state = jnp.concatenate(
                [state, jnp.zeros((nchan_padded - nchan, t), state.dtype)])
        for i, (k_tiles, k_tiles_h, rows_max) in enumerate(iter_meta):
            il, ih, sh, shh = (tables[4 * i + j][0] for j in range(4))
            if use_pallas:
                state = merge_rows_traced(
                    state, il, ih, sh,
                    shh if k_tiles_h else jnp.zeros_like(sh),
                    k_tiles=k_tiles, k_tiles_h=k_tiles_h, t_tile=t_tile,
                    interpret=interpret)
            else:
                state = _merge_xla(state, il, ih, sh,
                                   shh if k_tiles_h else None)
        stacked = score_profiles_chunked(state, jnp, with_cert=True)
        # ---- ONE small all-gather (6 x D*rows floats): every device
        # sees the global coarse table, mapped onto the plan grid
        gathered = jax.lax.all_gather(stacked, "dm")       # (D, 6, R)
        coarse = gathered.transpose(1, 0, 2).reshape(
            6, -1)[:, idx_map]                             # (6, ndm_plan)
        snr_c = coarse[2]
        floor = cert_params[2]
        # ---- the guarantee loop's seed rule (hybrid_guarantee_loop),
        # device-side: plausible-best + floor rows, grown +/-1 grid
        # neighbours (clipped, not wrapped — matching np.clip there)
        seed = snr_c >= snr_c.max() - 0.5
        seed |= snr_c >= floor - 0.75
        z = jnp.zeros((1,), bool)
        grown = (seed | jnp.concatenate([seed[1:], z])
                 | jnp.concatenate([z, seed[:-1]]))
        sel, n_seed = fused_masked_topk(snr_c, grown, bucket)

        # ---- exact rescore, sharded over the full (dm, chan) mesh with
        # the unfused path's layout: device (i, j) dedisperses its row
        # slice over its channel slice, one psum over chan reduces
        i_dm = jax.lax.axis_index("dm")
        i_ch = jax.lax.axis_index("chan")
        if nchan_rs > nchan:
            data_rs = jnp.concatenate(
                [data, jnp.zeros((nchan_rs - nchan, t), data.dtype)])
        else:
            data_rs = data
        data_loc = jax.lax.dynamic_slice(data_rs, (i_ch * c_loc, 0),
                                         (c_loc, t))

        def rescore_rows(rows):
            nrows = rows.shape[0]
            rps = nrows // dm_size
            offs = offsets_rs[rows]
            offs_loc = jax.lax.dynamic_slice(
                offs, (i_dm * rps, i_ch * c_loc), (rps, c_loc))
            if rescore_kernel == "pallas":
                from ..ops.pallas_dedisperse import (
                    dedisperse_plane_pallas_traced,
                )

                partial = dedisperse_plane_pallas_traced(data_loc, offs_loc,
                                                         max_off)
            else:
                partial = dedisperse_block_chunked_jax(data_loc, offs_loc,
                                                       chan_block)
            dedisp = jax.lax.psum(partial, "chan")
            if rescore_kernel == "pallas":
                dedisp = jnp.roll(dedisp, -roll_k, axis=1)
            scores = score_profiles_stacked(dedisp, xp=jnp)  # (5, rps)
            g = jax.lax.all_gather(scores, "dm")             # (D, 5, rps)
            return g.transpose(1, 0, 2).reshape(5, nrows)

        exact = rescore_rows(sel)
        parts = [coarse.reshape(-1), sel.astype(jnp.float32),
                 exact.reshape(-1), n_seed.astype(jnp.float32)[None]]
        if bucket2:
            best_exact = exact[2].max()
            rescored = jnp.zeros(ndm_plan, bool).at[sel].set(True)
            sel2, n_need = fused_need_stage(coarse, best_exact, rescored,
                                            cert_params, bucket2)
            # skipped (lax.cond) when nothing is flagged, exactly like
            # the single-device kernel — the predicate is replicated, so
            # every device takes the same branch and the branch's
            # collectives stay matched
            exact2 = jax.lax.cond(
                n_need > 0, rescore_rows,
                lambda _: jnp.zeros((5, bucket2), jnp.float32), sel2)
            parts += [sel2.astype(jnp.float32), exact2.reshape(-1),
                      n_need.astype(jnp.float32)[None]]
        return jnp.concatenate(parts)

    from .mesh import shard_map_compat

    in_specs = [P(), P(), P(), P(), P()] + [P("dm")] * (4 * len(iter_meta))
    fn = shard_map_compat(local_fn, mesh=mesh, in_specs=tuple(in_specs),
                          out_specs=P(), check_vma=False)
    return jax.jit(fn)


def sharded_hybrid_search(data, dmmin, dmmax, start_freq, bandwidth,
                          sample_time, mesh, snr_floor=None,
                          noise_certificate=True, capture_plane=False,
                          rho_cert=None, cert_slack=None, fused=None):
    """Hybrid (exact hits at coarse cost) over a ``(dm, chan)`` mesh.

    Multi-device composition of ``dedispersion_search(kernel="hybrid")``:
    the coarse stage is the DM-sliced sharded FDMT (the ``chan`` axis is
    idle/replicated there — use ``chan=1`` meshes when the coarse stage
    dominates), and the exact rescore of candidate rows runs through
    :func:`~pulsarutils_tpu.parallel.sharded.sharded_dedispersion_search`
    over the full mesh.  The guarantee loop, the cert-based skip
    criterion and the noise certificate are shared with the
    single-device hybrid (:mod:`~pulsarutils_tpu.ops.certify`), so the
    contract is identical: the returned argbest row holds the exact
    kernel's scores (unless ``meta["certified"]``, which asserts no
    detection above ``snr_floor`` exists — sound under the stated
    signal model up to the Gaussian noise cross-term, residual risk in
    ``meta["cert_miss_p_at_floor"]``), with an ``exact`` column marking
    exact rows.

    ``capture_plane`` returns ``(table, plane)`` with ``plane`` a
    :class:`~.sharded_plane.ShardedPlane` over the *coarse* (FDMT) plane
    remapped to the plan grid — the same coarse-plane convention as the
    single-device hybrid's capture (``ops/search.py``:
    ``_search_jax_hybrid``), kept DM-sharded and device-resident.

    ``rho_cert`` / ``cert_slack`` mirror ``dedispersion_search``'s
    knobs: a precomputed retention bound (or ``False`` to opt out of
    the cert machinery) and a certificate slack derived from a target
    miss probability (:func:`~pulsarutils_tpu.ops.certify.cert_slack_for_miss_p`).

    ``data`` may be a :class:`~pulsarutils_tpu.io.lowbit.PackedFrames`
    (ISSUE 11): the fused program's operand is then the raw 1/2/4-bit
    bytes, unpacked inside the one ``shard_map`` dispatch — 1/8-1/16th
    the link traffic; the escape-hatch rescore decodes lazily through a
    cached device program, so certified / fused-converged chunks never
    pay the float materialisation.  Results are byte-identical to the
    host-unpacked run (``tests/test_lowbit_e2e.py``).

    ``fused`` (round 6): ``None`` (default) runs the first round —
    coarse FDMT + seed selection + exact seed/need rescore — as ONE
    ``shard_map`` dispatch (:func:`_build_fused_sharded_hybrid`)
    whenever eligible: no plane capture, no certificate-mode floor
    (mirroring the single-device gating — a noise-certified chunk
    should pay one coarse dispatch, not a burned seed rescore), cert
    machinery not opted out, and a trial grid at least one seed bucket
    wide.  The :func:`~..ops.search.hybrid_certificate_gate` loop stays
    as the escape hatch: only rows the fused program did not rescore
    trigger (now rare) follow-up
    :func:`~.sharded.sharded_dedispersion_search` dispatches, and when
    the device's seed or need stage overflows its bucket the host
    discards that stage and completes the round itself, so the rescored
    set — argbest, ``exact`` column and certificate metadata — is
    identical to ``fused=False`` (up to float32-vs-float64 threshold
    ties on the mask criteria — measure-zero, score-equivalent rows;
    see :func:`_build_fused_sharded_hybrid`).  ``fused=False`` forces
    the unfused multi-dispatch composition (the A/B baseline);
    ``fused=True`` raises if the fused program is not eligible.
    """
    import jax
    import jax.numpy as jnp

    from ..ops.certify import cert_meta, fused_cert_params
    from ..ops.search import (
        HYBRID_NEED_BUCKET,
        HYBRID_SEED_BUCKET,
        auto_chan_block,
        fused_scores_to_host,
        hybrid_certificate_gate,
        iter_rescore_buckets,
        nearest_rows,
        unpack_fused_hybrid,
    )
    from .sharded import sharded_dedispersion_search

    from ..io.lowbit import PackedFrames

    pf = data if isinstance(data, PackedFrames) else None
    nchan, nsamples = np.shape(data)  # PackedFrames reports logical shape
    dm_size = mesh.shape["dm"]
    chan_size = mesh.shape["chan"]
    # (the pad-free soundness guard lives in hybrid_certificate_gate,
    # shared verbatim with the single-device hybrid)
    # ONE host->device transfer: the coarse stage and every rescore call
    # reuse the same device-resident array (sharded_dedispersion_search
    # passes aligned device inputs through untouched).  Packed low-bit
    # input (ISSUE 11): the RAW bytes are the transfer; the fused
    # program unpacks them in its own shard_map body, and the float
    # view for the (rare) escape-hatch rescore is decoded lazily by a
    # cached device program — a certified or fused-converged chunk
    # never materialises it.
    if pf is not None:
        raw_dev = jnp.asarray(pf.frames)
        data = None
    else:
        data = jnp.asarray(data, jnp.float32)

    def _float_data():
        nonlocal data
        if data is None:
            data = pf.to_device()
        return data

    # chunk-geometry plan + offsets: ONE cached host computation, sliced
    # per rescore bucket (was re-derived inside every bucket call)
    trial_dms, offsets_full = _plan_offsets(
        nchan, float(dmmin), float(dmmax), float(start_freq),
        float(bandwidth), float(sample_time), int(nsamples))
    ndm = len(trial_dms)

    use_pallas = jax.default_backend() == "tpu"
    # the exact-rescore per-shard kernel: tuner-resolved at the chunk
    # geometry (the same (backend, geometry, mesh) key the sharded
    # direct sweep uses, so both paths agree on the winner); off-TPU
    # meshes have one applicable variant and resolve statically at zero
    # cost.  The escape-hatch rescore below passes this choice
    # explicitly — the fused program and the hatch MUST rescore with
    # the same per-shard kernel for the bit-identity contract
    from ..tuning.autotune import resolve_mesh_kernel

    rescore_kernel = resolve_mesh_kernel(mesh, nchan, nsamples, ndm,
                                         start_freq, bandwidth,
                                         sample_time, trial_dms)
    # rescore offsets aligned to the chan axis once (zero channels are
    # exact no-ops); the escape hatch gets slices of the same raw table
    # and a matching pre-padded device array, so repeat buckets never
    # bounce the chunk through the host again
    offsets_raw, _ = pad_to_multiple(offsets_full, 1, chan_size,
                                     mode="constant")
    nchan_rs = offsets_raw.shape[1]
    _rs_cache = {}

    def _data_rs():
        """Chan-aligned float chunk for the escape-hatch rescore, built
        lazily: the fused program rescoring in-dispatch (the common
        case) and the certified chunk never pay it — on the packed path
        that also skips the whole device decode.  Device-side pad: a
        np.pad here would bounce the (possibly multi-GB,
        device-resident) chunk through the host on every search
        (code-review r7)."""
        if "v" not in _rs_cache:
            d = _float_data() if pf is not None else data
            _rs_cache["v"] = (jnp.pad(d, ((0, nchan_rs - nchan), (0, 0)))
                              if nchan_rs > nchan else d)
        return _rs_cache["v"]

    roll_k = 0
    rescore_max_off = None
    offsets_rs = offsets_raw  # the fused kernel's operand
    if rescore_kernel == "pallas":
        # ONE rebase bound over the full table, power-of-two rounded:
        # every bucket subset shares the compiled programs' static halo
        # (no per-subset cache keys, no silent retrace)
        from ..ops.pallas_dedisperse import rebase_offsets

        offsets_rs, roll_k, rescore_max_off = rebase_offsets(offsets_raw,
                                                             nsamples)
        if rescore_max_off > 0:
            rescore_max_off = 1 << int(
                np.ceil(np.log2(rescore_max_off + 1)))
        rescore_max_off = max(rescore_max_off, 256)

    def _round_up(x, m):
        return -(-x // m) * m

    bucket = _round_up(HYBRID_SEED_BUCKET, dm_size)
    bucket2 = _round_up(min(HYBRID_NEED_BUCKET, ndm), dm_size)
    fused_why = None
    if capture_plane:
        fused_why = "capture_plane needs the two-stage coarse program"
    elif snr_floor is not None and noise_certificate:
        fused_why = ("certificate mode: a certified chunk should pay one "
                     "coarse dispatch, not a burned seed rescore")
    elif rho_cert is False:
        fused_why = ("rho_cert=False drops the loop to legacy margins, "
                     "whose adaptive term the device cannot evaluate")
    elif ndm < max(bucket, bucket2):
        fused_why = f"trial grid ({ndm}) narrower than the seed bucket"
    elif use_pallas and _pick_fdmt_tile(nsamples) == 0:
        fused_why = "padded TPU time axis (rescore wrap convention)"
    if fused is True and fused_why is not None:
        raise ValueError(f"fused=True not eligible: {fused_why}")
    use_fused = fused is not False and fused_why is None
    from ..resilience import ladder as _ladder

    if fused is None and use_fused and _ladder.unfuse_engaged():
        # OOM ladder "unfuse" rung (ISSUE 12): under memory pressure
        # the one-dispatch program splits back into its coarse +
        # rescore composition, whose rescored set is already pinned
        # bit-identical to the fused run (explicit fused=True still
        # forces the fused program — the A/B baseline must not shift
        # under a stale global level)
        use_fused = False

    plane = None
    n_seed = n_need = 0
    seed_done = False
    if use_fused:
        # ---- ONE dispatch: coarse + seed + need-stage rescore ----------
        interpret = jax.default_backend() != "tpu"
        fdmt_dms, n_lo, n_hi = fdmt_trial_dms(nchan, dmmin, dmmax,
                                              start_freq, bandwidth,
                                              sample_time)
        idx = nearest_rows(fdmt_dms, trial_dms)
        slices = slice_delay_range(n_lo, n_hi, dm_size)
        t_tile = _pick_fdmt_tile(nsamples)
        if not use_pallas and t_tile == 0:
            t_tile = 1024  # unused by the XLA merge path
        plans = [fdmt_plan(nchan, float(start_freq), float(bandwidth), hi,
                           lo) for lo, hi in slices]
        tables = _stacked_tables(plans, t_tile)
        plan_key = tuple((it["k_tiles"], it["k_tiles_h"], it["rows_max"])
                         for it in tables)
        # plan row -> padded position in the all-gathered coarse pack:
        # device d's shard starts at d * rows_max and its row j holds
        # delay lo_d + j (the same stitching rule sharded_fdmt_search
        # applies host-side)
        rows_max = plan_key[-1][2]
        his = np.array([hi for _, hi in slices])
        los = np.array([lo for lo, _ in slices])
        delay = idx + n_lo
        dev = np.searchsorted(his, delay)
        idx_map = (dev * rows_max + (delay - los[dev])).astype(np.int32)

        chan_block = auto_chan_block(nchan_rs // chan_size, nsamples,
                                     bucket // dm_size)
        cert_params = fused_cert_params(
            nchan, trial_dms, start_freq, bandwidth, sample_time, nsamples,
            snr_floor=snr_floor, rho_cert=rho_cert, cert_slack=cert_slack)
        kernel_fn = _build_fused_sharded_hybrid(
            mesh, nchan, plans[0].nchan_padded, nsamples, t_tile,
            use_pallas, interpret, plan_key, ndm, bucket, bucket2,
            rescore_kernel, chan_block,
            0 if rescore_max_off is None else rescore_max_off, nchan_rs,
            pf.meta() if pf is not None else None)
        flat = []
        for it in tables:
            flat += [jnp.asarray(it[k]) for k in
                     ("idx_low", "idx_high", "shift", "shift_high")]
        from ..faults import inject as fault_inject
        from ..obs import roofline

        try:
            # the "mesh" fault site also fires HERE (not only in the
            # pipeline's run_one): direct callers — stream_search's
            # mesh route, tests — get the same injection seam; a
            # times=1 spec already consumed at the pipeline seam is
            # exhausted and no-ops here
            fault_inject.fire("mesh", chunk=None)
            roof = roofline.begin()
            with budget_bucket("search/fused"):
                # operand conversions stay inside the bucket
                # (attributed); on the packed path the operand IS the
                # raw packed bytes
                fused_args = (raw_dev if pf is not None else data,
                              jnp.asarray(idx_map),
                              jnp.asarray(offsets_rs),
                              jnp.asarray(cert_params),
                              jnp.int32(roll_k), *flat)
                packed = np.asarray(kernel_fn(*fused_args))
                budget_count("dispatches")
                budget_count("readbacks")
            roofline.end(roof, "sharded_fused_hybrid", kernel_fn,
                         fused_args)
        except (ValueError, TypeError):
            raise  # deterministic configuration error, never OOM
        except Exception as exc:  # jax errors share no base class
            if fused is True or not _ladder.is_resource_exhausted(exc):
                raise
            # the fused program's compound footprint OOMed: descend to
            # the two-stage composition (the "unfuse" rung) — its
            # rescored set is bit-identical to the fused one (ISSUE 12)
            _ladder.oom_event("mesh_fused")
            _ladder.descend("unfuse")
            logger.warning("fused mesh hybrid hit RESOURCE_EXHAUSTED "
                           "(%r); un-fusing to the two-stage "
                           "composition", exc)
            use_fused = False
        else:
            (coarse, sel, seed_scores, n_seed, sel2, need_scores,
             n_need) = unpack_fused_hybrid(packed, ndm, bucket, bucket2)
            maxvalues, stds, snrs = coarse[0], coarse[1], coarse[2]
            windows = np.rint(coarse[3]).astype(np.int32)
            peaks = np.rint(coarse[4]).astype(np.int64)
            cert_scores = coarse[5]
    if not use_fused:
        # ---- two-stage composition (plane capture / certificate mode /
        # forced A/B baseline): coarse program, scores mapped host-side
        # (a packed chunk rides through as raw bytes — the coarse
        # shard_map program unpacks in-body)
        coarse_out = sharded_fdmt_search(pf if pf is not None
                                         else data, dmmin, dmmax,
                                         start_freq, bandwidth,
                                         sample_time, mesh,
                                         axis="dm", with_cert=True,
                                         capture_plane=capture_plane)
        t_coarse, plane = (coarse_out if capture_plane
                           else (coarse_out, None))
        # coarse-table columns may still be device-backed; attribute the
        # conversion like every other coarse readback (putpu-lint
        # device-trip)
        with budget_bucket("search/coarse_readback"):
            idx = nearest_rows(np.asarray(t_coarse["DM"]), trial_dms)
            if plane is not None:
                plane = plane.remap(idx)  # coarse rows -> plan grid
            maxvalues = np.asarray(t_coarse["max"], np.float64)[idx]
            stds = np.asarray(t_coarse["std"], np.float64)[idx]
            snrs = np.asarray(t_coarse["snr"], np.float64)[idx]
            windows = np.asarray(t_coarse["rebin"], np.int32)[idx]
            peaks = np.asarray(t_coarse["peak"], np.int64)[idx]
            cert_scores = np.asarray(t_coarse["cert"], np.float64)[idx]
            budget_count("readbacks")

    coarse_snrs = snrs.copy()
    exact = np.zeros(ndm, dtype=bool)

    def _apply(blk, scored):
        m, s, b, w, p = scored
        k = len(blk)
        maxvalues[blk] = m[:k]
        stds[blk] = s[:k]
        snrs[blk] = b[:k]
        windows[blk] = w[:k]
        peaks[blk] = p[:k]
        exact[blk] = True

    def rescore(rows):
        """Escape hatch: exact scores via the sharded direct sweep —
        slices of the one cached offset table, pinned Pallas halo, and
        the pre-aligned device chunk (no per-bucket host work beyond
        the slice)."""
        budget_count("rescore_calls")
        budget_count("rescore_rows", len(rows))
        for blk, padded in iter_rescore_buckets(rows):
            t_ex = sharded_dedispersion_search(
                _data_rs(), dmmin, dmmax, start_freq, bandwidth, sample_time,
                mesh=mesh, trial_dms=trial_dms[padded],
                offsets=offsets_raw[padded],
                # the hatch must rescore with the SAME per-shard kernel
                # the fused program used (bit-identity contract) — an
                # independent kernel="auto" resolution at the bucket's
                # own geometry key could pick the other variant
                kernel=rescore_kernel,
                pallas_max_off=rescore_max_off)
            k = len(blk)
            _apply(blk, (np.asarray(t_ex["max"]), np.asarray(t_ex["std"]),
                         np.asarray(t_ex["snr"]),
                         np.asarray(t_ex["rebin"]),
                         np.asarray(t_ex["peak"])))

    if use_fused and n_seed <= bucket:
        # the device covered the loop's ENTIRE seed round; its scores are
        # the escape hatch's bit for bit (same per-shard kernel, channel
        # split and psum order), so the loop continues from the same
        # state the unfused path would reach.  A need stage that fit its
        # bucket likewise completes round 1; an overflowed stage is
        # discarded — the loop recomputes the full round itself.
        # roll_k=0 HERE: unlike the single-device fused kernel (which
        # scores the rebased plane and leaves the peak correction to
        # this unpack), the mesh kernel un-rotates in-kernel
        # (jnp.roll(dedisp, -roll_k) on the pallas rescore branch) to
        # stay bit-for-bit with the unfused sharded sweep — its peaks
        # arrive already in true coordinates, and subtracting roll_k
        # again would shift every seed/need arrival time on TPU meshes
        # (code-review r7)
        _apply(sel, fused_scores_to_host(seed_scores, 0, nsamples))
        seed_done = True
        if 0 < n_need <= bucket2:
            _apply(sel2, fused_scores_to_host(need_scores, 0, nsamples))

    certified, rho_cert_min = hybrid_certificate_gate(
        cert_scores, coarse_snrs, snrs, exact, rescore, nchan=nchan,
        trial_dms=trial_dms, start_freq=start_freq, bandwidth=bandwidth,
        sample_time=sample_time, nsamples=nsamples, snr_floor=snr_floor,
        noise_certificate=noise_certificate, seed_done=seed_done,
        rho_cert=rho_cert, cert_slack=cert_slack)
    table = ResultTable({
        "DM": trial_dms,
        "max": maxvalues,
        "std": stds,
        "snr": snrs,
        "rebin": windows,
        "peak": peaks,
        "exact": exact,
        "cert": cert_scores,
    }, meta=cert_meta(certified, rho_cert_min, snr_floor, cert_slack))
    return (table, plane) if capture_plane else table
