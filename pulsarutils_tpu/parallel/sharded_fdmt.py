"""DM-sliced sharded FDMT: the fast tree kernel scaled over a device mesh.

:mod:`.sharded` scales the *direct* sweep (the bit-exact kernel) over a
``(dm, chan)`` mesh; this module scales the *FDMT* — the throughput
kernel behind ``kernel="fdmt"`` and the hybrid — over the ``dm`` axis:

* the trial-delay range ``[n_lo, n_hi]`` splits into one contiguous
  slice per device;
* each device runs the **delay-range-pruned** transform
  (:class:`~pulsarutils_tpu.ops.fdmt.FdmtPlan` with its slice as
  ``[min_delay, max_delay]``) — rows outside its slice are never built,
  so per-device work for the deep (delay-dominated) iterations scales
  ~1/D while only the shallow channel-dominated iterations are
  replicated;
* the per-device merge schedules differ (different delay windows), but
  ``shard_map`` compiles ONE program: the tables are padded to common
  shapes and shipped as **sharded runtime operands** riding the merge
  kernel's scalar-prefetch inputs
  (:func:`~pulsarutils_tpu.ops.fdmt.merge_rows_traced`);
* scores come back ``dm``-sharded; each device's leading ``hi - lo + 1``
  rows are its delay slice and the padded remainder is dropped when the
  host stitches the global table.

Input data is replicated across the ``dm`` axis (each device needs the
whole band to dedisperse any trial — same trade the reference's
shared-memory ``prange`` sweep makes, ``pulsarutils/dedispersion.py:174``).
Communication: none at all inside the transform (the slices are
independent), so the layout scales over DCN as well as ICI.
"""

from __future__ import annotations

import functools

import numpy as np

from ..ops.fdmt import (
    MERGE_ROW_BLOCK,
    _pick_fdmt_tile,
    fdmt_plan,
    fdmt_trial_dms,
)
from ..utils.table import ResultTable
from .mesh import fetch_global

__all__ = ["sharded_fdmt_search", "sharded_hybrid_search",
           "slice_delay_range"]


def slice_delay_range(n_lo, n_hi, n_slices):
    """Split ``[n_lo, n_hi]`` (inclusive) into contiguous near-equal
    slices; returns a list of ``(lo, hi)`` pairs.  Requires at least one
    trial per slice."""
    total = n_hi - n_lo + 1
    if total < n_slices:
        raise ValueError(f"{total} trials cannot fill {n_slices} devices; "
                         "use a smaller mesh or a wider DM range")
    edges = [n_lo + (total * i) // n_slices for i in range(n_slices + 1)]
    return [(edges[i], edges[i + 1] - 1) for i in range(n_slices)]


def _pad_rows(a, rows):
    """Pad a 1-D table to ``rows`` by repeating its last entry."""
    return np.concatenate([a, a[-1:].repeat(rows - len(a))])


def _stacked_tables(plans, t_tile):
    """Per-iteration tables stacked over devices + static kernel bounds.

    Returns a list of dicts with ``idx_low/idx_high/shift/shift_high``
    as ``(D, rows_max)`` int32 arrays (device-shardable) and the static
    ``k_tiles``/``k_tiles_h``/``rows_max`` the one compiled program
    needs (maxima over devices).
    """
    n_iter = len(plans[0].iterations)
    assert all(len(p.iterations) == n_iter for p in plans)
    L = t_tile // 8
    out = []
    for i in range(n_iter):
        its = [p.iterations[i] for p in plans]
        rows_max = max(len(it["idx_low"]) for it in its)
        rows_max += (-rows_max) % min(MERGE_ROW_BLOCK, rows_max)
        idx_low = np.stack([_pad_rows(it["idx_low"], rows_max)
                            for it in its])
        idx_high = np.stack([_pad_rows(it["idx_high"], rows_max)
                             for it in its])
        shift = np.stack([_pad_rows(it["shift"], rows_max) for it in its])
        max_shift = int(shift.max(initial=0))
        k_tiles = (max_shift // L + 23) // 8
        if its[0]["shift_high"] is not None:
            shift_high = np.stack([_pad_rows(it["shift_high"], rows_max)
                                   for it in its])
            k_tiles_h = (int(shift_high.max(initial=0)) // L + 23) // 8
        else:
            shift_high = np.zeros_like(shift)
            k_tiles_h = 0
        out.append({
            "idx_low": idx_low.astype(np.int32),
            "idx_high": idx_high.astype(np.int32),
            "shift": shift.astype(np.int32),
            "shift_high": shift_high.astype(np.int32),
            "k_tiles": k_tiles,
            "k_tiles_h": k_tiles_h,
            "rows_max": rows_max,
        })
    return out


@functools.lru_cache(maxsize=8)
def _build_sharded_fdmt(mesh, axis, nchan, nchan_padded, t, t_tile,
                        use_pallas, interpret, plan_key, t_orig,
                        with_cert=False, with_plane=False):
    """Compile the SPMD transform+score program for one mesh/geometry.

    ``plan_key`` carries the static per-iteration bounds (k_tiles,
    rows_max, ...) so the cache key captures the schedule shapes; the
    table *values* are runtime operands.  ``t`` is the (possibly padded)
    run length; scores are computed over the first ``t_orig`` samples.
    ``with_plane`` additionally emits the final transform state — the
    dedispersed plane, DM-sharded ``P(axis, None)`` and device-resident
    (the mesh plane-products path, :mod:`.sharded_plane`).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.fdmt import _merge_xla, merge_rows_traced
    from ..ops.search import score_profiles_chunked

    iter_meta = plan_key  # tuple of (k_tiles, k_tiles_h, rows_max)

    def local_fn(data, *tables):
        # data (nchan, T) replicated; tables: 4 arrays per iteration,
        # each (1, rows_max) — this device's merge schedule
        state = data
        if nchan < nchan_padded:
            state = jnp.concatenate(
                [state, jnp.zeros((nchan_padded - nchan, t), state.dtype)])
        for i, (k_tiles, k_tiles_h, rows_max) in enumerate(iter_meta):
            il, ih, sh, shh = (tables[4 * i + j][0] for j in range(4))
            if use_pallas:
                state = merge_rows_traced(
                    state, il, ih, sh,
                    shh if k_tiles_h else jnp.zeros_like(sh),
                    k_tiles=k_tiles, k_tiles_h=k_tiles_h, t_tile=t_tile,
                    interpret=interpret)
            else:
                state = _merge_xla(state, il, ih, sh,
                                   shh if k_tiles_h else None)
        if t_orig != t:
            state = state[:, :t_orig]
        # score every (padded) row; junk rows are dropped host-side
        scores = score_profiles_chunked(state, jnp,
                                        with_cert=with_cert)[None]
        return (scores, state) if with_plane else scores

    in_specs = [P()] + [P(axis)] * (4 * len(iter_meta))
    out_specs = (P(axis), P(axis, None)) if with_plane else P(axis)
    fn = jax.jit(jax.shard_map(
        local_fn, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_specs,
        # pallas_call outputs carry no varying-mesh-axes metadata, which
        # trips shard_map's vma lint; there are no collectives at all in
        # this program, so the check adds nothing
        check_vma=not use_pallas))
    return fn


def sharded_fdmt_search(data, dmmin, dmmax, start_freq, bandwidth,
                        sample_time, mesh, axis="dm", use_pallas=None,
                        with_cert=False, capture_plane=False):
    """FDMT sweep with the trial-DM axis sharded over ``mesh[axis]``.

    Same scientific contract as ``dedispersion_search(kernel="fdmt")``
    (integer band-delay trial grid, within-one-trial hit agreement with
    the exact kernels), with per-device HBM for the output plane/state
    cut ~1/D and the deep tree iterations parallelised over devices.
    ``use_pallas`` forces the Pallas (True, interpret mode off-TPU — for
    testing the traced-table kernel path) or XLA (False) merge; default
    auto: Pallas on TPU.

    Returns a :class:`~pulsarutils_tpu.utils.table.ResultTable` with the
    usual ``DM, max, std, snr, rebin, peak`` columns over the full grid.
    With ``capture_plane`` returns ``(table, plane)`` where ``plane`` is
    a :class:`~pulsarutils_tpu.parallel.sharded_plane.ShardedPlane` —
    the dedispersed plane left DM-sharded and device-resident, with
    shard-local per-row products (the mesh diagnostics/period-search
    path; the single-device path's host-gathered plane never exists).
    """
    import jax
    import jax.numpy as jnp

    from ..ops.search import unstack_scores

    nchan, t = np.shape(data)
    n_dev = mesh.shape[axis]
    trial_dms, n_lo, n_hi = fdmt_trial_dms(nchan, dmmin, dmmax, start_freq,
                                           bandwidth, sample_time)
    slices = slice_delay_range(n_lo, n_hi, n_dev)

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    interpret = jax.default_backend() != "tpu"
    data = jnp.asarray(data, jnp.float32)
    t_run = t
    t_tile = _pick_fdmt_tile(t)
    if use_pallas and t_tile == 0:
        # same zero-pad rule as the single-device path
        # (ops/fdmt.py:_transform_setup): the XLA merge's per-row rolls
        # scalarise on TPU, so padding to a tile multiple and slicing
        # the scores back is far cheaper than falling off Pallas
        t_run = -(-t // 1024) * 1024
        data = jnp.pad(data, ((0, 0), (0, t_run - t)))
        t_tile = _pick_fdmt_tile(t_run)
    elif t_tile == 0:
        t_tile = 1024  # unused by the XLA merge path

    plans = [fdmt_plan(nchan, float(start_freq), float(bandwidth), hi, lo)
             for lo, hi in slices]
    tables = _stacked_tables(plans, t_tile)
    plan_key = tuple((it["k_tiles"], it["k_tiles_h"], it["rows_max"])
                     for it in tables)

    fn = _build_sharded_fdmt(mesh, axis, nchan, plans[0].nchan_padded,
                             t_run, t_tile, use_pallas, interpret,
                             plan_key, t, with_cert, capture_plane)
    flat = []
    for it in tables:
        flat += [jnp.asarray(it[k]) for k in
                 ("idx_low", "idx_high", "shift", "shift_high")]
    plane_handle = None
    if capture_plane:
        from .sharded_plane import ShardedPlane

        out, plane = fn(data, *flat)
        out = fetch_global(out)
        # device d's padded shard starts at d * rows_max in the global
        # concatenated plane; its first (hi-lo+1) rows are its slice
        rows_max = plane.shape[0] // n_dev
        row_index = np.concatenate(
            [d * rows_max + np.arange(hi - lo + 1)
             for d, (lo, hi) in enumerate(slices)])
        plane_handle = ShardedPlane(plane, mesh, axis, row_index)
    else:
        out = fetch_global(fn(data, *flat))

    # stitch the dm-sharded scores: device d's first (hi-lo+1) rows are
    # its delay slice; the rest is padding junk
    cols = []
    for d, (lo, hi) in enumerate(slices):
        stacked = out[d]  # (5|6, rows_max_final)
        cols.append(stacked[:, :hi - lo + 1])
    scores = unstack_scores(np.concatenate(cols, axis=1))
    maxvalues, stds, snrs, wins, peaks = scores[:5]
    columns = {
        "DM": trial_dms,
        "max": maxvalues,
        "std": stds,
        "snr": snrs,
        "rebin": wins,
        "peak": peaks,
    }
    if with_cert:
        columns["cert"] = scores[5]
    table = ResultTable(columns)
    return (table, plane_handle) if capture_plane else table


def sharded_hybrid_search(data, dmmin, dmmax, start_freq, bandwidth,
                          sample_time, mesh, snr_floor=None,
                          noise_certificate=True, capture_plane=False,
                          rho_cert=None, cert_slack=None):
    """Hybrid (exact hits at coarse cost) over a ``(dm, chan)`` mesh.

    Multi-device composition of ``dedispersion_search(kernel="hybrid")``:
    the coarse stage is the DM-sliced sharded FDMT (the ``chan`` axis is
    idle/replicated there — use ``chan=1`` meshes when the coarse stage
    dominates), and the exact rescore of candidate rows runs through
    :func:`~pulsarutils_tpu.parallel.sharded.sharded_dedispersion_search`
    over the full mesh.  The guarantee loop, the cert-based skip
    criterion and the noise certificate are shared with the
    single-device hybrid (:mod:`~pulsarutils_tpu.ops.certify`), so the
    contract is identical: the returned argbest row holds the exact
    kernel's scores (unless ``meta["certified"]``, which asserts no
    detection above ``snr_floor`` exists — sound under the stated
    signal model up to the Gaussian noise cross-term, residual risk in
    ``meta["cert_miss_p_at_floor"]``), with an ``exact`` column marking
    exact rows.

    ``capture_plane`` returns ``(table, plane)`` with ``plane`` a
    :class:`~.sharded_plane.ShardedPlane` over the *coarse* (FDMT) plane
    remapped to the plan grid — the same coarse-plane convention as the
    single-device hybrid's capture (``ops/search.py``:
    ``_search_jax_hybrid``), kept DM-sharded and device-resident.

    ``rho_cert`` / ``cert_slack`` mirror ``dedispersion_search``'s
    knobs: a precomputed retention bound (or ``False`` to opt out of
    the cert machinery) and a certificate slack derived from a target
    miss probability (:func:`~pulsarutils_tpu.ops.certify.cert_slack_for_miss_p`).
    """
    import jax.numpy as jnp

    from ..ops.certify import cert_meta
    from ..ops.plan import dedispersion_plan
    from ..ops.search import (
        hybrid_certificate_gate,
        iter_rescore_buckets,
        nearest_rows,
    )
    from .sharded import sharded_dedispersion_search

    nchan, nsamples = np.shape(data)
    # (the pad-free soundness guard lives in hybrid_certificate_gate,
    # shared verbatim with the single-device hybrid)
    # ONE host->device transfer: the coarse stage and every rescore call
    # reuse the same device-resident array (sharded_dedispersion_search
    # passes aligned device inputs through untouched)
    data = jnp.asarray(data, jnp.float32)
    coarse_out = sharded_fdmt_search(data, dmmin, dmmax, start_freq,
                                     bandwidth, sample_time, mesh,
                                     axis="dm", with_cert=True,
                                     capture_plane=capture_plane)
    t_coarse, plane = coarse_out if capture_plane else (coarse_out, None)
    trial_dms = np.asarray(dedispersion_plan(
        nchan, dmmin, dmmax, start_freq, bandwidth, sample_time),
        dtype=np.float64)
    ndm = len(trial_dms)
    idx = nearest_rows(np.asarray(t_coarse["DM"]), trial_dms)
    if plane is not None:
        plane = plane.remap(idx)  # coarse rows -> plan grid, still sharded

    maxvalues = np.asarray(t_coarse["max"], np.float64)[idx]
    stds = np.asarray(t_coarse["std"], np.float64)[idx]
    snrs = np.asarray(t_coarse["snr"], np.float64)[idx]
    windows = np.asarray(t_coarse["rebin"], np.int32)[idx]
    peaks = np.asarray(t_coarse["peak"], np.int64)[idx]
    cert_scores = np.asarray(t_coarse["cert"], np.float64)[idx]
    coarse_snrs = snrs.copy()
    exact = np.zeros(ndm, dtype=bool)

    def rescore(rows):
        for blk, padded in iter_rescore_buckets(rows):
            t_ex = sharded_dedispersion_search(
                data, dmmin, dmmax, start_freq, bandwidth, sample_time,
                mesh=mesh, trial_dms=trial_dms[padded])
            k = len(blk)
            maxvalues[blk] = np.asarray(t_ex["max"])[:k]
            stds[blk] = np.asarray(t_ex["std"])[:k]
            snrs[blk] = np.asarray(t_ex["snr"])[:k]
            windows[blk] = np.asarray(t_ex["rebin"])[:k]
            peaks[blk] = np.asarray(t_ex["peak"])[:k]
            exact[blk] = True

    certified, rho_cert_min = hybrid_certificate_gate(
        cert_scores, coarse_snrs, snrs, exact, rescore, nchan=nchan,
        trial_dms=trial_dms, start_freq=start_freq, bandwidth=bandwidth,
        sample_time=sample_time, nsamples=nsamples, snr_floor=snr_floor,
        noise_certificate=noise_certificate, rho_cert=rho_cert,
        cert_slack=cert_slack)
    table = ResultTable({
        "DM": trial_dms,
        "max": maxvalues,
        "std": stds,
        "snr": snrs,
        "rebin": windows,
        "peak": peaks,
        "exact": exact,
        "cert": cert_scores,
    }, meta=cert_meta(certified, rho_cert_min, snr_floor, cert_slack))
    return (table, plane) if capture_plane else table
