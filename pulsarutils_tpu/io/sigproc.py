"""Native SIGPROC filterbank I/O.

The reference reads filterbank files through the third-party
``sigpyproc.Readers.FilReader`` (``pulsarutils/clean.py:18,284-294``,
``pulsarutils/stats.py:6,37``).  This framework implements the format
natively: a binary header of length-prefixed keyword/value records between
``HEADER_START`` and ``HEADER_END``, followed by time-major sample frames
of ``nifs * nchans`` values at 8/16/32 bits.

Provided:

* :class:`FilterbankReader` — memory-mapped reader with the
  ``read_block(istart, nsamples) -> (nchans, n)`` access pattern the
  pipeline drivers use, plus a sigpyproc-compatible ``header`` dict
  (``fbottom``/``ftop``/``bandwidth``/``foff``/``nchans``/``tsamp``/
  ``nsamples``/``tstart`` — the exact keys the reference pipeline consumes,
  ``clean.py:284-294``).
* :class:`FilterbankWriter` / :func:`write_filterbank` — streaming writer,
  which also makes ``PUclean`` a real tool (the reference's
  ``cleanup_data`` was a stub, ``clean.py:354-357``).

Byte order is little-endian (SIGPROC convention on all modern hardware).
"""

from __future__ import annotations

import os
import struct

import numpy as np

from ..faults import inject as fault_inject

_INT_KEYS = {
    "machine_id", "telescope_id", "data_type", "barycentric",
    "pulsarcentric", "nbits", "nsamples", "nchans", "nifs", "nbeams",
    "ibeam",
}
_DOUBLE_KEYS = {
    "az_start", "za_start", "src_raj", "src_dej", "tstart", "tsamp",
    "fch1", "foff", "refdm", "period",
}
_STR_KEYS = {"source_name", "rawdatafile"}
#: single-byte keys (sigproc's ``signed`` flag for 8-bit data)
_CHAR_KEYS = {"signed"}

_DTYPES = {8: np.uint8, 16: np.uint16, 32: np.float32}


def _pack_string(s):
    b = s.encode("ascii")
    return struct.pack("<i", len(b)) + b


def _pack_record(key, value):
    rec = _pack_string(key)
    if key in _INT_KEYS:
        rec += struct.pack("<i", int(value))
    elif key in _DOUBLE_KEYS:
        rec += struct.pack("<d", float(value))
    elif key in _STR_KEYS:
        rec += _pack_string(str(value))
    elif key in _CHAR_KEYS:
        rec += struct.pack("<b", int(value))
    else:
        raise KeyError(f"unknown SIGPROC header key {key!r}")
    return rec


def _read_exact(f, n, path, what):
    """Read exactly ``n`` bytes or raise a clean ``ValueError`` naming
    the byte offset and expected length (a file truncated mid-header
    used to surface as a raw ``struct.error`` from ``struct.unpack``)."""
    offset = f.tell()
    data = f.read(n)
    if len(data) != n:
        raise ValueError(
            f"{path}: truncated SIGPROC header — expected {n} bytes for "
            f"{what} at byte offset {offset}, got {len(data)}")
    return data


def read_header(path):
    """Parse a SIGPROC header.  Returns ``(header_dict, data_offset)``."""
    header = {}
    with open(path, "rb") as f:
        def read_string():
            (n,) = struct.unpack(
                "<i", _read_exact(f, 4, path, "a string length"))
            if not 0 < n < 128:
                raise ValueError(f"corrupt SIGPROC header string length {n}")
            return _read_exact(f, n, path,
                               "a header string").decode("ascii")

        if read_string() != "HEADER_START":
            raise ValueError(f"{path}: not a SIGPROC filterbank file")
        while True:
            key = read_string()
            if key == "HEADER_END":
                break
            if key in _INT_KEYS:
                (header[key],) = struct.unpack(
                    "<i", _read_exact(f, 4, path, f"int key {key!r}"))
            elif key in _DOUBLE_KEYS:
                (header[key],) = struct.unpack(
                    "<d", _read_exact(f, 8, path, f"double key {key!r}"))
            elif key in _STR_KEYS:
                header[key] = read_string()
            elif key in _CHAR_KEYS:
                (header[key],) = struct.unpack(
                    "<b", _read_exact(f, 1, path, f"char key {key!r}"))
            else:
                # unknown keys cannot be skipped (their payload length is
                # key-specific), so fail loudly with the offending name
                raise ValueError(f"{path}: unknown header key {key!r}")
        return header, f.tell()


def derived_header(header, data_size_bytes):
    """Add the derived fields the pipeline consumes (band edges, size).

    Channel ``i`` has centre frequency ``fch1 + i * foff``; band edges
    extend half a channel beyond the extreme centres.  ``foff < 0``
    (descending band) is the common convention; both signs are handled.
    """
    h = dict(header)
    nchans = h["nchans"]
    nifs = h.get("nifs", 1)
    nbits = h.get("nbits", 32)
    fch1, foff = h["fch1"], h["foff"]
    centres = fch1 + np.arange(nchans) * foff
    h["bandwidth"] = abs(foff) * nchans
    h["fbottom"] = float(centres.min() - abs(foff) / 2)
    h["ftop"] = float(centres.max() + abs(foff) / 2)
    bytes_per_sample = nchans * nifs * nbits // 8
    available = int(data_size_bytes // bytes_per_sample)
    if "nsamples" not in h or h["nsamples"] <= 0:
        h["nsamples"] = available
    else:
        # a truncated data section (interrupted write / partial transfer)
        # must not crash the memmap — read what is actually present
        h["nsamples"] = min(int(h["nsamples"]), available)
    h.setdefault("tstart", 0.0)
    return h


class FilterbankReader:
    """Memory-mapped SIGPROC filterbank reader.

    ``read_block(istart, n)`` returns a float ``(nchans, n)`` array in
    **ascending frequency order** when ``band_ascending=True`` (default
    False returns file order) — the reference flips descending bands by
    hand in its chunk loop (``clean.py:332-333``); the flag folds that in.

    Multi-IF files (``nifs > 1`` — polarisation/IF planes interleaved
    per time frame as ``[t][if][chan]``, the SIGPROC layout) are
    supported natively (the reference inherited this from sigpyproc's
    ``FilReader``, used at ``clean.py:284-294`` / ``stats.py:37``):
    ``if_mode`` selects what ``read_block`` returns —

    * ``"sum"`` (default): total intensity, the IF planes summed — what
      a single-pulse search wants from e.g. dual-polarisation data;
    * an integer ``k``: IF plane ``k`` alone.
    """

    def __init__(self, path, if_mode="sum"):
        self.path = path
        raw_header, offset = read_header(path)
        data_size = os.path.getsize(path) - offset
        self.header = derived_header(raw_header, data_size)
        nbits = self.header.get("nbits", 32)
        self._nbits = nbits
        nifs = self.header.get("nifs", 1)
        self.nifs = nifs
        if if_mode != "sum":
            k = int(if_mode)
            if not 0 <= k < nifs:
                raise ValueError(f"if_mode={if_mode!r}: file has {nifs} "
                                 "IF planes")
        self.if_mode = if_mode
        nchans = self.header["nchans"]
        width = nifs * nchans  # values per time frame
        if nbits in (1, 2, 4):
            # packed low-bit samples: mmap the raw bytes, unpack per block
            # (native C loop when available — io/lowbit.py)
            if (width * nbits) % 8:
                raise ValueError(
                    f"nchans={nchans} x nifs={nifs} at nbits={nbits} does "
                    "not pack to whole bytes")
            self._mmap = np.memmap(
                path, dtype=np.uint8, mode="r", offset=offset,
                shape=(self.header["nsamples"], width * nbits // 8))
        elif nbits in _DTYPES:
            self._dtype = _DTYPES[nbits]
            if nbits == 8 and self.header.get("signed"):
                self._dtype = np.int8  # sigproc ``signed`` char flag
            self._mmap = np.memmap(path, dtype=self._dtype, mode="r",
                                   offset=offset,
                                   shape=(self.header["nsamples"], width))
        else:
            raise ValueError(f"unsupported nbits={nbits}")

    @property
    def nsamples(self):
        return self.header["nsamples"]

    @property
    def nchans(self):
        return self.header["nchans"]

    @property
    def band_descending(self):
        return self.header["foff"] < 0

    @property
    def nbeams(self):
        """Total beams of the observation this file belongs to (sigproc
        ``nbeams`` header key; ``None`` when the header omits it)."""
        n = self.header.get("nbeams")
        return int(n) if n is not None else None

    @property
    def ibeam(self):
        """This file's beam number (sigproc ``ibeam``, conventionally
        1-based; ``None`` when absent).  The multi-beam driver uses it
        to label per-beam candidates, canaries and coincidence groups
        without re-opening files."""
        b = self.header.get("ibeam")
        return int(b) if b is not None else None

    def read_block(self, istart, nsamps, band_ascending=False):
        istart = int(istart)
        fault_inject.fire("read", chunk=istart)
        nsamps = int(min(nsamps, self.nsamples - istart))
        nsamps = fault_inject.truncated_length("read", istart, nsamps)
        raw = np.asarray(self._mmap[istart:istart + nsamps])
        return self.unpack_frames(raw, band_ascending=band_ascending)

    def read_block_packed(self, istart, nsamps):
        """Raw packed frames ``(nsamps, bytes_per_frame)`` uint8 — the
        low-bit fast path: callers ship THESE over the host->device
        link (1/16th the bytes of float32 at 2 bits) and unpack in the
        device-clean jit (:func:`..io.lowbit.device_unpack_block`);
        :meth:`unpack_frames` is the matching host-side decode for
        fallback paths.  Low-bit single-IF files only: the device-side
        unpack takes the first ``nchans`` values of each frame, which on
        a multi-IF file would silently decode IF 0 instead of honouring
        ``if_mode`` the way :meth:`read_block` does."""
        if self._nbits not in (1, 2, 4):
            raise ValueError(
                f"read_block_packed needs a packed low-bit file "
                f"(nbits={self._nbits})")
        if self.nifs != 1:
            raise ValueError(
                f"read_block_packed is single-IF only (nifs={self.nifs}); "
                "use read_block, which honours if_mode")
        istart = int(istart)
        fault_inject.fire("read", chunk=istart)
        nsamps = int(min(nsamps, self.nsamples - istart))
        nsamps = fault_inject.truncated_length("read", istart, nsamps)
        return np.asarray(self._mmap[istart:istart + nsamps])

    def unpack_frames(self, raw, band_ascending=False):
        """Decode raw frames (packed low-bit or plain) to the
        ``(nchan, nsamps)`` float block ``read_block`` returns."""
        nsamps = raw.shape[0]
        if self._nbits in (1, 2, 4):
            from .lowbit import unpack

            frames = unpack(raw, self._nbits).reshape(
                nsamps, self.nifs, self.nchans).astype(float)
        else:
            frames = raw.reshape(nsamps, self.nifs,
                                 self.nchans).astype(float)
        if self.nifs == 1:
            block = frames[:, 0].T
        elif self.if_mode == "sum":
            block = frames.sum(axis=1).T
        else:
            block = frames[:, int(self.if_mode)].T
        if band_ascending and self.band_descending:
            block = block[::-1]
        return block

    def readBlock(self, istart, nsamps, as_filterbankBlock=False,
                  band_ascending=False):
        """sigpyproc-compatible alias: the reference calls
        ``readBlock(istart, size, as_filterbankBlock=False)``
        (reference ``stats.py:44``, ``clean.py:327``); the flag is accepted
        and ignored (plain arrays are always returned)."""
        return self.read_block(istart, nsamps, band_ascending=band_ascending)

    def iter_blocks(self, chunksize, band_ascending=False):
        """Yield ``(istart, block)`` over the whole file."""
        for istart in range(0, self.nsamples, chunksize):
            yield istart, self.read_block(istart, chunksize,
                                          band_ascending=band_ascending)


class FilterbankWriter:
    """Streaming SIGPROC filterbank writer (time-major frames).

    With ``nifs > 1`` in the header, :meth:`write_block` takes
    ``(nifs, nchans, n)`` blocks and interleaves the IF planes per time
    frame (the SIGPROC ``[t][if][chan]`` layout the reader expects).
    """

    def __init__(self, path, header):
        self.path = path
        self.header = dict(header)
        self.nchans = int(self.header["nchans"])
        self.nifs = int(self.header.get("nifs", 1))
        self.nbits = int(self.header.get("nbits", 32))
        if self.nbits in (1, 2, 4):
            if (self.nifs * self.nchans * self.nbits) % 8:
                raise ValueError(
                    f"nchans={self.nchans} x nifs={self.nifs} at "
                    f"nbits={self.nbits} does not pack to whole bytes")
            self._dtype = np.uint8
        elif self.nbits in _DTYPES:
            self._dtype = _DTYPES[self.nbits]
            if self.nbits == 8 and self.header.get("signed"):
                self._dtype = np.int8  # sigproc ``signed`` char flag
        else:
            raise ValueError(f"unsupported nbits={self.nbits}")
        self._file = open(path, "wb")
        self._nsamples_written = 0
        self._file.write(_pack_string("HEADER_START"))
        for key in sorted(set(self.header) & (_INT_KEYS | _DOUBLE_KEYS |
                                              _STR_KEYS | _CHAR_KEYS)):
            if key == "nsamples":
                continue  # computed from data size on read
            self._file.write(_pack_record(key, self.header[key]))
        self._file.write(_pack_string("HEADER_END"))

    def write_block(self, block):
        """Write a ``(nchans, n)`` block (channel-major in, time-major
        out), or ``(nifs, nchans, n)`` for a multi-IF file."""
        block = np.asarray(block)
        if self.nifs > 1:
            if block.ndim != 3 or block.shape[:2] != (self.nifs,
                                                      self.nchans):
                raise ValueError(
                    f"multi-IF block must be ({self.nifs}, {self.nchans}, "
                    f"n); got {block.shape}")
            nsamps = block.shape[2]
            frames = np.ascontiguousarray(
                block.transpose(2, 0, 1)).reshape(nsamps,
                                                  self.nifs * self.nchans)
        else:
            if block.shape[0] != self.nchans:
                raise ValueError(f"block has {block.shape[0]} channels, "
                                 f"expected {self.nchans}")
            nsamps = block.shape[1]
            frames = np.ascontiguousarray(block.T)
        if self.nbits in (1, 2, 4):
            from .lowbit import pack

            frames = pack(frames, self.nbits)  # clips to [0, 2^nbits - 1]
            self._file.write(frames.tobytes())
            self._nsamples_written += nsamps
            return
        if self.nbits < 32:
            info = np.iinfo(self._dtype)
            frames = np.clip(np.rint(frames), info.min, info.max)
        self._file.write(frames.astype(self._dtype).tobytes())
        self._nsamples_written += nsamps

    def close(self):
        if not self._file.closed:
            self._file.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_filterbank(path, data, tsamp, fch1, foff, nbits=32, tstart=0.0,
                     source_name="pulsarutils_tpu", **extra):
    """Write a whole ``(nchans, nsamples)`` array as a filterbank file."""
    data = np.asarray(data)
    header = {
        "nchans": data.shape[0],
        "nbits": nbits,
        "nifs": 1,
        "tsamp": tsamp,
        "fch1": fch1,
        "foff": foff,
        "tstart": tstart,
        "source_name": source_name,
        "machine_id": 0,
        "telescope_id": 0,
        "data_type": 1,
    }
    header.update(extra)
    with FilterbankWriter(path, header) as w:
        w.write_block(data)
    return header


def write_simulated_filterbank(path, array, sim_header, descending=False,
                               **extra):
    """Write a simulator-convention array (ascending band, row i = lowest
    frequency first) as a filterbank file, handling the row flip a
    descending-band header requires.

    Use this instead of composing :func:`write_filterbank` +
    :func:`header_from_simulated` by hand — forgetting the row flip for
    ``descending=True`` silently corrupts the band orientation and ruins
    DM recovery.
    """
    data = np.asarray(array)[::-1] if descending else array
    kw = header_from_simulated(sim_header, descending=descending)
    kw.update(extra)
    return write_filterbank(path, data, **kw)


def header_from_simulated(sim_header, descending=False):
    """Map a simulator header (ascending-band, band-edge keys) onto writer
    kwargs (``fch1``/``foff`` channel-centre convention)."""
    nchan = sim_header["nchans"]
    df = sim_header["bandwidth"] / nchan
    if descending:
        fch1 = sim_header["fbottom"] + sim_header["bandwidth"] - df / 2
        foff = -df
    else:
        fch1 = sim_header["fbottom"] + df / 2
        foff = df
    return {"tsamp": sim_header["tsamp"], "fch1": fch1, "foff": foff}
