"""Candidate store with deterministic resume.

The reference persisted candidates as ad-hoc pickles named
``{root}_{istart}-{iend}.pkl`` (``pulsarutils/clean.py:349-351``) and had no
way to resume a crashed search except a manual ``tmin`` (``clean.py:276``,
SURVEY §5).  This store makes both first-class:

* candidates are npz records (:class:`..pipeline.pulse_info.PulseInfo`)
  plus the chunk's full result table, named by chunk index — safe to load,
  idempotent to rewrite;
* a ``progress.json`` ledger records every *processed* chunk (hit or not),
  keyed by a config fingerprint, so a restarted search skips exactly the
  work already done and redoes nothing else.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os

from ..faults import inject as fault_inject
from ..pipeline.pulse_info import PulseInfo
from ..utils.table import ResultTable

logger = logging.getLogger("pulsarutils_tpu")


def config_fingerprint(**kwargs):
    """Stable hash of the search configuration; a resume ledger is only
    valid for identical configuration."""
    blob = json.dumps(kwargs, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class CandidateStore:
    """``fingerprint=None`` disables the resume ledger entirely (every
    chunk reports not-done, nothing is recorded) — a no-resume run must
    never pollute another configuration's ledger.  Each fingerprint gets
    its own ledger file, so interleaved runs over different files/configs
    in one output directory never invalidate each other."""

    def __init__(self, directory, fingerprint=None):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.fingerprint = fingerprint
        if fingerprint is None:
            self._ledger_path = None
            self._ledger = {"fingerprint": None, "done": []}
        else:
            self._ledger_path = os.path.join(
                self.directory, f"progress_{fingerprint}.json")
            self._ledger = self._load_ledger()

    def _load_ledger(self):
        """Load the ledger, surviving a torn/corrupt file.

        ``mark_done`` writes atomically (tmp + rename), but the file can
        still arrive torn — a crash mid-``os.replace`` on some
        filesystems, a partial rsync, disk corruption.  A corrupt ledger
        used to raise ``json.JSONDecodeError`` and kill resume entirely;
        now the bad file is backed up to ``<ledger>.corrupt`` and a
        fresh ledger starts (worst case: already-done chunks are
        re-searched, which resume semantics make idempotent).

        Only parse/shape failures (``ValueError``) mean corruption: a
        transient ``OSError`` on an intact file must propagate, not
        trash hours of resume progress (code-review r8).
        """
        if os.path.exists(self._ledger_path):
            try:
                with open(self._ledger_path) as f:
                    ledger = json.load(f)
                if not isinstance(ledger, dict) \
                        or not isinstance(ledger.get("done"), list):
                    raise ValueError("ledger is not a {fingerprint, done} "
                                     "record")
                return ledger
            except ValueError as exc:
                backup = self._ledger_path + ".corrupt"
                try:
                    os.replace(self._ledger_path, backup)
                except OSError:
                    backup = "<unremovable>"
                logger.warning(
                    "torn/corrupt resume ledger %s (%r): backed up to %s, "
                    "starting a fresh ledger (done chunks will be "
                    "re-searched)", self._ledger_path, exc, backup)
        return {"fingerprint": self.fingerprint, "done": []}

    # -- resume ledger -------------------------------------------------------

    def is_done(self, istart):
        if self.fingerprint is None:
            return False
        return istart in self._ledger["done"]

    def mark_done(self, istart, reason=None):
        """Record a chunk as processed.  ``reason`` marks a chunk done
        **with a reason** — quarantined or persist-dead-lettered: it is
        never re-searched on resume (exact resume semantics), and the
        reason survives in the ledger for the integrity audit.  The
        ``quarantined`` key only appears when a reason was recorded, so
        a clean run's ledger stays byte-identical to pre-hardening."""
        if self.fingerprint is None:
            return
        quarantined = self._ledger.get("quarantined", {})
        if istart not in self._ledger["done"] \
                or (reason is not None
                    and quarantined.get(str(istart)) != reason):
            if istart not in self._ledger["done"]:
                self._ledger["done"].append(istart)
            if reason is not None:
                self._ledger.setdefault(
                    "quarantined", {})[str(istart)] = str(reason)
            tmp = self._ledger_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._ledger, f)
            os.replace(tmp, self._ledger_path)  # atomic: crash-safe resume

    @property
    def done_chunks(self):
        return sorted(self._ledger["done"])

    @property
    def quarantined_chunks(self):
        """``{str(istart): reason}`` for chunks marked done-with-reason."""
        return dict(self._ledger.get("quarantined", {}))

    # -- candidates ----------------------------------------------------------

    def _base(self, root, istart, iend):
        return os.path.join(self.directory, f"{root}_{istart}-{iend}")

    #: persisted-waterfall element budget: above this, ``save_candidate``
    #: stores a window around the pulse instead of the whole chunk (a
    #: 1024 x 1M survey chunk is a multi-GB compressed npz per hit and
    #: took ~10 min of single-core zlib per candidate — measured in the
    #: round-5 survey rehearsal, where persist dominated the pipeline)
    WATERFALL_BUDGET = 1 << 22

    def save_candidate(self, root, istart, iend, info: PulseInfo,
                       table: ResultTable):
        fault_inject.fire("persist", chunk=istart)
        base = self._base(root, istart, iend)
        self.trim_waterfall(info, table).save(base + ".info.npz")
        table.to_npz(base + ".table.npz")
        return base

    def trim_waterfall(self, info, table):
        """Bound the persisted record: full chunk in, pulse cutout out.

        The window covers the dispersed track — ``[peak - pad,
        peak + span + pad]`` with ``span`` the band-crossing delay at
        the candidate's DM — then block-sum decimates if still over
        budget.  The passed ``info`` is untouched (a trimmed *copy* is
        returned, or ``info`` itself when already under budget), with
        ``cutout_start``/``cutout_decim`` recording the window (see
        :class:`..pipeline.pulse_info.PulseInfo`).

        Tracks wrapping the chunk end are followed circularly (round 6,
        ADVICE r5): the search's roll convention wraps a dispersed tail
        past the chunk end to the chunk start, so for a pulse near the
        end the informative columns live at BOTH edges — the window is
        taken mod ``nbin`` (``cutout_start`` may therefore exceed
        ``nbin - width``; consumers recover absolute columns as
        ``(cutout_start + j * cutout_decim) mod nbin``).

        ``info.allprofs`` may be a device (jnp) array: the window is
        sliced device-side, so only the cutout — not the multi-GB chunk
        — crosses the host link (the streaming driver relies on this,
        round 6).
        """
        import dataclasses

        import numpy as np

        wf = info.allprofs
        if wf is None or wf.size <= self.WATERFALL_BUDGET:
            return info
        nbin = wf.shape[1]
        tsamp = (1.0 / (info.pulse_freq * info.nbin)
                 if info.pulse_freq and info.nbin else None)
        best = table.best_row()
        peak = int(best["peak"]) if "peak" in table.colnames else nbin // 2
        span = 256
        if tsamp and info.start_freq and info.bandwidth and best["DM"]:
            from ..ops.plan import delta_delay

            span = int(delta_delay(float(best["DM"]), info.start_freq,
                                   info.start_freq + info.bandwidth)
                       / tsamp) + 1
        pad = max(span // 2, 256)
        lo = peak - pad
        hi = peak + span + pad
        if hi - lo >= nbin:  # window covers the whole chunk
            lo, hi = 0, nbin
        if lo >= 0 and hi <= nbin:
            cut = np.asarray(wf[:, lo:hi])
        else:
            # circular window: the dispersed tail wrapped past an edge
            cols = np.arange(lo, hi) % nbin
            if isinstance(wf, np.ndarray):
                cut = np.take(wf, cols, axis=1, mode="wrap")
            else:  # device array: gather on device, read back the window
                cut = np.asarray(wf[:, cols])
            lo = lo % nbin
        decim = 1
        if cut.size > self.WATERFALL_BUDGET:
            from ..ops.rebin import quick_resample

            decim = -(-cut.size // self.WATERFALL_BUDGET)
            cut = np.asarray(quick_resample(cut, decim))
        return dataclasses.replace(info, allprofs=cut, cutout_start=lo,
                                   cutout_decim=decim)

    # backward-compatible alias (pre-round-6 name)
    _trim_waterfall = trim_waterfall

    def load_candidate(self, root, istart, iend):
        base = self._base(root, istart, iend)
        return (PulseInfo.load(base + ".info.npz"),
                ResultTable.from_npz(base + ".table.npz"))

    def candidates(self):
        """Yield ``(root, istart, iend)`` for every stored candidate."""
        for name in sorted(os.listdir(self.directory)):
            if name.endswith(".info.npz"):
                stem = name[: -len(".info.npz")]
                root, _, span = stem.rpartition("_")
                lo, _, hi = span.partition("-")
                yield root, int(lo), int(hi)
