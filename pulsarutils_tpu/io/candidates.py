"""Candidate store with deterministic resume.

The reference persisted candidates as ad-hoc pickles named
``{root}_{istart}-{iend}.pkl`` (``pulsarutils/clean.py:349-351``) and had no
way to resume a crashed search except a manual ``tmin`` (``clean.py:276``,
SURVEY §5).  This store makes both first-class:

* candidates are npz records (:class:`..pipeline.pulse_info.PulseInfo`)
  plus the chunk's full result table, named by chunk index — safe to load,
  idempotent to rewrite;
* a ``progress.json`` ledger records every *processed* chunk (hit or not),
  keyed by a config fingerprint, so a restarted search skips exactly the
  work already done and redoes nothing else.
"""

from __future__ import annotations

import hashlib
import json
import os

from ..pipeline.pulse_info import PulseInfo
from ..utils.table import ResultTable


def config_fingerprint(**kwargs):
    """Stable hash of the search configuration; a resume ledger is only
    valid for identical configuration."""
    blob = json.dumps(kwargs, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class CandidateStore:
    """``fingerprint=None`` disables the resume ledger entirely (every
    chunk reports not-done, nothing is recorded) — a no-resume run must
    never pollute another configuration's ledger.  Each fingerprint gets
    its own ledger file, so interleaved runs over different files/configs
    in one output directory never invalidate each other."""

    def __init__(self, directory, fingerprint=None):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.fingerprint = fingerprint
        if fingerprint is None:
            self._ledger_path = None
            self._ledger = {"fingerprint": None, "done": []}
        else:
            self._ledger_path = os.path.join(
                self.directory, f"progress_{fingerprint}.json")
            self._ledger = self._load_ledger()

    def _load_ledger(self):
        if os.path.exists(self._ledger_path):
            with open(self._ledger_path) as f:
                return json.load(f)
        return {"fingerprint": self.fingerprint, "done": []}

    # -- resume ledger -------------------------------------------------------

    def is_done(self, istart):
        if self.fingerprint is None:
            return False
        return istart in self._ledger["done"]

    def mark_done(self, istart):
        if self.fingerprint is None:
            return
        if istart not in self._ledger["done"]:
            self._ledger["done"].append(istart)
            tmp = self._ledger_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._ledger, f)
            os.replace(tmp, self._ledger_path)  # atomic: crash-safe resume

    @property
    def done_chunks(self):
        return sorted(self._ledger["done"])

    # -- candidates ----------------------------------------------------------

    def _base(self, root, istart, iend):
        return os.path.join(self.directory, f"{root}_{istart}-{iend}")

    def save_candidate(self, root, istart, iend, info: PulseInfo,
                       table: ResultTable):
        base = self._base(root, istart, iend)
        info.save(base + ".info.npz")
        table.to_npz(base + ".table.npz")
        return base

    def load_candidate(self, root, istart, iend):
        base = self._base(root, istart, iend)
        return (PulseInfo.load(base + ".info.npz"),
                ResultTable.from_npz(base + ".table.npz"))

    def candidates(self):
        """Yield ``(root, istart, iend)`` for every stored candidate."""
        for name in sorted(os.listdir(self.directory)):
            if name.endswith(".info.npz"):
                stem = name[: -len(".info.npz")]
                root, _, span = stem.rpartition("_")
                lo, _, hi = span.partition("-")
                yield root, int(lo), int(hi)
