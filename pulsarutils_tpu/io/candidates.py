"""Candidate store with deterministic resume.

The reference persisted candidates as ad-hoc pickles named
``{root}_{istart}-{iend}.pkl`` (``pulsarutils/clean.py:349-351``) and had no
way to resume a crashed search except a manual ``tmin`` (``clean.py:276``,
SURVEY §5).  This store makes both first-class:

* candidates are npz records (:class:`..pipeline.pulse_info.PulseInfo`)
  plus the chunk's full result table, named by chunk index — safe to load,
  idempotent to rewrite;
* a ``progress.json`` ledger records every *processed* chunk (hit or not),
  keyed by a config fingerprint, so a restarted search skips exactly the
  work already done and redoes nothing else.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import time

from ..faults import inject as fault_inject
from ..obs import metrics as _metrics
from ..pipeline.pulse_info import PulseInfo
from ..utils.table import ResultTable
from .atomic import atomic_write_json

logger = logging.getLogger("pulsarutils_tpu")


def config_fingerprint(**kwargs):
    """Stable hash of the search configuration; a resume ledger is only
    valid for identical configuration."""
    blob = json.dumps(kwargs, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class CandidateStore:
    """``fingerprint=None`` disables the resume ledger entirely (every
    chunk reports not-done, nothing is recorded) — a no-resume run must
    never pollute another configuration's ledger.  Each fingerprint gets
    its own ledger file, so interleaved runs over different files/configs
    in one output directory never invalidate each other."""

    def __init__(self, directory, fingerprint=None, fence=None):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.fingerprint = fingerprint
        #: monotonic lease-epoch fencing token (ISSUE 15).  ``None``
        #: (every single-process path) is byte-inert: no fence file is
        #: ever read or written and the store behaves exactly as before.
        #: Set (the fleet worker passes its lease's epoch), every
        #: ``save_candidate`` consults ``fence_<fingerprint>.json`` and
        #: REFUSES to clobber an artifact another session stamped with
        #: a *higher* epoch — the defence the ledger's union merge
        #: cannot give the ``.npz``/report artifacts: a partitioned
        #: zombie whose lease was stolen keeps computing, and its late
        #: writes must never overwrite the new owner's output.
        self.fence = int(fence) if fence is not None else None
        self._fence_path = (
            os.path.join(self.directory, f"fence_{fingerprint}.json")
            if self.fence is not None and fingerprint is not None
            else None)
        #: artifact writes this session refused under the fence
        self.fenced_rejects = 0
        if fingerprint is None:
            self._ledger_path = None
            self._ledger = {"fingerprint": None, "done": []}
        else:
            self._ledger_path = os.path.join(
                self.directory, f"progress_{fingerprint}.json")
            self._ledger = self._load_ledger()
        #: (st_size, st_mtime_ns) of OUR last ledger write — lets
        #: mark_done skip the concurrent-session merge (one stat
        #: instead of a read+parse) when nobody else has written
        self._last_write_stat = None

    def _load_ledger(self):
        """Load the ledger, surviving a torn/corrupt file.

        ``mark_done`` writes atomically (tmp + rename), but the file can
        still arrive torn — a crash mid-``os.replace`` on some
        filesystems, a partial rsync, disk corruption.  A corrupt ledger
        used to raise ``json.JSONDecodeError`` and kill resume entirely;
        now the bad file is backed up to ``<ledger>.corrupt`` and a
        fresh ledger starts (worst case: already-done chunks are
        re-searched, which resume semantics make idempotent).

        Only parse/shape failures (``ValueError``) mean corruption: a
        transient ``OSError`` on an intact file must propagate, not
        trash hours of resume progress (code-review r8).
        """
        if os.path.exists(self._ledger_path):
            try:
                with open(self._ledger_path) as f:
                    ledger = json.load(f)
                if not isinstance(ledger, dict) \
                        or not isinstance(ledger.get("done"), list):
                    raise ValueError("ledger is not a {fingerprint, done} "
                                     "record")
                return ledger
            except ValueError as exc:
                backup = self._ledger_path + ".corrupt"
                try:
                    os.replace(self._ledger_path, backup)
                except OSError:
                    backup = "<unremovable>"
                logger.warning(
                    "torn/corrupt resume ledger %s (%r): backed up to %s, "
                    "starting a fresh ledger (done chunks will be "
                    "re-searched)", self._ledger_path, exc, backup)
        return {"fingerprint": self.fingerprint, "done": []}

    # -- resume ledger -------------------------------------------------------

    def is_done(self, istart):
        if self.fingerprint is None:
            return False
        return istart in self._ledger["done"]

    def mark_done(self, istart, reason=None):
        """Record a chunk as processed.  ``reason`` marks a chunk done
        **with a reason** — quarantined or persist-dead-lettered: it is
        never re-searched on resume (exact resume semantics), and the
        reason survives in the ledger for the integrity audit.  The
        ``quarantined`` key only appears when a reason was recorded, so
        a clean run's ledger stays byte-identical to pre-hardening.

        Fleet sessions (ISSUE 9) made the on-disk bytes *canonical*:

        * the ``done`` list is kept **sorted** — a single-process run
          already completes chunks in ascending order, so its ledger
          bytes are unchanged, while N workers completing interleaved
          subsets of one file converge on the identical file (the
          byte-identity contract bench config 14 gates);
        * each write **merges with the on-disk ledger** first.  Two
          sessions share a ledger only in the work-stealing edge — a
          stalled worker's lease expires, its remaining chunks are
          re-leased, and the straggler still finishes its in-flight
          chunk — and a blind rewrite from the straggler's stale
          in-memory copy would erase the thief's entries.  The merge is
          a union (chunks are only ever *added*), so last-writer-wins
          degrades to no-loss; the coordinator additionally re-reads
          the ledger at every grant/complete, so even a torn interleave
          only causes an idempotent re-search, never a lost chunk.
        """
        if self.fingerprint is None:
            return
        quarantined = self._ledger.get("quarantined", {})
        if istart not in self._ledger["done"] \
                or (reason is not None
                    and quarantined.get(str(istart)) != reason):
            if istart not in self._ledger["done"]:
                self._ledger["done"].append(istart)
            if reason is not None:
                self._ledger.setdefault(
                    "quarantined", {})[str(istart)] = str(reason)
            self._merge_from_disk()
            self._ledger["done"].sort()
            if "quarantined" in self._ledger:
                q = self._ledger["quarantined"]
                # tolerant order: a wrong-shaped-but-parseable ledger
                # (non-numeric key) must stay the carried-through
                # oddity it always was, not a crash of every write
                self._ledger["quarantined"] = {
                    k: q[k] for k in sorted(
                        q, key=lambda k: (0, int(k), "") if
                        str(k).lstrip("-").isdigit() else (1, 0, str(k)))}
            atomic_write_json(self._ledger_path, self._ledger)
            try:
                st = os.stat(self._ledger_path)
                self._last_write_stat = (st.st_size, st.st_mtime_ns)
            except OSError:
                self._last_write_stat = None

    def _merge_from_disk(self):
        """Union the in-memory ledger with the current on-disk one.

        Unreadable/torn disk state is simply not merged (the in-memory
        copy wins): this is a best-effort anti-lost-update measure for
        concurrent fleet sessions, NOT the corruption-recovery path —
        that stays in :meth:`_load_ledger`, which backs the bad file up.

        Cost control: when the file's ``(size, mtime_ns)`` still match
        OUR last write, nobody else has written and the read+parse is
        skipped — a plain single-process survey pays one ``stat`` per
        chunk instead of re-parsing an O(n) ledger n times.  A stale
        match can only *skip* a merge, and the fleet coordinator
        re-reads the ledger at every grant/complete anyway, so the
        worst case stays an idempotent re-search, never a lost chunk.
        """
        try:
            if self._last_write_stat is not None:
                st = os.stat(self._ledger_path)
                if (st.st_size, st.st_mtime_ns) == self._last_write_stat:
                    return
            with open(self._ledger_path) as f:
                disk = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(disk, dict):
            return
        done = disk.get("done")
        if isinstance(done, list):
            have = set(self._ledger["done"])
            self._ledger["done"].extend(
                c for c in done if isinstance(c, int) and c not in have)
        quarantined = disk.get("quarantined")
        if isinstance(quarantined, dict):
            mine = self._ledger.setdefault("quarantined", {})
            for key, val in quarantined.items():
                mine.setdefault(key, val)

    @property
    def done_chunks(self):
        return sorted(self._ledger["done"])

    @property
    def quarantined_chunks(self):
        """``{str(istart): reason}`` for chunks marked done-with-reason."""
        return dict(self._ledger.get("quarantined", {}))

    # -- candidates ----------------------------------------------------------

    def _base(self, root, istart, iend):
        return os.path.join(self.directory, f"{root}_{istart}-{iend}")

    #: persisted-waterfall element budget: above this, ``save_candidate``
    #: stores a window around the pulse instead of the whole chunk (a
    #: 1024 x 1M survey chunk is a multi-GB compressed npz per hit and
    #: took ~10 min of single-core zlib per candidate — measured in the
    #: round-5 survey rehearsal, where persist dominated the pipeline)
    WATERFALL_BUDGET = 1 << 22

    def save_candidate(self, root, istart, iend, info: PulseInfo,
                       table: ResultTable):
        fault_inject.fire("persist", chunk=istart)
        base = self._base(root, istart, iend)

        def write():
            self.trim_waterfall(info, table).save(base + ".info.npz")
            table.to_npz(base + ".table.npz")

        self.fenced_write(base, write)
        return base

    def save_lineage(self, root, istart, iend, doc):
        """Persist a candidate's lineage doc beside its npz pair
        (ISSUE 18): ``{base}.lineage.json``, atomic, under the same
        epoch fence as the candidate artifacts — a zombie's stale
        lineage can no more clobber the new owner's than its npz can.
        Only called when lineage is armed; off-path runs never touch
        this, so their output directories are byte-identical."""
        base = self._base(root, istart, iend)

        def write():
            atomic_write_json(base + ".lineage.json", doc, indent=2,
                              sort_keys=True, trailing_newline=True)

        self.fenced_write(base, write)
        return base + ".lineage.json"

    # -- the artifact fence (ISSUE 15) ---------------------------------------

    def fenced_write(self, path, write_fn):
        """Run ``write_fn()`` (which writes the artifact at ``path``)
        under the epoch fence; returns ``True`` when it ran.

        Unfenced stores (``fence=None`` — every single-process path)
        just run it.  Fenced stores take a cross-process lockfile
        around check → write → stamp, so the steal edge's
        admit-then-write window cannot interleave two writers: without
        it, a zombie could pass the admit check before the new owner
        stamps and land its bytes *after* — and two concurrent stamps
        could lose the higher epoch (read-merge-write races).  The
        re-search is deterministic, so even a lost race rewrites
        identical bytes today; the lock keeps the fence a guarantee
        rather than a bet on that property.
        """
        if self._fence_path is None:
            write_fn()
            return True
        with self._fence_lock():
            if not self._fence_admits(path):
                return False
            write_fn()
            self._fence_stamp(path)
        return True

    @contextlib.contextmanager
    def _fence_lock(self, timeout_s=30.0):
        """Cross-process mutual exclusion for fenced writes: an
        ``O_EXCL`` lockfile beside the fence map (the one primitive
        that works on the fleet's shared filesystems).  A lock held
        past ``timeout_s`` is presumed abandoned (its holder
        SIGKILLed mid-write) and broken with a warning — availability
        over the defence-in-depth, and contention only exists at the
        steal edge at all."""
        lock_path = self._fence_path + ".lock"
        deadline = time.monotonic() + timeout_s
        fd = None
        while fd is None:
            try:
                fd = os.open(lock_path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                if time.monotonic() >= deadline:
                    logger.warning(
                        "breaking abandoned fence lock %s (held past "
                        "%.0fs)", lock_path, timeout_s)
                    try:
                        os.unlink(lock_path)
                    except OSError:
                        pass
                    deadline = time.monotonic() + timeout_s
                else:
                    time.sleep(0.05)
        try:
            yield
        finally:
            os.close(fd)
            try:
                os.unlink(lock_path)
            except OSError:
                pass

    def _read_fence(self):
        """``{artifact base name: epoch}`` off disk.  Unreadable/torn
        state resolves to "nothing stamped" — the worst case is an
        *allowed* write of idempotent bytes, never a lost artifact (the
        same degrade-open rule as :meth:`_merge_from_disk`)."""
        try:
            with open(self._fence_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {}
        epochs = doc.get("epochs") if isinstance(doc, dict) else None
        if not isinstance(epochs, dict):
            return {}
        return {str(k): int(v) for k, v in epochs.items()
                if isinstance(v, int)}

    def _fence_admits(self, base):
        """False when another session stamped ``base`` with a higher
        epoch — this writer's lease was stolen and the new owner has
        already written; clobbering it would let a zombie's stale
        compute overwrite live output."""
        name = os.path.basename(base)
        stamped = self._read_fence().get(name)
        if stamped is not None and stamped > self.fence:
            self.fenced_rejects += 1
            _metrics.counter("putpu_fleet_fenced_writes_total").inc()
            logger.warning(
                "fenced write rejected: %s is stamped epoch %d, this "
                "session holds epoch %d (lease stolen; the new owner's "
                "artifact stands)", name, stamped, self.fence)
            return False
        return True

    def _fence_stamp(self, base):
        """Record our epoch for ``base`` (read-merge-write keeping the
        max per artifact; callers hold :meth:`_fence_lock`, so the
        merge cannot lose a concurrent higher stamp)."""
        name = os.path.basename(base)
        epochs = self._read_fence()
        epochs[name] = max(epochs.get(name, 0), self.fence)
        atomic_write_json(self._fence_path,
                          {"schema_version": 1,
                           "epochs": dict(sorted(epochs.items()))})

    def trim_waterfall(self, info, table):
        """Bound the persisted record: full chunk in, pulse cutout out.

        The window covers the dispersed track — ``[peak - pad,
        peak + span + pad]`` with ``span`` the band-crossing delay at
        the candidate's DM — then block-sum decimates if still over
        budget.  The passed ``info`` is untouched (a trimmed *copy* is
        returned, or ``info`` itself when already under budget), with
        ``cutout_start``/``cutout_decim`` recording the window (see
        :class:`..pipeline.pulse_info.PulseInfo`).

        Tracks wrapping the chunk end are followed circularly (round 6,
        ADVICE r5): the search's roll convention wraps a dispersed tail
        past the chunk end to the chunk start, so for a pulse near the
        end the informative columns live at BOTH edges — the window is
        taken mod ``nbin`` (``cutout_start`` may therefore exceed
        ``nbin - width``; consumers recover absolute columns as
        ``(cutout_start + j * cutout_decim) mod nbin``).

        ``info.allprofs`` may be a device (jnp) array: the window is
        sliced device-side, so only the cutout — not the multi-GB chunk
        — crosses the host link (the streaming driver relies on this,
        round 6).
        """
        import dataclasses

        import numpy as np

        wf = info.allprofs
        if wf is None or wf.size <= self.WATERFALL_BUDGET:
            return info
        nbin = wf.shape[1]
        tsamp = (1.0 / (info.pulse_freq * info.nbin)
                 if info.pulse_freq and info.nbin else None)
        best = table.best_row()
        peak = int(best["peak"]) if "peak" in table.colnames else nbin // 2
        span = 256
        if tsamp and info.start_freq and info.bandwidth and best["DM"]:
            from ..ops.plan import delta_delay

            span = int(delta_delay(float(best["DM"]), info.start_freq,
                                   info.start_freq + info.bandwidth)
                       / tsamp) + 1
        pad = max(span // 2, 256)
        lo = peak - pad
        hi = peak + span + pad
        if hi - lo >= nbin:  # window covers the whole chunk
            lo, hi = 0, nbin
        if lo >= 0 and hi <= nbin:
            cut = np.asarray(wf[:, lo:hi])
        else:
            # circular window: the dispersed tail wrapped past an edge
            cols = np.arange(lo, hi) % nbin
            if isinstance(wf, np.ndarray):
                cut = np.take(wf, cols, axis=1, mode="wrap")
            else:  # device array: gather on device, read back the window
                cut = np.asarray(wf[:, cols])
            lo = lo % nbin
        decim = 1
        if cut.size > self.WATERFALL_BUDGET:
            from ..ops.rebin import quick_resample

            decim = -(-cut.size // self.WATERFALL_BUDGET)
            cut = np.asarray(quick_resample(cut, decim))
        return dataclasses.replace(info, allprofs=cut, cutout_start=lo,
                                   cutout_decim=decim)

    # backward-compatible alias (pre-round-6 name)
    _trim_waterfall = trim_waterfall

    def load_candidate(self, root, istart, iend):
        base = self._base(root, istart, iend)
        return (PulseInfo.load(base + ".info.npz"),
                ResultTable.from_npz(base + ".table.npz"))

    def candidates(self):
        """Yield ``(root, istart, iend)`` for every stored candidate."""
        for name in sorted(os.listdir(self.directory)):
            if name.endswith(".info.npz"):
                stem = name[: -len(".info.npz")]
                root, _, span = stem.rpartition("_")
                lo, _, hi = span.partition("-")
                yield root, int(lo), int(hi)
