"""THE sanctioned tmp+``os.replace`` persistence helper.

Every durable ``*.json``/``*.jsonl`` state file in this tree — resume
ledgers, the tune cache, the memory-budget calibration, zap lists, the
fleet journal, the artifact fence map — lives or dies by the PR 4
torn-write rules: a crash mid-write must leave the *previous* state
intact, and a reader must survive whatever a crash still managed to
tear.  Five PRs of copy-pasting ``tmp = path + ".tmp" ... os.replace``
left the rule enforced by reviewer memory; this module is the rule,
written down once, and the ``atomic-write`` checker of
:mod:`pulsarutils_tpu.analysis` statically rejects direct
``open(..., "w")`` persists of ``.json``/``.jsonl`` paths anywhere
else.

Two write shapes:

* :func:`atomic_write_json` / :func:`atomic_write_text` — whole-document
  rewrite via tmp + ``os.replace``: crash-safe, last-writer-wins;
* :func:`append_jsonl` — one-record append for journals: each record is
  a single ``write()`` + ``flush()`` of one line, so a SIGKILL can tear
  at most the final line (the torn *tail*, which
  :func:`read_jsonl_tail_safe` truncates on replay after backing the
  torn file up).

Keep this module stdlib-only: the analysis layer names it and the
tuning/fleet layers import it on jax-free code paths.
"""

from __future__ import annotations

import json
import logging
import os
import shutil

logger = logging.getLogger("pulsarutils_tpu")

__all__ = ["JsonlAppender", "append_jsonl", "atomic_write_json",
           "atomic_write_text", "read_jsonl_tail_safe"]


def atomic_write_text(path, text):
    """Write ``text`` to ``path`` atomically (tmp + ``os.replace``)."""
    path = str(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)  # atomic: a crash keeps the old file


def atomic_write_json(path, doc, *, indent=None, sort_keys=False,
                      trailing_newline=False):
    """Serialise ``doc`` and write it atomically.

    The formatting knobs exist because several pre-helper writers'
    on-disk bytes are pinned by tests and fleet byte-identity contracts
    (the resume ledger is compact, the tune cache is
    ``indent=1, sort_keys=True`` + newline) — centralising the atomic
    rule must not move a byte of any of them.
    """
    text = json.dumps(doc, indent=indent, sort_keys=sort_keys)
    if trailing_newline:
        text += "\n"
    atomic_write_text(path, text)


def append_jsonl(path, record):
    """Append ``record`` as one JSON line; returns the serialised line.

    One ``write()`` of one ``\\n``-terminated line + ``flush()``: after
    this returns, a SIGKILLed *process* loses nothing (the data is in
    the page cache), and a machine crash can tear at most the last
    line — exactly the torn tail :func:`read_jsonl_tail_safe` recovers
    from.  Records must be single-line by construction (``json.dumps``
    never emits a bare newline).
    """
    path = str(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    line = json.dumps(record) + "\n"
    with open(path, "a", encoding="utf-8") as f:
        f.write(line)
        f.flush()
    return line


class JsonlAppender:
    """A persistent append-mode handle with the :func:`append_jsonl`
    discipline — for journals on a hot path, where re-opening the file
    per record (often under the caller's global lock, often on a
    shared filesystem) would serialize every handler behind filesystem
    open latency.  NOT thread-safe: the caller owns concurrency.

    :meth:`reset` MUST be called after anything that replaces the file
    behind the handle (a torn-tail truncation rewrite, a ``.stale``
    move): a cached handle points at the *old inode* and its appends
    would vanish.
    """

    def __init__(self, path):
        self.path = str(path)
        self._fh = None

    def append(self, record):
        if self._fh is None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
        self._fh.write(json.dumps(record) + "\n")
        self._fh.flush()

    def reset(self):
        """Drop the cached handle (reopened lazily on next append)."""
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    close = reset


def read_jsonl_tail_safe(path, what="journal"):
    """Parse a JSONL file, surviving a torn tail.

    Returns ``(records, truncated)``.  Every parseable line from the
    top is a record; the first unparseable (or unterminated) line and
    everything after it is the torn tail of an interrupted append — the
    whole torn file is backed up to ``<path>.corrupt`` and the good
    prefix is rewritten in place (atomically), so the next append lands
    on a clean file.  A missing file is simply ``([], False)``.
    """
    path = str(path)
    try:
        with open(path, encoding="utf-8") as f:
            raw = f.read()
    except FileNotFoundError:
        return [], False
    records = []
    good = []
    truncated = False
    for i, line in enumerate(raw.split("\n")):
        if line == "" and i == raw.count("\n"):
            break   # trailing empty split after the final newline
        try:
            records.append(json.loads(line))
            good.append(line)
        except ValueError:
            truncated = True
            break
    # an unterminated final line is torn even if it happens to parse:
    # the writer always terminates, so a missing newline means the
    # append died mid-write and the line cannot be trusted complete
    if not truncated and raw and not raw.endswith("\n") and good:
        records.pop()
        good.pop()
        truncated = True
    if truncated:
        backup = path + ".corrupt"
        try:
            shutil.copy2(path, backup)
        except OSError:
            backup = "<uncopyable>"
        atomic_write_text(path, "".join(g + "\n" for g in good))
        logger.warning(
            "torn %s tail in %s: backed up to %s, truncated to %d good "
            "record(s)", what, path, backup, len(records))
    return records, truncated
