"""1/2/4-bit sample packing/unpacking for SIGPROC filterbanks.

The reference delegates filterbank decoding to the third-party
``sigpyproc`` (``clean.py:18``, ``stats.py:6``), which supports 1-32 bit
samples; this module provides the low-bit half of that capability
natively.  Bit order is LSB-first within each byte (lowest channel index
in the least-significant bits — the sigproc ecosystem convention).

Two implementations:

* a C++ lookup-table loop (``native/unpack.cpp``) compiled on demand
  with the system toolchain and loaded via ``ctypes`` — 3-5x faster
  than numpy on the streaming driver's hundreds-of-MB chunks;
* a pure-numpy shift-and-mask fallback, always available, and the
  correctness oracle in the tests.

Use :func:`unpack` / :func:`pack`; they pick the native path when it
loads, unless ``PUTPU_NO_NATIVE=1``.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sys
import tempfile

import numpy as np

logger = logging.getLogger("pulsarutils_tpu")

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "unpack.cpp")

#: values per byte for each supported width
_PER_BYTE = {1: 8, 2: 4, 4: 2}

_lib = None
_lib_tried = False


def _build_library():
    """Compile unpack.cpp to a cached shared library; return its path.

    The cache lives next to the source (``native/_unpack.<abi>.so``) when
    writable, else in a per-user temp dir.  Rebuilds when the source is
    newer than the cached binary.
    """
    tag = f"cpython{sys.version_info.major}{sys.version_info.minor}"
    # per-user temp dir: os.getuid does not exist on Windows — fall
    # back to USERNAME there (the windows CI leg must reach the numpy
    # fallback through the normal probe chain, not an AttributeError)
    uid = (os.getuid() if hasattr(os, "getuid")
           else os.environ.get("USERNAME", "user"))
    build_dirs = [os.path.dirname(_SRC),
                  os.path.join(tempfile.gettempdir(),
                               f"pulsarutils_tpu_native_{uid}")]
    for d in build_dirs:
        try:
            os.makedirs(d, exist_ok=True)
            out = os.path.join(d, f"_unpack.{tag}.so")
            if (os.path.exists(out)
                    and os.path.getmtime(out) >= os.path.getmtime(_SRC)):
                return out
            # compile to a unique temp path and rename into place: rename
            # is atomic on POSIX, so a concurrent process never CDLLs a
            # half-written (yet ELF-parsable) library
            tmp = f"{out}.tmp{os.getpid()}"
            try:
                _compile(tmp)
                os.replace(tmp, out)
            finally:
                if os.path.exists(tmp):  # failed build: no orphan files
                    os.unlink(tmp)
            return out
        except (OSError, subprocess.SubprocessError) as exc:
            logger.debug("native unpack build failed in %s: %s", d, exc)
    logger.info("native low-bit unpacker unavailable (no working C++ "
                "toolchain); using the numpy fallback — correct but "
                "slower on multi-GB low-bit files")
    return None


def _compile(out):
    """Build ``unpack.cpp`` with the first working compiler.

    ``$CXX`` wins when set; otherwise g++ then clang++ then c++ — on
    macOS ``g++`` is usually a clang shim and all three take the same
    ``-shared -fPIC`` flags (the library is self-contained, so no
    ``-undefined dynamic_lookup`` is needed).  Raises the last failure
    when none work (the caller logs and falls back to numpy).
    """
    compilers = ([os.environ["CXX"]] if os.environ.get("CXX")
                 else ["g++", "clang++", "c++"])
    last = None
    for cxx in compilers:
        try:
            subprocess.run([cxx, "-O3", "-shared", "-fPIC", "-o", out,
                            _SRC], check=True, capture_output=True,
                           timeout=120)
            return
        except (OSError, subprocess.SubprocessError) as exc:
            last = exc
    raise last


def _load():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("PUTPU_NO_NATIVE") == "1":
        return None
    try:
        path = _build_library()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        for name in ("unpack1", "unpack2", "unpack4"):
            getattr(lib, name).argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
        for name in ("pack1", "pack2", "pack4"):
            getattr(lib, name).argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
        _lib = lib
    except OSError as exc:
        logger.debug("native unpack unavailable: %s", exc)
        _lib = None
    return _lib


def native_available():
    """True when the C++ unpacker compiled and loaded."""
    return _load() is not None


def device_unpack_block(frames, nbits, nchan, band_descending=False,
                        xp=None):
    """Jittable device unpack: packed frames -> ``(nchan, n)`` float32.

    ``frames`` is the raw ``(nsamps, nbytes_per_frame)`` uint8 block a
    low-bit filterbank stores (``FilterbankReader.read_block_packed``),
    single-IF.  Same LSB-first convention as :func:`unpack_numpy`; the
    returned block is ASCENDING-band (``band_descending=True`` flips
    the file's channel order, mirroring ``read_block(band_ascending=
    True)``).

    Why this exists (round 4): the streaming pipeline used to unpack on
    the host and upload float32 — 16x the bytes of a 2-bit file over
    the host->device link, which is the survey bottleneck on thin
    links (measured 647 s per 4 GB chunk on a congested tunnel).
    Uploading the packed bytes and unpacking in the device-clean jit
    moves the inflation to HBM, where it is free by comparison.
    """
    if xp is None:
        import jax.numpy as xp
    per = _PER_BYTE[nbits]
    mask = (1 << nbits) - 1
    frames = xp.asarray(frames)
    shifts = xp.arange(per, dtype=xp.uint8) * np.uint8(nbits)
    vals = (frames[:, :, None] >> shifts[None, None, :]) & np.uint8(mask)
    block = vals.reshape(frames.shape[0], -1)[:, :nchan]
    block = block.astype(xp.float32).T
    if band_descending:
        block = block[::-1]
    return block


def unpack_numpy(packed, nbits):
    """Numpy reference: packed uint8 -> float32, LSB-first."""
    packed = np.ascontiguousarray(packed, dtype=np.uint8).ravel()
    per = _PER_BYTE[nbits]
    mask = (1 << nbits) - 1
    shifts = np.arange(per, dtype=np.uint8) * nbits
    out = (packed[:, None] >> shifts[None, :]) & mask
    return out.astype(np.float32).ravel()


def pack_numpy(values, nbits):
    """Numpy reference: float32 -> packed uint8 (clipped, LSB-first)."""
    per = _PER_BYTE[nbits]
    maxval = (1 << nbits) - 1
    v = np.asarray(values, dtype=np.float32).ravel()
    if v.size % per:
        raise ValueError(f"value count {v.size} not a multiple of {per}")
    q = np.clip(np.rint(v), 0, maxval).astype(np.uint8).reshape(-1, per)
    shifts = np.arange(per, dtype=np.uint8) * nbits
    return np.bitwise_or.reduce(q << shifts[None, :], axis=1).astype(np.uint8)


def unpack(packed, nbits):
    """Packed uint8 buffer -> float32 values (native path when available)."""
    if nbits not in _PER_BYTE:
        raise ValueError(f"unsupported nbits={nbits}")
    lib = _load()
    if lib is None:
        return unpack_numpy(packed, nbits)
    packed = np.ascontiguousarray(packed, dtype=np.uint8).ravel()
    out = np.empty(packed.size * _PER_BYTE[nbits], dtype=np.float32)
    getattr(lib, f"unpack{nbits}")(
        packed.ctypes.data, out.ctypes.data, packed.size)
    return out


def pack(values, nbits):
    """Float values -> packed uint8 (native path when available)."""
    if nbits not in _PER_BYTE:
        raise ValueError(f"unsupported nbits={nbits}")
    lib = _load()
    if lib is None:
        return pack_numpy(values, nbits)
    per = _PER_BYTE[nbits]
    v = np.ascontiguousarray(values, dtype=np.float32).ravel()
    if v.size % per:
        raise ValueError(f"value count {v.size} not a multiple of {per}")
    out = np.empty(v.size // per, dtype=np.uint8)
    getattr(lib, f"pack{nbits}")(v.ctypes.data, out.ctypes.data, out.size)
    return out
