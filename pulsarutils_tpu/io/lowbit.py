"""1/2/4-bit sample packing/unpacking for SIGPROC filterbanks.

The reference delegates filterbank decoding to the third-party
``sigpyproc`` (``clean.py:18``, ``stats.py:6``), which supports 1-32 bit
samples; this module provides the low-bit half of that capability
natively.  Bit order is LSB-first within each byte (lowest channel index
in the least-significant bits — the sigproc ecosystem convention).

Two implementations:

* a C++ lookup-table loop (``native/unpack.cpp``) compiled on demand
  with the system toolchain and loaded via ``ctypes`` — 3-5x faster
  than numpy on the streaming driver's hundreds-of-MB chunks;
* a pure-numpy shift-and-mask fallback, always available, and the
  correctness oracle in the tests.

Use :func:`unpack` / :func:`pack`; they pick the native path when it
loads, unless ``PUTPU_NO_NATIVE=1``.
"""

from __future__ import annotations

import ctypes
import functools
import logging
import os
import subprocess
import sys
import tempfile

import numpy as np

logger = logging.getLogger("pulsarutils_tpu")

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "unpack.cpp")

#: values per byte for each supported width
_PER_BYTE = {1: 8, 2: 4, 4: 2}

_lib = None
_lib_tried = False


def _build_library():
    """Compile unpack.cpp to a cached shared library; return its path.

    The cache lives next to the source (``native/_unpack.<abi>.so``) when
    writable, else in a per-user temp dir.  Rebuilds when the source is
    newer than the cached binary.
    """
    tag = f"cpython{sys.version_info.major}{sys.version_info.minor}"
    # per-user temp dir: os.getuid does not exist on Windows — fall
    # back to USERNAME there (the windows CI leg must reach the numpy
    # fallback through the normal probe chain, not an AttributeError)
    uid = (os.getuid() if hasattr(os, "getuid")
           else os.environ.get("USERNAME", "user"))
    build_dirs = [os.path.dirname(_SRC),
                  os.path.join(tempfile.gettempdir(),
                               f"pulsarutils_tpu_native_{uid}")]
    for d in build_dirs:
        try:
            os.makedirs(d, exist_ok=True)
            out = os.path.join(d, f"_unpack.{tag}.so")
            if (os.path.exists(out)
                    and os.path.getmtime(out) >= os.path.getmtime(_SRC)):
                return out
            # compile to a unique temp path and rename into place: rename
            # is atomic on POSIX, so a concurrent process never CDLLs a
            # half-written (yet ELF-parsable) library
            tmp = f"{out}.tmp{os.getpid()}"
            try:
                _compile(tmp)
                os.replace(tmp, out)
            finally:
                if os.path.exists(tmp):  # failed build: no orphan files
                    os.unlink(tmp)
            return out
        except (OSError, subprocess.SubprocessError) as exc:
            logger.debug("native unpack build failed in %s: %s", d, exc)
    logger.info("native low-bit unpacker unavailable (no working C++ "
                "toolchain); using the numpy fallback — correct but "
                "slower on multi-GB low-bit files")
    return None


def _compile(out):
    """Build ``unpack.cpp`` with the first working compiler.

    ``$CXX`` wins when set; otherwise g++ then clang++ then c++ — on
    macOS ``g++`` is usually a clang shim and all three take the same
    ``-shared -fPIC`` flags (the library is self-contained, so no
    ``-undefined dynamic_lookup`` is needed).  Raises the last failure
    when none work (the caller logs and falls back to numpy).
    """
    compilers = ([os.environ["CXX"]] if os.environ.get("CXX")
                 else ["g++", "clang++", "c++"])
    last = None
    for cxx in compilers:
        try:
            subprocess.run([cxx, "-O3", "-shared", "-fPIC", "-o", out,
                            _SRC], check=True, capture_output=True,
                           timeout=120)
            return
        except (OSError, subprocess.SubprocessError) as exc:
            last = exc
    raise last


def _load():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("PUTPU_NO_NATIVE") == "1":
        return None
    try:
        path = _build_library()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        for name in ("unpack1", "unpack2", "unpack4"):
            getattr(lib, name).argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
        for name in ("pack1", "pack2", "pack4"):
            getattr(lib, name).argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
        _lib = lib
    except OSError as exc:
        logger.debug("native unpack unavailable: %s", exc)
        _lib = None
    return _lib


def native_available():
    """True when the C++ unpacker compiled and loaded."""
    return _load() is not None


def accum_dtype(nbits, nchan):
    """Name of the smallest integer dtype that EXACTLY holds a
    full-channel dedispersion sum of ``nbits``-bit codes — the
    integer-sweep-accumulation contract (ISSUE 11):

    * a worst-case sum is ``(2^nbits - 1) * nchan`` (every channel at
      the top rail);
    * below 2^15 the whole ``(ndm, T)`` plane accumulates in **int16**
      (half the HBM traffic of float32 on the memory-bound sweep);
    * below 2^24 it accumulates in **int32** AND its float32 view is
      still exact (float32 represents every integer < 2^24), so the
      scores computed from the integer plane are bit-identical to the
      float-accumulated reference — float32 addition of exact integers
      with an exact-representable running sum never rounds;
    * at or above 2^24 the exactness argument breaks and callers must
      stay on the float32 path (``None`` is returned).

    The ladder itself lives in :func:`..precision.exactness_domain`,
    the single owner of the 2^24 bound (ISSUE 17) — this wrapper keeps
    the historic call signature.
    """
    from ..precision import exactness_domain

    return exactness_domain(nchan, nbits=nbits).accum_dtype


def device_unpack_block(frames, nbits, nchan, band_descending=False,
                        xp=None, dtype=None):
    """Jittable device unpack: packed frames -> ``(nchan, n)`` float32.

    ``frames`` is the raw ``(nsamps, nbytes_per_frame)`` uint8 block a
    low-bit filterbank stores (``FilterbankReader.read_block_packed``),
    single-IF.  Same LSB-first convention as :func:`unpack_numpy`; the
    returned block is ASCENDING-band (``band_descending=True`` flips
    the file's channel order, mirroring ``read_block(band_ascending=
    True)``).

    Why this exists (round 4): the streaming pipeline used to unpack on
    the host and upload float32 — 16x the bytes of a 2-bit file over
    the host->device link, which is the survey bottleneck on thin
    links (measured 647 s per 4 GB chunk on a congested tunnel).
    Uploading the packed bytes and unpacking in the device-clean jit
    moves the inflation to HBM, where it is free by comparison.

    ``dtype`` (round 11) overrides the output dtype: an integer dtype
    (see :func:`accum_dtype`) keeps the unpacked codes integral so the
    dedispersion sweep can accumulate in int16/int32 — same values,
    half the HBM traffic — converting to float only at scoring.
    """
    if xp is None:
        import jax.numpy as xp
    per = _PER_BYTE[nbits]
    mask = (1 << nbits) - 1
    frames = xp.asarray(frames)
    shifts = xp.arange(per, dtype=xp.uint8) * np.uint8(nbits)
    vals = (frames[:, :, None] >> shifts[None, None, :]) & np.uint8(mask)
    block = vals.reshape(frames.shape[0], -1)[:, :nchan]
    block = block.astype(dtype if dtype is not None else xp.float32).T
    if band_descending:
        block = block[::-1]
    return block


def unpack_from_meta(data, meta, xp):
    """In-jit unpack from a :meth:`PackedFrames.meta` tuple.

    The ONE traceable body every surface embeds (direct-sweep kernel,
    batched beam body, both shard_map programs) — so the meta's dtype
    element is always honored and the bit-identity-critical unpack
    cannot drift between copies.
    """
    nbits, nchan, descending, dtype_name = meta
    return device_unpack_block(data, nbits, nchan,
                               band_descending=descending, xp=xp,
                               dtype=getattr(xp, dtype_name))


def sample_codes(frames, nbits, nchan, max_rows=4096):
    """Bounded strided decode of packed frames -> ``(nchan, k)`` codes
    in FILE channel order.

    Shared by the reader-thread consumers that need statistics, not the
    whole chunk (the packed canary's noise scale, the code-domain
    integrity gate): at most ``max_rows`` frames are decoded regardless
    of chunk size.
    """
    frames = np.asarray(frames)
    stride = max(1, frames.shape[0] // int(max_rows))
    per_frame = frames.shape[1] * _PER_BYTE[nbits]
    return unpack_numpy(frames[::stride], nbits).reshape(
        -1, per_frame)[:, :int(nchan)].T


@functools.lru_cache(maxsize=16)
def _unpack_program(nbits, nchan, band_descending, dtype_name):
    """ONE compiled device-unpack program per (geometry, dtype): raw
    packed bytes in, ``(nchan, n)`` block out.  Shared by every surface
    that uploads packed frames but runs a kernel that cannot unpack
    in-program (Pallas/FDMT/fourier, the mesh exact sweep): the link
    still carries 1/8-1/16th the bytes, the shift/mask inflation
    happens on HBM."""
    import jax
    import jax.numpy as jnp

    dtype = getattr(jnp, dtype_name)

    @jax.jit
    def run(frames):
        return device_unpack_block(frames, nbits, nchan,
                                   band_descending=band_descending,
                                   xp=jnp, dtype=dtype)

    return run


class PackedFrames:
    """A packed low-bit chunk in transit: raw SIGPROC frames plus the
    metadata needed to decode them.

    This is the carrier every scaled dispatch surface accepts in place
    of a float ``(nchan, n)`` block (ISSUE 11): the streaming driver
    (``parallel/stream.py``), the mesh searches
    (``parallel/sharded_fdmt.py`` / ``parallel/sharded.py``), the
    batched beam dispatcher (``beams/batcher.py``) and the single-device
    facade (``ops/search.py``).  ``frames`` is exactly what
    ``FilterbankReader.read_block_packed`` returns — ``(nsamps,
    bytes_per_frame)`` uint8 — so shipping it to the device costs
    ``nbits/32`` of the float32 upload.  ``.shape`` reports the LOGICAL
    ``(nchan, nsamps)`` block shape so geometry-planning code
    (``np.shape(data)``) works unchanged.
    """

    __slots__ = ("frames", "nbits", "nchan", "band_descending")

    def __init__(self, frames, nbits, nchan, band_descending=False):
        if nbits not in _PER_BYTE:
            raise ValueError(f"unsupported nbits={nbits}")
        self.frames = np.asarray(frames)
        if self.frames.ndim != 2 or self.frames.dtype != np.uint8:
            raise ValueError(
                "PackedFrames wants the raw (nsamps, bytes_per_frame) "
                f"uint8 frames; got {self.frames.dtype} "
                f"{self.frames.shape}")
        self.nbits = int(nbits)
        self.nchan = int(nchan)
        self.band_descending = bool(band_descending)

    @classmethod
    def read(cls, reader, istart, nsamps):
        """Read one packed chunk off a low-bit single-IF
        :class:`~pulsarutils_tpu.io.sigproc.FilterbankReader`."""
        return cls(reader.read_block_packed(istart, nsamps),
                   reader._nbits, reader.nchans,
                   band_descending=reader.band_descending)

    @property
    def shape(self):
        """Logical decoded shape ``(nchan, nsamps)``."""
        return (self.nchan, int(self.frames.shape[0]))

    @property
    def nsamps(self):
        return int(self.frames.shape[0])

    @property
    def nbytes(self):
        """Bytes actually shipped over the link (the packed bytes)."""
        return int(self.frames.nbytes)

    @property
    def float_nbytes(self):
        """Bytes the host-unpack path would have shipped (float32)."""
        return self.nchan * self.nsamps * 4

    def meta(self, dtype_name="float32"):
        """Hashable unpack descriptor ``(nbits, nchan, descending,
        dtype)`` — the static operand in-jit unpackers key on."""
        return (self.nbits, self.nchan, self.band_descending,
                str(dtype_name))

    def to_device(self, dtype_name="float32"):
        """Upload the PACKED bytes and unpack on device.

        Returns the device-resident ``(nchan, nsamps)`` ascending-band
        block (float32 by default, or an :func:`accum_dtype` integer
        dtype) — one cached compiled program per geometry, so steady
        state never retraces.
        """
        return _unpack_program(self.nbits, self.nchan,
                               self.band_descending,
                               str(dtype_name))(self.frames)

    def to_host(self):
        """Host decode (C++ when built, numpy otherwise) to the float32
        ``(nchan, nsamps)`` ascending-band block — the fallback path and
        the byte-identity oracle the device unpack is pinned against."""
        per_frame = self.frames.shape[1] * _PER_BYTE[self.nbits]
        block = unpack(self.frames, self.nbits).reshape(
            self.nsamps, per_frame)[:, :self.nchan].T
        if self.band_descending:
            block = block[::-1]
        return np.ascontiguousarray(block)


def unpack_numpy(packed, nbits):
    """Numpy reference: packed uint8 -> float32, LSB-first."""
    packed = np.ascontiguousarray(packed, dtype=np.uint8).ravel()
    per = _PER_BYTE[nbits]
    mask = (1 << nbits) - 1
    shifts = np.arange(per, dtype=np.uint8) * nbits
    out = (packed[:, None] >> shifts[None, :]) & mask
    return out.astype(np.float32).ravel()


def pack_numpy(values, nbits):
    """Numpy reference: float32 -> packed uint8 (clipped, LSB-first)."""
    per = _PER_BYTE[nbits]
    maxval = (1 << nbits) - 1
    v = np.asarray(values, dtype=np.float32).ravel()
    if v.size % per:
        raise ValueError(f"value count {v.size} not a multiple of {per}")
    q = np.clip(np.rint(v), 0, maxval).astype(np.uint8).reshape(-1, per)
    shifts = np.arange(per, dtype=np.uint8) * nbits
    return np.bitwise_or.reduce(q << shifts[None, :], axis=1).astype(np.uint8)


def unpack(packed, nbits):
    """Packed uint8 buffer -> float32 values (native path when available)."""
    if nbits not in _PER_BYTE:
        raise ValueError(f"unsupported nbits={nbits}")
    lib = _load()
    if lib is None:
        return unpack_numpy(packed, nbits)
    packed = np.ascontiguousarray(packed, dtype=np.uint8).ravel()
    out = np.empty(packed.size * _PER_BYTE[nbits], dtype=np.float32)
    getattr(lib, f"unpack{nbits}")(
        packed.ctypes.data, out.ctypes.data, packed.size)
    return out


def pack(values, nbits):
    """Float values -> packed uint8 (native path when available)."""
    if nbits not in _PER_BYTE:
        raise ValueError(f"unsupported nbits={nbits}")
    lib = _load()
    if lib is None:
        return pack_numpy(values, nbits)
    per = _PER_BYTE[nbits]
    v = np.ascontiguousarray(values, dtype=np.float32).ravel()
    if v.size % per:
        raise ValueError(f"value count {v.size} not a multiple of {per}")
    out = np.empty(v.size // per, dtype=np.uint8)
    getattr(lib, f"pack{nbits}")(v.ctypes.data, out.ctypes.data, out.size)
    return out
