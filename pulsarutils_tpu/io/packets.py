"""Versioned wire format for the live ingest frontend (ISSUE 19).

One packet carries a contiguous run of time samples for a contiguous
channel range, as either float32 frames or the :mod:`.lowbit` packed
codes — a 1/2/4-bit payload lands byte-for-byte on the
:class:`~.lowbit.PackedFrames` device-unpack path, so ingest bandwidth
is *bytes, not floats* (the PR 10 contract extended to the wire).

Layout (little-endian, 40-byte header + payload)::

    magic     4s   b"PUTP"
    version   B    PACKET_VERSION (1)
    nbits     B    0 = float32 frames; 1/2/4 = lowbit packed codes
    flags     B    bit 0: band_descending payload channel order
    _pad      B    zero
    nchan     H    channels in this packet's range
    chan0     H    first channel of the range (0 = full band)
    nsamps    I    time samples (frames) in the payload
    seq       Q    monotone packet counter (gap/reorder detection)
    sample0   Q    absolute sample index of the first frame
    payload_len I  payload bytes that follow the header
    crc32     I    zlib.crc32 of the payload (corruption detection)

The payload is **frame-major**: ``nsamps`` frames, each one either
``nchan`` float32 values or ``ceil(nchan * nbits / 8)`` packed bytes
(exactly a :class:`~.lowbit.PackedFrames` row).  Frame-major order is
what makes reassembly a row copy instead of a transpose per packet.

Framing is self-delimiting (the header carries ``payload_len``), so the
same byte stream works over a TCP connection, a UDP datagram per
packet, or a flat file piped through ``nc`` (the docs' netcat
quickstart).  Decode errors raise :class:`PacketError`; a CRC mismatch
raises the :class:`PacketCorruptError` subclass so the assembler can
count a corrupt packet as *lost* (its samples become a gap) rather than
poisoning a chunk with flipped bits.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass

import numpy as np

__all__ = ["PACKET_MAGIC", "PACKET_VERSION", "HEADER_SIZE", "Packet",
           "PacketError", "PacketCorruptError", "encode_packet",
           "decode_packet", "read_packet_stream", "packetize_array"]

PACKET_MAGIC = b"PUTP"
PACKET_VERSION = 1

_HEADER = struct.Struct("<4sBBBBHHIQQII")
HEADER_SIZE = _HEADER.size

_FLAG_BAND_DESCENDING = 0x01

#: packed payload bytes per frame, keyed by nbits (0 = float32)
_PER_BYTE = {1: 8, 2: 4, 4: 2}


class PacketError(ValueError):
    """Malformed packet: bad magic, unsupported version, short buffer,
    or inconsistent header/payload lengths."""


class PacketCorruptError(PacketError):
    """Structurally valid packet whose payload fails its CRC — the
    assembler treats the samples as lost (a gap), never as data."""


def frame_nbytes(nchan, nbits):
    """Payload bytes per time sample for this channel count/depth."""
    nchan = int(nchan)
    if nbits == 0:
        return 4 * nchan
    if nbits not in _PER_BYTE:
        raise PacketError(f"unsupported nbits {nbits!r} (0, 1, 2 or 4)")
    per = _PER_BYTE[nbits]
    return (nchan + per - 1) // per


@dataclass(frozen=True)
class Packet:
    """One decoded packet: header fields + the frame-major payload.

    ``payload`` is the raw bytes; :meth:`frames` views them as the
    ``(nsamps, frame_nbytes)`` uint8 array (packed) or
    ``(nsamps, nchan)`` float32 array (nbits == 0).
    """

    seq: int
    sample0: int
    nsamps: int
    nchan: int
    chan0: int
    nbits: int
    band_descending: bool
    payload: bytes

    def frames(self):
        """Frame-major payload view (no copy)."""
        if self.nbits == 0:
            return np.frombuffer(self.payload, dtype=np.float32).reshape(
                self.nsamps, self.nchan)
        return np.frombuffer(self.payload, dtype=np.uint8).reshape(
            self.nsamps, frame_nbytes(self.nchan, self.nbits))


def encode_packet(*, seq, sample0, nchan, nbits, payload, chan0=0,
                  band_descending=False):
    """Serialize one packet; ``payload`` must be the frame-major bytes
    of a whole number of frames."""
    payload = bytes(payload)
    fb = frame_nbytes(nchan, nbits)
    if fb == 0 or len(payload) % fb:
        raise PacketError(
            f"payload of {len(payload)} bytes is not a whole number of "
            f"{fb}-byte frames (nchan={nchan}, nbits={nbits})")
    nsamps = len(payload) // fb
    flags = _FLAG_BAND_DESCENDING if band_descending else 0
    header = _HEADER.pack(PACKET_MAGIC, PACKET_VERSION, int(nbits),
                          flags, 0, int(nchan), int(chan0), nsamps,
                          int(seq), int(sample0), len(payload),
                          zlib.crc32(payload) & 0xFFFFFFFF)
    return header + payload


def decode_packet(buf):
    """Decode one packet from ``buf`` (header + payload, exact or
    longer); returns ``(Packet, bytes_consumed)``."""
    buf = bytes(buf)
    if len(buf) < HEADER_SIZE:
        raise PacketError(f"short header: {len(buf)} < {HEADER_SIZE}")
    (magic, version, nbits, flags, _pad, nchan, chan0, nsamps, seq,
     sample0, payload_len, crc) = _HEADER.unpack_from(buf)
    if magic != PACKET_MAGIC:
        raise PacketError(f"bad magic {magic!r}")
    if version != PACKET_VERSION:
        raise PacketError(f"unsupported packet version {version}")
    if nbits not in (0, 1, 2, 4):
        raise PacketError(f"unsupported nbits {nbits}")
    if payload_len != nsamps * frame_nbytes(nchan, nbits):
        raise PacketError(
            f"payload_len {payload_len} inconsistent with "
            f"{nsamps} frames of {frame_nbytes(nchan, nbits)} bytes")
    end = HEADER_SIZE + payload_len
    if len(buf) < end:
        raise PacketError(f"short payload: {len(buf)} < {end}")
    payload = buf[HEADER_SIZE:end]
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise PacketCorruptError(
            f"payload CRC mismatch on seq {seq} (sample0 {sample0})")
    return Packet(seq=seq, sample0=sample0, nsamps=nsamps, nchan=nchan,
                  chan0=chan0, nbits=nbits,
                  band_descending=bool(flags & _FLAG_BAND_DESCENDING),
                  payload=payload), end


def read_packet_stream(read, on_corrupt=None):
    """Generator over packets from a byte-stream ``read(n)`` callable
    (socket ``recv`` adapter or file ``read``).  ``read`` must return
    b"" at EOF and at most ``n`` bytes otherwise.  Raises
    :class:`PacketError` on a torn header/payload (mid-packet EOF).

    The stream is length-framed, so one corrupt payload does not lose
    framing: with ``on_corrupt`` given a CRC-rejected packet is
    reported to it and skipped (its samples surface as a gap);
    without, :class:`PacketCorruptError` propagates.
    """
    def read_exact(n, *, partial_ok=False):
        parts = []
        got = 0
        while got < n:
            piece = read(n - got)
            if not piece:
                if got == 0 and partial_ok:
                    return b""
                raise PacketError(
                    f"stream ended mid-packet ({got}/{n} bytes)")
            parts.append(piece)
            got += len(piece)
        return b"".join(parts)

    while True:
        header = read_exact(HEADER_SIZE, partial_ok=True)
        if not header:
            return
        payload_len = _HEADER.unpack_from(header)[10]
        try:
            pkt, _ = decode_packet(header + read_exact(payload_len))
        except PacketCorruptError as exc:
            if on_corrupt is None:
                raise
            on_corrupt(exc)
            continue
        yield pkt


def packetize_array(data, *, samples_per_packet=256, nbits=0, nchan=None,
                    sample0=0, seq0=0, band_descending=False):
    """Cut a block into encoded packets (the local feeder / test
    harness; a real backend would do this on the correlator).

    ``data`` is either a ``(nchan, nsamps)`` float array (``nbits`` 0)
    or the raw ``(nsamps, bytes_per_frame)`` uint8 packed-frame array
    of a :class:`~.lowbit.PackedFrames` (``nbits`` 1/2/4; pass the
    logical ``nchan`` explicitly when the last byte is padding).
    Returns a list of encoded packet byte strings with consecutive
    ``seq`` and ``sample0`` fields.
    """
    if nbits == 0:
        arr = np.ascontiguousarray(np.asarray(data, dtype=np.float32).T)
        nchan = arr.shape[1]
    else:
        arr = np.ascontiguousarray(np.asarray(data, dtype=np.uint8))
        if nchan is None:
            nchan = _PER_BYTE[nbits] * arr.shape[1]
        elif frame_nbytes(nchan, nbits) != arr.shape[1]:
            raise PacketError(
                f"nchan {nchan} needs {frame_nbytes(nchan, nbits)} "
                f"bytes/frame, got rows of {arr.shape[1]}")
    out = []
    step = int(samples_per_packet)
    for i, off in enumerate(range(0, arr.shape[0], step)):
        rows = arr[off:off + step]
        out.append(encode_packet(
            seq=seq0 + i, sample0=sample0 + off, nchan=nchan,
            nbits=nbits, payload=rows.tobytes(),
            band_descending=band_descending))
    return out
