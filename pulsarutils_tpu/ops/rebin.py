"""Rebinning ops: block-sum down-sampling along channel and time axes.

Capability-equivalents of the reference's ``quick_chan_rebin``
(``pulsarutils/dedispersion.py:15-35``) and numba-jitted ``quick_resample``
(``pulsarutils/dedispersion.py:38-57``).  Both are pure reshape+sum, which
XLA lowers to a tiny fused reduction — no loops needed on any backend.

Both truncate trailing elements that do not fill a whole block, exactly like
the reference.
"""

from __future__ import annotations

import numpy as np


def quick_chan_rebin(counts, factor, xp=np):
    """Rebin along the **channel** (first) axis by an integer factor.

    Reference: ``pulsarutils/dedispersion.py:15-35``.  Trailing channels
    that do not fill a block are truncated:

    >>> quick_chan_rebin(np.ones((5, 3)), 2)
    array([[2., 2., 2.],
           [2., 2., 2.]])
    >>> quick_chan_rebin(np.arange(8).reshape(4, 2), 2)
    array([[ 2,  4],
           [10, 12]])
    """
    nchan, nbin = counts.shape
    n = int(nchan // factor)
    return counts[: n * factor, :].reshape(n, factor, nbin).sum(axis=1)


def quick_resample(counts, factor, xp=np):
    """Rebin along the **time** (last) axis by an integer factor.

    Returns a float array like the reference's njit loop accumulation
    (``pulsarutils/dedispersion.py:38-57``).  Works on 1-D or 2-D input
    (the reference requires 2-D; 1-D is accepted here for convenience and
    treated as a single channel).

    >>> quick_resample(np.ones((2, 6)), 3)
    array([[3., 3.],
           [3., 3.]])
    >>> quick_resample(np.arange(5.0), 2)  # trailing sample truncated
    array([1., 5.])
    """
    counts = xp.asarray(counts)
    squeeze = counts.ndim == 1
    if squeeze:
        counts = counts[None, :]
    nchan, nbin = counts.shape
    n = int(nbin // factor)
    out = (
        counts[:, : n * factor]
        .reshape(nchan, n, factor)
        .astype(_float_dtype(counts, xp))
        .sum(axis=2)
    )
    return out[0] if squeeze else out


def stretch_resample(x, indices, xp=np):
    """Resample along the time (last) axis at precomputed sample indices.

    The **fractional-stretch generalisation** of :func:`quick_resample`
    (the reference's resampling primitive only ever rebinned by an
    integer factor): ``out[..., n] = x[..., indices[n]]`` for any
    monotone index map, so a caller can stretch the time axis by a
    *non-integer, even time-varying* rate — the acceleration-search
    resample (:mod:`~pulsarutils_tpu.periodicity.accel`) maps
    ``n -> n - kappa n^2``.  ``indices`` must be integer, precomputed
    on the host in float64 (index arithmetic in float32 drifts by
    whole samples past ``n ~ 2^24``) and already clipped to the axis.

    >>> stretch_resample(np.arange(6.0), np.array([0, 2, 4]))
    array([0., 2., 4.])
    """
    x = xp.asarray(x)
    return xp.take(x, indices, axis=-1)


def block_sum_time(x, factor, xp=np):
    """Block-sum a batch of series ``(..., T)`` along the last axis.

    Generalised form of :func:`quick_resample` used by the batched S/N
    scorer: keeps whatever leading (trial) axes exist, truncates ``T`` to a
    multiple of ``factor``.
    """
    t = x.shape[-1]
    n = t // factor
    lead = x.shape[:-1]
    return x[..., : n * factor].reshape(*lead, n, factor).sum(axis=-1)


def _float_dtype(arr, xp):
    if arr.dtype in (np.dtype("float32"),):
        return arr.dtype
    if xp is np:
        return np.float64
    # keep accumulation in f32 on accelerator backends
    return np.float32
