"""Pallas TPU kernel for the dedispersion sweep hot loop.

Why a hand-written kernel: the XLA lowering of ``take_along_axis`` along
the time (lane) axis scalarises on TPU — the batched-gather formulation of
the sweep (see :mod:`.dedisperse`) runs barely above single-core NumPy
speed.  This kernel restores the op to what it physically is — per-channel
*contiguous shifted reads* accumulated into each trial's series — which the
VPU executes at near HBM bandwidth.

Design (capability-equivalent of the reference's hot trio
``roll_and_sum`` / ``_dedisperse`` / ``_dedispersion_search`` inner loop,
``pulsarutils/dedispersion.py:60-98,174-202``, re-thought for TPU):

* All trial delays are bounded by the band-crossing delay ``max_off``, so
  an output time tile ``[t0, t0 + T_TILE)`` of any trial only ever reads
  input samples from ``[t0, t0 + T_TILE + max_off)`` — i.e. from ``K =
  ceil(max_off / T_TILE) + 1`` *adjacent, tile-aligned* input tiles.  That
  makes the data movement expressible with plain ``BlockSpec``s (the same
  array is passed K times at staggered tile indices); Pallas's pipeline
  machinery then double-buffers the HBM->VMEM streaming automatically.
* Circular wraparound (the reference's ``np.roll`` semantics) is handled
  by extending the array host-side with its own head: ``data_ext[c, t] =
  data[c, t mod T]`` for ``t < Text``.  Gather arithmetic inside the
  kernel is then purely linear.
* The per-(trial, channel) shifts arrive as an SMEM block of int32; the
  inner loop is ``out[d] += window[c, shift[d, c] : shift[d, c] + T_TILE]``
  realised as aligned vector loads plus dynamic rotates (Mosaic forbids
  unaligned vector loads).  Two layouts:

  - ``layout="rows"`` (default, ~3x faster): each time tile is viewed as
    ``(8, L)`` row chunks (row s = samples ``[s*L, (s+1)*L)``), so a
    shifted tile read at offset ``r = q*L + m`` is a 16-row aligned load,
    one lane-rotate by ``m``, one sublane-rotate by ``q mod 8``, and a
    two-row blend at the ``L - m`` lane boundary — every op uses all 8
    sublanes (measured ~150 Gadd/s on v5e vs ~50 for flat).
  - ``layout="flat"``: (1, t_tile + 128)-lane aligned load plus a sub-128
    lane-rotate per (trial, channel) — simpler, but each op occupies one
    sublane of the VPU.
* Grid is ``(dm_blocks, time_tiles, chan_blocks)`` with channels innermost
  so each output block stays resident in VMEM while all channel blocks
  accumulate into it.

The public entry is :func:`dedisperse_plane_pallas`; shape padding (trials
to the DM block, channels to the channel block, time to the tile) happens
host-side and is sliced away on return.
"""

from __future__ import annotations

import functools

import numpy as np


def _pallas_modules():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return jax, jnp, pl, pltpu


def _kernel_body(off_ref, *refs, dm_block, chan_block, t_tile, k_tiles,
                 jnp, pl, pltpu):
    """out[d, :] += sum_c window[c, off[d, c] : off[d, c] + t_tile]."""
    import jax

    data_refs = refs[:k_tiles]
    out_ref = refs[k_tiles]
    win_ref = refs[k_tiles + 1]

    i_c = pl.program_id(2)

    @pl.when(i_c == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # stitch the K adjacent tiles into one contiguous VMEM window
    for k in range(k_tiles):
        win_ref[:, k * t_tile:(k + 1) * t_tile] = data_refs[k][:]

    # Mosaic vector loads need lane starts provably 128-aligned, so the
    # unaligned shifted read is an aligned (t_tile + 128)-lane load plus a
    # dynamic sub-128 left-rotate (tpu.DynamicRotateOp via pltpu.roll)
    def body(d, carry):
        acc = out_ref[pl.ds(d, 1), :]
        for c in range(chan_block):
            start = off_ref[0, 0, d, c]
            aligned = pl.multiple_of((start // 128) * 128, 128)
            win = win_ref[pl.ds(c, 1), pl.ds(aligned, t_tile + 128)]
            # left-rotate by r = start - aligned, expressed as a
            # non-negative right-rotate — tpu.DynamicRotateOp mishandles
            # negative dynamic shifts (interpret mode accepts them)
            rolled = pltpu.roll(win, (t_tile + 128 - (start - aligned))
                                % (t_tile + 128), 1)
            acc = acc + rolled[:, :t_tile]
        out_ref[pl.ds(d, 1), :] = acc
        return carry

    jax.lax.fori_loop(0, dm_block, body, 0)


def shifted_row_tile(win_ref, c, r, L, lane, jnp, pl, pltpu, q0=False):
    """Read ``window[r : r + 8L]`` as an (8, L) chunked tile.

    The circular-shift primitive shared by the rows-layout dedispersion
    kernel and the FDMT merge kernel: with ``r = q*L + m``, load 16
    window rows from the 8-aligned base (sublane starts must be provably
    8-aligned), lane-rotate left by ``m``, sublane-rotate up by
    ``q mod 8``, and blend each row with its successor at the ``L - m``
    lane boundary.  ``c`` indexes the leading dim of a 3-D window ref
    (``None`` for a 2-D ref); ``lane`` is a (8, L) lane iota.

    ``q0=True`` is the statically-known ``r < L`` fast path (every offset
    below one lane row, i.e. halo ``k_tiles == 2``): ``q = 0`` always, so
    the load base is static and the dynamic sublane rotate — a full
    16-row VPU op per (trial, channel) — is elided entirely (~1.3-1.5x
    on the benchmark geometry, whose band-crossing delay is < L = 1024).
    """
    if q0:
        rows16 = (win_ref[pl.ds(0, 16), :] if c is None
                  else win_ref[c, pl.ds(0, 16), :])
        rolled = pltpu.roll(rows16, (L - r) % L, 1)
        return jnp.where(lane < L - r, rolled[0:8], rolled[1:9])
    q = r // L
    m = r - q * L
    qa = pl.multiple_of((q // 8) * 8, 8)
    if c is None:
        rows16 = win_ref[pl.ds(qa, 16), :]
    else:
        rows16 = win_ref[c, pl.ds(qa, 16), :]
    rolled = pltpu.roll(rows16, (L - m) % L, 1)
    sr = pltpu.roll(rolled, (16 - (q - qa)) % 16, 0)
    return jnp.where(lane < L - m, sr[0:8], sr[1:9])


def _kernel_body_rows(off_ref, *refs, dm_block, chan_block, t_tile, k_tiles,
                      jnp, pl, pltpu):
    """Chunked-row variant: full-sublane ops.

    Each time tile is viewed as ``(8, L)`` with ``L = t_tile // 8`` (row s
    holds samples ``[s*L, (s+1)*L)``), so a shifted read of the whole tile
    at offset ``r = q*L + m`` is: load window rows ``q..q+8`` (9 rows),
    lane-rotate the block left by ``m``, and blend each row with its
    successor at the ``L - m`` lane boundary.  Every op runs on 8-sublane
    blocks — ~8x the VPU utilisation of the flat (1, t_tile) formulation.
    """
    import jax

    data_refs = refs[:k_tiles]
    out_ref = refs[k_tiles]
    win_ref = refs[k_tiles + 1]
    L = t_tile // 8
    q0 = k_tiles == 2  # halo of 2 tiles <=> every offset < L (see
    # _halo_tiles: (off // L + 23) // 8 == 2 iff off // L == 0)

    i_c = pl.program_id(2)

    @pl.when(i_c == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # stitch the K adjacent (8, L)-chunked tiles into one row window
    for k in range(k_tiles):
        win_ref[:, k * 8:(k + 1) * 8, :] = data_refs[k][:, 0]

    lane = jax.lax.broadcasted_iota(jnp.int32, (8, L), 1)

    def body(d, carry):
        acc = out_ref[d, 0]
        for c in range(chan_block):
            acc = acc + shifted_row_tile(win_ref, c, off_ref[0, 0, d, c],
                                         L, lane, jnp, pl, pltpu, q0=q0)
        out_ref[d, 0] = acc
        return carry

    jax.lax.fori_loop(0, dm_block, body, 0)


@functools.lru_cache(maxsize=64)
def _build_kernel_rows(ndm_p, nchan_p, t_ext, t_out, dm_block, chan_block,
                       t_tile, k_tiles, interpret):
    jax, jnp, pl, pltpu = _pallas_modules()

    n_dm = ndm_p // dm_block
    n_t = t_out // t_tile
    n_chan = nchan_p // chan_block
    n_src = t_ext // t_tile
    L = t_tile // 8

    data_specs = [
        pl.BlockSpec((chan_block, 1, 8, L),
                     functools.partial(lambda i_d, i_t, i_c, _k:
                                       (i_c, (i_t + _k) % n_src, 0, 0), _k=k))
        for k in range(k_tiles)
    ]
    off_spec = pl.BlockSpec((1, 1, dm_block, chan_block),
                            lambda i_d, i_t, i_c: (i_d, i_c, 0, 0),
                            memory_space=pltpu.SMEM)
    out_spec = pl.BlockSpec((dm_block, 1, 8, L),
                            lambda i_d, i_t, i_c: (i_d, i_t, 0, 0))

    kernel = functools.partial(_kernel_body_rows, dm_block=dm_block,
                               chan_block=chan_block, t_tile=t_tile,
                               k_tiles=k_tiles, jnp=jnp, pl=pl, pltpu=pltpu)

    call = pl.pallas_call(
        kernel,
        grid=(n_dm, n_t, n_chan),
        in_specs=[off_spec] + data_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((ndm_p, n_t, 8, L), jnp.float32),
        scratch_shapes=[pltpu.VMEM((chan_block, k_tiles * 8, L),
                                   jnp.float32)],
        interpret=bool(interpret),
    )

    @jax.jit
    def run(offsets, data_ext):
        data_4d = data_ext.reshape(nchan_p, n_src, 8, L)
        out = call(offsets, *([data_4d] * k_tiles))
        return out.reshape(ndm_p, t_out)

    return run


@functools.lru_cache(maxsize=64)
def _build_kernel(ndm_p, nchan_p, t_ext, t_out, dm_block, chan_block,
                  t_tile, k_tiles, interpret):
    jax, jnp, pl, pltpu = _pallas_modules()

    n_dm = ndm_p // dm_block
    n_t = t_out // t_tile
    n_chan = nchan_p // chan_block
    # number of time tiles in the source array; when it equals n_t (no
    # extension) the staggered reads wrap tile-modulo, which IS the exact
    # circular wrap because t_tile divides the array length
    n_src = t_ext // t_tile

    # the same (extended) array is passed K times at staggered tile
    # indices, giving the kernel a (chan_block, K * t_tile) contiguous
    # window
    data_specs = [
        pl.BlockSpec((chan_block, t_tile),
                     functools.partial(lambda i_d, i_t, i_c, _k:
                                       (i_c, (i_t + _k) % n_src), _k=k))
        for k in range(k_tiles)
    ]
    # Mosaic requires the last two block dims to be (8, 128)-divisible OR
    # equal to the array dims; a raw (dm_block, chan_block) window over the
    # (ndm, nchan) table satisfies neither, so the offsets arrive pre-tiled
    # as (n_dm, n_chan, dm_block, chan_block) and each grid step takes one
    # whole (dm_block, chan_block) tile — trailing dims == array dims.
    off_spec = pl.BlockSpec((1, 1, dm_block, chan_block),
                            lambda i_d, i_t, i_c: (i_d, i_c, 0, 0),
                            memory_space=pltpu.SMEM)
    out_spec = pl.BlockSpec((dm_block, t_tile),
                            lambda i_d, i_t, i_c: (i_d, i_t))

    kernel = functools.partial(_kernel_body, dm_block=dm_block,
                               chan_block=chan_block, t_tile=t_tile,
                               k_tiles=k_tiles, jnp=jnp, pl=pl, pltpu=pltpu)

    call = pl.pallas_call(
        kernel,
        grid=(n_dm, n_t, n_chan),
        in_specs=[off_spec] + data_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((ndm_p, t_out), jnp.float32),
        scratch_shapes=[pltpu.VMEM((chan_block, k_tiles * t_tile),
                                   jnp.float32)],
        interpret=bool(interpret),
    )

    @jax.jit
    def run(offsets, data_ext):
        return call(offsets, *([data_ext] * k_tiles))

    return run


def _pick_t_tile(max_off, nsamples, layout="flat"):
    """Default time tile: 8192 for the rows layout (measured optimum on
    v5e), else the smallest power-of-two >= 2048 covering the halo; capped
    so tiny inputs still work."""
    if layout == "rows":
        t_tile = 8192
    else:
        t_tile = 2048
        while t_tile < min(max_off, 1 << 15):
            t_tile *= 2
    return min(t_tile, max(256, 1 << int(np.floor(np.log2(max(nsamples, 256))))))


#: scoped-VMEM budget (bytes) the auto-blocking tries to stay under; the
#: hardware limit is 16 MB and the pipeline double-buffers in/out blocks
VMEM_BUDGET = 10 << 20


def _halo_tiles(max_off, t_tile, layout):
    """Number of staggered input tiles covering the shifted-read halo.

    One formula shared by the kernel builder and the VMEM fitter — the
    footprint model must match the kernel actually built.
    """
    if layout == "rows":
        l_lane = max(1, t_tile // 8)
        return (max_off // l_lane + 23) // 8
    return (max_off + 128) // t_tile + 2


def _fit_blocks_to_vmem(dm_block, chan_block, t_tile, max_off, layout):
    """Shrink blocking factors until the kernel's VMEM footprint fits.

    Footprint model: double-buffered data blocks (k_tiles * chan_block *
    t_tile), the stitched window scratch (same size), and double-buffered
    output blocks (dm_block * t_tile), all float32.
    """
    while True:
        k_tiles = _halo_tiles(max_off, t_tile, layout)
        win = chan_block * k_tiles * t_tile * 4
        data = 2 * k_tiles * chan_block * t_tile * 4
        out = 2 * dm_block * t_tile * 4
        if win + data + out <= VMEM_BUDGET:
            return dm_block, chan_block, t_tile
        if chan_block > 8:
            chan_block //= 2
        elif dm_block > 8:
            dm_block //= 2
        elif t_tile > 1024:
            t_tile //= 2
        else:
            return dm_block, chan_block, t_tile  # smallest legal; let
            # Mosaic report the real limit if this still does not fit


def rebase_offsets(offsets, nsamples):
    """Host-side offset rebase: wrapped ``[0, T)`` offsets -> small
    non-negative offsets plus a static rotation constant.

    ``normalize_shifts`` wraps negative (above-band-centre) shifts to values
    near ``T``, which would force the kernel's halo to span the whole array.
    Mapping back to signed form and subtracting the (128-aligned) minimum
    yields offsets bounded by the band-crossing span instead.  The kernel
    output is then the reference plane rotated by ``k``; rolling each row by
    ``-k`` restores it exactly (same floats, same summation order).

    Returns ``(offsets_rebased, k, max_off)`` — all host values.
    """
    offsets = np.asarray(offsets, dtype=np.int64)
    half = nsamples // 2
    signed = (offsets + half) % nsamples - half
    k = 128 * int(np.floor(signed.min(initial=0) / 128))
    rebased = (signed - k).astype(np.int32)
    return rebased, k, int(rebased.max(initial=0))


def dedisperse_plane_pallas_traced(data, offsets, max_off, dm_block=None,
                                   chan_block=None, t_tile=None,
                                   interpret=None, roll_k=0, layout="rows"):
    """Trace-friendly core of :func:`dedisperse_plane_pallas`.

    ``data`` and ``offsets`` may be traced jax arrays (e.g. shards inside a
    ``shard_map``); ``max_off`` must be a *static* host int bounding every
    offset (it sets the halo tile count, which is a compile-time property).
    ``roll_k`` is the static rotation constant from :func:`rebase_offsets`
    (the returned plane is rolled by ``-roll_k`` to undo the rebase).
    """
    jax, jnp, pl, pltpu = _pallas_modules()
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    data = jnp.asarray(data, dtype=jnp.float32)
    offsets = jnp.asarray(offsets, dtype=jnp.int32)
    nchan, t = data.shape
    ndm = offsets.shape[0]

    max_off = int(max_off)
    if dm_block is None:
        dm_block = 32
    if chan_block is None:
        chan_block = 64
    if t_tile is None:
        t_tile = _pick_t_tile(max_off, t, layout)
    t_tile = int(min(t_tile, t))

    dm_block = int(min(dm_block, max(1, ndm)))
    chan_block = int(min(chan_block, nchan))
    if not interpret:
        # shrink (possibly caller-supplied) blockings that would overrun
        # scoped VMEM — a compile failure helps nobody
        dm_block, chan_block, t_tile = _fit_blocks_to_vmem(
            dm_block, chan_block, t_tile, max_off, layout)
        # Mosaic block rule: trailing block dims must be (8, 128)-divisible
        # or equal to the (padded) array dims.  dm_block/chan_block sit in
        # the sublane slot of their blocks; t_tile in the lane slot.  For
        # the rows layout the lane slot holds L = t_tile // 8, so compiled
        # rows tiles are at least 1024 (an explicit smaller t_tile is
        # honoured in interpret mode, where Mosaic rules don't apply).
        dm_block = max(8, -(-dm_block // 8) * 8)
        chan_block = max(8, -(-chan_block // 8) * 8)
        if layout == "rows":
            t_tile = max(1024, t_tile - t_tile % 1024)
        else:
            t_tile = max(128, t_tile - t_tile % 128)
    elif layout == "rows":
        # interpret mode: honour the requested tile, but the (8, L) row
        # view still needs t_tile divisible by 8
        t_tile = max(8, t_tile - t_tile % 8)

    # halo: rows layout reads window rows qa..qa+15 with qa = 8*(off//(8L));
    # flat layout loads (t_tile + 128) lanes from floor(off/128)*128
    k_tiles = _halo_tiles(max_off, t_tile, layout)

    # pad trials (duplicate last), channels (zeros), time (circular wrap)
    ndm_p = -(-ndm // dm_block) * dm_block
    if ndm_p != ndm:
        offsets = jnp.concatenate(
            [offsets, jnp.repeat(offsets[-1:], ndm_p - ndm, axis=0)])
    nchan_p = -(-nchan // chan_block) * chan_block
    if nchan_p != nchan:
        data = jnp.concatenate(
            [data, jnp.zeros((nchan_p - nchan, t), jnp.float32)])
        # padded channels read window start 0; they contribute zeros anyway
        offsets = jnp.concatenate(
            [offsets, jnp.zeros((ndm_p, nchan_p - nchan), jnp.int32)],
            axis=1)

    # pre-tile the offsets to the (n_dm, n_chan, dm_block, chan_block)
    # layout the kernel's SMEM BlockSpec expects (see _build_kernel)
    offsets = (offsets
               .reshape(ndm_p // dm_block, dm_block,
                        nchan_p // chan_block, chan_block)
               .transpose(0, 2, 1, 3))

    n_t = -(-t // t_tile)
    t_out = n_t * t_tile
    if t % t_tile == 0:
        # no extension: the staggered BlockSpec reads wrap tile-modulo,
        # which is the exact circular wrap when t_tile divides t — zero
        # extra HBM (the extension copy would double the footprint at the
        # 4 GB benchmark size)
        text = t
        data_ext = data
    else:
        # circular extension: data_ext[:, i] = data[:, i % t]
        text = (n_t + k_tiles - 1) * t_tile
        if text - t <= t:
            data_ext = jnp.concatenate([data, data[:, :text - t]], axis=1)
        else:
            reps = max(2, -(-text // t) + 1)
            data_ext = jnp.concatenate([data] * reps, axis=1)[:, :text]

    build = _build_kernel_rows if layout == "rows" else _build_kernel
    run = build(ndm_p, nchan_p, text, t_out, dm_block, chan_block,
                t_tile, k_tiles, interpret)
    plane = run(offsets, data_ext)[:ndm, :t]
    if roll_k:
        plane = jnp.roll(plane, -roll_k, axis=1)
    return plane


def dedisperse_plane_pallas(data, offsets, dm_block=None, chan_block=None,
                            t_tile=None, interpret=None, layout="rows"):
    """Dedispersed plane ``out[d, t] = sum_c data[c, (t + off[d,c]) % T]``.

    Parameters
    ----------
    data : (nchan, T) float32 array (device or host)
    offsets : (ndm, nchan) int32 gather offsets — the per-channel DM delays
        in samples, wrapped into ``[0, T)`` (same convention as
        :func:`~pulsarutils_tpu.ops.dedisperse.dedisperse_block_jax`).
        Must be concrete (host) values; inside traced code use
        :func:`dedisperse_plane_pallas_traced` with a static ``max_off``.
    dm_block, chan_block : kernel blocking (trials per output block,
        channels accumulated per grid step).
    t_tile : time-tile length; default picked from the maximum offset.
    interpret : run in the Pallas interpreter.  Default (``None``) auto:
        compiled on TPU, interpreted elsewhere (CPU testing).

    Returns
    -------
    (ndm, T) float32 device array.
    """
    nsamples = int(np.shape(data)[1])
    offsets, roll_k, max_off = rebase_offsets(offsets, nsamples)
    return dedisperse_plane_pallas_traced(data, offsets, max_off,
                                          dm_block=dm_block,
                                          chan_block=chan_block,
                                          t_tile=t_tile, interpret=interpret,
                                          roll_k=roll_k, layout=layout)
