"""Pallas TPU kernel for the dedispersion sweep hot loop.

Why a hand-written kernel: the XLA lowering of ``take_along_axis`` along
the time (lane) axis scalarises on TPU — the batched-gather formulation of
the sweep (see :mod:`.dedisperse`) runs barely above single-core NumPy
speed.  This kernel restores the op to what it physically is — per-channel
*contiguous shifted reads* accumulated into each trial's series — which the
VPU executes at near HBM bandwidth.

Design (capability-equivalent of the reference's hot trio
``roll_and_sum`` / ``_dedisperse`` / ``_dedispersion_search`` inner loop,
``pulsarutils/dedispersion.py:60-98,174-202``, re-thought for TPU):

* All trial delays are bounded by the band-crossing delay ``max_off``, so
  an output time tile ``[t0, t0 + T_TILE)`` of any trial only ever reads
  input samples from ``[t0, t0 + T_TILE + max_off)`` — i.e. from ``K =
  ceil(max_off / T_TILE) + 1`` *adjacent, tile-aligned* input tiles.  That
  makes the data movement expressible with plain ``BlockSpec``s (the same
  array is passed K times at staggered tile indices); Pallas's pipeline
  machinery then double-buffers the HBM->VMEM streaming automatically.
* Circular wraparound (the reference's ``np.roll`` semantics) is handled
  by extending the array host-side with its own head: ``data_ext[c, t] =
  data[c, t mod T]`` for ``t < Text``.  Gather arithmetic inside the
  kernel is then purely linear.
* The per-(trial, channel) shifts arrive as an SMEM block of int32; the
  inner loop is ``out[d] += window[c, shift[d, c] : shift[d, c] + T_TILE]``
  — a dynamic *lane slice* from VMEM, which Mosaic lowers to vector
  rotates instead of a scalarised gather.
* Grid is ``(dm_blocks, time_tiles, chan_blocks)`` with channels innermost
  so each output block stays resident in VMEM while all channel blocks
  accumulate into it.

The public entry is :func:`dedisperse_plane_pallas`; shape padding (trials
to the DM block, channels to the channel block, time to the tile) happens
host-side and is sliced away on return.
"""

from __future__ import annotations

import functools

import numpy as np


def _pallas_modules():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return jax, jnp, pl, pltpu


def _kernel_body(off_ref, *refs, dm_block, chan_block, t_tile, k_tiles,
                 jnp, pl):
    """out[d, :] += sum_c window[c, off[d, c] : off[d, c] + t_tile]."""
    import jax

    data_refs = refs[:k_tiles]
    out_ref = refs[k_tiles]
    win_ref = refs[k_tiles + 1]

    i_c = pl.program_id(2)

    @pl.when(i_c == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # stitch the K adjacent tiles into one contiguous VMEM window
    for k in range(k_tiles):
        win_ref[:, k * t_tile:(k + 1) * t_tile] = data_refs[k][:]

    def body(d, carry):
        acc = out_ref[d, :]
        for c in range(chan_block):
            start = off_ref[d, c]
            acc = acc + win_ref[c, pl.ds(start, t_tile)]
        out_ref[d, :] = acc
        return carry

    jax.lax.fori_loop(0, dm_block, body, 0)


@functools.lru_cache(maxsize=64)
def _build_kernel(ndm_p, nchan_p, t_ext, t_out, dm_block, chan_block,
                  t_tile, k_tiles, interpret):
    jax, jnp, pl, pltpu = _pallas_modules()

    n_dm = ndm_p // dm_block
    n_t = t_out // t_tile
    n_chan = nchan_p // chan_block

    # the same extended array is passed K times at staggered tile indices,
    # giving the kernel a (chan_block, K * t_tile) contiguous window
    data_specs = [
        pl.BlockSpec((chan_block, t_tile),
                     functools.partial(lambda i_d, i_t, i_c, _k:
                                       (i_c, i_t + _k), _k=k))
        for k in range(k_tiles)
    ]
    off_spec = pl.BlockSpec((dm_block, chan_block),
                            lambda i_d, i_t, i_c: (i_d, i_c),
                            memory_space=pltpu.SMEM)
    out_spec = pl.BlockSpec((dm_block, t_tile),
                            lambda i_d, i_t, i_c: (i_d, i_t))

    kernel = functools.partial(_kernel_body, dm_block=dm_block,
                               chan_block=chan_block, t_tile=t_tile,
                               k_tiles=k_tiles, jnp=jnp, pl=pl)

    call = pl.pallas_call(
        kernel,
        grid=(n_dm, n_t, n_chan),
        in_specs=[off_spec] + data_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((ndm_p, t_out), jnp.float32),
        scratch_shapes=[pltpu.VMEM((chan_block, k_tiles * t_tile),
                                   jnp.float32)],
        interpret=bool(interpret),
    )

    @jax.jit
    def run(offsets, data_ext):
        return call(offsets, *([data_ext] * k_tiles))

    return run


def _pick_t_tile(max_off, nsamples):
    """Smallest power-of-two tile >= 2048 that needs at most 2 extra tiles
    of halo, capped so tiny inputs still work."""
    t_tile = 2048
    while t_tile < min(max_off, 1 << 15):
        t_tile *= 2
    return min(t_tile, max(256, 1 << int(np.floor(np.log2(max(nsamples, 256))))))


def dedisperse_plane_pallas_traced(data, offsets, max_off, dm_block=64,
                                   chan_block=8, t_tile=None, interpret=None):
    """Trace-friendly core of :func:`dedisperse_plane_pallas`.

    ``data`` and ``offsets`` may be traced jax arrays (e.g. shards inside a
    ``shard_map``); ``max_off`` must be a *static* host int bounding every
    offset (it sets the halo tile count, which is a compile-time property).
    """
    jax, jnp, pl, pltpu = _pallas_modules()
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    data = jnp.asarray(data, dtype=jnp.float32)
    offsets = jnp.asarray(offsets, dtype=jnp.int32)
    nchan, t = data.shape
    ndm = offsets.shape[0]

    max_off = int(max_off)
    if t_tile is None:
        t_tile = _pick_t_tile(max_off, t)
    t_tile = int(min(t_tile, t))
    k_tiles = max_off // t_tile + 2  # halo tiles covering off + t_tile - 1

    dm_block = int(min(dm_block, max(1, ndm)))
    chan_block = int(min(chan_block, nchan))

    # pad trials (duplicate last), channels (zeros), time (circular wrap)
    ndm_p = -(-ndm // dm_block) * dm_block
    if ndm_p != ndm:
        offsets = jnp.concatenate(
            [offsets, jnp.repeat(offsets[-1:], ndm_p - ndm, axis=0)])
    nchan_p = -(-nchan // chan_block) * chan_block
    if nchan_p != nchan:
        data = jnp.concatenate(
            [data, jnp.zeros((nchan_p - nchan, t), jnp.float32)])
        # padded channels read window start 0; they contribute zeros anyway
        offsets = jnp.concatenate(
            [offsets, jnp.zeros((ndm_p, nchan_p - nchan), jnp.int32)],
            axis=1)

    n_t = -(-t // t_tile)
    t_out = n_t * t_tile
    text = (n_t + k_tiles - 1) * t_tile
    # circular extension: data_ext[:, i] = data[:, i % t]
    reps = max(2, -(-text // t) + 1)
    data_ext = jnp.concatenate([data] * reps, axis=1)[:, :text]

    run = _build_kernel(ndm_p, nchan_p, text, t_out, dm_block, chan_block,
                        t_tile, k_tiles, interpret)
    plane = run(offsets, data_ext)
    return plane[:ndm, :t]


def dedisperse_plane_pallas(data, offsets, dm_block=64, chan_block=8,
                            t_tile=None, interpret=None):
    """Dedispersed plane ``out[d, t] = sum_c data[c, (t + off[d,c]) % T]``.

    Parameters
    ----------
    data : (nchan, T) float32 array (device or host)
    offsets : (ndm, nchan) int32 gather offsets — the per-channel DM delays
        in samples, wrapped into ``[0, T)`` (same convention as
        :func:`~pulsarutils_tpu.ops.dedisperse.dedisperse_block_jax`).
        Must be concrete (host) values; inside traced code use
        :func:`dedisperse_plane_pallas_traced` with a static ``max_off``.
    dm_block, chan_block : kernel blocking (trials per output block,
        channels accumulated per grid step).
    t_tile : time-tile length; default picked from the maximum offset.
    interpret : run in the Pallas interpreter.  Default (``None``) auto:
        compiled on TPU, interpreted elsewhere (CPU testing).

    Returns
    -------
    (ndm, T) float32 device array.
    """
    offsets = np.asarray(offsets, dtype=np.int32)
    max_off = int(offsets.max(initial=0))
    return dedisperse_plane_pallas_traced(data, offsets, max_off,
                                          dm_block=dm_block,
                                          chan_block=chan_block,
                                          t_tile=t_tile, interpret=interpret)
