"""One-pass Pallas scorer for coarse (FDMT) planes (round 5).

VERDICT r4 #3 named the fused one-pass scorer as the next FDMT lever:
the stage probe (``tools/fdmt_stage_probe.py``, ``docs/performance.md``)
measured the XLA chunked scorer at ~0.17 s standalone on the 513 x 1M
coarse plane — instruction/materialisation-bound, not traffic-bound
(the mean-subtracted copy plus the boxcar pyramid and three sliding
cert sums materialise ~9 GB of effective HBM temps against a ~2 GB
plane).  This kernel reads the plane ONCE: a grid of (8-row block,
time tile) cells accumulates per-row partial statistics in VMEM
scratch across the time tiles and emits the finished score vectors at
each row block's last tile — no plane-sized temporary ever exists.

Scoring semantics are :func:`..ops.search.score_profiles` +
:func:`..ops.search.cert_profile_scores` (reference per-trial loop,
``pulsarutils/dedispersion.py:186-201``, plus the hybrid's sliding
certificate row):

* window/peak selection is EXACT (same strict-inequality tie-breaking,
  same first-occurrence argmax, same ``peak = block_index * window``
  convention) — pinned by ``tests/test_score_pallas.py``;
* float values (max, std, snr, cert) agree to f32 reduction order: the
  kernel accumulates per-tile partials sequentially where the XLA
  scorer reduces whole rows, so sums associate differently (same
  floats, different trees).  Coarse scores feed seed selection and
  guarantee-loop margins, both of which already absorb
  within-one-trial coarse error; the hybrid's EXACT rescore path
  (``_fused_rescore_kernel`` -> ``score_profiles_stacked``) is
  untouched, so exact-hit parity vs the reference is unaffected.

Numerical safety (the round-4 mean-fold lesson): raw block sums cancel
catastrophically at large DC offsets in float32, so nothing here
reduces raw values.  Each row block is CENTERED on the first tile's
mean ``c`` (within ~std/sqrt(T_BLK) of the row mean) before any
reduction; the exact residual mean ``m = mean(x - c)`` is recovered
from the accumulated centered sum and folded back analytically
(``max(blocksum(x - mean)) = max(blocksum(x - c)) - w*m`` — subtracting
a constant moves every block sum equally, so maxima/argmaxima are
computed on well-centered values and the correction is exact algebra,
not a cancelling subtraction of large floats).
"""

from __future__ import annotations

import functools

import numpy as np

#: scratch slot indices (each slot is one (8, 128) f32 tile per row block)
_C, _SUM, _SSQ = 0, 1, 2
_MAX1, _ARG1 = 3, 4
_SQ2, _MAX2, _ARG2 = 5, 6, 7
_SQ4, _MAX4, _ARG4 = 8, 9, 10
_SQ8, _MAX8, _ARG8 = 11, 12, 13
_CM2, _CM3, _CM4 = 14, 15, 16
_FIRST3, _LAST3 = 17, 18
_NSLOT = 19

#: preferred time-tile widths (largest dividing T wins; all multiples of
#: 8 so width-8 blocks never cross a tile boundary)
_T_BLKS = (16384, 8192, 4096, 2048, 1024)


def pick_score_tile(t):
    """Largest supported time tile dividing ``t`` (0 if none)."""
    for t_blk in _T_BLKS:
        if t % t_blk == 0:
            return t_blk
    return 0


@functools.lru_cache(maxsize=16)
def _build_score_kernel(rows_p, t, t_blk, with_cert, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_t = t // t_blk
    n_rb = rows_p // 8
    BIG = np.float32(1e18)
    NEG = np.float32(-1e30)

    def lroll(v, s):
        # left-rotate by s lanes: result[i] = v[(i + s) mod L]
        length = v.shape[-1]
        return pltpu.roll(v, (length - s) % length, 1)

    def rroll(v, s):
        return pltpu.roll(v, s % v.shape[-1], 1)

    def kernel(x_ref, out_ref, st_ref):
        i_t = pl.program_id(1)
        lane = jax.lax.broadcasted_iota(jnp.int32, (8, t_blk), 1)
        lane_f = lane.astype(jnp.float32)
        lane128 = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1)

        raw = x_ref[:]

        @pl.when(i_t == 0)
        def _init():
            c = jnp.sum(raw, axis=1, keepdims=True) / jnp.float32(t_blk)
            st_ref[_C] = jnp.broadcast_to(c, (8, 128))
            zero = jnp.zeros((8, 128), jnp.float32)
            for s in (_SUM, _SSQ, _ARG1, _ARG2, _ARG4, _ARG8,
                      _SQ2, _SQ4, _SQ8):
                st_ref[s] = zero
            for s in (_MAX1, _MAX2, _MAX4, _MAX8, _CM2, _CM3, _CM4):
                st_ref[s] = jnp.full((8, 128), NEG)

        c = st_ref[_C][:, 0:1]
        x = raw - c

        if with_cert:
            @pl.when(i_t == 0)
            def _first3():
                # centered first 3 samples at lanes 3..5 (the final
                # circular boundary pass reads them there)
                st_ref[_FIRST3] = rroll(x[:, :128], 3)

        # ---- sliding-window boundary pass for the PREVIOUS tile -------
        # (windows starting in the previous tile's last 3 lanes reach
        # into this tile; st[_LAST3] holds those lanes at positions 0..2)
        def boundary(prev3, cur3):
            m0_2 = lane128 < 3
            m3_5 = (lane128 >= 3) & (lane128 < 6)
            seq = (jnp.where(m0_2, prev3, 0.0)
                   + jnp.where(m3_5, cur3, 0.0))
            s2 = seq + lroll(seq, 1)
            s3 = s2 + lroll(seq, 2)
            s4 = s2 + lroll(s2, 2)
            st_ref[_CM2] = jnp.maximum(
                st_ref[_CM2],
                jnp.max(jnp.where(lane128 == 2, s2, NEG), axis=1,
                        keepdims=True))
            st_ref[_CM3] = jnp.maximum(
                st_ref[_CM3],
                jnp.max(jnp.where((lane128 >= 1) & (lane128 < 3), s3,
                                  NEG), axis=1, keepdims=True))
            st_ref[_CM4] = jnp.maximum(
                st_ref[_CM4],
                jnp.max(jnp.where(lane128 < 3, s4, NEG), axis=1,
                        keepdims=True))

        if with_cert:
            @pl.when(i_t > 0)
            def _bnd_prev():
                boundary(st_ref[_LAST3], rroll(x[:, :128], 3))

        # ---- in-tile partials ----------------------------------------
        st_ref[_SUM] += jnp.sum(x, axis=1, keepdims=True)
        st_ref[_SSQ] += jnp.sum(x * x, axis=1, keepdims=True)

        s2 = x + lroll(x, 1)
        s4 = s2 + lroll(s2, 2)
        s8 = s4 + lroll(s4, 4)

        def upd(vals, mask, max_slot, arg_slot, sq_slot):
            v = jnp.where(mask, vals, NEG)
            tile_max = jnp.max(v, axis=1, keepdims=True)
            tile_arg = jnp.min(
                jnp.where(v == tile_max, lane_f, BIG), axis=1,
                keepdims=True)
            run_max = st_ref[max_slot][:, 0:1]
            better = tile_max > run_max
            st_ref[max_slot] = jnp.broadcast_to(
                jnp.where(better, tile_max, run_max), (8, 128))
            run_arg = st_ref[arg_slot][:, 0:1]
            g_arg = tile_arg + jnp.float32(t_blk) * i_t.astype(jnp.float32)
            st_ref[arg_slot] = jnp.broadcast_to(
                jnp.where(better, g_arg, run_arg), (8, 128))
            if sq_slot is not None:
                st_ref[sq_slot] += jnp.sum(
                    jnp.where(mask, vals * vals, 0.0), axis=1,
                    keepdims=True)

        true_mask = lane >= 0
        upd(x, true_mask, _MAX1, _ARG1, None)
        upd(s2, lane % 2 == 0, _MAX2, _ARG2, _SQ2)
        upd(s4, lane % 4 == 0, _MAX4, _ARG4, _SQ4)
        upd(s8, lane % 8 == 0, _MAX8, _ARG8, _SQ8)

        if with_cert:
            # sliding cert maxima over windows fully inside this tile
            s3 = s2 + lroll(x, 2)
            st_ref[_CM2] = jnp.maximum(
                st_ref[_CM2],
                jnp.max(jnp.where(lane <= t_blk - 2, s2, NEG), axis=1,
                        keepdims=True))
            st_ref[_CM3] = jnp.maximum(
                st_ref[_CM3],
                jnp.max(jnp.where(lane <= t_blk - 3, s3, NEG), axis=1,
                        keepdims=True))
            st_ref[_CM4] = jnp.maximum(
                st_ref[_CM4],
                jnp.max(jnp.where(lane <= t_blk - 4, s4, NEG), axis=1,
                        keepdims=True))

            # centered last 3 samples -> lanes 0..2 for the next boundary
            st_ref[_LAST3] = lroll(x, t_blk - 3)[:, :128]

        # ---- finish the row block ------------------------------------
        @pl.when(i_t == n_t - 1)
        def _emit():
            if with_cert:
                # circular wrap: windows starting in the row's last 3
                # samples
                boundary(st_ref[_LAST3], st_ref[_FIRST3])

            tt = jnp.float32(t)
            m = st_ref[_SUM][:, 0:1] / tt
            var = st_ref[_SSQ][:, 0:1] / tt - m * m
            std = jnp.sqrt(jnp.maximum(var, 0.0))
            maxv = st_ref[_MAX1][:, 0:1] - m

            best_snr = jnp.zeros((8, 1), jnp.float32)
            best_w = jnp.zeros((8, 1), jnp.float32)
            best_p = jnp.zeros((8, 1), jnp.float32)
            for w, max_slot, arg_slot, sq_slot in (
                    (1, _MAX1, _ARG1, None),
                    (2, _MAX2, _ARG2, _SQ2),
                    (4, _MAX4, _ARG4, _SQ4),
                    (8, _MAX8, _ARG8, _SQ8)):
                wm = jnp.float32(w) * m
                if sq_slot is None:
                    var_w, mx = var, maxv
                else:
                    nb = tt / jnp.float32(w)
                    var_w = st_ref[sq_slot][:, 0:1] / nb - wm * wm
                    mx = st_ref[max_slot][:, 0:1] - wm
                snr_w = mx / jnp.sqrt(jnp.maximum(var_w, 1e-30))
                better = snr_w > best_snr
                best_snr = jnp.where(better, snr_w, best_snr)
                best_w = jnp.where(better, jnp.float32(w), best_w)
                best_p = jnp.where(better, st_ref[arg_slot][:, 0:1],
                                   best_p)

            cols = [maxv, std, best_snr, best_w, best_p]
            if with_cert:
                denom = jnp.maximum(std, 1e-30)
                cert = (st_ref[_CM2][:, 0:1] - 2.0 * m) / (
                    denom * jnp.float32(np.sqrt(2.0)))
                cert = jnp.maximum(
                    cert, (st_ref[_CM3][:, 0:1] - 3.0 * m) / (
                        denom * jnp.float32(np.sqrt(3.0))))
                cert = jnp.maximum(
                    cert, (st_ref[_CM4][:, 0:1] - 4.0 * m) / (
                        denom * jnp.float32(2.0)))
                cols.append(cert)

            out = jnp.zeros((8, 128), jnp.float32)
            for k, v in enumerate(cols):
                out = out + jnp.where(lane128 == k, v, 0.0)
            out_ref[:] = out

    call = pl.pallas_call(
        kernel,
        grid=(n_rb, n_t),
        in_specs=[pl.BlockSpec((8, t_blk), lambda i_r, i_t: (i_r, i_t))],
        out_specs=pl.BlockSpec((8, 128), lambda i_r, i_t: (i_r, 0)),
        out_shape=jax.ShapeDtypeStruct((rows_p, 128), jnp.float32),
        scratch_shapes=[pltpu.VMEM((_NSLOT, 8, 128), jnp.float32)],
        interpret=bool(interpret),
    )
    return call


def score_enabled():
    """Resolve the one-pass-scorer knob (PUTPU_PALLAS_SCORE: ''=auto,
    0, 1).  Mirrors ``fdmt._head_enabled``: resolved at call sites so a
    toggle is never served a stale compiled program."""
    from ..utils.knobs import tristate_env

    return tristate_env("PUTPU_PALLAS_SCORE")


def _kernel_scores(rows_p, t, t_blk, with_cert, interpret, sub):
    """Run the one-pass kernel on the 8-aligned row block ``sub``.

    Split out of :func:`score_plane_pallas` so tests can stub the
    (expensive) kernel invocation while exercising the wrapper's
    checks (the 2^24 peak-exactness warning below).
    """
    import jax.numpy as jnp

    return _build_score_kernel(rows_p, t, t_blk, with_cert, interpret)(
        jnp.asarray(sub, jnp.float32))


def score_plane_pallas(plane, with_cert=False, interpret=False):
    """One-pass scores of ``plane`` — drop-in for
    :func:`..ops.search.score_profiles_chunked` on tile-friendly shapes.

    Returns the stacked ``(5, rows)`` float32 array (``(6, rows)`` with
    ``with_cert``: the sliding certificate row appended).  Raises
    ``ValueError`` when no supported tile divides the time axis — the
    caller falls back to the XLA scorer.

    Peak indices are accumulated as float32 in the kernel (the global
    argmax slot is ``tile_arg + t_blk * i_t``), exact only below 2^24
    samples — the same float32-pack limit as
    :func:`..ops.search.score_profiles_stacked`, and the same warning
    fires above it (ADVICE r5: this path previously accepted e.g. a
    tile-divisible 2^25 silently while the XLA scorer warned).

    Row counts are handled without any plane-sized copy (the motivating
    coarse plane is 513 x 1M — an odd row count; padding it would
    re-materialise ~2 GB per search, code-review r5): the 8-aligned
    row prefix goes through the kernel and the <= 7 remainder rows
    through the XLA scorer (same per-row semantics, independent rows).
    """
    import jax.numpy as jnp

    from .search import warn_peak_exactness

    rows, t = plane.shape
    t_blk = pick_score_tile(t)
    if t_blk == 0:
        raise ValueError(f"no supported score tile divides T={t}")
    rows8 = (rows // 8) * 8
    if rows8 == rows:
        # remainder rows (below) route through the XLA stacked scorer,
        # whose own warn_peak_exactness covers the call — warning here
        # too would fire twice for one call (code-review r6)
        warn_peak_exactness(t)
    parts = []
    if rows8:
        out = _kernel_scores(rows8, t, t_blk, bool(with_cert),
                             bool(interpret), plane[:rows8])
        parts.append(out[:, :6 if with_cert else 5].T)
    if rows8 != rows:
        from .search import score_profiles_chunked

        parts.append(score_profiles_chunked(plane[rows8:], jnp,
                                            with_cert=with_cert))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
