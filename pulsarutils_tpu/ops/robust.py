"""Robust statistics and periodicity scores, self-contained.

The reference borrowed two scientific functions from third-party packages;
this framework implements them natively (SURVEY §2 note):

* ``mad`` — normalised median absolute deviation
  (capability-equivalent of ``statsmodels.robust.mad``, used at reference
  ``pulsarutils/stats.py:4,32`` and ``clean.py:24,186``);
* ``h_test`` / ``z_n_test`` — de Jager H-test and Z^2_n periodicity
  statistics over binned profiles (capability-equivalent of
  ``hendrics.efsearch.h_test``, used at reference ``clean.py:20,252-255``).

Plus the derived estimators the reference defines itself:

* ``ref_mad`` — MAD of the first difference / sqrt(2), a noise estimate
  robust to smooth baselines (reference ``stats.py:11-32``).  The
  reference's docstring promises a rolling-window minimum that the body
  never implemented; here ``window > 1`` actually does it.
* ``median_filter_1d`` — zero-padded running median matching
  ``scipy.signal.medfilt`` semantics (used for bandpass smoothing at
  reference ``stats.py:74``, ``clean.py:61``), with a jit-friendly
  stacked-sort implementation for the JAX path.
* ``digitize`` — scale data to integer counts for the H-test (reference
  ``clean.py:183-189``).

Everything takes ``xp`` (numpy or jax.numpy) and is jit-compatible under
``xp=jax.numpy``.
"""

from __future__ import annotations

import numpy as np

#: Phi^-1(3/4): scipy.stats.norm.ppf(0.75), the consistency constant that
#: makes MAD estimate sigma for Gaussian data (statsmodels' default).
MAD_SCALE = 0.6744897501960817


def mad(array, axis=None, xp=np):
    """Normalised median absolute deviation: ``median(|x - med|) / 0.6745``.

    ``axis=None`` reduces over the whole array (scalar); an integer axis
    reduces along it.  Capability-equivalent of ``statsmodels.robust.mad``
    (whose default is ``axis=0``; pass ``axis=0`` for bug-compatible
    behaviour on 2-D input).
    """
    array = xp.asarray(array)
    med = xp.median(array, axis=axis, keepdims=axis is not None)
    return xp.median(xp.abs(array - med), axis=axis) / MAD_SCALE


def ref_mad(array, window=1, xp=np):
    """Reference MAD: ``mad(diff(x)) / sqrt(2)`` — noise of the underlying
    series, insensitive to smooth trends (reference ``stats.py:11-32``).

    ``window > 1`` implements the rolling-window-minimum the reference
    documented but never wrote: the MAD is computed in non-overlapping
    windows of ``window`` samples and the minimum is returned (the quietest
    stretch estimates the true noise floor).
    """
    array = xp.asarray(array)
    d = xp.diff(array)
    if window and window > 1:
        n = d.shape[0] // int(window)
        if n >= 1:
            blocks = d[: n * int(window)].reshape(n, int(window))
            return xp.min(mad(blocks, axis=1, xp=xp)) / np.sqrt(2)
    return mad(d, xp=xp) / np.sqrt(2)


def median_filter_1d(x, size, xp=np):
    """Running median with zero padding, matching ``scipy.signal.medfilt``.

    ``size`` must be odd.  Implemented as a stacked-window sort so the same
    code jits on TPU (the windows tensor is ``(size, n)`` — tiny for the
    bandpass spectra this is applied to).
    """
    if size % 2 != 1:
        raise ValueError("median filter size must be odd")
    x = xp.asarray(x)
    n = x.shape[0]
    half = size // 2
    pad = xp.zeros(half, dtype=x.dtype)
    xpadded = xp.concatenate([pad, x, pad])
    windows = xp.stack([xpadded[i:i + n] for i in range(size)])
    return xp.median(windows, axis=0)


def z_n_test(profile, n_harmonics, xp=np):
    """Z^2_n periodicity statistic of a binned phase profile.

    ``Z^2_n = (2/N) * sum_{k=1..n} |FFT(profile)_k|^2`` with ``N`` the total
    number of counts.  Buccheri et al. 1983; the statistic the reference
    reserves slots for on its candidate record (``clean.py:43-55``).
    """
    profile = xp.asarray(profile, dtype=float)
    nbin = profile.shape[0]
    n_harmonics = int(n_harmonics)
    if n_harmonics > nbin // 2:
        # rfft only resolves nbin//2 harmonics; silently summing fewer
        # would understate the statistic the caller asked for
        raise ValueError(
            f"n_harmonics={n_harmonics} exceeds the {nbin // 2} harmonics "
            f"resolvable in a {nbin}-bin profile")
    total = profile.sum()
    spec = xp.fft.rfft(profile)
    powers = xp.abs(spec[1:n_harmonics + 1]) ** 2
    return 2.0 / total * powers.sum()


def h_test(profile, nmax=20, xp=np):
    """de Jager H-test over a binned phase profile.

    ``H = max_m (Z^2_m - 4m + 4)`` for ``1 <= m <= nmax``.  Returns
    ``(H, m_best)``.  Capability-equivalent of ``hendrics.efsearch.h_test``
    as called by the reference's diagnostic plot (``clean.py:252-255``).
    Works under jit for fixed ``nmax``.
    """
    profile = xp.asarray(profile, dtype=float)
    nmax = int(max(1, min(nmax, profile.shape[0] // 2 if profile.shape[0] >= 4 else 1)))
    total = profile.sum()
    spec = xp.fft.rfft(profile)
    powers = xp.abs(spec[1:nmax + 1]) ** 2
    z2 = 2.0 / total * xp.cumsum(powers)
    m = xp.arange(1, nmax + 1)
    h_candidates = z2 - 4.0 * m + 4.0
    best = xp.argmax(h_candidates)
    return h_candidates[best], best + 1


def h_test_batch(profiles, nmax=20, xp=np, total=None):
    """Vectorised H-test over a batch of profiles ``(nprof, nbin)``.

    Returns ``(H, m_best)`` arrays of shape ``(nprof,)``.  This is what the
    diagnostics use to score the whole dedispersed plane in one shot instead
    of the reference's per-row Python loop (``clean.py:253``).

    ``total`` overrides the ``2 / total`` normalising denominator.  The
    default (per-profile sum) is the Poisson/event-count convention; for
    profiles folded from *Gaussian* data pass ``total = T * sigma**2``
    (samples times per-sample variance) — then the Fourier powers have
    variance ``T sigma^2 / 2`` per component and ``Z^2_m ~ chi^2_{2m}``
    under the null, keeping H chi-square calibrated instead of scaling
    with the noise amplitude.
    """
    profiles = xp.asarray(profiles, dtype=float)
    nbin = profiles.shape[1]
    nmax = int(max(1, min(nmax, nbin // 2 if nbin >= 4 else 1)))
    if total is None:
        total = profiles.sum(axis=1, keepdims=True)
    else:
        total = xp.reshape(xp.asarray(total, dtype=float), (-1, 1))
    spec = xp.fft.rfft(profiles, axis=1)
    powers = xp.abs(spec[:, 1:nmax + 1]) ** 2
    z2 = 2.0 / total * xp.cumsum(powers, axis=1)
    m = xp.arange(1, nmax + 1)[None, :]
    h_candidates = z2 - 4.0 * m + 4.0
    best = xp.argmax(h_candidates, axis=1)
    h = xp.take_along_axis(h_candidates, best[:, None], axis=1)[:, 0]
    return h, best + 1


def digitize(data, xp=np, center=None, scale=None):
    """Scale data to non-negative integer counts for event statistics.

    ``rint(clip((x - median) / MAD * 3, 0, inf))`` — reference
    ``clean.py:183-189``.  Deviations from the reference, on purpose:
    integer input passes through (the reference's ``isinstance(data,
    np.int)`` check could never fire for arrays), and the MAD is a *global*
    scalar rather than statsmodels' silent per-column axis-0 reduction.

    ``center``/``scale`` override the internally computed median/MAD —
    for callers whose array carries rows that must not contaminate the
    stats (the DM-sharded plane's SPMD pad rows,
    :meth:`~pulsarutils_tpu.parallel.sharded_plane.ShardedPlane.h_curve`).
    """
    data = xp.asarray(data)
    if np.issubdtype(np.dtype(str(data.dtype)), np.integer):
        return data
    std = mad(data, xp=xp) if scale is None else scale
    med = xp.median(data) if center is None else center
    scaled = (data - med) / std * 3.0
    scaled = xp.where(scaled < 0, 0.0, scaled)
    return xp.rint(scaled).astype(xp.int32)
