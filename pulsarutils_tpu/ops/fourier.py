"""Fourier-domain dedispersion (FDD): exact fractional-sample delays.

Every other kernel in this framework (and the whole reference,
``pulsarutils/dedispersion.py:125-139``) quantises per-channel dispersion
delays to integer samples — ``rint(delay // tsamp)`` — which smears
pulses narrower than a sample and dithers arrival times by up to half a
sample per channel.  Fourier-domain dedispersion (Bassa, Pleunis &
Hessels 2022, A&C 38:100549 — PAPERS.md) applies each channel's *exact*
delay as a phase ramp on its spectrum:

    out(t) = sum_c  F^-1[ F[data_c] * exp(+2pi i f tau_c(DM)) ](t)

— a circular *advance* by the un-rounded ``tau_c`` (the positive sign
matches the integer kernels' gather convention ``out[t] = x[(t + shift)
mod T]``, module :mod:`.dedisperse`), so results line up with them
bin-for-bin.

Cost model (why this is the *precision* option, not the survey kernel):
``O(ndm * nchan * T)`` complex multiply-adds — asymptotically the direct
sweep's cost, vs the FDMT's ``O(nchan * T * log nchan)``.  The rFFT of
the input is computed once and reused by every trial.

TPU notes — two device paths:

* **uniform-grid incremental rotation** (the fast path; every standard
  plan grid is uniform in DM, and dispersion delay is *linear* in DM, so
  consecutive trials differ by one constant per-channel phase ramp):
  trials are processed in anchored superblocks — the anchor trial's
  phase comes from the exact integer-limb table, then each next trial is
  one complex multiply by the (constant) step ramp via ``lax.scan``.
  This removes the transcendental from the inner loop entirely: ``exp``
  runs once per (superblock, channel) instead of once per (trial,
  channel, bin) — a ~``superblock``-fold cut of the dominant cost.
  Phase error: anchors are exact to the 36-bit limb quantisation
  (~2.4e-5 rad at T=2^20); the 48-bit step limbs accumulate
  < ~1e-5 rad across a superblock.
* **arbitrary-grid fallback**: the phase table is built on the fly from
  an outer product (``f x tau``) and consumed immediately — XLA fuses
  exp + complex multiply + channel reduction into one pass over the
  spectrum block.
"""

from __future__ import annotations

import functools
import os
import warnings

import numpy as np

from .plan import channel_frequencies, dm_delay

#: trials per device block in the arbitrary-grid fallback (bounds the
#: phase/workspace to dm_block * chan_block * (T/2+1) complex64)
FOURIER_DM_BLOCK = 4
FOURIER_CHAN_BLOCK = 128

#: trials per anchored segment in the uniform-grid incremental path; the
#: scan's rotation carry is chan_block * (T/2+1) complex64 and each
#: superblock materialises a (superblock, T/2+1) spectrum accumulator
FOURIER_SUPERBLOCK = 64

#: HBM budget (bytes) the FDD's live-set estimate must fit in; oversized
#: blocking requests are auto-shrunk (with a warning) instead of
#: compile-OOMing the chip — the FDD analogue of the Pallas kernel's
#: VMEM_BUDGET.  Default 12 GB leaves headroom on a 16 GB chip for the
#: allocator and XLA's FFT temporaries; override via PUTPU_FDD_HBM.
FDD_HBM_BUDGET = 12 << 30


def _fdd_hbm_budget():
    raw = os.environ.get("PUTPU_FDD_HBM")
    try:
        value = int(float(raw or 0))
    except (ValueError, OverflowError):  # "8GB", "inf", ...
        value = 0
    if raw and value <= (1 << 28):
        # mirror the PUTPU_MERGE_ROW_BLOCK guard: a rejected override
        # must not silently budget for the 12 GB default on a smaller
        # chip (the compile-OOM this knob exists to prevent)
        warnings.warn(
            f"PUTPU_FDD_HBM={raw!r} ignored (needs a byte count "
            "> 2^28, e.g. 8589934592 for 8 GB); using the "
            f"{FDD_HBM_BUDGET >> 30} GB default", stacklevel=2)
    return value if value > (1 << 28) else FDD_HBM_BUDGET


def _fdd_live_bytes(nchan, t, superblock, chan_block, cross=False):
    """Conservative live-set estimate of an FDD program.

    Counts the resident spectrum (complex64, the irreducible term), the
    float32 input, the per-channel-block phasors (anchor, step, carry,
    spectrum slice), the superblock accumulators, and a 2x allowance on
    the superblock-sized irfft for XLA's FFT temporaries.  ``cross=True``
    adds the arbitrary-grid fallback's dominant
    ``dm_block x chan_block x nbin`` complex phase tensor (the
    uniform-grid kernel never materialises that cross term).
    """
    nbin = t // 2 + 1
    nchan_p = -(-nchan // chan_block) * chan_block
    spec = 8 * nchan_p * nbin
    data = 4 * nchan_p * t
    phasors = 8 * nbin * 4 * chan_block
    acc = 8 * nbin * 3 * superblock
    fft = 2 * 4 * superblock * t
    phase_cross = 2 * 8 * superblock * chan_block * nbin if cross else 0
    return spec + data + phasors + acc + fft + phase_cross


def _auto_fdd_blocks(nchan, t, superblock, chan_block, cross=False):
    """Shrink (superblock, chan_block) until the estimate fits the HBM
    budget; returns the (possibly reduced) pair."""
    budget = _fdd_hbm_budget()
    req = (superblock, chan_block)
    min_s = 1 if cross else 8
    while (_fdd_live_bytes(nchan, t, superblock, chan_block, cross)
           > budget and (superblock > min_s or chan_block > 32)):
        # shrink whichever block contributes more shrinkable bytes
        # (uniform path: superblock terms ~ 20*S*t vs chan terms
        # ~ 16*C*t; with the cross term both shrink it equally, so the
        # same dominance rule still picks the bigger contributor)
        if chan_block <= 32 or (superblock > min_s
                                and 20 * superblock >= 16 * chan_block):
            superblock //= 2
        else:
            chan_block //= 2
    if (superblock, chan_block) != req:
        warnings.warn(
            f"FDD blocking {req} exceeds the HBM budget "
            f"({_fdd_live_bytes(nchan, t, *req, cross) >> 30} GB est. > "
            f"{budget >> 30} GB); shrunk to "
            f"({superblock}, {chan_block}) — set PUTPU_FDD_HBM to raise",
            stacklevel=3)
    return superblock, chan_block


def fractional_delays(trial_dms, nchan, start_freq, bandwidth):
    """Un-rounded per-channel delays (seconds) for each trial DM.

    Same band-centre reference convention as the integer path
    (``dedispersion_shifts``, reference ``dedispersion.py:128-135``) so
    the two kernels dedisperse to the same epoch: the delay of channel
    ``c`` is relative to the band centre frequency.
    """
    trial_dms = np.atleast_1d(np.asarray(trial_dms, dtype=np.float64))
    freqs = channel_frequencies(nchan, start_freq, bandwidth)
    center = start_freq + bandwidth / 2.0
    # (ndm, nchan): positive = channel lags the band centre
    return (dm_delay(trial_dms[:, None], freqs[None, :])
            - dm_delay(trial_dms, center)[:, None])


def _dedisperse_fourier_numpy(data, delays, sample_time):
    data = np.asarray(data, dtype=np.float64)
    nchan, t = data.shape
    spec = np.fft.rfft(data, axis=1)
    f = np.fft.rfftfreq(t, d=sample_time)
    out = np.empty((delays.shape[0], t))
    for d in range(delays.shape[0]):
        phase = np.exp(2j * np.pi * f[None, :] * delays[d][:, None])
        out[d] = np.fft.irfft((spec * phase).sum(axis=0), n=t)
    return out


@functools.lru_cache(maxsize=16)
def _jitted_fourier(t, dm_block, chan_block, with_scores, with_plane=True):
    """One compiled FDD program.

    Memory: when the plane is not requested (``with_scores`` and not
    ``with_plane``), each dm block is scored inside the loop and only the
    ``(5, ndm)`` score array accumulates — the live set is one
    ``dm_block x T`` block regardless of trial count, matching the other
    kernels' bounded-plane behaviour.
    """
    import jax
    import jax.numpy as jnp

    def one_block(spec_b, limbs_b, k, kf):
        # spec_b (C_b, F) complex; limbs_b (3, D_b, C_b) int32 12-bit
        # limbs of the per-(trial, channel) phase slope (see
        # _phase_limbs).  The phase at rfft bin k is k * M / 2^36 cycles
        # with M = M1*2^24 + M2*2^12 + M3; each k*Mi fits the wrapping
        # int32 product's congruence class, so the phase error is bounded
        # by the 36-bit quantisation of the slope (~2.4e-5 rad at
        # T = 2^20) — float32 `f * tau` would be off by ~0.1 rad at the
        # 1M-sample sizes this kernel exists to serve.
        m1, m2, m3 = (limbs_b[i][:, :, None] for i in range(3))
        th = (((k * m1) & 0xFFF).astype(jnp.float32) / (1 << 12)
              + ((k * m2) & 0xFFFFFF).astype(jnp.float32) / (1 << 24)
              + kf * m3.astype(jnp.float32) / np.float32(1 << 36))
        phase = jnp.exp((2j * jnp.pi) * th)
        return (spec_b[None, :, :] * phase).sum(axis=1)  # (D_b, F)

    keep_plane = with_plane or not with_scores

    @jax.jit
    def run(data, limbs):
        from .search import score_profiles_stacked

        nbin = t // 2 + 1
        k = jnp.arange(nbin, dtype=jnp.int32)[None, None, :]
        kf = k.astype(jnp.float32)
        nchan = data.shape[0]
        ndm = limbs.shape[1]
        nc = -(-nchan // chan_block)
        nd = -(-ndm // dm_block)
        data_p = jnp.pad(data, ((0, nc * chan_block - nchan), (0, 0)))
        spec = _blocked_rfft(data_p, chan_block, nbin)
        limbs_p = jnp.pad(limbs, ((0, 0), (0, nd * dm_block - ndm),
                                  (0, nc * chan_block - nchan)))

        def series_block(i):
            dl = jax.lax.dynamic_slice_in_dim(limbs_p, i * dm_block,
                                              dm_block, axis=1)

            def chan_step(j, acc_spec):
                sp = jax.lax.dynamic_slice_in_dim(spec, j * chan_block,
                                                  chan_block, axis=0)
                db = jax.lax.dynamic_slice_in_dim(dl, j * chan_block,
                                                  chan_block, axis=2)
                return acc_spec + one_block(sp, db, k, kf)

            out_spec = jax.lax.fori_loop(
                0, nc, chan_step,
                jnp.zeros((dm_block, t // 2 + 1), jnp.complex64))
            return jnp.fft.irfft(out_spec, n=t, axis=1).astype(jnp.float32)

        def dm_step(i, carry):
            plane_acc, score_acc = carry
            series = series_block(i)
            if keep_plane:
                plane_acc = jax.lax.dynamic_update_slice_in_dim(
                    plane_acc, series, i * dm_block, axis=0)
            if with_scores:
                score_acc = jax.lax.dynamic_update_slice_in_dim(
                    score_acc, score_profiles_stacked(series, xp=jnp),
                    i * dm_block, axis=1)
            return plane_acc, score_acc

        plane0 = jnp.zeros((nd * dm_block if keep_plane else 1, t),
                           jnp.float32)
        score0 = jnp.zeros((5, nd * dm_block if with_scores else 1),
                           jnp.float32)
        plane, scores = jax.lax.fori_loop(0, nd, dm_step, (plane0, score0))
        plane = plane[:ndm]
        scores = scores[:, :ndm]
        if not with_scores:
            return plane
        return (scores, plane) if with_plane else scores

    return run


def _blocked_rfft(data, chan_block, nbin):
    """rFFT of ``data`` row-blocks via ``fori_loop``.

    XLA's TPU FFT lowering materialises convolution temps proportional
    to the *batch* size — a single rfft over (1024, 1M) data wants
    ~20 GB of HLO temps and fails to compile on a 16 GB chip.  Rows are
    independent, so filling the spectrum ``chan_block`` rows at a time
    is bit-identical and caps the temps at ``chan_block/nchan`` of that.
    """
    import jax
    import jax.numpy as jnp

    nchan_p, t = data.shape
    nc = nchan_p // chan_block

    def fill(j, spec):
        sp = jnp.fft.rfft(
            jax.lax.dynamic_slice_in_dim(data, j * chan_block, chan_block,
                                         axis=0), axis=1)
        return jax.lax.dynamic_update_slice_in_dim(spec, sp, j * chan_block,
                                                   axis=0)

    return jax.lax.fori_loop(
        0, nc, fill, jnp.zeros((nchan_p, nbin), jnp.complex64))


def _uniform_spacing(trial_dms):
    """The constant DM step of a uniform grid, or ``None`` if non-uniform.

    Every standard plan grid (one trial per integer band-delay sample,
    ``dedispersion_plan``) is uniform: DM is linear in the delay index.
    """
    dms = np.asarray(trial_dms, dtype=np.float64)
    if dms.size < 2:
        return 0.0
    d = np.diff(dms)
    step = d.mean()
    scale = max(abs(step), abs(dms).max() * 1e-12, 1e-300)
    if np.abs(d - step).max() <= 1e-8 * scale:
        return float(step)
    return None


def _step_limbs(delays_step, sample_time, t):
    """48-bit phase-slope limbs for the per-trial increment ramp.

    Same congruence scheme as :func:`_phase_limbs` but quantised to 48
    bits (four 12-bit limbs): the step's phase error is *accumulated*
    over a superblock of trials, so it gets 12 more bits than the
    anchors (64 * 2pi * (T/2) * 2^-49 ~ 1e-5 rad at T = 2^20).
    """
    a = np.asarray(delays_step, dtype=np.float64) / (sample_time * t)
    m = np.rint((a % 1.0) * (1 << 48)).astype(np.int64) & ((1 << 48) - 1)
    return np.stack([(m >> 36).astype(np.int32),
                     ((m >> 24) & 0xFFF).astype(np.int32),
                     ((m >> 12) & 0xFFF).astype(np.int32),
                     (m & 0xFFF).astype(np.int32)])


@functools.lru_cache(maxsize=16)
def _jitted_fourier_uniform(t, superblock, chan_block, with_scores,
                            with_plane=True, use_pallas=False,
                            interpret=False):
    """One compiled uniform-grid FDD program (incremental rotation).

    Inputs: ``data (nchan, T)``, ``anchor_limbs (3, nblocks, nchan)`` —
    exact phase limbs of each superblock's first trial — and
    ``step_limbs (4, nchan)`` — 48-bit limbs of the constant per-trial
    increment ramp.  Trials covered: ``nblocks * superblock`` (callers
    pad the grid and slice).

    ``use_pallas`` routes the rotate-accumulate recurrence through the
    VMEM-resident kernel (:mod:`.fourier_pallas`): same anchors, same
    step ramp, same recurrence, but the per-trial rotation state never
    round-trips HBM — measured 18 s -> ~2.9 s at the canonical
    513-trial 1024 x 1M sweep (the ``lax.scan`` form carries ~1 TB of
    rotation state through HBM and runs at ~6% of the VPU).  Float sum
    order over channels differs (per-channel accumulation instead of
    the scan's per-chan-block contribution sums), so results agree to
    float32 tolerance, not bitwise.
    """
    import jax
    import jax.numpy as jnp

    nbin = t // 2 + 1
    keep_plane = with_plane or not with_scores

    def limb_phase(limbs, k, kf, nlimb):
        # limbs (nlimb, C) int32 -> (C, nbin) complex64 unit phasor.
        # k * m1 / m2 wrap in int32: int32 wrap is mod 2^32, a multiple
        # of each masked modulus, so the congruence classes are exact.
        m = [limbs[i][:, None] for i in range(nlimb)]
        th = ((k * m[0]) & 0xFFF).astype(jnp.float32) / (1 << 12)
        th = th + ((k * m[1]) & 0xFFFFFF).astype(jnp.float32) / (1 << 24)
        th = th + kf * m[2].astype(jnp.float32) / np.float32(1 << 36)
        if nlimb > 3:
            # k * m4 / 2^48 < 2^-16: no wrap possible, float32 is ample
            th = th + kf * m[3].astype(jnp.float32) / np.float32(2.0 ** 48)
        return jnp.exp((2j * jnp.pi) * th)

    @jax.jit
    def run(data, anchor_limbs, step_limbs):
        from .search import score_profiles_stacked

        nchan = data.shape[0]
        nblocks = anchor_limbs.shape[1]
        nc = -(-nchan // chan_block)
        data_p = jnp.pad(data, ((0, nc * chan_block - nchan), (0, 0)))
        spec = _blocked_rfft(data_p, chan_block, nbin)
        anchor_p = jnp.pad(anchor_limbs,
                           ((0, 0), (0, 0), (0, nc * chan_block - nchan)))
        step_p = jnp.pad(step_limbs, ((0, 0), (0, nc * chan_block - nchan)))
        k = jnp.arange(nbin, dtype=jnp.int32)[None, :]
        kf = k.astype(jnp.float32)
        ndm_p = nblocks * superblock

        def super_step(i, carry):
            plane_acc, score_acc = carry

            def chan_step(j, acc):
                sp = jax.lax.dynamic_slice_in_dim(spec, j * chan_block,
                                                  chan_block, axis=0)
                al = jax.lax.dynamic_slice_in_dim(
                    anchor_p[:, i], j * chan_block, chan_block, axis=1)
                sl = jax.lax.dynamic_slice_in_dim(step_p, j * chan_block,
                                                  chan_block, axis=1)
                rot0 = limb_phase(al, k, kf, 3)
                step = limb_phase(sl, k, kf, 4)

                if use_pallas:
                    from .fourier_pallas import fdd_superblock_spectra

                    return acc + fdd_superblock_spectra(
                        sp * rot0, step, superblock, interpret=interpret)

                def trial(rot, _):
                    # rot IS trial d's total phasor; emit its channel
                    # sum, advance to trial d+1 by the constant ramp
                    return rot * step, (sp * rot).sum(axis=0)

                _, contribs = jax.lax.scan(trial, rot0, None,
                                           length=superblock)
                return acc + contribs  # (superblock, nbin)

            out_spec = jax.lax.fori_loop(
                0, nc, chan_step,
                jnp.zeros((superblock, nbin), jnp.complex64))
            series = jnp.fft.irfft(out_spec, n=t, axis=1).astype(jnp.float32)
            if keep_plane:
                plane_acc = jax.lax.dynamic_update_slice_in_dim(
                    plane_acc, series, i * superblock, axis=0)
            if with_scores:
                score_acc = jax.lax.dynamic_update_slice_in_dim(
                    score_acc, score_profiles_stacked(series, xp=jnp),
                    i * superblock, axis=1)
            return plane_acc, score_acc

        plane0 = jnp.zeros((ndm_p if keep_plane else 1, t), jnp.float32)
        score0 = jnp.zeros((5, ndm_p if with_scores else 1), jnp.float32)
        plane, scores = jax.lax.fori_loop(0, nblocks, super_step,
                                          (plane0, score0))
        if not with_scores:
            return plane
        return (scores, plane) if with_plane else scores

    return run


def _uniform_fourier_inputs(trial_dms, dm_step, nchan, start_freq,
                            bandwidth, sample_time, t, superblock):
    """Host-side limb tables for the uniform-grid kernel.

    Returns ``(anchor_limbs, step_limbs, ndm)``; the grid is extended to
    a whole number of superblocks (extra trials are sliced off).
    """
    dms = np.asarray(trial_dms, dtype=np.float64)
    ndm = dms.size
    nblocks = -(-ndm // superblock)
    anchors = dms[0] + dm_step * superblock * np.arange(nblocks)
    anchor_delays = fractional_delays(anchors, nchan, start_freq, bandwidth)
    anchor_limbs = _phase_limbs(anchor_delays, sample_time, t)
    # dispersion delay is linear in DM: the step ramp is dm_step times
    # the unit-DM delay curve
    step_delays = dm_step * fractional_delays(
        np.array([1.0]), nchan, start_freq, bandwidth)[0]
    step_limbs = _step_limbs(step_delays, sample_time, t)
    return anchor_limbs, step_limbs, ndm


def _phase_limbs(delays, sample_time, t):
    """Host-side exact phase-slope limbs for the device kernel.

    The phase at rfft bin ``k`` is ``k * A mod 1`` cycles with
    ``A = tau / (tsamp * T)``.  ``A mod 1`` is quantised to 36 bits
    (float64 is exact here) and split into three 12-bit limbs so the
    device can form ``k * A mod 1`` with wrapping int32 products —
    phase error <= 2pi * (T/2) * 2^-37 cycles-rounding ~ 2.4e-5 rad at
    T = 2^20 (it grows linearly with T: ~1.5e-3 rad by T = 2^26).

    Returns int32 ``(3, ndm, nchan)``.
    """
    a = np.asarray(delays, dtype=np.float64) / (sample_time * t)
    m = np.rint((a % 1.0) * (1 << 36)).astype(np.int64) & ((1 << 36) - 1)
    return np.stack([(m >> 24).astype(np.int32),
                     ((m >> 12) & 0xFFF).astype(np.int32),
                     (m & 0xFFF).astype(np.int32)])


def _fourier_device_run(data, trial_dms, start_freq, bandwidth, sample_time,
                        with_scores, with_plane, dm_block, chan_block):
    """Shared device dispatch: uniform-grid incremental kernel when the
    trial grid allows it, arbitrary-grid exp fallback otherwise."""
    import jax.numpy as jnp

    import jax

    nchan, t = data.shape[0], data.shape[1]
    chan_block = chan_block or FOURIER_CHAN_BLOCK
    dm_step = _uniform_spacing(trial_dms)
    if dm_step is not None:
        # the VMEM-resident rotation kernel: default on TPU;
        # PUTPU_FDD_PALLAS=0|1 overrides (1 off-TPU = interpret mode,
        # the CPU test path); garbage values warn via the shared parser
        from ..utils.knobs import tristate_env

        knob = tristate_env("PUTPU_FDD_PALLAS")
        on_tpu = jax.default_backend() == "tpu"
        use_pallas = on_tpu if knob is None else knob
        superblock = dm_block or FOURIER_SUPERBLOCK
        # clamp to the trial count BEFORE the budget check: a 512-block
        # request over 8 trials would otherwise warn and shrink
        # chan_block for a program that was never going to be built
        superblock = max(1, min(superblock, len(np.atleast_1d(trial_dms))))
        superblock, chan_block = _auto_fdd_blocks(nchan, t, superblock,
                                                  chan_block)
        if use_pallas:
            from .fourier_pallas import FDD_L, FDD_N_UNROLL

            # the kernel's revisited output block pair is
            # 2 * superblock * 8 * FDD_L * 4 bytes of VMEM (plus ~2 MB
            # of input staging) — clamp so it stays well inside the
            # ~16 MB chip budget (the scan form had no such ceiling;
            # dm_block=512 would otherwise compile a 32 MB block and
            # fail where the old path worked — code-review r4)
            vmem_cap = (10 << 20) // (2 * 8 * FDD_L * 4)
            superblock = min(superblock, max(FDD_N_UNROLL, vmem_cap))
            # the kernel's trial loop is unrolled in FDD_N_UNROLL steps
            superblock = -(-superblock // FDD_N_UNROLL) * FDD_N_UNROLL
        anchor_limbs, step_limbs, ndm = _uniform_fourier_inputs(
            trial_dms, dm_step, nchan, start_freq, bandwidth, sample_time,
            t, superblock)
        run = _jitted_fourier_uniform(t, superblock, chan_block,
                                      with_scores, with_plane,
                                      use_pallas=use_pallas,
                                      interpret=not on_tpu)
        out = run(jnp.asarray(data, jnp.float32),
                  jnp.asarray(anchor_limbs), jnp.asarray(step_limbs))
    else:
        delays = fractional_delays(trial_dms, nchan, start_freq, bandwidth)
        ndm = delays.shape[0]
        dm_block, chan_block = _auto_fdd_blocks(
            nchan, t, min(dm_block or FOURIER_DM_BLOCK, max(1, ndm)),
            chan_block, cross=True)
        run = _jitted_fourier(t, dm_block, chan_block,
                              with_scores, with_plane)
        out = run(jnp.asarray(data, jnp.float32),
                  jnp.asarray(_phase_limbs(delays, sample_time, t)))
    # slice off superblock/dm_block padding
    if with_scores and with_plane:
        return out[0][:, :ndm], out[1][:ndm]
    if with_scores:
        return out[:, :ndm], None
    return out[:ndm], None


def dedisperse_fourier(data, trial_dms, start_freq, bandwidth, sample_time,
                       xp=np, dm_block=None, chan_block=None):
    """Dedisperse ``data`` at exact (fractional-sample) delays per trial.

    Returns the ``(ndm, T)`` dedispersed plane.  ``xp=np`` is the float64
    reference implementation; ``xp=jax.numpy`` runs blocked on device
    (``dm_block`` is the trial superblock of the uniform-grid kernel, or
    the phase-table block of the arbitrary-grid fallback).
    """
    if xp is np:
        delays = fractional_delays(trial_dms, data.shape[0], start_freq,
                                   bandwidth)
        return _dedisperse_fourier_numpy(data, delays, sample_time)
    plane, _ = _fourier_device_run(data, trial_dms, start_freq, bandwidth,
                                   sample_time, with_scores=False,
                                   with_plane=True, dm_block=dm_block,
                                   chan_block=chan_block)
    return plane


def search_fourier(data, trial_dms, start_freq, bandwidth, sample_time,
                   capture_plane=False, dm_block=None, chan_block=None):
    """FDD sweep + standard boxcar scoring (jax path; used by
    ``dedispersion_search(kernel="fourier")``)."""
    from .search import unstack_scores

    stacked, plane = _fourier_device_run(
        data, trial_dms, start_freq, bandwidth, sample_time,
        with_scores=True, with_plane=bool(capture_plane),
        dm_block=dm_block, chan_block=chan_block)
    return unstack_scores(stacked) + (plane,)
