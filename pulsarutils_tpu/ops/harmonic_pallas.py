"""One-pass Pallas kernel for the periodicity hot loop (ISSUE 17).

The XLA chain (:func:`..ops.periodicity.normalize_power` ->
:func:`..ops.periodicity.score_normalized_power`) is the memory-bound
half of the periodicity search (the PulsarX point, arxiv 2309.02544):
the median-normalise materialises a normalised copy of the spectrum and
every harmonic depth re-reads it through a strided gather.  This kernel
fuses the whole chain for one 8-row block of spectra: the raw power rows
are read into VMEM ONCE, median-normalised in place, and the incremental
harmonic stack accumulates VMEM-resident partials in the accumulation
dtype the active :mod:`..precision` policy declares (plain f32,
TwoSum-compensated f32 pairs, or bf16 operands with an f32 accumulator).
Only the per-depth (peak value, peak bin) pairs leave the kernel — the
host-side wrapper reconstructs the false-alarm/sigma chain with the
IDENTICAL XLA ops.  Discrete fields (peak bin, frequency bin, harmonic
depth) match the XLA scorer exactly: the harmonic addends are generated
in the same order with the same values (the stride-``j`` slice
``norm[:, ::j]`` zero-padded to ``nbins`` IS ``_add_harmonic``'s
gather).  Score floats agree to within one f32 ulp — XLA may fuse the
``p / (med / ln2)`` normalise differently across the two programs
(reciprocal-multiply vs true divide), a data-dependent last-bit
difference that uniformly scales a row and does not move an argmax
(the equivalence harness gates the razor-edge tie case anyway) — so
the identity tests pin discrete fields exactly and scores at tight
``allclose`` tolerance, the same contract the autotuner harness gates.

Like :mod:`.pallas_dedisperse`, the kernel is developed and tested in
interpret mode on CPU (``tests/test_harmonic_pallas.py`` pins identity
on host, under jit, and on the (4,2)/(2,4) CPU meshes); on TPU it runs
compiled.  The in-kernel ``jnp.median`` (a per-row sort of the spectrum)
is the part most likely to need a Mosaic workaround on real hardware —
it is deliberately kept at the top of the kernel so a TPU-side rewrite
(bucketed histogram median) swaps in without touching the stack.

Registered as a scoring candidate through
:func:`~pulsarutils_tpu.tuning.autotune.resolve_harmonic_kernel`
(``kernel="auto"`` in ``_spectral_chunk``): a Pallas win is only ever
cached after the identity harness passes — discrete top-cell fields
exact, scores within the declared tolerance.
"""

from __future__ import annotations

import functools

import numpy as np

from .periodicity import (HARMONIC_SUMS, _LN2, power_sf_log, power_spectrum,
                          sf_log_to_sigma)

#: rows per grid cell (the f32 sublane width — one VMEM tile of rows)
_ROW_BLK = 8


def _pallas_modules():
    from jax.experimental import pallas as pl

    return pl


@functools.lru_cache(maxsize=64)
def _build_harmonic_kernel(rows_p, nbins, depths, lo, hi, policy, interpret):
    """Compile (or interpret) the fused normalize+stack kernel.

    Static key: padded row count, spectrum width, harmonic depth
    schedule, band ``[lo, hi)``, precision policy name and interpret
    flag.  Outputs per 8-row block: ``(8, 128)`` f32 peak values and
    ``(8, 128)`` int32 peak bins, lane ``k`` = depth ``depths[k]``.
    """
    import jax
    import jax.numpy as jnp

    pl = _pallas_modules()

    compensated = policy in ("f32_compensated", "split_f32")
    bf16 = policy == "bf16_operand_f32_accum"

    def kernel(p_ref, val_ref, idx_ref):
        p = p_ref[...]  # (8, nbins) raw power, DC bin already zeroed
        # normalize_power, verbatim: median over bins [1:], ln2 scaling
        med = jnp.median(p[:, 1:], axis=-1, keepdims=True)
        norm = p / jnp.where(med > 0, med / _LN2, 1.0)
        # the bf16_operand_f32_accum strategy's cast, inside the traced
        # kernel body where the host-side cast_operand seam cannot reach
        gath = (norm.astype(jnp.bfloat16)  # putpu-lint: disable=bf16-cast — policy-gated (bf16_operand_f32_accum)
                if bf16 else norm)

        col = jax.lax.broadcasted_iota(jnp.int32, (_ROW_BLK, nbins), 1)
        band = ((col >= lo) & (col < hi)).astype(norm.dtype)
        lane = jax.lax.broadcasted_iota(jnp.int32, (_ROW_BLK, 128), 1)

        acc = jnp.zeros_like(norm)
        comp = jnp.zeros_like(norm) if compensated else None
        vals = jnp.zeros((_ROW_BLK, 128), jnp.float32)
        idxs = jnp.zeros((_ROW_BLK, 128), jnp.int32)

        depth = 0
        for k, h in enumerate(depths):
            for j in range(depth + 1, h + 1):
                # harmonic j of fundamental i is bin i*j: the stride-j
                # slice zero-padded to nbins — same addends, same
                # order, as _add_harmonic's gather
                g = gath[:, ::j]
                v = jnp.pad(g.astype(jnp.float32),
                            ((0, 0), (0, nbins - g.shape[1])))
                if compensated:
                    s = acc + v
                    bp = s - acc
                    comp = comp + ((acc - (s - bp)) + (v - bp))
                    acc = s
                else:
                    acc = acc + v
            depth = h
            hsum = (acc + comp if compensated else acc) * band
            peak = jnp.argmax(hsum, axis=-1)
            pval = jnp.take_along_axis(hsum, peak[:, None], axis=-1)[:, 0]
            vals = jnp.where(lane == k, pval[:, None], vals)
            idxs = jnp.where(lane == k, peak.astype(jnp.int32)[:, None],
                             idxs)
        val_ref[...] = vals
        idx_ref[...] = idxs

    n_rb = rows_p // _ROW_BLK
    return pl.pallas_call(
        kernel,
        grid=(n_rb,),
        in_specs=[pl.BlockSpec((_ROW_BLK, nbins), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((_ROW_BLK, 128), lambda i: (i, 0)),
                   pl.BlockSpec((_ROW_BLK, 128), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows_p, 128), jnp.float32),
                   jax.ShapeDtypeStruct((rows_p, 128), jnp.int32)],
        interpret=bool(interpret),
    )


def score_power_pallas(power, nsamples, tsamp, max_harmonics=16, fmin=None,
                       fmax=None, policy=None, interpret=None):
    """Pallas analogue of ``normalize_power`` -> ``score_normalized_power``.

    ``power`` is the RAW ``(rows, nbins)`` power spectrum (DC zeroed,
    un-normalised — normalisation happens inside the kernel, one VMEM
    pass).  Returns the same dict as
    :func:`..ops.periodicity.score_normalized_power`: ``freq, power,
    nharm, log_sf, sigma`` per row.  ``interpret=None`` auto-selects
    interpret mode off-TPU, like :mod:`.pallas_dedisperse`.
    """
    import jax
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    power = jnp.asarray(power, dtype=jnp.float32)
    rows, nbins = power.shape
    t = int(nsamples)

    # band edges: verbatim score_normalized_power
    lo = 1 if fmin is None else max(1, int(np.ceil(fmin * t * tsamp)))
    hi = (nbins if fmax is None
          else min(nbins, int(fmax * t * tsamp) + 1))  # putpu-lint: disable=device-trip — host band-edge scalars
    depths = tuple(h for h in HARMONIC_SUMS if h <= int(max_harmonics))

    name = "f32"
    if policy not in (None, "f32"):
        from ..precision import policy_name

        name = policy_name(policy)

    rows_p = -(-rows // _ROW_BLK) * _ROW_BLK
    if rows_p != rows:
        # benign padding rows: all-ones spectra (positive median, so
        # the normalise never divides by zero); sliced off below
        pad = jnp.ones((rows_p - rows, nbins), jnp.float32)
        power_p = jnp.concatenate([power, pad], axis=0)
    else:
        power_p = power
    run = _build_harmonic_kernel(rows_p, nbins, depths, lo, hi, name,
                                 bool(interpret))
    vals, idxs = run(power_p)
    vals, idxs = vals[:rows], idxs[:rows]

    # best-depth selection with the IDENTICAL XLA ops (bit-parity with
    # score_normalized_power's loop under the same policy)
    freqs = jnp.arange(nbins) / (t * tsamp)
    best_logsf = jnp.full((rows,), jnp.inf)
    best_freq = jnp.zeros((rows,))
    best_power = jnp.zeros((rows,))
    best_nharm = jnp.zeros((rows,), dtype=jnp.int32)
    for k, h in enumerate(depths):
        pval = vals[:, k]
        peak = idxs[:, k]
        log_sf = power_sf_log(pval, nsum=h, xp=jnp)
        better = log_sf < best_logsf
        best_logsf = jnp.where(better, log_sf, best_logsf)
        best_freq = jnp.where(better, jnp.take(freqs, peak), best_freq)
        best_power = jnp.where(better, pval, best_power)
        best_nharm = jnp.where(better, h, best_nharm)
    return {
        "freq": best_freq,
        "power": best_power,
        "nharm": best_nharm,
        "log_sf": best_logsf,
        "sigma": sf_log_to_sigma(best_logsf, xp=jnp),
    }


def spectral_search_pallas(plane, tsamp, max_harmonics=16, fmin=None,
                           fmax=None, policy=None, interpret=None):
    """Pallas counterpart of :func:`..ops.periodicity.spectral_search`.

    The batched rFFT stays on XLA (it is MXU/FFT-library territory);
    the normalise+harmonic-stack scoring runs in the fused kernel.
    """
    import jax.numpy as jnp

    plane = jnp.asarray(plane, dtype=jnp.float32)
    t = plane.shape[-1]
    power = power_spectrum(plane, xp=jnp)
    return score_power_pallas(power, t, tsamp,
                              max_harmonics=max_harmonics, fmin=fmin,
                              fmax=fmax, policy=policy,
                              interpret=interpret)
