"""Fourier-domain acceleration/jerk response templates (host float64).

A constant line-of-sight acceleration ``a`` drifts a pulsar's apparent
spin frequency across the observation, smearing its power over ``z =
f a T_obs^2 / c`` Fourier bins; a jerk ``j`` adds a quadratic drift of
``w = f j T_obs^3 / c`` bins.  PRESTO-lineage Fourier-domain search
(PulsarX, arxiv 2309.02544) recovers the smeared power with ONE FFT per
DM row plus a short complex correlation against precomputed *response
templates* — the Fourier transform of a unit-amplitude linear/quadratic
chirp.  This module builds those templates on host in float64 (the
anchored-fold rule: template phases wrap thousands of cycles and must
not be computed in float32), with no dependency beyond numpy — the
Fresnel integrals the closed form needs are implemented here (power
series + asymptotic expansion) because scipy is not a dependency of
this repo.

Math.  For the normalised chirp ``s(u) = exp(2 pi i (z u^2/2 + w
u^3/6))`` on ``u in [0, 1]`` the response at Fourier-bin offset ``q``
from the starting frequency is::

    A_{z,w}(q) = integral_0^1 exp(2 pi i (z u^2/2 + w u^3/6 - q u)) du

* ``w = 0``: completing the square gives the Fresnel closed form

  ``A_z(q) = exp(-i pi q^2/z) / sqrt(2 z) * [(C(y2)-C(y1)) + i (S(y2)-S(y1))]``

  with ``y1 = -q sqrt(2/z)``, ``y2 = sqrt(2 z) (1 - q/z)`` and the
  ``z < 0`` half from conjugate symmetry ``A_{-z}(q) = conj(A_z(-q))``.
  Below ``|z| < Z_SMALL`` the prefactor ``1/sqrt(2 z)`` and the Fresnel
  difference cancel catastrophically, so a first-order series branch
  ``A ~ A_0(q) + i pi z M_1(q)`` takes over (``A_0(q) = exp(-i pi q)
  sinc(q)``, ``M_1(q) = integral_0^1 u^2 exp(-2 pi i q u) du``).
* ``w != 0``: no Fresnel closed form exists; the template is the FFT of
  the finely-sampled chirp (the FFT's bin spacing at ``M`` samples of
  ``u in [0,1)`` is exactly one Fourier bin of the real series, so
  integer-``q`` samples read straight out of the transform).  The
  closed form is kept for every ``w = 0`` entry and property-tested
  against the numerical path at the seam.

Templates are stored *centred*: entry ``i`` holds the matched filter
``conj(A(c_i + j))`` for ``j in [-h, h]`` with ``c_i = rint(z_i/2 +
w_i/6)`` the drift centroid, unit-normalised so a white-noise spectrum
correlated with any entry keeps unit variance (the median
normalisation downstream then behaves identically for every bin).
"""

from __future__ import annotations

import functools
import warnings

import numpy as np

__all__ = ["Z_SMALL", "fresnel", "z_response", "zw_response",
           "response_bank", "response_bank_pairs", "bank_for_trials"]

#: below this |z| the Fresnel closed form loses ~half its digits to
#: cancellation; the first-order series branch (error O(z^2) ~ 1e-6 at
#: the boundary) takes over
Z_SMALL = 1e-3

#: speed of light (m/s) — must match ``periodicity.accel.C_M_S`` (the
#: ops layer cannot import upward; pinned by a test instead)
_C_M_S = 299792458.0

#: series/asymptotic split for the Fresnel integrals: at |x| = 3.2 the
#: power series still holds ~10 digits (its largest term is ~1e6) and
#: the asymptotic tail bottoms out near 1e-8 — ample for templates
#: that are themselves ~1e-4 from the sampled-chirp path
_FRESNEL_SPLIT = 3.2


def fresnel(x):
    """Fresnel integrals ``C(x), S(x)`` (``integral_0^x cos/sin(pi t^2/2)``).

    Vectorised float64: Maclaurin series for ``|x| <= 2.5``, the
    integration-by-parts asymptotic expansion of the complementary
    integral beyond (truncated at its smallest term per element).
    Both integrals are odd; accuracy ~1e-9 absolute everywhere.
    """
    x = np.asarray(x, dtype=np.float64)
    ax = np.abs(x)
    out = np.where(ax <= _FRESNEL_SPLIT, _fresnel_series(
        np.minimum(ax, _FRESNEL_SPLIT)), _fresnel_asymptotic(
        np.maximum(ax, _FRESNEL_SPLIT)))
    out = np.sign(x) * out
    return out.real, out.imag


def _fresnel_series(x):
    """``C + iS`` by the Maclaurin series of ``integral_0^x e^{i pi t^2/2}``."""
    x = np.asarray(x, dtype=np.float64)
    x2 = (0.5j * np.pi) * x * x
    term = x.astype(np.complex128)          # n = 0 term: x
    total = term.copy()
    for n in range(70):
        term = term * x2 / (n + 1.0) * ((2 * n + 1.0) / (2 * n + 3.0))
        total = total + term
    return total


def _fresnel_asymptotic(x):
    """``C + iS`` for large positive ``x`` via the complementary integral
    ``E(x) = integral_x^inf e^{i pi t^2/2} dt = e^{i pi x^2/2} sum c_m``
    with ``c_0 = i/(pi x)`` and ``c_{m+1} = -i (2m+1)/(pi x^2) c_m``
    (integration by parts); the divergent tail is truncated at the
    smallest term, which at ``x = 2.5`` is ~1e-9."""
    x = np.asarray(x, dtype=np.float64)
    c = np.asarray(1j / (np.pi * x))
    total = c.copy()
    prev = np.abs(c)
    shrinking = np.ones(np.shape(x), dtype=bool)
    for m in range(18):
        c = c * (-1j) * (2 * m + 1.0) / (np.pi * x * x)
        mag = np.abs(c)
        shrinking = shrinking & (mag < prev)
        total = np.where(shrinking, total + c, total)
        prev = mag
    # phase of e^{i pi x^2/2} in float64: x <= ~1e3 here, x^2/2 exact
    # enough (templates never reach the regime where it is not)
    e = np.exp(0.5j * np.pi * x * x)
    return (0.5 + 0.5j) - e * total


def _m1_integral(q):
    """``M_1(q) = integral_0^1 u^2 exp(-2 pi i q u) du`` (float64).

    Closed form ``(e^a (a^2 - 2a + 2) - 2) / a^3`` with ``a = -2 pi i
    q``; the small-``|a|`` limit (1/3) is taken by series to dodge the
    0/0 cancellation."""
    q = np.asarray(q, dtype=np.float64)
    a = -2j * np.pi * q
    small = np.abs(a) < 0.5
    a_safe = np.where(small, 1.0, a)
    closed = (np.exp(a_safe) * (a_safe * a_safe - 2.0 * a_safe + 2.0)
              - 2.0) / a_safe ** 3
    term = np.full(q.shape, 1.0 / 3.0, dtype=np.complex128)
    series = term.copy()
    ab = np.where(small, a, 0.0)
    for n in range(20):
        term = term * ab / (n + 1.0) * ((n + 3.0) / (n + 4.0))
        series = series + term
    return np.where(small, series, closed)


def z_response(z, q):
    """Complex acceleration response ``A_z(q)`` at bin offsets ``q``.

    ``z`` is a host scalar (total drift in Fourier bins over the
    observation); ``q`` an array of offsets from the *starting*
    frequency bin.  Fresnel closed form with the small-``|z|`` series
    branch below :data:`Z_SMALL`; ``z < 0`` by conjugate symmetry.
    """
    z = float(z)
    q = np.asarray(q, dtype=np.float64)
    if abs(z) < Z_SMALL:
        a0 = np.exp(-1j * np.pi * q) * np.sinc(q)
        return a0 + (1j * np.pi * z) * _m1_integral(q)
    if z < 0.0:
        return np.conj(z_response(-z, -q))
    y1 = -q * np.sqrt(2.0 / z)
    y2 = np.sqrt(2.0 * z) + y1
    c1, s1 = fresnel(y1)
    c2, s2 = fresnel(y2)
    pref = np.exp(-1j * np.pi * q * q / z) / np.sqrt(2.0 * z)
    return pref * ((c2 - c1) + 1j * (s2 - s1))


def zw_response(z, w, q, oversample=8):
    """Acceleration+jerk response ``A_{z,w}(q)`` at *integer* offsets ``q``.

    The quadratic-drift chirp has no Fresnel closed form, so the
    template is read from the FFT of the chirp sampled on ``M`` points
    of ``u in [0, 1)`` — bin spacing exactly one Fourier bin of the
    real series.  ``M`` is a power of two at least ``oversample`` times
    the template span so aliased tails sit ~1e-4 below the peak.
    """
    q = np.asarray(q)
    if not np.issubdtype(q.dtype, np.integer):
        qi = np.rint(np.asarray(q, dtype=np.float64)).astype(np.int64)
        if not np.allclose(q, qi):
            raise ValueError("zw_response samples integer bin offsets only")
        q = qi
    span = float(abs(z) + abs(w) + np.max(np.abs(q)) + 16.0)
    m = 1 << max(12, int(np.ceil(np.log2(span * float(oversample)))))
    u = np.arange(m, dtype=np.float64) / m
    chirp = np.exp(2j * np.pi * (0.5 * float(z) * u * u
                                 + (float(w) / 6.0) * u ** 3))
    spec = np.fft.fft(chirp) / m
    return spec[np.mod(q, m)]


def _batched_zw_rows(zs, w, c_half, j):
    """All ``(z, w)`` templates for one ``w != 0`` in a single batched
    chirp FFT — the python-level loop is per ``w`` value, not per
    template, so bank construction stays vectorised."""
    zs = np.asarray(zs, dtype=np.float64)
    span = float(np.max(np.abs(zs)) + abs(w) + np.max(np.abs(c_half))
                 + j[-1] + 16.0)
    m = 1 << max(12, int(np.ceil(np.log2(span * 8.0))))
    u = np.arange(m, dtype=np.float64) / m
    phase = (0.5 * zs[:, None] * (u * u)[None, :]
             + (float(w) / 6.0) * (u ** 3)[None, :])
    spec = np.fft.fft(np.exp(2j * np.pi * phase), axis=-1) / m
    q = c_half[:, None] + j[None, :]                 # (nz, mtap)
    return np.take_along_axis(spec, np.mod(q, m), axis=-1)


def response_bank(zs, ws, half_width):
    """Matched-filter bank over the ``(z, w)`` grid.

    Returns ``(bank, centers)``: ``bank`` is ``(len(zs) * len(ws),
    2 * half_width + 1)`` complex128 holding ``conj(A_{z,w}(c + j))``
    for ``j in [-h, h]``, each row unit-normalised; ``centers`` the
    int32 drift centroids ``c = rint(z/2 + w/6)``.  Row order is
    ``z``-major (``row = iz * len(ws) + iw``).
    """
    zs = np.atleast_1d(np.asarray(zs, dtype=np.float64))
    ws = np.atleast_1d(np.asarray(ws, dtype=np.float64))
    h = int(half_width)
    j = np.arange(-h, h + 1, dtype=np.int64)
    nz, nw = len(zs), len(ws)
    bank = np.empty((nz * nw, 2 * h + 1), dtype=np.complex128)
    centers = np.rint(zs[:, None] / 2.0
                      + ws[None, :] / 6.0).astype(np.int32).reshape(-1)
    for iw, w in enumerate(ws):
        c_half = centers.reshape(nz, nw)[:, iw].astype(np.int64)
        if w == 0.0:
            for iz, z in enumerate(zs):
                bank[iz * nw + iw] = z_response(z, (c_half[iz] + j)
                                                .astype(np.float64))
        else:
            bank[iw::nw] = _batched_zw_rows(zs, w, c_half, j)
    bank = np.conj(bank)
    energy = np.sqrt(np.sum(np.abs(bank) ** 2, axis=-1, keepdims=True))
    return bank / np.maximum(energy, 1e-30), centers


def response_bank_pairs(zs, ws, half_width):
    """Matched-filter rows for *parallel* ``(z, w)`` pairs.

    Same row contract as :func:`response_bank` (``conj(A_{z,w}(c + j))``
    unit-normalised, centers ``rint(z/2 + w/6)``) but builds exactly one
    row per ``(zs[i], ws[i])`` pair instead of the full cartesian
    lattice: a physical trial grid touches a union of ~monotone paths
    through the lattice — thousands of cells — while the bounding box
    spanning the extreme drifts can run to hundreds of thousands of
    rows (gigabytes of templates for a full-band jerk sweep).  Rows
    sharing a ``w`` still batch into one chirp FFT.
    """
    zs = np.atleast_1d(np.asarray(zs, dtype=np.float64))
    ws = np.atleast_1d(np.asarray(ws, dtype=np.float64))
    h = int(half_width)
    j = np.arange(-h, h + 1, dtype=np.int64)
    centers = np.rint(zs / 2.0 + ws / 6.0).astype(np.int32)
    bank = np.empty((len(zs), 2 * h + 1), dtype=np.complex128)
    for w in np.unique(ws):
        sel = np.flatnonzero(ws == w)
        c_half = centers[sel].astype(np.int64)
        if w == 0.0:
            for i in sel:
                bank[i] = z_response(zs[i], (int(centers[i]) + j)
                                     .astype(np.float64))
        else:
            bank[sel] = _batched_zw_rows(zs[sel], w, c_half, j)
    bank = np.conj(bank)
    energy = np.sqrt(np.sum(np.abs(bank) ** 2, axis=-1, keepdims=True))
    return bank / np.maximum(energy, 1e-30), centers


#: half-width ceiling: a template wider than this is truncated (with a
#: warning) — the matched filter degrades gracefully, and the autotune
#: equivalence harness rejects the fdas backend before a truncated
#: regime could silently ship different candidates
MAX_HALF_WIDTH = 256


@functools.lru_cache(maxsize=8)
def bank_for_trials(accels, jerks, nbins, tsamp, nsamples, dz=1.0,
                    dw=4.0, pad=8):
    """Bank + per-(trial, bin) lookup tables for a physical trial grid.

    The search sweeps *physical* ``(a, j)`` trials (matching the
    time-stretch backend cell for cell), so the drift is frequency
    dependent: bin ``k`` of a trial ``(a, j)`` sees ``z_k = k a T / c``
    and ``w_k = k j T^2 / c``.  Each ``(trial, bin)`` is quantised to
    the nearest bank entry.  The grid steps lean on the residual
    degeneracies of the chirp family: a ``dz/2`` quantisation error is
    mostly absorbed by the (always searched) frequency axis, leaving a
    ~``dz/16``-bin smear (Chebyshev residual of a quadratic after its
    best linear fit is 1/8), and a ``dw/2`` error likewise leaves
    ~``dw/64`` (cubic residual 1/32) — so ``dz=1, dw=4`` (PRESTO's
    production z-step is 2) keeps the mismatch loss under a percent
    while the bank stays thousands of rows, not hundreds of
    thousands.

    ``accels``/``jerks`` are hashable tuples of the *flattened trial*
    values (one entry per trial, accel-major).  Returns a dict:

    * ``bank`` — ``(nbank, m)`` complex128 unit matched filters;
    * ``centers`` — ``(nbank,)`` int32 drift centroids;
    * ``tidx`` — ``(ntrials, nbins)`` int32 bank row per (trial, bin);
    * ``gidx`` — ``(ntrials, nbins)`` int32 spectrum gather origin
      ``k + centers[tidx]`` (callers add the tap offset ``[-h, h]``);
    * ``half_width`` — ``h`` (template half width in bins);
    * ``zero_index`` — bank row of the ``(z=0, w=0)`` delta template
      (mesh paths pad the trial axis with it).
    """
    accels = np.asarray(accels, dtype=np.float64)
    jerks = np.asarray(jerks, dtype=np.float64)
    t_obs = float(nsamples) * float(tsamp)
    zeta = accels * t_obs / _C_M_S                # z per bin index
    eta = jerks * t_obs * t_obs / _C_M_S          # w per bin index
    kmax = float(nbins - 1)
    z_top = float(np.max(np.abs(zeta))) * kmax
    w_top = float(np.max(np.abs(eta))) * kmax
    half = int(np.ceil(z_top / 2.0 + w_top / 3.0)) + int(pad)
    if half > MAX_HALF_WIDTH:
        warnings.warn(
            f"fdas template half-width {half} exceeds {MAX_HALF_WIDTH} "
            f"bins (z_max={z_top:.1f}, w_max={w_top:.1f}); truncating — "
            "the matched filter loses sensitivity at the highest "
            "drift rates", UserWarning, stacklevel=2)
        half = MAX_HALF_WIDTH
    nzi = int(np.ceil(z_top / dz)) if z_top > 0 else 0
    nwi = int(np.ceil(w_top / dw)) if w_top > 0 else 0
    k = np.arange(int(nbins), dtype=np.float64)
    zk = zeta[:, None] * k[None, :]               # (ntrials, nbins)
    wk = eta[:, None] * k[None, :]
    iz = np.clip(np.rint(zk / dz).astype(np.int64) + nzi, 0, 2 * nzi)
    iw = np.clip(np.rint(wk / dw).astype(np.int64) + nwi, 0, 2 * nwi)
    # build only the lattice cells the trial paths touch (plus the
    # delta cell, which mesh padding needs) — each trial traces a
    # monotone path of <= nzi + nwi cells, so the compact bank is
    # thousands of rows where the bounding cartesian box over the
    # extreme drifts would be hundreds of thousands
    nws = 2 * nwi + 1
    pair = iz * nws + iw
    zero_pair = np.int64(nzi * nws + nwi)
    uniq = np.union1d(pair.ravel(), zero_pair)
    tidx = np.searchsorted(uniq, pair).astype(np.int32)
    zs = (uniq // nws - nzi).astype(np.float64) * dz
    ws = (uniq % nws - nwi).astype(np.float64) * dw
    bank, centers = response_bank_pairs(zs, ws, half)
    gidx = (np.arange(int(nbins), dtype=np.int64)[None, :]
            + centers[tidx].astype(np.int64)).astype(np.int32)
    return {"bank": bank, "centers": centers, "tidx": tidx,
            "gidx": gidx, "half_width": half,
            "zero_index": int(np.searchsorted(uniq, zero_pair))}
