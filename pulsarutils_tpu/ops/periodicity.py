"""Folded period search: FFT power spectra, harmonic summing, phase folding.

The reference reserves Z^2/H statistic slots on its candidate record
(``pulsarutils/clean.py:43-55``) and scores the dedispersed plane with an
H-test borrowed from ``hendrics`` (``clean.py:252-255``), but never builds
the periodicity *search* those statistics exist for.  This module is that
search, designed TPU-first:

* the power spectrum of a whole dedispersed plane ``(ndm, T)`` is ONE
  batched real FFT — XLA maps it onto the MXU/VPU and it stays in HBM;
* harmonic summing is a batched gather at stride-``j`` indices (the
  "stretch" method), fused by XLA with the spectrum normalisation;
* phase folding over a grid of trial frequencies is a scatter-add under
  ``vmap`` (one-hot-free, O(T) per trial), refined by the native
  Z^2_n / H statistics in :mod:`.robust`;
* everything takes ``xp`` (numpy | jax.numpy) like the rest of the ops
  layer, and the jax path is jit-compatible with static shapes.

White-noise calibration: spectra are median-normalised (median of an
Exp(1) variable is ``ln 2``) so a sum of ``h`` harmonics is Erlang(h)
under the null, giving closed-form false-alarm probabilities
(:func:`power_sf_log`) without any scipy dependency.
"""

from __future__ import annotations

import functools

import numpy as np

from .robust import h_test_batch, ref_mad

#: harmonic-sum depths tried by the search (PRESTO-style powers of two)
HARMONIC_SUMS = (1, 2, 4, 8, 16)

_LN2 = float(np.log(2.0))


# ---------------------------------------------------------------------------
# Power spectra
# ---------------------------------------------------------------------------

def power_spectrum(series, xp=np):
    """Raw rFFT power of ``series`` (..., T) -> (..., T//2 + 1).

    The DC bin is zeroed (the search never uses it and the mean level would
    otherwise dominate every normalisation).
    """
    series = xp.asarray(series)
    spec = xp.fft.rfft(series, axis=-1)
    power = xp.abs(spec) ** 2
    return power * _dc_mask(power.shape[-1], xp)


def _dc_mask(nbins, xp):
    mask = xp.ones(nbins)
    return mask.at[0].set(0.0) if xp is not np else _np_dc_mask(nbins)


def _np_dc_mask(nbins):
    mask = np.ones(nbins)
    mask[0] = 0.0
    return mask


def normalize_power(power, xp=np):
    """Median-normalise so white-noise bins are ~ Exp(1).

    For exponentially distributed raw powers the median is ``ln 2`` times
    the mean, so dividing by ``median / ln 2`` is a robust unit-mean
    normalisation that a strong periodic signal cannot bias the way the
    mean can.  Normalises each spectrum (last axis) independently.
    """
    power = xp.asarray(power)
    med = xp.median(power[..., 1:], axis=-1, keepdims=True)
    return power / xp.where(med > 0, med / _LN2, 1.0)


# ---------------------------------------------------------------------------
# Harmonic summing
# ---------------------------------------------------------------------------

def _add_harmonic(acc, power, j, xp):
    """Add harmonic ``j`` of every fundamental bin into ``acc`` (one gather)."""
    n = power.shape[-1]
    idx = xp.arange(n) * j
    valid = idx < n
    gathered = xp.take(power, xp.where(valid, idx, 0), axis=-1)
    return acc + xp.where(valid, gathered, 0.0)


def _add_harmonic_comp(acc, comp, power, j, xp):
    """Compensated (TwoSum) variant of :func:`_add_harmonic`.

    Carries the rounding error of each harmonic add in ``comp`` — the
    ``f32_compensated``/``split_f32`` policy's path through the stack
    (the harmonic count is small, so the two strategies share the
    sequential compensated form here).
    """
    n = power.shape[-1]
    idx = xp.arange(n) * j
    valid = idx < n
    gathered = xp.take(power, xp.where(valid, idx, 0), axis=-1)
    v = xp.where(valid, gathered, 0.0)
    s = acc + v
    bp = s - acc
    comp = comp + ((acc - (s - bp)) + (v - bp))
    return s, comp


def harmonic_sum(power, nharm, xp=np, policy=None):
    """Stretch-sum the first ``nharm`` harmonics of every fundamental bin.

    ``out[..., i] = sum_{j=1..nharm} power[..., i * j]`` with out-of-range
    harmonics contributing zero.  A bin whose fundamental is ``i`` collects
    the power a narrow pulse spreads over its harmonics; under the null the
    result is Erlang(``nharm``) when ``power`` is Exp(1)-normalised.

    ``policy`` selects a :mod:`..precision` accumulation strategy for
    the harmonic adds (``None``/``"f32"`` = the unchanged plain path).
    """
    power = xp.asarray(power)
    out = xp.zeros_like(power)
    if policy not in (None, "f32"):
        from ..precision import STRATEGIES, policy_name

        strat = STRATEGIES[policy_name(policy)]
        if strat.accumulator in ("compensated", "split"):
            comp = xp.zeros_like(power)
            for j in range(1, int(nharm) + 1):
                out, comp = _add_harmonic_comp(out, comp, power, j, xp)
            return out + comp
    for j in range(1, int(nharm) + 1):
        out = _add_harmonic(out, power, j, xp)
    return out


def power_sf_log(power, nsum=1, xp=np):
    """``log`` survival function of an Erlang(``nsum``) harmonic sum.

    ``P(S > p) = exp(-p) * sum_{k<nsum} p^k / k!`` — the false-alarm
    probability of a single bin of an ``nsum``-harmonic sum of Exp(1)
    powers.  Returned in log space to stay finite for strong detections.
    """
    power = xp.asarray(power, dtype=float)
    # log-sum-exp over k of (k*log p - log k!)
    logp = xp.log(xp.where(power > 0, power, 1e-300))
    terms = [k * logp - _log_factorial(k) for k in range(int(nsum))]
    stacked = xp.stack(terms)
    m = xp.max(stacked, axis=0)
    lse = m + xp.log(xp.sum(xp.exp(stacked - m), axis=0))
    return -power + lse


def _log_factorial(k):
    return float(np.sum(np.log(np.arange(1, k + 1)))) if k > 1 else 0.0


def sf_log_to_sigma(log_sf, xp=np):
    """Gaussian-equivalent significance of a log false-alarm probability.

    Uses the asymptotic expansion of the normal quantile for small tail
    probabilities, ``sigma ~ sqrt(u - log u)`` with ``u = -2 log(sf) -
    log(2 pi)`` — accurate to ~1% for sigma > 2, exact enough for ranking
    candidates (the number the reference never computed at all).
    """
    log_sf = xp.asarray(log_sf, dtype=float)
    u = -2.0 * log_sf - float(np.log(2.0 * np.pi))
    u = xp.where(u > 1.0, u, 1.0)
    return xp.sqrt(u - xp.log(u))


# ---------------------------------------------------------------------------
# Spectral search over a dedispersed plane
# ---------------------------------------------------------------------------

def score_normalized_power(power, nsamples, tsamp, max_harmonics=16,
                           fmin=None, fmax=None, xp=np, policy=None):
    """Harmonic-sum scoring of an already Exp(1)-normalised power
    spectrum ``power`` (..., nbins) of a length-``nsamples`` series.

    The scoring half of :func:`spectral_search`, split out so the
    Fourier-domain acceleration backend
    (:mod:`pulsarutils_tpu.periodicity.fdas`) can feed its correlated
    trial spectra through the IDENTICAL harmonic-sum / false-alarm /
    sigma chain — the cell-for-cell agreement contract between the
    backends rides on this being one implementation, not two.

    ``policy`` selects the :mod:`..precision` accumulation strategy for
    the incremental harmonic stack: compensated strategies thread a
    TwoSum carry through the adds; ``bf16_operand_f32_accum`` gathers
    bfloat16 bins and accumulates float32 (jax only).
    ``None``/``"f32"`` is the byte-identical default.
    """
    strat = None
    if policy not in (None, "f32"):
        from ..precision import STRATEGIES, policy_name

        strat = STRATEGIES[policy_name(policy)]
        if strat.operand_dtype == "bfloat16" and xp is np:
            raise ValueError("bf16_operand_f32_accum needs the jax path "
                             "(numpy has no bfloat16)")
    t = int(nsamples)
    nbins = power.shape[-1]
    freqs = xp.arange(nbins) / (t * tsamp)

    lo = 1 if fmin is None else max(1, int(np.ceil(fmin * t * tsamp)))
    hi = nbins if fmax is None else min(nbins, int(fmax * t * tsamp) + 1)
    band = xp.zeros(nbins)
    if xp is np:
        band[lo:hi] = 1.0
    else:
        band = band.at[lo:hi].set(1.0)

    best_logsf = xp.full(power.shape[:-1], xp.inf)
    best_freq = xp.zeros(power.shape[:-1])
    best_power = xp.zeros(power.shape[:-1])
    best_nharm = xp.zeros(power.shape[:-1], dtype=xp.int32)

    # incremental harmonic accumulation: one gather per harmonic (16 total),
    # scored whenever the depth hits one of HARMONIC_SUMS
    gath = power
    if strat is not None and strat.operand_dtype == "bfloat16":
        # narrow the gathered operand (the bandwidth-bound read); the
        # accumulator stays float32 below
        from ..precision import cast_operand

        gath = cast_operand(power, strat.name, xp)
    compensated = (strat is not None
                   and strat.accumulator in ("compensated", "split"))
    acc = xp.zeros_like(power)
    comp = xp.zeros_like(power) if compensated else None
    depth = 0
    for h in HARMONIC_SUMS:
        if h > max_harmonics:
            break
        for j in range(depth + 1, h + 1):
            if compensated:
                acc, comp = _add_harmonic_comp(acc, comp, power, j, xp)
            elif gath is not power:
                n = power.shape[-1]
                idx = xp.arange(n) * j
                valid = idx < n
                g = xp.take(gath, xp.where(valid, idx, 0), axis=-1)
                acc = acc + xp.where(valid, g.astype(power.dtype), 0.0)
            else:
                acc = _add_harmonic(acc, power, j, xp)
        depth = h
        hsum = (acc + comp if compensated else acc) * band
        peak = xp.argmax(hsum, axis=-1)
        pval = xp.take_along_axis(hsum, peak[..., None], axis=-1)[..., 0]
        log_sf = power_sf_log(pval, nsum=h, xp=xp)
        better = log_sf < best_logsf
        best_logsf = xp.where(better, log_sf, best_logsf)
        best_freq = xp.where(better, xp.take(freqs, peak), best_freq)
        best_power = xp.where(better, pval, best_power)
        best_nharm = xp.where(better, h, best_nharm)

    return {
        "freq": best_freq,
        "power": best_power,
        "nharm": best_nharm,
        "log_sf": best_logsf,
        "sigma": sf_log_to_sigma(best_logsf, xp=xp),
    }


def spectral_search(series, tsamp, max_harmonics=16, fmin=None, fmax=None,
                    xp=np, policy=None):
    """FFT periodicity search of ``series`` (..., T).

    For every harmonic-sum depth ``h`` in :data:`HARMONIC_SUMS` up to
    ``max_harmonics``, find the most significant fundamental bin; return the
    overall best per series.

    Returns a dict of arrays (leading axes = ``series``'s batch axes):
    ``freq`` (Hz), ``power`` (summed normalised power), ``nharm``,
    ``log_sf`` (single-bin log false-alarm probability) and ``sigma``.
    ``policy`` threads a :mod:`..precision` accumulation strategy into
    the harmonic stack (see :func:`score_normalized_power`).
    """
    series = xp.asarray(series)
    t = series.shape[-1]
    power = normalize_power(power_spectrum(series, xp=xp), xp=xp)
    return score_normalized_power(power, t, tsamp,
                                  max_harmonics=max_harmonics,
                                  fmin=fmin, fmax=fmax, xp=xp,
                                  policy=policy)


_SPEC_KEYS = ("freq", "power", "nharm", "log_sf", "sigma")


@functools.lru_cache(maxsize=32)
def _jitted_spectral_stacked(tsamp, max_harmonics, fmin, fmax, policy=None):
    """One jitted program per (tsamp, depth, band) running the whole
    spectral search and returning the five per-row results as ONE
    ``(5, rows)`` array — eager dispatch costs ~50 op round trips per
    chunk on the tunnelled platform, plus five readbacks."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(chunk):
        spec = spectral_search(chunk, tsamp, max_harmonics=max_harmonics,
                               fmin=fmin, fmax=fmax, xp=jnp, policy=policy)
        return jnp.stack([spec[k].astype(jnp.float32) if k == "nharm"
                          else spec[k] for k in _SPEC_KEYS])

    return run


def _spectral_chunk(plane_chunk, tsamp, max_harmonics, fmin, fmax, xp,
                    kernel="auto", policy=None):
    """Spectral-search one row chunk; host dict out (one readback on jax).

    ``kernel`` picks the jax scoring program: ``"xla"`` (the jitted
    :func:`spectral_search` chain), ``"pallas"`` (the one-pass
    :mod:`.harmonic_pallas` normalize+stack kernel) or ``"auto"`` — the
    measured selection via
    :func:`~pulsarutils_tpu.tuning.autotune.resolve_harmonic_kernel`
    (static fallback ``"xla"``; a Pallas win is only ever cached after
    the identity harness passes).  The numpy path ignores ``kernel``.
    """
    if xp is np:
        c = spectral_search(np.asarray(plane_chunk), tsamp,
                            max_harmonics=max_harmonics, fmin=fmin,
                            fmax=fmax, xp=np, policy=policy)
        return {k: np.asarray(v) for k, v in c.items()}
    rows, t = plane_chunk.shape[-2], plane_chunk.shape[-1]
    if kernel == "auto":
        from ..tuning.autotune import resolve_harmonic_kernel

        kernel = resolve_harmonic_kernel(rows, t, float(tsamp),
                                         max_harmonics=int(max_harmonics),
                                         fmin=fmin, fmax=fmax,
                                         policy=policy)
    if kernel == "pallas":
        from .harmonic_pallas import spectral_search_pallas

        spec = spectral_search_pallas(plane_chunk, tsamp,
                                      max_harmonics=max_harmonics,
                                      fmin=fmin, fmax=fmax, policy=policy)
        out = {k: np.asarray(v) for k, v in spec.items()}
        out["nharm"] = np.rint(out["nharm"]).astype(np.int32)
        return out
    run = _jitted_spectral_stacked(
        float(tsamp), int(max_harmonics),
        None if fmin is None else float(fmin),
        None if fmax is None else float(fmax), policy)
    stacked = np.asarray(run(xp.asarray(plane_chunk)))
    out = dict(zip(_SPEC_KEYS, stacked))
    out["nharm"] = np.rint(out["nharm"]).astype(np.int32)
    return out


# ---------------------------------------------------------------------------
# Phase folding
# ---------------------------------------------------------------------------

#: samples per phase-anchor block in the device fold kernel.  Anchored
#: folding bounds the float32 phase error to ~``_FOLD_BLOCK * eps`` cycles
#: regardless of series length (see :func:`_phase_anchors`).
_FOLD_BLOCK = 4096


def _phase_anchors(nsamples, freqs, tsamp, t0):
    """Host-side float64 phase at the start of every anchor block.

    Device arithmetic is float32; computing ``(i * tsamp * freq) mod 1``
    directly in float32 accumulates phase error linearly in ``i`` (0.05
    cycles by ``i ~ 2^24`` at 100 Hz — enough to smear a profile).  Instead
    the exact (float64) phase is evaluated every ``_FOLD_BLOCK`` samples and
    the device only extrapolates within a block, where the float32 error is
    a few 1e-4 cycles.  Returns ``(anchors, step_frac)``: ``(nfreq,
    nblocks)`` block-start phases in [0, 1) and the per-freq fractional
    phase step per sample.
    """
    freqs = np.atleast_1d(np.asarray(freqs, dtype=np.float64))
    nblocks = -(-int(nsamples) // _FOLD_BLOCK)
    starts = np.arange(nblocks, dtype=np.float64) * _FOLD_BLOCK
    step = freqs * float(tsamp)
    anchors = ((starts[None, :] * step[:, None])
               + float(t0) * freqs[:, None]) % 1.0
    return anchors, step % 1.0


def _fold_jax_anchored(series, anchors, step_frac, nbin):
    """Device fold from precomputed anchors: one trial frequency."""
    import jax.numpy as jnp

    t = series.shape[0]
    nblocks = anchors.shape[0]
    i = jnp.arange(_FOLD_BLOCK, dtype=series.dtype)
    # (nblocks, B): i * step mod 1 == i * frac(step) mod 1 for integer i
    phase = (anchors[:, None] + i[None, :] * step_frac) % 1.0
    bins = (phase * nbin).astype(jnp.int32) % nbin
    bins = bins.reshape(-1)[:t]
    profile = jnp.zeros(nbin, dtype=series.dtype).at[bins].add(series)
    hits = jnp.zeros(nbin, dtype=series.dtype).at[bins].add(1.0)
    return profile, hits


def fold(series, freq, tsamp, nbin=32, t0=0.0, xp=np):
    """Fold ``series`` (T,) at frequency ``freq`` into ``nbin`` phase bins.

    Returns ``(profile, hits)``: the per-bin sum of samples and the per-bin
    sample counts (callers divide for a mean profile; the raw sums are what
    the Z^2/H statistics want).  ``freq`` must be a concrete (host) scalar:
    phase anchors are precomputed in float64 so device folding stays
    accurate for arbitrarily long series (see :func:`_phase_anchors`).
    """
    series = xp.asarray(series)
    t = series.shape[0]
    if xp is np:
        phases = ((np.arange(t) * float(tsamp) + t0) * float(freq)) % 1.0
        bins = np.floor(phases * nbin).astype(np.int64) % nbin
        profile = np.bincount(bins, weights=series, minlength=nbin)
        hits = np.bincount(bins, minlength=nbin).astype(float)
        return profile, hits
    anchors, step_frac = _phase_anchors(t, float(freq), tsamp, t0)
    return _fold_jax_anchored(series, xp.asarray(anchors[0], dtype=series.dtype),
                              xp.asarray(step_frac[0], dtype=series.dtype), nbin)


def fold_batch(series, freqs, tsamp, nbin=32, t0=0.0, xp=np):
    """Fold one series at many trial frequencies -> ``(nfreq, nbin)`` sums.

    On the jax path the frequency axis is ``vmap``-ed over the precomputed
    phase anchors so all trials fold in one compiled program.  ``freqs``
    must be concrete host values (they parameterise the float64 anchor
    table, not the traced computation).
    """
    freqs = np.asarray(  # putpu-lint: disable=device-trip — concrete host anchors by contract
        freqs, dtype=np.float64)
    if xp is np:
        folded = [fold(series, f, tsamp, nbin, t0) for f in freqs]
        return (np.stack([p for p, _ in folded]),
                np.stack([h for _, h in folded]))
    import jax

    anchors, step_frac = _phase_anchors(series.shape[0], freqs, tsamp, t0)
    f = jax.vmap(lambda a, s: _fold_jax_anchored(series, a, s, nbin))
    return f(xp.asarray(anchors, dtype=series.dtype),
             xp.asarray(step_frac, dtype=series.dtype))


def _epoch_fold_score(series, profiles, hits, nmax, xp):
    """Exposure-correct folded profiles and H-test them (pure, jittable)."""
    mean_rate = profiles.sum(axis=-1, keepdims=True) / xp.maximum(
        hits.sum(axis=-1, keepdims=True), 1.0)
    corrected = profiles - hits * mean_rate
    sigma = ref_mad(series, xp=xp)
    total = series.shape[0] * xp.maximum(sigma * sigma, 1e-30)
    return h_test_batch(corrected, nmax=nmax, xp=xp, total=total)


@functools.lru_cache(maxsize=16)
def _jitted_epoch_fold(nbin, nmax):
    """Fold + exposure-correct + H-test as ONE compiled program (eager
    dispatch costs ~30 op round trips on the tunnelled platform)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(series, anchors, step_frac):
        profiles, hits = jax.vmap(
            lambda a, s: _fold_jax_anchored(series, a, s, nbin))(
                anchors, step_frac)
        h, m = _epoch_fold_score(series, profiles, hits, nmax, jnp)
        return h, m, profiles

    return run


def epoch_folding_search(series, tsamp, freqs, nbin=32, nmax=8, xp=np):
    """Refine candidate frequencies by folding + H-test.

    Folds ``series`` at every trial frequency, exposure-corrects the
    profiles (uneven per-bin hit counts tilt them) and scores with the
    de Jager H-test under the *Gaussian* normalisation ``total = T sigma^2``
    (robust sigma from :func:`~.robust.ref_mad`), so H stays chi-square
    calibrated instead of scaling with the input noise amplitude.  Returns
    ``(h_stats, m_best, profiles)``.  Capability-equivalent of the efsearch
    step the reference outsourced to hendrics (``clean.py:252-255``), run
    over frequency instead of plane rows.
    """
    series = xp.asarray(series)
    if xp is not np:
        freqs64 = np.asarray(freqs, dtype=np.float64)
        anchors, step_frac = _phase_anchors(series.shape[0], freqs64, tsamp,
                                            0.0)
        run = _jitted_epoch_fold(int(nbin), int(nmax))
        return run(series, xp.asarray(anchors, dtype=series.dtype),
                   xp.asarray(step_frac, dtype=series.dtype))
    profiles, hits = fold_batch(series, freqs, tsamp, nbin=nbin, xp=xp)
    h, m = _epoch_fold_score(series, profiles, hits, nmax, xp)
    return h, m, profiles


def refine_grid(freq, tsamp, nsamples, oversample=8, half_width_bins=2):
    """Trial-frequency grid around ``freq`` spanning ±``half_width_bins``
    Fourier bins at ``oversample`` trials per bin (the Fourier resolution of
    an ``nsamples``-long series is ``1 / (T tsamp)``)."""
    df = 1.0 / (nsamples * tsamp)
    n = 2 * half_width_bins * oversample + 1
    return freq + np.linspace(-half_width_bins * df, half_width_bins * df, n)


# ---------------------------------------------------------------------------
# Full folded period search (the BASELINE config-4 pipeline step)
# ---------------------------------------------------------------------------

def period_search_plane(plane, tsamp, max_harmonics=16, fmin=None, fmax=None,
                        nbin=32, oversample=8, refine_top=1, row_chunk=None,
                        xp=np):
    """Folded period search over a dedispersed plane ``(ndm, T)``.

    Stage 1 (device): batched FFT + harmonic-sum search per DM trial,
    processed ``row_chunk`` rows at a time — XLA's batched rFFT allocates
    several (rows x T) temporaries, so an unchunked 4096-trial x 256k
    plane overruns HBM.  Default keeps each chunk's FFT workspace near
    0.5 GB.  Per-row results concatenate exactly, so chunking changes
    nothing numerically.
    Stage 2 (device): for the ``refine_top`` most significant DM rows, fold
    on a fine frequency grid around the spectral candidate and H-test.

    Returns a dict: per-DM spectral results (``freq, power, nharm, log_sf,
    sigma``) plus ``best_dm_index``, ``best_freq``, ``best_h``, ``best_m``,
    ``best_sigma`` (Gaussian-equivalent significance of the refined H via
    the de Jager & Büsching 2010 tail ``P(>H) ~ exp(-0.4 H)``) and
    ``best_profile``.
    """
    # NOTE: do not blanket-convert ``plane`` with xp.asarray — a plane the
    # search spilled to host (ndm beyond one superblock) would be shipped
    # back to HBM whole, defeating the chunked memory bound below; chunks
    # are converted as they are processed
    ndm, t = plane.shape
    if row_chunk is None:
        row_chunk = max(16, (1 << 27) // max(1, t))
    if hasattr(plane, "spectral_scores"):
        # mesh path: the plane is a DM-sharded device-resident handle
        # (:class:`~pulsarutils_tpu.parallel.sharded_plane.ShardedPlane`);
        # stage 1 runs shard-locally on each device's rows and only the
        # per-row score vectors come to host.  Stage 2 below fetches the
        # refine rows individually (``plane[d]`` -> one host row).
        spec = plane.spectral_scores(tsamp, max_harmonics=max_harmonics,
                                     fmin=fmin, fmax=fmax)
    elif ndm <= row_chunk:
        spec = _spectral_chunk(plane, tsamp, max_harmonics, fmin, fmax, xp)
    else:
        chunks = []
        for lo in range(0, ndm, row_chunk):
            # each chunk runs as one jitted program with one host readback
            # (_spectral_chunk); pulling to host INSIDE the loop keeps a
            # single chunk's FFT workspace live in HBM at a time — async
            # dispatch would otherwise run several concurrently, the very
            # blow-up the chunking exists to prevent
            chunks.append(_spectral_chunk(plane[lo:lo + row_chunk], tsamp,
                                          max_harmonics, fmin, fmax, xp))
        spec = {k: np.concatenate([c[k] for c in chunks])
                for k in chunks[0]}

    order = np.argsort(np.asarray(spec["log_sf"]))
    best = {}
    for rank in range(min(int(refine_top), ndm)):
        d = int(order[rank])
        f0 = float(np.asarray(spec["freq"])[d])
        if f0 <= 0:
            continue
        grid = refine_grid(f0, tsamp, t, oversample=oversample)
        h, m, profiles = epoch_folding_search(plane[d], tsamp,
                                              xp.asarray(grid), nbin=nbin,
                                              xp=xp)
        k = int(np.argmax(np.asarray(h)))
        cand = {
            "dm_index": d,
            "freq": float(grid[k]),
            "h": float(np.asarray(h)[k]),
            "m": int(np.asarray(m)[k]),
            "profile": np.asarray(profiles[k]),
        }
        if not best or cand["h"] > best["h"]:
            best = cand

    best_h = best.get("h", 0.0)
    best_sigma = float(sf_log_to_sigma(np.asarray(-0.4 * best_h), xp=np)) \
        if best_h > 0 else float(np.asarray(spec["sigma"])[order[0]])
    return {
        **{k: np.asarray(v) for k, v in spec.items()},
        "best_dm_index": best.get("dm_index", int(order[0])),
        "best_freq": best.get("freq", float(np.asarray(spec["freq"])[order[0]])),
        "best_h": best_h,
        "best_m": best.get("m", 0),
        "best_sigma": best_sigma,
        "best_profile": best.get("profile"),
    }
