"""Dedispersion plan math: per-channel delays, trial-DM grids, smearing.

These are the scientific correctness anchors of the whole framework.  They
reproduce — exactly, including the rounding conventions — the behaviour of
the reference implementation:

* per-channel shifts: reference ``pulsarutils/dedispersion.py:125-139``
* differential band delay: reference ``pulsarutils/dedispersion.py:142-146``
* trial-DM plan (one trial per integer sample of differential band delay):
  reference ``pulsarutils/dedispersion.py:149-171``
* shift normalisation into ``[0, N)``: reference
  ``pulsarutils/dedispersion.py:101-122``
* intra-channel DM smearing: reference ``pulsarutils/clean.py:272-274``

Every function is written against a pluggable array namespace (``xp``) so the
identical formula runs under NumPy on the host (static plan construction) and
under ``jax.numpy`` inside jitted/sharded kernels (on-device shift
computation, which keeps the (ndm, nchan) shift table out of host->device
transfers).

Sign/rounding conventions that the S/N recovery depends on (pinned by tests):

* delays are measured **relative to the band-centre frequency**, so shifts are
  positive below centre and negative above;
* a shift is ``rint(delay // sample_time)`` — float floor-division first,
  then round-to-nearest-even (reference ``dedispersion.py:137``);
* ``normalize_shifts`` rounds with ``rint`` then wraps into ``[0, N)``
  (reference ``dedispersion.py:101-122``).
"""

from __future__ import annotations

import numpy as np

#: Dispersion constant in s MHz^2 cm^3 pc^-1 (reference uses the rounded
#: value 4149; ``pulsarutils/dedispersion.py:130,136,144-145``).
DM_DELAY_CONST = 4149.0

#: Intra-channel smearing constant (seconds, MHz): ``8300 * DM * df / f^3``
#: (reference ``pulsarutils/clean.py:272-274``).
DM_SMEARING_CONST = 8300.0


def dm_delay(dm, freq, xp=np):
    """Cold-plasma dispersion delay (seconds) at ``freq`` MHz for ``dm``."""
    return DM_DELAY_CONST * dm * freq ** (-2.0)


def delta_delay(dm, start_freq, stop_freq, xp=np):
    """Differential dispersion delay (s) between two frequencies (MHz).

    Reference: ``pulsarutils/dedispersion.py:142-146``.
    """
    return dm_delay(dm, start_freq, xp=xp) - dm_delay(dm, stop_freq, xp=xp)


def dm_broadening(dm, freq, df, xp=np):
    """Intra-channel DM smearing time (s) in a channel of width ``df`` MHz.

    Reference: ``pulsarutils/clean.py:272-274``.  Used by the streaming
    driver to pick the automatic resampling factor.
    """
    return DM_SMEARING_CONST * dm * df / freq ** 3


def channel_frequencies(nchan, start_freq, bandwidth, xp=np):
    """Lower-edge frequency of each channel (MHz).

    The reference indexes channels from the *bottom* of the band with the
    channel's lower edge as its frequency (``dedispersion.py:127,135``).
    """
    dfreq = bandwidth / nchan
    return start_freq + xp.arange(nchan) * dfreq


def dedispersion_shifts(nchan, dm, start_freq, bandwidth, sample_time, xp=np):
    """Integer per-channel sample delays (as a float array) for one DM.

    ``shift[i] = rint((delay_i - delay_center) // sample_time)`` where
    ``delay_f = 4149 * dm / f^2`` and the reference point is the band-centre
    frequency.  Reference: ``pulsarutils/dedispersion.py:125-139`` (note the
    float floor-division *before* ``rint`` — kept bit-identical here).

    Returns a float array of shape ``(nchan,)`` holding integer values,
    matching the reference's return type.
    """
    center_freq = start_freq + bandwidth / 2.0
    ref_delay = dm_delay(dm, center_freq, xp=xp)
    chan_freq = channel_frequencies(nchan, start_freq, bandwidth, xp=xp)
    delay = DM_DELAY_CONST * dm * chan_freq ** (-2.0) - ref_delay
    return xp.rint(delay // sample_time)


def dedispersion_shifts_batch(trial_dms, nchan, start_freq, bandwidth,
                              sample_time, xp=np):
    """Per-channel shifts for a whole trial-DM grid at once.

    Vectorised form of :func:`dedispersion_shifts` over the trial axis —
    the batched equivalent of the per-trial call inside the reference sweep
    (``pulsarutils/dedispersion.py:183``).  Returns ``(ndm, nchan)`` floats
    holding integer values; bit-identical per row to the scalar function.
    """
    trial_dms = xp.asarray(trial_dms)
    center_freq = start_freq + bandwidth / 2.0
    chan_freq = channel_frequencies(nchan, start_freq, bandwidth, xp=xp)
    # delay[d, c] relative to band centre
    delay = (DM_DELAY_CONST * trial_dms[:, None]
             * (chan_freq[None, :] ** (-2.0) - center_freq ** (-2.0)))
    return xp.rint(delay // sample_time)


def normalize_shifts(shifts, n, xp=np):
    """Round shifts and wrap them into ``[0, n)`` as ``int32``.

    Vectorised re-statement of the reference's rint + while-loop wrap
    (``pulsarutils/dedispersion.py:101-122``): for any finite shift,
    repeatedly adding/subtracting ``n`` is exactly the mathematical modulo,
    which both NumPy's and JAX's ``%`` implement for the int32 values
    produced by ``rint``.

    >>> normalize_shifts(np.array([-1.2, 0.0, 3.6, 10.0]), 8)
    array([7, 0, 4, 2], dtype=int32)
    """
    shifts = xp.asarray(shifts)
    # float modulo is exact for the integer-valued magnitudes produced here
    # (|shift| < 2**24 even in float32), and avoids int64 on accelerators
    wrapped = xp.rint(shifts) % n
    return wrapped.astype(xp.int32)


def dedispersion_plan(nchan, dmmin, dmmax, start_freq, bandwidth, sample_time,
                      xp=np):
    """Trial-DM grid: one trial per integer sample of band-crossing delay.

    The spacing criterion of the reference (``dedispersion.py:149-171``):
    the differential delay across the full band, in samples, steps by one
    between consecutive trials.  ``trial_N = arange(min_N, max_N + 1)`` is
    then inverted to DM.  (The reference's ``np.float`` calls — removed from
    NumPy >= 1.24 — are simply dropped; values are already floats.)

    The endpoints bracket the requested range and consecutive trials differ
    by one sample of band delay:

    >>> dms = dedispersion_plan(64, 100, 200.0, 1200.0, 200.0, 0.0005)
    >>> bool(dms[0] <= 100.5) and bool(dms[-1] >= 199.0)
    True
    >>> d = (delta_delay(dms[1], 1200.0, 1400.0)
    ...      - delta_delay(dms[0], 1200.0, 1400.0)) / 0.0005
    >>> round(float(d), 6)
    1.0
    """
    stop_freq = start_freq + bandwidth
    f0 = float(start_freq)
    f1 = float(stop_freq)

    max_n = delta_delay(float(dmmax), f0, f1) / sample_time
    min_n = delta_delay(float(dmmin), f0, f1) / sample_time

    trial_n = xp.arange(min_n, max_n + 1)
    trial_dm = trial_n * sample_time / DM_DELAY_CONST / (f0 ** -2.0 - f1 ** -2.0)
    return trial_dm


def dmmax_for_trials(dmmin, n_trials, start_freq, bandwidth, sample_time):
    """DM upper bound whose canonical integer-band-delay grid spans exactly
    ``n_trials`` starting at ``dmmin``.

    The inverse of :func:`pulsarutils_tpu.ops.fdmt.fdmt_trial_dms`'s grid
    sizing: trials sit at integer samples of band-crossing delay, the first
    at ``ceil(delta_delay(dmmin) / sample_time)``.  A half-sample margin is
    added so float rounding cannot drop the last trial.

    >>> dmmax = dmmax_for_trials(300.0, 512, 1200.0, 200.0, 0.0005)
    >>> from pulsarutils_tpu.ops.fdmt import fdmt_trial_dms
    >>> len(fdmt_trial_dms(1024, 300.0, dmmax, 1200.0, 200.0, 0.0005)[0])
    512
    """
    f0 = float(start_freq)
    f1 = f0 + float(bandwidth)
    unit = delta_delay(1.0, f0, f1)  # band-delay seconds per DM unit
    n_lo = int(np.ceil(delta_delay(float(dmmin), f0, f1) / sample_time))
    return (n_lo + n_trials - 0.5) * sample_time / unit


def plan_size(nchan, dmmin, dmmax, start_freq, bandwidth, sample_time):
    """Number of trials the plan will contain, computed without allocating.

    Useful for static-shape padding decisions before jit tracing.
    """
    stop_freq = start_freq + bandwidth
    max_n = delta_delay(float(dmmax), start_freq, stop_freq) / sample_time
    min_n = delta_delay(float(dmmin), start_freq, stop_freq) / sample_time
    # len(np.arange(a, b)) == ceil(b - a) for b > a
    return int(np.ceil(max_n + 1 - min_n))
