"""Per-config soundness bounds for the hybrid search: a computed lower
bound on how much of a real pulse's exact S/N the coarse (FDMT) sweep
retains (exact for the *deterministic* track scatter; the stochastic
noise cross-term is handled separately — see *Miss risk* below), and the
noise certificate built on it.

The hybrid search (:func:`~pulsarutils_tpu.ops.search._search_jax_hybrid`)
screens every trial with the tree transform and exactly rescores the rows
that could hold the best hit.  Both its stopping margin and its
noise-certificate fast path rest on ONE quantity: a lower bound on the
ratio ``coarse_snr / exact_snr`` for an impulsive signal.  Round 2 carried
that bound as a hand-set constant (``HYBRID_COARSE_TRUST = 0.45``, citing
the Zackay & Ofek 2017 §2.3 track-deviation argument); this module
*computes* it per search configuration, exactly, from the transform's own
merge tables:

1. :func:`~pulsarutils_tpu.ops.fdmt.fdmt_tracks` reconstructs the
   effective per-channel track of every coarse row — no data, no noise;
2. for each plan trial, the deviation of its mapped coarse row's track
   from the exact kernel's integer offsets gives the *exact* per-channel
   scatter a pulse's energy suffers in the coarse sweep;
3. the worst-case retention over pulse phase follows combinatorially from
   that scatter histogram and the scorer's block-boxcar geometry
   (widths 1, 2, 4, 8, non-sliding block sums — reference
   ``pulsarutils/dedispersion.py:190-196``).

Signal model (stated, not hidden): the bound covers **impulsive signals**
— one coherent pulse per channel riding a dispersion track, width >=
``min_width`` samples, any alignment — which is the signal class the
search exists to find (and the same class the reference's own integer
rounding is analysed for).  Arbitrary adversarial inputs can defeat any
coarse screen; they can also defeat the reference's rounding.

Noise certificate
-----------------
For a detection floor ``s`` (the pipeline's ``snr > s`` hit criterion,
reference ``clean.py:349``), any pulse with exact S/N >= ``s`` must show
coarse S/N >= ``rho * s - HYBRID_CERT_SLACK``.  Contrapositive: when no
coarse row reaches that level, **no detectable pulse exists in the
chunk** and the costly exact-argbest localisation can be skipped
entirely — the chunk is certified signal-free at floor ``s``.  On survey
data (overwhelmingly noise) this converts the hybrid's worst case (the
degenerate full exact sweep on signal-free chunks, VERDICT r2) into its
best case: one coarse sweep per noise chunk.

A certified table does NOT carry an exact argbest (its best row holds
coarse scores); the certificate's claim is strictly about the absence of
detections above the floor.  A pure-noise fluctuation that would have
crossed the floor on the exact grid can be suppressed by the certificate
— that is a false alarm the exact pipeline would have flagged, not a
missed signal.

Miss risk (the honest fine print)
---------------------------------
The retention bound covers the *deterministic* part of the coarse score
exactly, but the coarse row also carries a stochastic cross-term: the
noise already sitting in the bins the pulse's scattered energy lands in.
In S/N units that cross-term is (sub-)Gaussian with standard deviation
<= 1 — the certificate's best capture window of width ``w`` holds ``w``
iid noise samples whose normalised sum has unit variance, and the
max-over-windows selection can only push the realised score *up* (see
:func:`cert_slack_for_miss_p` for the derivation).  The certificate
inequality absorbs it with the absolute allowance
:data:`HYBRID_CERT_SLACK`; the inequality is therefore **sound under the
stated impulsive-signal model up to this Gaussian cross-term**, not
adversarially absolute.  Quantitatively: a worst-case-phase,
worst-case-width pulse sitting *exactly* at the floor evades the
certificate with probability at most ``Phi(-slack)`` (~0.31 at the
default 0.5), decaying as ``Phi(-(slack + rho * (s - floor)))`` for a
pulse of exact S/N ``s`` — ``Phi(-1.1)`` ~ 14% one S/N unit above a
rho=0.6 floor via the deterministic surplus alone, ~2% three units
above, and far smaller at typical phases,
where the realised retention exceeds the worst-case ``rho`` by enough
to absorb several cross-term sigmas (empirically the cross-term never
exceeded ~0.3 across the seeded calibration sweeps).  Callers that need
a stated at-floor miss probability should pass
``cert_slack=cert_slack_for_miss_p(p)`` to ``dedispersion_search`` /
``sharded_hybrid_search``; the operating assumption is recorded in
``table.meta`` (``cert_slack``, ``cert_miss_p_at_floor``) wherever
``certified`` is reported.

Detection floors at long chunks
-------------------------------
The reference's ``snr > 6`` criterion was tuned for its physics-sized
chunks (a few thousand samples, noise max ~ 4).  At this framework's
million-sample device-resident chunks the expected signal-free maximum is
~ 5.3-5.6, so a fixed 6.0 floor false-alarms on a few percent of pure
noise chunks *regardless of kernel* — and sits too close to the noise for
the certificate to clear it.  :func:`expected_noise_max_snr` /
:func:`matched_snr_floor` compute the statistically matched floor for a
given chunk geometry (the same false-alarm philosophy as the reference's
6, adapted to the chunk size).
"""

from __future__ import annotations

import functools

import numpy as np

def _windows():
    """The detection scorer's boxcar widths — imported lazily from the
    single source of truth so the bounds can never silently diverge
    from the scorer."""
    from .search import SEARCH_WINDOWS

    return SEARCH_WINDOWS

#: absolute S/N slack in the certificate inequality
#: ``coarse >= rho * exact - HYBRID_CERT_SLACK``: the allowance for the
#: stochastic noise cross-term (the pulse's scattered energy interacting
#: with the noise already in its bins) and sub-sample pulse phase.  The
#: cross-term is Gaussian-tailed with sd <= 1 in S/N units, so this
#: value IS a z-score, not a hard bound: an at-floor worst-case-phase
#: pulse evades the certificate with probability up to ``Phi(-slack)``
#: (~0.31 at 0.5) — see the module docstring's *Miss risk* section and
#: :func:`cert_slack_for_miss_p` to derive the slack from a target miss
#: probability instead.  The 0.5 default is an empirically supported
#: operating point (worst observed cross-term ~< 0.3 over hundreds of
#: seeded draws in ``tests/test_certify.py``/``tools/hybrid_calibrate.py``
#: — typical-phase retention surplus absorbs the dips), chosen to keep
#: ``certifiable_snr_floor`` low; it is NOT a proof.
HYBRID_CERT_SLACK = 0.5

#: upper bound on the certificate noise cross-term's standard deviation
#: in S/N units (see :func:`cert_slack_for_miss_p` for the argument)
CERT_CROSS_TERM_SD = 1.0


def cert_slack_for_miss_p(miss_p):
    """Certificate slack achieving an at-floor miss probability <= ``miss_p``.

    Derivation: write the coarse row's certificate score for a pulse of
    exact S/N ``s`` as ``cert = rho_realised * s + Z`` where
    ``rho_realised >= rho`` (the computed deterministic retention bound)
    and ``Z`` is the noise already in the certificate's best capture
    window.  For a width-``w`` sliding window, ``Z`` is a sum of ``w``
    iid unit-variance noise samples divided by ``std * sqrt(w)`` — unit
    variance; taking the max over windows and alignments only *raises*
    the realised score, so ``P(cert < rho * s - slack) <=
    P(Z < -slack) = Phi(-slack / CERT_CROSS_TERM_SD)``.  Hence
    ``slack = CERT_CROSS_TERM_SD * Phi^{-1}(1 - miss_p)`` guarantees an
    at-floor miss probability <= ``miss_p`` *for the worst-case phase
    and width*; pulses above the floor gain ``rho * (s - floor)`` extra
    margin on top.

    Note the cost: a 1e-3 target needs slack ~3.1, which raises
    :func:`certifiable_snr_floor` by ``(3.1 - 0.5) / rho`` (~4.3 S/N at
    rho = 0.6) over the default operating point — the price of a stated
    guarantee instead of an empirical allowance.
    """
    from statistics import NormalDist

    if not 0.0 < miss_p < 1.0:
        raise ValueError(f"miss_p={miss_p!r}: expected a probability in "
                         "(0, 1)")
    return CERT_CROSS_TERM_SD * NormalDist().inv_cdf(1.0 - float(miss_p))


def cert_miss_p_at_floor(slack=None):
    """At-floor worst-case miss probability implied by ``slack``
    (``Phi(-slack / CERT_CROSS_TERM_SD)``, the inverse of
    :func:`cert_slack_for_miss_p`) — the residual-risk number recorded
    in ``table.meta`` alongside ``certified``."""
    from statistics import NormalDist

    if slack is None:
        slack = HYBRID_CERT_SLACK
    return NormalDist().cdf(-float(slack) / CERT_CROSS_TERM_SD)


def cert_meta(certified, rho_cert, snr_floor, cert_slack=None):
    """The hybrid searches' certificate block of ``table.meta`` — ONE
    place constructs it so the single-device and sharded hybrids (whose
    docstrings promise an identical contract) can never drift.

    ``cert_miss_p_at_floor`` is recorded only when there was actually a
    floor for the number to refer to (``snr_floor`` set and the bound
    computed); ``cert_slack`` is always recorded — the skip criterion
    uses it even on floorless runs.
    """
    slack_used = (HYBRID_CERT_SLACK if cert_slack is None
                  else float(cert_slack))
    return {"certified": certified, "rho_cert": rho_cert,
            "snr_floor": snr_floor, "cert_slack": slack_used,
            "cert_miss_p_at_floor": (
                round(cert_miss_p_at_floor(slack_used), 4)
                if rho_cert is not None and snr_floor is not None
                else None)}


def _retention_from_offsets(offsets, weights=None, min_width=1):
    """Worst-case coarse/exact S/N ratio given per-channel track offsets.

    ``offsets`` is the signed per-channel deviation (samples) of the
    coarse track from the exact track for one trial.  A width-``W`` pulse
    (amplitude spread uniformly over ``W`` samples per channel) that the
    exact kernel sees as a clean ``W``-sample box becomes, in the coarse
    row, the box convolved with the offset histogram.  Both series are
    scored identically (block sums of widths 1/2/4/8, ``max/std``), so
    the retention at pulse phase ``p`` is the ratio of the best
    block-capture of the scattered mass to the best block-capture of the
    clean box; the bound takes the worst phase.  Noise std is identical
    in both series (each channel contributes exactly one sample per bin
    in either kernel), so S/N ratio == capture ratio.
    """
    offsets = np.asarray(offsets, dtype=np.int64)
    offsets = offsets - offsets.min()
    if weights is None:
        weights = np.full(offsets.shape, 1.0 / len(offsets))
    span = int(offsets.max()) + 1
    h = np.zeros(span)
    np.add.at(h, offsets, weights)
    h /= h.sum()
    w_pulse = int(min_width)
    # mass distributions over absolute bins, pulse starting at phase p:
    # exact = box of width W at [p, p+W); coarse = same box convolved
    # with h -> support [p, p + W + span - 1)
    box = np.full(w_pulse, 1.0 / w_pulse)
    coarse_mass = np.convolve(h, box)
    worst = np.inf
    for p in range(8):  # lcm of the window widths
        def best_score(mass):
            best = 0.0
            for w in _windows():
                bins = p + np.arange(len(mass))
                blocks = bins // w
                cap = np.zeros(blocks[-1] + 1)
                np.add.at(cap, blocks, mass)
                best = max(best, cap.max() / np.sqrt(w))
            return best

        exact_score = best_score(box)
        coarse_score = best_score(coarse_mass)
        worst = min(worst, coarse_score / exact_score)
    return float(worst)


@functools.lru_cache(maxsize=64)
def _exact_best_phase(width):
    """Best block-boxcar score of a clean width-``width`` box (in total-
    mass units), over all windows AND phases — the soundness-relevant
    denominator of the certificate ratio.  Depends on ``width`` alone,
    so it is memoised (cert_retention evaluates it once per trial x
    width otherwise — a multi-second host stall at multi-thousand-trial
    configs)."""
    box = np.full(width, 1.0 / width)
    best = 0.0
    for w in _windows():
        # best phase: the box starts on a block boundary; blocks
        # capture min(w, width)/width contiguously
        for p in range(8):
            bins = p + np.arange(width)
            blocks = bins // w
            cap = np.zeros(blocks[-1] + 1)
            np.add.at(cap, blocks, box)
            best = max(best, cap.max() / np.sqrt(w))
    return best


def _cert_retention_from_offsets(offsets, max_width=16):
    """Worst-case ``cert_score / exact_snr`` ratio for one trial's track.

    The certificate numerator is the *sliding* window-2/4 capture
    (:func:`~pulsarutils_tpu.ops.search.cert_profile_scores`) — phase
    invariant, so no worst-phase minimisation applies to it; the
    denominator is the exact kernel's best detection score of the same
    pulse, taken at the pulse's *best* phase (the soundness-relevant
    worst case: the exact sweep scoring the pulse as well as it possibly
    can while the coarse row still must flag it).  Minimised over pulse
    widths 1..``max_width``; beyond the scorer's largest block (8) both
    sides decay ~1/W and the ratio tends to a constant ~0.7, so the
    minimum always sits at small widths.
    """
    offsets = np.asarray(offsets, dtype=np.int64)
    offsets = offsets - offsets.min()
    span = int(offsets.max()) + 1
    h = np.zeros(span)
    np.add.at(h, offsets, 1.0 / len(offsets))

    from .search import CERT_WINDOWS

    def sliding_capture(mass, w):
        if len(mass) <= w:
            return mass.sum()
        kernel = np.ones(w)
        return np.convolve(mass, kernel).max()

    worst = np.inf
    for width in range(1, max_width + 1):
        mass = np.convolve(h, np.full(width, 1.0 / width))
        cert = max(sliding_capture(mass, w) / np.sqrt(w)
                   for w in CERT_WINDOWS)
        worst = min(worst, cert / _exact_best_phase(width))
    return float(worst)


def _track_deviations(nchan, trial_dms, start_freq, bandwidth, sample_time,
                      nsamples):
    """Signed per-channel deviation of each plan trial's mapped coarse
    row from the exact kernel's integer offsets: ``(ndm, nchan)``."""
    from .fdmt import fdmt_plan, fdmt_tracks, fdmt_trial_dms
    from .plan import dedispersion_shifts_batch, normalize_shifts
    from .search import nearest_rows

    trial_dms = np.asarray(trial_dms, dtype=np.float64)
    fdmt_dms, n_lo, n_hi = fdmt_trial_dms(
        nchan, float(trial_dms.min()), float(trial_dms.max()), start_freq,
        bandwidth, sample_time)
    plan = fdmt_plan(nchan, float(start_freq), float(bandwidth), n_hi, n_lo)
    tracks = fdmt_tracks(plan)[:, :nchan]
    idx = nearest_rows(fdmt_dms, trial_dms)

    shifts = dedispersion_shifts_batch(trial_dms, nchan, start_freq,
                                       bandwidth, sample_time)
    exact = normalize_shifts(shifts, nsamples).astype(np.int64)
    dev = (tracks[idx] % nsamples) - exact
    # wrap to signed: a track and an offset that agree mod T are the
    # same gather; centre the deviation on the dominant branch
    return (dev + nsamples // 2) % nsamples - nsamples // 2


@functools.lru_cache(maxsize=32)
def _retention_cached(nchan, dms_key, start_freq, bandwidth, sample_time,
                      nsamples, min_width, cert):
    trial_dms = np.frombuffer(dms_key, dtype=np.float64)
    dev = _track_deviations(nchan, trial_dms, start_freq, bandwidth,
                            sample_time, nsamples)
    rho = np.empty(len(trial_dms))
    for j in range(len(trial_dms)):
        if cert:
            rho[j] = _cert_retention_from_offsets(dev[j])
        else:
            rho[j] = _retention_from_offsets(dev[j], min_width=min_width)
    return rho


def coarse_retention(nchan, trial_dms, start_freq, bandwidth, sample_time,
                     nsamples, min_width=1):
    """Per-trial worst-case ``coarse_snr / exact_snr`` retention (block
    detection scorer on both sides).

    Computed exactly from the transform's merge tables (no data, no
    noise); see the module docstring for the signal model.  ``min_width``
    is the narrowest pulse width (samples) the bound must cover — wider
    pulses always retain more, so 1 is fully conservative.  This is the
    quantity that justifies (and per-config recalibrates)
    ``search.HYBRID_COARSE_TRUST``.

    Returns a ``(ndm,)`` float array in ``(0, 1]``.
    """
    trial_dms = np.ascontiguousarray(trial_dms, dtype=np.float64)
    return _retention_cached(int(nchan), trial_dms.tobytes(),
                             float(start_freq), float(bandwidth),
                             float(sample_time), int(nsamples),
                             int(min_width), False)


def cert_retention(nchan, trial_dms, start_freq, bandwidth, sample_time,
                   nsamples):
    """Per-trial worst-case ``cert_score / exact_snr`` retention (the
    sliding certificate scorer as numerator — phase-invariant, so much
    tighter than :func:`coarse_retention` at the same track scatter:
    ~0.6 vs ~0.44 at the benchmark config).  Returns ``(ndm,)``."""
    trial_dms = np.ascontiguousarray(trial_dms, dtype=np.float64)
    return _retention_cached(int(nchan), trial_dms.tobytes(),
                             float(start_freq), float(bandwidth),
                             float(sample_time), int(nsamples), 1, True)


def retention_bound(nchan, trial_dms, start_freq, bandwidth, sample_time,
                    nsamples, min_width=1, cert=False):
    """``min`` over trials of :func:`coarse_retention` (or
    :func:`cert_retention` with ``cert=True``) — the single per-config
    constant the hybrid's margin and certificate use."""
    fn = cert_retention if cert else functools.partial(coarse_retention,
                                                       min_width=min_width)
    return float(fn(nchan, trial_dms, start_freq, bandwidth, sample_time,
                    nsamples).min())


def fused_cert_params(nchan, trial_dms, start_freq, bandwidth, sample_time,
                      nsamples, snr_floor=None, rho_cert=None,
                      cert_slack=None):
    """The ``(rho, slack, floor)`` float32 runtime operand of the fused
    hybrid programs — ONE place constructs it so the single-device
    (``ops/search.py:_fused_hybrid_seed_kernel``) and mesh
    (``parallel/sharded_fdmt.py``) fused kernels share the need stage's
    contract: ``rho = +inf`` disables the device's cert terms (the
    consistency guards still fire), ``floor = +inf`` disables the floor
    terms.  ``rho_cert=None`` computes the retention bound — the same
    lru-cached computation :func:`~..ops.search.hybrid_certificate_gate`
    performs, under the same ``search/cert_floor`` budget bucket so a
    cache miss cannot hide inside the fused dispatch.
    """
    from ..utils.logging_utils import budget_bucket

    if rho_cert is False:
        rho_val = np.inf
    elif rho_cert is not None:
        rho_val = float(rho_cert)
    else:
        with budget_bucket("search/cert_floor"):
            rho_val = retention_bound(nchan, trial_dms, start_freq,
                                      bandwidth, sample_time, nsamples,
                                      cert=True)
    slack_val = (HYBRID_CERT_SLACK if cert_slack is None
                 else float(cert_slack))
    floor_val = np.inf if snr_floor is None else float(snr_floor)
    return np.asarray([rho_val, slack_val, floor_val], np.float32)


def certify_noise_only(cert_scores, snr_floor, rho_cert_min,
                       coarse_snrs=None, slack=None):
    """True iff the coarse sweep certifies no pulse reaches ``snr_floor``
    (under the stated impulsive-signal model, up to the Gaussian noise
    cross-term the ``slack`` absorbs — see the module docstring's *Miss
    risk* section for the residual probability).

    The certificate inequality: an impulsive signal with exact S/N ``s``
    shows a sliding certificate score ``>= rho_cert_min * s - slack``
    (up to the cross-term); when every trial's certificate score sits
    below ``rho_cert_min * snr_floor - slack``, no trial's exact S/N
    reaches the floor.  ``slack`` defaults to :data:`HYBRID_CERT_SLACK`;
    derive it from a target miss probability with
    :func:`cert_slack_for_miss_p`.

    ``coarse_snrs`` (the block detection scores), when given, add a
    consistency guard: a chunk whose coarse BLOCK score already reaches
    the floor is never certified, whatever the sliding scores say.  For
    impulsive signals the sliding capture dominates and the guard is
    redundant; for non-impulsive junk (e.g. a single-sample spike
    flanked by negative dips after aggressive RFI filtering — outside
    the signal model) it prevents the absurd state of a chunk counted
    signal-free while its own table shows an above-floor score.
    """
    if snr_floor is None:
        return False
    if slack is None:
        slack = HYBRID_CERT_SLACK
    threshold = rho_cert_min * float(snr_floor) - float(slack)
    ok = bool(np.max(cert_scores) < threshold)
    if ok and coarse_snrs is not None:
        ok = bool(np.max(coarse_snrs) < float(snr_floor))
    return ok


def certifiable_snr_floor(nsamples, ndm, rho_cert_min, margin=0.75,
                          slack=None):
    """The smallest detection floor whose noise certificate actually
    fires on typical signal-free chunks of this geometry.

    The certificate threshold ``rho * floor - slack`` must clear the
    chunk's expected signal-free certificate-score maximum (plus
    ``margin`` Gumbel spread); below this floor the certificate is still
    *valid* but never triggers, and the hybrid pays the full
    exact-argbest localisation on every chunk.  ``slack`` defaults to
    :data:`HYBRID_CERT_SLACK`; a slack derived from a stricter miss
    probability (:func:`cert_slack_for_miss_p`) raises the floor
    proportionally.
    """
    if slack is None:
        slack = HYBRID_CERT_SLACK
    ceiling = expected_noise_max_snr(nsamples, ndm) + float(margin)
    return (ceiling + float(slack)) / float(rho_cert_min)


# ---------------------------------------------------------------------------
# Matched detection floors for long chunks
# ---------------------------------------------------------------------------

def expected_noise_max_snr(nsamples, ndm=1):
    """Expected maximum certificate score of a signal-free chunk.

    Gumbel location for an effective count ``m = 6 * nsamples * ndm``.
    The multiplier was FIT to seeded half-normal-noise simulation of the
    full hybrid coarse+cert scorer; it bundles the sliding-window
    multiplicity, the boxcar family, and the noise skew.  The Gumbel
    scale is ``1 / sqrt(2 ln m)`` (~0.15-0.19 at these sizes), so
    chunk-to-chunk maxima spread by a few tenths.

    FIT DOMAIN (extrapolate with care): half-normal iid noise after the
    pipeline's renormalisation, T = 4k-32k, ndm ~ 60-300 (original fit
    T = 8k/16k/32k x 154 trials, measured means 5.17/5.21/5.40 vs this
    formula's 5.16/5.28/5.41; re-validated in
    ``tests/test_certify.py::TestNoiseCeiling`` at a second trial count).
    Outside it — strongly correlated channels after aggressive RFI
    cleaning, non-Gaussian residuals, very large ndm — the effective
    count ``m`` drifts and the location can be off by a few tenths;
    ``snr_threshold="auto"`` additionally clamps to the reference's 6.0
    floor so small chunks never resolve below the reference default.
    """
    m = 6.0 * float(nsamples) * max(1.0, float(ndm))
    a = np.sqrt(2.0 * np.log(m))
    return float(a - (np.log(np.log(m)) + np.log(4.0 * np.pi)) / (2.0 * a))


def matched_snr_floor(nsamples, ndm=1, margin=1.0):
    """A detection floor matched to the chunk's noise statistics.

    ``expected_noise_max_snr + margin``: the same "clearly above the
    noise maximum" philosophy as the reference's fixed ``snr > 6``
    (tuned for its ~1e3-sample chunks), adapted to the chunk geometry.
    ``margin = 1.0`` puts the per-chunk false-alarm probability at the
    sub-percent level (Gumbel scale ``1/sqrt(2 ln m)`` ~ 0.19 at 2^20
    samples).
    """
    return expected_noise_max_snr(nsamples, ndm) + float(margin)
