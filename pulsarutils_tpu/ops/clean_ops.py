"""Data conditioning and RFI excision ops.

Capability-equivalents of the reference's array-level cleaning layer
(``pulsarutils/clean.py:58-133,183-189``), written as pure functions that
run identically under NumPy and ``jax.numpy`` (all jit-compatible: static
shapes, ``where`` instead of boolean fancy-indexing).

Components and their reference counterparts:

* :func:`get_noisier_channels`  <- ``clean.py:58-67``
* :func:`renormalize_data`      <- ``clean.py:70-111`` (with the
  ``cut_outliers`` accumulation bug fixed: the reference computed
  ``bad_bins`` per window but only applied the last window's mask,
  ``clean.py:93-105``; here every window's outliers are cut)
* :func:`measure_channel_variability` <- ``clean.py:114-133`` (with the
  quartile indices taken over the *good*-channel count — the reference
  indexed the filtered array with full-size indices, an out-of-bounds
  hazard when many channels are masked)
* :func:`fft_zap_time` — FFT-domain periodic-RFI mask (the "FFT mask"
  stage of benchmark config 3); no direct reference counterpart, the
  reference's excision is purely spectral-statistics based.

The smoothing primitives (:func:`gaussian_filter_1d`,
:func:`uniform_filter_1d`) reproduce ``scipy.ndimage`` semantics
(reflect/"symmetric" boundary, ``truncate=4`` Gaussian radius) so the NumPy
path matches the reference's scipy calls while the same code jits on TPU.
"""

from __future__ import annotations

import numpy as np

from .robust import mad, median_filter_1d, ref_mad


# ---------------------------------------------------------------------------
# scipy.ndimage-equivalent smoothing primitives (backend-generic)
# ---------------------------------------------------------------------------

def _symmetric_pad_1d(x, left, right, xp):
    """'reflect' boundary of scipy.ndimage (edge value repeated)."""
    if left == 0 and right == 0:
        return x
    n = x.shape[0]
    left = min(left, n)
    right = min(right, n)
    return xp.concatenate([x[:left][::-1], x, x[n - right:][::-1]])


def _convolve_valid(padded, kernel, xp):
    """``convolve(padded, kernel, mode='valid')`` with a TPU-safe jax path.

    ``xp.convolve`` lowers to ``conv_general_dilated``; at awkward
    lengths (e.g. 120000-sample chunks) XLA:TPU's convolution tiling
    compiles pathologically (observed: minutes to never).  The jax path
    therefore runs the convolution in the Fourier domain at a
    power-of-two size — deterministic compile, exact same 'valid' slice.
    """
    kernel = xp.asarray(kernel, dtype=float)
    if xp is np:
        return np.convolve(padded, kernel, mode="valid")
    n = int(padded.shape[0])
    k = int(kernel.shape[0])
    m = n + k - 1
    size = 1 << int(np.ceil(np.log2(max(m, 2))))
    full = xp.fft.irfft(xp.fft.rfft(padded, n=size)
                        * xp.fft.rfft(kernel, n=size), n=size)
    return full[k - 1:n]


def gaussian_filter_1d(x, sigma, truncate=4.0, xp=np):
    """Gaussian smoothing matching ``scipy.ndimage.gaussian_filter1d``
    (mode='reflect', radius ``int(truncate * sigma + 0.5)``)."""
    x = xp.asarray(x, dtype=float)
    radius = int(truncate * float(sigma) + 0.5)
    if radius == 0:
        return x
    # kernel built host-side: sigma is a static configuration value
    kx = np.arange(-radius, radius + 1)
    kernel = np.exp(-0.5 * (kx / float(sigma)) ** 2)
    kernel = kernel / kernel.sum()
    # scipy clips the requested radius to the array length via reflection;
    # for radius >= n repeat the symmetric extension until long enough
    padded = x
    left = right = radius
    while left > 0 or right > 0:
        n = padded.shape[0]
        take_l, take_r = min(left, n), min(right, n)
        padded = _symmetric_pad_1d(padded, take_l, take_r, xp)
        left, right = left - take_l, right - take_r
    return _convolve_valid(padded, kernel, xp)


def uniform_filter_1d(x, size, xp=np):
    """Boxcar mean matching ``scipy.ndimage.uniform_filter1d``
    (mode='reflect', window centred with left-bias for even sizes)."""
    x = xp.asarray(x, dtype=float)
    size = int(size)
    if size <= 1:
        return x
    left = size // 2
    right = size - 1 - left
    padded = _symmetric_pad_1d(x, left, right, xp)
    kernel = np.full(size, 1.0 / size)
    return _convolve_valid(padded, kernel, xp)


# ---------------------------------------------------------------------------
# Channel flagging
# ---------------------------------------------------------------------------

def _masked_channel_mean(array, good, xp):
    """Per-sample mean over the good channels (shared by the cleaners)."""
    ngood = xp.maximum(good.sum(), 1)
    return xp.where(good[:, None], array, 0.0).sum(axis=0) / ngood


def zero_dm_filter(array, badchans_mask=None, xp=np):
    """Subtract the per-sample mean over (good) channels — the classic
    "zero-DM" broadband-RFI filter (Eatough, Keane & Lyne 2009).

    Terrestrial interference arrives un-dispersed, so it sits at DM 0:
    removing the channel-averaged time series cancels it while a
    dispersed pulse (spread across samples per channel) loses only
    ``~nchan_occupied/nchan`` of its power.  No reference counterpart —
    the reference's excision is purely spectral-statistics based
    (``stats.py``/``clean.py``); this complements it for impulsive
    broadband RFI.  Pure / jit-compatible.
    """
    array = xp.asarray(array)
    nchan = array.shape[0]
    if badchans_mask is None:
        badchans_mask = xp.zeros(nchan, dtype=bool)
    good = ~xp.asarray(badchans_mask)
    mean_t = _masked_channel_mean(array, good, xp)
    return xp.where(good[:, None], array - mean_t[None, :], array)


def get_noisier_channels(array, medfilt_size=7, nsigma=5.0, xp=np):
    """Flag channels whose mean lies above a median-filtered bandpass by
    ``nsigma`` reference-MADs (reference ``clean.py:58-67``)."""
    array = xp.asarray(array)
    spec = array.mean(axis=1)
    smooth = median_filter_1d(spec, medfilt_size, xp=xp)
    sigma = ref_mad(spec, xp=xp)
    return spec > smooth + nsigma * sigma


def measure_channel_variability(array, badchans_mask=None, xp=np):
    """Flag channels whose time-std falls outside robust quartile fences:
    ``[q2 - 2(q2 - q1), q2 + 2(q3 - q2)]`` (reference ``clean.py:114-133``).

    jit-friendly: already-bad channels are pushed to +inf before sorting and
    the quartile indices are computed from the good-channel count.
    """
    array = xp.asarray(array)
    nchan = array.shape[0]
    if badchans_mask is None:
        badchans_mask = xp.zeros(nchan, dtype=bool)
    spec = xp.std(array, axis=1)
    spec_for_sort = xp.where(badchans_mask, xp.inf, spec)
    ordered = xp.sort(spec_for_sort)
    ngood = (~badchans_mask).sum()
    q1 = ordered[ngood // 4]
    q2 = ordered[ngood // 2]
    q3 = ordered[ngood // 4 * 3]
    lowlim = q2 - 2 * (q2 - q1)
    hilim = q2 + 2 * (q3 - q2)
    return (spec < lowlim) | (spec > hilim) | badchans_mask


# ---------------------------------------------------------------------------
# Renormalisation / conditioning
# ---------------------------------------------------------------------------

def renormalize_data(array, badchans_mask=None, baseline_window=101,
                     cut_outliers=False, xp=np):
    """Condition a filterbank chunk for searching.

    Reference semantics (``clean.py:70-111``):

    1. flatten the time baseline: divide out the Gaussian-smoothed mean
       lightcurve of the good channels (window clipped to
       ``nsamples // 100 * 2 + 1``);
    2. per-channel bandpass normalisation to fractional deviation
       ``(x - mean_c) / mean_c``;
    3. zero the bad channels;
    4. optionally zero time bins where the boxcar-smoothed mean lightcurve
       exceeds +5 sigma or dips below -3 sigma at *any* boxcar width
       1,2,4,8,16 (the reference only applied the width-16 mask —
       fixed here, see module docstring).

    Pure function; jit-compatible for fixed shapes and flags.
    """
    array = xp.asarray(array).astype(float)
    nchan, nsamples = array.shape
    if badchans_mask is None:
        badchans_mask = xp.zeros(nchan, dtype=bool)
    badchans_mask = xp.asarray(badchans_mask)
    good = ~badchans_mask

    lc = _masked_channel_mean(array, good, xp)
    window = min(int(baseline_window), nsamples // 100 * 2 + 1)
    lc_smooth = gaussian_filter_1d(lc, window, xp=xp)
    lc_smooth = xp.where(lc_smooth == 0, 1.0, lc_smooth)
    factor = xp.median(lc_smooth) / lc_smooth
    renorm = array * factor[None, :]

    spec = renorm.mean(axis=1)
    denom = xp.where(spec == 0, 1.0, spec)
    renorm = (renorm - spec[:, None]) / denom[:, None]

    renorm = xp.where(badchans_mask[:, None], 0.0, renorm)

    if cut_outliers:
        lc = renorm.mean(axis=0)
        bad_bins = xp.zeros(nsamples, dtype=bool)
        for wpow in range(5):
            window = 1 << wpow
            lc_reb = uniform_filter_1d(lc, window, xp=xp)
            sigma = xp.std(lc_reb[::window])
            bad_bins = bad_bins | (lc_reb > 5 * sigma) | (lc_reb < -3 * sigma)
        renorm = xp.where(bad_bins[None, :], 0.0, renorm)

    return renorm


# ---------------------------------------------------------------------------
# FFT-domain RFI mask
# ---------------------------------------------------------------------------

def fft_zap_time(array, nsigma=5.0, protect_dc=1, xp=np):
    """Excise *periodic* broadband RFI in the Fourier domain.

    rFFT each channel over time, form the channel-averaged power spectrum,
    flag Fourier bins whose log-power exceeds a running-median + MAD
    threshold, null those bins in every channel, inverse transform.

    Returns ``(cleaned_array, zapped_bins_mask)``.  This is the "FFT mask"
    stage of benchmark config 3 (``BASELINE.json``); the reference package
    has no Fourier-domain excision — its cleaning is purely spectral-stats
    based — so this op is an extension, not a parity item.

    jit-compatible (fixed shapes; threshold via ``where``).
    """
    array = xp.asarray(array, dtype=float)
    spec = xp.fft.rfft(array, axis=1)
    power = (xp.abs(spec) ** 2).mean(axis=0)
    logp = xp.log(power + 1e-30)
    baseline = median_filter_1d(logp, 11, xp=xp)
    sigma = mad(logp - baseline, xp=xp)
    zap = logp > baseline + nsigma * sigma
    if protect_dc:
        keep = xp.arange(zap.shape[0]) < protect_dc
        zap = zap & ~keep
    cleaned = xp.fft.irfft(xp.where(zap[None, :], 0.0, spec), n=array.shape[1],
                           axis=1)
    return cleaned, zap
