"""VMEM-resident fused head for the FDMT: the first ~7 tree levels in
ONE Pallas kernel, intermediate states never touching HBM.

Why: the per-level merge kernel is HBM-bound — every tree level writes
its full state and the next reads it back (plus halo), ~100 GB of
traffic for the 1M-sample benchmark transform, measured at ~40% of the
chip's bandwidth (``docs/performance.md`` round 2: 0.35 s vs the 0.15 s
traffic bound).  The EARLY levels are 75% of that traffic (row counts
shrink slowly: 1023, 767, 639, ... for the benchmark plan) *and* they
are channel-local: level ``l`` only ever combines rows within
``2^(l+1)``-channel bands.  So the first ``HEAD_LEVELS`` levels split
into independent 128-channel groups whose whole sub-tree state
(~260 live rows x a few-thousand-sample slice) fits VMEM:

* grid = (channel groups, time slices);
* each step stitches its input slice (+ the head's cumulative shift
  halo) into a VMEM buffer, runs all head levels ping-pong between two
  VMEM scratch buffers, and writes only the LAST head level's rows to
  HBM — one read of the input + one write of the head output instead of
  ~4 HBM passes per level;
* per-row shifted reads reuse the aligned-load + lane-rotate + blend
  primitive of the dedispersion kernel
  (:func:`~pulsarutils_tpu.ops.pallas_dedisperse.shifted_row_tile`);
  merge tables ride scalar prefetch exactly like the per-level kernel.

The deep levels (large shifts, few rows) stay on the existing
per-level kernel: their halos are too wide for VMEM residency and they
carry only ~25% of the traffic.

Numerics: the fused head performs the SAME adds in the SAME order as
the per-level path (each level's partial sums are identical floats,
merely held in VMEM) — outputs are bit-identical, pinned by
``tests/test_fdmt_resident.py``.

Time-axis convention: circular mod T via slice-modulo staggered
``BlockSpec``s (``t_slice`` divides T), the same trick as every other
kernel in this package.
"""

from __future__ import annotations

import functools

import numpy as np

#: tree levels fused into the VMEM-resident head; 2^HEAD_LEVELS channels
#: per independent group (128 -> ~260 live rows per group, ~5 MB VMEM)
HEAD_LEVELS = 7

#: default time-slice (samples); must divide T and hold the head halo
HEAD_T_SLICE = 2048

#: lane width of the chunked-row layout (one (8, L) chunk = 2048 samples).
#: 256 lanes keep the per-row vector ops wide (the first cut used 128 and
#: measured SLOWER than the per-level kernel: 8x narrower ops than its
#: (8, 1024) tiles drowned the HBM win in instruction overhead); it also
#: lets every head-level shifted read take the static-base fast path —
#: all head-level shifts are < L by eligibility, so the 16-row load base
#: is static and no dynamic sublane rotate is ever issued.
_L = 256
_CHUNK = 8 * _L

#: rows per fori_loop iteration of the head kernel.  The scalar core's
#: per-iteration overhead (loop control + dynamic address formation)
#: dominated the un-unrolled kernel (~110 ns/row vs ~20 ns of vector
#: work -> 0.53 s, SLOWER than the per-level path's 0.37 s); unrolling
#: by 8 amortises it and flips the comparison (0.32 s measured, v5e
#: 1024 x 1M benchmark); 16 regresses hard (4.2 s measured — register
#: pressure/spill pathology), so 8 is pinned.
_ROW_UNROLL = 8


def _pad_stack(arrs, rows_max):
    """Stack per-group 1-D tables padded (repeat last entry) to rows_max."""
    out = np.empty((len(arrs), rows_max), np.int32)
    for g, a in enumerate(arrs):
        a = np.asarray(a, np.int32)
        if len(a) == 0:
            raise ValueError("empty group table")
        out[g, :len(a)] = a
        out[g, len(a):] = a[-1]
    return out


class HeadPlan:
    """Static per-group merge schedule for the fused head.

    Built from an :class:`~pulsarutils_tpu.ops.fdmt.FdmtPlan`: the first
    ``n_levels`` iterations' flat tables are re-based to each
    ``2^n_levels``-channel group's own input-row window and padded to the
    per-level max row count over groups (padded rows repeat the last
    real row — they compute junk that nothing references and that is
    sliced off host-side).
    """

    def __init__(self, plan, n_levels=HEAD_LEVELS):
        chan_group = 1 << n_levels
        nchp = plan.nchan_padded
        if nchp < chan_group or len(plan.iterations) < n_levels:
            raise ValueError(
                f"head needs nchan_padded >= {chan_group} and >= "
                f"{n_levels} iterations")
        self.n_levels = n_levels
        self.n_groups = nchp // chan_group
        self.rows_in = chan_group

        self.tables = []       # per level: group-local padded tables
        self.rows_out = []     # per level: padded (max) rows per group
        # per-input-band start rows; level 0's input bands are the raw
        # channels themselves (one row each)
        in_offsets = np.arange(nchp + 1)
        for lev in range(n_levels):
            it = plan.iterations[lev]
            nd = np.asarray(it["ndelay"])
            out_offsets = np.concatenate([[0], np.cumsum(nd)])
            n_bands_in = len(in_offsets) - 1
            n_bands_out = len(nd)
            bpg_in = n_bands_in // self.n_groups
            bpg_out = n_bands_out // self.n_groups
            assert bpg_out * self.n_groups == n_bands_out, (lev, n_bands_out)
            ils, ihs, ss, shs, counts = [], [], [], [], []
            for g in range(self.n_groups):
                r0 = out_offsets[g * bpg_out]
                r1 = out_offsets[(g + 1) * bpg_out]
                in_start = int(in_offsets[g * bpg_in])
                in_end = int(in_offsets[(g + 1) * bpg_in])
                il = it["idx_low"][r0:r1] - in_start
                ih = it["idx_high"][r0:r1] - in_start
                # bands merge strictly within the group: group-local
                # indices must land inside the group's input window
                assert il.min() >= 0 and ih.min() >= 0, (lev, g)
                assert max(il.max(), ih.max()) < in_end - in_start, (lev, g)
                ils.append(il)
                ihs.append(ih)
                ss.append(it["shift"][r0:r1])
                shs.append(it["shift_high"][r0:r1]
                           if it["shift_high"] is not None
                           else np.zeros(r1 - r0, np.int32))
                counts.append(int(r1 - r0))
            # padded to the row-loop unroll factor (amortises the
            # scalar loop/address overhead per iteration)
            rows_max = -(-max(counts) // _ROW_UNROLL) * _ROW_UNROLL
            self.rows_out.append(rows_max)
            self.tables.append({
                "idx_low": _pad_stack(ils, rows_max),
                "idx_high": _pad_stack(ihs, rows_max),
                "shift": _pad_stack(ss, rows_max),
                "shift_high": _pad_stack(shs, rows_max),
                "counts": np.asarray(counts),
                "leaf": it["shift_high"] is not None,
            })
            in_offsets = out_offsets[::bpg_out]
        self.rows_valid = self.tables[-1]["counts"]  # real final counts
        self.row_starts = np.concatenate(
            [[0], np.cumsum(self.rows_valid)])[:-1]
        self.rows_total = int(self.rows_valid.sum())
        #: cumulative worst-case shift a sample travels through the head
        self.max_shift_per_level = [
            int(t["shift"].max(initial=0)) for t in self.tables]
        self.max_shift_per_level[0] = max(
            self.max_shift_per_level[0],
            int(self.tables[0]["shift_high"].max(initial=0)))
        self.halo = int(sum(self.max_shift_per_level))

    def remaining_halo(self, lev):
        """Cumulative max shift applied at levels ``lev..end`` — how far
        past ``t_slice`` level ``lev``'s INPUT must stay valid."""
        return int(sum(self.max_shift_per_level[lev:]))


@functools.lru_cache(maxsize=8)
def _head_plan_cached(nchan, start_freq, bandwidth, max_delay, min_delay,
                      n_levels):
    from .fdmt import fdmt_plan

    return HeadPlan(fdmt_plan(nchan, start_freq, bandwidth, max_delay,
                              min_delay), n_levels)


#: VMEM budget (bytes) for the head's two ping-pong scratch buffers —
#: the chip's ~16 MB VMEM minus headroom for the small DMA staging and
#: compiler temporaries (t_slice = 8192 at the benchmark plan lands at
#: 12.6 MB; 16384 would need 21 MB and is rejected)
_VMEM_BUDGET = 14 << 20


def _head_geometry(head, t_slice):
    """Derived sizes for one (plan, t_slice): chunks allocated per step
    and the scratch rows — shared by the builder and the slice chooser."""
    # level-0 input must stay valid over t_slice + halo; +1 chunk so the
    # 16-row shifted loads (8 rows past a chunk's base) never run off
    chunks_alloc = -(-(t_slice + head.halo) // _CHUNK) + 1
    rows_buf = max([head.rows_in] + head.rows_out)
    return chunks_alloc, rows_buf


def pick_head_t_slice(head, t):
    """Largest power-of-two time slice whose scratch fits VMEM.

    Bigger slices amortise the head's halo recompute (every non-final
    level computes ``ceil((t_slice + halo)/CHUNK)`` chunks for
    ``t_slice/CHUNK`` useful ones: 2-for-1 at 2048 with the benchmark's
    148-sample halo, 5-for-4 at 8192) and cut the per-step grid
    overhead — measured 0.232 s -> 0.146 s head-only at the 1024 x 1M
    benchmark.  The ceiling is the two ping-pong buffers' VMEM
    footprint (:data:`_VMEM_BUDGET`); the floor is the eligibility
    t_slice (:data:`HEAD_T_SLICE`), which callers have already checked
    divides T.
    """
    for t_slice in (16384, 8192, 4096, 2048):
        if t_slice < HEAD_T_SLICE or t % t_slice or t_slice % _CHUNK:
            continue
        if head.halo > (2 * t_slice) // 3:
            continue
        chunks_alloc, rows_buf = _head_geometry(head, t_slice)
        if 2 * rows_buf * chunks_alloc * _CHUNK * 4 <= _VMEM_BUDGET:
            return t_slice
    return HEAD_T_SLICE


@functools.lru_cache(maxsize=8)
def _build_head_kernel(nchan, start_freq, bandwidth, max_delay, min_delay,
                       n_levels, t, t_slice, interpret):
    """Compile the fused-head pallas program for one (plan, T) config.

    I/O is MANUAL DMA (``ANY``-space operands + ``make_async_copy``)
    rather than pipelined BlockSpecs: the pipelined form double-buffers
    ``k_in`` whole input slices in VMEM, which at t_slice > 2048 blew
    the ~16 MB VMEM (measured: every (t_slice >= 4096 | levels >= 8)
    combination failed to compile).  Manual copies stage exactly the
    ``chunks_alloc`` chunks a step needs, un-double-buffered — the DMA
    is ~microseconds against a ~200 us compute step, so the lost
    overlap is noise and the freed VMEM buys the big-slice win
    (:func:`pick_head_t_slice`).  The circular wrap is handled by
    statically-unrolled per-step copy segments (DMA shapes must be
    static; only the last few steps wrap and each split is a
    compile-time constant).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    head = _head_plan_cached(nchan, start_freq, bandwidth, max_delay,
                             min_delay, n_levels)
    assert t % t_slice == 0 and t_slice % _CHUNK == 0
    # the static-base fast path requires every level's shift < one lane
    # row (head_supported enforces it; belt and braces here)
    assert max(head.max_shift_per_level) < _L, head.max_shift_per_level
    n_slices = t // t_slice
    cpb = t_slice // _CHUNK          # (8, L) chunks per slice
    chunks_alloc, rows_buf = _head_geometry(head, t_slice)
    r_alloc = chunks_alloc * 8
    c8 = n_slices * cpb * 8          # time axis in 8-row units
    rows_final = head.rows_out[-1]

    grid = (head.n_groups, n_slices)

    n_chunks_out = [-(-(t_slice + head.remaining_halo(lev + 1)) // _CHUNK)
                    for lev in range(n_levels)]
    n_chunks_out[-1] = cpb  # the head's output is exactly the slice

    def kernel(*args):
        # scalar prefetch: 4 tables per level, each (n_groups, rows_max)
        tabs = args[:4 * n_levels]
        data_hbm = args[4 * n_levels]       # (rows, c8, L) in ANY space
        out_hbm = args[4 * n_levels + 1]    # (G*rows_final, c8, L) in ANY
        buf_a, buf_b, sem_in, sem_out = args[4 * n_levels + 2:]

        g = pl.program_id(0)
        i_s = pl.program_id(1)
        lane = jax.lax.broadcasted_iota(jnp.int32, (8, _L), 1)

        # stage this step's input window straight into the level-0
        # buffer; un-overlapped: the copies are ~us against a ~200 us
        # compute step.  DMA shapes must be static, so the circular
        # wrap is handled by per-step static segment lists: only the
        # last few steps wrap, and each such step's (dst, src, size)
        # split is a compile-time constant — no padded copy of the
        # 4 GB input (a device-side pad doubled input HBM and OOMed
        # the 1M benchmark).
        def stage(step, segs):
            @pl.when(i_s == step)
            def _():
                for dst_off, src_off, size in segs:
                    c = pltpu.make_async_copy(
                        data_hbm.at[pl.ds(g * head.rows_in, head.rows_in),
                                    pl.ds(src_off, size)],
                        buf_a.at[pl.ds(0, head.rows_in),
                                 pl.ds(dst_off, size)],
                        sem_in)
                    c.start()
                    c.wait()

        def segments(start):
            segs, p = [], 0
            while p < r_alloc:
                src = (start + p) % c8
                size = min(r_alloc - p, c8 - src)
                segs.append((p, src, size))
                p += size
            return segs

        n_wrap = min(n_slices,
                     -(-(r_alloc - cpb * 8) // (cpb * 8)))
        for w in range(n_wrap):
            step = n_slices - 1 - w
            stage(step, segments(step * cpb * 8))

        if n_slices > n_wrap:
            # generic branch: steps whose window stays in-bounds (dead
            # -- and structurally oversized -- when the window laps the
            # whole axis, so emitted only when some step qualifies)
            @pl.when(i_s < n_slices - n_wrap)
            def _():
                c = pltpu.make_async_copy(
                    data_hbm.at[pl.ds(g * head.rows_in, head.rows_in),
                                pl.ds(i_s * cpb * 8, r_alloc)],
                    buf_a.at[pl.ds(0, head.rows_in), pl.ds(0, r_alloc)],
                    sem_in)
                c.start()
                c.wait()

        def shifted_chunk(src, row, c, s):
            """``src[row, c*CHUNK + s : +CHUNK]`` as an (8, L) tile.

            Every head shift is < L (eligibility), so the 16-row load
            base ``c*8`` is STATIC — one aligned load, one dynamic
            lane-rotate, one two-row blend; no dynamic sublane rotate
            (the same q0 specialisation as the dedispersion kernel).
            """
            rows16 = src[row, pl.ds(c * 8, 16), :]
            rolled = pltpu.roll(rows16, (_L - s) % _L, 1)
            return jnp.where(lane < _L - s, rolled[0:8], rolled[1:9])

        src, dst = buf_a, buf_b
        for lev in range(n_levels):
            il_t, ih_t, s_t, sh_t = tabs[4 * lev:4 * lev + 4]
            leaf = head.tables[lev]["leaf"]
            nco = n_chunks_out[lev]

            def row_body(rb, _, il_t=il_t, ih_t=ih_t, s_t=s_t, sh_t=sh_t,
                         leaf=leaf, nco=nco, src=src, dst=dst):
                # row unroll: one loop iteration's scalar overhead
                # (control flow + dynamic address formation) amortised
                # over _ROW_UNROLL rows of vector work
                for dr in range(_ROW_UNROLL):
                    r = rb * _ROW_UNROLL + dr
                    il = il_t[g, r]
                    ih = ih_t[g, r]
                    s = s_t[g, r]
                    for c in range(nco):
                        low = shifted_chunk(src, il, c, s)
                        if leaf:
                            high = shifted_chunk(src, ih, c, sh_t[g, r])
                        else:
                            high = src[ih, pl.ds(c * 8, 8), :]
                        dst[r, pl.ds(c * 8, 8), :] = low + high
                return 0

            jax.lax.fori_loop(0, head.rows_out[lev] // _ROW_UNROLL,
                              row_body, 0)
            src, dst = dst, src

        # the final level landed in `src` (post-swap): one DMA out
        copy_out = pltpu.make_async_copy(
            src.at[pl.ds(0, rows_final), pl.ds(0, cpb * 8)],
            out_hbm.at[pl.ds(g * rows_final, rows_final),
                       pl.ds(i_s * cpb * 8, cpb * 8)],
            sem_out)
        copy_out.start()
        copy_out.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4 * n_levels,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((rows_buf, r_alloc, _L), jnp.float32),
            pltpu.VMEM((rows_buf, r_alloc, _L), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    call = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (head.n_groups * rows_final, c8, _L), jnp.float32),
        interpret=bool(interpret))

    flat_tabs = []
    for tab in head.tables:
        flat_tabs += [jnp.asarray(tab[k]) for k in
                      ("idx_low", "idx_high", "shift", "shift_high")]

    # host-side reassembly index: global level-n row -> (group, local row)
    gather_g = np.concatenate(
        [np.full(c, g) for g, c in enumerate(head.rows_valid)])
    gather_r = np.concatenate(
        [np.arange(c) for c in head.rows_valid])

    def run(data):
        # traceable (un-jitted) so the whole-transform jit can inline it
        data3 = data.reshape(data.shape[0], c8, _L)
        out = call(*flat_tabs, data3)
        # (G*rows_max, c8, L) -> (rows_total, t)
        out = out.reshape(head.n_groups, rows_final, t)
        return out[jnp.asarray(gather_g), jnp.asarray(gather_r)]

    return run, head


def head_transform(data, max_delay, start_freq, bandwidth, min_delay=0,
                   n_levels=HEAD_LEVELS, t_slice=None, interpret=None):
    """Run the fused head: raw (nchan, T) -> level-``n_levels`` state.

    Returns the same float32 rows the first ``n_levels`` per-level
    merges would produce (bit-identical), band-major.  The caller feeds
    this into the remaining per-level merges.
    """
    import jax
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    data = jnp.asarray(data, jnp.float32)
    nchan, t = data.shape
    if t_slice is None:
        t_slice = pick_head_t_slice(
            _head_plan_cached(nchan, float(start_freq), float(bandwidth),
                              int(max_delay), int(min_delay),
                              int(n_levels)), int(t))
    run, head = _build_head_kernel(
        nchan, float(start_freq), float(bandwidth), int(max_delay),
        int(min_delay), int(n_levels), int(t), int(t_slice),
        bool(interpret))
    if nchan < head.rows_in * head.n_groups:
        data = jnp.concatenate(
            [data, jnp.zeros((head.rows_in * head.n_groups - nchan, t),
                             jnp.float32)])
    return jax.jit(run)(data)


def head_supported(nchan_padded, n_iterations, t, t_slice=None,
                   halo=None, max_level_shift=None):
    """Static eligibility check shared with the transform integration."""
    t_slice = t_slice or HEAD_T_SLICE
    if nchan_padded < (1 << HEAD_LEVELS) or n_iterations <= HEAD_LEVELS:
        return False
    if t % t_slice or t_slice % _CHUNK:
        return False
    if halo is not None and halo > (2 * t_slice) // 3:
        return False  # halo-dominated slices waste the residency win
    if max_level_shift is not None and max_level_shift >= _L:
        return False  # static-base shifted reads need shifts < one row
    return True
