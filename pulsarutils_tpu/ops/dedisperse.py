"""Incoherent dedispersion kernels.

The hot op of the whole framework: circularly shift each frequency channel
by its DM delay and sum over channels.  Capability-equivalent of the
reference's numba trio ``roll_and_sum`` / ``_dedisperse`` / ``dedisperse``
(``pulsarutils/dedispersion.py:60-98``), re-designed for TPU:

* the in-place ``roll_and_sum`` accumulation contract becomes a pure
  functional gather+reduce — the shared-memory race class disappears;
* a whole *batch* of DM trials is dedispersed at once: the gather indices
  ``(t + shift[d, c]) mod T`` for a block of trials form a single
  ``take_along_axis`` that XLA fuses with the channel reduction, keeping the
  op HBM-bandwidth-bound instead of latency-bound;
* blocking over (trial, channel) keeps the gather workspace bounded so
  million-sample chunks stay resident in HBM.

Sign convention (pinned by tests, see reference ``dedispersion.py:94-98``):
``dedisperse(data, shifts)`` *negates* the shifts before rolling, i.e. it
computes ``out[t] = sum_c data[c, (t + shifts[c]) mod T]``, which undoes the
``+shifts`` roll the simulator applies (reference ``simulate.py:17-19``).
"""

from __future__ import annotations

import numpy as np

from .plan import normalize_shifts


# ---------------------------------------------------------------------------
# NumPy reference path (exact reference semantics, vectorised)
# ---------------------------------------------------------------------------

def roll_and_sum(array, sum_array, n):
    """Add ``np.roll(array, n)`` into ``sum_array`` in place.

    Kept for API parity with the reference's numba kernel
    (``pulsarutils/dedispersion.py:60-83``), including the in-place
    contract:

    >>> array = np.arange(10)
    >>> sum_array = np.zeros(10)
    >>> bool(np.allclose(roll_and_sum(array, sum_array, 3), np.roll(array, 3)))
    True
    >>> sum_array is roll_and_sum(array, sum_array, 3)
    True
    """
    t = len(sum_array)
    n = int(n) % t
    # np.roll(array, n)[i] = array[(i - n) mod t]: two slice-adds, no
    # temporary (the reference keeps this allocation-free for the same
    # reason, ``dedispersion.py:73-83``)
    sum_array[n:] += array[:t - n]
    sum_array[:n] += array[t - n:]
    return sum_array


def dedisperse(data, shifts):
    """Dedisperse one (nchan, nsamples) array at one DM's shifts (NumPy).

    ``out[t] = sum_c data[c, (t + shifts[c]) mod T]`` — the reference
    negates-then-normalises the shifts and rolls (``dedispersion.py:93-98``);
    here the same result is a single gather+reduce.
    """
    t = data.shape[1]
    sh = normalize_shifts(-np.asarray(shifts), t)
    idx = (np.arange(t)[None, :] - sh[:, None]) % t
    return np.take_along_axis(np.asarray(data), idx, axis=1).sum(axis=0)


def dedisperse_batch_numpy(data, shifts, out=None):
    """Dedisperse a batch of trials: ``shifts`` is ``(ndm, nchan)``.

    Returns the ``(ndm, T)`` dedispersed plane.  This is the single-core
    NumPy baseline the benchmark measures the TPU path against.
    """
    data = np.asarray(data)
    ndm = shifts.shape[0]
    nchan, t = data.shape
    if out is None:
        out = np.empty((ndm, t), dtype=np.float64)
    for d in range(ndm):
        # gather offsets: out[d, i] = sum_c data[c, (i + off[c]) mod t],
        # i.e. roll each channel by -off and accumulate — two slice-adds
        # per channel, no index arrays or temporaries (the naive
        # take_along_axis form materialises a (nchan, t) index + gather
        # pair per trial and is ~60x slower at the benchmark sizes)
        off = normalize_shifts(shifts[d], t)
        acc = out[d]
        acc[:] = 0.0
        for c in range(nchan):
            o = off[c]
            acc[:t - o] += data[c, o:]
            acc[t - o:] += data[c, :o]
    return out


def apply_dm_shifts_to_data(data, shifts, xp=np):
    """Roll each channel by ``-rint(shift)`` **without** summing.

    Used to display the dedispersed waterfall.  Reference:
    ``pulsarutils/dedispersion.py:254-258``.
    """
    data = xp.asarray(data)
    t = data.shape[1]
    sh = xp.rint(xp.asarray(shifts)).astype(xp.int32)
    idx = (xp.arange(t)[None, :] + sh[:, None]) % t
    if xp is np:
        return np.take_along_axis(data, idx, axis=1)
    return xp.take_along_axis(data, idx, axis=1)


# ---------------------------------------------------------------------------
# JAX path
# ---------------------------------------------------------------------------

def _jax():
    import jax
    import jax.numpy as jnp

    return jax, jnp


def _policy_strategy(policy):
    """Resolve a non-default policy name to its Strategy (None for f32).

    Lazy so the default ``policy=None`` trace never imports (or pays
    for) the precision engine — byte-identity with the pre-policy
    programs is pinned by test.
    """
    if policy in (None, "f32"):
        return None
    from ..precision import STRATEGIES, policy_name

    return STRATEGIES[policy_name(policy)]


def dedisperse_block_roll_jax(data, offsets, policy=None):
    """Roll-accumulate formulation of :func:`dedisperse_block_jax`.

    Scans over channels; each step adds every trial's circular roll of
    that channel (one two-slice ``dynamic_slice`` of the doubled row per
    trial) into the ``(ndm_block, T)`` carry — the XLA analogue of the
    reference's ``roll_and_sum`` walk.  Workspace is ``O(ndm_block * T)``
    regardless of ``nchan``, and every memory access is contiguous.

    This is the CPU fast path: XLA:CPU lowers the batched
    ``take_along_axis`` gather to scalar loads — measured 14x slower
    than this formulation at a 16-trial x 256-chan x 65k-sample hybrid
    rescore bucket (6.3 s vs 0.5 s; the round-6 streaming-budget work
    caught the rescore stage dominating the CPU survey stream).
    Integer inputs (the packed low-bit path's int16/int32 codes,
    ISSUE 11) accumulate in their own dtype — the scan carry inherits
    it — giving the same exact sums as the gather formulation's
    explicit integer reduction.  On TPU
    the batched gather vectorises well and the Pallas kernel owns the
    fast path anyway, so the gather formulation stays (see
    :func:`dedisperse_block_jax`).  Float32 channel sums associate
    sequentially here vs the gather's tree reduce — same floats within
    normal f32 reassociation tolerance, and the exactness-sensitive
    consumers compare per-backend (the hybrid's rescore and the direct
    kernel route through the SAME formulation on a given backend).

    ``policy`` selects a :mod:`..precision` accumulation strategy for
    float inputs: compensated/split strategies thread a two-float
    (sum, compensation) carry through the channel scan;
    ``bf16_operand_f32_accum`` rolls bfloat16 rows and accumulates in
    float32.  ``None``/``"f32"`` is the unchanged default path.
    """
    jax, jnp = _jax()
    t = data.shape[1]
    strat = _policy_strategy(policy)
    if strat is not None and jnp.issubdtype(data.dtype, jnp.integer):
        strat = None  # integer ladder is already exact; policy is a no-op
    # dynamic_slice CLAMPS out-of-range starts where the gather's index
    # arithmetic wraps mod T — re-wrap here so a caller passing raw
    # (un-normalised) shifts gets the same circular semantics on every
    # backend instead of a silently clamped plane (code-review r6)
    offsets = offsets % t

    def roll_rows(row, offs_c):
        ext = jnp.concatenate([row, row])
        return jax.vmap(
            lambda off: jax.lax.dynamic_slice(ext, (off,), (t,)))(offs_c)

    # the carry is seeded with channel 0 (not zeros): under shard_map a
    # zeros-constant carry is UNVARYING while the body's sum is varying
    # over the mesh axes, and lax.scan rejects the carry-type mismatch
    # (same constraint as the chunked fori_loop below, found live on a
    # chan-sharded mesh in round 5).  Bit-identical: 0 + c0 == c0 in f32.
    if strat is not None and strat.operand_dtype == "bfloat16":
        from ..precision import cast_operand

        data = cast_operand(data, strat.name, jnp)
        acc0 = roll_rows(data[0], offsets[:, 0]).astype(jnp.float32)

        def body_bf16(acc, co):
            row, offs_c = co
            return acc + roll_rows(row, offs_c).astype(jnp.float32), None

        acc, _ = jax.lax.scan(body_bf16, acc0,
                              (data[1:], offsets[:, 1:].T))
        return acc

    if strat is not None and strat.accumulator in ("compensated", "split"):
        # Two-float carry (Knuth TwoSum per step): the compensation is
        # seeded varying (acc0 - acc0, numerically zero) for the same
        # shard_map carry-type reason as acc0 itself.
        acc0 = roll_rows(data[0], offsets[:, 0])

        def body_comp(carry, co):
            acc, comp = carry
            row, offs_c = co
            v = roll_rows(row, offs_c)
            s = acc + v
            bp = s - acc
            comp = comp + ((acc - (s - bp)) + (v - bp))
            return (s, comp), None

        (acc, comp), _ = jax.lax.scan(body_comp, (acc0, acc0 - acc0),
                                      (data[1:], offsets[:, 1:].T))
        return acc + comp

    acc0 = roll_rows(data[0], offsets[:, 0])

    def body(acc, co):
        row, offs_c = co
        return acc + roll_rows(row, offs_c), None

    acc, _ = jax.lax.scan(body, acc0, (data[1:], offsets[:, 1:].T))
    return acc


def dedisperse_block_jax(data, offsets, formulation=None, policy=None):
    """Dedisperse a block of trials on device.

    Parameters
    ----------
    data : (nchan, T) float array (device)
    offsets : (ndm_block, nchan) int32 — **gather offsets**, i.e. the raw
        dedispersion shifts wrapped into ``[0, T)`` (NOT negated: the
        negation in the reference's roll convention and the gather direction
        cancel; see module docstring).
    formulation : ``None`` (backend-resolved, below), ``"gather"`` or
        ``"roll"`` — forced, so the autotuner can measure both families
        on any backend instead of trusting the static rule.
    policy : ``None`` or a :mod:`..precision` strategy name — selects
        the float accumulation strategy (compensated / two-float
        pairwise / bf16-operand).  ``None``/``"f32"`` keeps the
        pre-policy program byte-identical; integer inputs ignore the
        policy (the exact-integer ladder already owns them).

    Returns
    -------
    (ndm_block, T) dedispersed plane block.

    Default formulation is backend-resolved at trace time: the batched
    gather on accelerators (XLA fuses it with the channel reduction),
    the roll-accumulate scan on CPU (:func:`dedisperse_block_roll_jax`
    — XLA:CPU scalarises the gather, measured 14x slower in PR 1; the
    tuner now re-measures that trade per geometry instead of assuming
    it).
    """
    jax, jnp = _jax()
    if formulation is None:
        formulation = ("roll" if jax.default_backend() == "cpu"
                       else "gather")
    if formulation == "roll":
        return dedisperse_block_roll_jax(data, offsets, policy=policy)
    t = data.shape[1]
    strat = _policy_strategy(policy)
    if strat is not None and jnp.issubdtype(data.dtype, jnp.integer):
        strat = None  # integer ladder is already exact; policy is a no-op
    if strat is not None and strat.operand_dtype == "bfloat16":
        # narrow BEFORE the gather so the memory-bound gather itself
        # moves half the bytes — the whole point of the strategy
        from ..precision import cast_operand

        data = cast_operand(data, strat.name, jnp)
    tidx = jnp.arange(t, dtype=jnp.int32)
    # idx[d, c, t] = (t + off[d, c]) mod T
    idx = (tidx[None, None, :] + offsets[:, :, None]) % t
    gathered = jnp.take_along_axis(data[None, :, :], idx, axis=2)
    if jnp.issubdtype(data.dtype, jnp.integer):
        # integer sweep accumulation (packed low-bit path, ISSUE 11):
        # the caller unpacked to an accum_dtype that provably holds the
        # full-channel sum, so the accumulation stays in that dtype —
        # an int16 plane halves the sweep's HBM traffic vs float32, and
        # scoring's float32 view of the exact integer sums is
        # bit-identical to the float-accumulated reference (io/lowbit.
        # accum_dtype states the bound).  The explicit dtype pins the
        # reduction against numpy-style silent promotion to int64.
        return gathered.sum(axis=1, dtype=data.dtype)
    if strat is None:
        return gathered.sum(axis=1)
    if strat.operand_dtype == "bfloat16":
        return gathered.astype(jnp.float32).sum(axis=1)
    from ..precision import neumaier_sum, split_sum

    if strat.accumulator == "compensated":
        return neumaier_sum(gathered, axis=1, xp=jnp)
    return split_sum(gathered, axis=1, xp=jnp)


def dedisperse_block_chunked_jax(data, offsets, chan_block=None,
                                 formulation=None, policy=None):
    """Like :func:`dedisperse_block_jax` but accumulates over channel blocks.

    Bounds the gather workspace to ``ndm_block * chan_block * T`` elements so
    large (nchan, T) chunks fit in HBM.  ``nchan`` must be divisible by
    ``chan_block`` (callers pad channels with zeros — zero channels are
    exact no-ops for the sum).  Under the roll-accumulate formulation
    (forced, or the CPU default) the workspace is already
    ``O(ndm_block * T)``, so chunking would only add loop overhead and
    is skipped.

    A non-default ``policy`` applies *within* each channel block; the
    cross-block accumulation stays plain float32 (nblocks is small, so
    the extra term is ``nblocks * eps`` — negligible next to the
    in-block bound each strategy documents).
    """
    jax, jnp = _jax()
    nchan = data.shape[0]
    eff = formulation or ("roll" if jax.default_backend() == "cpu"
                          else "gather")
    if chan_block is None or chan_block >= nchan or eff == "roll":
        return dedisperse_block_jax(data, offsets, formulation=eff,
                                    policy=policy)
    assert nchan % chan_block == 0, (nchan, chan_block)
    nblocks = nchan // chan_block
    t = data.shape[1]
    ndm = offsets.shape[0]

    data_b = data.reshape(nblocks, chan_block, t)
    off_b = offsets.reshape(ndm, nblocks, chan_block).transpose(1, 0, 2)

    del ndm

    def body(i, acc):
        return acc + dedisperse_block_jax(data_b[i], off_b[i],
                                          formulation=eff, policy=policy)

    # the carry is seeded with block 0 (not zeros): under shard_map a
    # zeros-constant carry is UNVARYING while the body's sum is varying
    # over the mesh axes, and lax.fori_loop rejects the carry-type
    # mismatch (hit live on a (n, 1) mesh whose per-device gather
    # exceeded the chan_block budget — round 5).  Bit-identical:
    # 0 + b0 == b0 in f32.
    acc0 = dedisperse_block_jax(data_b[0], off_b[0], formulation=eff,
                                policy=policy)
    return jax.lax.fori_loop(1, nblocks, body, acc0)
