"""Fast DM Transform (FDMT): tree dedispersion in O(nchan · T · log nchan).

The direct sweep costs ``O(ndm · nchan · T)`` shifted adds (reference
``pulsarutils/dedispersion.py:174-202``; our Pallas kernel).  The FDMT
(Zackay & Ofek 2017, ApJ 835:11) computes **every integer-delay trial at
once** by recursively merging adjacent frequency sub-bands: partial
dedispersed sums over a sub-band are reused by all trials that cross it,
collapsing the trial axis into ``log2(nchan)`` shift-and-add passes.  For
the benchmark geometry (1024 chan, 512-sample delay span) this is ~100x
fewer adds than the direct sweep.

Semantics and how they relate to the reference:

* The FDMT's natural trial grid IS the reference's plan (one trial per
  integer sample of band-crossing delay, ``dedispersion.py:149-171``):
  row ``N`` of the transform sums one sample per channel along the
  dispersion track whose differential delay across the full band is ``N``
  samples.  DM values are recovered with the same inversion the plan uses.
* Per-channel delays along a track are rounded *recursively* (each merge
  rounds the track's crossing of the sub-band boundary) instead of
  directly per channel, so individual channel delays can differ from the
  reference's ``rint(delay // tsamp)`` by ~1 sample (Zackay & Ofek §2.3
  bound the deviation).  Hit detection therefore agrees with the exact
  kernels to within a trial, but is not bit-identical — use
  ``kernel="pallas"`` when bit-exact parity with the NumPy reference path
  matters, ``kernel="fdmt"`` for throughput.
* Time shifts are circular (the reference's ``np.roll`` convention,
  ``dedispersion.py:60-98``), so no edge-validity bookkeeping is needed.
* Rows are anchored at the top of the band: row ``N`` equals the exact
  trial's series up to a small per-trial circular rotation (scores are
  rotation-invariant; the boxcar scorer sees windows shifted by a few
  samples, a sub-percent S/N effect).

Implementation notes (TPU):

* Each merge pass is ONE fused Pallas kernel launch: for every output row
  ``(band, Δ)`` it reads the two parent rows directly from the state
  array — row indices arrive via scalar-prefetch (the BlockSpec index
  maps read them from SMEM), so the XLA-level gather never materialises —
  applies the re-anchoring circular shift to the low-band row with the
  aligned-load + rotate + blend scheme of
  :mod:`.pallas_dedisperse` (chunked ``(8, L)`` row layout, full-sublane
  ops), adds, and writes the output tile.
* Off TPU (or for time axes no power-of-two tile divides) the same merge
  runs as an XLA ``take_along_axis`` + per-row roll fallback.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from .plan import DM_DELAY_CONST, delta_delay


# ---------------------------------------------------------------------------
# Plan: per-iteration merge tables (host, numpy, static)
# ---------------------------------------------------------------------------

def _lam(f):
    return f ** -2.0


class FdmtPlan:
    """Static merge schedule for one (nchan, geometry, delay-range) tuple.

    Attributes
    ----------
    iterations : list of dict with keys
        ``idx_low``, ``idx_high`` — (rows_out,) int32 flat parent-row
        indices into the previous state's row axis;
        ``shift`` — (rows_out,) int32 circular shift applied to the
        low-band parent row;
        ``shift_high`` — (rows_out,) int32 shift for the high parent
        (leaf merge only; ``None`` for deeper iterations);
        ``nbands``, ``ndelay`` — output layout (rows_out = sum(ndelay)).
    nchan_padded : channel count rounded up to a power of two (the extra
        channels are zero and contribute nothing).
    max_delay : largest differential band delay (inclusive) produced.
    min_delay : smallest band delay produced (DM-range pruning): the final
        state holds rows ``min_delay..max_delay`` only, and every earlier
        iteration allocates just the (contiguous) parent-delay window
        those rows reach through the recursion — for a search restricted
        to DM 300-635 (the benchmark config) this nearly halves the tree's
        rows, HBM traffic and adds versus the classic 0-anchored transform.
    """

    def __init__(self, nchan, start_freq, bandwidth, max_delay, min_delay=0):
        self.nchan = nchan
        self.max_delay = int(max_delay)
        self.min_delay = int(min_delay)
        if not 0 <= self.min_delay <= self.max_delay:
            raise ValueError(
                f"min_delay {min_delay} outside [0, {max_delay}]")
        nch2 = 1
        while nch2 < nchan:
            nch2 *= 2
        self.nchan_padded = nch2
        # zero-padded channels sit ABOVE the real band: they must not
        # stretch the physical frequency span, so give them zero bandwidth
        # by keeping the per-channel width of the real band
        df = bandwidth / nchan
        f_edge = lambda c: start_freq + min(c, nchan) * df  # noqa: E731
        maxn = self.max_delay

        # Flat row layout with PER-BAND delay counts, allocated top-down:
        # only the (band, delay) rows some final trial actually requests
        # exist.  (Padding every band to the bottom band's depth, or even
        # a uniform +1 slack per band, inflates the 1M-sample state past
        # HBM.)  The initial state is the raw data itself — one row per
        # channel, NO delay expansion: the first merge samples each
        # channel directly with per-parent shifts (``shift_high`` = the
        # track's delay at the high channel's lower edge, ``shift`` = at
        # the low channel's lower edge — the reference's frequency
        # convention, ``dedispersion.py:127,135``).  Deeper merges only
        # shift the low parent (the high parent is already anchored).
        # State rows: band-major, delay-minor, nd[b] slots for band b.

        # pass A (top-down): per-iteration band split fractions, then the
        # (contiguous) delay window each band is ever asked for.  Both the
        # min and max of the window propagate: dd increasing by 1 moves
        # dh = round(dd * frac) and dl = dd - dh by 0 or 1 each, so the
        # parent windows of a contiguous child window are contiguous too.
        widths = []
        w = 1
        while w < nch2:
            widths.append(w)
            w *= 2
        fracs = []  # fracs[i][b]: high-band share of band b's delay split
        for w in widths:
            nb = nch2 // (2 * w)
            fr = np.empty(nb)
            for b in range(nb):
                c0, c1, c2 = 2 * b * w, (2 * b + 1) * w, (2 * b + 2) * w
                w02 = _lam(f_edge(c0)) - _lam(f_edge(c2))
                w12 = _lam(f_edge(c1)) - _lam(f_edge(c2))
                fr[b] = w12 / w02 if w02 > 0 else 0.0
            fracs.append(fr)
        used = [None] * (len(widths) + 1)
        used_min = [None] * (len(widths) + 1)
        used[-1] = np.asarray([maxn])  # final band serves Δ = minn..maxn
        used_min[-1] = np.asarray([self.min_delay])
        for i in range(len(widths) - 1, 0, -1):
            u_out, u_out_min = used[i + 1], used_min[i + 1]
            nb = len(u_out)
            u_in = np.zeros(2 * nb, np.int64)
            u_in_min = np.zeros(2 * nb, np.int64)
            for b in range(nb):
                dd = np.arange(u_out_min[b], u_out[b] + 1)
                dh = np.round(dd * fracs[i][b]).astype(np.int64)
                dl = dd - dh
                u_in[2 * b], u_in_min[2 * b] = dl.max(), dl.min()
                u_in[2 * b + 1], u_in_min[2 * b + 1] = dh.max(), dh.min()
            used[i], used_min[i] = u_in, u_in_min

        # pass B (bottom-up): flat index tables over the allocated rows
        # (row layout: band-major, delay-minor, band b holding delays
        # used_min[b]..used[b] inclusive)
        self.iterations = []
        nd_in = [1] * nch2       # the raw channels
        min_in = [0] * nch2
        for i, w in enumerate(widths):
            u_out, u_out_min = used[i + 1], used_min[i + 1]
            nd_out = [int(u_out[b] - u_out_min[b]) + 1
                      for b in range(len(u_out))]
            in_off = np.concatenate([[0], np.cumsum(nd_in)])
            out_rows = int(np.sum(nd_out))
            idx_low = np.empty(out_rows, np.int32)
            idx_high = np.empty(out_rows, np.int32)
            shift = np.empty(out_rows, np.int32)
            shift_high = np.zeros(out_rows, np.int32) if i == 0 else None
            pos = 0
            for b in range(len(nd_out)):
                dd = np.arange(u_out_min[b], u_out[b] + 1)
                dh = np.round(dd * fracs[i][b]).astype(np.int64)
                dl = dd - dh
                if i == 0:
                    # leaf merge: parents are raw channel rows, sampled
                    # at the track's delay at their lower edges (relative
                    # to the pair's top edge): high -> dh, low -> dd
                    idx_low[pos:pos + len(dd)] = in_off[2 * b]
                    idx_high[pos:pos + len(dd)] = in_off[2 * b + 1]
                    shift[pos:pos + len(dd)] = dd
                    shift_high[pos:pos + len(dd)] = dh
                else:
                    assert dh.min() >= min_in[2 * b + 1], (i, b)
                    assert dh.max() - min_in[2 * b + 1] < nd_in[2 * b + 1], \
                        (i, b)
                    assert dl.min() >= min_in[2 * b], (i, b)
                    assert dl.max() - min_in[2 * b] < nd_in[2 * b], (i, b)
                    idx_low[pos:pos + len(dd)] = (in_off[2 * b]
                                                  + dl - min_in[2 * b])
                    idx_high[pos:pos + len(dd)] = (in_off[2 * b + 1]
                                                   + dh - min_in[2 * b + 1])
                    shift[pos:pos + len(dd)] = dh
                pos += len(dd)
            self.iterations.append({
                "idx_low": idx_low,
                "idx_high": idx_high,
                "shift": shift,
                "shift_high": shift_high,
                "nbands": len(nd_out),
                "ndelay": nd_out,
            })
            nd_in = nd_out
            min_in = [int(m) for m in u_out_min]


@functools.lru_cache(maxsize=32)
def fdmt_plan(nchan, start_freq, bandwidth, max_delay, min_delay=0):
    """Cached :class:`FdmtPlan` (all-static inputs)."""
    return FdmtPlan(nchan, start_freq, bandwidth, max_delay, min_delay)


def compose_iterations(it_a, it_b):
    """Fuse two consecutive deep merge iterations into one 4-parent pass.

    With ``state_b[q] = state[ih_a[q]] + roll(state[il_a[q]], s_a[q])``
    and ``out[r] = state_b[ih_b[r]] + roll(state_b[il_b[r]], s_b[r])``,
    substituting gives (roll composition is additive, circular):

    ``out[r] = state[ih_a[ih_b[r]]]
             + roll(state[il_a[ih_b[r]]], s_a[ih_b[r]])
             + roll(state[ih_a[il_b[r]]], s_b[r])
             + roll(state[il_a[il_b[r]]], s_b[r] + s_a[il_b[r]])``

    — the intermediate state never exists, trading one full write + read
    of ``state_b`` (the larger of the deep states) for two extra parent
    reads per output row (round 5, VERDICT r4 #3 deep-level fusion).
    Leaf iterations (``shift_high`` set) cannot be composed this way.

    Returns ``(idx, shift)``: lists of four ``(rows_out,)`` int32 arrays
    (parent row indices / circular shifts; parent 0's shift is 0).
    """
    if it_a["shift_high"] is not None or it_b["shift_high"] is not None:
        raise ValueError("compose_iterations requires deep (post-leaf) "
                         "iterations")
    ih_b, il_b, s_b = it_b["idx_high"], it_b["idx_low"], it_b["shift"]
    ih_a, il_a, s_a = it_a["idx_high"], it_a["idx_low"], it_a["shift"]
    idx = [ih_a[ih_b], il_a[ih_b], ih_a[il_b], il_a[il_b]]
    shift = [np.zeros_like(s_b), s_a[ih_b], s_b, s_b + s_a[il_b]]
    return ([np.ascontiguousarray(i, np.int32) for i in idx],
            [np.ascontiguousarray(s, np.int32) for s in shift])


def fdmt_tracks(plan):
    """The effective dispersion track of every final transform row.

    Walks the plan's merge tables with an offset accumulator instead of
    data: row ``r`` of the transform computes exactly
    ``out[t] = sum_c data[c, (t + tracks[r, c]) mod T]`` (the same gather
    convention as the exact kernels, :mod:`.dedisperse`), so comparing
    ``tracks`` against :func:`~pulsarutils_tpu.ops.plan.dedispersion_shifts`
    gives the tree's per-channel track rounding *exactly* — no data, no
    noise, no device.  Consumers: the hybrid's per-config retention bound
    (:mod:`.certify`) and the track-deviation tests.

    Returns int64 ``(rows_final, nchan_padded)``; rows are the plan's
    ``min_delay..max_delay`` delay slice, columns ``>= plan.nchan`` belong
    to zero-padded channels (no data flows through them — slice them off
    before comparing).
    """
    nchp = plan.nchan_padded
    tracks = np.zeros((nchp, nchp), np.int64)
    valid = np.eye(nchp, dtype=bool)
    for it in plan.iterations:
        tl = tracks[it["idx_low"]] + it["shift"][:, None]
        th = tracks[it["idx_high"]]
        if it["shift_high"] is not None:
            th = th + it["shift_high"][:, None]
        vl, vh = valid[it["idx_low"]], valid[it["idx_high"]]
        # low/high parents cover disjoint channel halves of the output band
        tracks = np.where(vl, tl, th) * (vl | vh)
        valid = vl | vh
    assert valid.all(), "final band must cover every channel"
    return tracks


def max_band_delay(nchan, dmmax, start_freq, bandwidth, sample_time):
    """Largest integer band-crossing delay for ``dmmax`` (plan row count)."""
    return int(np.ceil(
        delta_delay(float(dmmax), start_freq, start_freq + bandwidth)
        / sample_time))


# ---------------------------------------------------------------------------
# Merge executors
# ---------------------------------------------------------------------------

def _merge_xla(state, idx_low, idx_high, shift, shift_high=None):
    """Portable merge: row gathers + per-row circular roll via gather."""
    import jax.numpy as jnp

    t = state.shape[-1]
    low = state[idx_low]                      # (rows_out, T)
    high = state[idx_high]
    tidx = jnp.arange(t, dtype=jnp.int32)
    gather = (tidx[None, :] + shift[:, None]) % t
    low = jnp.take_along_axis(low, gather, axis=1)
    if shift_high is not None:
        gather_h = (tidx[None, :] + shift_high[:, None]) % t
        high = jnp.take_along_axis(high, gather_h, axis=1)
    return high + low


def _pick_fdmt_tile(t):
    """Largest power-of-two tile in [1024, 8192] dividing ``t`` (0 if none).

    Env ``PUTPU_FDMT_TILE`` caps/overrides the preference (tuning knob:
    the kernel accepts any power-of-two tile dividing ``t``, but VMEM
    limits the (tile x MERGE_ROW_BLOCK) product).
    """
    prefs = (8192, 4096, 2048, 1024)
    try:
        override = int(os.environ.get("PUTPU_FDMT_TILE") or 0)
    except ValueError:
        override = 0
    # only a power-of-two >= 1024 is a legal tile; anything else would
    # break the pad-guarantees-a-tile invariant of _transform_setup, so
    # invalid overrides fall back to the defaults (which stay in prefs
    # unconditionally for the same reason)
    if override >= 1024 and (override & (override - 1)) == 0:
        prefs = (override,) + prefs
    for t_tile in prefs:
        if t % t_tile == 0:
            return t_tile
    return 0


def _transform_setup(data, use_pallas):
    """Resolve the Pallas/XLA choice and tile for a time axis of length T.

    When the Pallas path is wanted but no power-of-two tile divides T,
    the data is zero-padded to the next multiple of 1024 (the XLA gather
    fallback scalarises on TPU); circular wraps then cross the short zero
    pad — an edge effect of the same order as the tree's track rounding.
    The caller slices outputs back to ``t_orig``.

    Returns ``(data, t_run, t_tile, use_pallas, interpret, t_orig)``.
    """
    import jax
    import jax.numpy as jnp

    t = data.shape[1]
    t_run = t
    t_tile = _pick_fdmt_tile(t)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas and t_tile == 0:
        t_run = -(-t // 1024) * 1024
        data = jnp.pad(data, ((0, 0), (0, t_run - t)))
        t_tile = _pick_fdmt_tile(t_run)
    return (data, t_run, t_tile, bool(use_pallas),
            jax.default_backend() != "tpu", t)


def _merge_row_block():
    # guarded like PUTPU_FDMT_TILE: a malformed value must not crash the
    # import (ValueError) or the padding math later (0/negative ->
    # ZeroDivisionError in the (-rows) % row_block pads)
    raw = os.environ.get("PUTPU_MERGE_ROW_BLOCK")
    try:
        value = int(raw or 0)
    except ValueError:
        value = 0
    if raw and not 0 < value <= 256:
        import warnings

        warnings.warn(
            f"PUTPU_MERGE_ROW_BLOCK={raw!r} ignored (needs an int in "
            "[1, 256]); using 32", stacklevel=2)
    return value if 0 < value <= 256 else 32


#: output rows processed per merge-kernel grid step; amortises the
#: per-step Pallas/DMA orchestration overhead (the kernel is otherwise
#: grid-overhead-bound: one row per step = ~1.4M steps per transform).
#: Re-swept on v5e at the 1024x1M headline with the DM-pruned plan
#: (tools/fdmt_tune.py): 32 @ tile 8192 = 0.352 s (1454 tr/s) vs 8 =
#: 0.394 s; 64 @ 8192 exhausts scoped VMEM; tile size still dominates
#: (8192 >> 4096 >> 2048).  Compile is slower at 32 (~25 s cold) but the
#: persistent compilation cache amortises it.  Overridable via env
#: ``PUTPU_MERGE_ROW_BLOCK`` (an int in [1, 256]; anything else warns
#: and falls back to 32) — tuning/bisection without code edits.
MERGE_ROW_BLOCK = _merge_row_block()


@functools.lru_cache(maxsize=64)
def _build_merge_kernel(rows_out, rows_in, t, t_tile, k_tiles, k_tiles_h,
                        row_block, interpret):
    """Fused FDMT merge: ``out[r] = roll(high[ih[r]], sh[r]) +
    roll(low[il[r]], s[r])``, ``row_block`` rows per grid step.

    ``k_tiles_h = 0`` compiles the common asymmetric form (high parent
    read aligned, no rotation) used by every iteration except the leaf
    merge.  ``rows_out`` must be a multiple of ``row_block`` (callers pad
    the tables; padded rows write junk rows that are sliced off).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .pallas_dedisperse import shifted_row_tile

    L = t_tile // 8
    n_t = t // t_tile
    kh = max(1, k_tiles_h)

    def shifted_tile(win_ref, r, lane, jnp, pl, pltpu, q0):
        return shifted_row_tile(win_ref, None, r, L, lane, jnp, pl, pltpu,
                                q0=q0)

    def kernel(idx_low_ref, idx_high_ref, shift_ref, shift_high_ref,
               *refs):
        lane = jax.lax.broadcasted_iota(jnp.int32, (8, L), 1)
        nin = row_block * (k_tiles + kh)
        out_ref = refs[nin]
        win_ref = refs[nin + 1]
        win_h_ref = refs[nin + 2] if k_tiles_h else None
        i_r = pl.program_id(0)

        for j in range(row_block):
            low_refs = refs[j * k_tiles:(j + 1) * k_tiles]
            high_refs = refs[row_block * k_tiles + j * kh:
                             row_block * k_tiles + (j + 1) * kh]
            # stitch the low-band row's staggered (8, L) chunks
            for k in range(k_tiles):
                win_ref[k * 8:(k + 1) * 8, :] = low_refs[k][0, 0]
            low_tile = shifted_tile(win_ref, shift_ref[i_r * row_block + j],
                                    lane, jnp, pl, pltpu, k_tiles == 2)
            if k_tiles_h:
                for k in range(k_tiles_h):
                    win_h_ref[k * 8:(k + 1) * 8, :] = high_refs[k][0, 0]
                high_tile = shifted_tile(
                    win_h_ref, shift_high_ref[i_r * row_block + j], lane,
                    jnp, pl, pltpu, k_tiles_h == 2)
            else:
                high_tile = high_refs[0][0, 0]
            out_ref[j, 0] = high_tile + low_tile

    # scalar-prefetch index maps: parent rows are chosen per grid step by
    # the prefetched tables, so no gathered copy of the state is ever
    # materialised
    def low_spec(j, k):
        return pl.BlockSpec(
            (1, 1, 8, L),
            functools.partial(lambda i_r, i_t, il, ih, sh, shh, _j, _k:
                              (il[i_r * row_block + _j],
                               (i_t + _k) % n_t, 0, 0), _j=j, _k=k))

    def high_spec(j, k):
        return pl.BlockSpec(
            (1, 1, 8, L),
            functools.partial(lambda i_r, i_t, il, ih, sh, shh, _j, _k:
                              (ih[i_r * row_block + _j],
                               (i_t + _k) % n_t, 0, 0), _j=j, _k=k))

    low_specs = [low_spec(j, k) for j in range(row_block)
                 for k in range(k_tiles)]
    high_specs = [high_spec(j, k) for j in range(row_block)
                  for k in range(kh)]
    out_spec = pl.BlockSpec(
        (row_block, 1, 8, L),
        lambda i_r, i_t, il, ih, sh, shh: (i_r, i_t, 0, 0))

    scratch = [pltpu.VMEM((k_tiles * 8, L), jnp.float32)]
    if k_tiles_h:
        scratch.append(pltpu.VMEM((k_tiles_h * 8, L), jnp.float32))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(rows_out // row_block, n_t),
        in_specs=low_specs + high_specs,
        out_specs=out_spec,
        scratch_shapes=scratch,
    )
    call = pl.pallas_call(kernel, grid_spec=grid_spec,
                          out_shape=jax.ShapeDtypeStruct(
                              (rows_out, n_t, 8, L), jnp.float32),
                          interpret=bool(interpret))

    @jax.jit
    def run(state, idx_low, idx_high, shift, shift_high):
        s4 = state.reshape(rows_in, n_t, 8, L)
        n_in = row_block * (k_tiles + kh)
        out = call(idx_low, idx_high, shift, shift_high,
                   *([s4] * n_in))
        return out.reshape(rows_out, t)

    return run


@functools.lru_cache(maxsize=16)
def _build_merge4_kernel(rows_out, rows_in, t, t_tile, k_tiles, row_block,
                         interpret):
    """Fused two-level FDMT merge: ``out[r] = sum_p roll(state[idx_p[r]],
    shift_p[r])`` over 4 parents (:func:`compose_iterations`).

    Same scalar-prefetch scheme as :func:`_build_merge_kernel`, with one
    shared ``k_tiles`` bound covering every composed shift (parent 0's
    shift is 0; the rotate machinery handles it without a special
    case).  ``rows_out`` must be a multiple of ``row_block``.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .pallas_dedisperse import shifted_row_tile

    L = t_tile // 8
    n_t = t // t_tile
    P = 4

    def kernel(*refs):
        idx_refs = refs[:P]          # scalar-prefetch (unused directly)
        shift_refs = refs[P:2 * P]
        data_refs = refs[2 * P:2 * P + row_block * P * k_tiles]
        out_ref = refs[2 * P + row_block * P * k_tiles]
        win_ref = refs[2 * P + row_block * P * k_tiles + 1]
        del idx_refs
        lane = jax.lax.broadcasted_iota(jnp.int32, (8, L), 1)
        i_r = pl.program_id(0)

        for j in range(row_block):
            tiles = []
            for p in range(P):
                base = (j * P + p) * k_tiles
                for k in range(k_tiles):
                    win_ref[k * 8:(k + 1) * 8, :] = \
                        data_refs[base + k][0, 0]
                tiles.append(shifted_row_tile(
                    win_ref, None, shift_refs[p][i_r * row_block + j], L,
                    lane, jnp, pl, pltpu, q0=(k_tiles == 2)))
            # PAIRWISE association — bit-identical to the two per-level
            # merges it replaces: parent pairs (0,1) and (2,3) are the
            # two level-a outputs (the roll distributes exactly over the
            # inner add), and the outer add is level b's
            out_ref[j, 0] = (tiles[0] + tiles[1]) + (tiles[2] + tiles[3])

    def data_spec(j, p, k):
        return pl.BlockSpec(
            (1, 1, 8, L),
            functools.partial(
                lambda i_r, i_t, i0, i1, i2, i3, s0, s1, s2, s3, _j, _p,
                _k: ((i0, i1, i2, i3)[_p][i_r * row_block + _j],
                     (i_t + _k) % n_t, 0, 0), _j=j, _p=p, _k=k))

    data_specs = [data_spec(j, p, k) for j in range(row_block)
                  for p in range(P) for k in range(k_tiles)]
    out_spec = pl.BlockSpec(
        (row_block, 1, 8, L),
        lambda i_r, i_t, i0, i1, i2, i3, s0, s1, s2, s3: (i_r, i_t, 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(rows_out // row_block, n_t),
        in_specs=data_specs,
        out_specs=out_spec,
        scratch_shapes=[pltpu.VMEM((k_tiles * 8, L), jnp.float32)],
    )
    call = pl.pallas_call(kernel, grid_spec=grid_spec,
                          out_shape=jax.ShapeDtypeStruct(
                              (rows_out, n_t, 8, L), jnp.float32),
                          interpret=bool(interpret))

    @jax.jit
    def run(state, idx, shift):
        s4 = state.reshape(rows_in, n_t, 8, L)
        n_in = row_block * P * k_tiles
        out = call(*idx, *shift, *([s4] * n_in))
        return out.reshape(rows_out, t)

    return run


def _merge4_pallas(state, idx, shift, t_tile, interpret):
    """Run one composed 4-parent merge pass (host-side table prep)."""
    import jax.numpy as jnp

    rows_in, t = state.shape
    rows_out = len(idx[0])
    L = t_tile // 8
    max_shift = max(int(s.max(initial=0))  # putpu-lint: disable=device-trip — host plan tables
                    for s in shift)
    k_tiles = (max_shift // L + 23) // 8

    # the 4-parent kernel carries 4x the BlockSpec operands per row, so
    # its row block is kept smaller than MERGE_ROW_BLOCK to bound both
    # operand count and per-step VMEM
    row_block = min(max(1, MERGE_ROW_BLOCK // 2), rows_out)
    pad = (-rows_out) % row_block
    idx_p = [np.concatenate([i, i[-1:].repeat(pad)]) for i in idx]
    shift_p = [np.concatenate([s, s[-1:].repeat(pad)]) for s in shift]
    run = _build_merge4_kernel(rows_out + pad, rows_in, t, t_tile,
                               k_tiles, row_block, interpret)
    out = run(state, tuple(jnp.asarray(i) for i in idx_p),
              tuple(jnp.asarray(s) for s in shift_p))
    return out[:rows_out] if pad else out


def _deep_pair_enabled():
    """PUTPU_FDMT_DEEP_PAIR: ''=auto (ON), 0, 1.

    Default ON (round-5 A/B, v5e 1024x1M coarse sweep, min-of-4:
    0.241 s -> 0.229 s on top of the one-pass scorer — the two
    per-level passes it replaces write and re-read the largest deep
    state).  Applies only where the Pallas merge path runs; the knob
    bisects."""
    from ..utils.knobs import tristate_env

    knob = tristate_env("PUTPU_FDMT_DEEP_PAIR")
    return True if knob is None else knob


def merge_rows_traced(state, idx_low, idx_high, shift, shift_high, *,
                      k_tiles, k_tiles_h, t_tile, interpret):
    """One Pallas merge pass with *traced* (runtime) tables.

    The tables arrive as jax arrays — they ride the scalar-prefetch
    operands, so the same compiled program serves different merge
    schedules of identical shape (the sharded FDMT ships each device its
    own tables through ``shard_map``).  ``k_tiles``/``k_tiles_h`` must be
    static bounds covering every shift value; row count must already be
    a multiple of :data:`MERGE_ROW_BLOCK` (or smaller than it).
    """
    rows_in, t = state.shape
    rows_out = idx_low.shape[0]
    row_block = min(MERGE_ROW_BLOCK, rows_out)
    run = _build_merge_kernel(rows_out, rows_in, t, t_tile, k_tiles,
                              k_tiles_h, row_block, interpret)
    return run(state, idx_low, idx_high, shift, shift_high)


def _merge_pallas(state, it, t_tile, interpret):
    import jax.numpy as jnp

    rows_in, t = state.shape
    rows_out = len(it["idx_low"])
    L = t_tile // 8
    max_shift = int(  # putpu-lint: disable=device-trip — host plan tables
        it["shift"].max(initial=0))
    k_tiles = (max_shift // L + 23) // 8

    row_block = min(MERGE_ROW_BLOCK, rows_out)
    pad = (-rows_out) % row_block
    idx_low = np.concatenate([it["idx_low"],
                              it["idx_low"][-1:].repeat(pad)])
    idx_high = np.concatenate([it["idx_high"],
                               it["idx_high"][-1:].repeat(pad)])
    shift = np.concatenate([it["shift"], it["shift"][-1:].repeat(pad)])

    if it["shift_high"] is not None:
        max_sh = int(  # putpu-lint: disable=device-trip — host plan tables
            it["shift_high"].max(initial=0))
        k_tiles_h = (max_sh // L + 23) // 8
        shift_high = np.concatenate([it["shift_high"],
                                     it["shift_high"][-1:].repeat(pad)])
    else:
        k_tiles_h = 0
        shift_high = np.zeros(rows_out + pad, np.int32)
    out = merge_rows_traced(state, jnp.asarray(idx_low),
                            jnp.asarray(idx_high), jnp.asarray(shift),
                            jnp.asarray(shift_high), k_tiles=k_tiles,
                            k_tiles_h=k_tiles_h, t_tile=t_tile,
                            interpret=interpret)
    return out[:rows_out] if pad else out


def _head_enabled(use_pallas):
    """Resolve the fused-head knob (PUTPU_FDMT_HEAD: ''=auto, 0, 1).

    Resolved at the call sites (not inside the cached transform
    builders) so the choice is part of the compile-cache key.

    Default ON for TPU (measured, v5e, 1024 x 1M benchmark): the head
    is bit-identical, cuts the covered levels' HBM traffic ~4x, and
    with the 8-row-unrolled row loop measures 0.323 s vs 0.365 s for
    the per-level path (transform+score).  The win needed two tuning
    rounds — 128-lane chunks measured 0.62 s and an un-unrolled row
    loop 0.53 s (both scalar/instruction-bound, see
    ops/fdmt_resident.py) — so the knob stays for bisection.
    """
    from ..utils.knobs import tristate_env

    knob = tristate_env("PUTPU_FDMT_HEAD")
    return bool(use_pallas) if knob is None else knob


def head_active(nchan, start_freq, bandwidth, max_delay, n_lo, t):
    """True iff the fused head WILL run for this transform config.

    THE eligibility gate — `_transform_fn` consults it and so must any
    A/B harness (tools/tpu_smoke.py's head parity check): a
    hand-replicated copy of these conditions could silently diverge and
    turn the A/B vacuous.
    """
    from .fdmt_resident import (
        HEAD_LEVELS,
        _head_plan_cached,
        head_supported,
    )

    plan = fdmt_plan(nchan, start_freq, bandwidth, max_delay, n_lo)
    if not head_supported(plan.nchan_padded, len(plan.iterations), t):
        return False
    hp = _head_plan_cached(nchan, start_freq, bandwidth, max_delay, n_lo,
                           HEAD_LEVELS)
    return head_supported(plan.nchan_padded, len(plan.iterations), t,
                          halo=hp.halo,
                          max_level_shift=max(hp.max_shift_per_level))


def _score_kernel_choice(use_pallas, interpret):
    """Resolve the one-pass-scorer choice at a call site.

    Like ``_head_enabled``: the result must be passed into
    ``_transform_fn``/``_build_transform`` so it keys their lru/compile
    caches — an in-builder env read would serve a stale compiled
    program after toggling ``PUTPU_PALLAS_SCORE`` in-process.  Auto
    (knob unset) enables the kernel on the compiled TPU path only
    (interpret-mode Pallas is minutes-slow; tests opt in explicitly).
    """
    from .score_pallas import score_enabled

    knob = score_enabled()
    return (bool(use_pallas) and not interpret) if knob is None else knob


@functools.lru_cache(maxsize=16)
def _transform_fn(nchan, start_freq, bandwidth, max_delay, t, t_tile,
                  use_pallas, interpret, n_lo=0, with_scores=False,
                  with_plane=True, t_orig=None, with_cert=False,
                  use_head=False, use_score=False, deep_pair=False):
    """The traceable (un-jitted) transform body: DM-pruned merges
    [+ scoring].  :func:`_build_transform` wraps it in ``jax.jit``;
    the hybrid search composes it with its fused seed-rescore program
    (``ops/search.py:_fused_hybrid_seed_kernel``) instead.

    The plan is built with ``min_delay = n_lo`` (see :class:`FdmtPlan`),
    so rows below the searched DM range are never computed — the final
    state IS rows ``n_lo..max_delay``.  Fusing the scorer into the
    program keeps the live set between calls near zero — returning the
    full state keeps gigabytes alive and OOMs back-to-back searches at
    the 1M-sample size.
    """
    import jax.numpy as jnp

    plan = fdmt_plan(nchan, start_freq, bandwidth, max_delay, n_lo)

    # VMEM-resident fused head (ops/fdmt_resident.py): the first
    # HEAD_LEVELS merges — ~75% of the per-level HBM traffic — run in
    # one Pallas program whose intermediate states never leave VMEM,
    # bit-identical to the per-level path.  ``use_head`` is resolved by
    # the caller via _head_enabled (auto on TPU; PUTPU_FDMT_HEAD
    # overrides) so it keys the compile caches.
    head_run = None
    n_head = 0
    if use_head and head_active(nchan, start_freq, bandwidth, max_delay,
                                n_lo, t):
        from .fdmt_resident import (
            HEAD_LEVELS,
            _build_head_kernel,
            _head_plan_cached,
            pick_head_t_slice,
        )

        hp = _head_plan_cached(nchan, start_freq, bandwidth, max_delay,
                               n_lo, HEAD_LEVELS)
        head_run, _ = _build_head_kernel(
            nchan, start_freq, bandwidth, max_delay, n_lo,
            HEAD_LEVELS, t, pick_head_t_slice(hp, t), interpret)
        n_head = HEAD_LEVELS

    # deep-level pairing (round 5, VERDICT r4 #3): fuse the LAST TWO
    # per-level merges into one 4-parent pass — the intermediate state
    # (the largest deep state) is never written or re-read.  Pallas
    # path only; leaf merges (shift_high) cannot compose.
    iters = plan.iterations[n_head:]
    paired = None
    if (deep_pair and use_pallas and len(iters) >= 2
            and iters[-1]["shift_high"] is None
            and iters[-2]["shift_high"] is None):
        paired = compose_iterations(iters[-2], iters[-1])
        iters = iters[:-2]

    def fn(data):
        state = data
        if nchan < plan.nchan_padded:
            state = jnp.concatenate(
                [state,
                 jnp.zeros((plan.nchan_padded - nchan, t), state.dtype)])
        if head_run is not None:
            state = head_run(state)
        for it in iters:
            if use_pallas:
                state = _merge_pallas(state, it, t_tile, interpret)
            else:
                sh = (jnp.asarray(it["shift_high"])
                      if it["shift_high"] is not None else None)
                state = _merge_xla(state, jnp.asarray(it["idx_low"]),
                                   jnp.asarray(it["idx_high"]),
                                   jnp.asarray(it["shift"]), sh)
        if paired is not None:
            state = _merge4_pallas(state, paired[0], paired[1], t_tile,
                                   interpret)
        plane = state  # rows n_lo..max_delay by construction
        if t_orig is not None and t_orig != t:
            plane = plane[:, :t_orig]
        if not with_scores:
            return plane
        from .score_pallas import pick_score_tile
        from .search import score_profiles_chunked

        # one-pass Pallas scorer (round 5): reads the plane once and
        # accumulates per-row partials in VMEM — the XLA chunked scorer
        # materialises ~9 GB of mean-sub/pyramid/sliding temps at the
        # 513 x 1M coarse plane and measured 0.17 s standalone against
        # this kernel's ~0.02 s.  ``use_score`` is resolved by the
        # caller via _score_kernel_choice (auto on compiled TPU;
        # PUTPU_PALLAS_SCORE=0|1 bisects) so it keys the compile caches.
        if use_score and not pick_score_tile(plane.shape[1]):
            import warnings

            # trace-time, once per shape: a silent fall-through would
            # make a PUTPU_PALLAS_SCORE A/B bisection measure the same
            # XLA scorer twice (the _head_enabled lesson)
            warnings.warn(
                f"one-pass scorer unavailable: no supported tile "
                f"divides T={plane.shape[1]}; falling back to the XLA "
                "chunked scorer", stacklevel=2)
        if use_score and pick_score_tile(plane.shape[1]):
            from .score_pallas import score_plane_pallas

            stacked = score_plane_pallas(plane, with_cert=with_cert,
                                         interpret=interpret)
        else:
            # row-chunked scoring bounds the scorer's HBM temps (see
            # score_profiles_chunked) while still emitting ONE (5, ndm)
            # array ((6, ndm) with the hybrid's certificate row) -> one
            # host readback round trip over the tunnel
            stacked = score_profiles_chunked(plane, jnp,
                                             with_cert=with_cert)
        return (stacked, plane) if with_plane else stacked

    return fn


@functools.lru_cache(maxsize=16)
def _build_transform(nchan, start_freq, bandwidth, max_delay, t, t_tile,
                     use_pallas, interpret, n_lo=0, with_scores=False,
                     with_plane=True, t_orig=None, with_cert=False,
                     use_head=False, use_score=False, deep_pair=False):
    """Jitted wrapper of :func:`_transform_fn` (same signature)."""
    import jax

    return jax.jit(_transform_fn(nchan, start_freq, bandwidth, max_delay,
                                 t, t_tile, use_pallas, interpret,
                                 n_lo=n_lo, with_scores=with_scores,
                                 with_plane=with_plane, t_orig=t_orig,
                                 with_cert=with_cert, use_head=use_head,
                                 use_score=use_score,
                                 deep_pair=deep_pair))


# ---------------------------------------------------------------------------
# Public transform + search
# ---------------------------------------------------------------------------

def fdmt_transform(data, max_delay, start_freq, bandwidth, use_pallas=None,
                   min_delay=0):
    """All integer-delay dedispersed series of ``data`` at once.

    Parameters
    ----------
    data : (nchan, T) array (host or device).
    max_delay : largest differential band delay (samples, inclusive).
    start_freq, bandwidth : band geometry in MHz (channel = lower edge,
        reference convention ``dedispersion.py:127,135``).
    use_pallas : force the Pallas (True) or XLA (False) merge; default
        auto (Pallas on TPU when a power-of-two tile divides T).
    min_delay : smallest band delay to compute (DM-range pruning — rows
        below it are never built; see :class:`FdmtPlan`).

    Returns
    -------
    (max_delay - min_delay + 1, T) float32 device array: row ``i`` sums
    one sample per channel along the track with band-crossing delay
    ``min_delay + i``, anchored at the top of the band.
    """
    import jax.numpy as jnp

    data = jnp.asarray(data, dtype=jnp.float32)
    nchan = data.shape[0]
    data, t_run, t_tile, use_pallas, interpret, t_orig = _transform_setup(
        data, use_pallas)

    # The whole transform runs as ONE jitted program: enqueueing the
    # merges eagerly allocates every intermediate state up-front (~4x the
    # live set — an HBM OOM at the 1M-sample size), whereas XLA's buffer
    # assignment inside a single program frees each state as soon as its
    # consumer has read it.
    run = _build_transform(nchan, float(start_freq), float(bandwidth),
                           int(max_delay), t_run, t_tile, use_pallas,
                           interpret, n_lo=int(min_delay), t_orig=t_orig,
                           use_head=_head_enabled(use_pallas),
                           deep_pair=_deep_pair_enabled())
    return run(data)


def fdmt_trial_dms(nchan, dmmin, dmmax, start_freq, bandwidth, sample_time):
    """The FDMT's integer band-delay trial grid on ``[dmmin, dmmax]``.

    Same one-sample spacing as the reference plan, but snapped to integer
    band delays — the reference's ``arange(min_n, max_n + 1)`` grid sits
    at the *fractional* offset of ``min_n`` (``dedispersion.py:165-168``),
    so DM values (and occasionally the trial count) differ from the plan
    by up to one trial.

    Returns ``(trial_dms, n_lo, n_hi)`` where rows ``n_lo..n_hi`` of the
    transform correspond to the returned DMs (same inversion as
    ``dedispersion_plan``, reference ``dedispersion.py:168-169``).
    """
    f0 = float(start_freq)
    f1 = f0 + float(bandwidth)
    n_lo = int(np.ceil(delta_delay(float(dmmin), f0, f1) / sample_time))
    n_hi = int(np.floor(delta_delay(float(dmmax), f0, f1) / sample_time))
    if n_hi < n_lo:
        # the range is narrower than one band-delay sample and straddles
        # no integer: return the single nearest trial (never an empty
        # grid — every other backend guarantees >= 1 trial)
        n_hi = n_lo
    trial_n = np.arange(n_lo, n_hi + 1)
    trial_dm = (trial_n * sample_time / DM_DELAY_CONST
                / (f0 ** -2.0 - f1 ** -2.0))
    return trial_dm, n_lo, n_hi
