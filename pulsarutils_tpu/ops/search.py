"""The dedispersion search: plan -> dedisperse every trial -> boxcar S/N.

Public entry point :func:`dedispersion_search` is the capability-equivalent
of the reference's fast/slow search façade
(``pulsarutils/dedispersion.py:205-251``) with its numba ``prange`` sweep
(``pulsarutils/dedispersion.py:174-202``), unified:

* one search implementation, optional dedispersed-plane capture (the
  reference had a second, older copy of the slow path in
  ``pulsarutils/clean.py:136-180`` — intentionally not reproduced);
* ``backend="numpy"`` keeps exact reference semantics (float64, same
  rounding, same scoring) and is the correctness/benchmark baseline;
* ``backend="jax"`` runs the whole sweep as one jitted program: the trial
  axis is processed in blocks via ``lax.map``, each block dedispersed by a
  batched gather (see :mod:`..ops.dedisperse`) and scored on device.  All
  shift/plan math is computed host-side in float64 and shipped as int32
  gather offsets (2 MB for 512 trials x 1024 chans) so hit detection is
  bit-identical to the NumPy path regardless of device precision.

Scoring (reference ``dedispersion.py:186-201``): for each trial, subtract
the mean, then for boxcar block-sums of width 1, 2, 4, 8 compute
``snr = max / std`` and keep the best; also record the peak and std of the
unbinned series.
"""

from __future__ import annotations

import functools
import logging
import os

import numpy as np

logger = logging.getLogger("pulsarutils_tpu")

from .dedisperse import dedisperse_batch_numpy, dedisperse_block_chunked_jax
from .plan import (
    dedispersion_plan,
    dedispersion_shifts_batch,
    normalize_shifts,
)
from .rebin import block_sum_time
from ..obs import roofline
from ..utils.logging_utils import budget_bucket, budget_count
from ..utils.table import ResultTable

#: boxcar widths tried by the scorer (reference ``dedispersion.py:190-191``)
SEARCH_WINDOWS = (1, 2, 4, 8)

#: sliding windows of the hybrid's certificate scorer.  SOUNDNESS
#: COUPLING: :func:`cert_profile_scores` unrolls exactly these widths
#: structurally, and ``certify._cert_retention_from_offsets`` computes
#: the retention bound over the same set — change all three together or
#: the noise certificate's bound no longer describes the scorer
#: (``tests/test_certify.py`` pins the coupling).
CERT_WINDOWS = (2, 3, 4)


def score_profiles(plane, xp=np):
    """Score a block of dedispersed series ``(ndm, T)``.

    Returns ``(maxvalues, stds, best_snrs, best_windows, best_peaks)`` per
    trial, reproducing the reference's per-trial loop
    (``pulsarutils/dedispersion.py:186-201``) in batched form, plus the
    peak's sample index in the unbinned series (``argmax`` of the best
    window's block sums, scaled back by the window — the reference threw
    the arrival time away; candidate sifting needs it).

    HBM-traffic transform (round 4), algebraically neutral — every
    backend shares this function, so cross-backend hit parity is
    untouched: the block-sum pyramid is incremental — width 4 sums
    width 2's output, width 8 sums width 4's — reading ~1.8 GB instead
    of 6.3 GB at the 513 x 1M coarse plane (identical sample coverage
    for any T: ``floor(floor(T/2)/2) == floor(T/4)``; only the float
    ASSOCIATION of the in-block adds changes).  The mean subtraction
    stays materialised up front: folding it into the reductions read
    catastrophically-cancelling raw block sums on planes with a large
    DC offset (measured S/N errors of several units at baseline ~1e7
    in float32 — code-review r4).
    """
    assert SEARCH_WINDOWS == (1, 2, 4, 8), \
        "the incremental pyramid assumes doubling windows"
    plane = xp.asarray(plane)
    if not xp.issubdtype(plane.dtype, xp.floating):
        # integer-accumulated sweep plane (packed low-bit path): every
        # value is an exact integer below 2^24 (io/lowbit.accum_dtype's
        # bound), so this float32 view is exact and the scores are
        # bit-identical to a float32-accumulated plane's
        plane = plane.astype(xp.float32)
    x = plane - plane.mean(axis=1, keepdims=True)
    maxvalues = x.max(axis=1)
    stds = x.std(axis=1)

    best_snrs = xp.zeros(x.shape[0], dtype=x.dtype)
    best_windows = xp.zeros(x.shape[0], dtype=xp.int32)
    best_peaks = xp.zeros(x.shape[0], dtype=xp.int32)
    reb = x
    for window in SEARCH_WINDOWS:
        if window > 1:
            reb = block_sum_time(reb, 2, xp=xp)
        snr = reb.max(axis=1) / reb.std(axis=1)
        peak = xp.argmax(reb, axis=1).astype(xp.int32) * window
        better = snr > best_snrs
        best_snrs = xp.where(better, snr, best_snrs)
        best_windows = xp.where(better, window, best_windows)
        best_peaks = xp.where(better, peak, best_peaks)
    return maxvalues, stds, best_snrs, best_windows, best_peaks


def warn_peak_exactness(nsamples, stacklevel=3):
    """Warn when float32 peak-index accumulation loses exactness.

    Stacked score packs carry the peak sample index as float32, exact
    only below 2^24; every scorer that emits such a pack (the XLA
    :func:`score_profiles_stacked` and the one-pass Pallas
    :func:`..ops.score_pallas.score_plane_pallas`) shares this check so
    no path silently accepts an over-long series (ADVICE r5).  The
    bound itself is owned by :func:`..precision.exactness_domain`
    (ISSUE 17) — this is a consumer, not a second copy of 2^24.
    """
    from ..precision import exactness_domain

    dom = exactness_domain(1, nsamples=nsamples)
    if not dom.peak_index_exact:
        import warnings

        warnings.warn(
            f"series length {nsamples} exceeds 2^24: float32 peak "
            "indices lose exactness (off by up to "
            f"{dom.index_error_samples:.1f} samples)",
            stacklevel=stacklevel)


def score_profiles_stacked(plane, xp=np):
    """:func:`score_profiles` packed into ONE ``(5, ndm)`` float array.

    The tunnelled-TPU transfer layer pays a full round trip per array
    fetched; stacking the per-trial score vectors device-side makes the
    whole search's host readback a single transfer.  Row order:
    ``max, std, snr, window, peak`` (windows are 1..8 and peaks are
    sample indices < 2^24 — both exact in float32).
    """
    warn_peak_exactness(plane.shape[1])
    scores = score_profiles(plane, xp=xp)
    dtype = scores[0].dtype
    return xp.stack([s.astype(dtype) for s in scores])


def cert_profile_scores(plane, xp=np):
    """Sliding-window certificate score per row of a (coarse) plane.

    ``max_t (x * box_w)(t) / (std * sqrt(w))`` for ``w`` in (2, 3, 4)
    over ALL alignments (sliding, circular) — unlike the detection scorer's
    non-sliding block sums, this capture is pulse-phase-invariant, which
    is what makes the hybrid's structural bounds usable: a pulse whose
    energy the tree scatters over a few adjacent bins always shows a
    sliding-window capture near its full mass, whereas a block boxcar at
    the worst phase splits it (the difference between a worst-case
    retention of ~0.6 and ~0.44 at the benchmark config — see
    :mod:`.certify`).  Used only on the hybrid's coarse plane; detection
    scores keep the reference's block convention.
    """
    assert CERT_WINDOWS == (2, 3, 4), \
        "cert_profile_scores structurally unrolls widths 2/3/4"
    plane = xp.asarray(plane)
    # the mean subtraction is materialised (NOT folded into the maxima):
    # raw sliding sums cancel catastrophically at large DC offsets in
    # float32 — see score_profiles
    x = plane - plane.mean(axis=1, keepdims=True)
    std = x.std(axis=1)
    s2 = x + xp.roll(x, -1, axis=1)
    best = s2.max(axis=1) / (std * np.float32(np.sqrt(2.0)))
    s3 = s2 + xp.roll(x, -2, axis=1)
    best = xp.maximum(best, s3.max(axis=1) / (std * np.float32(np.sqrt(3.0))))
    s4 = s2 + xp.roll(s2, -2, axis=1)
    return xp.maximum(best, s4.max(axis=1) / (std * np.float32(2.0)))


def score_profiles_chunked(plane, xp, chunk=512, with_cert=False):
    """:func:`score_profiles_stacked` over row chunks of a large plane.

    Whole-plane scoring materialises the mean-subtracted copy plus four
    boxcar block-sum arrays (~1.9x the plane) all at once — an HBM OOM
    at multi-thousand-trial x long-T shapes on a 16 GB chip.  The
    statically-unrolled chunk loop bounds the scorer's live temps to
    ~``chunk/ndm`` of that, still emitting ONE ``(5, ndm)`` array (one
    host readback round trip) — ``(6, ndm)`` with ``with_cert`` (the
    hybrid's sliding certificate row appended).  The cert row's three
    sliding sums add ~3 more plane-sized temps, so its chunk is capped
    at 128 rows: at 512 x 1M the uncapped 512-row chunk pushed the
    coarse program to a measured 16.25 GB HBM compile-OOM.
    """
    if with_cert:
        chunk = min(chunk, 128)
    rows = plane.shape[0]

    def one(sub):
        stacked = score_profiles_stacked(sub, xp=xp)
        if with_cert:
            stacked = xp.concatenate(
                [stacked, cert_profile_scores(sub, xp=xp)[None]])
        return stacked

    return xp.concatenate(
        [one(plane[lo:min(lo + chunk, rows)])
         for lo in range(0, rows, chunk)], axis=1)


def unstack_scores(stacked):
    """Host-side inverse of :func:`score_profiles_stacked` (one readback).

    Accepts the 5-row pack or the 6-row ``with_cert`` pack; the cert row
    (when present) is returned as-is as a sixth element.
    """
    stacked = np.asarray(stacked)
    maxvalues, stds, best_snrs, wins, peaks = stacked[:5]
    out = (maxvalues, stds, best_snrs, np.rint(wins).astype(np.int32),
           np.rint(peaks).astype(np.int64))
    if stacked.shape[0] > 5:
        out = out + (stacked[5],)
    return out


#: soft cap on the gather workspace (elements) a single trial-block may
#: materialise; keeps the kernel HBM-resident at 1M-sample configs
GATHER_BUDGET_ELEMENTS = 1 << 28


def auto_chan_block(nchan, nsamples, dm_block):
    """Largest power-of-two channel block that (a) divides ``nchan`` and
    (b) keeps ``dm_block * chan_block * nsamples`` under the gather budget.

    Returns ``None`` (no chunking) when the whole channel axis fits.
    """
    if dm_block * nchan * nsamples <= GATHER_BUDGET_ELEMENTS:
        return None
    block = 1
    candidate = 2
    while candidate <= nchan:
        if (nchan % candidate == 0
                and dm_block * candidate * nsamples <= GATHER_BUDGET_ELEMENTS):
            block = candidate
        candidate *= 2
    return block


def _offsets_for(trial_dms, nchan, start_freq, bandwidth, sample_time, nsamples):
    """Host-side float64 shift table -> int32 gather offsets in ``[0, T)``."""
    shifts = dedispersion_shifts_batch(
        np.asarray(trial_dms, dtype=np.float64), nchan, start_freq, bandwidth,
        sample_time)
    return normalize_shifts(shifts, nsamples)


def block_offsets(offsets, dm_block):
    """Pad the trial axis to a multiple of ``dm_block`` (duplicating the
    last trial — sliced off after the kernel) and reshape to the
    ``(nblocks, dm_block, nchan)`` layout :func:`search_kernel_fn` takes."""
    ndm, nchan = offsets.shape
    npad = (-ndm) % dm_block
    if npad:
        offsets = np.concatenate([offsets, offsets[-1:].repeat(npad, axis=0)])
    return offsets.reshape(-1, dm_block, nchan)


# ---------------------------------------------------------------------------
# NumPy backend
# ---------------------------------------------------------------------------

def _search_numpy(data, trial_dms, start_freq, bandwidth, sample_time,
                  capture_plane):
    data = np.asarray(data, dtype=np.float64)
    nchan, nsamples = data.shape
    ndm = len(trial_dms)
    offsets = _offsets_for(trial_dms, nchan, start_freq, bandwidth,
                           sample_time, nsamples)

    if capture_plane == "memmap":
        plane = plane_memmap(ndm, nsamples)  # float32 on disk (16 GB at
        # 4096 x 1M in float64 would double the spill for scores the
        # jax paths keep in float32 anyway); scoring stays float64
    elif capture_plane:
        plane = np.empty((ndm, nsamples), dtype=np.float64)
    else:
        plane = None
    maxvalues = np.empty(ndm)
    stds = np.empty(ndm)
    best_snrs = np.empty(ndm)
    best_windows = np.empty(ndm, dtype=np.int32)
    best_peaks = np.empty(ndm, dtype=np.int64)

    budget_count("host_sweeps")
    block = 16  # score in small batches to bound the workspace
    work = np.empty((block, nsamples))
    for lo in range(0, ndm, block):
        hi = min(lo + block, ndm)
        sub = work[:hi - lo]
        dedisperse_batch_numpy(data, offsets[lo:hi], out=sub)
        if capture_plane:
            plane[lo:hi] = sub
        m, s, b, w, p = score_profiles(sub)
        maxvalues[lo:hi] = m
        stds[lo:hi] = s
        best_snrs[lo:hi] = b
        best_windows[lo:hi] = w
        best_peaks[lo:hi] = p

    return maxvalues, stds, best_snrs, best_windows, best_peaks, plane


# ---------------------------------------------------------------------------
# JAX backend
# ---------------------------------------------------------------------------

def search_kernel_fn(data, offset_blocks, capture_plane=False,
                     chan_block=None, formulation=None, policy=None):
    """The pure, jittable forward step of the search (flagship kernel).

    ``data`` is ``(nchan, T)``; ``offset_blocks`` is
    ``(nblocks, dm_block, nchan)`` int32 gather offsets.  Returns the
    per-block stacked scores ``(nblocks, 5, dm_block)`` (see
    :func:`score_profiles_stacked`) — plus the dedispersed plane blocks
    when ``capture_plane``.  Traceable under ``jit``/``shard_map``; the
    blocks are processed by ``lax.map`` so the compiled program is
    independent of the trial count.  ``formulation`` forces the
    dedisperse formulation (``"gather"``/``"roll"``; ``None`` =
    backend-resolved) — the axis the autotuner measures.  ``policy``
    names a :mod:`..precision` accumulation strategy for the channel
    reduction (``None`` = the byte-identical ``f32`` default) — the
    second axis the autotuner measures (ISSUE 17).
    """
    import jax
    import jax.numpy as jnp

    def per_block(offs):
        plane = dedisperse_block_chunked_jax(data, offs, chan_block,
                                             formulation=formulation,
                                             policy=policy)
        scores = score_profiles_stacked(plane, xp=jnp)
        if capture_plane:
            return scores, plane
        return scores

    return jax.lax.map(per_block, offset_blocks)


@functools.lru_cache(maxsize=32)
def _jax_search_kernel(capture_plane, chan_block, formulation=None,
                       packed=None, policy=None):
    """The direct-sweep program.  ``packed`` (a
    :meth:`~pulsarutils_tpu.io.lowbit.PackedFrames.meta` tuple) makes
    ``data`` the RAW packed uint8 frames: the bit-unpack runs inside
    this jit, so the host->device link carries 1/8-1/16th the bytes and
    — when the meta names an integer dtype — the sweep accumulates in
    int16/int32 (exact; converted to float32 only at scoring)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(data, offset_blocks):
        if packed is not None:
            from ..io.lowbit import unpack_from_meta

            data = unpack_from_meta(data, packed, jnp)
        return search_kernel_fn(data, offset_blocks,
                                capture_plane=capture_plane,
                                chan_block=chan_block,
                                formulation=formulation,
                                policy=policy)

    return kernel


#: trials dedispersed per Pallas pass — bounds the live plane to
#: superblock * nsamples floats (512 x 1M = 2 GB) regardless of ndm
PALLAS_SUPERBLOCK = 512


def plane_memmap(ndm, nsamples, directory=None, delete=False):
    """A disk-backed ``(ndm, nsamples)`` float32 plane (``.npy`` memmap).

    The reference spills its dedispersed plane to a disk memmap so
    ``show=True`` works at any size (``pulsarutils/dedispersion.py:
    215-218``); this is the equivalent for ``capture_plane="memmap"`` —
    a 4096-trial x 1M-sample capture is 16 GB, beyond host RAM on many
    driver nodes.  The file is a valid ``.npy`` (``np.load(...,
    mmap_mode=...)`` reopens it); its path is ``plane.filename``.
    Directory: ``directory`` arg, else ``$PUTPU_PLANE_DIR``, else the
    system temp dir (size that directory for ndm*nsamples*4 bytes per
    concurrent capture).  Deletion: by default the file persists so
    diagnostics can outlive the search — free it with
    :func:`release_plane` (or ``os.unlink(plane.filename)``) when done;
    ``delete=True`` instead ties the file's lifetime to the returned
    memmap (``weakref.finalize`` unlinks it at garbage collection), so
    repeated captures cannot silently fill the temp dir.
    """
    import tempfile
    import weakref

    directory = directory or os.environ.get("PUTPU_PLANE_DIR") or None
    fd, path = tempfile.mkstemp(suffix=".npy", prefix="putpu_plane_",
                                dir=directory)
    os.close(fd)
    mm = np.lib.format.open_memmap(path, mode="w+", dtype=np.float32,
                                   shape=(int(ndm), int(nsamples)))
    if delete:
        weakref.finalize(mm, _unlink_quiet, path)
    return mm


def _unlink_quiet(path):
    try:
        os.unlink(path)
    except OSError:
        pass


def release_plane(plane):
    """Unlink the disk file behind a :func:`plane_memmap` capture.

    Accepts any plane a search returned: a plain ndarray (no-op) or a
    ``np.memmap``-backed capture, whose ``.npy`` file is removed.  Safe
    to call twice.
    """
    path = getattr(plane, "filename", None)
    if path:
        _unlink_quiet(path)


@functools.lru_cache(maxsize=8)
def _jitted_scorer():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def score(plane):
        return score_profiles_stacked(plane, xp=jnp)

    return score


def _search_jax_pallas(data, offsets, capture_plane, dm_block=None,
                       chan_block=None):
    """Pallas-kernel sweep: dedisperse in trial superblocks, score each."""
    from .pallas_dedisperse import dedisperse_plane_pallas

    ndm = offsets.shape[0]
    nsamples = int(np.shape(data)[1])
    scorer = _jitted_scorer()
    mm = plane_memmap(ndm, nsamples) if capture_plane == "memmap" else None
    outs, planes = [], []
    for lo in range(0, ndm, PALLAS_SUPERBLOCK):
        sub = offsets[lo:lo + PALLAS_SUPERBLOCK]
        with budget_bucket("search/dispatch"):
            plane = dedisperse_plane_pallas(data, sub, dm_block=dm_block,
                                            chan_block=chan_block)
            scored = scorer(plane)
            budget_count("dispatches", 2)
        with budget_bucket("search/readback"):
            outs.append(unstack_scores(scored))  # one readback
            budget_count("readbacks")
        if mm is not None:
            # disk spill (reference memmap parity, dedispersion.py:
            # 215-218): host RAM holds one superblock transiently, disk
            # holds the plane — any ndm x T capture in bounded memory.
            # The spill is the LARGEST single transfer in a capture run,
            # so it gets its own bucket + trip count
            with budget_bucket("search/plane_spill"):
                mm[lo:lo + plane.shape[0]] = np.asarray(plane)
                budget_count("readbacks")
        elif capture_plane:
            # single superblock: keep the plane device-resident so
            # downstream consumers (plane period search, diagnostics)
            # pull only what they need over the slow host link.  Multiple
            # superblocks: spill each to host as it completes — device
            # concatenation would hold all blocks plus the result (2x the
            # full plane) in HBM, breaking the PALLAS_SUPERBLOCK bound.
            if ndm <= PALLAS_SUPERBLOCK:
                planes.append(plane)
            else:
                with budget_bucket("search/plane_spill"):
                    planes.append(np.asarray(plane))
                    budget_count("readbacks")
    maxvalues, stds, best_snrs, best_windows, best_peaks = (
        np.concatenate([o[i] for o in outs]) for i in range(5))
    if mm is not None:
        mm.flush()
        plane = mm
    elif not capture_plane:
        plane = None
    elif len(planes) == 1:
        plane = planes[0]
    else:
        plane = np.concatenate(planes)
    return maxvalues, stds, best_snrs, best_windows, best_peaks, plane


def _search_jax_fdmt(data, dmmin, dmmax, start_freq, bandwidth, sample_time,
                     capture_plane, with_cert=False):
    """FDMT sweep: every integer-delay trial in one log-depth transform.

    Trial grid is the FDMT's natural (= the reference plan's) integer
    band-delay grid on ``[dmmin, dmmax]`` — see
    :func:`pulsarutils_tpu.ops.fdmt.fdmt_trial_dms`.  ``with_cert``
    appends the sliding certificate row (hybrid's coarse stage).
    """
    import jax.numpy as jnp

    from .fdmt import (_build_transform, _head_enabled,
                       _score_kernel_choice, _transform_setup,
                       fdmt_trial_dms)

    nchan = data.shape[0]
    trial_dms, n_lo, n_hi = fdmt_trial_dms(nchan, dmmin, dmmax, start_freq,
                                           bandwidth, sample_time)
    data = jnp.asarray(data, jnp.float32)
    data, t_run, t_tile, use_pallas, interpret, t_orig = _transform_setup(
        data, None)
    # scoring (and the row slice) run inside the transform's jit: only
    # the per-trial score vectors (and optionally the plane) leave the
    # device, keeping back-to-back searches within HBM
    from .fdmt import _deep_pair_enabled

    run = _build_transform(nchan, float(start_freq), float(bandwidth),
                           n_hi, t_run, t_tile, use_pallas, interpret,
                           n_lo=n_lo, with_scores=True,
                           with_plane=capture_plane, t_orig=t_orig,
                           with_cert=with_cert,
                           use_head=_head_enabled(use_pallas),
                           use_score=_score_kernel_choice(use_pallas,
                                                          interpret),
                           deep_pair=_deep_pair_enabled())
    roof = roofline.begin()
    with budget_bucket("search/coarse"):
        out = run(data)
        budget_count("dispatches")
    if capture_plane:
        stacked, plane_out = out  # plane stays device-resident
    else:
        stacked, plane_out = out, None
    with budget_bucket("search/coarse_readback"):
        scores = unstack_scores(stacked)
        budget_count("readbacks")
    roofline.end(roof, "fdmt_coarse", run, (data,))
    (maxvalues, stds, best_snrs, best_windows, best_peaks) = scores[:5]
    out = (trial_dms, maxvalues, stds, best_snrs, best_windows, best_peaks,
           plane_out)
    if with_cert:
        out = out + (scores[5],)
    return out


def _search_jax(data, trial_dms, start_freq, bandwidth, sample_time,
                capture_plane, dm_block, chan_block, dtype, kernel="auto",
                precision=None):
    import jax
    import jax.numpy as jnp

    from ..io.lowbit import PackedFrames, accum_dtype
    from ..precision import engage as _engage
    from ..precision import resolve_policy as _resolve_policy

    # explicit precision wins; else PUTPU_PRECISION; else "f32".  "auto"
    # defers to the autotuner once the formulation is known (below).
    eff_policy = _resolve_policy(precision)
    packed = data if isinstance(data, PackedFrames) else None
    nchan, nsamples = np.shape(data)  # PackedFrames reports its logical shape
    ndm = len(trial_dms)
    if packed is not None and dtype not in (None, jnp.float32):
        raise ValueError("packed low-bit input unpacks to float32 (or an "
                         "exact integer accumulator); pass dtype=None")

    if kernel == "fourier":
        from .fourier import search_fourier

        if eff_policy not in ("f32", "auto"):
            raise ValueError("precision policies apply to the gather/roll "
                             "channel reductions; kernel='fourier' is "
                             "float32-only")
        if capture_plane == "memmap":
            raise ValueError("capture_plane='memmap' requires "
                             "kernel='pallas'/'auto' or backend='numpy'")
        if dtype not in (None, jnp.float32):
            raise ValueError("kernel='fourier' supports float32 only")
        if packed is not None:
            # FDD wants the float block: packed upload + cached device
            # unpack (the link still carries the packed bytes)
            data = packed.to_device()
        # before the integer-offset table: the FDD uses un-rounded delays
        # (and data passes through untouched — converting a
        # device-resident chunk would bounce it over the slow link)
        return search_fourier(data, trial_dms, start_freq, bandwidth,
                              sample_time, capture_plane=capture_plane,
                              dm_block=dm_block, chan_block=chan_block)

    offsets = _offsets_for(trial_dms, nchan, start_freq, bandwidth,
                           sample_time, nsamples)

    if kernel == "auto":
        # measured per-(backend, geometry) selection with a persistent
        # tune cache (the PAPERS.md auto-tuning survey's lesson, made
        # operational).  The static heuristic — Pallas on TPU, roll-scan
        # on CPU (PR 1's measured 14x), gather elsewhere — stays as the
        # zero-measurement fallback and the PUTPU_AUTOTUNE=off escape
        # hatch; a winner is only ever cached after passing the
        # exact-hit-match equivalence harness.
        from ..tuning import autotune as _autotune

        kernel = _autotune.resolve_search_kernel(
            nchan, nsamples, ndm, dtype, capture_plane, start_freq,
            bandwidth, sample_time, trial_dms, dm_block=dm_block,
            chan_block=chan_block)
    if kernel in ("gather", "roll") and capture_plane == "memmap":
        raise ValueError("capture_plane='memmap' requires the Pallas "
                         "spill path (kernel='pallas'/'auto' with the "
                         "default float32 dtype) or backend='numpy' — "
                         "the gather/roll kernels hold the full plane in "
                         "device memory, and the Pallas kernel is "
                         "float32-only")
    if kernel == "pallas":
        if eff_policy not in ("f32", "auto"):
            raise ValueError("precision policies apply to the gather/roll "
                             "channel reductions; kernel='pallas' declares "
                             "its own f32 accumulation")
        if dtype not in (None, jnp.float32):
            raise ValueError("kernel='pallas' supports float32 only; use "
                             "kernel='gather' for other dtypes")
        if packed is not None:
            data = packed.to_device()  # packed upload, unpack on HBM
        data = jnp.asarray(data, dtype=jnp.float32)
        return _search_jax_pallas(data, offsets, capture_plane, dm_block,
                                  chan_block)
    packed_meta = None
    if packed is not None:
        # in-jit unpack for the traceable formulations: the RAW bytes
        # are the program's operand.  Integer accumulation only when
        # the plane never leaves the program (capture consumers expect
        # a float plane) and the exactness bound holds.
        acc = (None if capture_plane
               else accum_dtype(packed.nbits, nchan)) or "float32"
        packed_meta = packed.meta(acc)
        data = packed.frames
    dtype = dtype or jnp.float32
    data = (jnp.asarray(data) if packed_meta is not None
            else jnp.asarray(data, dtype=dtype))

    if dm_block is None:
        dm_block = max(1, min(ndm, 32))
    if chan_block is None:
        chan_block = auto_chan_block(nchan, nsamples, dm_block)
    offset_blocks = block_offsets(offsets, dm_block)

    # both spellings force their formulation (an auto-resolving
    # "gather" would make the CPU tuner measure the same program twice
    # and never reproduce PR 1's 14x) — pre-tuner "auto" callers are
    # unaffected because the static fallback names the formulation the
    # old backend switch picked ("roll" on CPU, the gather elsewhere)
    from ..resilience import ladder as _ladder
    from ..resilience import memory_budget as _membudget

    formulation = (kernel if kernel in ("gather", "roll")
                   else ("roll" if jax.default_backend() == "cpu"
                         else "gather"))
    if eff_policy == "auto":
        # measured (kernel, policy)-pair selection (ISSUE 17): a
        # non-default strategy only ever wins after the exact-hit-match
        # harness passes at its stated bound; the static fallback is
        # the formulation's plain f32 pairing.
        from ..tuning import autotune as _autotune

        pair = _autotune.resolve_search_policy(
            formulation, nchan, nsamples, ndm, start_freq, bandwidth,
            sample_time, trial_dms, dm_block=dm_block,
            chan_block=chan_block)
        eff_policy = pair.split("+", 1)[1]
    policy_arg = None if eff_policy == "f32" else eff_policy
    if policy_arg is not None:
        _engage(policy_arg)
    nblocks = len(offset_blocks)
    # preflight (ISSUE 12): a dispatch whose footprint estimate exceeds
    # measured headroom splits BEFORE compiling — no-op when headroom
    # is unknown (the CPU default), so the default path is byte-inert
    _membudget.preflight_direct(
        formulation, nchan, nsamples, ndm, dm_block=dm_block,
        chan_block=chan_block, capture_plane=bool(capture_plane),
        nblocks=nblocks,
        packed_nbits=packed_meta[0] if packed_meta else 0)
    while True:
        passes = _ladder.direct_plan(formulation, nblocks)
        try:
            stacked, plane_blocks = _dispatch_direct(
                data, offset_blocks, capture_plane, chan_block, kernel,
                packed_meta, passes, policy=policy_arg)
            break
        except (ValueError, TypeError):
            raise  # deterministic configuration error, never OOM
        except Exception as exc:  # jax errors share no base class
            if not _ladder.is_resource_exhausted(exc) \
                    or _ladder.direct_maxed(formulation, nblocks):
                raise
            # RESOURCE_EXHAUSTED: descend the ladder and re-dispatch
            # smaller — byte-identical by construction (per-trial rows
            # are independent sums; gather columns are independent)
            _ladder.oom_event("direct_sweep")
            step = _ladder.direct_step(formulation)
            logger.warning("direct sweep OOM (%r); ladder step %r",
                           exc, step)
            _ladder.descend(step)
            _ladder.count_split("ladder")
    if _membudget.allocator_reports_limit():
        # calibration loop (ISSUE 12): fold this dispatch's allocator
        # high-water mark against the model's estimate into the
        # persisted per-geometry offset.  Gated on a REAL allocator
        # limit — the CPU live-array fallback has no watermark to
        # learn from (and must not pay a live_arrays sweep here).
        _membudget.observe(nchan, nsamples, ndm, _membudget.estimate_direct(
            nchan, nsamples, ndm, dm_block=dm_block,
            chan_block=chan_block, formulation=formulation,
            capture_plane=bool(capture_plane), dm_passes=passes,
            packed_nbits=packed_meta[0] if packed_meta else 0)["total"])
    stacked = stacked.transpose(1, 0, 2).reshape(5, -1)[:, :ndm]
    (maxvalues, stds, best_snrs, best_windows,
     best_peaks) = unstack_scores(stacked)
    if capture_plane:  # keep device-resident (see _search_jax_pallas)
        plane = plane_blocks.reshape(-1, *plane_blocks.shape[2:])
        if plane.shape[0] != ndm:  # slicing outside jit is a real copy
            plane = plane[:ndm]
    else:
        plane = None
    return maxvalues, stds, best_snrs, best_windows, best_peaks, plane


def _dispatch_direct(data, offset_blocks, capture_plane, chan_block,
                     formulation, packed_meta, passes, policy=None):
    """One direct-sweep dispatch at the given degradation level.

    ``passes == 1`` is the exact pre-resilience path (single dispatch,
    plane kept device-resident).  Degraded levels split the trial-block
    axis into ``passes`` dispatches of the SAME compiled per-block body
    — each pass's buffers die before the next dispatch, which is the
    footprint reduction, and because only the ``lax.map``-ed outer axis
    shrinks (every per-block shape is unchanged) the concatenated score
    packs and captured plane are byte-identical to the unsplit run
    (``tests/test_resilience.py`` pins it; splitting the *inner* time
    axis was tested and rejected — XLA reassociates the channel
    reduction when the column extent changes, see docs/robustness.md).
    """
    import jax.numpy as jnp

    kernel_fn = _jax_search_kernel(capture_plane, chan_block, formulation,
                                   packed_meta, policy)
    if passes <= 1:
        roof = roofline.begin()  # wall spans dispatch -> readback
        with budget_bucket("search/dispatch"):
            offs_dev = jnp.asarray(offset_blocks)  # attributed
            out = kernel_fn(data, offs_dev)
            budget_count("dispatches")
        stacked = out[0] if capture_plane else out  # (nblocks, 5, dmb)
        with budget_bucket("search/readback"):
            stacked = np.asarray(stacked)
            budget_count("readbacks")
        roofline.end(roof, "gather_sweep", kernel_fn, (data, offs_dev))
        return stacked, (out[1] if capture_plane else None)
    parts = []
    planes = []
    for sub in np.array_split(offset_blocks, passes):
        if not len(sub):
            continue
        with budget_bucket("search/dispatch"):
            offs_dev = jnp.asarray(sub)
            out = kernel_fn(data, offs_dev)
            budget_count("dispatches")
        with budget_bucket("search/readback"):
            parts.append(np.asarray(out[0] if capture_plane else out))
            budget_count("readbacks")
            if capture_plane:
                # degraded mode trades plane residency for footprint:
                # each pass's plane blocks spill to host so at most one
                # pass's worth of plane lives in HBM
                planes.append(np.asarray(out[1]))
                budget_count("readbacks")
    stacked = np.concatenate(parts, axis=0)
    return stacked, (np.concatenate(planes, axis=0) if capture_plane
                     else None)


#: rescore-call row buckets (requested rows pad up to the next bucket);
#: a small set of static shapes keeps compiles bounded while not paying
#: the biggest block's VPU cost for a handful of rows.  The 32-row top
#: bucket matters for LARGE rescans (the round-budget fallback rescores
#: every remaining row — halving the top bucket would double its tunnel
#: dispatches); the fused seed uses its own smaller
#: :data:`HYBRID_SEED_BUCKET`.
HYBRID_RESCORE_BUCKETS = (8, 16, 32)

#: hard cap on guarantee-loop iterations before the hybrid falls back to
#: rescoring every remaining candidate row (correctness is then trivial)
HYBRID_MAX_ROUNDS = 20

#: structural bound on how much of a real pulse's S/N the coarse (FDMT)
#: sweep can lose to tree track rounding: every unrescored row whose
#: coarse S/N is within this fraction of the exact best gets rescored
#: regardless of the adaptively-observed error (guards against the
#: observed-error sample being biased toward the peak, where the coarse
#: score tracks well).  MEASURED (round 3, ops/certify.py — worst-case
#: retention computed exactly from the transform's own merge tables):
#: at the 1024-chan / 1M-sample / DM 300-635 headline config the block
#: detection scorer retains >= 0.436 of a worst-phase width-1 pulse's
#: exact S/N (mean 0.60), so the matching margin fraction is
#: 1 - 0.436 = 0.564 — the round-2 hand value of 0.45 was slightly
#: optimistic at the worst phase and is corrected here.  This constant
#: is only the FALLBACK for callers that do not supply the sliding
#: certificate scores; the hybrid itself now uses the per-config
#: phase-invariant bound (``certify.cert_retention``) — computed rather
#: than hand-set, and tighter (~0.56 retention; sound up to the noise
#: cross-term, see certify's *Miss risk* section).
HYBRID_COARSE_TRUST = 0.60


def iter_rescore_buckets(rows):
    """Yield ``(rows_block, padded_block)`` per fixed-shape bucket.

    Splits a rescore request into :data:`HYBRID_RESCORE_BUCKETS`-sized
    blocks, each padded (repeating the last row) up to the next bucket —
    a small set of static shapes keeps compiles bounded while not paying
    the biggest block's cost for a handful of rows.  Shared by the
    single-device and sharded hybrids.
    """
    rows = np.asarray(rows)
    top = HYBRID_RESCORE_BUCKETS[-1]
    for blk_lo in range(0, len(rows), top):
        blk = rows[blk_lo:blk_lo + top]
        bucket = next(b for b in HYBRID_RESCORE_BUCKETS if b >= len(blk))
        yield blk, np.concatenate(
            [blk, blk[-1:].repeat(bucket - len(blk))])


def nearest_rows(sorted_grid, targets):
    """Index of the nearest ``sorted_grid`` entry for each target value.

    Maps plan-grid trial DMs onto the coarse integer-band-delay grid
    (both sorted, one-sample spacing, offset < 1 trial apart) — shared
    by the single-device and sharded hybrid searches.
    """
    sorted_grid = np.asarray(sorted_grid)
    targets = np.asarray(targets)
    pos = np.searchsorted(sorted_grid, targets)
    lo = np.clip(pos - 1, 0, len(sorted_grid) - 1)
    hi = np.clip(pos, 0, len(sorted_grid) - 1)
    return np.where(np.abs(sorted_grid[lo] - targets)
                    <= np.abs(sorted_grid[hi] - targets), lo, hi)


def hybrid_guarantee_loop(coarse_snrs, snrs, exact, rescore,
                          snr_floor=None, seed_done=False,
                          cert_scores=None, rho_cert=None,
                          cert_slack=None):
    """The hybrid's seed + guarantee iteration (see
    :func:`_search_jax_hybrid` for the full rationale).

    ``snrs``/``exact`` are mutated in place by ``rescore(rows)``.

    With ``cert_scores``/``rho_cert`` supplied (the sliding certificate
    row and the per-config retention bound, :mod:`.certify`), the loop
    uses the cert-based skip criterion: row ``j`` is left unrescored
    only when ``(cert_j + HYBRID_CERT_SLACK) / rho_cert < best_exact``
    — an impulsive signal beating the exact best would show a
    certificate score above that line, so skipped rows cannot hold the
    best hit *under the stated signal model, up to the Gaussian noise
    cross-term the slack absorbs* (sd <= 1 S/N unit; at the default
    slack an at-worst-phase row whose true S/N exactly ties the best
    retains a ``Phi(-0.5)`` ~ 31% chance of evading rescoring — see
    :mod:`.certify`'s *Miss risk* section; the probability collapses as
    the true gap grows, and such a tie is score-equivalent anyway).
    This replaces the round-2 heuristic margins (1.5x the *observed*
    underestimate — a peak-biased sample — and the hand-set
    :data:`HYBRID_COARSE_TRUST` fraction), which the round-3 worst-case
    analysis showed could in principle skip a worst-phase width-1
    pulse deterministically.  Consequence worth knowing: on chunks
    whose best is barely above the noise (no certificate, no bright
    pulse) the cert-based criterion rescans honestly toward a full
    exact sweep — the noise-certificate fast path, not the margin, is
    what makes signal-free chunks cheap.

    Without cert scores the legacy margins apply (conservative fallback
    for callers that only have block coarse scores).  ``seed_done=True``
    skips the seeding round (the fused TPU program already rescored it).
    ``cert_slack`` overrides :data:`~.certify.HYBRID_CERT_SLACK` in the
    skip criterion (derive it from a target miss probability with
    :func:`~.certify.cert_slack_for_miss_p`).
    """
    from .certify import HYBRID_CERT_SLACK

    if cert_slack is None:
        cert_slack = HYBRID_CERT_SLACK
    ndm = len(coarse_snrs)
    if not seed_done:
        seed = (coarse_snrs >= coarse_snrs.max() - 0.5)
        if snr_floor is not None:
            seed |= coarse_snrs >= snr_floor - 0.75
        seed_idx = np.flatnonzero(seed)
        grown = np.unique(np.clip(seed_idx[:, None]
                                  + np.arange(-1, 2)[None, :], 0, ndm - 1))
        rescore(grown)
    cert_based = cert_scores is not None and rho_cert is not None
    for _round in range(HYBRID_MAX_ROUNDS):
        best_exact = snrs[exact].max()
        if cert_based:
            need = (~exact) & (cert_scores
                               >= rho_cert * best_exact - cert_slack)
            # consistency guard (mirrors certify_noise_only's): a row
            # whose DISPLAYED coarse block score already beats the exact
            # best must be rescored even if its sliding cert score is
            # low (single-spike-with-negative-dips junk outside the
            # impulsive model) — otherwise argbest could land on a
            # non-exact row, breaking the exact-argbest contract
            need |= (~exact) & (coarse_snrs >= best_exact)
            if snr_floor is not None:
                need |= (~exact) & (cert_scores >= rho_cert * snr_floor
                                    - cert_slack)
                # same consistency guard for the floor contract: a row
                # DISPLAYING an above-floor coarse score must be exact
                need |= (~exact) & (coarse_snrs >= snr_floor)
        else:
            under = (snrs[exact] - coarse_snrs[exact]).max(initial=0.0)
            margin = max(1.5 * under, HYBRID_COARSE_TRUST * best_exact, 0.25)
            need = (~exact) & (coarse_snrs >= best_exact - margin)
            if snr_floor is not None:
                need |= (~exact) & (coarse_snrs >= snr_floor - 0.75)
        todo = np.flatnonzero(need)
        if todo.size == 0:
            break
        rescore(todo)
    else:
        # round budget exhausted: rescore EVERY remaining row, exactly as
        # documented at HYBRID_MAX_ROUNDS — a narrower criterion here
        # (e.g. best_exact - 0.25) could leave a row whose coarse score
        # understates the true best unrescored, silently voiding the
        # exact-hit guarantee in precisely the pathological cases this
        # cap exists for
        todo = np.flatnonzero(~exact)
        if todo.size:
            rescore(todo)


def hybrid_certificate_gate(cert_scores, coarse_snrs, snrs, exact, rescore,
                            *, nchan, trial_dms, start_freq, bandwidth,
                            sample_time, nsamples, snr_floor,
                            noise_certificate, seed_done=False,
                            rho_cert=None, cert_slack=None):
    """The certificate check + guarantee loop, shared VERBATIM by the
    single-device and sharded hybrids (their docstrings promise an
    identical contract — this helper is what makes that true).

    Owns the PAD-FREE soundness guard: on TPU a time axis no
    power-of-two tile divides gets zero-padded inside the transform
    (``fdmt._transform_setup``), gathers wrap through the pad instead
    of circularly mod ``nsamples``, and the retention bound's circular
    model no longer applies — neither the certificate nor the
    cert-based skip proof may run, so the loop falls back to the
    legacy conservative margins (and the retention bound is not even
    computed — it could inform nothing).

    Otherwise computes the per-config retention bound, certifies the
    chunk signal-free when permitted (skipping the loop entirely), and
    runs :func:`hybrid_guarantee_loop` with the cert-based skip
    criterion (sound under the stated signal model up to the Gaussian
    noise cross-term — :mod:`.certify`, *Miss risk*).  Returns
    ``(certified, rho_cert_min)`` — ``rho_cert_min`` is ``None`` on
    padded runs.

    ``rho_cert`` pre-empts the bound computation: a float is used
    verbatim (callers cycling many distinct geometries can precompute
    ``certify.cert_retention(...).min()`` off the hot path — the
    first-call cost is multi-second at multi-thousand-trial configs,
    lru-cached per config afterwards); ``False`` opts out of the
    cert-based machinery entirely, dropping the loop to the legacy
    conservative margins (no certificate, no bound computation).
    ``cert_slack`` overrides the default
    :data:`~.certify.HYBRID_CERT_SLACK` in both the certificate
    threshold and the skip criterion.
    """
    import jax

    from .certify import certify_noise_only, retention_bound
    from .fdmt import _pick_fdmt_tile

    if rho_cert is False or (jax.default_backend() == "tpu"
                             and _pick_fdmt_tile(int(nsamples)) == 0):
        cert_scores = None
        noise_certificate = False

    rho_cert_min = None
    certified = False
    if cert_scores is not None:
        if rho_cert is not None:
            rho_cert_min = float(rho_cert)
        else:
            # multi-second host computation on first call per config
            # (lru-cached after) — a named budget bucket so a cache miss
            # cannot hide inside the search stage (VERDICT r5 #2 listed
            # "floor computation" among the uninstrumented suspects)
            with budget_bucket("search/cert_floor"):
                rho_cert_min = retention_bound(nchan, trial_dms,
                                               start_freq, bandwidth,
                                               sample_time, nsamples,
                                               cert=True)
        certified = bool(noise_certificate
                         and certify_noise_only(cert_scores, snr_floor,
                                                rho_cert_min,
                                                coarse_snrs=coarse_snrs,
                                                slack=cert_slack))
    if not certified:
        hybrid_guarantee_loop(coarse_snrs, snrs, exact, rescore,
                              snr_floor=snr_floor, seed_done=seed_done,
                              cert_scores=cert_scores,
                              rho_cert=rho_cert_min,
                              cert_slack=cert_slack)
    return certified, rho_cert_min


#: top-k coarse rows the fused seed program rescores device-side (plus
#: grid neighbours, padded to one HYBRID_SEED_BUCKET)
HYBRID_SEED_TOPK = 2

#: rows the fused first-round program rescores.  Round-3 A/B (v5e 1M
#: headline) picked bucket 16 with top-5 (0.489 s): smaller seeds
#: regressed because every miss cost a host-loop ROUND TRIP.  Round 4's
#: in-dispatch need stage (HYBRID_NEED_BUCKET) absorbs those misses on
#: the device, flipping the trade — re-swept with the need stage on:
#: (top-5, 16): 0.512 s; (top-2, 8): 0.451 s, same exact argbest.  The
#: exact rescore costs ~6 ms/row regardless of batch, so every padded
#: slot is real money.  Deliberately decoupled from
#: HYBRID_RESCORE_BUCKETS so shrinking the seed does not shrink the
#: max block of large guarantee-loop rescans.
HYBRID_SEED_BUCKET = 8

#: rows the fused program's SECOND stage rescores (round 4, VERDICT r3
#: #4): after the seed's exact scores, the device evaluates the
#: guarantee loop's own cert-based need mask against the seed's
#: best_exact and rescores the top-scoring flagged rows in the same
#: dispatch — on typical hit chunks the host loop then finds nothing
#: left and the whole search costs ONE round trip (each trip is ~0.1 s
#: on the tunnelled platform).  Sized 8, measured (v5e 1M headline):
#: the exact rescore costs ~6 ms/row regardless of batch (VPU-bound),
#: so padding slots are pure waste — kernel-only A/B: bucket2 0/8/32 =
#: 0.396/0.449/0.591 s with n_need = 1 flagged row.  Chunks flagging
#: more than 8 rows fall through to the host loop (which was the only
#: path for ALL of them before round 4).
HYBRID_NEED_BUCKET = 8


def fused_masked_topk(score, mask, bucket):
    """Device-side selection of up to ``bucket`` rows of ``mask``.

    Shared by the single-device and mesh fused hybrid kernels:
    ``top_k`` over ``score`` restricted to ``mask``, with slots beyond
    the flagged count (``n = mask.sum()``) repeating the top selected
    row — every returned index names a flagged row (or a duplicate of
    one, whose exact scores are equally valid), so the host may apply
    the whole selection unconditionally.  Returns ``(sel, n)`` with
    ``sel`` int32 of length ``bucket``.
    """
    import jax
    import jax.numpy as jnp

    ndm = score.shape[0]
    k = min(bucket, ndm)
    _, sel = jax.lax.top_k(jnp.where(mask, score, -jnp.inf), k)
    if bucket > k:
        sel = jnp.concatenate(
            [sel, jnp.broadcast_to(sel[:1], (bucket - k,))])
    n = mask.sum()
    return jnp.where(jnp.arange(bucket) < n, sel, sel[0]), n


def fused_need_stage(coarse, best_exact, rescored, cert_params, bucket2):
    """The guarantee loop's round-1 need mask, evaluated device-side.

    Mirrors :func:`hybrid_guarantee_loop`'s cert-based criterion exactly
    — including both consistency guards and the floor terms — against
    the seed stage's ``best_exact``.  ``coarse`` is the ``(6, ndm)``
    plan-grid score pack (row 2 the block S/N, row 5 the sliding
    certificate score); ``cert_params = (rho, slack, floor)`` arrives as
    a runtime array so one compiled program serves any bound/floor
    (``+inf`` disables the respective terms — see
    :func:`~.certify.fused_cert_params`).  Returns ``(sel2, n_need)``:
    the top-``bucket2`` flagged rows cert-descending (the rows hardest
    to rule out; overflow slots duplicate the top row) and the total
    flagged count.  Shared by the single-device and mesh fused kernels
    so the two programs can never drift from the host loop or from each
    other.
    """
    rho, slack, floor = cert_params[0], cert_params[1], cert_params[2]
    snr_c, cert = coarse[2], coarse[5]
    need = cert >= rho * best_exact - slack
    need |= snr_c >= best_exact          # consistency guard
    need |= cert >= rho * floor - slack  # floor contract
    need |= snr_c >= floor               # its consistency guard
    need &= ~rescored
    return fused_masked_topk(cert, need, bucket2)


def unpack_fused_hybrid(packed, ndm, bucket, bucket2):
    """Host-side inverse of the fused hybrid kernels' packed layout.

    ``[coarse (6*ndm) | sel (bucket) | exact (5*bucket) | n_seed (1) |
    sel2 (bucket2) | exact2 (5*bucket2) | n_need (1)]`` — the trailing
    four parts absent when ``bucket2 == 0`` (indices < 2^24 are exact in
    float32).  Returns ``(coarse, sel, seed_scores, n_seed, sel2,
    need_scores, n_need)`` with ``coarse`` float64 ``(6, ndm)``.
    """
    coarse = packed[:6 * ndm].reshape(6, ndm).astype(np.float64)
    pos = 6 * ndm
    sel = np.rint(packed[pos:pos + bucket]).astype(np.int64)
    pos += bucket
    seed_scores = packed[pos:pos + 5 * bucket].reshape(5, bucket)
    pos += 5 * bucket
    n_seed = int(np.rint(packed[pos]))
    pos += 1
    if not bucket2:
        return coarse, sel, seed_scores, n_seed, None, None, 0
    sel2 = np.rint(packed[pos:pos + bucket2]).astype(np.int64)
    pos += bucket2
    need_scores = packed[pos:pos + 5 * bucket2].reshape(5, bucket2)
    n_need = int(np.rint(packed[pos + 5 * bucket2]))
    return coarse, sel, seed_scores, n_seed, sel2, need_scores, n_need


def fused_scores_to_host(scores, roll_k, nsamples):
    """Float32 ``(5, n)`` score pack -> host column tuple
    ``(max, std, snr, window, peak)``, the rebase rotation undone on the
    peak index (shared by the fused hybrids' seed/need-stage unpacks)."""
    m, s, b, w, p = (scores[i].astype(np.float64) for i in range(5))
    w = np.rint(w).astype(np.int32)
    p = (np.rint(p).astype(np.int64) - roll_k) % nsamples
    return m, s, b, w, p


@functools.lru_cache(maxsize=8)
def _fused_hybrid_seed_kernel(nchan, start_freq, bandwidth, n_hi, t_run,
                              t_tile, n_lo, t_orig, max_off, ndm_plan,
                              bucket, use_head=False, bucket2=0,
                              use_score=False, deep_pair=False):
    """ONE jitted program for the hybrid's first round on TPU:

    FDMT coarse sweep -> plan-grid score mapping -> device-side top-k
    seed selection (+/-1 grid neighbours) -> exact Pallas rescore of the
    seed bucket -> (round 4) the guarantee loop's OWN cert-based need
    mask evaluated against the seed's best exact S/N, with the
    top-``bucket2`` flagged rows exactly rescored in the same program ->
    everything packed into a single flat float32 array.

    Collapses the tunnel round trips (coarse readback, seed offsets
    upload [cached instead], rescore readbacks) into one dispatch + one
    readback — each trip costs ~0.1 s on the tunnelled platform.  With
    the fused need stage a typical hit chunk's guarantee loop finds
    nothing left to rescore and the whole search is ONE round trip
    (VERDICT r3 #4).
    Packing layout: the shared fused-hybrid pack
    (:func:`unpack_fused_hybrid`); the ``n_seed`` slot is the constant
    ``bucket`` here (the top-k seed always fills its slots — the mesh
    kernel's mask-based seed is the variable-count case).  Coarse row 5
    is the sliding certificate score (:func:`cert_profile_scores`).

    The need mask mirrors :func:`hybrid_guarantee_loop`'s cert-based
    criterion exactly (including both consistency guards and the floor
    terms); ``cert_params = (rho_cert, slack, floor)`` arrives as a
    runtime array so one compiled program serves any bound/floor —
    ``rho_cert = +inf`` disables the cert terms (legacy-margin callers:
    the device then pre-rescores only rows whose DISPLAYED coarse score
    beats the seed best, a correct subset; the host loop backstops),
    ``floor = +inf`` disables the floor terms.
    """
    import jax
    import jax.numpy as jnp

    from .fdmt import _transform_fn
    from .pallas_dedisperse import dedisperse_plane_pallas_traced

    coarse_fn = _transform_fn(nchan, start_freq, bandwidth, n_hi, t_run,
                              t_tile, True, False, n_lo=n_lo,
                              with_scores=True, with_plane=False,
                              t_orig=t_orig, with_cert=True,
                              use_head=use_head, use_score=use_score,
                              deep_pair=deep_pair)
    k = min(HYBRID_SEED_TOPK, ndm_plan)  # top_k requires k <= axis size

    @jax.jit
    def run(data, idx_map, offsets_rebased, cert_params):
        stacked_f = coarse_fn(data)               # (6, ndm_fdmt)
        coarse = stacked_f[:, idx_map]            # (6, ndm_plan)
        _, top = jax.lax.top_k(coarse[2], k)
        sel = jnp.concatenate([top - 1, top, top + 1])
        sel = jnp.clip(sel, 0, ndm_plan - 1)
        sel = jnp.concatenate(
            [sel, jnp.broadcast_to(sel[:1], (bucket - 3 * k,))])
        offs = offsets_rebased[sel]               # (bucket, nchan) rows
        plane = dedisperse_plane_pallas_traced(data, offs, max_off,
                                               dm_block=bucket)
        exact = score_profiles_stacked(plane, xp=jnp)   # (5, bucket)
        parts = [coarse.reshape(-1), sel.astype(jnp.float32),
                 exact.reshape(-1),
                 jnp.full((1,), bucket, jnp.float32)]  # n_seed slot
        if bucket2:
            best_exact = exact[2].max()
            rescored = jnp.zeros(ndm_plan, bool).at[sel].set(True)
            # rescore the strongest flagged rows (fused_need_stage:
            # cert-descending — the rows hardest to rule out; overflow
            # slots duplicate the top flagged row).  The whole stage is
            # SKIPPED (lax.cond) when nothing is flagged — the common
            # bright-pulse case converges on the seed alone, and an
            # unconditional 32-row rescore measured 1069 -> 806 tr/s on
            # the benchmark (the host applies sel2 only when n_need > 0,
            # so the skip branch's zeros are never consumed).
            sel2, n_need = fused_need_stage(coarse, best_exact, rescored,
                                            cert_params, bucket2)

            def rescore2(rows):
                plane2 = dedisperse_plane_pallas_traced(
                    data, offsets_rebased[rows], max_off,
                    dm_block=bucket2)
                return score_profiles_stacked(plane2, xp=jnp)

            exact2 = jax.lax.cond(
                n_need > 0, rescore2,
                lambda _: jnp.zeros((5, bucket2), jnp.float32), sel2)
            parts += [sel2.astype(jnp.float32), exact2.reshape(-1),
                      n_need.astype(jnp.float32)[None]]
        return jnp.concatenate(parts)

    return run


@functools.lru_cache(maxsize=4)
def _device_offsets_cache(offsets_bytes, shape):
    """Device-resident rebased-offset table, cached across searches.

    The 2 MB int32 table is deterministic in (geometry, trial grid,
    nsamples); re-uploading it per search costs ~0.1 s over the tunnel.
    Keyed by the host bytes — the lru holds the device buffer alive.
    """
    import jax.numpy as jnp

    return jnp.asarray(
        np.frombuffer(offsets_bytes, dtype=np.int32).reshape(shape))


@functools.lru_cache(maxsize=16)
def _fused_rescore_kernel(max_off, dm_block):
    """One jitted program: Pallas dedisperse (un-rebased output) + score.

    The hybrid's exact-rescore hot path on TPU.  ``max_off`` is the
    *full* offset table's rebased bound — static and identical for every
    subset, so all guarantee-loop rounds (and warm/timed bench runs) hit
    one compiled program per row bucket.  The plane is scored WITHOUT
    undoing the rebase rotation: max/std/snr/window are
    rotation-invariant (the rebase constant is 128-aligned, a multiple
    of every boxcar width, so block sums are a rotation of the reference
    ones), and the peak index is corrected host-side
    (``(peak - roll_k) mod T``) — saving a full-plane roll pass and two
    dispatch round trips per call over the tunnelled link.
    """
    import jax
    import jax.numpy as jnp

    from .pallas_dedisperse import dedisperse_plane_pallas_traced

    @jax.jit
    def run(data, offs):
        plane = dedisperse_plane_pallas_traced(data, offs, max_off,
                                               dm_block=dm_block)
        return score_profiles_stacked(plane, xp=jnp)

    return run


def _search_jax_hybrid(data, trial_dms, start_freq, bandwidth, sample_time,
                       capture_plane, dm_block, chan_block,
                       snr_floor=None, noise_certificate=True,
                       rho_cert=None, cert_slack=None):
    """FDMT coarse sweep + exact rescore of the hit region.

    The throughput/exactness trade (VERDICT round 1): the FDMT computes
    every trial in O(nchan log nchan) passes but its tree-rounded tracks
    make scores approximate (within ~a trial of the exact kernels); the
    direct kernels are bit-exact-vs-NumPy but O(ndm * nchan).  This path
    delivers both at once:

    1. coarse-score ALL plan trials with the FDMT (each plan row takes
       the S/N of its nearest integer-band-delay FDMT row);
    2. exactly rescore — same offsets, same scorer, same summation order
       as the direct kernels — every row whose coarse estimate could be
       the global best;
    3. iterate with a margin bound derived from the *observed* coarse
       error on already-rescored rows until no unrescored row's coarse
       estimate reaches ``best_exact - margin``.  On exhaustion of the
       round budget, rescore everything still in question.

    Hit detection (``argbest`` row: DM, snr, rebin, peak) is therefore
    the exact kernel's — byte-equal to ``kernel="pallas"`` and matching
    ``backend="numpy"`` wherever the direct kernel does — at a cost of
    one FDMT pass plus a few dozen exact trials instead of the full
    O(ndm) sweep.  The returned table carries an ``exact`` bool column
    marking which rows hold exact scores.

    Cost note: the rescore count adapts to the data.  With a real
    candidate the loop converges in ~10-50 rows; on signal-free noise
    every trial's score is statistically equivalent, so pinning down the
    exact argbest correctly degenerates toward a full exact sweep — the
    hybrid is never *wrong*, just no faster than ``kernel="pallas"``
    when there is nothing to find in the chunk.

    ``snr_floor`` (opt-in): additionally rescore every row that could
    hold an above-floor detection (sliding certificate score within the
    per-config retention bound of the floor, :mod:`.certify`), making
    *all* above-threshold detections exact, not just the best — and,
    with ``noise_certificate`` (default on), enabling the noise
    certificate: when NO trial's certificate score reaches
    ``rho_cert * snr_floor - HYBRID_CERT_SLACK``, the chunk holds no
    impulsive signal detectable at the floor (sound under the stated
    signal model up to the Gaussian noise cross-term the slack absorbs
    — residual at-floor miss risk recorded in
    ``meta["cert_miss_p_at_floor"]``, see :mod:`.certify` *Miss risk*),
    the guarantee loop is skipped entirely, and the coarse table is
    returned with ``meta["certified"] = True`` (its rows are then
    coarse scores, NOT exact — the certificate's claim is strictly the
    absence of detections).  On survey data this is the difference between the
    hybrid degenerating to a full exact sweep on every signal-free
    chunk and paying one tree transform per such chunk.  Note the floor
    must sit at ``certify.certifiable_snr_floor`` (~12 at 1M-sample
    chunks) for the certificate to actually fire on typical noise;
    lower floors remain correct but uncertifiable — at T = 2^20 the
    reference's ``snr > 6`` floor (``clean.py:349``) is a mere 0.5
    above the noise max, and pinning down exactness that close to the
    noise genuinely costs a full sweep.

    ``capture_plane`` returns the *coarse* (FDMT) plane: the plane is a
    diagnostics product and the tree rows agree with the exact series up
    to track rounding and a small circular rotation (:mod:`.fdmt`).
    """
    import jax

    from .fdmt import _pick_fdmt_tile, fdmt_trial_dms

    ndm = len(trial_dms)
    nchan, nsamples = np.shape(data)
    dmmin = float(np.min(trial_dms))
    dmmax = float(np.max(trial_dms))

    use_fused = jax.default_backend() == "tpu"
    # (the pad-free soundness guard — disabling certificate + cert-proof
    # on zero-padded TPU time axes — lives in hybrid_certificate_gate;
    # the streaming driver sizes chunks so the post-resample axis is a
    # tile multiple precisely so it never triggers there, and 50%
    # overlap re-contains edge pulses in the neighbouring chunk)
    if use_fused:
        import jax.numpy as jnp

        from .pallas_dedisperse import rebase_offsets

        offsets_full = _offsets_for(trial_dms, nchan, start_freq, bandwidth,
                                    sample_time, nsamples)
        # ONE rebase over the full table: every subset then shares the
        # same static max_off (one compiled program per bucket) and the
        # same host-side peak correction constant
        rebased_full, roll_k, max_off = rebase_offsets(offsets_full,
                                                       nsamples)
        data32 = jnp.asarray(data, jnp.float32)

    # nearest coarse (integer band-delay) row for each plan row —
    # host-computable before any device work
    fdmt_dms, n_lo, n_hi = fdmt_trial_dms(nchan, dmmin, dmmax, start_freq,
                                          bandwidth, sample_time)
    idx = nearest_rows(fdmt_dms, trial_dms)

    plane = None
    # the fused program earns its keep on wide sweeps; narrow grids
    # (fewer trials than the seed bucket) take the two-stage path, which
    # also avoids top_k k > ndm edge cases.  With a detection floor set
    # (streaming mode) the two-stage path is preferred even on TPU: a
    # noise-certified chunk then pays ONE coarse dispatch and readback —
    # the fused program would burn a full seed-bucket exact rescore on
    # every chunk the certificate is about to skip (the survey majority),
    # while a non-certified chunk only pays one extra ~0.1 s round trip.
    from ..resilience import ladder as _ladder

    fused_seed = (use_fused and not capture_plane
                  and ndm >= 3 * HYBRID_SEED_TOPK
                  and _pick_fdmt_tile(nsamples) > 0
                  and (snr_floor is None or not noise_certificate)
                  # OOM ladder "unfuse" rung (ISSUE 12): under memory
                  # pressure the one-dispatch program splits back into
                  # coarse + rescore (bit-identity already pinned)
                  and not _ladder.unfuse_engaged())
    if fused_seed:
        # 1+2 fused: coarse sweep, device-side top-k seed selection and
        # exact seed rescore in ONE dispatch + ONE packed readback (each
        # tunnel round trip costs ~0.1 s).  Requires the unpadded time
        # axis (a pad would shift the rescore's circular wrap off the
        # exact kernels' convention).
        bucket = HYBRID_SEED_BUCKET
        assert bucket >= 3 * HYBRID_SEED_TOPK
        bucket2 = min(HYBRID_NEED_BUCKET, ndm)
        t_tile = _pick_fdmt_tile(nsamples)
        from .fdmt import _head_enabled

        # the need stage wants the retention bound BEFORE the dispatch;
        # same lru-cached computation the gate performs, so no extra
        # cost — rho_cert=False (cert opt-out) sends +inf, which
        # disables the device's cert terms (the consistency guards
        # still flag displayed-score beats).  fused_cert_params is the
        # one constructor of this operand, shared with the mesh kernel.
        from .certify import fused_cert_params

        cert_params = fused_cert_params(nchan, trial_dms, start_freq,
                                        bandwidth, sample_time, nsamples,
                                        snr_floor=snr_floor,
                                        rho_cert=rho_cert,
                                        cert_slack=cert_slack)

        # the head flag is resolved HERE so it keys the builder's lru
        # cache (an in-builder env read would serve a stale compiled
        # program after toggling PUTPU_FDMT_HEAD in-process)
        from .fdmt import _deep_pair_enabled, _score_kernel_choice

        kernel = _fused_hybrid_seed_kernel(
            nchan, float(start_freq), float(bandwidth), n_hi, nsamples,
            t_tile, n_lo, None, max_off, ndm, bucket,
            use_head=_head_enabled(True), bucket2=bucket2,
            use_score=_score_kernel_choice(True, False),
            deep_pair=_deep_pair_enabled())
        offs_dev = _device_offsets_cache(rebased_full.tobytes(),
                                         rebased_full.shape)
        roof = roofline.begin()
        with budget_bucket("search/fused"):
            idx_dev = jnp.asarray(idx.astype(np.int32))
            cert_dev = jnp.asarray(cert_params)
            packed = np.asarray(kernel(data32, idx_dev, offs_dev, cert_dev))
            budget_count("dispatches")
            budget_count("readbacks")
        roofline.end(roof, "fused_hybrid_seed", kernel,
                     (data32, idx_dev, offs_dev, cert_dev))
        (coarse, sel, seed_scores, _, sel2, need_scores,
         n_need) = unpack_fused_hybrid(packed, ndm, bucket, bucket2)
        maxvalues, stds, snrs = coarse[0], coarse[1], coarse[2]
        windows = np.rint(coarse[3]).astype(np.int32)
        peaks = np.rint(coarse[4]).astype(np.int64)
        cert_scores = coarse[5]
    else:
        # two-stage path (CPU, plane capture, or awkward time axes):
        # coarse sweep first, scores mapped host-side
        (_, c_max, c_std, c_snr, c_win, c_peak, plane,
         c_cert) = _search_jax_fdmt(
            data, dmmin, dmmax, start_freq, bandwidth, sample_time,
            capture_plane, with_cert=True)
        if plane is not None and plane.shape[0] != ndm:
            # align the coarse plane with the plan grid (row gather —
            # cheap, and row-major on TPU unlike the scalarising lane
            # gather)
            plane = plane[idx]
        # the coarse score vectors come back from the device here — the
        # fused path's readback is bucketed above, and this two-stage
        # path must attribute the same trip (putpu-lint device-trip)
        with budget_bucket("search/coarse_readback"):
            maxvalues = np.asarray(c_max, np.float64)[idx]
            stds = np.asarray(c_std, np.float64)[idx]
            snrs = np.asarray(c_snr, np.float64)[idx]
            windows = np.asarray(c_win, np.int32)[idx]
            peaks = np.asarray(c_peak, np.int64)[idx]
            cert_scores = np.asarray(c_cert, np.float64)[idx]
            budget_count("readbacks")

    coarse_snrs = snrs.copy()
    exact = np.zeros(ndm, dtype=bool)

    def _apply(blk, scored):
        m, s, b, w, p = scored
        k = len(blk)
        maxvalues[blk] = m[:k]
        stds[blk] = s[:k]
        snrs[blk] = b[:k]
        windows[blk] = w[:k]
        peaks[blk] = p[:k]
        exact[blk] = True

    _rescore_kernel = {}

    def rescore_kernel():
        """ONE tuner resolution at the CHUNK geometry (full plan ndm),
        shared by every rescore bucket and resolved lazily on the first
        actual rescore (a certified chunk never pays it).  Passing
        ``kernel="auto"`` per bucket would tune independent
        (ndm=8/16/32) keys — repeated mid-loop synthetic-chunk
        measurements, and a bucket whose winner differed from its
        neighbour's would diverge at float level from the
        ``PUTPU_AUTOTUNE=off`` run.  The sharded hybrid pins its
        ``rescore_kernel`` for the same reason."""
        if "k" not in _rescore_kernel:
            from ..tuning.autotune import resolve_search_kernel

            _rescore_kernel["k"] = resolve_search_kernel(
                nchan, nsamples, ndm, None, False, start_freq, bandwidth,
                sample_time, trial_dms, dm_block=dm_block,
                chan_block=chan_block)
        return _rescore_kernel["k"]

    def rescore(rows):
        """Exact scores for ``rows`` — fused Pallas+score program on TPU
        (one dispatch + one readback per bucketed call), the portable
        direct kernel elsewhere (whose own budget buckets attribute the
        dispatch/readback time; here only the call/row counters)."""
        budget_count("rescore_calls")
        budget_count("rescore_rows", len(rows))
        for blk, padded in iter_rescore_buckets(rows):
            if use_fused:
                run = _fused_rescore_kernel(max_off, len(padded))
                with budget_bucket("search/rescore"):
                    stacked = run(data32,
                                  jnp.asarray(rebased_full[padded]))
                    budget_count("dispatches")
                    m, s, b_, w, p = unstack_scores(stacked)
                    budget_count("readbacks")
                p = (p - roll_k) % nsamples  # undo the rebase rotation
                _apply(blk, (m, s, b_, w, p))
            else:
                m, s, b_, w, p, _ = _search_jax(
                    data, trial_dms[padded], start_freq, bandwidth,
                    sample_time, capture_plane=False, dm_block=dm_block,
                    chan_block=chan_block, dtype=None,
                    kernel=rescore_kernel())
                _apply(blk, (m, s, b_, w, p))

    # 2. seed (plausible-best rows + grid neighbours; the coarse grid
    # sits up to one trial off the plan) and 3. guarantee loop — shared
    # with the sharded hybrid (see hybrid_guarantee_loop).  An
    # unrescored row j can only beat the exact best if its coarse score
    # understated it (exact_j <= coarse_j + U, U the true max
    # underestimate), so the margin is one-sided: the overestimate side
    # (coarse > exact, typical of wing rows whose nearest coarse
    # neighbour is the peak) must NOT widen it.  U is estimated two
    # ways and the wider wins: adaptively (1.5x the worst underestimate
    # observed on rescored rows — a biased, peak-clustered sample) and
    # structurally (the HYBRID_COARSE_TRUST bound: tree track rounding
    # deviates <= ~2 samples/channel, Zackay & Ofek 2017 sec 2.3,
    # costing a boxcar-scored pulse at most ~1/sqrt(3) of its S/N).
    if fused_seed:
        # the device already rescored the top-k neighbourhood: unpack it
        # (kept even when certified — the scores are already computed and
        # exact rows are strictly more informative).  The need-stage
        # scores exist only when the device's mask flagged rows
        # (n_need > 0; the skipped branch emits zeros, never applied)
        blocks = [(sel, seed_scores)]
        if n_need > 0:
            blocks.append((sel2, need_scores))
        for rows, scores in blocks:
            _apply(rows, fused_scores_to_host(scores, roll_k, nsamples))
    # the cert-based criterion covers the snr_floor rows directly
    # (every row that could hold an above-floor detection is flagged
    # per-row), so no separate floor pre-pass is needed
    certified, rho_cert_min = hybrid_certificate_gate(
        cert_scores, coarse_snrs, snrs, exact, rescore, nchan=nchan,
        trial_dms=trial_dms, start_freq=start_freq, bandwidth=bandwidth,
        sample_time=sample_time, nsamples=nsamples, snr_floor=snr_floor,
        noise_certificate=noise_certificate, seed_done=fused_seed,
        rho_cert=rho_cert, cert_slack=cert_slack)
    logger.debug("hybrid: %d/%d rows rescored exactly%s%s", exact.sum(), ndm,
                 f" (device need stage flagged {n_need})" if fused_seed
                 else "",
                 " (noise-certified)" if certified else "")

    return (maxvalues, stds, snrs, windows, peaks, exact, plane,
            cert_scores, certified, rho_cert_min)


# ---------------------------------------------------------------------------
# Public façade
# ---------------------------------------------------------------------------

def dedispersion_search(data, dmmin, dmmax, start_freq, bandwidth, sample_time,
                        show=False, *, backend="numpy", capture_plane=None,
                        trial_dms=None, dm_block=None, chan_block=None,
                        dtype=None, kernel="auto", snr_floor=None,
                        noise_certificate=True, rho_cert=None,
                        cert_slack=None, precision=None):
    """Sweep trial DMs over ``data`` and score each dedispersed series.

    Parameters mirror the reference façade
    (``pulsarutils/dedispersion.py:205``); ``show=True`` additionally
    returns the dedispersed plane, like the reference's slow path (but
    computed by the same fast kernel — no duplicate implementation).

    Extra keyword-only parameters select and tune the execution backend:

    backend : ``"numpy"`` (reference semantics, float64, single core) or
        ``"jax"`` (jitted batched gather kernel; TPU/CPU).
    capture_plane : override for plane capture (defaults to ``show``).
        ``"memmap"`` spills the plane to a disk-backed ``.npy``
        (:func:`plane_memmap` — the reference's memmap behaviour,
        ``dedispersion.py:215-218``): host RAM holds one superblock at
        a time, so ``show=True``-class diagnostics work at any
        ``ndm x T``.  Requires the superblocked kernels —
        ``backend="numpy"`` or the Pallas path (``kernel="pallas"``, or
        ``"auto"``, which then resolves to Pallas even off-TPU); the
        fdmt/hybrid/fourier/gather kernels hold the full plane in
        device memory by construction and reject it.
    trial_dms : explicit trial grid; default is the reference plan
        (one trial per integer sample of band-crossing delay).
    dm_block, chan_block : JAX blocking factors (memory/speed trade-off).
    dtype : device dtype for the JAX path (default float32).
    snr_floor : ``kernel="hybrid"`` only — when set, every row that
        could hold an above-floor detection is exactly rescored (all
        above-threshold detections exact, not just the best), and the
        noise certificate becomes available; see
        :func:`_search_jax_hybrid`.
    noise_certificate : ``kernel="hybrid"`` with ``snr_floor`` only —
        allow the certified fast path on signal-free chunks (default
        on); the verdict lands in ``table.meta["certified"]``, with the
        certificate's operating assumptions (``cert_slack``,
        ``cert_miss_p_at_floor`` — see :mod:`.certify` *Miss risk*)
        alongside.
    rho_cert : ``kernel="hybrid"`` only — the per-config certificate
        retention bound.  ``None`` (default) computes it from the
        transform's merge tables; NOTE this is a multi-second host
        computation on the FIRST call at a multi-thousand-trial config
        (lru-cached per config afterwards, 32 entries).  Pass a
        precomputed ``certify.cert_retention(...).min()`` to move that
        cost off the hot path (one-shot calls at large configs,
        workloads cycling > 32 geometries), or ``False`` to skip the
        certificate machinery entirely (the guarantee loop then uses
        the legacy conservative margins — still exact-argbest, no
        certified fast path).
    cert_slack : ``kernel="hybrid"`` only — override the certificate
        slack (default :data:`~.certify.HYBRID_CERT_SLACK`).  Derive it
        from a target at-floor miss probability with
        :func:`~.certify.cert_slack_for_miss_p`; a larger slack
        tightens the miss risk at the cost of a higher
        :func:`~.certify.certifiable_snr_floor` and more rescoring.
        The value used is recorded in ``meta["cert_slack"]``.
    kernel : JAX-path kernel selector: ``"auto"`` (measured per-
        (backend, geometry) selection among the exact direct-sweep
        variants via the plan-level autotuner with a persistent tune
        cache — see :mod:`pulsarutils_tpu.tuning`; the static heuristic
        — Pallas on TPU, roll-scan on CPU, gather elsewhere — is the
        zero-measurement fallback and the ``PUTPU_AUTOTUNE=off`` escape
        hatch), ``"pallas"`` (hand-written tiled TPU kernel, see
        :mod:`.pallas_dedisperse`), ``"gather"`` (portable XLA
        ``take_along_axis`` formulation), ``"roll"`` (the roll-scan
        scan/roll-accumulate formulation — the measured CPU winner,
        14x over the scalarising CPU gather at the PR 1 rescore
        geometry), ``"fdmt"`` (tree dedispersion,
        O(nchan log nchan) instead of O(ndm * nchan) — fastest for dense
        DM sweeps; uses its own integer band-delay trial grid and tree-
        rounded tracks, so hits agree with the exact kernels to within a
        trial but not bit-identically; see :mod:`.fdmt`), ``"hybrid"``
        (FDMT coarse sweep + exact rescore of the hit region: exact hit
        detection on the plan grid at near-FDMT throughput; adds an
        ``exact`` bool column, see :func:`_search_jax_hybrid`) or
        ``"fourier"``
        (Fourier-domain dedispersion: exact *fractional*-sample delays —
        the precision option for narrow pulses at high time resolution;
        O(ndm * nchan * T) with transcendentals, see :mod:`.fourier`).
    precision : accumulation-precision policy for the gather/roll
        channel reductions (:mod:`pulsarutils_tpu.precision`):
        ``None``/``"f32"`` (the byte-identical default), a strategy
        name (``"f32_compensated"``, ``"split_f32"``,
        ``"bf16_operand_f32_accum"``), or ``"auto"`` — the measured
        (kernel, policy)-pair selection, where a non-default strategy
        only ever wins after the exact-hit-match equivalence harness
        passes at its documented error bound.  ``PUTPU_PRECISION``
        sets the default when the argument is omitted.

    Returns
    -------
    :class:`~pulsarutils_tpu.utils.table.ResultTable` with columns
    ``DM, max, std, snr, rebin, peak`` (``peak`` = sample index of the
    best-window maximum — arrival time within the chunk) — plus the
    ``(ndm, nsamples)`` plane if ``show``/``capture_plane``.
    """
    from ..io.lowbit import PackedFrames

    if isinstance(data, PackedFrames):
        # packed low-bit input (ISSUE 11).  The traceable direct-sweep
        # formulations unpack INSIDE their jit (handled in _search_jax);
        # every other consumer gets the decode it can use while the
        # link still carries only the packed bytes: a cached device
        # unpack program for the jax tree/hybrid kernels, the C++/numpy
        # host decode for the reference backend.
        if backend == "numpy":
            data = data.to_host()
        elif kernel in ("fdmt", "hybrid"):
            data = data.to_device()

    if precision not in (None, "f32", "auto") and (
            backend != "jax" or kernel in ("fdmt", "hybrid")):
        raise ValueError("precision policies apply to the jax gather/roll "
                         f"channel reductions; got precision={precision!r} "
                         f"with backend={backend!r}, kernel={kernel!r}")

    nchan = data.shape[0]
    if capture_plane is None:
        capture_plane = bool(show)

    if kernel == "fdmt":
        # the FDMT computes its own trial grid: the plan's one-sample
        # spacing snapped to integer band delays (the plan itself sits at
        # a fractional offset, so values/count can differ by one trial);
        # an explicit trial_dms only bounds the DM range.  dm_block /
        # chan_block do not apply to the tree transform.
        if backend != "jax":
            raise ValueError("kernel='fdmt' requires backend='jax'")
        if capture_plane == "memmap":
            raise ValueError("capture_plane='memmap' requires kernel="
                             "'pallas'/'auto' or backend='numpy' (the "
                             "tree transform is one whole-plane program)")
        import jax.numpy as _jnp

        if dtype not in (None, _jnp.float32):
            raise ValueError("kernel='fdmt' supports float32 only")
        if trial_dms is not None:
            dmmin = float(np.min(trial_dms))
            dmmax = float(np.max(trial_dms))
        (trial_dms, maxvalues, stds, best_snrs, best_windows, best_peaks,
         plane) = _search_jax_fdmt(data, dmmin, dmmax, start_freq,
                                   bandwidth, sample_time, capture_plane)
        table = ResultTable({
            "DM": trial_dms,
            "max": maxvalues,
            "std": stds,
            "snr": best_snrs,
            "rebin": best_windows,
            "peak": best_peaks,
        })
        return (table, plane) if (capture_plane or show) else table

    if trial_dms is None:
        with budget_bucket("search/plan"):
            trial_dms = dedispersion_plan(nchan, dmmin, dmmax, start_freq,
                                          bandwidth, sample_time)
    trial_dms = np.asarray(trial_dms, dtype=np.float64)

    if kernel == "hybrid":
        if backend != "jax":
            raise ValueError("kernel='hybrid' requires backend='jax'")
        if capture_plane == "memmap":
            raise ValueError("capture_plane='memmap' requires kernel="
                             "'pallas'/'auto' or backend='numpy' (the "
                             "hybrid's coarse plane is one whole-plane "
                             "program)")
        import jax.numpy as _jnp

        if dtype not in (None, _jnp.float32):
            raise ValueError("kernel='hybrid' supports float32 only")
        from .certify import cert_meta

        (maxvalues, stds, best_snrs, best_windows, best_peaks, exact,
         plane, cert_scores, certified,
         rho_out) = _search_jax_hybrid(data, trial_dms, start_freq,
                                       bandwidth, sample_time,
                                       capture_plane, dm_block,
                                       chan_block, snr_floor=snr_floor,
                                       noise_certificate=noise_certificate,
                                       rho_cert=rho_cert,
                                       cert_slack=cert_slack)
        table = ResultTable({
            "DM": trial_dms,
            "max": maxvalues,
            "std": stds,
            "snr": best_snrs,
            "rebin": best_windows,
            "peak": best_peaks,
            "exact": exact,
            "cert": cert_scores,
            # meta records the certificate's operating assumptions
            # wherever its verdict is (ADVICE r3): the slack is a
            # z-score against the Gaussian noise cross-term, not a hard
            # bound — see certify's *Miss risk* section
        }, meta=cert_meta(certified, rho_out, snr_floor, cert_slack))
        return (table, plane) if (capture_plane or show) else table

    if backend == "numpy":
        (maxvalues, stds, best_snrs, best_windows, best_peaks,
         plane) = _search_numpy(data, trial_dms, start_freq, bandwidth,
                                sample_time, capture_plane)
    elif backend == "jax":
        (maxvalues, stds, best_snrs, best_windows, best_peaks,
         plane) = _search_jax(data, trial_dms, start_freq, bandwidth,
                              sample_time, capture_plane, dm_block,
                              chan_block, dtype, kernel,
                              precision=precision)
    else:
        raise ValueError(f"unknown backend {backend!r}")

    table = ResultTable({
        "DM": trial_dms,
        "max": maxvalues,
        "std": stds,
        "snr": best_snrs,
        "rebin": best_windows,
        "peak": best_peaks,
    })
    if capture_plane or show:
        return table, plane
    return table
