"""The dedispersion search: plan -> dedisperse every trial -> boxcar S/N.

Public entry point :func:`dedispersion_search` is the capability-equivalent
of the reference's fast/slow search façade
(``pulsarutils/dedispersion.py:205-251``) with its numba ``prange`` sweep
(``pulsarutils/dedispersion.py:174-202``), unified:

* one search implementation, optional dedispersed-plane capture (the
  reference had a second, older copy of the slow path in
  ``pulsarutils/clean.py:136-180`` — intentionally not reproduced);
* ``backend="numpy"`` keeps exact reference semantics (float64, same
  rounding, same scoring) and is the correctness/benchmark baseline;
* ``backend="jax"`` runs the whole sweep as one jitted program: the trial
  axis is processed in blocks via ``lax.map``, each block dedispersed by a
  batched gather (see :mod:`..ops.dedisperse`) and scored on device.  All
  shift/plan math is computed host-side in float64 and shipped as int32
  gather offsets (2 MB for 512 trials x 1024 chans) so hit detection is
  bit-identical to the NumPy path regardless of device precision.

Scoring (reference ``dedispersion.py:186-201``): for each trial, subtract
the mean, then for boxcar block-sums of width 1, 2, 4, 8 compute
``snr = max / std`` and keep the best; also record the peak and std of the
unbinned series.
"""

from __future__ import annotations

import functools

import numpy as np

from .dedisperse import dedisperse_block_chunked_jax
from .plan import (
    dedispersion_plan,
    dedispersion_shifts_batch,
    normalize_shifts,
)
from .rebin import block_sum_time
from ..utils.table import ResultTable

#: boxcar widths tried by the scorer (reference ``dedispersion.py:190-191``)
SEARCH_WINDOWS = (1, 2, 4, 8)


def score_profiles(plane, xp=np):
    """Score a block of dedispersed series ``(ndm, T)``.

    Returns ``(maxvalues, stds, best_snrs, best_windows)`` per trial,
    reproducing the reference's per-trial loop
    (``pulsarutils/dedispersion.py:186-201``) in batched form.
    """
    plane = xp.asarray(plane)
    x = plane - plane.mean(axis=1, keepdims=True)
    maxvalues = x.max(axis=1)
    stds = x.std(axis=1)

    best_snrs = xp.zeros(x.shape[0], dtype=x.dtype)
    best_windows = xp.zeros(x.shape[0], dtype=xp.int32)
    for window in SEARCH_WINDOWS:
        reb = block_sum_time(x, window, xp=xp)
        snr = reb.max(axis=1) / reb.std(axis=1)
        better = snr > best_snrs
        best_snrs = xp.where(better, snr, best_snrs)
        best_windows = xp.where(better, window, best_windows)
    return maxvalues, stds, best_snrs, best_windows


def _offsets_for(trial_dms, nchan, start_freq, bandwidth, sample_time, nsamples):
    """Host-side float64 shift table -> int32 gather offsets in ``[0, T)``."""
    shifts = dedispersion_shifts_batch(
        np.asarray(trial_dms, dtype=np.float64), nchan, start_freq, bandwidth,
        sample_time)
    return normalize_shifts(shifts, nsamples)


# ---------------------------------------------------------------------------
# NumPy backend
# ---------------------------------------------------------------------------

def _search_numpy(data, trial_dms, start_freq, bandwidth, sample_time,
                  capture_plane):
    data = np.asarray(data, dtype=np.float64)
    nchan, nsamples = data.shape
    ndm = len(trial_dms)
    offsets = _offsets_for(trial_dms, nchan, start_freq, bandwidth,
                           sample_time, nsamples)

    plane = np.empty((ndm, nsamples), dtype=np.float64) if capture_plane else None
    maxvalues = np.empty(ndm)
    stds = np.empty(ndm)
    best_snrs = np.empty(ndm)
    best_windows = np.empty(ndm, dtype=np.int32)

    tidx = np.arange(nsamples)
    block = 16  # score in small batches to bound the workspace
    for lo in range(0, ndm, block):
        hi = min(lo + block, ndm)
        idx = (tidx[None, None, :] + offsets[lo:hi, :, None]) % nsamples
        sub = np.take_along_axis(data[None, :, :], idx, axis=2).sum(axis=1)
        if capture_plane:
            plane[lo:hi] = sub
        m, s, b, w = score_profiles(sub)
        maxvalues[lo:hi] = m
        stds[lo:hi] = s
        best_snrs[lo:hi] = b
        best_windows[lo:hi] = w

    return maxvalues, stds, best_snrs, best_windows, plane


# ---------------------------------------------------------------------------
# JAX backend
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _jax_search_kernel(capture_plane, chan_block):
    import jax
    import jax.numpy as jnp

    def per_block(data, offs):
        plane = dedisperse_block_chunked_jax(data, offs, chan_block)
        scores = score_profiles(plane, xp=jnp)
        if capture_plane:
            return scores + (plane,)
        return scores

    @jax.jit
    def kernel(data, offset_blocks):
        # data (C, T); offset_blocks (nblocks, dm_block, C) int32
        return jax.lax.map(lambda offs: per_block(data, offs), offset_blocks)

    return kernel


def _search_jax(data, trial_dms, start_freq, bandwidth, sample_time,
                capture_plane, dm_block, chan_block, dtype):
    import jax.numpy as jnp

    dtype = dtype or jnp.float32
    data = jnp.asarray(data, dtype=dtype)
    nchan, nsamples = data.shape
    ndm = len(trial_dms)
    offsets = _offsets_for(trial_dms, nchan, start_freq, bandwidth,
                           sample_time, nsamples)

    if dm_block is None:
        dm_block = max(1, min(ndm, 32))
    npad = (-ndm) % dm_block
    if npad:
        offsets = np.concatenate([offsets, offsets[-1:].repeat(npad, axis=0)])
    offset_blocks = offsets.reshape(-1, dm_block, nchan)

    kernel = _jax_search_kernel(capture_plane, chan_block)
    out = kernel(data, jnp.asarray(offset_blocks))
    out = [np.asarray(o).reshape(-1, *o.shape[2:])[:ndm] for o in out]
    if capture_plane:
        maxvalues, stds, best_snrs, best_windows, plane = out
    else:
        maxvalues, stds, best_snrs, best_windows = out
        plane = None
    return maxvalues, stds, best_snrs, best_windows, plane


# ---------------------------------------------------------------------------
# Public façade
# ---------------------------------------------------------------------------

def dedispersion_search(data, dmmin, dmmax, start_freq, bandwidth, sample_time,
                        show=False, *, backend="numpy", capture_plane=None,
                        trial_dms=None, dm_block=None, chan_block=None,
                        dtype=None):
    """Sweep trial DMs over ``data`` and score each dedispersed series.

    Parameters mirror the reference façade
    (``pulsarutils/dedispersion.py:205``); ``show=True`` additionally
    returns the dedispersed plane, like the reference's slow path (but
    computed by the same fast kernel — no duplicate implementation).

    Extra keyword-only parameters select and tune the execution backend:

    backend : ``"numpy"`` (reference semantics, float64, single core) or
        ``"jax"`` (jitted batched gather kernel; TPU/CPU).
    capture_plane : override for plane capture (defaults to ``show``).
    trial_dms : explicit trial grid; default is the reference plan
        (one trial per integer sample of band-crossing delay).
    dm_block, chan_block : JAX blocking factors (memory/speed trade-off).
    dtype : device dtype for the JAX path (default float32).

    Returns
    -------
    :class:`~pulsarutils_tpu.utils.table.ResultTable` with columns
    ``DM, max, std, snr, rebin`` — plus the ``(ndm, nsamples)`` plane if
    ``show``/``capture_plane``.
    """
    nchan = data.shape[0]
    if trial_dms is None:
        trial_dms = dedispersion_plan(nchan, dmmin, dmmax, start_freq,
                                      bandwidth, sample_time)
    trial_dms = np.asarray(trial_dms, dtype=np.float64)
    if capture_plane is None:
        capture_plane = bool(show)

    if backend == "numpy":
        maxvalues, stds, best_snrs, best_windows, plane = _search_numpy(
            data, trial_dms, start_freq, bandwidth, sample_time, capture_plane)
    elif backend == "jax":
        maxvalues, stds, best_snrs, best_windows, plane = _search_jax(
            data, trial_dms, start_freq, bandwidth, sample_time, capture_plane,
            dm_block, chan_block, dtype)
    else:
        raise ValueError(f"unknown backend {backend!r}")

    table = ResultTable({
        "DM": trial_dms,
        "max": maxvalues,
        "std": stds,
        "snr": best_snrs,
        "rebin": best_windows,
    })
    if capture_plane or show:
        return table, plane
    return table
