"""Pallas rotate-accumulate kernel for the uniform-grid FDD (round 4).

VERDICT r3 #5 asked for the FDD on the MXU or a committed negative
result.  The honest answer is both halves of neither: an EXACT MXU
formulation does not exist — ``out[n, f] = sum_c u[c, f] * step[c, f]^n``
is a Vandermonde-structured contraction whose per-``(c, f)`` generators
admit no shared matrix across the batch axis ``f`` (a matmul needs one
operand reused across an output axis; here every ``(c, f)`` pair carries
its own geometric sequence, and building the ``(n, c)`` matrix per ``f``
costs exactly the work it was meant to save).  NUFFT-style interpolation
onto a shared grid would make it matmuls but gives up the exact
fractional delays that are this kernel's entire reason to exist.

What IS on the table: the XLA incremental kernel
(:func:`..fourier._jitted_fourier_uniform`) runs at ~6% of the VPU —
its ``lax.scan`` carries a ``(chan_block, nbin)`` complex rotation state
through HBM every trial (~1 TB of carry traffic per sweep) and XLA
materialises complex-multiply temporaries besides.  This module keeps
the same mathematics (same anchors, same 48-bit step limbs, same
rotate-then-accumulate recurrence) but runs the recurrence in VMEM:

* grid = (rfft-bin tiles, channel blocks); the ``(superblock, tile)``
  accumulator lives in the revisited output block, the per-channel
  rotation state in registers/VMEM — NOTHING complex ever round-trips
  HBM per trial;
* complex arithmetic is explicit float32 re/im pairs on ``(8, L)``
  tiles (full-sublane VPU ops, the package's standard layout);
* the trial loop is unrolled by :data:`FDD_N_UNROLL` — the fused-head
  lesson: un-unrolled ``fori_loop`` iterations cost ~110 ns of scalar
  control against ~20 ns of vector work.

Traffic per superblock: one read of ``u = spec * anchor`` and of the
step ramp (the only per-``(c, f)`` inputs), one write of the
accumulator — ~9 GB per 64-trial superblock at the canonical
513-trial 1024 x 1M config against ~1 TB for the scan form.
"""

from __future__ import annotations

import functools

import numpy as np

#: trials advanced per scalar-loop iteration (amortises loop control)
FDD_N_UNROLL = 8

#: lane width of one (8, L) bin tile
FDD_L = 1024

#: channels accumulated per grid step
FDD_C_BLOCK = 8


def _batch_carry():
    """PUTPU_FDD_BATCH_CARRY: channel-group size of the batched carry
    (''/0 = off, the per-channel form; 2/4/8 = group size).

    The per-(channel, trial) output accumulate is the kernel's VMEM
    traffic hot spot (~4.4 TB of out read+write per canonical sweep);
    batching ``g`` channels into one (g, 8, L) re/im carry divides it
    by ``g`` at the cost of ``16 * g`` vregs of loop state.  Round-5
    A/B (v5e, canonical 513-trial 1024 x 1M sweep, min-of-4): g=8 —
    the full block — MEASURED SLOWER (233 -> 180 tr/s; ~128 vregs of
    carry against a ~64-vreg register file spills on every rotation,
    the fused head's 16-row-unroll pathology); the measured middle
    ground is recorded in docs/performance.md.
    """
    import os

    raw = os.environ.get("PUTPU_FDD_BATCH_CARRY", "")
    try:
        value = int(raw or 0)
    except ValueError:
        value = 0
    if raw and value not in (0, 2, 4, 8):
        import warnings

        warnings.warn(f"PUTPU_FDD_BATCH_CARRY={raw!r} ignored (expected "
                      "0/2/4/8); using the per-channel form",
                      stacklevel=2)
        value = 0
    return value if value in (2, 4, 8) else 0


@functools.lru_cache(maxsize=8)
def _build_fdd_kernel(n_tiles, superblock, n_cblocks, c_block, interpret,
                      batch_carry=False):
    """out[n] = sum_c u_c * step_c^n over one superblock of trials.

    Shapes (all float32): ``u_re/u_im/s_re/s_im (nchan_p, n_tiles, 8, L)``
    chunked over the padded rfft-bin axis; output
    ``(superblock, n_tiles, 8, L)`` re/im pair.  Bin tiles beyond the
    real ``nbin`` are zero in ``u`` and stay zero through the rotation.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    L = FDD_L

    def kernel(ure, uim, sre, sim, outre, outim):
        i_c = pl.program_id(1)

        @pl.when(i_c == 0)
        def _():
            outre[:] = jnp.zeros_like(outre)
            outim[:] = jnp.zeros_like(outim)

        if batch_carry:
            # (g, 8, L) re/im carries: one output accumulate per trial
            # per channel GROUP instead of per channel (see
            # _batch_carry for the measured trade)
            g = min(batch_carry, c_block)
            for c0 in range(0, c_block, g):
                sr = sre[c0:c0 + g, 0]
                si = sim[c0:c0 + g, 0]

                def body(nb, carry, sr=sr, si=si):
                    cr, ci = carry
                    for dn in range(FDD_N_UNROLL):
                        n = nb * FDD_N_UNROLL + dn
                        outre[n, 0] += jnp.sum(cr, axis=0)
                        outim[n, 0] += jnp.sum(ci, axis=0)
                        nr = cr * sr - ci * si
                        ci = cr * si + ci * sr
                        cr = nr
                    return cr, ci

                jax.lax.fori_loop(0, superblock // FDD_N_UNROLL, body,
                                  (ure[c0:c0 + g, 0], uim[c0:c0 + g, 0]))
            return

        for c in range(c_block):
            sr = sre[c, 0]
            si = sim[c, 0]

            def body(nb, carry, sr=sr, si=si):
                cr, ci = carry
                for dn in range(FDD_N_UNROLL):
                    n = nb * FDD_N_UNROLL + dn
                    outre[n, 0] += cr
                    outim[n, 0] += ci
                    nr = cr * sr - ci * si
                    ci = cr * si + ci * sr
                    cr = nr
                return cr, ci

            jax.lax.fori_loop(0, superblock // FDD_N_UNROLL, body,
                              (ure[c, 0], uim[c, 0]))

    in_spec = pl.BlockSpec((c_block, 1, 8, L),
                           lambda i_f, i_c: (i_c, i_f, 0, 0))
    step_spec = pl.BlockSpec((c_block, 1, 8, L),
                             lambda i_f, i_c: (i_c, i_f, 0, 0))
    out_spec = pl.BlockSpec((superblock, 1, 8, L),
                            lambda i_f, i_c: (0, i_f, 0, 0))

    call = pl.pallas_call(
        kernel,
        grid=(n_tiles, n_cblocks),
        in_specs=[in_spec, in_spec, step_spec, step_spec],
        out_specs=[out_spec, out_spec],
        out_shape=[jax.ShapeDtypeStruct((superblock, n_tiles, 8, L),
                                        jnp.float32)] * 2,
        interpret=bool(interpret),
    )

    def run(u_re, u_im, s_re, s_im):
        return call(u_re, u_im, s_re, s_im)

    return run


def fdd_superblock_spectra(u, step, superblock, interpret=False):
    """``out[n] = sum_c u[c] * step[c]**n`` for ``n`` in one superblock.

    ``u``/``step`` are ``(nchan, nbin)`` complex64 device arrays
    (``u = spec * anchor``); returns ``(superblock, nbin)`` complex64.
    Traceable (callable under jit).  ``superblock`` must be a multiple
    of :data:`FDD_N_UNROLL`; the bin axis is zero-padded to a whole
    number of ``8 * FDD_L`` tiles and sliced back.
    """
    import jax.numpy as jnp

    nchan, nbin = u.shape
    tile = 8 * FDD_L
    n_tiles = -(-nbin // tile)
    nbin_p = n_tiles * tile
    c_block = min(FDD_C_BLOCK, nchan)
    n_cblocks = -(-nchan // c_block)
    nchan_p = n_cblocks * c_block

    def prep(z):
        z = jnp.pad(z, ((0, nchan_p - nchan), (0, nbin_p - nbin)))
        return z.reshape(nchan_p, n_tiles, 8, FDD_L)

    run = _build_fdd_kernel(n_tiles, int(superblock), n_cblocks, c_block,
                            bool(interpret), batch_carry=_batch_carry())
    out_re, out_im = run(prep(jnp.real(u).astype(jnp.float32)),
                         prep(jnp.imag(u).astype(jnp.float32)),
                         prep(jnp.real(step).astype(jnp.float32)),
                         prep(jnp.imag(step).astype(jnp.float32)))
    out = (out_re.reshape(superblock, nbin_p)
           + 1j * out_im.reshape(superblock, nbin_p))
    return out[:, :nbin].astype(jnp.complex64)
