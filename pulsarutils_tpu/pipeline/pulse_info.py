"""Candidate / chunk record with periodicity-statistic slots.

Typed re-design of the reference's ``PulseInfo`` (``pulsarutils/clean.py:
27-55``) — the reference decorated a field-less class with ``@dataclass``
(no annotations, so all "fields" were shared class attributes, and ``date``
was attached dynamically at ``clean.py:343``).  Here every field is a real
dataclass field, the Z^2_n / H / M statistic slots are filled by an actual
method (:meth:`PulseInfo.compute_stats`, using the native
:mod:`..ops.robust` statistics), and persistence is npz+json instead of
pickle (:meth:`save` / :meth:`load`) — safe to load, diffable, and
self-describing.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np

from ..ops.robust import digitize, h_test, z_n_test

_ARRAY_FIELDS = ("allprofs", "dedisp_profile", "disp_profile",
                 "fold_profile")


@dataclasses.dataclass
class PulseInfo:
    # chunk geometry / metadata
    nbin: int = 0
    nchan: int = 0
    start_freq: float | None = None
    bandwidth: float | None = None
    pulse_freq: float | None = None
    date: float | None = None          # MJD of observation start
    t0: float | None = None            # chunk start time (s into the file)
    istart: int | None = None          # chunk start sample in the file
    # beam provenance (sigproc ``ibeam``/``nbeams``, ISSUE 8): carried
    # on every candidate so the cross-beam coincidence sift and the
    # survey report can label beams without re-opening files
    ibeam: int | None = None
    nbeams: int | None = None

    # candidate parameters
    dm: float | None = None
    snr: float | None = None
    width: float | None = None
    amp: float | None = None
    ph0: float | None = None
    noise_level: float | None = None

    # data products
    allprofs: np.ndarray | None = None        # (nchan, nbin) chunk waterfall
    disp_profile: np.ndarray | None = None    # band-averaged, dispersed
    dedisp_profile: np.ndarray | None = None  # band-averaged, dedispersed
    # persisted-record provenance: when the candidate STORE trims the
    # waterfall to a window around the pulse (a survey chunk's full
    # waterfall is gigabytes — round 5), these record the window so the
    # cutout is self-describing.  ``cutout_start`` is the cutout's
    # first column in the searched chunk's (post-resample) samples;
    # ``cutout_decim`` its time decimation factor.  ``nbin``/``t0``/
    # ``istart`` keep describing the SEARCHED CHUNK, not the cutout.
    cutout_start: int | None = None
    cutout_decim: int | None = None

    # folded-period-search candidate (ops.periodicity stage)
    period_freq: float | None = None   # candidate spin frequency (Hz)
    period_dm: float | None = None     # DM of the plane row it was found in
    period_sigma: float | None = None  # Gaussian-equivalent significance
    period_H: float | None = None      # refined H statistic
    period_M: int | None = None        # best harmonic count of the H-test
    fold_profile: np.ndarray | None = None  # folded pulse profile (nbin,)

    # periodicity statistics (reference clean.py:43-55 slots)
    disp_z2: float | None = None
    disp_z6: float | None = None
    disp_z12: float | None = None
    disp_z20: float | None = None
    disp_H: float | None = None
    disp_M: int | None = None
    dedisp_z2: float | None = None
    dedisp_z6: float | None = None
    dedisp_z12: float | None = None
    dedisp_z20: float | None = None
    dedisp_H: float | None = None
    dedisp_M: int | None = None

    def compute_stats(self):
        """Fill the Z^2_n / H-test slots from the stored profiles.

        Profiles are digitized to counts first (reference intent,
        ``clean.py:183-189,252``).  Harmonic numbers above what the profile
        resolves are left as ``None``.
        """
        for prefix, profile in (("disp", self.disp_profile),
                                ("dedisp", self.dedisp_profile)):
            if profile is None:
                continue
            counts = np.maximum(digitize(np.asarray(profile)), 0)
            nmax = counts.size // 2
            for n in (2, 6, 12, 20):
                if n <= nmax:
                    setattr(self, f"{prefix}_z{n}",
                            float(z_n_test(counts, n)))
            h, m = h_test(counts, nmax=min(20, max(nmax, 1)))
            setattr(self, f"{prefix}_H", float(h))
            setattr(self, f"{prefix}_M", int(m))
        return self

    # -- persistence --------------------------------------------------------

    def save(self, path):
        """Write as ``<path>`` npz (arrays + a json-encoded scalar record)."""
        scalars = {}
        arrays = {}
        for f in dataclasses.fields(self):
            value = getattr(self, f.name)
            if f.name in _ARRAY_FIELDS:
                if value is not None:
                    arrays[f.name] = np.asarray(value)
            elif value is not None:
                scalars[f.name] = value
        np.savez_compressed(path, __scalars__=json.dumps(scalars), **arrays)
        return path

    @classmethod
    def load(cls, path):
        with np.load(path, allow_pickle=False) as data:
            scalars = json.loads(str(data["__scalars__"]))
            info = cls(**scalars)
            for name in _ARRAY_FIELDS:
                if name in data.files:
                    setattr(info, name, data[name])
        return info
