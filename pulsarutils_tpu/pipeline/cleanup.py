"""Write a cleaned copy of a filterbank file.

The reference declared this capability and left it a stub
(``pulsarutils/clean.py:354-357``: opens the file, computes the mask, does
nothing).  Implemented here for real: stream the file in chunks, zero the
flagged channels, optionally excise periodic RFI in the Fourier domain,
and write a valid SIGPROC file with the same header/geometry.
"""

from __future__ import annotations

import numpy as np

from ..io.sigproc import FilterbankReader, FilterbankWriter, read_header
from ..ops.clean_ops import fft_zap_time
from ..pipeline.spectral_stats import get_bad_chans
from ..utils.logging_utils import logger


def cleanup_data(fname, outname, surelybad=(), fft_zap=False,
                 chunksize=65536):
    """Stream-clean ``fname`` into ``outname``.

    Bad channels (``get_bad_chans`` + ``surelybad``) are zeroed; with
    ``fft_zap`` each chunk additionally passes through
    :func:`..ops.clean_ops.fft_zap_time`.  Channel order, header and bit
    depth are preserved.  Returns the bad-channel mask (file order).
    """
    mask = get_bad_chans(fname, surelybad=surelybad)
    reader = FilterbankReader(fname)
    raw_header, _ = read_header(fname)
    raw_header.setdefault("nbits", reader.header.get("nbits", 32))

    # multi-IF files are cleaned PER IF PLANE and written back
    # interleaved (same nifs header): the bad-channel mask comes from
    # the IF-summed bandpass (one mask for all planes, the standard
    # convention), but zeroing/zapping must touch each plane's own data
    # — writing the IF sum under a multi-IF header would corrupt the
    # file's layout
    nifs = reader.nifs
    if_readers = ([reader] if nifs == 1 else
                  [FilterbankReader(fname, if_mode=k) for k in range(nifs)])

    def clean_block(block):
        nonlocal nzapped
        block = block.copy()
        block[mask, :] = 0.0
        if fft_zap:
            block, zapped = fft_zap_time(block)
            block[mask, :] = 0.0  # irfft reintroduces tiny leakage
            nzapped += int(np.asarray(zapped).sum())
        return block

    nzapped = 0
    with FilterbankWriter(outname, raw_header) as writer:
        for istart in range(0, reader.nsamples, chunksize):
            planes = [clean_block(r.read_block(istart, chunksize))
                      for r in if_readers]
            writer.write_block(planes[0] if nifs == 1
                               else np.stack(planes))
    logger.info("cleaned %s -> %s (%d bad channels%s)", fname, outname,
                int(mask.sum()),
                f", {nzapped} Fourier bins zapped" if fft_zap else "")
    return mask
