"""Candidate sifting: collapse duplicate detections of one physical pulse.

The streaming driver advances by 50% of the chunk length (reference
``clean.py:318``), so every pulse is fully contained in at least one chunk
— and therefore *detected in up to two* (plus trial-DM neighbours within a
chunk).  The reference persists every per-chunk hit separately
(``clean.py:349-351``), leaving deduplication to the human.  This module
groups hits whose (absolute arrival time, DM) fall within a matching
radius and keeps the highest-S/N member of each group — the standard
"sifting" stage of modern single-pulse pipelines.

Pure host-side post-processing: candidate lists are tiny compared to the
data, so no device work is warranted.
"""

from __future__ import annotations

import json

import numpy as np

from ..obs import metrics as _metrics
from ..utils.logging_utils import logger

__all__ = ["sift_hits", "sift_candidates", "hit_fields"]

#: histogram edges for candidate-quality telemetry: S/N follows the
#: detection-floor decades (6 is the reference criterion), DM covers the
#: plausible galactic-to-FRB range in coarse decades
SNR_EDGES = (6.0, 7.0, 8.0, 10.0, 12.0, 15.0, 20.0, 30.0, 50.0, 100.0)
DM_EDGES = (50.0, 100.0, 200.0, 300.0, 400.0, 600.0, 800.0, 1200.0, 2000.0)


def hit_fields(istart, iend, info, table):
    """Arrival time (s), DM, S/N and width (s) of one chunk hit."""
    best = table.best_row()
    tsamp = 1.0 / (info.pulse_freq * info.nbin)
    # absolute arrival time: chunk start + the scorer's in-chunk peak
    # sample (the ``peak`` table column; tables without it fall back to
    # the chunk start — the default time radius is chunk-scale).  A
    # record with no populated t0 (pre-t0 save) gets the best-effort
    # ``istart * tsamp`` (offset when the pipeline resampled: istart is
    # in file samples, tsamp is the effective one).
    t0 = getattr(info, "t0", None)
    t_peak = float(t0) if t0 is not None else istart * tsamp
    if "peak" in table.colnames:
        t_peak = t_peak + float(best["peak"]) * tsamp
    return {
        # the istart * tsamp fallback mixes units whenever the pipeline
        # resampled (file-sample index x effective sample time); flag it
        # so consumers (CSV export, sifting radii) know the time is
        # best-effort, not exact
        "time_approx": t0 is None,
        "istart": int(istart),
        "iend": int(iend),
        # chunk duration in seconds: nbin is the post-resample sample
        # count of the searched array, tsamp its effective sample time
        # (istart/iend are in FILE samples — a different unit whenever
        # the pipeline resampled)
        "span": float(info.nbin) * tsamp,
        "time": float(t_peak),
        "dm": float(best["DM"]),
        "snr": float(best["snr"]),
        "width": float(best["rebin"]) * tsamp,
        # beam provenance (ISSUE 8): present on candidates produced by
        # beam-labelled files/drivers, None otherwise — the cross-beam
        # coincidence sift keys on it
        "beam": getattr(info, "ibeam", None),
        "info": info,
        "table": table,
    }


def sift_candidates(cands, time_radius, dm_radius=None, stats=None):
    """Group candidate dicts (keys ``time, dm, snr``) and keep each group's
    best.

    Greedy single-linkage in descending S/N order: a candidate joins the
    first kept group within the time radius AND the group's DM radius;
    otherwise it seeds a new group.

    ``time_radius`` is seconds, or the string ``"pair-width"`` (round 6,
    ADVICE r5): the radius is then evaluated PER PAIR as ``max(0.5 s,
    4 x the wider of the two candidates' widths)`` — a single wide
    (rebin=8, coarse-tsamp) candidate no longer inflates the merge
    radius of every narrow pulse in the run, while a wide pulse still
    absorbs its own boxcar-quantised duplicates.  Candidates without a
    ``width`` key contribute 0 (the 0.5 s floor rules).

    ``dm_radius=None`` (default) derives the radius from each group's
    *seed* DM (``0.02 * seed_dm + 1`` — trial-grid spacing grows with
    DM), so one high-DM candidate cannot inflate the merge radius of
    every low-DM group.  Returns the kept candidates (descending S/N),
    each annotated with ``n_members`` — the number of raw detections it
    absorbed.

    ``stats`` (round 7, candidate-quality telemetry): a mutable dict the
    sift fills with ``in`` / ``kept`` and a per-reason breakdown of the
    absorbed duplicates under ``rejected``:

    * ``width`` — (pair-width mode only) absorbed because the
      width-scaled time radius stretched past the 0.5 s floor
      (wide-boxcar quantisation); with a plain numeric ``time_radius``
      no width-derived radius exists, so this reason never fires;
    * ``dm_radius`` — time matched but the DM offset exceeded 1 and
      needed the DM-proportional radius (chunk-to-chunk DM jitter);
    * ``duplicate`` — everything else: time and DM both matched
      trivially (the textbook chunk-overlap / trial-neighbour
      duplicate).
    """
    pair_width = time_radius == "pair-width"
    order = sorted(range(len(cands)), key=lambda i: -cands[i]["snr"])
    if stats is None:
        stats = {}
    stats["in"] = len(cands)
    rejected = stats.setdefault(
        "rejected", {"duplicate": 0, "width": 0, "dm_radius": 0})
    kept = []
    for i in order:
        c = cands[i]
        for k in kept:
            if pair_width:
                t_radius = max(0.5, 4.0 * max(c.get("width", 0.0),
                                              k.get("width", 0.0)))
            else:
                t_radius = time_radius
            k_radius = (0.02 * k["dm"] + 1.0 if dm_radius is None
                        else dm_radius)
            dt = abs(c["time"] - k["time"])
            ddm = abs(c["dm"] - k["dm"])
            if dt <= t_radius and ddm <= k_radius:
                k["n_members"] += 1
                # the 0.5 s floor is a pair-width-mode concept: only
                # there can "needed the width-scaled radius" be blamed
                reason = ("width" if pair_width and dt > 0.5
                          else "dm_radius" if ddm > 1.0 else "duplicate")
                rejected[reason] += 1
                break
        else:
            kept.append({**c, "n_members": 1})
    stats["kept"] = len(kept)
    return kept


def sift_hits(hits, time_radius=None, dm_radius=None, stats=None):
    """Sift the ``hits`` list returned by
    :func:`~pulsarutils_tpu.pipeline.search_pipeline.search_by_chunks`
    (``(istart, iend, PulseInfo, ResultTable)`` tuples).

    Default radii: when every hit carries an EXACT arrival time (the
    ``peak`` column), duplicates from the 50% chunk overlap land at the
    *same* time up to boxcar rounding, so ``time_radius`` is
    width-scale — PER PAIR, ``max(0.5 s, 4x the wider of the two)``
    (round 6: the previous global ``4x the widest hit in the run`` let
    one wide rebin=8 candidate inflate the radius for every narrow
    pulse; per-pair keeps the wide pulse's own duplicates merged without
    coupling unrelated narrow ones — ADVICE r5).  A chunk-scale
    radius here is actively wrong at survey chunk sizes: two REAL
    pulses minutes apart merged into one candidate (round-5 survey
    rehearsal, 2 GB file — the sift swallowed a DM-394 pulse 555 s
    from a DM-395 one because 1.5 chunk spans was 786 s).  Hits with
    only approximate times (``time_approx``, legacy tables without a
    peak column) keep the old 1.5-chunk-span radius, which their
    chunk-start-quantised times genuinely need.  A chunk holding only
    part of a pulse can still report its *circular-wrap artifact* as a
    separate weaker candidate (the roll convention wraps the dispersed
    tail, reference ``dedispersion.py:60-98``) — the overlapping
    neighbour that contains the pulse outright outranks it, and keeping
    the artifact visible beats merging distinct pulses.  ``dm_radius``
    = per group, 2% of the group seed's DM + 1 (trial-grid neighbours
    and chunk-to-chunk jitter — see :func:`sift_candidates`).

    Returns a list of candidate dicts (descending S/N) with keys
    ``time, dm, snr, width, istart, iend, n_members, info, table``.

    Telemetry (round 7): the in/kept totals and the per-reason rejected
    counts land in the metrics registry
    (``putpu_sift_candidates_in_total`` / ``..._kept_total`` /
    ``putpu_sift_rejected_total{reason=...}``), kept candidates feed the
    ``putpu_sift_snr`` / ``putpu_sift_dm`` histograms, and one
    ``SIFT_JSON {...}`` footer line is logged for artifact parsers —
    the sift counterpart of the stream's ``BUDGET_JSON`` footer.

    ``stats`` (optional) is an out-param: pass a dict and the same
    in/kept/rejected record that feeds SIFT_JSON is written into it —
    the CLI uses this to fold sift telemetry into the survey report.
    """
    stats = {} if stats is None else stats
    if not hits:
        return []
    cands = [hit_fields(*h) for h in hits]
    if time_radius is None:
        if any(c["time_approx"] for c in cands):
            time_radius = 1.5 * max(c["span"] for c in cands)
        else:
            time_radius = "pair-width"
    kept = sift_candidates(cands, time_radius, dm_radius, stats=stats)
    _metrics.counter("putpu_sift_candidates_in_total").inc(stats["in"])
    _metrics.counter("putpu_sift_candidates_kept_total").inc(stats["kept"])
    for reason, n in stats["rejected"].items():
        _metrics.counter("putpu_sift_rejected_total", reason=reason).inc(n)
    snr_hist = _metrics.histogram("putpu_sift_snr", edges=SNR_EDGES)
    dm_hist = _metrics.histogram("putpu_sift_dm", edges=DM_EDGES)
    for c in kept:
        snr_hist.observe(c["snr"])
        dm_hist.observe(c["dm"])
    logger.info("SIFT_JSON %s", json.dumps(stats))
    return kept
