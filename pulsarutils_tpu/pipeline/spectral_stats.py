"""Streaming bandpass statistics and bad-channel detection.

Capability-equivalents of the reference's L2 stats layer
(``pulsarutils/stats.py:35-90``):

* :func:`get_spectral_stats` — one-pass mean & std bandpass spectra via
  running ``sum(x)`` / ``sum(x^2)`` moment accumulation over chunks
  (reference ``stats.py:35-60``).  The accumulation itself is a pure
  function (:func:`moment_accumulate` / :func:`moments_to_spectra`) so the
  same logic runs host-side over file chunks or on device inside a
  ``lax.scan`` (:func:`spectral_stats_scan_jax`) for HBM-resident streams.
* :func:`get_bad_chans` — flag channels above ``medfilt(spec, 11) +
  4 * ref_mad(spec)`` on both the mean and std spectra, with a
  ``.badchans`` text-cache making the computation restartable
  (reference ``stats.py:63-90``; the deprecated ``np.bool`` alias is
  simply not an issue here).

Input flexibility: all entry points accept a path to a SIGPROC file, an
open :class:`~pulsarutils_tpu.io.sigproc.FilterbankReader`, or an in-memory
``(nchans, nsamples)`` array.
"""

from __future__ import annotations

import os

import numpy as np

from ..io.sigproc import FilterbankReader
from ..ops.robust import median_filter_1d, ref_mad


def _as_reader(source):
    if isinstance(source, FilterbankReader):
        return source
    if isinstance(source, (str, os.PathLike)):
        return FilterbankReader(source)
    return None


def moment_accumulate(carry, block):
    """Fold one ``(nchans, n)`` block into running ``(sum, sumsq, count)``.

    Pure function — usable directly as a ``lax.scan`` body.
    """
    s, sq, n = carry
    block_f = block.astype(s.dtype) if hasattr(block, "astype") else block
    return (s + block_f.sum(axis=1),
            sq + (block_f ** 2).sum(axis=1),
            n + block.shape[1])


def moments_to_spectra(s, sq, n, xp=np):
    """Running moments -> (mean spectrum, std spectrum).

    ``std = sqrt(E[x^2] - E[x]^2)`` (reference ``stats.py:55-57``).
    """
    mean = s / n
    var = xp.maximum(sq / n - mean ** 2, 0.0)
    return mean, xp.sqrt(var)


def get_spectral_stats(source, chunksize=10000):
    """One-pass mean & std bandpass spectra of a filterbank.

    Reference ``stats.py:35-60`` (diagnostic plotting lives in
    :mod:`..pipeline.diagnostics`, not here).
    """
    reader = _as_reader(source)
    if reader is None:
        data = np.asarray(source, dtype=float)
        return data.mean(axis=1), data.std(axis=1)

    nchans = reader.nchans
    s = np.zeros(nchans)
    sq = np.zeros(nchans)
    n = 0
    for _, block in reader.iter_blocks(chunksize):
        s, sq, n = moment_accumulate((s, sq, n), block)
    return moments_to_spectra(s, sq, n)


def spectral_stats_scan_jax(chunks):
    """Device-resident streaming moments: ``chunks`` is
    ``(nchunks, nchans, chunk_len)``; returns (mean, std) spectra.

    The TPU equivalent of the reference's host chunk loop: a single jitted
    ``lax.scan`` that keeps the accumulator in HBM.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(chunks):
        nchans = chunks.shape[1]
        # Shifted moments: accumulate around a per-channel pivot (the first
        # chunk's mean) so float32 does not lose the variance to
        # catastrophic cancellation in E[x^2] - E[x]^2 when the bandpass
        # baseline is large (the naive formulation costs ~1.5% std error at
        # baseline ~100; shifted it is exact to f32 rounding).
        pivot = chunks[0].mean(axis=1)
        init = (jnp.zeros(nchans, dtype=jnp.float32),
                jnp.zeros(nchans, dtype=jnp.float32),
                jnp.zeros((), dtype=jnp.float32))

        def body(carry, block):
            return moment_accumulate(carry, block - pivot[:, None]), None

        (s, sq, n), _ = jax.lax.scan(body, init, chunks)
        mean, std = moments_to_spectra(s, sq, n, xp=jnp)
        return pivot + mean, std

    return run(jnp.asarray(chunks))


def flag_bad_channels(mean_spec, std_spec, medfilt_size=11, nsigma=4.0,
                      xp=np):
    """Threshold both spectra against their median-filtered baselines.

    Reference ``stats.py:70-77``.  Pure / jit-compatible.
    """
    nchan = mean_spec.shape[0]
    bad = xp.zeros(nchan, dtype=bool)
    for spec in (mean_spec, std_spec):
        smooth = median_filter_1d(spec, medfilt_size, xp=xp)
        sigma = ref_mad(spec, xp=xp)
        bad = bad | (spec > smooth + nsigma * sigma)
    return bad


def get_bad_chans(source, cache=None, surelybad=(), refresh=False,
                  spectra=None):
    """Bad-channel mask for a filterbank, with a restartable text cache.

    Reference ``stats.py:63-90`` (cache file ``<fname>.badchans``) plus the
    ``surelybad`` user override that the reference applied in its chunk
    driver (``clean.py:280-282``).  Pass ``refresh=True`` to ignore a stale
    cache, or ``spectra=(mean, std)`` to reuse already-computed bandpass
    spectra instead of streaming the file again.
    """
    path = source if isinstance(source, (str, os.PathLike)) else None
    if cache is None and path is not None:
        cache = f"{path}.badchans"

    if spectra is None and cache is not None and os.path.exists(cache) \
            and not refresh:
        bad = np.loadtxt(cache).astype(bool)
    else:
        mean_spec, std_spec = spectra if spectra is not None \
            else get_spectral_stats(source)
        bad = np.asarray(flag_bad_channels(mean_spec, std_spec))
        if cache is not None:
            np.savetxt(cache, [bad.astype(int)], fmt="%d")

    bad = np.array(bad, dtype=bool)
    for chan in surelybad:
        bad[int(chan)] = True
    return bad
