"""Candidate diagnostic plots and plane-level periodicity scoring.

Capability-equivalent of the reference's 7-panel candidate figure
(``pulsarutils/clean.py:192-269``) with its one structural flaw removed:
the reference *re-ran the whole slow dedispersion search inside the plot
function* (``clean.py:204-205``, SURVEY §3.1) — here the plot takes the
table and plane the pipeline already computed.

Panels (GridSpec 3x3, same layout intent as ``clean.py:221-229``):
raw and dedispersed waterfalls, their band-averaged lightcurves, the
DM-time plane, the S/N-vs-DM curve, and the H-test-vs-DM curve (computed
in one batched FFT over the whole plane instead of a per-row Python loop).

Everything is headless-safe (Agg backend forced before pyplot import).
"""

from __future__ import annotations

import numpy as np

from ..ops.dedisperse import apply_dm_shifts_to_data
from ..ops.plan import dedispersion_shifts
from ..ops.rebin import quick_resample
from ..ops.robust import digitize, h_test_batch


def plane_h_test(plane, nmax=None):
    """H-test score per plane row (trial DM), batched.

    Digitises the plane globally and scores every row with one rFFT —
    the vectorised form of the reference's per-row loop
    (``clean.py:252-255``).
    """
    plane = np.asarray(plane)
    if nmax is None:
        nmax = max(1, plane.shape[1] // 10)
    counts = np.maximum(digitize(plane), 0)
    h, m = h_test_batch(counts, nmax=nmax)
    return np.asarray(h), np.asarray(m)


def plot_diagnostics(info, table, plane, outname="info.jpg", t0=0.0,
                     show=False):
    """Render the candidate diagnostic figure.

    Parameters
    ----------
    info : :class:`..pipeline.pulse_info.PulseInfo` — chunk record (uses
        ``allprofs``, geometry fields, ``date``).
    table, plane : the search result and dedispersed plane for this chunk
        (from ``dedispersion_search(..., capture_plane=True)``) — NOT
        recomputed here.
    """
    # build first: for the batch path it pins the Agg backend BEFORE the
    # first pyplot import resolves a (possibly GUI) backend
    fig, _axes = build_diagnostic_figure(info, table, plane, t0=t0,
                                         interactive=show)
    import matplotlib.pyplot as plt

    fig.savefig(outname, bbox_inches="tight")
    if show:
        plt.show()
    plt.close(fig)
    return outname


def build_diagnostic_figure(info, table, plane, t0=0.0, interactive=False):
    """Build (but do not save) the 7-panel figure.

    Returns ``(fig, axes)`` with ``axes`` a dict keyed ``raw, dedisp,
    lc_raw, lc_dedisp, plane, snr, h`` — separated from
    :func:`plot_diagnostics` so tests can assert each panel's artists
    against the data that should back them.  ``interactive=False``
    (the pipeline default) pins the Agg backend so batch runs never
    touch a display; ``interactive=True`` leaves the user's backend
    alone so a subsequent ``plt.show()`` can actually open a window.
    """
    import matplotlib

    if not interactive:
        matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    array = np.asarray(info.allprofs)
    sample_time = 1.0 / info.pulse_freq / info.nbin
    nchan = info.nchan

    best = table.argbest("snr")
    dm = float(table["DM"][best])
    snr = float(table["snr"][best])
    window = int(table["rebin"][best])
    trial_dms = np.asarray(table["DM"])

    shifts = dedispersion_shifts(nchan, dm, info.start_freq, info.bandwidth,
                                 sample_time)
    dedisp = apply_dm_shifts_to_data(array, shifts)
    array_r = quick_resample(array, window)
    dedisp_r = quick_resample(dedisp, window)
    if hasattr(plane, "h_curve"):
        # mesh path: the plane is a DM-sharded device-resident handle
        # (:class:`~pulsarutils_tpu.parallel.sharded_plane.ShardedPlane`).
        # The two plane-consuming panels come from shard-local products:
        # the H-vs-DM curve per row on device, and a time-decimated image
        # for the plane panel (the full plane is never gathered).
        h_values, _ = plane.h_curve(window)
        plane_r, plane_factor = plane.decimated()
    else:
        plane_r = quick_resample(np.asarray(plane), window)
        plane_factor = window
        h_values, _ = plane_h_test(plane_r)

    allfreqs = np.linspace(info.start_freq, info.start_freq + info.bandwidth,
                           nchan + 1)
    nbins_r = array_r.shape[1]
    dt_r = sample_time * window
    times = np.arange(nbins_r) * dt_r + t0
    tedges = np.arange(nbins_r + 1) * dt_r + t0          # pcolormesh edges
    dm_edges = np.concatenate([
        [trial_dms[0] - 0.5 * (trial_dms[1] - trial_dms[0])] if
        trial_dms.size > 1 else [trial_dms[0] - 0.5],
        0.5 * (trial_dms[1:] + trial_dms[:-1]),
        [trial_dms[-1] + 0.5 * (trial_dms[-1] - trial_dms[-2])] if
        trial_dms.size > 1 else [trial_dms[0] + 0.5],
    ])

    if plane_factor == window:
        plane_tedges = tedges
    else:  # decimated handle image: its own bin width
        plane_tedges = (np.arange(plane_r.shape[1] + 1)
                        * sample_time * plane_factor + t0)

    fig = plt.figure(figsize=(10, 8), dpi=60)
    gs = plt.GridSpec(3, 3, height_ratios=(1.5, 1, 1),
                      width_ratios=[0.5, 0.5, 1], hspace=0.01, wspace=0.01)
    ax_raw = plt.subplot(gs[2, 0:2])
    ax_ded = plt.subplot(gs[2, 2], sharex=ax_raw, sharey=ax_raw)
    ax_lc_raw = plt.subplot(gs[1, 0:2], sharex=ax_raw)
    ax_lc_ded = plt.subplot(gs[1, 2], sharex=ax_raw, sharey=ax_lc_raw)
    ax_plane = plt.subplot(gs[0, 2], sharex=ax_raw)
    ax_snr = plt.subplot(gs[0, 0])
    ax_h = plt.subplot(gs[0, 1])

    for ax in (ax_snr, ax_h, ax_plane, ax_lc_raw, ax_lc_ded):
        ax.tick_params(labelbottom=False)
    for ax in (ax_plane, ax_lc_ded, ax_ded):
        ax.tick_params(labelleft=False)

    ax_raw.set_xlabel("Time (s)")
    ax_ded.set_xlabel("Time (s)")
    ax_raw.set_ylabel("Frequency (MHz)")
    ax_lc_raw.set_ylabel("Flux (arbitrary units)")
    ax_snr.set_ylabel("Trial DM")
    ax_snr.set_xlabel("S/N")
    ax_h.set_xlabel("H test")

    ax_raw.pcolormesh(tedges, allfreqs, array_r, rasterized=True)
    ax_ded.pcolormesh(tedges, allfreqs, dedisp_r, rasterized=True)
    ax_lc_raw.plot(times, array_r.mean(0), rasterized=True)
    ax_lc_ded.plot(times, dedisp_r.mean(0), rasterized=True)
    ax_plane.pcolormesh(plane_tedges, dm_edges, plane_r, rasterized=True)
    ax_snr.plot(-np.asarray(table["snr"]), trial_dms)
    ax_h.plot(-h_values, trial_dms)
    ax_raw.set_xlim(t0, times[-1])

    date = info.date if info.date is not None else "unknown"
    text = (f"Obs. Date: {date}\n"
            f"Freq: {info.start_freq}--{info.start_freq + info.bandwidth}\n"
            f"Best DM: {dm:.2f}\n"
            f"Best SNR: {snr:.2f}")
    if getattr(info, "period_freq", None):
        text += (f"\nPeriod: {1.0 / info.period_freq * 1e3:.3f} ms "
                 f"({info.period_sigma:.1f}σ)")
    ax_snr.text(0.5, 0.5, text, va="center", ha="center", fontsize=7,
                transform=ax_snr.transAxes)

    if getattr(info, "fold_profile", None) is not None:
        # folded-pulse inset (two cycles) for periodic candidates
        ax_fold = ax_h.inset_axes([0.45, 0.62, 0.5, 0.33])
        prof = np.asarray(info.fold_profile, dtype=float)
        cyc = np.concatenate([prof, prof])
        ax_fold.plot(np.arange(cyc.size) / prof.size, cyc, lw=0.8)
        ax_fold.set_xticks([]), ax_fold.set_yticks([])
        ax_fold.set_title("folded", fontsize=6, pad=1)

    return fig, {"raw": ax_raw, "dedisp": ax_ded, "lc_raw": ax_lc_raw,
                 "lc_dedisp": ax_lc_ded, "plane": ax_plane, "snr": ax_snr,
                 "h": ax_h}
