"""The main streaming search driver: file -> clean -> sweep -> candidates.

Capability-equivalent of the reference's ``search_by_chunks``
(``pulsarutils/clean.py:276-351``), rebuilt around the TPU execution model:

* one place owns band orientation (everything downstream sees an
  *ascending* band — the reference flipped inline at ``clean.py:332-333``);
* physics-driven chunk/hop/resample sizing via
  :func:`..parallel.stream.plan_chunks` (reference ``clean.py:296-316``);
* every interior chunk has the same shape, so ONE compiled search
  executable serves the entire file; candidates above the S/N threshold
  (reference's ``snr > 6``, ``clean.py:349``) are persisted through the
  :class:`..io.candidates.CandidateStore` with a crash-safe resume ledger
  (replacing the reference's manual ``tmin`` restart);
* diagnostics are rendered from the plane the search already computed —
  never recomputed (the reference re-ran its slow search per chunk,
  ``clean.py:204-205``, and plotted unconditionally with ``show=True``,
  ``clean.py:347``; here plotting is opt-in and hit-gated by default).
"""

from __future__ import annotations

import json
import os
import time
import zipfile
import zlib

import numpy as np

from ..faults import inject as fault_inject
from ..faults import reasons as fault_reasons
from ..faults.policy import (DispatchPolicy, QuarantineManifest,
                             call_with_deadline, gate_chunk,
                             gate_chunk_lowbit, gate_chunk_packed,
                             resolve_integrity_policy)
from ..io.candidates import CandidateStore, config_fingerprint
from ..io.sigproc import FilterbankReader
from ..obs import memory as obs_memory
from ..obs import metrics as obs_metrics
from ..obs import roofline
from ..obs.canary import CanaryController
from ..obs.capacity import EwmaThroughput
from ..obs.health import HealthEngine
from ..obs.server import start_obs_server
from ..obs.lineage import LineageRecorder
from ..obs.push import AlertBroker
from ..obs.trace import begin_span, span as trace_span
from ..ops.clean_ops import (fft_zap_time, renormalize_data, zero_dm_filter)
from ..ops.rebin import quick_resample
from ..ops.search import dedispersion_search
from ..parallel.stream import iter_chunk_starts, plan_chunks
from ..pipeline.pulse_info import PulseInfo
from ..pipeline.spectral_stats import get_bad_chans
from ..resilience import ladder as _resilience_ladder
from ..utils.logging_utils import (BudgetAccountant, logger,
                                   measure_device_rtt)
from ..utils.table import ResultTable


def _search_with_fallback(array, dmmin, dmmax, start_freq, bandwidth,
                          eff_tsamp, *, backend, kernel, capture_plane,
                          state=None, mesh=None, snr_floor=None,
                          chunk=None, policy=None):
    """One chunk's search with failure containment.

    The reference has no failure handling at all (SURVEY §5).  Policy:

    - configuration errors (ValueError/TypeError) propagate immediately —
      they are deterministic and would fail identically on every chunk;
    - a device-side failure (worker crash, wedged tunnel, OOM) is retried
      on the same backend (``policy.retries`` times, default once, with
      exponential ``policy.backoff_s`` between attempts), then the chunk
      falls back to the NumPy reference path (a ``mesh`` run falls back
      the same way: the mesh route is dropped along with the jax
      backend).  With ``policy.timeout_s`` set, every device attempt
      runs on a watchdog thread (:func:`..faults.policy.
      call_with_deadline`) so a *wedged* dispatch — previously an
      infinite stall — is bounded by ``timeout_s × (retries + 1)``
      before the fallback;
    - the fallback decision is remembered in ``state`` (a mutable dict
      shared across the chunk loop), so a persistently broken device is
      discovered once — not re-discovered with two doomed attempts per
      chunk — and every subsequent chunk runs on the same backend/kernel
      (one consistent trial grid in the candidate store).

    Retries are counted (``putpu_dispatch_retries_total``) and each
    retry attempt is a ``dispatch_retry`` span, so a flaky device is
    visible in the metrics snapshot and the Chrome trace.

    ``mesh`` routes the chunk through the sharded multi-device searches
    (``kernel="hybrid"`` -> :func:`..parallel.sharded_fdmt.sharded_hybrid_search`,
    ``"fdmt"`` -> :func:`..parallel.sharded_fdmt.sharded_fdmt_search`,
    anything else -> the DM x chan sharded exact sweep).  ``snr_floor``
    reaches the hybrid searches (single- and multi-device) so the noise
    certificate can fire on signal-free chunks.  Round 6: a floorless
    mesh hybrid chunk (the common streaming configuration — thresholds
    below the certifiable floor resolve to ``snr_floor=None``) runs its
    whole first round as ONE fused ``shard_map`` dispatch, with the
    guarantee loop as the escape hatch; with a certificate-mode floor
    the two-stage composition is kept deliberately, so a certified
    chunk pays one coarse dispatch and no seed rescore — the same
    gating as the single-device fused path.
    """
    from ..resilience import ladder as _ladder

    policy = policy if policy is not None else DispatchPolicy()
    state = state if state is not None else {}
    bk = state.get("backend", backend)
    kern = state.get("kernel", kernel)
    # attempt tuples carry an oom_retry flag: a RESOURCE_EXHAUSTED is
    # NOT one of the transient faults the retry budget exists for
    # (retrying the identical dispatch would OOM identically) — it gets
    # ladder descents instead, counted as putpu_oom_* rather than
    # putpu_dispatch_retries_total (ISSUE 12)
    attempts = [(bk, kern, False)] * (1 + max(int(policy.retries), 0))
    if bk != "numpy":
        attempts.append(("numpy", "auto", False))
    last = None
    oom_descents = 0

    def run_one(b, k):
        if b != "numpy":
            # the numpy reference path is the last-resort fallback this
            # ladder exists to reach: injecting there too would make a
            # *persistent* dispatch fault (FaultSpec times=None) crash
            # the run through the very fallback the harness must prove
            # (code-review r8)
            fault_inject.fire("dispatch", chunk=chunk, backend=b)
        else:
            # the OOM drill's floor seam: only kind="oom" specs target
            # the "host" site, so every pre-existing dispatch-fault
            # class still proves the numpy fallback un-injected
            fault_inject.fire("host", chunk=chunk, backend=b)
        if mesh is not None and b == "jax":
            fault_inject.fire("mesh", chunk=chunk)
            # plane capture on the mesh path stays DM-sharded and
            # device-resident (a ShardedPlane handle; the downstream
            # period search and diagnostics consume shard-local products
            # instead of a gathered plane — see parallel/sharded_plane)
            from ..parallel.sharded import sharded_dedispersion_search
            from ..parallel.sharded_fdmt import (
                sharded_fdmt_search,
                sharded_hybrid_search,
            )

            if k == "hybrid":
                return sharded_hybrid_search(
                    array, dmmin, dmmax, start_freq, bandwidth, eff_tsamp,
                    mesh=mesh, snr_floor=snr_floor,
                    capture_plane=capture_plane)
            if k == "fdmt":
                return sharded_fdmt_search(
                    array, dmmin, dmmax, start_freq, bandwidth, eff_tsamp,
                    mesh=mesh, capture_plane=capture_plane)
            return sharded_dedispersion_search(
                array, dmmin, dmmax, start_freq, bandwidth, eff_tsamp,
                mesh=mesh, capture_plane=capture_plane, plane_handle=True)
        return dedispersion_search(
            array, dmmin, dmmax, start_freq, bandwidth, eff_tsamp,
            backend=b, kernel=k, capture_plane=capture_plane,
            **({"snr_floor": snr_floor} if k == "hybrid" else {}))

    i = 0
    while i < len(attempts):
        b, k, oom_retry = attempts[i]
        try:
            # the numpy reference path is the reliability floor: no
            # watchdog (a deadline there would turn the last-resort
            # fallback into another way to fail)
            timeout = policy.timeout_s if b != "numpy" else None
            if i and (b, k) == (bk, kern) and not oom_retry:
                # a same-backend RETRY: counted, backed off, and traced
                # as one — the numpy fallback attempt is neither (span
                # and counter must agree; code-review r8), and an OOM
                # ladder re-dispatch is counted under putpu_oom_*
                obs_metrics.counter("putpu_dispatch_retries_total").inc()
                if policy.backoff_s:
                    time.sleep(policy.backoff_s * (2 ** (i - 1)))
                with trace_span("dispatch_retry", chunk=chunk, attempt=i,
                                backend=b):
                    result = call_with_deadline(
                        lambda: run_one(b, k), timeout)
            else:
                result = call_with_deadline(lambda: run_one(b, k), timeout)
            if (b, k) != (bk, kern):
                logger.error(
                    "device search failed persistently; the rest of this "
                    "run uses backend=%s kernel=%s (reference path)", b, k)
                state["backend"], state["kernel"] = b, k
            return result
        except (ValueError, TypeError):
            raise  # deterministic configuration error
        except _ladder.OOMFloorError:
            raise  # already classified at a deeper rung
        except Exception as exc:  # jax runtime errors share no base class
            last = exc
            if _ladder.is_resource_exhausted(exc):
                # RESOURCE_EXHAUSTED — distinguished from the transient
                # dispatch faults above (ISSUE 12).  On a device rung:
                # descend the degradation ladder and re-dispatch
                # smaller (byte-identical by construction).  On the
                # numpy floor: the chunk cannot be searched on this
                # host at all — quarantine it (oom_floor), never wedge
                # or kill the survey.
                _ladder.oom_event("chunk_search")
                if b == "numpy":
                    raise _ladder.OOMFloorError(
                        f"chunk {chunk}: the numpy reliability floor "
                        f"itself ran out of memory ({exc!r}); "
                        "quarantining the chunk as oom_floor") from exc
                step = ("unfuse" if k == "hybrid" else "split_dm")
                _ladder.descend(step)
                if oom_descents < 2 * len(_ladder.STEPS):
                    oom_descents += 1
                    attempts.insert(i + 1, (b, k, True))
                logger.warning(
                    "chunk %s search hit RESOURCE_EXHAUSTED on "
                    "backend=%s kernel=%s (%r); degradation ladder "
                    "step %r, re-dispatching smaller", chunk, b, k,
                    exc, step)
            elif i + 1 < len(attempts):
                nxt = attempts[i + 1]
                logger.warning(
                    "chunk search failed on backend=%s kernel=%s (%r); "
                    "retrying with backend=%s kernel=%s", b, k, exc,
                    nxt[0], nxt[1])
            i += 1
            continue
    raise last


class _ReadFailure:
    """Sentinel from the reader thread: a chunk's read failed even after
    the bounded retries.  The chunk loop quarantines that one chunk
    (done-with-reason in the ledger, a manifest record) instead of the
    whole stream dying on one bad disk region."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def plan_survey(fname, chunk_length=None, new_sample_time=None, tmin=0,
                dmmin=200, dmmax=800, surelybad=(), *, backend="jax",
                kernel="auto", snr_threshold=6.0, fft_zap=False,
                cut_outliers=False, zero_dm=False, mesh=None,
                exact_floor="auto", quarantine_policy="sanitize",
                period_search=False, period_sigma_threshold=8.0,
                fingerprint_extra=None):
    """Resolve a survey's geometry, threshold and resume fingerprint
    WITHOUT searching anything.

    ``fingerprint_extra`` (a flat JSON-safe dict) is folded into the
    resume-ledger fingerprint when non-empty — the workload seam
    (ISSUE 13): a periodicity job over a file must not share a ledger
    with a single-pulse survey of the same physics (its accumulation
    snapshot advances in lockstep with *its* ledger), and ``None``
    keeps every pre-existing fingerprint byte-identical.  Keys must
    not collide with the driver's own fingerprint fields.

    This is the single source of truth :func:`search_by_chunks` plans
    from, split out (ISSUE 9) so the fleet coordinator
    (:mod:`..fleet.coordinator`) can shard a file into the *exact* chunk
    grid — and read the *exact* resume-ledger fingerprint — that a
    worker's ``search_by_chunks`` run will use.  Any drift between the
    two would silently orphan ledgers across the fleet, so there is
    deliberately no second copy of this logic anywhere.

    Returns a dict: ``reader`` (the open
    :class:`~pulsarutils_tpu.io.sigproc.FilterbankReader`), ``plan``
    (the :class:`~pulsarutils_tpu.parallel.stream.ChunkPlan`),
    ``chunk_starts`` (every planned chunk ``istart``, before any resume
    filtering), ``snr_threshold`` (the resolved float — ``"auto"`` /
    ``"certifiable"`` strings are resolved here), ``search_snr_floor``
    (the hybrid's forwarded floor, or ``None``), ``fingerprint`` (the
    resume-ledger key), ``root`` (the candidate filename stem) and
    ``nsamples``/``sample_time``.
    """
    logger.info("opening %s", fname)
    # strip only the final extension: "obs.day1.fil" and "obs.day2.fil"
    # must keep distinct candidate roots in a shared output directory
    root = os.path.splitext(os.path.basename(str(fname)))[0]
    reader = FilterbankReader(fname)
    header = reader.header
    nsamples = header["nsamples"]
    sample_time = header["tsamp"]
    start_freq = header["fbottom"]
    stop_freq = header["ftop"]
    bandwidth = header["bandwidth"]
    foff = header["foff"]

    plan = plan_chunks(nsamples, sample_time, dmmin, dmmax, start_freq,
                       stop_freq, foff, chunk_length=chunk_length,
                       new_sample_time=new_sample_time)
    eff_tsamp = plan.sample_time
    logger.info("chunk plan: step=%d hop=%d resample=%d -> tsamp=%g s",
                plan.step, plan.hop, plan.resample, eff_tsamp)

    def _chunk_cert_floor():
        """Certifiable floor for this chunk geometry (lazy: the
        retention bound is a multi-second host computation at
        multi-thousand-trial configs and only two configurations need
        it — snr_threshold='certifiable', and the hybrid's
        exact_floor='auto' comparison)."""
        from ..ops.certify import certifiable_snr_floor, retention_bound
        from ..ops.plan import dedispersion_plan

        nchan = header["nchans"]
        t_eff = max(plan.step // plan.resample, 2)
        trial_dms = dedispersion_plan(nchan, dmmin, dmmax, start_freq,
                                      bandwidth, eff_tsamp)
        rho = retention_bound(nchan, trial_dms, start_freq, bandwidth,
                              eff_tsamp, t_eff, cert=True)
        return certifiable_snr_floor(t_eff, len(trial_dms), rho)

    if isinstance(snr_threshold, str):
        from ..ops.certify import matched_snr_floor
        from ..ops.plan import dedispersion_plan

        t_eff = max(plan.step // plan.resample, 2)
        if snr_threshold == "auto":
            ndm = len(dedispersion_plan(header["nchans"], dmmin, dmmax,
                                        start_freq, bandwidth, eff_tsamp))
            # clamped to the reference default (clean.py:349): at short
            # chunks the matched floor resolves BELOW 6 and "auto" must
            # never be more permissive than the reference's criterion
            # (the Gumbel fit is also least validated at small m —
            # certify.expected_noise_max_snr's stated fit domain)
            snr_threshold = max(matched_snr_floor(t_eff, ndm), 6.0)
        elif snr_threshold == "certifiable":
            snr_threshold = _chunk_cert_floor()
        else:
            raise ValueError(
                f"snr_threshold={snr_threshold!r}: expected a number, "
                "'auto' or 'certifiable'")
        snr_threshold = round(float(snr_threshold), 2)
        logger.info("snr_threshold resolved to %.2f for %d-sample chunks",
                    snr_threshold, t_eff)

    # the hybrid gets the threshold as its snr_floor ONLY when the noise
    # certificate can actually fire at that level: forwarding a
    # sub-certifiable floor (e.g. the reference default 6.0 on
    # million-sample chunks) would make the rigorous all-detections-exact
    # criterion rescan toward a full exact sweep on EVERY chunk — the
    # round-2 behaviour this round removed.  Below the certifiable level
    # the hybrid runs floorless (exact-argbest-only contract, the round-2
    # streaming semantics), which is both faster and what the fixed
    # thresholds historically meant.
    search_snr_floor = None
    if kernel == "hybrid" and exact_floor is not False:
        cert_floor = None if exact_floor is True else _chunk_cert_floor()
        if exact_floor is True \
                or snr_threshold >= round(cert_floor, 2) - 1e-9:
            search_snr_floor = snr_threshold
        else:
            logger.info(
                "snr_threshold %.2f sits below the certifiable floor "
                "%.2f for this chunk geometry: hybrid runs without "
                "snr_floor (exact best row only; pass exact_floor=True "
                "to force the all-detections-exact contract, or "
                "snr_threshold='certifiable' for the noise-certificate "
                "fast path)", snr_threshold, cert_floor)

    fingerprint = config_fingerprint(
        fname=os.path.abspath(str(fname)), dmmin=dmmin, dmmax=dmmax,
        step=plan.step, resample=plan.resample, backend=backend,
        kernel=kernel, snr_threshold=snr_threshold, fft_zap=fft_zap,
        cut_outliers=cut_outliers,
        # only fingerprint zero_dm when it changes the result: adding the
        # key unconditionally would orphan every pre-existing resume
        # ledger for plain runs
        **({"zero_dm": True} if zero_dm else {}),
        # same orphan-avoidance rule for the mesh route (device count
        # changes the f32 reduction shapes, not the science)
        **({"mesh": list(mesh.shape.values())} if mesh is not None else {}),
        # and for the integrity gate: a non-default policy changes what
        # gets searched on flagged data, so its ledger must not be
        # interchangeable with the default's (a default-policy run
        # keeps the pre-hardening fingerprint — no orphaned ledgers)
        **({"quarantine_policy": str(quarantine_policy)}
           if quarantine_policy != "sanitize" else {}),
        surelybad=sorted(int(c) for c in surelybad),
        period_search=bool(period_search),
        period_sigma_threshold=float(period_sigma_threshold),
        # workload-distinct ledgers (ISSUE 13): merged LAST so a
        # collision with a driver field fails loudly in review, and
        # absent entirely when unset — every pre-existing ledger
        # fingerprint is unchanged
        **(fingerprint_extra or {}))

    return {
        "reader": reader, "plan": plan, "root": root,
        "nsamples": nsamples, "sample_time": sample_time,
        "snr_threshold": snr_threshold,
        "search_snr_floor": search_snr_floor,
        "fingerprint": fingerprint,
        "chunk_starts": list(iter_chunk_starts(nsamples, plan, tmin=tmin,
                                               sample_time=sample_time)),
    }


def search_by_chunks(fname, chunk_length=None, new_sample_time=None, tmin=0,
                     dmmin=200, dmmax=800, surelybad=(), *, backend="jax",
                     kernel="auto", snr_threshold=6.0, output_dir=None,
                     make_plots="hits", resume=True, fft_zap=False,
                     cut_outliers=False, zero_dm=False, max_chunks=None,
                     progress=True, period_search=False,
                     period_sigma_threshold=8.0, show_plots=False,
                     mesh=None, exact_floor="auto", overlap_persist=True,
                     budget=None, dispatch_timeout=None, dispatch_retries=1,
                     dispatch_backoff=0.0, quarantine_policy="sanitize",
                     persist_retries=2, persist_backoff=0.05,
                     http_port=None, http_host="127.0.0.1", canary=None,
                     health=None, report_out=None, chunks=None,
                     cancel_cb=None, plane_consumer=None,
                     fingerprint_extra=None, fence=None, lineage=None,
                     push=None):
    """Search a filterbank file for dispersed single pulses.

    Parameters follow the reference driver (``clean.py:276``) plus the
    TPU-framework knobs (keyword-only).  ``make_plots``: ``"hits"``
    (diagnostic JPEG per candidate), ``"all"``, or ``False``.

    ``snr_threshold`` is the reference's hit criterion (``snr > 6``,
    ``clean.py:349``).  Besides a number it accepts two strings that
    adapt the floor to the chunk geometry (the fixed 6 was tuned for the
    reference's ~1e3-sample chunks; at million-sample chunks the
    signal-free maximum alone is ~5.5 — see :mod:`..ops.certify`):

    * ``"auto"`` — the statistically matched floor
      (:func:`~pulsarutils_tpu.ops.certify.matched_snr_floor`): noise
      ceiling + 1, sub-percent false alarms per chunk;
    * ``"certifiable"`` — the lowest floor whose noise certificate fires
      on typical signal-free chunks
      (:func:`~pulsarutils_tpu.ops.certify.certifiable_snr_floor`):
      with ``kernel="hybrid"`` the streaming cost of a signal-free chunk
      drops to one coarse sweep (the survey fast path).

    ``exact_floor`` controls whether ``snr_threshold`` is also forwarded
    as the hybrid kernel's ``snr_floor`` (the all-above-threshold-
    detections-exact contract + the noise certificate):

    * ``"auto"`` (default) — forwarded only when the threshold sits at
      or above the chunk geometry's certifiable floor; below it the
      hybrid runs floorless (exact best row only — the fast behaviour
      the fixed reference thresholds historically got), with an
      info-level log stating so;
    * ``True`` — always forwarded: every above-threshold detection is
      exact, accepting that below the certifiable floor this honestly
      costs up to a full exact sweep per chunk;
    * ``False`` — never forwarded.

    ``mesh`` (a ``jax.sharding.Mesh``) routes every chunk through the
    multi-device sharded searches — the same device-resident chunk is
    searched by all devices (for ``kernel="hybrid"`` the DM-sliced
    coarse stage, seed selection and exact seed/need rescore run as ONE
    fused ``shard_map`` dispatch on floorless chunks, round 6; the
    per-chunk dispatch/readback trip counts land in the chunk budget
    exactly as on the single-device path, so the ``BUDGET_JSON`` footer
    prices the mesh route's tunnel trips honestly).
    ``make_plots``/``period_search`` work on the mesh path too: the
    captured plane stays DM-sharded and device-resident, the
    periodicity spectra and the figure's per-row H curve are computed
    shard-locally, and only per-row score vectors, a decimated image
    and single rows are gathered (:mod:`..parallel.sharded_plane`).

    ``show_plots=True`` additionally displays each diagnostic figure in
    an interactive window (the reference's ``show=True`` behaviour,
    ``clean.py:347``) — a no-op under a non-interactive matplotlib
    backend, so headless runs are unaffected.

    ``period_search=True`` adds the folded period search
    (:func:`..ops.periodicity.period_search_plane`) on every chunk's
    dedispersed plane: a chunk whose best periodic candidate exceeds
    ``period_sigma_threshold`` is persisted as a hit even without a
    single-pulse detection, with the folded profile and H statistics on
    its :class:`~.pulse_info.PulseInfo`.

    ``overlap_persist`` (default on, round 6) moves each chunk's
    candidate persist + ledger write onto a single-worker executor so
    the host-side npz compression of chunk ``k`` overlaps the device
    search of chunk ``k+1``.  The worker is FIFO, ``save_candidate``
    precedes ``mark_done`` inside one task, and every task is drained
    before the function returns — ledger ordering, crash-safe resume
    semantics and the persisted candidate set are identical to the
    serial loop (pinned by ``tests/test_budget.py``).
    ``overlap_persist=False`` restores the strictly serial loop.

    ``budget`` accepts a caller-owned
    :class:`~pulsarutils_tpu.utils.logging_utils.BudgetAccountant`; by
    default one is created internally.  Either way every chunk's wall
    clock is attributed to named buckets (read/upload_wait/clean/search
    with the kernel facade's sub-buckets/trim/persist/...), with the
    residual reported as ``unattributed`` per chunk and in the run
    footer, a measured device RTT pricing the dispatch+readback trip
    counters, and a one-line ``BUDGET_JSON`` record logged for
    artifact parsers (the round-5 rehearsal's stage table explained ~6%
    of its wall clock; this layer exists so that can never happen
    silently again).

    Robustness knobs (ISSUE 4; see ``docs/robustness.md``).  On clean
    (all-finite) input the defaults reproduce the pre-hardening data
    path exactly — pinned by test; on data the integrity gate flags,
    the defaults *deliberately* diverge (sanitize or quarantine where
    the old path searched garbage); pass ``quarantine_policy="off"``
    for the literal pre-hardening behaviour:

    * ``dispatch_timeout`` (seconds, default off) bounds each device
      dispatch on a watchdog thread — a wedged device used to stall the
      stream forever; with a deadline the chunk proceeds to retry /
      numpy fallback within ``dispatch_timeout × (dispatch_retries +
      1)``.  Off by default (inline dispatch, byte-identical path);
      when arming it, note the watchdog dispatches from a non-main
      thread — device clients that require main-thread dispatch must
      be tested first (``docs/robustness.md``).  ``dispatch_retries``
      / ``dispatch_backoff`` shape the same-backend retry ladder
      before the numpy fallback;
    * ``quarantine_policy`` (``"sanitize"`` default / ``"strict"`` /
      ``"off"``) arms the pre-search data-integrity gate: chunks whose
      NaN/Inf, dead-channel, zero-run or saturation fractions breach
      the :class:`~pulsarutils_tpu.faults.policy.IntegrityPolicy`
      thresholds are **quarantined** — recorded in
      ``quarantine_<fingerprint>.jsonl`` and marked done-with-reason in
      the ledger (exact resume semantics) instead of poisoning the S/N
      statistics or crashing; sub-threshold NaN chunks are sanitized
      (non-finite values imputed, counted) under ``"sanitize"``.  The
      gate runs on the reader thread (overlapped, not on the chunk's
      serial critical path); low-bit (1/2/4-bit) chunks — packed fast
      path or host-decoded — are gated in the CODE domain instead
      (rail/zero/dead-channel fractions off the raw packed bytes, with
      thresholds rescaled onto the quantization floor, round 11 — the
      float gate used to skip them entirely, leaving low-bit runs
      health-blind);
    * persist failures retry ``persist_retries`` times with exponential
      ``persist_backoff`` and then **dead-letter** the chunk into the
      quarantine manifest instead of failing the whole run on one bad
      write; an end-of-run integrity audit
      (:func:`~pulsarutils_tpu.faults.audit.audit_run`) cross-checks
      ledger vs candidate files vs manifest and logs any inconsistency.

    Live observability knobs (ISSUE 5; ``docs/observability.md``) —
    all default-off, and when off the data path is byte-identical to
    the pre-PR driver:

    * ``http_port`` starts the live HTTP surface
      (:mod:`~pulsarutils_tpu.obs.server`): ``/metrics`` (live
      Prometheus scrape), ``/healthz`` (engine verdict, HTTP 503 on
      CRITICAL), ``/progress`` (chunks done/total of *this session's*
      work list, ETA, canary recall).  ``0`` binds an ephemeral port;
      ``http_host`` picks the bind address — the loopback default
      keeps the surface on-machine, ``"0.0.0.0"`` exposes it to a
      remote Prometheus scrape job or fleet ``/healthz`` probe;
    * ``canary`` arms continuous synthetic-pulse injection-recovery
      (:class:`~pulsarutils_tpu.obs.canary.CanaryController`, or a bare
      float taken as the injection rate): known-(DM, width, S/N)
      dispersed pulses on the reader thread, matched against the
      emitted tables into live recall / S/N-recovery / DM-error
      metrics.  Canary-matched best rows are tagged and **excluded**
      from the hits list, candidate files and ledger — when the canary
      outranks a genuine weaker pulse in the same chunk, that pulse is
      promoted (persisted with the canary rows masked out of its
      table) so the science candidate set matches the canary-off run;
      on the packed low-bit fast path the bump is quantized into the
      low-bit codes and re-packed on the reader thread (round 11), so
      recall is measured there too — the old auto-disable is gone;
    * ``health`` accepts a caller-owned
      :class:`~pulsarutils_tpu.obs.health.HealthEngine` (the chaos
      drill passes one); with ``http_port`` set and no engine given,
      one is created internally.  The engine receives one update per
      chunk (wall, candidate count, quarantines, retries, retraces,
      headroom, canary recall) and folds them into the OK / DEGRADED /
      CRITICAL verdict ``/healthz`` serves;
    * ``report_out`` writes the end-of-run survey report (markdown +
      single-file HTML, :mod:`~pulsarutils_tpu.obs.report`) stitching
      budget, roofline, canary recall curve, health incidents, sift
      counters and the quarantine manifest into one artifact.

    Fleet knobs (ISSUE 9; ``docs/fleet.md``) — default-off, byte-inert
    when unset:

    * ``chunks`` restricts the session to the given chunk ``istart``
      values (an iterable; chunk starts not in the plan are ignored).
      This is the fleet worker's lease seam: a leased work unit is a
      subset of one file's chunk grid, and each chunk's persisted
      candidate/ledger bytes are independent of which session searches
      it — the byte-identity contract bench config 14 gates.  Chunks
      outside the subset are neither searched nor marked done;
    * ``cancel_cb`` (zero-arg callable) is checked before each chunk:
      once it returns True the session finishes nothing further — the
      in-flight chunk completes, its persist/ledger write drains, and
      the remaining chunks stay un-marked so a resumed (or re-leased)
      session picks up exactly there.  This is the worker's graceful
      drain seam.

    Periodicity seams (ISSUE 13; ``docs/periodicity.md``) — both
    byte-inert when unset:

    * ``plane_consumer`` (a ``fn(istart, plane, table)`` callable)
      forces plane capture and hands each searched chunk's dedispersed
      plane — a device array, or a DM-sharded
      :class:`~pulsarutils_tpu.parallel.sharded_plane.ShardedPlane`
      handle on the mesh route — downstream before it is dropped.
      Called BEFORE the chunk's ledger mark, so a crash window at
      worst re-delivers a chunk on resume; consumers must de-duplicate
      by ``istart`` (the
      :class:`~pulsarutils_tpu.periodicity.accumulate.
      DMTimeAccumulator` does).  With the single-pulse ``canary``
      armed, injected chunks' planes carry the synthetic track — the
      periodicity driver runs canary-off on this leg and injects its
      own periodic canary downstream;
    * ``fingerprint_extra`` rides to :func:`plan_survey` so a
      different *workload* over the same file keeps its own resume
      ledger.

    Candidate lifecycle observability (ISSUE 18), both ``None``-gated
    (off keeps the output directory byte-identical):

    * ``lineage`` — ``True`` (or a
      :class:`~pulsarutils_tpu.obs.lineage.LineageRecorder`) stamps
      every hit with monotone stage timestamps (read → dispatch →
      device ready → sift → persist → alert), persists a
      ``.lineage.json`` doc beside the candidate npz pair, feeds the
      ``putpu_candidate_stage_seconds`` /
      ``putpu_candidate_latency_seconds`` histograms (the
      candidate-latency p95 SLO) and opens a ``candidate`` span on the
      chunk's Perfetto track;
    * ``push`` — an :class:`~pulsarutils_tpu.obs.push.AlertBroker` (or
      a list of subscriber specs, which builds a driver-owned broker
      dead-lettering into the output directory and closes it, bounded,
      at the tail) fans each hit out to webhook subscribers on a
      bounded-queue daemon thread; a slow or dead subscriber can only
      fill the queue (drop-oldest, counted), never stall this loop.
      Canary-tagged rows are excluded before the publish site.

    Returns ``(hits, store)`` where hits is a list of
    ``(istart, iend, PulseInfo, ResultTable)``.  NOTE (round 6): when
    plotting is off, a hit's retained/persisted ``info.allprofs`` is the
    self-describing pulse **cutout** (``cutout_start``/``cutout_decim``
    set, device-sliced before readback), not the full chunk waterfall —
    pulling multi-GB cleaned chunks back over a slow link per hit was
    the survey rehearsal's single largest unattributed cost.
    """
    # identity checks on purpose: exact_floor=1 must NOT silently pass
    # as True (the floor-forwarding branches use `is True`/`is not
    # False`); validated before any file IO so config errors fail fast
    if exact_floor is not True and exact_floor is not False \
            and exact_floor != "auto":
        raise ValueError(f"exact_floor={exact_floor!r}: expected True, "
                         "False or 'auto'")
    if mesh is not None:
        # fail fast: a missing axis would otherwise surface as a KeyError
        # inside the first chunk's search, which the failure-containment
        # path misreads as a transient device fault and silently retries
        # into the numpy fallback.  kernel="fdmt" routes to the DM-sliced
        # sharded FDMT only, so a dm-only mesh is valid there; every
        # other kernel reaches sharded_dedispersion_search, which indexes
        # both axes.
        needed = {"dm"} if kernel == "fdmt" else {"dm", "chan"}
        if not needed <= set(mesh.shape):
            raise ValueError(
                f"mesh axes {tuple(mesh.shape)} must include "
                f"{sorted(needed)} for kernel={kernel!r} (build one with "
                "make_mesh((d, c), ('dm', 'chan')))")
    # resolved before any file IO so a bogus policy string fails fast
    integrity = resolve_integrity_policy(quarantine_policy)
    dispatch_policy = DispatchPolicy(timeout_s=dispatch_timeout,
                                     retries=dispatch_retries,
                                     backoff_s=dispatch_backoff)
    # canary normalisation fails fast too: a bare number is the rate
    if canary is not None and not isinstance(canary, CanaryController):
        canary = CanaryController(rate=float(canary))
    if canary is not None and canary.rate <= 0.0:
        canary = None  # rate 0 is the documented spelled-out "off"
    output_dir = output_dir or os.path.dirname(os.path.abspath(str(fname)))

    if make_plots:
        try:
            import matplotlib  # noqa: F401 — optional [plot] extra
        except ImportError:
            logger.warning("matplotlib not installed: diagnostic plots "
                           "disabled (install the [plot] extra)")
            make_plots = False

    timer = budget if budget is not None else BudgetAccountant()
    timer.begin_stream()  # reused accountants: retrace baseline per run
    # each survey session starts undegraded: within a run OOM descents
    # are sticky (a measured slowdown, not a crash loop); a fresh run
    # rediscovers pressure through the preflight estimator (ISSUE 12)
    _resilience_ladder.reset()

    with_timer = timer.bucket
    with with_timer("badchans"):
        # the pre-scan streams the whole file through the same reader
        # seam the chunk loop uses, but BEFORE the hardened loop
        # exists: injection is suppressed here so an env-armed read
        # fault targets the search chunks (and cannot crash the run at
        # startup or silently eat a times=1 budget); the scan has its
        # own resilience story (.badchans cache, restartable)
        with fault_inject.suppressed():
            mask_fileorder = get_bad_chans(fname, surelybad=surelybad)

    # geometry, resolved threshold and ledger fingerprint all come from
    # the ONE planning function the fleet coordinator also calls — any
    # second copy of this logic would let coordinator and worker drift
    # onto different ledgers (ISSUE 9)
    sp = plan_survey(fname, chunk_length=chunk_length,
                     new_sample_time=new_sample_time, tmin=tmin,
                     dmmin=dmmin, dmmax=dmmax, surelybad=surelybad,
                     backend=backend, kernel=kernel,
                     snr_threshold=snr_threshold, fft_zap=fft_zap,
                     cut_outliers=cut_outliers, zero_dm=zero_dm,
                     mesh=mesh, exact_floor=exact_floor,
                     quarantine_policy=quarantine_policy,
                     period_search=period_search,
                     period_sigma_threshold=period_sigma_threshold,
                     fingerprint_extra=fingerprint_extra)
    reader = sp["reader"]
    root = sp["root"]
    header = reader.header
    nsamples = sp["nsamples"]
    sample_time = sp["sample_time"]
    start_freq = header["fbottom"]
    bandwidth = header["bandwidth"]
    date = header.get("tstart", None)

    # single place that owns band orientation: ascending everywhere below
    mask = mask_fileorder[::-1] if reader.band_descending else mask_fileorder

    plan = sp["plan"]
    eff_tsamp = plan.sample_time
    snr_threshold = sp["snr_threshold"]
    search_snr_floor = sp["search_snr_floor"]
    fingerprint = sp["fingerprint"]
    # fence (ISSUE 15): the fleet worker's lease epoch — candidate
    # artifact writes stamped with a higher epoch are refused (see
    # CandidateStore).  None (every non-fleet caller) is byte-inert.
    store = CandidateStore(output_dir, fingerprint if resume else None,
                           fence=fence)
    # quarantine manifest: created lazily on first record, so a clean
    # run's output directory is byte-identical to pre-hardening
    manifest = QuarantineManifest(output_dir,
                                  fingerprint if resume else None)

    # candidate lifecycle observability (ISSUE 18).  ``lineage=True``
    # builds a per-run recorder (or pass a LineageRecorder to share one
    # across files); ``push`` accepts an AlertBroker or a list of
    # subscriber specs (urls/dicts) — specs build a driver-owned broker
    # dead-lettering into the output directory, closed (bounded) at the
    # tail.  Both are None-gated: off is the pre-PR code path and the
    # output directory is byte-identical.
    if lineage is True:
        lineage = LineageRecorder(fingerprint=fingerprint,
                                  source="search_by_chunks")
    elif not lineage:
        lineage = None          # accept False/0/"" as "off" (CLI flag)
    push_owned = False
    if not push:
        push = None
    elif not isinstance(push, AlertBroker):
        push = AlertBroker(
            push, health=health,
            dead_letter_path=os.path.join(
                output_dir, f"push_dead_letter_{fingerprint}.jsonl"))
        push_owned = True

    hits = []
    nproc = 0
    ncertified = 0
    capture = bool(make_plots) or bool(period_search) \
        or plane_consumer is not None
    fallback_state = {}

    # one conditioning pipeline, parameterised by array namespace — the
    # device (jitted) and host (fallback) paths must never diverge
    def _clean(block, m, xp=np):
        cleaned = renormalize_data(block, badchans_mask=m,
                                   cut_outliers=cut_outliers, xp=xp)
        if zero_dm:
            cleaned = zero_dm_filter(cleaned, badchans_mask=m, xp=xp)
        if fft_zap:
            cleaned, _ = fft_zap_time(cleaned, xp=xp)
        if plan.resample > 1:
            cleaned = quick_resample(cleaned, plan.resample, xp=xp)
        return cleaned

    # device-side cleaning: with backend="jax" the chunk is uploaded raw
    # and conditioned on the accelerator (one jitted program reused for
    # every chunk) — the host, often a single core, only reads/decodes,
    # and the cleaned chunk is already device-resident for the search.
    # Low-bit single-IF files go further (round 4): the PACKED bytes are
    # uploaded and the bit-unpack runs inside the same jit — 1/16th the
    # link traffic at 2 bits, which is the survey bottleneck on thin
    # links (the C++ host unpacker stays as the fallback decode).
    packed_bits = (reader._nbits
                   if (backend == "jax" and reader.nifs == 1
                       and reader._nbits in (1, 2, 4)) else 0)
    if canary is not None:
        # the packed fast path injects too (round 11): the bump is
        # quantized into the low-bit codes and re-packed on the reader
        # thread (CanaryController.maybe_inject_packed), so the device
        # signature is exact and recall is measured on packed runs —
        # the old auto-disable seam is gone
        canary.bind(nchan=header["nchans"], start_freq=start_freq,
                    bandwidth=bandwidth, tsamp=sample_time,
                    dmmin=dmmin, dmmax=dmmax,
                    resample=plan.resample)
    device_clean = None
    if backend == "jax":
        import functools

        import jax
        import jax.numpy as jnp

        mask_dev = jnp.asarray(np.asarray(mask))
        # donate the raw chunk buffer into the clean program on
        # accelerators: it is never touched again (the host copy backs
        # the fallback), so the cleaned output can reuse its HBM — one
        # fewer live chunk-sized buffer during the double-buffered
        # stream.  CPU ignores donation with a per-call warning, so the
        # flag is backend-gated rather than unconditional.
        donate = ((0,) if jax.default_backend() in ("tpu", "gpu") else ())
        if packed_bits:
            from ..io.lowbit import device_unpack_block

            nchan_file = header["nchans"]
            descending = reader.band_descending

            def _unpack_clean(raw, m):
                return _clean(device_unpack_block(
                    raw, packed_bits, nchan_file,
                    band_descending=descending, xp=jnp), m, xp=jnp)

            device_clean = jax.jit(_unpack_clean, donate_argnums=donate)
        else:
            device_clean = jax.jit(functools.partial(_clean, xp=jnp),
                                   donate_argnums=donate)
        if timer.rtt_s is None:  # keep a caller-calibrated RTT
            timer.rtt_s = measure_device_rtt()
        if timer.rtt_s is not None:
            logger.info("device round-trip floor: %.4fs per "
                        "dispatch+readback trip", timer.rtt_s)

    # the chunk list is known upfront, so the NEXT chunk's read/decode
    # overlaps the current chunk's device compute (single reader thread —
    # the driver host is often one core doing nothing during the search)
    todo = [s for s in sp["chunk_starts"]
            if not (resume and store.is_done(s))]
    if chunks is not None:
        # fleet lease subset: only the leased chunk starts are searched
        # (or marked done) this session; unknown starts are ignored so a
        # stale lease over a replanned file degrades to a no-op, not a
        # crash
        wanted = {int(c) for c in chunks}
        todo = [s for s in todo if s in wanted]
    if max_chunks is not None:
        todo = todo[:max_chunks]

    # -- live surface (ISSUE 5): health engine + HTTP endpoints ---------
    if http_port is not None and health is None:
        health = HealthEngine()
    t_run0 = time.time()
    # EWMA chunk throughput (ISSUE 20): the /progress ETA follows the
    # CURRENT rate, so one slow warm-up/compile chunk stops poisoning
    # the estimate after a few folds.  The lifetime mean stays as the
    # fallback until the model has evidence.
    eta_model = EwmaThroughput()

    def _progress_snapshot():
        """The ``/progress`` document (read from the scrape thread —
        plain reads of ints/lists under the GIL)."""
        done = nproc
        total = len(todo)
        elapsed = time.time() - t_run0
        eta = eta_model.eta_s(max(total - done, 0))
        if eta is None and done and elapsed > 0:
            eta = (total - done) * elapsed / done
        doc = {"fname": os.path.basename(str(fname)),
               "chunks_done": done, "chunks_total": total,
               "elapsed_s": round(elapsed, 1),
               "eta_s": None if eta is None else round(eta, 1),
               "hits": len(hits), "certified": ncertified,
               "quarantined": len(store.quarantined_chunks)}
        if canary is not None:
            doc["canary"] = canary.summary()
        return doc

    obs_server = None
    if http_port is not None:
        obs_server = start_obs_server(http_port, health=health,
                                      progress_fn=_progress_snapshot,
                                      host=http_host, push=push)

    # health consumes per-chunk DELTAS of process-wide counters (other
    # runs in this process may have bumped them already).  OOM events
    # arrive per surface label, so the delta is over the labelled sum.
    def _oom_events_total():
        return sum(
            m.get("value", 0)
            for m in obs_metrics.REGISTRY.snapshot()
            if m.get("name") == "putpu_oom_events_total")

    health_base = {}
    if health is not None:
        for key, name in (("dead", "putpu_persist_dead_letter_total"),
                          ("retry", "putpu_dispatch_retries_total"),
                          ("retrace", "putpu_retraces_total")):
            health_base[key] = obs_metrics.counter(name).value
        health_base["oom"] = _oom_events_total()

    def _health_update(istart, wall_s, candidates=None, quarantined=False,
                       headroom_frac=None, oom_floor=False):
        # every completion path lands here, so this is where the ETA
        # model folds — quarantined chunks count too (they drain the
        # backlog just the same).  wall_s is None on the tail flush:
        # nothing completed, nothing to fold.
        if wall_s is not None:
            eta_model.note(1, wall_s)
        if health is None:
            return
        deltas = {}
        for key, name in (("dead", "putpu_persist_dead_letter_total"),
                          ("retry", "putpu_dispatch_retries_total"),
                          ("retrace", "putpu_retraces_total")):
            v = obs_metrics.counter(name).value
            deltas[key] = v - health_base[key]
            health_base[key] = v
        oom_now = _oom_events_total()
        oom_delta = oom_now - health_base["oom"]
        health_base["oom"] = oom_now
        health.update(
            istart, wall_s=wall_s, candidates=candidates,
            quarantined=quarantined, dead_letter=deltas["dead"] > 0,
            dispatch_retries=deltas["retry"],
            retraces=deltas["retrace"], headroom_frac=headroom_frac,
            oom_events=oom_delta, oom_floor=oom_floor,
            fallback=bool(backend != "numpy"
                          and fallback_state.get("backend") == "numpy"),
            canary=canary.summary() if canary is not None else None)

    from concurrent.futures import ThreadPoolExecutor

    def read_at(s):
        """Read (and gate) one chunk on the reader thread.

        Returns ``(block, gate_info)`` — ``gate_info`` is ``None`` when
        the integrity gate is off or the packed fast path is in use,
        else the verdict/stats dict from :func:`..faults.policy.
        gate_chunk`.  A transient read error is retried (bounded,
        counted); a persistent one returns a ``_ReadFailure`` sentinel
        so the chunk loop quarantines the chunk instead of the whole
        stream dying.  SCOPE: this contains read failures that surface
        as ``OSError`` (network filesystems, injected faults); a bad
        sector under the mmapped file raises SIGBUS, which no except
        clause can catch — pread-based reads would be needed at the
        sigproc seam to contain that class.
        """
        t0 = time.perf_counter()
        if lineage is not None:
            lineage.mark(s, "read")
        try:
            nread = min(plan.step, nsamples - s)
            block = None
            for attempt in range(3):
                try:
                    if packed_bits:
                        # packed bytes straight off the mmap: decode
                        # happens on device (or in the host fallback
                        # below on demand)
                        block = reader.read_block_packed(s, nread)
                    else:
                        block = reader.read_block(s, nread,
                                                  band_ascending=True)
                    break
                except OSError as exc:
                    if attempt == 2:
                        logger.error("chunk %d read failed after %d "
                                     "attempts (%r)", s, attempt + 1, exc)
                        return _ReadFailure(exc), None
                    obs_metrics.counter("putpu_read_retries_total").inc()
                    logger.warning("chunk %d read error (%r); retrying",
                                   s, exc)
                    # backoff before re-reading (reader thread — off the
                    # critical path): immediate retries would exhaust
                    # the budget in microseconds and quarantine a chunk
                    # over a sub-second I/O blip (code-review r8)
                    time.sleep(0.1 * (2 ** attempt))
            if packed_bits:
                # packed fast path (round 11): the canary bump is
                # quantized into the low-bit codes and re-packed here —
                # whatever unpacks these bytes (device jit, host
                # fallback) sees an exact signature — and the
                # code-domain integrity gate reads cheap shift/mask
                # stats off the raw bytes (the float gate was skipped
                # on quantized data since PR 4, leaving low-bit runs
                # health-blind)
                if canary is not None:
                    block = canary.maybe_inject_packed(
                        block, s, nbits=packed_bits,
                        nchan=header["nchans"],
                        band_descending=reader.band_descending)
                if integrity is not None:
                    block, gate_info = gate_chunk_packed(
                        block, packed_bits, header["nchans"], integrity)
                    return block, gate_info
            else:
                block = fault_inject.corrupt("corrupt", block, chunk=s)
                if canary is not None:
                    # canary rides AFTER any armed fault corruption: it
                    # is injected into exactly the bytes the search
                    # will see, so an RFI storm that masks real pulses
                    # masks canaries too — which is the point
                    block = canary.maybe_inject(block, s)
                if integrity is not None \
                        and reader._nbits in (1, 2, 4):
                    # host-decoded low-bit chunk (numpy backend): the
                    # float-domain gate is meaningless on quantized
                    # codes (a healthy 1-bit chunk is ~50% at the
                    # rail, code-review r8) — the CODE-domain rule
                    # applies instead
                    block, gate_info = gate_chunk_lowbit(
                        np.asarray(block), reader._nbits, integrity)
                    return block, gate_info
                if integrity is not None:
                    # gated HERE, on the reader thread: the stats pass
                    # overlaps the previous chunk's device work instead
                    # of sitting on the chunk's serial critical path
                    block, gate_info = gate_chunk(np.asarray(block),
                                                  integrity)
                    return block, gate_info
            return block, None
        finally:
            # reader-thread seconds: overlapped with the previous
            # chunk's device work, so accounted but not in any chunk's
            # serial budget
            timer.add_async("read_decode", time.perf_counter() - t0)

    def prefetch_upload(read_future):
        """Start the async device transfer of the NEXT chunk (main thread).

        Called right before the current chunk's (blocking) search: by then
        the reader thread has usually finished decoding chunk k+1, so its
        host->device transfer proceeds while the device searches chunk k —
        on slow links the transfer dominates the whole stream.  COST: peak
        HBM briefly carries one extra raw chunk; a failure here is
        non-fatal (the main path re-uploads).  All device ops stay on the
        main thread — a transfer started from the reader thread deadlocks
        the tunnelled (axon) client.
        """
        if device_clean is None or read_future is None \
                or not read_future.done():
            return None
        try:
            import jax

            host, gate_info = read_future.result()
            if isinstance(host, _ReadFailure) or (
                    gate_info is not None
                    and gate_info["verdict"] != "clean"):
                # failed/sanitized/quarantined chunks skip the prefetch:
                # the main path handles them (and must never upload the
                # un-sanitized bytes)
                return None
            buf = jax.device_put(host)
            timer.count("prefetch_uploads")
            obs_metrics.counter("putpu_bytes_uploaded_total").inc(
                int(getattr(host, "nbytes", 0)))
            return buf
        except Exception:
            return None

    # persist executor (round 6): one FIFO worker absorbs the per-chunk
    # candidate compression + ledger write so it overlaps the NEXT
    # chunk's device search.  Single worker + save-before-mark inside
    # one task = ledger order and crash-resume semantics byte-identical
    # to the serial loop.
    persist_pool = (ThreadPoolExecutor(max_workers=1) if overlap_persist
                    else None)
    persist_futures = []

    def _persist_and_mark(payload, istart_, iend_, reason=None):
        """Persist + mark done, with bounded retry and a dead-letter.

        A write failure used to fail the whole run (the overlap only
        deferred the raise).  Now: ``persist_retries`` bounded retries
        with exponential backoff, then a ``persist_dead_letter`` record
        in the quarantine manifest and done-with-reason in the ledger —
        the run continues, the audit knows the candidate is missing on
        purpose.  Only ``OSError`` is retried: anything else is a bug,
        not a disk hiccup, and still propagates.
        """
        if payload is not None:
            for attempt in range(max(int(persist_retries), 0) + 1):
                try:
                    store.save_candidate(root, istart_, iend_, *payload)
                    break
                except OSError as exc:
                    if attempt < persist_retries:
                        obs_metrics.counter(
                            "putpu_persist_retries_total").inc()
                        logger.warning(
                            "persist of chunk %d-%d failed (%r); "
                            "retry %d/%d", istart_, iend_, exc,
                            attempt + 1, persist_retries)
                        time.sleep(persist_backoff * (2 ** attempt))
                    else:
                        obs_metrics.counter(
                            "putpu_persist_dead_letter_total").inc()
                        logger.error(
                            "persist of chunk %d-%d failed %d times "
                            "(%r): dead-letter recorded, run continues",
                            istart_, iend_, attempt + 1, exc)
                        manifest.record(istart_, iend_,
                                        fault_reasons.PERSIST_DEAD_LETTER,
                                        {"error": repr(exc)})
                        reason = fault_reasons.PERSIST_DEAD_LETTER
        store.mark_done(istart_, reason=reason)
        return reason

    def _lineage_finish(cl, istart_, iend_, payload, reason_out):
        """Stamp persist-complete on a hit's lineage and write its doc
        beside the npz pair (ISSUE 18).  A dead-lettered persist has no
        artifact to sit beside — the candidate span still ends so the
        trace never shows an unterminated bar."""
        if cl is None:
            return
        if payload is not None and reason_out is None:
            try:
                lineage.persisted(
                    cl, writer=lambda doc, a=istart_, b=iend_:
                    store.save_lineage(root, a, b, doc))
            except OSError as exc:
                # the doc is observability riding beside the candidate:
                # a full disk here must not fail a persisted hit
                logger.warning("lineage doc for chunk %d-%d failed "
                               "(%r); candidate unaffected",
                               istart_, iend_, exc)
                cl.span.end()
        else:
            cl.span.end()

    def _persist_async(payload, istart_, iend_, pspan=None, reason=None,
                       cl=None):
        t0 = time.perf_counter()
        try:
            out = _persist_and_mark(payload, istart_, iend_,
                                    reason=reason)
            _lineage_finish(cl, istart_, iend_, payload, out)
        finally:
            timer.add_async("persist", time.perf_counter() - t0)
            if pspan is not None:
                # async completion: submitted on the main thread inside
                # the chunk, finished here on the worker — the trace
                # shows the overlap the serial budget deliberately omits
                pspan.end()

    def _drain_persist(block=False):
        # serial semantics: a persist failure that survives the retry +
        # dead-letter policy (i.e. a bug, not a disk hiccup) must fail
        # the run — the overlap only defers the raise to the next drain
        while persist_futures and (block or persist_futures[0].done()):
            persist_futures.pop(0).result()

    reader_pool = ThreadPoolExecutor(max_workers=1)
    next_read = reader_pool.submit(read_at, todo[0]) if todo else None
    array_dev = None  # chunk's prefetched device buffer (if any)
    try:
        for ichunk, istart in enumerate(todo):
          if cancel_cb is not None and cancel_cb():
              # graceful drain (fleet workers, service cancel): nothing
              # further starts; completed chunks are already persisted +
              # marked, the rest stay un-marked for the next session
              logger.info("search cancelled before chunk %d: %d of %d "
                          "chunks left for a resumed session", istart,
                          len(todo) - ichunk, len(todo))
              break
          with timer.chunk(istart):
            t_chunk = time.perf_counter()
            chunk_size = min(plan.step, nsamples - istart)
            iend = istart + chunk_size
            t0 = istart * sample_time

            with with_timer("read"):
                array, gate_info = next_read.result()
            next_read = (reader_pool.submit(read_at, todo[ichunk + 1])
                         if ichunk + 1 < len(todo) else None)

            # -- failure containment: quarantine, never poison/crash --
            # an unreadable, truncated or unrecoverably corrupt chunk is
            # recorded (manifest + done-with-reason in the ledger, so
            # resume never retries it) and the stream moves on
            quarantine_reason = q_stats = None
            if isinstance(array, _ReadFailure):
                quarantine_reason = fault_reasons.READ_ERROR
                q_stats = {"error": repr(array.exc)}
            else:
                got = array.shape[0] if packed_bits else array.shape[1]
                if got < chunk_size:
                    quarantine_reason = fault_reasons.SHORT_READ
                    q_stats = {"expected": int(chunk_size),
                               "got": int(got)}
                elif gate_info is not None:
                    if gate_info["verdict"] == "quarantine":
                        quarantine_reason = \
                            fault_reasons.INTEGRITY_PREFIX + ",".join(
                                gate_info["reasons"])
                        q_stats = gate_info["stats"]
                    elif gate_info["verdict"] == "sanitized":
                        obs_metrics.counter(
                            "putpu_chunks_sanitized_total").inc()
                        logger.warning(
                            "chunk %d-%d sanitized (non-finite values "
                            "imputed): %s", istart, iend,
                            gate_info["stats"])
            if quarantine_reason is not None:
                obs_metrics.counter(
                    "putpu_chunks_quarantined_total").inc()
                logger.error("chunk %d-%d QUARANTINED (%s): %s -> %s",
                             istart, iend, quarantine_reason, q_stats,
                             manifest.path)
                manifest.record(istart, iend, quarantine_reason, q_stats)
                if persist_pool is not None:
                    persist_futures.append(persist_pool.submit(
                        _persist_async, None, istart, iend,
                        reason=quarantine_reason))
                else:
                    with with_timer("persist"):
                        _persist_and_mark(None, istart, iend,
                                          reason=quarantine_reason)
                array_dev = None  # drop any prefetched device copy
                nproc += 1
                if canary is not None:
                    # the chunk never reaches the search: its pending
                    # injection must not count as a recall miss
                    canary.discard(istart)
                _health_update(istart,
                               wall_s=time.perf_counter() - t_chunk,
                               quarantined=True)
                continue

            src = None
            if device_clean is not None:
                if packed_bits:
                    # the acceptance metric of the packed path: chunks
                    # served from raw bytes, and the link bytes the
                    # float32 upload would have cost on top
                    obs_metrics.counter(
                        "putpu_lowbit_packed_chunks_total").inc()
                    obs_metrics.counter(
                        "putpu_lowbit_bytes_saved_total").inc(
                        int(header["nchans"] * array.shape[0] * 4
                            - array.nbytes))
                with with_timer("upload_wait"):
                    try:
                        import jax as _jax

                        if array_dev is None:
                            src = _jax.device_put(array)
                            obs_metrics.counter(
                                "putpu_bytes_uploaded_total").inc(
                                int(getattr(array, "nbytes", 0)))
                        else:
                            src = array_dev
                        # force the async host->device transfer HERE so
                        # link time has its own bucket: un-forced, the
                        # wait surfaces inside whatever device op blocks
                        # next (the round-5 rehearsal's "search" stage
                        # silently absorbed the next chunk's upload)
                        np.asarray(src[:1, :1])
                        timer.count("readbacks")
                    except Exception as exc:
                        logger.warning("device upload failed (%r); "
                                       "cleaning on host from here on",
                                       exc)
                        device_clean = None
            with with_timer("clean"):
                if device_clean is not None:
                    try:
                        roof = roofline.begin()
                        cleaned = device_clean(src, mask_dev)
                        timer.count("dispatches")
                        # force: dispatch is async, so a device failure
                        # would otherwise surface as a poisoned array
                        # later, past both fallbacks (block_until_ready
                        # is unreliable on tunnelled platforms — read
                        # one element instead).  ``array`` still holds
                        # the raw host chunk until the force succeeds, so
                        # the host fallback below never touches a
                        # poisoned device array.
                        np.asarray(cleaned[0, :1])
                        timer.count("readbacks")
                        roofline.end(roof, "device_clean", device_clean,
                                     (src, mask_dev))
                        array = cleaned
                    except Exception as exc:
                        logger.warning("device clean failed (%r); cleaning "
                                       "on host from here on", exc)
                        device_clean = None
                if device_clean is None:
                    host_raw = np.asarray(array)
                    if packed_bits and host_raw.dtype == np.uint8:
                        # fallback decode of a packed chunk (C++/numpy
                        # host unpacker; same result as the device jit)
                        host_raw = reader.unpack_frames(
                            host_raw, band_ascending=True)
                    array = _clean(host_raw, mask)

            info = PulseInfo(
                allprofs=array, start_freq=start_freq, bandwidth=bandwidth,
                nbin=array.shape[1], nchan=array.shape[0], date=date, t0=t0,
                istart=istart,
                pulse_freq=1.0 / (array.shape[1] * eff_tsamp),
                # beam provenance from the sigproc header (ISSUE 8):
                # None on single-beam files, so their persisted bytes
                # are unchanged — beam-labelled files carry it into the
                # candidate record for the cross-beam coincidence sift
                ibeam=reader.ibeam, nbeams=reader.nbeams)

            # overlap: start chunk k+1's async upload before chunk k's
            # blocking search (see prefetch_upload)
            array_dev = prefetch_upload(next_read)

            if lineage is not None:
                lineage.mark(istart, "dispatch")
            try:
                with with_timer("search"):
                    result = _search_with_fallback(
                        array, dmmin, dmmax, start_freq, bandwidth,
                        eff_tsamp, backend=backend, kernel=kernel,
                        capture_plane=capture, state=fallback_state,
                        mesh=mesh, snr_floor=search_snr_floor,
                        chunk=istart, policy=dispatch_policy)
            except _resilience_ladder.OOMFloorError as exc:
                # the degradation ladder's floor itself OOMed: this
                # chunk cannot be searched on this host at ANY geometry
                # — quarantine it (manifest + done-with-reason, exact
                # resume) and keep the survey alive (ISSUE 12)
                obs_metrics.counter("putpu_oom_floor_total").inc()
                obs_metrics.counter(
                    "putpu_chunks_quarantined_total").inc()
                logger.error("chunk %d-%d QUARANTINED (oom_floor): %r "
                             "-> %s", istart, iend, exc, manifest.path)
                manifest.record(istart, iend, fault_reasons.OOM_FLOOR,
                                {"error": repr(exc)})
                if persist_pool is not None:
                    persist_futures.append(persist_pool.submit(
                        _persist_async, None, istart, iend,
                        reason=fault_reasons.OOM_FLOOR))
                else:
                    with with_timer("persist"):
                        _persist_and_mark(None, istart, iend,
                                          reason=fault_reasons.OOM_FLOOR)
                nproc += 1
                if canary is not None:
                    canary.discard(istart)
                if lineage is not None:
                    lineage.discard(istart)
                _health_update(istart,
                               wall_s=time.perf_counter() - t_chunk,
                               quarantined=True, oom_floor=True)
                continue
            table, plane = result if capture else (result, None)
            if lineage is not None:
                # device ready/readback: the search result is host-
                # visible from here on
                lineage.mark(istart, "ready")
            if plane_consumer is not None and plane is not None:
                # the periodicity accumulation seam: the consumer sees
                # the plane (device array or ShardedPlane handle)
                # before the sift/persist machinery drops it, and
                # before mark_done — so the consumer's own durable
                # state can never be AHEAD of the ledger in the
                # direction that loses data
                with with_timer("plane_consume"):
                    plane_consumer(istart, plane, table)
            if reader.ibeam is not None:
                # chunk metadata rides the in-process table (meta is not
                # persisted; the PulseInfo fields are the durable copy)
                table.meta["ibeam"] = reader.ibeam
                table.meta["nbeams"] = reader.nbeams

            canary_obs = (canary.observe(istart, table, snr_threshold)
                          if canary is not None else None)
            ncand_above = None
            if health is not None:
                # candidate RATE (table rows above threshold), not the
                # 0/1 hit decision: the engine's RFI-storm detector
                # needs the many-DM-trials-at-once signature
                ncand_above = int(np.count_nonzero(
                    np.asarray(table["snr"], dtype=np.float64)
                    > float(snr_threshold)))
                if canary_obs is not None:
                    # rows the injection lit must not feed the storm
                    # detector: an injected chunk's canary sidelobes
                    # would inflate the candidate-rate baseline
                    ncand_above = max(
                        ncand_above - canary_obs["n_above_near"], 0)

            best = table.best_row()
            is_hit = bool(best["snr"] > snr_threshold)
            # sci_table is what downstream consumers see (persist, sift,
            # cutout window, plots); best_plane_idx indexes the DM-trial
            # plane for the dedispersed profile.  Both shift only when a
            # canary tops the chunk and a genuine weaker pulse is
            # promoted in its place.
            sci_table = table
            best_plane_idx = None
            if is_hit and canary_obs is not None \
                    and canary_obs["best_is_canary"]:
                # the chunk's best row IS this chunk's injected canary
                # (DM *and* dedispersed-time matched): tag it — canaries
                # must never become candidates, ledger payloads, or sift
                # input.  A genuine weaker pulse in the same chunk must
                # persist exactly as the canary-off run would: promote
                # the strongest row OUTSIDE the canary track, with the
                # track's rows masked out of the persisted table so
                # sift/cutout/plots see the real detection as best
                canary.tag_hit(istart)
                sci_idx = canary_obs["science_idx"]
                sci_snr = canary_obs["science_snr"]
                if sci_idx is not None and sci_snr > float(snr_threshold):
                    keep = ~canary_obs["canary_rows"]
                    sci_table = ResultTable(
                        {name: table[name][keep]
                         for name in table.colnames}, meta=table.meta)
                    best = {name: table[name][sci_idx]
                            for name in table.colnames}
                    best_plane_idx = int(sci_idx)
                    obs_metrics.counter(
                        "putpu_canary_promoted_hits_total").inc()
                    logger.info(
                        "chunk %d-%d: canary outranked a genuine pulse "
                        "— promoted the science best row (DM=%.2f "
                        "snr=%.2f), canary rows dropped from the "
                        "persisted table", istart, iend,
                        float(best["DM"]), float(best["snr"]))
                else:
                    is_hit = False
            elif is_hit and canary_obs is not None \
                    and canary_obs["recovered"]:
                # a REAL pulse outranked this chunk's canary: the hit
                # is genuine and persists, but the per-trial table
                # saved with it still contains the canary-lit rows —
                # counted and logged so consumers of the full table
                # know synthetic rows ride along (the candidate's own
                # best row is real; see docs/observability.md)
                obs_metrics.counter(
                    "putpu_canary_contaminated_tables_total").inc()
                logger.info(
                    "chunk %d-%d: real hit persisted alongside a "
                    "recovered canary — trial rows near DM %.1f in "
                    "the persisted table include synthetic signal",
                    istart, iend, canary.dm)
            if getattr(table, "meta", {}).get("certified"):
                # hybrid noise certificate: the chunk holds no detection
                # above snr_threshold (up to the certificate's stated
                # miss risk, table.meta["cert_miss_p_at_floor"] — so
                # is_hit is False by construction) and no exact
                # rescoring was paid
                if ncertified == 0:
                    # state the operating assumption once, where
                    # certification is consumed: the certificate is
                    # probabilistic, and the at-floor miss risk is a
                    # tunable (cert_slack / cert_slack_for_miss_p), not
                    # fine print (ADVICE r4)
                    logger.info(
                        "noise certificate active: certified chunks skip "
                        "exact rescoring; worst-case at-floor miss "
                        "probability %.3g (tune via cert_slack, see "
                        "ops.certify.cert_slack_for_miss_p)",
                        table.meta.get("cert_miss_p_at_floor", float("nan")))
                ncertified += 1
                obs_metrics.counter("putpu_certified_chunks_total").inc()

            if period_search and plane is not None \
                    and canary_obs is not None:
                # the folded plane carries the injected canary's track:
                # a synthetic single pulse must neither resurrect a
                # tagged canary as a periodicity "hit" (is_hit was set
                # False above; best still points at the canary row) nor
                # decorate a real one with its DM — injected chunks
                # skip the period stage (the injection rate bounds the
                # loss; canary-off runs are untouched)
                obs_metrics.counter(
                    "putpu_canary_period_skips_total").inc()
                logger.debug("chunk %d-%d: period search skipped on a "
                             "canary-injected chunk", istart, iend)
            elif period_search and plane is not None:
                from ..ops.periodicity import period_search_plane

                # key off the EFFECTIVE backend: a device failure flips
                # _search_with_fallback to numpy permanently, and the
                # period stage must follow it off the dead device
                if fallback_state.get("backend", backend) == "jax":
                    import jax.numpy as _xp
                else:
                    _xp = np
                with with_timer("period"):
                    pres = period_search_plane(
                        plane, eff_tsamp,
                        fmin=4.0 / (plane.shape[1] * eff_tsamp),
                        refine_top=1, xp=_xp)
                if pres["best_sigma"] > period_sigma_threshold:
                    info.period_freq = float(pres["best_freq"])
                    info.period_dm = float(
                        table["DM"][pres["best_dm_index"]])
                    info.period_sigma = float(pres["best_sigma"])
                    info.period_H = float(pres["best_h"])
                    info.period_M = int(pres["best_m"])
                    if pres["best_profile"] is not None:
                        info.fold_profile = np.asarray(pres["best_profile"])
                    is_hit = True
                    logger.info("PERIODIC chunk %d-%d: f=%.4f Hz DM=%.2f "
                                "sigma=%.1f", istart, iend,
                                info.period_freq, info.period_dm,
                                info.period_sigma)

            cl = None
            if is_hit:
                info.dm = float(best["DM"])
                info.snr = float(best["snr"])
                info.width = float(best["rebin"]) * eff_tsamp
                with with_timer("hit_products"):
                    # readback counters only for DEVICE sources: after a
                    # fallback to the numpy backend these are host
                    # arrays and counting them would inflate the
                    # trips x RTT floor the budget exists to make honest
                    n_rb = (not isinstance(array, np.ndarray)) \
                        + (plane is not None
                           and not isinstance(plane, np.ndarray))
                    info.disp_profile = np.asarray(array.mean(0))
                    if plane is not None:
                        info.dedisp_profile = np.asarray(
                            plane[best_plane_idx
                                  if best_plane_idx is not None
                                  else table.argbest()])
                    n_rb += not isinstance(info.allprofs, np.ndarray)
                    if make_plots:
                        # the diagnostic figure needs the full waterfall:
                        # convert device arrays to host now (retained in
                        # the hits list — an un-pulled hit would pin the
                        # whole chunk's HBM until the search ends)
                        info.allprofs = np.asarray(info.allprofs)
                    else:
                        # round 6: slice the pulse window DEVICE-side and
                        # read back only the cutout.  The full cleaned
                        # chunk is ~GBs over a slow link per hit — the
                        # round-5 rehearsal's single largest unattributed
                        # wall cost; the persisted record was this
                        # trimmed cutout all along
                        info = store.trim_waterfall(info, sci_table)
                        info.allprofs = np.asarray(info.allprofs)
                    if n_rb:
                        timer.count("readbacks", int(n_rb))
                    obs_metrics.counter("putpu_bytes_readback_total").inc(
                        int(np.asarray(info.allprofs).nbytes))
                info.compute_stats()
                hits.append((istart, iend, info, sci_table))
                obs_metrics.counter("putpu_hits_total").inc()
                logger.info("HIT chunk %d-%d: DM=%.2f snr=%.2f width=%gs",
                            istart, iend, info.dm, info.snr, info.width)
                if lineage is not None:
                    # sift verdict: freeze the chunk's stage marks into
                    # this candidate's lineage doc + open its span
                    cl = lineage.candidate(
                        istart, iend, name=f"{root}_{istart}-{iend}",
                        dm=info.dm, snr=info.snr, width=info.width)
                if push is not None:
                    # fan-out at the hit-append site: canary best rows
                    # were tagged/promoted above, so the broker only
                    # ever sees genuine science candidates.  Enqueue-
                    # only — a wedged subscriber cannot touch the loop.
                    push.publish(
                        {"schema_version": 1, "kind": "candidate",
                         "fname": os.path.basename(str(fname)),
                         "root": root, "chunk": int(istart),
                         "iend": int(iend), "t_start_s": float(t0),
                         "dm": info.dm, "snr": info.snr,
                         "width_s": info.width,
                         "fingerprint": fingerprint},
                        on_delivered=(
                            None if cl is None else
                            lambda sub, _lat, _cl=cl:
                            lineage.delivered(_cl, sub)))

            if make_plots == "all" or (make_plots == "hits" and is_hit):
                from .diagnostics import plot_diagnostics

                # the figure gets the FULL table: its plane panel is
                # labeled by the table's DM trials row-for-row, so the
                # canary-masked sci_table cannot back it (a promoted
                # chunk's figure therefore renders the canary track —
                # diagnostics, not a candidate artifact)
                with with_timer("plot"):
                    plot_diagnostics(
                        info, table, plane,
                        outname=os.path.join(output_dir,
                                             f"{root}_{istart}-{iend}.jpg"),
                        t0=t0, show=show_plots)

            # candidate persist + ledger write: overlapped with the NEXT
            # chunk's device work (FIFO worker), or inline when
            # overlap_persist=False — identical order and bytes either
            # way.  Submitted AFTER the plot so mark_done cannot precede
            # the chunk's diagnostic figure: a crash mid-plot leaves the
            # chunk un-marked and the resumed run re-renders it, exactly
            # like the serial loop (code-review r6)
            payload = (info, sci_table) if is_hit else None
            if persist_pool is not None:
                # putpu-lint: disable=span-leak — ends in _persist_async on the FIFO persist worker (cross-thread by design; the drain barrier guarantees completion)
                pspan = begin_span("persist", track="persist-worker",
                                   chunk=istart)
                persist_futures.append(persist_pool.submit(
                    _persist_async, payload, istart, iend, pspan,
                    cl=cl))
                # backpressure: each queued payload retains its cutout +
                # table on the host, so an unbounded backlog on a
                # hit-dense stream would grow without limit (the serial
                # loop had natural backpressure); two in flight keeps
                # the overlap win while bounding retained memory
                while len(persist_futures) > 2:
                    with with_timer("persist_backpressure"):
                        persist_futures.pop(0).result()
            else:
                with with_timer("persist"):
                    reason_out = _persist_and_mark(payload, istart, iend)
                    _lineage_finish(cl, istart, iend, payload,
                                    reason_out)
            # second prefetch window: by the end of the iteration the
            # reader has had the whole search/persist to finish decoding
            # chunk k+1, so this attempt usually fires even when the
            # pre-search one found the read still in flight
            if array_dev is None:
                array_dev = prefetch_upload(next_read)
            mem_snap = None
            if fallback_state.get("backend", backend) == "jax":
                # per-chunk device-memory watermark: HBM headroom is a
                # tracked gauge, not an OOM surprise (obs.memory)
                mem_snap = obs_memory.record_watermark()
            nproc += 1
            headroom_frac = None
            if mem_snap and mem_snap.get("bytes_limit"):
                headroom_frac = ((mem_snap["bytes_limit"]
                                  - mem_snap["bytes_in_use"])
                                 / mem_snap["bytes_limit"])
            _health_update(istart, wall_s=time.perf_counter() - t_chunk,
                           candidates=ncand_above,
                           headroom_frac=headroom_frac)
            if lineage is not None:
                # any candidate froze its marks at the sift verdict;
                # dropping them here bounds the recorder's memory
                lineage.discard(istart)
            if progress and nproc % 50 == 0:
                logger.info("processed %d chunks (through sample %d/%d)",
                            nproc, iend, nsamples)
          _drain_persist()
    except BaseException:
        reader_pool.shutdown(wait=False, cancel_futures=True)
        if persist_pool is not None:
            persist_pool.shutdown(wait=False, cancel_futures=True)
        if obs_server is not None:
            obs_server.close()
        raise
    reader_pool.shutdown(wait=True)
    if persist_pool is not None:
        # the tail of the persist queue is the only persist time left on
        # the critical path — everything else overlapped chunk k+1
        with timer.bucket("persist_drain"):
            persist_pool.shutdown(wait=True)
            _drain_persist(block=True)
    if push is not None and push_owned:
        # bounded drain: a wedged subscriber journals to the dead
        # letter and cannot stall the driver's exit.  PUSH_JSON is the
        # one-line machine-readable delivery ledger, BUDGET_JSON-style.
        logger.info("PUSH_JSON %s", json.dumps(push.close()))
    if health is not None and nproc:
        # tail flush: a persist dead-letter from the final drain (the
        # last chunk's write overlaps nothing) would otherwise never
        # reach the engine — one post-drain update folds it in
        _health_update("drain", wall_s=None)
    timer.report()
    timer.footer()
    logger.info("BUDGET_JSON %s", json.dumps(timer.to_json()))
    if canary is not None:
        # one-line machine-readable canary ledger, BUDGET_JSON-style
        logger.info("CANARY_JSON %s", json.dumps(canary.to_json()))
    if health is not None:
        logger.info("health verdict at end of run: %s%s", health.verdict,
                    " (" + ", ".join(health.reasons()) + ")"
                    if health.reasons() else "")
    logger.info("done: %d chunks processed, %d hits, %d noise-certified",
                nproc, len(hits), ncertified)
    if resume:
        # a resumed run must report the COMPLETE result, not just this
        # session's chunks: candidates persisted by interrupted runs are
        # restored from the store so downstream sifting/reporting sees
        # every detection (round-5 survey rehearsal: the injected pulse
        # was found before the interrupt and then absent from the
        # resumed run's report)
        seen = {(h[0], h[1]) for h in hits}
        restored = 0
        for cand_root, lo, hi in store.candidates():
            # only chunks this fingerprint's ledger marks done: the
            # store directory may hold same-named candidates persisted
            # by other configurations
            if (cand_root != root or (lo, hi) in seen
                    or not store.is_done(lo)):
                continue
            try:
                info, table = store.load_candidate(root, lo, hi)
            # the actual load failure modes of a partial/corrupt npz
            # pair (missing file, truncated zip, bad member, bad json,
            # bit-rotted deflate stream) — anything else is a bug and
            # must propagate, and every skip is counted so silent
            # skips show in the metrics snapshot (ISSUE 4 satellite)
            except (OSError, ValueError, KeyError, EOFError,
                    zipfile.BadZipFile, zlib.error) as exc:
                obs_metrics.counter(
                    "putpu_resume_pairs_skipped_total").inc()
                logger.warning("could not restore candidate %s_%d-%d: %r",
                               root, lo, hi, exc)
                continue
            hits.append((lo, hi, info, table))
            restored += 1
        if restored:
            hits.sort(key=lambda h: h[0])
            logger.info("restored %d persisted candidate(s) from the "
                        "resume ledger", restored)
        # end-of-run integrity audit: ledger vs candidate files vs
        # quarantine manifest (read-only; inconsistencies are logged
        # and counted, never fatal — observability must not take down
        # a survey run)
        from ..faults.audit import audit_run

        try:
            report = audit_run(output_dir, fingerprint, root=root)
        except Exception as exc:  # never fatal — by contract
            logger.warning("integrity audit failed (%r); run result is "
                           "unaffected", exc)
        else:
            if report["issues"]:
                logger.warning("integrity audit: %d inconsistencies: %s",
                               len(report["issues"]), report["issues"])
            else:
                logger.info("integrity audit: ok %s", report["checked"])
    if report_out:
        from ..obs import report as obs_report

        try:  # never fatal — observability must not take down a run
            md_path, html_path = obs_report.write_report(
                str(report_out),
                meta={"root": root,
                      "fname": os.path.abspath(str(fname)),
                      "fingerprint": fingerprint,
                      "chunks_processed": nproc, "hits": len(hits),
                      "certified": ncertified, "backend": backend,
                      "kernel": kernel,
                      "snr_threshold": snr_threshold},
                budget=timer.to_json(max_per_chunk=0),
                roofline=roofline.table(),
                health=health.snapshot() if health is not None else None,
                canary=canary.to_json() if canary is not None else None,
                quarantine=manifest.records(),
                metrics=obs_metrics.REGISTRY.snapshot(),
                lineage=(lineage.summary()
                         if lineage is not None else None),
                push=push.stats() if push is not None else None)
        except Exception as exc:
            logger.warning("survey report failed (%r); run result is "
                           "unaffected", exc)
        else:
            logger.info("survey report -> %s + %s", md_path, html_path)
    if obs_server is not None:
        obs_server.close()
    return hits, store
