"""End-of-run integrity audit: ledger vs candidate files vs quarantine.

A survey run leaves three artifacts in its output directory — the
resume ledger (``progress_<fingerprint>.json``), the persisted
candidate pairs (``*.info.npz`` + ``*.table.npz``) and the quarantine
manifest (``quarantine_<fingerprint>.jsonl``).  :func:`audit_run`
cross-checks them and reports every inconsistency:

* a **torn pair** — an ``.info.npz`` without its ``.table.npz`` or vice
  versa (a crash mid-persist); ``repair=True`` removes the stray half
  so the resume restore path never trips over it;
* a **quarantined chunk with candidate files** — quarantine means the
  chunk was never searched, so a pair for it is contradictory;
* a **manifest/ledger mismatch** — a quarantine or dead-letter record
  whose chunk the ledger does not mark done-with-reason, or a ledger
  quarantine entry with no manifest record.

Candidate pairs present but *absent from the ledger* are reported
separately as ``orphans`` (informational, not an inconsistency): they
are the legitimate crash window between ``save_candidate`` and
``mark_done`` — resume reprocesses those chunks — and a shared output
directory may hold same-root pairs persisted by another configuration's
ledger.

``search_by_chunks`` runs this audit at the end of every resumable run;
issue counts land on ``putpu_audit_issues_total``.
"""

from __future__ import annotations

import json
import logging
import os

from ..obs import metrics as _metrics
from . import reasons
from .policy import QuarantineManifest

logger = logging.getLogger("pulsarutils_tpu")

#: dead-letter reason (the persist hardening writes it; the audit knows
#: a dead-lettered chunk legitimately has no candidate pair).
#: Re-exported from the single-source vocabulary (ISSUE 19).
DEAD_LETTER_REASON = reasons.PERSIST_DEAD_LETTER


def _candidate_pairs(directory):
    """``{(root, lo, hi): {"info": bool, "table": bool}}`` for every
    candidate stem in ``directory``."""
    pairs = {}
    for name in sorted(os.listdir(directory)):
        for suffix, part in ((".info.npz", "info"), (".table.npz", "table")):
            if not name.endswith(suffix):
                continue
            stem = name[: -len(suffix)]
            root, _, span = stem.rpartition("_")
            lo, _, hi = span.partition("-")
            try:
                key = (root, int(lo), int(hi))
            except ValueError:
                continue  # not a candidate file
            pairs.setdefault(key, {"info": False, "table": False})[part] = True
    return pairs


def audit_run(directory, fingerprint, root=None, repair=False):
    """Cross-check ledger vs candidate files vs quarantine manifest.

    Returns ``{"ok", "issues": [...], "orphans": [...], "repaired":
    [...], "checked": {...}}``; each issue is ``{"kind", "chunk"?,
    "detail"}``.  ``root`` restricts ledger-coupled checks to one file's
    candidates (a shared directory holds many roots); ``repair=True``
    deletes the stray half of torn pairs.

    The ledger is read directly (NOT through ``CandidateStore``, whose
    loader *recovers* a torn ledger by renaming it aside — an audit
    must never move the evidence it is auditing); an unreadable ledger
    is itself reported as an issue.
    """
    directory = str(directory)
    done = set()
    ledger_q = {}
    issues = []
    if fingerprint is not None:
        ledger_path = os.path.join(directory,
                                   f"progress_{fingerprint}.json")
        if os.path.exists(ledger_path):
            try:
                with open(ledger_path) as f:
                    ledger = json.load(f)
                done = set(ledger.get("done", []))
                ledger_q = {int(k): v for k, v in
                            ledger.get("quarantined", {}).items()}
            except (ValueError, OSError) as exc:
                issues.append({"kind": "ledger_unreadable",
                               "detail": f"{ledger_path}: {exc!r}"})
    manifest = QuarantineManifest(directory, fingerprint)
    records = manifest.records()
    manifest_by_chunk = {}
    for rec in records:
        manifest_by_chunk.setdefault(int(rec["chunk"]), []).append(rec)

    orphans = []
    repaired = []
    pairs = _candidate_pairs(directory)

    for (r, lo, hi), have in sorted(pairs.items()):
        # root filter FIRST: in a shared output directory another
        # configuration's run may be mid-save (info written, table not
        # yet) — flagging it would be a false inconsistency and
        # repair=True would delete its half-written file out from under
        # it (code-review r8)
        if root is not None and r != root:
            continue
        base = os.path.join(directory, f"{r}_{lo}-{hi}")
        if not (have["info"] and have["table"]):
            missing = "table" if have["info"] else "info"
            present = "info" if have["info"] else "table"
            if lo in ledger_q:
                # expected remnant of a dead-lettered/quarantined
                # persist: the failed save may have written half the
                # pair before giving up — the ledger carries the
                # reason, so this is NOT an inconsistency (code-review
                # r8); repair still removes the stray half
                orphans.append({"kind": "dead_letter_remnant",
                                "chunk": lo,
                                "detail": f"{r}_{lo}-{hi}: partial pair "
                                          f"left by {ledger_q[lo]!r}"})
            else:
                issues.append({"kind": "torn_pair", "chunk": lo,
                               "detail": f"{r}_{lo}-{hi}: .{missing}.npz "
                                         "missing"})
            if repair:
                path = f"{base}.{present}.npz"
                try:
                    os.remove(path)
                    repaired.append(path)
                except OSError:
                    pass
            continue
        if lo in ledger_q:
            issues.append({"kind": "quarantined_with_candidate",
                           "chunk": lo,
                           "detail": f"{r}_{lo}-{hi} persisted but ledger "
                                     f"quarantines it ({ledger_q[lo]})"})
        elif fingerprint is not None and lo not in done:
            orphans.append({"kind": "unmarked_candidate", "chunk": lo,
                            "detail": f"{r}_{lo}-{hi} persisted but not "
                                      "marked done (resume reprocesses it)"})

    for chunk, recs in sorted(manifest_by_chunk.items()):
        if fingerprint is not None and chunk not in done:
            issues.append({"kind": "quarantine_not_done", "chunk": chunk,
                           "detail": "manifest records the chunk but the "
                                     "ledger does not mark it done"})
        if chunk not in ledger_q:
            issues.append({"kind": "quarantine_unmarked", "chunk": chunk,
                           "detail": "manifest records the chunk but the "
                                     "ledger carries no reason for it"})
    for chunk, reason in sorted(ledger_q.items()):
        if chunk not in manifest_by_chunk:
            issues.append({"kind": "quarantine_unrecorded", "chunk": chunk,
                           "detail": f"ledger marks {reason!r} but the "
                                     "manifest has no record"})

    if issues:
        _metrics.counter("putpu_audit_issues_total").inc(len(issues))
    return {"ok": not issues, "issues": issues, "orphans": orphans,
            "repaired": repaired,
            "checked": {"pairs": len(pairs), "done": len(done),
                        "quarantined": len(ledger_q),
                        "manifest_records": len(records)}}
