"""Single source of truth for quarantine-manifest reason vocabulary.

Every record the :class:`~.policy.QuarantineManifest` appends names a
*reason*, and that reason is load-bearing three times over: the audit
(:mod:`.audit`) joins manifests against the ledger by reason, operators
grep post-mortems by reason, and the docs promise a failure-policy
matrix keyed by reason.  Before ISSUE 19 the vocabulary lived as string
literals scattered across the pipeline; this module is the one place a
reason may be *defined*, and the ``quarantine-reason`` putpu-lint
checker (:mod:`..analysis.reason_drift`) keeps three parties in sync
both ways:

* code — a string literal passed to ``manifest.record(...)`` must be a
  vocabulary member (or carry the ``integrity:`` composite prefix);
* docs — every row of the marked reason table in ``docs/robustness.md``
  must name a vocabulary member, and every vocabulary member must have
  a row;
* this module — a reason nobody records and nobody documents is flagged
  as dead vocabulary.

Stdlib-only and import-light on purpose: the lint checker AST-parses
this file without importing the package, and the ingest frontend
imports it on its socket-reader hot path.
"""

from __future__ import annotations

__all__ = [
    "READ_ERROR", "SHORT_READ", "INTEGRITY_PREFIX", "PERSIST_DEAD_LETTER",
    "OOM_FLOOR", "FEED_GAP", "SHED_OVERRUN", "QUARANTINE_REASONS",
    "is_known_reason",
]

#: the chunk could not be read from its source at all (I/O error)
READ_ERROR = "read_error"

#: the source returned fewer samples than the chunk geometry promised
SHORT_READ = "short_read"

#: composite prefix: the integrity gate condemned the chunk; the gate's
#: specific reasons (``nan_frac``, ``dead_frac``, ...) follow the colon
INTEGRITY_PREFIX = "integrity:"

#: candidate persist exhausted its retry budget; the manifest record IS
#: the durable artifact (the candidate npz is missing on purpose)
PERSIST_DEAD_LETTER = "persist_dead_letter"

#: even the degradation ladder's numpy floor ran out of memory — this
#: host cannot search chunks of this geometry
OOM_FLOOR = "oom_floor"

#: live-feed packet loss left the chunk's missing fraction above the
#: integrity policy's zero rail: zero-filled samples would dominate
FEED_GAP = "feed_gap"

#: ingest outran search and the admission-control seam dropped this
#: (oldest) assembled chunk whole — journaled, never silently lost
SHED_OVERRUN = "shed_overrun"

#: reason -> one-line meaning; THE vocabulary the lint checker enforces.
#: ``integrity:`` is a prefix entry: recorded reasons append the gate's
#: own condemnation list after the colon.
QUARANTINE_REASONS = {
    "read_error": "chunk unreadable from its source (I/O error)",
    "short_read": "source returned fewer samples than the geometry",
    "integrity:": "integrity gate condemned the chunk (composite prefix)",
    "persist_dead_letter": "candidate persist exhausted its retries",
    "oom_floor": "numpy ladder floor OOMed; geometry unsearchable here",
    "feed_gap": "live-feed packet loss above the missing-fraction rail",
    "shed_overrun": "ingest outran search; oldest chunk dropped whole",
}


def is_known_reason(reason):
    """True when ``reason`` is vocabulary — exact member, or an
    ``integrity:``-prefixed composite."""
    reason = str(reason)
    return reason in QUARANTINE_REASONS \
        or reason.startswith(INTEGRITY_PREFIX)
