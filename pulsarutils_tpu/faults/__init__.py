"""Fault injection + failure policy: the survey loop's immune system.

The reference package has no failure handling at all (SURVEY §5), yet on
real telescopes and preemptible TPU fleets wedged devices, truncated
filterbank files, RFI-saturated/NaN chunks and disk hiccups are the
steady state — real-time pipelines treat dropped/corrupt blocks as
routine input, not exceptions.  Three pillars (ISSUE 4):

* :mod:`.inject` — a seeded, composable :class:`~.inject.FaultPlan`
  that injects failures at every seam (reader I/O, data corruption,
  device dispatch, persist writes, the mesh route), armed via context
  manager or the ``PUTPU_FAULT_PLAN`` env var.  With no plan armed the
  production code path is byte-identical — every hook is a single
  module-global ``None`` check;
* :mod:`.policy` — the hardening the injection forces: deadline-wrapped
  device dispatch (:func:`~.policy.call_with_deadline`), the pre-search
  data-integrity gate (:func:`~.policy.gate_chunk`: sanitize
  recoverable chunks, quarantine unrecoverable ones into a
  ``quarantine_<fingerprint>.jsonl`` manifest), and bounded persist
  retry with dead-letter records;
* :mod:`.audit` — the end-of-run integrity audit cross-checking ledger
  entries vs candidate files vs the quarantine manifest.

``tools/chaos_drill.py`` is the proof: the full streaming survey under
a fault matrix, with recoverable runs asserted byte-identical to the
fault-free run.  Everything here is numpy+stdlib only and safe to
import before a JAX backend exists.
"""

from .inject import FaultPlan, FaultSpec, active, arm, disarm
from .policy import (DispatchPolicy, DispatchTimeoutError, IntegrityPolicy,
                     QuarantineManifest, call_with_deadline, gate_chunk,
                     resolve_integrity_policy)

__all__ = [
    "DispatchPolicy",
    "DispatchTimeoutError",
    "FaultPlan",
    "FaultSpec",
    "IntegrityPolicy",
    "QuarantineManifest",
    "active",
    "arm",
    "call_with_deadline",
    "disarm",
    "gate_chunk",
    "resolve_integrity_policy",
]
