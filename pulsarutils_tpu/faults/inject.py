"""Seeded, composable fault injection for the streaming survey loop.

A :class:`FaultPlan` is a list of :class:`FaultSpec` records, each
naming a **site** (the seam it fires at), a failure **kind**, the chunk
starts it applies to and a firing budget (``times``).  The instrumented
code calls the module-level hooks (:func:`fire`, :func:`corrupt`,
:func:`truncated_length`); with no plan armed every hook is one
module-global ``None`` check and the production path is byte-identical.

Sites and the seams they instrument:

========== ==================================================== ==========================
site       seam                                                 kinds
========== ==================================================== ==========================
``read``   ``FilterbankReader.read_block(_packed)``             ``error``, ``truncate``
``corrupt``the streaming driver's reader thread (post-decode)   ``nan``, ``inf``,
                                                                ``dead_channels``,
                                                                ``zero_run``, ``saturate``,
                                                                ``impulse`` (RFI storm)
``dispatch``the per-chunk device search dispatch                ``error``, ``hang``, ``oom``
``mesh``   the sharded multi-device route inside the dispatch   ``error``, ``hang``, ``oom``
``beams``  ``BeamBatcher.search`` (the batched beam dispatch)   ``error``, ``oom``
``host``   the numpy-fallback rung of the chunk ladder          ``oom``
``persist````CandidateStore.save_candidate``                    ``error``
``fleet``  ``FleetWorker._run_unit`` (per leased unit; ISSUE 9) ``error``, ``hang``
``period`` the periodicity trial-sweep device dispatch          ``error``, ``hang``, ``oom``
           (``periodicity/driver.py``, ISSUE 13) — any raise
           degrades the sweep to its numpy reference path, so
           the chaos class proves candidates survive it
``wire``   the fleet wire client (``protocol.post_json_retry``, ``drop``, ``delay``,
           ISSUE 15) — partition chaos per message: ``drop``    ``duplicate``
           raises a synthetic transport error (the request
           never lands), ``delay`` sleeps ``seconds`` before
           sending, ``duplicate`` sends the message twice.
           The optional ``msg`` selector restricts a spec to
           one message name (``register``/``lease``/
           ``complete``/``release``); ``None`` matches all
``ingest`` the live-feed packet path (``ingest/source.py``       ``drop``, ``reorder``,
           feeder, ISSUE 19) — feed chaos per packet, the       ``duplicate``, ``corrupt``,
           ``chunks`` selector matching the packet ``seq``:     ``disconnect``, ``burst``
           ``drop`` loses the packet, ``reorder`` swaps it
           with its successor, ``duplicate`` sends it twice,
           ``corrupt`` flips payload bytes (the CRC rejects
           it downstream -> a gap), ``disconnect`` tears the
           connection (the source must reconnect), ``burst``
           switches the feeder to unpaced firehose (overruns
           a slow search -> shedding)
========== ==================================================== ==========================

``kind="oom"`` (ISSUE 12) raises a *real* ``XlaRuntimeError``-shaped
``RESOURCE_EXHAUSTED`` (jaxlib's own exception class where importable),
so the resilience layer's classifier
(:func:`~pulsarutils_tpu.resilience.ladder.is_resource_exhausted`) and
its degradation ladder are exercised on exactly the failure production
raises; at the ``host`` site it raises ``MemoryError`` instead — the
ladder-floor (host memory) failure the ``oom_floor`` drill class
quarantines.  ``times=`` distinguishes transient (ladder recovers,
candidates byte-identical) from persistent (floor reached) pressure.

The ``fleet`` site fires *inside the worker*, before a leased unit's
``search_by_chunks`` session starts — ``kind="hang"`` wedges a worker
so the coordinator's lease TTL + health probes must steal the unit,
``kind="error"`` makes the unit fail and requeue; both drive the chaos
drill's killed/wedged-worker classes (the ``chunk`` selector matches
the unit's first leased chunk).

Arming: ``with plan.armed(): ...`` (tests, the chaos drill), or export
``PUTPU_FAULT_PLAN`` with the plan's JSON — the env form survives a
subprocess boundary, so a CLI survey run can be chaos-tested unchanged.
Every firing is counted per spec (for assertions) and mirrored into the
metrics registry as ``putpu_faults_injected_total{site=...}``.

Corruption is deterministic: the rng is seeded from ``(spec.seed,
chunk)``, so the same plan over the same file corrupts the same values.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time

import numpy as np

from ..obs import metrics as _metrics

#: the process-wide armed plan (None = injection off).  A bare module
#: global on purpose: the hooks sit on per-chunk hot paths and must cost
#: one LOAD_GLOBAL when disarmed.
_ACTIVE = None
_ENV_CHECKED = False
#: suppression depth: hooks no-op while > 0 (see :func:`suppressed`)
_SUPPRESS = 0

#: exception classes a spec may raise by name (kept to safe, relevant
#: types — the env var must not become an arbitrary-class loader)
_EXC_TYPES = {
    "OSError": OSError,
    "IOError": OSError,
    "RuntimeError": RuntimeError,
    "MemoryError": MemoryError,
}

#: default exception class per site when the spec names none
_SITE_DEFAULT_EXC = {"read": "OSError", "persist": "OSError"}

_CORRUPT_KINDS = ("nan", "inf", "dead_channels", "zero_run", "saturate",
                  "impulse")

#: partition-chaos kinds for the ``wire`` site (ISSUE 15)
_WIRE_KINDS = ("drop", "delay", "duplicate")

#: feed-chaos kinds for the ``ingest`` site (ISSUE 19); applied per
#: packet in the feeder/send path — the chunk selector matches seq
_INGEST_KINDS = ("drop", "reorder", "duplicate", "corrupt",
                 "disconnect", "burst")


def _resource_exhausted_exc(site, chunk):
    """An injected OOM shaped exactly like production's: jaxlib's own
    ``XlaRuntimeError`` carrying the XLA ``RESOURCE_EXHAUSTED`` status
    text (a local stand-in class of the same name on jax-free
    checkouts), or ``MemoryError`` at the ``host`` site — the ladder
    floor's failure mode."""
    msg = (f"RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
           f"17179869184 bytes. "
           f"(FAULTPLAN: injected {site} oom, chunk={chunk})")
    if site == "host":
        return MemoryError(msg)
    try:
        from jaxlib.xla_extension import XlaRuntimeError
    except ImportError:
        class XlaRuntimeError(RuntimeError):
            pass
    return XlaRuntimeError(msg)


@dataclasses.dataclass
class FaultSpec:
    """One injectable failure.  ``chunks=None`` matches every chunk;
    ``times=None`` never exhausts (a *persistent* fault — e.g. the dead
    mesh of the sticky-fallback test), ``times=1`` is a transient."""

    site: str
    kind: str = "error"
    chunks: tuple | None = None     # chunk istarts; None = all
    times: int | None = 1           # firing budget; None = unlimited
    frac: float = 0.01              # corruption fraction
    seconds: float = 60.0           # hang duration
    seed: int = 0                   # corruption rng seed (mixed w/ chunk)
    exc: str | None = None          # exception class name for kind=error
    amp: float = 20.0               # impulse amplitude, in block stds
    msg: str | None = None          # wire-message selector; None = all
    fired: int = dataclasses.field(default=0, init=False)

    def matches(self, site, chunk):
        if site != self.site:
            return False
        if self.chunks is not None and chunk is not None \
                and int(chunk) not in {int(c) for c in self.chunks}:
            return False
        return True

    def to_json(self):
        d = {"site": self.site, "kind": self.kind, "times": self.times,
             "frac": self.frac, "seconds": self.seconds, "seed": self.seed}
        if self.chunks is not None:
            d["chunks"] = [int(c) for c in self.chunks]
        if self.exc is not None:
            d["exc"] = self.exc
        if self.amp != 20.0:  # only when non-default: pre-existing plan
            d["amp"] = self.amp  # JSON stays byte-stable
        if self.msg is not None:
            d["msg"] = self.msg
        return d


class FaultPlan:
    """A composable set of :class:`FaultSpec` with thread-safe firing
    budgets (hooks fire from the reader thread, the persist worker and
    the main loop concurrently)."""

    def __init__(self, specs=()):
        self.specs = [s if isinstance(s, FaultSpec) else FaultSpec(**s)
                      for s in specs]
        self._lock = threading.Lock()

    # -- bookkeeping ---------------------------------------------------------

    def _claim(self, spec):
        """Atomically consume one firing from ``spec``'s budget."""
        with self._lock:
            if spec.times is not None and spec.fired >= spec.times:
                return False
            spec.fired += 1
        _metrics.counter("putpu_faults_injected_total",
                         site=spec.site).inc()
        return True

    def fired(self, site=None):
        """Total firings, optionally restricted to one site."""
        with self._lock:
            return sum(s.fired for s in self.specs
                       if site is None or s.site == site)

    # -- hooks (called via the module-level wrappers) ------------------------

    def fire(self, site, chunk=None, **ctx):
        """Raise / hang for matching ``error``/``hang``/``oom`` specs."""
        for spec in self.specs:
            if spec.kind not in ("error", "hang", "oom") \
                    or not spec.matches(site, chunk):
                continue
            if not self._claim(spec):
                continue
            if spec.kind == "hang":
                time.sleep(spec.seconds)
                continue
            if spec.kind == "oom":
                raise _resource_exhausted_exc(site, chunk)
            exc_name = spec.exc or _SITE_DEFAULT_EXC.get(site,
                                                         "RuntimeError")
            exc_cls = _EXC_TYPES.get(exc_name, RuntimeError)
            raise exc_cls(f"FAULTPLAN: injected {site} {spec.kind} "
                          f"(chunk={chunk})")

    def wire_action(self, site, msg=None):
        """First matching wire-chaos action: ``(kind, seconds)`` for
        ``drop``/``delay``/``duplicate`` specs, or ``None``.  A spec's
        ``msg`` selector restricts it to one wire message name."""
        for spec in self.specs:
            if spec.kind not in _WIRE_KINDS or spec.site != site:
                continue
            if spec.msg is not None and msg is not None \
                    and spec.msg != msg:
                continue
            if not self._claim(spec):
                continue
            return spec.kind, spec.seconds
        return None

    def ingest_action(self, site, seq=None):
        """First matching feed-chaos action for one packet:
        ``(kind, seconds, frac)`` for the ``ingest`` kinds
        (``drop``/``reorder``/``duplicate``/``corrupt``/``disconnect``/
        ``burst``), or ``None``.  The spec's ``chunks`` selector
        matches the packet ``seq`` — feed chaos is addressed per
        packet, not per chunk."""
        for spec in self.specs:
            if spec.kind not in _INGEST_KINDS or spec.site != site:
                continue
            if not spec.matches(site, seq):
                continue
            if not self._claim(spec):
                continue
            return spec.kind, spec.seconds, spec.frac
        return None

    def truncated_length(self, site, chunk, n):
        """Shortened read length for matching ``truncate`` specs."""
        for spec in self.specs:
            if spec.kind == "truncate" and spec.matches(site, chunk) \
                    and self._claim(spec):
                n = max(int(n * (1.0 - spec.frac)), 1)
        return n

    def corrupt(self, site, block, chunk=None):
        """Apply matching corruption kinds to a copy of ``block``."""
        out = None
        for spec in self.specs:
            if spec.kind not in _CORRUPT_KINDS \
                    or not spec.matches(site, chunk):
                continue
            if not self._claim(spec):
                continue
            if out is None:
                # preserve the block's floating dtype: a float64 copy of
                # a float32 survey chunk would retrace the jitted clean/
                # search for a signature production never runs (ints
                # promote to float32 so nan/inf kinds are expressible)
                src = np.asarray(block)
                dtype = (src.dtype if np.issubdtype(src.dtype, np.floating)
                         else np.float32)
                out = np.array(src, dtype=dtype, copy=True)
            rng = np.random.default_rng(
                (int(spec.seed), 0 if chunk is None else int(chunk)))
            nchan, nsamp = out.shape
            if spec.kind in ("nan", "inf"):
                k = max(int(out.size * spec.frac), 1)
                idx = rng.choice(out.size, size=k, replace=False)
                val = np.nan if spec.kind == "nan" else np.inf
                # .flat, not .ravel(): a transposed (F-ordered) block's
                # ravel() is a copy and the assignment would be lost
                out.flat[idx] = val
            elif spec.kind == "dead_channels":
                k = max(int(nchan * spec.frac), 1)
                out[rng.choice(nchan, size=k, replace=False)] = 0.0
            elif spec.kind == "impulse":
                # broadband RFI storm: bright un-dispersed impulses in
                # every channel at a few time bins — the classic
                # candidate-rate-spike signature the health engine's
                # storm detector exists for (ISSUE 5): many DM trials
                # light up at once while no real pulse exists
                k = max(int(nsamp * spec.frac), 1)
                ts = rng.choice(nsamp, size=k, replace=False)
                scale = float(np.nanstd(
                    np.where(np.isinf(out), np.nan, out)))
                if not np.isfinite(scale) or scale == 0.0:
                    scale = 1.0
                out[:, ts] += spec.amp * scale
            elif spec.kind == "zero_run":
                # dropped packets: a contiguous run of zeroed frames
                k = max(int(nsamp * spec.frac), 1)
                lo = int(rng.integers(0, max(nsamp - k, 1)))
                out[:, lo:lo + k] = 0.0
            elif spec.kind == "saturate":
                # clipped digitiser: everything above the (1-frac)
                # quantile collapses onto one rail value.  nan-aware:
                # composed after a nan/inf spec on the same chunk, the
                # plain quantile/max would be NaN and saturation a
                # silent no-op (code-review r8)
                v = np.nanquantile(np.where(np.isinf(out), np.nan, out),
                                   1.0 - spec.frac)
                if np.isfinite(v):
                    out[out >= v] = float(v)
        return block if out is None else out

    # -- arming --------------------------------------------------------------

    @contextlib.contextmanager
    def armed(self):
        """Arm this plan process-wide for the block (restores any
        previously armed plan on exit)."""
        global _ACTIVE
        prev = _ACTIVE
        _ACTIVE = self
        try:
            yield self
        finally:
            _ACTIVE = prev

    # -- (de)serialisation ---------------------------------------------------

    def to_json(self):
        return json.dumps({"specs": [s.to_json() for s in self.specs]})

    @classmethod
    def from_json(cls, blob):
        data = json.loads(blob) if isinstance(blob, str) else blob
        specs = data["specs"] if isinstance(data, dict) else data
        out = []
        for d in specs:
            d = dict(d)
            if d.get("chunks") is not None:
                d["chunks"] = tuple(d["chunks"])
            out.append(FaultSpec(**d))
        return cls(out)


@contextlib.contextmanager
def suppressed():
    """Temporarily disable every hook inside the block.

    For code that shares an instrumented seam but has its own
    resilience story and is NOT the chunk loop under test — e.g. the
    bad-channel pre-scan streams the whole file through the same
    ``read_block`` seam before the hardened chunk loop exists, so an
    env-armed read fault would crash the run at startup (and silently
    consume a ``times=1`` budget the targeted search chunk never sees).
    """
    global _SUPPRESS
    _SUPPRESS += 1
    try:
        yield
    finally:
        _SUPPRESS -= 1


def arm(plan):
    """Arm ``plan`` process-wide (prefer ``plan.armed()`` in tests)."""
    global _ACTIVE
    _ACTIVE = plan
    return plan


def disarm():
    global _ACTIVE
    _ACTIVE = None


def active():
    """The armed plan, or ``None``.  The first call honours the
    ``PUTPU_FAULT_PLAN`` env var (the plan's JSON) so a subprocess CLI
    run can be chaos-tested without code changes.  The env var is read
    ONCE and the result latched (the hooks sit on hot paths): set it
    before the process starts; to arm a plan mid-process use
    :func:`arm` / ``plan.armed()``."""
    global _ACTIVE, _ENV_CHECKED
    if _ACTIVE is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        blob = os.environ.get("PUTPU_FAULT_PLAN")
        if blob:
            _ACTIVE = FaultPlan.from_json(blob)
    return _ACTIVE


# -- hot-path hooks (one None check when disarmed) ---------------------------

def fire(site, chunk=None, **ctx):
    plan = _ACTIVE if _ACTIVE is not None or _ENV_CHECKED else active()
    if plan is not None and not _SUPPRESS:
        plan.fire(site, chunk=chunk, **ctx)


def corrupt(site, block, chunk=None):
    plan = _ACTIVE if _ACTIVE is not None or _ENV_CHECKED else active()
    if plan is None or _SUPPRESS:
        return block
    return plan.corrupt(site, block, chunk=chunk)


def truncated_length(site, chunk, n):
    plan = _ACTIVE if _ACTIVE is not None or _ENV_CHECKED else active()
    if plan is None or _SUPPRESS:
        return n
    return plan.truncated_length(site, chunk, n)


def wire_action(site, msg=None):
    plan = _ACTIVE if _ACTIVE is not None or _ENV_CHECKED else active()
    if plan is None or _SUPPRESS:
        return None
    return plan.wire_action(site, msg=msg)


def ingest_action(site, seq=None):
    plan = _ACTIVE if _ACTIVE is not None or _ENV_CHECKED else active()
    if plan is None or _SUPPRESS:
        return None
    return plan.ingest_action(site, seq=seq)
