"""Failure policy: dispatch deadlines, the data-integrity gate, the
quarantine manifest.

This module holds the *decisions* the hardened survey loop makes when
:mod:`pulsarutils_tpu.faults.inject` (or reality) misbehaves:

* :class:`DispatchPolicy` + :func:`call_with_deadline` — a wedged device
  dispatch was an infinite stall; now it runs on a watchdog thread with
  a configurable deadline, bounded retry and exponential backoff before
  the existing numpy fallback;
* :func:`gate_chunk` + :class:`IntegrityPolicy` — the pre-search
  data-integrity gate: NaN/Inf fraction, dead-channel fraction,
  saturation and zero-run fractions against configurable thresholds.
  Recoverable chunks are **sanitized** (non-finite values imputed with
  the per-channel median, counted); unrecoverable ones are
  **quarantined** instead of poisoning the S/N statistics or crashing.
  Low-bit (1/2/4-bit) data gets the CODE-domain gate instead
  (:func:`gate_chunk_packed` / :func:`gate_chunk_lowbit`, ISSUE 11):
  rail/zero/dead-channel fractions computed from the raw packed bytes
  with thresholds rescaled onto the quantization floor — strict/
  sanitize policies now work on low-bit files instead of silently
  passing;
* :class:`QuarantineManifest` — the ``quarantine_<fingerprint>.jsonl``
  record of every quarantined chunk and persist dead-letter (chunk
  span, reason, stats), the artifact the end-of-run audit
  (:mod:`.audit`) cross-checks against the resume ledger.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import warnings

import numpy as np

from ..obs import metrics as _metrics


class DispatchTimeoutError(RuntimeError):
    """A device dispatch exceeded its deadline.  Deliberately a
    ``RuntimeError`` (not ``TimeoutError``/``OSError``): the fallback
    ladder in ``_search_with_fallback`` treats it like any other
    device-side failure — retry, then numpy."""


@dataclasses.dataclass(frozen=True)
class DispatchPolicy:
    """Deadline + retry policy for one chunk's device dispatch.

    The defaults reproduce the pre-hardening behaviour exactly (one
    same-backend retry, no backoff, no deadline — dispatch runs inline
    on the calling thread).  ``timeout_s`` arms the watchdog: the
    dispatch runs on a daemon thread and a hang is bounded by
    ``timeout_s`` per attempt instead of stalling the stream forever.
    Caveats (``docs/robustness.md``): the watchdog dispatches from a
    non-main thread, which some tunnelled device clients cannot
    tolerate — test before enabling there; an abandoned hung attempt
    keeps running in the background (its late budget/trace writes may
    land in a later chunk's buckets, and a retry briefly overlaps it
    on the device).
    """

    timeout_s: float | None = None
    retries: int = 1          # same-backend re-attempts before fallback
    backoff_s: float = 0.0    # base for exponential backoff between them


def call_with_deadline(fn, timeout_s=None):
    """Run ``fn()`` bounded by ``timeout_s`` seconds.

    ``timeout_s=None``/``0`` calls inline (zero overhead, identical
    thread — the production default).  Otherwise ``fn`` runs on a fresh
    daemon thread carrying a copy of the caller's context (so budget /
    trace attribution keeps working) and :class:`DispatchTimeoutError`
    is raised when the deadline passes; the abandoned thread is left to
    finish and its result is discarded.
    """
    if not timeout_s:
        return fn()
    import contextvars

    box = {}
    ctx = contextvars.copy_context()

    def target():
        try:
            box["value"] = ctx.run(fn)
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            box["exc"] = exc

    t = threading.Thread(target=target, daemon=True,
                         name="putpu-dispatch-watchdog")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise DispatchTimeoutError(
            f"device dispatch exceeded the {timeout_s}s deadline "
            "(wedged device? the attempt was abandoned).  NOTE: XLA "
            "compile time counts against the deadline — if this fired "
            "on a first chunk, size the timeout above the cold compile "
            "or warm up first, or every retry times out too and the "
            "run stickily degrades to the numpy path")
    if "exc" in box:
        raise box["exc"]
    return box["value"]


# ---------------------------------------------------------------------------
# Data-integrity gate
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class IntegrityPolicy:
    """Thresholds for the pre-search chunk gate.  A chunk breaching any
    ``max_*`` fraction is quarantined; a chunk with a *sub-threshold*
    non-finite fraction is sanitized when ``sanitize`` is set (the
    ``"sanitize"`` policy) or quarantined when not (``"strict"``)."""

    max_nan_frac: float = 0.25
    max_dead_frac: float = 0.5
    max_sat_frac: float = 0.5
    max_zero_frac: float = 0.75
    sanitize: bool = True


def resolve_integrity_policy(policy):
    """``"sanitize"`` / ``"strict"`` / ``"off"`` / an
    :class:`IntegrityPolicy` / ``None`` -> policy instance or ``None``."""
    if policy is None or policy == "off" or policy is False:
        return None
    if isinstance(policy, IntegrityPolicy):
        return policy
    if policy == "sanitize":
        return IntegrityPolicy()
    if policy == "strict":
        return IntegrityPolicy(sanitize=False)
    raise ValueError(f"quarantine policy {policy!r}: expected 'sanitize', "
                     "'strict', 'off' or an IntegrityPolicy")


def chunk_stats(block, finite=None):
    """Integrity statistics of a ``(nchan, nsamp)`` float block.

    ``finite`` accepts a precomputed ``np.isfinite(block)`` mask so a
    caller that needs the mask afterwards (the sanitize path) pays the
    pass and the full-size boolean temporary once.

    A few host passes: non-finite fraction, dead-channel fraction
    (zero variance over the finite values — a flat channel carries no
    signal and divides to garbage downstream), exact-zero fraction
    (dropped-packet runs) and saturation fraction (values pinned at the
    block maximum — a clipped digitiser rail repeats its max, noise
    does not).  Fractions are returned at FULL precision — verdicts
    must never hinge on display rounding (two NaNs in a 2^26-sample
    chunk round to 0.0 at six decimals but still poison every DM trial
    they touch).  Variance is two-pass with float64 accumulation: the
    one-pass ``E[x²] − mean²`` form cancels catastrophically on float32
    blocks with a large DC offset (ordinary uncalibrated power levels)
    and falsely classified healthy channels dead.
    """
    block = np.asarray(block)
    if finite is None:
        finite = np.isfinite(block)
    n = block.size
    nfinite = int(finite.sum())
    nan_frac = (n - nfinite) / n
    safe = np.where(finite, block, 0.0)
    cnt = finite.sum(axis=1)
    denom = np.maximum(cnt, 1)
    mean = safe.sum(axis=1, dtype=np.float64) / denom
    # deviations stay in the block's dtype — centered values cannot
    # cancel catastrophically, and a survey-scale float32 chunk must
    # not materialize full-size float64 temporaries on the reader
    # thread (code-review r8); only the ACCUMULATIONS are float64
    # (einsum: no full-size product temporary either)
    mean_s = mean.astype(safe.dtype, copy=False)
    dev = np.where(finite, safe - mean_s[:, None], 0.0)
    var = np.einsum("ct,ct->c", dev, dev, dtype=np.float64) / denom
    dead_frac = float(((var <= 0) | (cnt == 0)).mean())
    zero_frac = float(((block == 0) & finite).sum() / n)
    if nfinite:
        vmax = float(safe.max())
        sat_frac = float(((block == vmax) & finite).sum() / n)
    else:
        sat_frac = 0.0
    return {"nan_frac": float(nan_frac), "dead_frac": dead_frac,
            "zero_frac": zero_frac, "sat_frac": sat_frac}


def gate_chunk(block, policy):
    """Gate one chunk.  Returns ``(block, info)`` with ``info`` =
    ``{"verdict": "clean"|"sanitized"|"quarantine", "stats": {...},
    "reasons": [...]}``.

    A clean chunk is returned **as the same object** — the gate must
    never perturb the byte-identical production path.  Sanitization
    imputes non-finite values with the per-channel median of the finite
    values (0 for a fully dead channel) — deliberately signal-free, so
    a sanitized noise chunk stays below any sane detection floor.

    Verdicts are decided on the RAW fractions; the six-decimal rounding
    in the returned ``stats`` is display-only (a handful of NaNs in a
    survey-scale chunk rounds to 0.0 but must still be sanitized).
    """
    block_arr = np.asarray(block)
    finite = np.isfinite(block_arr)
    raw = chunk_stats(block_arr, finite=finite)
    stats = {k: round(v, 6) for k, v in raw.items()}
    reasons = [name for name, frac, lim in (
        ("nan_frac", raw["nan_frac"], policy.max_nan_frac),
        ("dead_frac", raw["dead_frac"], policy.max_dead_frac),
        ("zero_frac", raw["zero_frac"], policy.max_zero_frac),
        ("sat_frac", raw["sat_frac"], policy.max_sat_frac),
    ) if frac > lim]
    if reasons:
        return block, {"verdict": "quarantine", "stats": stats,
                       "reasons": reasons}
    if raw["nan_frac"] == 0.0:
        return block, {"verdict": "clean", "stats": stats, "reasons": []}
    if not policy.sanitize:
        return block, {"verdict": "quarantine", "stats": stats,
                       "reasons": ["nan_frac(strict)"]}
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # all-NaN channel median
        med = np.nanmedian(np.where(finite, block_arr, np.nan), axis=1)
    med = np.where(np.isfinite(med), med, 0.0)
    out = np.where(finite, block_arr, med[:, None])
    return out, {"verdict": "sanitized", "stats": stats, "reasons": []}


def lowbit_code_stats(codes, nbits):
    """Integrity statistics of a low-bit CODE block (ISSUE 11).

    ``codes`` is ``(nchan, n)`` quantization codes (integer values
    ``0..2^nbits - 1``, any numeric dtype — the decoded floats a host
    unpack yields are exact codes too).  The float-domain
    :func:`chunk_stats` is meaningless here — low-bit data cannot hold
    NaN/Inf, and its zero/saturation fractions sit at the quantization
    levels *by construction* (a healthy 1-bit chunk is ~50% at each
    rail), which is why the gate used to skip quantized data entirely
    (PR 4) and silently passed genuinely broken low-bit chunks.  These
    are the code-domain equivalents:

    * ``zero_frac`` — codes at the bottom rail (dropped packets, a dead
      digitiser leg);
    * ``rail_frac`` — codes pinned at the TOP rail (clipped digitiser,
      persistent broadband RFI saturating the quantizer);
    * ``dead_frac`` — channels whose codes never change over the
      sample (a flat channel carries no signal and biases the
      renormalisation).
    """
    codes = np.asarray(codes)
    mask = (1 << int(nbits)) - 1
    zero_frac = float((codes == 0).mean())
    rail_frac = float((codes == mask).mean())
    dead_frac = float((codes.max(axis=1) == codes.min(axis=1)).mean())
    return {"zero_frac": zero_frac, "rail_frac": rail_frac,
            "dead_frac": dead_frac, "nbits": int(nbits)}


def _lowbit_verdict(raw, nbits, policy):
    """Code-domain gate rule shared by the packed and host-decoded
    low-bit paths.  The zero/rail thresholds are RESCALED onto the
    quantization floor: a uniform healthy code distribution already
    puts ``2^-nbits`` of the samples on each rail, so the policy's
    float-domain fraction limits are interpreted as *how far toward
    100% the excess may go* — ``limit' = expected + (1 - expected) *
    limit``.  At 2 bits with the default ``max_zero_frac=0.75`` that is
    0.8125 (healthy ~0.25 passes, a dropped-packet chunk at ~1.0
    trips); at 1 bit the default saturation limit resolves to 0.75
    (healthy ~0.5 passes, a clipped chunk at ~1.0 trips).
    ``dead_frac`` needs no rescale — channel flatness is
    rate-independent.  There is nothing to sanitize in integer codes
    (no NaN to impute), so ``"strict"`` and ``"sanitize"`` behave
    identically here: clean or quarantine.
    """
    expected = 2.0 ** -int(nbits)
    zero_lim = expected + (1.0 - expected) * policy.max_zero_frac
    rail_lim = expected + (1.0 - expected) * policy.max_sat_frac
    stats = {k: (round(v, 6) if isinstance(v, float) else v)
             for k, v in raw.items()}
    reasons = [name for name, frac, lim in (
        ("zero_frac", raw["zero_frac"], zero_lim),
        ("rail_frac", raw["rail_frac"], rail_lim),
        ("dead_frac", raw["dead_frac"], policy.max_dead_frac),
    ) if frac > lim]
    if reasons:
        return {"verdict": "quarantine", "stats": stats,
                "reasons": reasons}
    return {"verdict": "clean", "stats": stats, "reasons": []}


def gate_chunk_packed(frames, nbits, nchan, policy, max_rows=4096):
    """Gate one PACKED low-bit chunk from its raw bytes (ISSUE 11).

    ``frames`` is the raw ``(nsamps, bytes_per_frame)`` uint8 block the
    packed fast path ships to the device.  A bounded strided row
    subsample (``max_rows`` frames) is decoded with cheap shift/mask
    stats — the reader thread never pays a full-chunk unpack — and the
    code-domain verdict rule (:func:`_lowbit_verdict`) applies.  The
    frames are returned untouched either way: the gate must never
    perturb the byte-exact upload.
    """
    from ..io.lowbit import sample_codes

    frames = np.asarray(frames)
    codes = sample_codes(frames, nbits, nchan, max_rows=max_rows)
    return frames, _lowbit_verdict(lowbit_code_stats(codes, nbits),
                                   nbits, policy)


def gate_chunk_lowbit(block, nbits, policy, max_cols=4096):
    """Gate one host-DECODED low-bit chunk (the numpy-backend path):
    same code-domain rule as :func:`gate_chunk_packed`, computed from a
    strided column subsample of the float code block."""
    block = np.asarray(block)
    stride = max(1, block.shape[1] // int(max_cols))
    return block, _lowbit_verdict(
        lowbit_code_stats(block[:, ::stride], nbits), nbits, policy)


# ---------------------------------------------------------------------------
# Quarantine manifest
# ---------------------------------------------------------------------------

class QuarantineManifest:
    """Append-only ``quarantine_<fingerprint>.jsonl`` next to the
    candidate store: one JSON record per quarantined chunk or persist
    dead-letter (``{"chunk", "end", "reason", "stats"?}``).  Created
    lazily on first record, so a clean run's output directory is
    byte-identical to pre-hardening.  Thread-safe (records arrive from
    the main loop and the persist worker)."""

    def __init__(self, directory, fingerprint=None):
        self.directory = str(directory)
        self.fingerprint = fingerprint
        self.path = os.path.join(
            self.directory, f"quarantine_{fingerprint or 'noresume'}.jsonl")
        self._lock = threading.Lock()

    def record(self, chunk, end, reason, stats=None):
        rec = {"chunk": int(chunk), "end": int(end), "reason": str(reason)}
        if stats:
            rec["stats"] = stats
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            os.makedirs(self.directory, exist_ok=True)
            with open(self.path, "a") as f:
                f.write(line + "\n")
        _metrics.counter("putpu_quarantine_records_total").inc()
        return rec

    def records(self):
        """Every record in file order (``[]`` when no manifest exists)."""
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    # a torn final line (crash mid-append): the manifest
                    # is advisory — a torn record must never take down
                    # the audit or the run that triggers it
                    continue
        return out

    def chunks(self, reason_prefix=None):
        """Set of quarantined chunk starts, optionally filtered by a
        reason prefix (e.g. ``"persist_dead_letter"``)."""
        return {r["chunk"] for r in self.records()
                if reason_prefix is None
                or str(r["reason"]).startswith(reason_prefix)}
