"""Shared tri-state environment-knob parser.

Every Pallas-path bisection knob (``PUTPU_FDMT_HEAD``,
``PUTPU_PALLAS_SCORE``, ``PUTPU_FDD_PALLAS``) follows the same
contract: ``''``/unset means *auto* (platform default), ``'0'`` forces
off, ``'1'`` forces on, and anything else WARNS and falls back to auto
— a silently-ignored ``'true'``/``'off'`` would make an A/B bisection
measure the same compiled program twice (the ``_head_enabled`` lesson,
round 3).  Three hand-rolled copies of this parser had already drifted
(``PUTPU_FDD_PALLAS`` silently ignored garbage — code-review r5); this
helper pins the behaviour once.
"""

from __future__ import annotations

import os


def tristate_env(name):
    """Parse env knob ``name``: True / False / None (auto).

    Warns (and returns None) on any value other than '', '0', '1'.
    """
    knob = os.environ.get(name, "")
    if knob == "0":
        return False
    if knob == "1":
        return True
    if knob:
        import warnings

        warnings.warn(
            f"{name}={knob!r} ignored (expected '0' or '1'); using the "
            "platform default", stacklevel=3)
    return None
