"""A minimal column table for search results.

Drop-in stand-in for the ``astropy.table.Table`` the reference returns from
``dedispersion_search`` (``pulsarutils/dedispersion.py:248``): supports
``result["snr"]`` column access, ``len``, iteration over column names, and
npz round-tripping for the candidate store.  Self-contained on purpose —
astropy is not a dependency of this framework.
"""

from __future__ import annotations

from collections.abc import Mapping

import numpy as np


class ResultTable(Mapping):
    """Ordered mapping of column name -> 1-D numpy array (equal lengths).

    ``meta`` is a free-form dict for per-table annotations that are not
    columns (e.g. the hybrid's noise-certificate verdict); it is NOT
    persisted by :meth:`to_npz`.
    """

    def __init__(self, columns, meta=None):
        self._cols = {}
        self.meta = dict(meta) if meta else {}
        n = None
        for name, values in dict(columns).items():
            arr = np.asarray(values)
            if n is None:
                n = arr.shape[0]
            elif arr.shape[0] != n:
                raise ValueError(
                    f"column {name!r} has length {arr.shape[0]} != {n}")
            self._cols[name] = arr
        self._nrows = 0 if n is None else n

    # Mapping interface -----------------------------------------------------
    def __getitem__(self, name):
        return self._cols[name]

    def __iter__(self):
        return iter(self._cols)

    def __len__(self):
        return len(self._cols)

    # conveniences ----------------------------------------------------------
    @property
    def nrows(self):
        return self._nrows

    @property
    def colnames(self):
        return list(self._cols)

    def argbest(self, column="snr"):
        """Row index of the maximum of ``column``."""
        return int(np.argmax(self._cols[column]))

    def best_row(self, column="snr"):
        i = self.argbest(column)
        return {name: col[i] for name, col in self._cols.items()}

    def to_npz(self, path):
        np.savez(path, **self._cols)

    @classmethod
    def from_npz(cls, path):
        with np.load(path) as data:
            return cls({k: data[k] for k in data.files})

    def __repr__(self):
        cols = ", ".join(f"{k}[{self._nrows}]" for k in self._cols)
        return f"ResultTable({cols})"
