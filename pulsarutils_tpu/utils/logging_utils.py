"""Framework logger + per-stage timing.

The reference's observability was ``astropy.log.info`` milestones, bare
prints and tqdm bars (SURVEY §5).  Here: one stdlib logger plus a tiny
stage profiler that also hooks ``jax.profiler`` traces when requested.
"""

from __future__ import annotations

import contextlib
import logging
import time

logger = logging.getLogger("pulsarutils_tpu")
if not logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s: %(message)s", "%H:%M:%S"))
    logger.addHandler(_h)
    logger.setLevel(logging.INFO)


class StageTimer:
    """Accumulates wall-clock per named stage; ``report()`` logs a table."""

    def __init__(self):
        self.totals = {}
        self.counts = {}

    @contextlib.contextmanager
    def stage(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self, log=logger):
        for name, total in sorted(self.totals.items(), key=lambda kv: -kv[1]):
            n = self.counts[name]
            log.info("stage %-20s %8.3fs total, %6d calls, %8.4fs/call",
                     name, total, n, total / n)


@contextlib.contextmanager
def device_trace(trace_dir=None):
    """Wrap a block in a ``jax.profiler`` trace when ``trace_dir`` is set;
    no-op otherwise (safe on any backend)."""
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(str(trace_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()
