"""Framework logger + per-stage timing + the streaming budget accountant.

The reference's observability was ``astropy.log.info`` milestones, bare
prints and tqdm bars (SURVEY §5).  Here: one stdlib logger, a tiny
stage profiler that also hooks ``jax.profiler`` traces when requested,
and — round 6 — :class:`BudgetAccountant`, the hierarchical per-chunk
wall-clock budget the survey rehearsal was missing (its round-5 stage
table explained ~6% of wall; VERDICT r5 #1): every second of a chunk's
wall is assigned to a named bucket, with an explicit ``unattributed``
residual per chunk and in the run footer.

Round 7: the accountant's buckets and chunks are measured by
:mod:`pulsarutils_tpu.obs.trace` **spans** — one timing primitive whose
completed intervals feed both the budget ledger (same rounding, same
``BUDGET_JSON`` bytes) and, when a tracer is active, the Perfetto/Chrome
trace timeline; counters are mirrored into the process metrics registry
(:mod:`pulsarutils_tpu.obs.metrics`).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import threading
import time

from ..obs import metrics as _metrics
from ..obs import trace as _trace

logger = logging.getLogger("pulsarutils_tpu")
if not logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(levelname)s: %(message)s", "%H:%M:%S"))
    logger.addHandler(_h)
    logger.setLevel(logging.INFO)


class StageTimer:
    """Accumulates wall-clock per named stage; ``report()`` logs a table."""

    def __init__(self):
        self.totals = {}
        self.counts = {}

    @contextlib.contextmanager
    def stage(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self, log=logger):
        for name, total in sorted(self.totals.items(), key=lambda kv: -kv[1]):
            n = self.counts[name]
            log.info("stage %-20s %8.3fs total, %6d calls, %8.4fs/call",
                     name, total, n, total / n)


# ---------------------------------------------------------------------------
# Budget accountant (round 6)
# ---------------------------------------------------------------------------

#: the accountant deep code attributes to without API threading: kernel
#: facades call :func:`budget_bucket`/:func:`budget_count`, which no-op
#: unless a chunk budget is active on this (main) thread.  A ContextVar,
#: not a bare global, so overlapped worker threads (reader, persist)
#: never misattribute into the main thread's serial buckets.
_ACTIVE_BUDGET = contextvars.ContextVar("putpu_budget", default=None)

#: chunk-wall histogram edges: decade-ish coverage from sub-100ms CPU
#: test chunks to multi-minute tunnelled-TPU chunks
_CHUNK_WALL_EDGES = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0)


def _percentile(sorted_values, q):
    """Linear-interpolation percentile of an already-sorted list (the
    numpy default rule, reimplemented so the ledger stays stdlib-only
    and byte-deterministic)."""
    n = len(sorted_values)
    if n == 0:
        return None
    if n == 1:
        return float(sorted_values[0])
    pos = q * (n - 1)
    lo = int(pos)
    frac = pos - lo
    if lo + 1 >= n:
        return float(sorted_values[-1])
    return float(sorted_values[lo] * (1.0 - frac)
                 + sorted_values[lo + 1] * frac)


#: process-wide XLA compile observation (jax.monitoring events); installed
#: lazily, once — the listener registry has no deregister, so the counts
#: are cumulative and consumers take deltas
_COMPILE = {"count": 0, "secs": 0.0, "installed": False}
_COMPILE_LOCK = threading.Lock()


def _install_compile_listener():
    with _COMPILE_LOCK:
        if _COMPILE["installed"]:
            return
        _COMPILE["installed"] = True  # one attempt, even on failure
        try:
            from jax import monitoring

            def _on_event(name, secs, **kw):
                if name.endswith("backend_compile_duration"):
                    with _COMPILE_LOCK:
                        _COMPILE["count"] += 1
                        _COMPILE["secs"] += float(secs)

            monitoring.register_event_duration_secs_listener(_on_event)
        except Exception:  # monitoring API drift: degrade to no counts
            pass


def compile_snapshot():
    """Cumulative ``(count, seconds)`` of XLA backend compiles observed
    so far (0, 0.0 until JAX emits its first monitored compile)."""
    _install_compile_listener()
    with _COMPILE_LOCK:
        return _COMPILE["count"], _COMPILE["secs"]


def measure_device_rtt(n=5):
    """Median seconds for one trivial dispatch + one-element readback.

    The per-trip floor every device round trip pays (on a tunnelled TPU
    ~0.1 s; locally ~1e-4 s).  One warmup call absorbs the compile, so
    the median measures steady-state trips.  Returns ``None`` when no
    jax backend is importable.
    """
    try:
        import numpy as np

        import jax.numpy as jnp
    except Exception:
        return None
    x = jnp.float32(1.0)
    np.asarray(x + jnp.float32(1.0))  # warm (compile + session)
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        np.asarray(x + jnp.float32(1.0))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


class BudgetAccountant(StageTimer):
    """Per-chunk wall-clock budget: buckets + counters + residual.

    Drop-in superset of :class:`StageTimer` (``stage``/``report`` keep
    working, and every bucket second also lands in the stage totals, so
    the rehearsal's stage-table parsers see the same rows).  On top:

    * :meth:`chunk` opens a per-chunk budget; within it,
      :meth:`bucket`/:func:`budget_bucket` attribute **main-thread,
      serial** time to named buckets and :meth:`count` bumps counters
      (``dispatches``, ``readbacks``, ...).  Bucket names may nest with
      ``/`` (``search/coarse``): the residual math uses top-level names
      only, so instrumented sub-phases never double-count;
    * XLA compiles are observed via ``jax.monitoring`` and recorded per
      chunk (``compiles``/``compile_s`` counters).  A compile in any
      chunk after the first is flagged as a **retrace** in that chunk's
      record; the log escalates to a WARNING once retraces appear in 3+
      chunks (true shape drift recompiles everywhere, while a lazily
      built kernel's first use legitimately compiles once).  NOTE the
      compile listener is process-global: a concurrent JAX compile from
      another thread lands in whichever chunk is open;
    * work overlapped onto other threads (prefetch decode, persist) is
      recorded via :meth:`add_async` — reported, but deliberately NOT
      part of any chunk's serial budget (it does not occupy the chunk's
      critical path);
    * ``unattributed`` = chunk wall − Σ top-level buckets, per chunk and
      summed in :meth:`footer`; :meth:`to_json` emits the whole ledger
      for artifacts.

    ``rtt_s`` (see :func:`measure_device_rtt`) prices the per-trip
    floor: the footer reports ``dispatches+readbacks × rtt`` so tunnel
    round-trip cost is attributable even though each trip's wait is
    already inside the bucket that blocked on it.
    """

    def __init__(self, rtt_s=None):
        super().__init__()
        self.rtt_s = rtt_s
        self.chunks = []
        self.async_totals = {}
        self.counters_total = {}
        self._async_lock = threading.Lock()
        self._active = None
        self._retrace_chunks = 0
        self._stream_chunks = 0
        self._truncation_warned = False
        self._autotune_mark = self._autotune_seq()
        _install_compile_listener()

    @staticmethod
    def _autotune_seq():
        """Current position in the process autotune-decision ledger
        (lazy import: the tuning package consumes this module)."""
        from ..tuning.autotune import decision_seq

        return decision_seq()

    def begin_stream(self):
        """Mark the start of a new stream/run on a reused accountant.

        Retrace detection keys off the first chunk OF A STREAM (first-use
        compiles are normal there); a caller aggregating several runs
        into one accountant calls this per run so the second run's
        initial compiles are not misflagged as shape drift.  The drivers
        (``search_by_chunks``, ``stream_search``) call it for you.
        """
        self._stream_chunks = 0
        self._retrace_chunks = 0  # warning escalation is per stream too
        # per-key kernel-autotune decisions are reported per run too:
        # the footer shows THIS stream's resolutions, not the whole
        # process history (a reused accountant would otherwise repeat
        # the previous run's table)
        self._autotune_mark = self._autotune_seq()

    # -- per-chunk budget ----------------------------------------------------

    @contextlib.contextmanager
    def chunk(self, label):
        if self._active is not None:
            raise RuntimeError("budget chunks cannot nest")
        c0, s0 = compile_snapshot()
        rec = {"chunk": label, "wall_s": 0.0, "buckets": {}, "counters": {}}
        self._active = rec
        token = _ACTIVE_BUDGET.set(self)
        # chunk wall is a span: the tracer (when active) gets one "chunk"
        # event, and every nested span lands on this chunk's own track
        track_token = _trace.push_track(f"chunk {label}")
        s = _trace.open_span("chunk", {"chunk": label})
        try:
            yield rec
        finally:
            _trace.close_span(s)
            _trace.pop_track(track_token)
            rec["wall_s"] = s.dur
            _ACTIVE_BUDGET.reset(token)
            self._active = None
            self._stream_chunks += 1
            c1, s1 = compile_snapshot()
            if c1 > c0:
                rec["counters"]["compiles"] = c1 - c0
                rec["counters"]["compile_s"] = round(s1 - s0, 4)
                if self._stream_chunks > 1:
                    # a compile after chunk 0 is a retrace.  A FEW are
                    # expected — lazily-built kernels compiling on first
                    # use (the hybrid's rescore buckets on the first hit
                    # chunk, a ragged final chunk) — so the flag is
                    # recorded per chunk but the WARNING only escalates
                    # on the pattern first-use compiles cannot produce:
                    # retracing across several chunks (true shape drift
                    # recompiles on EVERY chunk; code-review r6)
                    rec["retrace"] = True
                    self._retrace_chunks += 1
                    _metrics.counter("putpu_retraces_total").inc()
                    log = (logger.warning if self._retrace_chunks >= 3
                           else logger.info)
                    log("retrace in chunk %s: %d XLA compile(s), %.2fs "
                        "(%s)", label, c1 - c0, s1 - s0,
                        "repeated retracing — shape drift? interior "
                        "chunks should reuse one compiled executable"
                        if self._retrace_chunks >= 3 else
                        "expected for a kernel's first use; repeated "
                        "occurrences escalate to a warning")
            top = sum(v for k, v in rec["buckets"].items() if "/" not in k)
            rec["unattributed_s"] = round(rec["wall_s"] - top, 4)
            rec["wall_s"] = round(rec["wall_s"], 4)
            # chunk-wall distribution (ISSUE 14): the SLO engine's
            # latency indicator — the histogram feeds the time-series
            # p95, the ledger below quotes exact percentiles
            _metrics.histogram("putpu_chunk_wall_seconds",
                               edges=_CHUNK_WALL_EDGES).observe(
                rec["wall_s"])
            rec["buckets"] = {k: round(v, 4)
                              for k, v in rec["buckets"].items()}
            self.chunks.append(rec)
            _metrics.counter("putpu_chunks_total").inc()
            logger.debug("chunk %s budget: wall=%.3fs %s "
                         "unattributed=%.3fs counters=%s", label,
                         rec["wall_s"],
                         " ".join(f"{k}={v:.3f}" for k, v in
                                  sorted(rec["buckets"].items(),
                                         key=lambda kv: -kv[1])
                                  if "/" not in k),
                         rec["unattributed_s"], rec["counters"])

    @contextlib.contextmanager
    def bucket(self, name):
        """Serial main-thread time bucket (also feeds the stage table).

        Measured as ONE span (:mod:`..obs.trace`): the budget consumes
        the span's duration, and an active tracer records the same
        interval as a timeline event — never two clocks for one block.
        """
        s = _trace.open_span(name)
        try:
            yield
        finally:
            _trace.close_span(s)
            self.add(name, s.dur)

    def add(self, name, dt):
        if self._active is not None:
            b = self._active["buckets"]
            b[name] = b.get(name, 0.0) + dt
        self.totals[name] = self.totals.get(name, 0.0) + dt
        self.counts[name] = self.counts.get(name, 0) + 1

    def count(self, name, n=1):
        if self._active is not None:
            c = self._active["counters"]
            c[name] = c.get(name, 0) + n
        self.counters_total[name] = self.counters_total.get(name, 0) + n
        # mirror into the process metrics registry (Prometheus/JSONL
        # exporters); the budget ledger stays the per-run source of truth
        # the ONE sanctioned dynamic-name seam; the possible names are
        # enumerated as BUDGET_COUNTERS in obs/names.py
        # putpu-lint: disable=metric-name-dynamic — enumerated manifest seam
        _metrics.counter(f"putpu_{name}_total").inc(n)

    def add_async(self, name, dt):
        """Overlapped (off-critical-path) seconds, any thread."""
        with self._async_lock:
            self.async_totals[name] = self.async_totals.get(name, 0.0) + dt

    def trips(self):
        """Total device round trips counted so far (``dispatches`` +
        ``readbacks`` over all chunks) — the quantity the RTT floor
        prices, and the number the mesh fused-hybrid A/B pins (one
        fused ``shard_map`` program per typical hit chunk vs one coarse
        dispatch plus one per rescore bucket)."""
        return (self.counters_total.get("dispatches", 0)
                + self.counters_total.get("readbacks", 0))

    # -- reporting -----------------------------------------------------------

    def to_json(self, max_per_chunk=32):
        from ..obs.gate import SCHEMA_VERSION

        nchunks = len(self.chunks)
        wall = sum(c["wall_s"] for c in self.chunks)
        buckets = {}
        for c in self.chunks:
            for k, v in c["buckets"].items():
                buckets[k] = buckets.get(k, 0.0) + v
        top = sum(v for k, v in buckets.items() if "/" not in k)
        unattributed = wall - top
        walls = sorted(c["wall_s"] for c in self.chunks)
        out = {
            # versioned footer (ISSUE 5 satellite): parsers and the perf
            # gate key off this instead of silently comparing records
            # whose meaning drifted.  ISSUE 14 added chunk_wall_s
            # percentiles — the schema_version bump that versions it.
            "schema_version": SCHEMA_VERSION,
            "chunks": nchunks,
            "wall_s": round(wall, 3),
            "chunk_wall_s": ({
                "p50": round(_percentile(walls, 0.50), 4),
                "p95": round(_percentile(walls, 0.95), 4),
                "p99": round(_percentile(walls, 0.99), 4)}
                if walls else None),
            "buckets_s": {k: round(v, 3) for k, v in sorted(
                buckets.items(), key=lambda kv: -kv[1])},
            "unattributed_s": round(unattributed, 3),
            "attributed_pct": round(100.0 * top / wall, 1) if wall else None,
            "counters": dict(self.counters_total),
            "async_s": {k: round(v, 3)
                        for k, v in self.async_totals.items()},
            # long streams: keep the JSON line bounded — head + tail
            # chunks (the aggregates above always cover every chunk);
            # max_per_chunk=0 drops the per-chunk detail entirely
            "per_chunk": (self.chunks if nchunks <= max_per_chunk
                          else self.chunks[:max_per_chunk // 2]
                          + self.chunks[nchunks - max_per_chunk // 2:]),
        }
        if nchunks > max_per_chunk:
            out["per_chunk_truncated"] = True
            # how many interior chunk records the head+tail window drops
            # (the aggregates above still cover every chunk) — recorded,
            # not silent, so long surveys know detail was elided
            out["truncated_chunks"] = nchunks - 2 * (max_per_chunk // 2)
            # max_per_chunk=0 is an explicit "no per-chunk detail"
            # request — record the count but don't warn about it
            if max_per_chunk > 0 and not self._truncation_warned:
                self._truncation_warned = True
                logger.warning(
                    "budget JSON truncated: per-chunk detail for %d of %d "
                    "chunks dropped (head+tail of %d kept; aggregates "
                    "cover all chunks — raise max_per_chunk for the full "
                    "ledger)", out["truncated_chunks"], nchunks,
                    max_per_chunk)
        if self.rtt_s is not None:
            out["rtt_s"] = round(self.rtt_s, 6)
            out["trips"] = self.trips()
            out["trips_x_rtt_s"] = round(self.trips() * self.rtt_s, 3)
        # per-key kernel-autotune decisions since this run's
        # begin_stream (ISSUE 7) — key absent when kernel="auto" never
        # resolved anything this run, so pre-tuner ledgers (and the
        # byte-pinned goldens) are unchanged
        from ..tuning.autotune import decisions_since

        decisions = decisions_since(self._autotune_mark)
        if decisions:
            out["autotune"] = decisions
        return out

    def footer(self, log=logger):
        """Log the run-level budget: every bucket's share of the summed
        chunk wall, the residual, trip pricing and overlapped work."""
        if not self.chunks:
            return
        j = self.to_json()
        wall = j["wall_s"] or 1.0
        log.info("chunk budget over %d chunks, %.2fs wall "
                 "(%.1f%% attributed):", j["chunks"], j["wall_s"],
                 j["attributed_pct"] or 0.0)
        cw = j.get("chunk_wall_s")
        if cw:
            log.info("  chunk wall p50/p95/p99: %.3f / %.3f / %.3f s",
                     cw["p50"], cw["p95"], cw["p99"])
        # group children under their PARENT (a flat sort-by-total can
        # interleave a child below an unrelated small bucket and
        # misrepresent the hierarchy — code-review r6)
        buckets = j["buckets_s"]
        tops = sorted((k for k in buckets if "/" not in k),
                      key=lambda k: -buckets[k])
        for top in tops:
            log.info("  %-22s %8.3fs  %5.1f%%", top, buckets[top],
                     100.0 * buckets[top] / wall)
            kids = sorted((k for k in buckets
                           if k.startswith(top + "/")),
                          key=lambda k: -buckets[k])
            for k in kids:
                log.info("    %-20s %8.3fs  %5.1f%%",
                         k[len(top) + 1:], buckets[k],
                         100.0 * buckets[k] / wall)
        log.info("  %-22s %8.3fs  %5.1f%%", "unattributed",
                 j["unattributed_s"], 100.0 * j["unattributed_s"] / wall)
        if j.get("counters"):
            log.info("  counters: %s", json.dumps(j["counters"]))
        for d in j.get("autotune", ()):
            log.info("  autotune %s -> %s (%s%s)", d["key"], d["kernel"],
                     d["source"],
                     f", {d['speedup_vs_static']}x vs static"
                     if d.get("speedup_vs_static") is not None else "")
        if self.rtt_s is not None:
            log.info("  device RTT %.4fs x %d trips = %.2fs (floor "
                     "inside the blocking buckets)", j["rtt_s"],
                     j["trips"], j["trips_x_rtt_s"])
        for k, v in sorted(j["async_s"].items(), key=lambda kv: -kv[1]):
            log.info("  overlapped %-17s %8.3fs (off critical path)", k, v)
        if j["wall_s"]:
            _metrics.gauge("putpu_chunks_per_s").set(
                round(j["chunks"] / j["wall_s"], 4))
        from ..obs import roofline as _roofline

        _roofline.log_table(log)  # no-op unless roofline accounting ran


def current_budget():
    """The :class:`BudgetAccountant` whose chunk context encloses this
    call on this thread, or ``None``."""
    return _ACTIVE_BUDGET.get()


@contextlib.contextmanager
def budget_bucket(name):
    """Attribute the block to ``name`` in the active chunk budget, if
    any — and, when a tracer is active, record the same interval as a
    span (kernel code calls this unconditionally; with neither consumer
    present it degrades to a plain yield)."""
    acct = _ACTIVE_BUDGET.get()
    if acct is None and not _trace.is_tracing():
        yield
        return
    s = _trace.open_span(name)
    try:
        yield
    finally:
        _trace.close_span(s)
        if acct is not None:
            acct.add(name, s.dur)


def budget_count(name, n=1):
    """Bump a counter (``dispatches``, ``readbacks``, ...) in the active
    chunk budget, if any."""
    acct = _ACTIVE_BUDGET.get()
    if acct is not None:
        acct.count(name, n)


@contextlib.contextmanager
def device_trace(trace_dir=None):
    """Wrap a block in a ``jax.profiler`` trace when ``trace_dir`` is set;
    no-op otherwise (safe on any backend).

    Round 7: one mechanism, two spellings — this delegates to
    :func:`pulsarutils_tpu.obs.trace.trace_session`, the session driver
    that can emit the span JSON and the XLA device trace together from a
    single flag (the CLI's ``--trace``); ``device_trace`` remains the
    device-only form the benches use.
    """
    if not trace_dir:
        yield
        return
    with _trace.trace_session(device_trace_dir=trace_dir):
        yield
