"""Benchmark: DM-trials/sec of the TPU dedispersion sweep vs single-core NumPy.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "DM-trials/sec", "vs_baseline": N, ...}

Headline configuration (BASELINE.json config 2): 1024 channels x 1M samples,
512 DM trials, single chip, kernel="auto" (the Pallas kernel on TPU).  The
NumPy baseline (the reference algorithm's vectorised single-core form:
per-trial gather + channel sum + 4-window boxcar scoring — semantics of
reference ``pulsarutils/dedispersion.py:174-202``) is measured on reduced
sample counts and extrapolated linearly in ``nsamples`` (the sweep is
O(ndm * nchan * nsamples); linearity is verified on two sizes and
reported).

Robustness: a TPU-side failure (worker crash, wedged tunnel) degrades to
smaller shapes and finally to the CPU backend — the JSON line is always
printed, with a "degraded" note when applicable.

Environment knobs:
  BENCH_PRESET=full|quick   (default full; quick = small shapes for smoke)
  BENCH_NCHAN, BENCH_NSAMP, BENCH_NDM  (override individual sizes)
  BENCH_KERNEL=auto|pallas|gather      (default auto)
"""

import json
import os
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_data(nchan, nsamp, start_freq, bandwidth, tsamp, inject_dm, seed=0):
    import numpy as np

    from pulsarutils_tpu.ops.plan import dedispersion_shifts

    rng = np.random.default_rng(seed)
    log(f"simulating {nchan} x {nsamp} filterbank ...")
    # in place: the full config is a 4-19 GB array on a 1-core host —
    # np.abs(...) * 0.5 would allocate two extra copies
    array = rng.standard_normal((nchan, nsamp), dtype=np.float32)
    np.abs(array, out=array)
    array *= 0.5
    array[:, nsamp // 2] += 1.0
    # disperse: per-channel circular roll (fast host path)
    shifts = np.rint(np.asarray(dedispersion_shifts(
        nchan, inject_dm, start_freq, bandwidth, tsamp))).astype(int) % nsamp
    for c in range(nchan):
        array[c] = np.roll(array[c], shifts[c])
    return array


def measure_jax(array, trial_dms, geom, kernel):
    import time as _t

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pulsarutils_tpu.ops.search import dedispersion_search

    start_freq, bandwidth, tsamp = geom

    # upload once, outside the timed region: the tunnel to the TPU has
    # highly variable bandwidth (15 s .. 380 s for 4 GB measured) and the
    # streaming pipeline double-buffers uploads anyway
    t0 = _t.time()
    device_array = jnp.asarray(array, dtype=jnp.float32)
    _ = np.asarray(device_array[0, :8])  # force (block_until_ready lies
    # on the tunnelled platform)
    log(f"host->device upload: {_t.time() - t0:.1f}s")

    def run():
        return dedispersion_search(
            device_array, None, None, start_freq, bandwidth, tsamp,
            backend="jax", trial_dms=trial_dms, kernel=kernel)

    log(f"compiling + warming up JAX kernel ({kernel}) ...")
    t0 = time.time()
    table = run()
    log(f"first run (incl. compile): {time.time() - t0:.2f}s")
    from pulsarutils_tpu.utils.logging_utils import device_trace

    trace_dir = os.environ.get("BENCH_TRACE")
    with device_trace(trace_dir):  # no-op when BENCH_TRACE unset
        t0 = time.time()
        table = run()
        jax_time = time.time() - t0
    if trace_dir:
        log(f"profiler trace written to {trace_dir}")
    return table, len(trial_dms) / jax_time, jax_time, device_array


def measure_numpy_baseline(array, trial_dms, geom, nsamp, ndm):
    import numpy as np

    from pulsarutils_tpu.ops.search import _search_numpy

    start_freq, bandwidth, tsamp = geom
    base_ndm = min(ndm, 16)
    base_samp_a = min(nsamp // 2, 1 << 14)
    base_samp_b = min(nsamp, 1 << 15)

    def numpy_time(ns, nd):
        sub = np.ascontiguousarray(array[:, :ns]).astype(np.float64)
        dms = trial_dms[:nd]
        t0 = time.time()
        _search_numpy(sub, dms, start_freq, bandwidth, tsamp,
                      capture_plane=False)
        return time.time() - t0

    log("measuring NumPy single-core baseline ...")
    numpy_time(min(nsamp, 2048), 4)  # warm up allocator/page cache
    t_a = numpy_time(base_samp_a, base_ndm)
    t_b = numpy_time(base_samp_b, base_ndm)
    per_trial_a = t_a / base_ndm / base_samp_a
    per_trial_b = t_b / base_ndm / base_samp_b
    linearity = per_trial_b / per_trial_a
    numpy_tps = 1.0 / (per_trial_b * nsamp)
    log(f"NumPy: {t_a:.2f}s@{base_samp_a}, {t_b:.2f}s@{base_samp_b} "
        f"(linearity ratio {linearity:.2f}) -> {numpy_tps:.4f} DM-trials/s "
        f"extrapolated at {nsamp} samples")
    return numpy_tps, linearity


def main():
    preset = os.environ.get("BENCH_PRESET", "full")
    nchan = int(os.environ.get("BENCH_NCHAN", 1024 if preset == "full" else 128))
    nsamp = int(os.environ.get("BENCH_NSAMP",
                               1 << 20 if preset == "full" else 1 << 14))
    ndm = int(os.environ.get("BENCH_NDM", 512 if preset == "full" else 64))
    kernel = os.environ.get("BENCH_KERNEL", "auto")

    import numpy as np

    geom = (1200.0, 200.0, 0.0005)
    inject_dm = 350.0
    degraded = None

    import jax

    try:
        # persistent compile cache: kernel compiles at the 1M-sample shapes
        # run minutes; cache them across bench invocations
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/jax_bench"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:
        pass

    try:
        platform = jax.devices()[0].platform
    except RuntimeError as exc:
        log(f"accelerator init failed ({exc}); falling back to CPU")
        jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform
        degraded = "accelerator init failed; CPU backend"
    log(f"platform: {platform}")

    attempts = [(nchan, nsamp, ndm)]
    if preset == "full":
        attempts.append((nchan, nsamp // 4, max(64, ndm // 4)))
    table = array = trial_dms = None
    measured_kernel = kernel
    for i, (nc, ns, nd) in enumerate(attempts):
        # rebuild at each size so the injected pulse and the full DM span
        # survive the reduction (slicing would lose both)
        sub = make_data(nc, ns, *geom, inject_dm) if i > 0 or array is None \
            else array
        dms = np.linspace(300.0, 400.0, nd)
        kernels = [kernel] + (["gather"] if kernel != "gather" else [])
        try:
            for j, kern in enumerate(kernels):
                try:
                    (table, jax_tps, jax_time,
                     device_array) = measure_jax(sub, dms, geom, kern)
                    measured_kernel = kern
                    if j > 0:
                        degraded = (f"kernel={kernel} failed; "
                                    f"fell back to kernel=gather")
                    break
                except Exception as exc:
                    if j + 1 == len(kernels):
                        raise
                    log(f"kernel={kern} failed at ({nc}x{ns}x{nd}): "
                        f"{exc!r}; trying gather")
            nchan, nsamp, ndm, trial_dms, array = nc, ns, nd, dms, sub
            if i > 0:
                degraded = f"TPU failure at full size; reduced to {ns} samples"
            break
        except Exception as exc:  # TPU worker crash / wedged tunnel
            log(f"jax path failed at ({nc}x{ns}x{nd}): {exc!r}")
    if table is None:
        # a post-init backend switch is a no-op in jax (backends are
        # memoized), so the only reliable CPU fallback is a fresh process
        if os.environ.get("BENCH_NO_SUBFALLBACK"):
            raise SystemExit("bench failed and sub-fallback is disabled")
        log("falling back to CPU backend in a fresh process ...")
        import subprocess

        env = dict(os.environ, BENCH_PRESET="quick", BENCH_KERNEL="gather",
                   BENCH_NO_SUBFALLBACK="1", BENCH_DEGRADED="1")
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu'); "
             "import bench; bench.main()"],
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
            capture_output=True, text=True, timeout=1800)
        sys.stderr.write(proc.stderr)
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        out = json.loads(line)
        out["degraded"] = "TPU unavailable; CPU backend, quick shapes"
        print(json.dumps(out), flush=True)
        return

    log(f"JAX steady-state: {jax_time:.3f}s -> {jax_tps:.1f} DM-trials/s")

    # secondary metric: the FDMT tree sweep covers EVERY physically
    # distinguishable trial in [300, 400] (the canonical integer-delay
    # plan) in one log-depth transform
    fdmt = None
    try:
        from pulsarutils_tpu.ops.search import dedispersion_search

        dev = device_array  # reuse measure_jax's upload (15-380 s for 4 GB)

        def frun():
            return dedispersion_search(dev, 300.0, 400.0, *geom,
                                       backend="jax", kernel="fdmt")

        tf = frun()  # compile + warm
        t0 = time.time()
        tf = frun()
        fdmt_time = time.time() - t0
        fdmt = {
            "native_trials": tf.nrows,
            "full_sweep_s": round(fdmt_time, 3),
            "trials_per_sec": round(tf.nrows / fdmt_time, 1),
            "best_dm": float(tf["DM"][tf.argbest()]),
        }
        log(f"FDMT full canonical sweep: {fdmt_time:.3f}s "
            f"({tf.nrows} native trials)")
    except Exception as exc:
        log(f"fdmt metric skipped: {exc!r}")

    numpy_tps, linearity = measure_numpy_baseline(array, trial_dms, geom,
                                                  nsamp, ndm)

    result = {
        "metric": f"DM-trials/sec, {nchan}-chan x {nsamp}-sample filterbank, "
                  f"{ndm} trials, backend=jax ({platform})",
        "value": round(jax_tps, 2),
        "unit": "DM-trials/sec",
        "vs_baseline": round(jax_tps / numpy_tps, 2),
        "baseline": {
            "what": "single-core NumPy (reference semantics), extrapolated "
                    "linearly in nsamples from two measured sizes",
            "dm_trials_per_sec": round(numpy_tps, 4),
            "linearity_check": round(linearity, 3),
        },
        "platform": platform,
        "kernel": measured_kernel,
        "best_dm": float(table["DM"][table.argbest()]),
        "injected_dm": inject_dm,
    }
    if fdmt:
        result["fdmt"] = fdmt
    if os.environ.get("BENCH_DEGRADED"):
        degraded = degraded or "degraded run"
    if degraded:
        result["degraded"] = degraded
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
