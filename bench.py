"""Benchmark: DM-trials/sec of the TPU dedispersion sweep vs single-core NumPy.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "DM-trials/sec", "vs_baseline": N, ...}

Headline configuration (BASELINE.json config 2): 1024 channels x 1M samples,
512 DM trials, single chip.  The NumPy baseline (the reference algorithm's
vectorised single-core form: per-trial gather + channel sum + 4-window
boxcar scoring — semantics of reference ``pulsarutils/dedispersion.py:
174-202``) is measured on reduced sample counts and extrapolated linearly in
``nsamples`` (the sweep is O(ndm * nchan * nsamples); linearity is verified
on two sizes and reported).

Environment knobs:
  BENCH_PRESET=full|quick   (default full; quick = small shapes for smoke)
  BENCH_NCHAN, BENCH_NSAMP, BENCH_NDM  (override individual sizes)
"""

import json
import os
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    preset = os.environ.get("BENCH_PRESET", "full")
    nchan = int(os.environ.get("BENCH_NCHAN", 1024 if preset == "full" else 128))
    nsamp = int(os.environ.get("BENCH_NSAMP",
                               1 << 20 if preset == "full" else 1 << 14))
    ndm = int(os.environ.get("BENCH_NDM", 512 if preset == "full" else 64))

    import jax

    try:
        devices = jax.devices()
        platform = devices[0].platform
    except RuntimeError as exc:  # axon tunnel unavailable -> CPU fallback
        log(f"accelerator init failed ({exc}); falling back to CPU")
        jax.config.update("jax_platforms", "cpu")
        devices = jax.devices()
        platform = devices[0].platform
    log(f"platform: {platform} devices: {devices}")

    import numpy as np

    from pulsarutils_tpu.ops.search import _search_numpy, dedispersion_search

    # ---- data -------------------------------------------------------------
    log(f"simulating {nchan} x {nsamp} filterbank ...")
    from pulsarutils_tpu.models.simulate import disperse_array

    rng = np.random.default_rng(0)
    array = np.abs(rng.normal(0.0, 0.5, (nchan, nsamp))).astype(np.float32)
    array[:, nsamp // 2] += 1.0
    start_freq, bandwidth, tsamp = 1200.0, 200.0, 0.0005
    inject_dm = 350.0
    array = disperse_array(array, inject_dm, start_freq, bandwidth,
                           tsamp).astype(np.float32)
    # an explicit ndm-trial grid around the headline range
    trial_dms = np.linspace(300.0, 400.0, ndm)

    # ---- JAX path ---------------------------------------------------------
    dm_block = int(os.environ.get("BENCH_DM_BLOCK", 8))
    chan_block = int(os.environ.get("BENCH_CHAN_BLOCK", 0)) or None

    def run_jax():
        return dedispersion_search(
            array, None, None, start_freq, bandwidth, tsamp,
            backend="jax", trial_dms=trial_dms, dm_block=dm_block,
            chan_block=chan_block)

    log("compiling + warming up JAX kernel ...")
    t0 = time.time()
    table = run_jax()
    log(f"first run (incl. compile): {time.time() - t0:.2f}s")
    t0 = time.time()
    table = run_jax()
    jax_time = time.time() - t0
    jax_tps = ndm / jax_time
    log(f"JAX steady-state: {jax_time:.3f}s -> {jax_tps:.1f} DM-trials/s")

    # ---- NumPy baseline (reduced + extrapolated) --------------------------
    base_ndm = min(ndm, 16)
    base_samp_a = min(nsamp // 2, 1 << 14)
    base_samp_b = min(nsamp, 1 << 15)

    def numpy_time(ns, nd):
        sub = np.ascontiguousarray(array[:, :ns]).astype(np.float64)
        dms = trial_dms[:nd]
        t0 = time.time()
        _search_numpy(sub, dms, start_freq, bandwidth, tsamp,
                      capture_plane=False)
        return time.time() - t0

    log("measuring NumPy single-core baseline ...")
    numpy_time(min(nsamp, 2048), 4)  # warm up allocator/page cache
    t_a = numpy_time(base_samp_a, base_ndm)
    t_b = numpy_time(base_samp_b, base_ndm)
    per_trial_a = t_a / base_ndm / base_samp_a
    per_trial_b = t_b / base_ndm / base_samp_b
    linearity = per_trial_b / per_trial_a
    # cost model: time per trial scales linearly in nsamples
    numpy_time_full_per_trial = per_trial_b * nsamp
    numpy_tps = 1.0 / numpy_time_full_per_trial
    log(f"NumPy: {t_a:.2f}s@{base_samp_a}, {t_b:.2f}s@{base_samp_b} "
        f"(linearity ratio {linearity:.2f}) -> {numpy_tps:.2f} DM-trials/s "
        f"extrapolated at {nsamp} samples")

    result = {
        "metric": f"DM-trials/sec, {nchan}-chan x {nsamp}-sample filterbank, "
                  f"{ndm} trials, backend=jax ({platform})",
        "value": round(jax_tps, 2),
        "unit": "DM-trials/sec",
        "vs_baseline": round(jax_tps / numpy_tps, 2),
        "baseline": {
            "what": "single-core NumPy (reference semantics), extrapolated "
                    "linearly in nsamples from two measured sizes",
            "dm_trials_per_sec": round(numpy_tps, 4),
            "linearity_check": round(linearity, 3),
        },
        "platform": platform,
        "best_dm": float(table["DM"][table.argbest()]),
        "injected_dm": inject_dm,
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
