"""Benchmark: DM-trials/sec of the TPU dedispersion sweep vs single-core NumPy.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "DM-trials/sec", "vs_baseline": N, ...}

Headline configuration (BASELINE.json config 2): 1024 channels x 1M samples,
512 DM trials (the canonical plan: one trial per integer sample of
band-crossing delay, starting at DM 300), single chip.  The headline
kernel is the HYBRID sweep (``ops/search.py:_search_jax_hybrid``): an
FDMT coarse pass over every trial plus an exact Pallas rescore of the hit
region — exact (bit-identical-vs-NumPy) hit detection at near-FDMT
throughput.  The run verifies the claim in-place under
``exact_hit_match``: the hybrid's best row must be byte-equal to a full
exact Pallas sweep on argbest plan index, DM, rebin and peak, and its
f32 snr must agree to reduction-order tolerance (``snr_close``,
rel < 1e-5 — the two paths add the same floats in the same order but
reduce through different plane shapes).  Pure-FDMT and pure-Pallas
sweeps are reported as secondary metrics.

The NumPy baseline is the reference algorithm (per-channel circular
roll-and-accumulate + 4-window boxcar scoring, semantics of reference
``pulsarutils/dedispersion.py:174-202``) in its efficient single-core
form: allocation-free slice-adds, no gather temporaries.  It is measured
AT the full benchmark size (no extrapolation in ``nsamples``) over a
handful of trials — per-trial cost is trial-count-independent by
construction (an outer Python loop over trials), and the reported
``linearity_check`` (per-trial cost ratio between a 4-trial and an
8-trial run at full size) confirms it.

Robustness: a TPU-side failure (worker crash, wedged tunnel) falls back
kernel=fdmt -> pallas, then to smaller shapes, and finally to the CPU
backend in a fresh process — the JSON line is always printed, with a
"degraded" note when applicable.  The XLA gather kernel is never run on
the TPU path: at benchmark sizes it scalarises and crashes the worker.

Environment knobs:
  BENCH_PRESET=full|quick   (default full; quick = small shapes for smoke)
  BENCH_NCHAN, BENCH_NSAMP  (override individual sizes)
  BENCH_KERNEL=fdmt|pallas|gather  (default fdmt)
  BENCH_TRACE=<dir>         (write a jax.profiler trace of the timed run)
"""

import json
import os
import sys
import time


GEOM = (1200.0, 200.0, 0.0005)  # start_freq MHz, bandwidth MHz, tsamp s
NTRIALS = 512  # BASELINE.json config 2
DMMIN = 300.0
INJECT_DM = 350.0


def _dmmax_for_trials(n_trials):
    from pulsarutils_tpu.ops.plan import dmmax_for_trials

    return dmmax_for_trials(DMMIN, n_trials, *GEOM)


DMMAX = _dmmax_for_trials(NTRIALS)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_data(nchan, nsamp, seed=0):
    import numpy as np

    from pulsarutils_tpu.ops.plan import dedispersion_shifts

    start_freq, bandwidth, tsamp = GEOM
    rng = np.random.default_rng(seed)
    log(f"simulating {nchan} x {nsamp} filterbank ...")
    # in place: the full config is a 4 GB array on a 1-core host —
    # np.abs(...) * 0.5 would allocate two extra copies
    array = rng.standard_normal((nchan, nsamp), dtype=np.float32)
    np.abs(array, out=array)
    array *= 0.5
    array[:, nsamp // 2] += 1.0
    # disperse: per-channel circular roll (fast host path)
    shifts = np.rint(np.asarray(dedispersion_shifts(
        nchan, INJECT_DM, start_freq, bandwidth, tsamp))).astype(int) % nsamp
    for c in range(nchan):
        array[c] = np.roll(array[c], shifts[c])
    return array


def upload(array):
    import jax.numpy as jnp
    import numpy as np

    # upload once, outside any timed region: the tunnel to the TPU has
    # highly variable bandwidth (15 s .. 930 s for 4 GB measured) and the
    # streaming pipeline double-buffers uploads anyway.  The measured
    # upload seconds are reported in the JSON so a congested session is
    # visible next to the headline instead of silently poisoning it
    # (VERDICT r4 #2a).
    t0 = time.time()
    device_array = jnp.asarray(array, dtype=jnp.float32)
    _ = np.asarray(device_array[0, :8])  # force (block_until_ready lies
    # on the tunnelled platform)
    dt = time.time() - t0
    log(f"host->device upload: {dt:.1f}s")
    return device_array, dt


#: headline timing protocol (VERDICT r4 #2a): at least MIN_REPEATS
#: steady-state sweeps, extended up to MAX_REPEATS until the spread of
#: the rank-2..5 cluster falls under SPREAD_BOUND — a congested session
#: then flags the artifact instead of silently shipping whatever the
#: tunnel allowed that minute (round 4's committed headline lost 11%
#: to a single congested run)
MIN_REPEATS = 5
MAX_REPEATS = 9
SPREAD_BOUND = 0.06


def measure_kernel(device_array, kernel, repeats=2, stabilize=False):
    """Warm + time steady-state sweeps (best of ``repeats``).

    Steady-state times vary ±15% run-to-run on the tunnelled platform
    (shared worker, host jitter); min-of-N is the honest steady-state
    estimator — all raw times are logged.  With ``stabilize`` (the
    headline protocol) repeats extend up to :data:`MAX_REPEATS` until
    the relative spread of the best three times is under
    :data:`SPREAD_BOUND`.
    Returns ``(table, trials/s, secs, timing_dict)``.
    """
    from pulsarutils_tpu.ops.search import dedispersion_search
    from pulsarutils_tpu.utils.logging_utils import device_trace

    def run():
        return dedispersion_search(
            device_array, DMMIN, DMMAX, *GEOM, backend="jax", kernel=kernel)

    log(f"compiling + warming up JAX kernel ({kernel}) ...")
    t0 = time.time()
    table = run()
    log(f"first run (incl. compile): {time.time() - t0:.2f}s")

    if stabilize:
        repeats = max(repeats, MIN_REPEATS)

    trace_dir = os.environ.get("BENCH_TRACE")
    times = []
    with device_trace(trace_dir):  # no-op when BENCH_TRACE unset
        t0 = time.time()
        table = run()
        times.append(time.time() - t0)
    if trace_dir:
        log(f"profiler trace written to {trace_dir}")

    def cluster_spread():
        """Relative spread of sweeps ranked 2-5 (0-indexed 1..4).

        Robust to ONE structurally-fast outlier — on this platform the
        first timed sweep is repeatably ~8% faster than the following
        tight cluster (measured across every round-5 session), and to
        slow stragglers.  A genuinely congested session still spreads
        the cluster itself and flags.
        """
        if len(times) < 5:
            return float("inf")
        s = sorted(times)
        return (s[4] - s[1]) / s[1]

    while len(times) < repeats or (
            stabilize and cluster_spread() > SPREAD_BOUND
            and len(times) < MAX_REPEATS):
        t0 = time.time()
        table = run()
        times.append(time.time() - t0)
    dt = min(times)
    timing = {"times_s": [round(x, 3) for x in times],
              "median_s": round(sorted(times)[len(times) // 2], 3),
              "cluster_spread": round(cluster_spread(), 4)}
    if stabilize:
        timing["stable"] = cluster_spread() <= SPREAD_BOUND
        timing["spread_bound"] = SPREAD_BOUND
    log(f"kernel={kernel}: {dt:.3f}s steady-state "
        f"(best of {timing['times_s']}, cluster spread "
        f"{timing['cluster_spread']:.1%}), {table.nrows} trials "
        f"-> {table.nrows / dt:.1f} DM-trials/s")
    return table, table.nrows / dt, dt, timing


def measure_numpy_baseline(array, nsamp):
    """Single-core reference-semantics sweep, measured AT full size.

    Runs 4 and 8 trials directly on the full ``(nchan, nsamp)`` array (the
    trials/s figure divides out the trial count, which is exact: the sweep
    is an outer Python loop over trials).  No extrapolation across
    ``nsamples``; the 4-vs-8-trial per-trial cost ratio is reported as
    ``linearity_check`` (VERDICT r1: the old two-size nsamples
    extrapolation drifted 44%).
    """
    import numpy as np

    from pulsarutils_tpu.ops.search import _search_numpy

    log("measuring NumPy single-core baseline at full size ...")
    data64 = np.asarray(array, dtype=np.float64)

    def numpy_time(ndm, repeats):
        dms = np.linspace(DMMIN, DMMAX, ndm)
        best = float("inf")
        for _ in range(repeats):  # min-of: host timing noise is +-30%
            t0 = time.time()
            _search_numpy(data64, dms, *GEOM, capture_plane=False)
            best = min(best, time.time() - t0)
        return best

    numpy_time(1, 1)  # warm up allocator/page cache
    t_4 = numpy_time(4, 2)
    t_8 = numpy_time(8, 2)
    linearity = (t_8 / 8) / (t_4 / 4)
    del data64
    numpy_tps = 8 / t_8
    log(f"NumPy @ full size: {t_4:.2f}s/4 trials, {t_8:.2f}s/8 trials "
        f"(per-trial linearity {linearity:.2f}) -> {numpy_tps:.4f} "
        f"DM-trials/s measured at {nsamp} samples")
    return numpy_tps, linearity


def main():
    preset = os.environ.get("BENCH_PRESET", "full")
    nchan = int(os.environ.get("BENCH_NCHAN", 1024 if preset == "full" else 128))
    nsamp = int(os.environ.get("BENCH_NSAMP",
                               1 << 20 if preset == "full" else 1 << 14))
    kernel = os.environ.get("BENCH_KERNEL", "hybrid")

    degraded = None

    import jax
    import numpy as np

    try:
        # persistent compile cache: kernel compiles at the 1M-sample shapes
        # run minutes; cache them across bench invocations
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/jax_bench"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:
        pass

    try:
        try:  # claim flaps for ~a minute after another process releases
            from tools.tpu_claim import claim_tpu

            claim_tpu(retries=6, sleep_s=20, log=log)
        except ImportError:
            pass
        platform = jax.devices()[0].platform
    except RuntimeError as exc:
        log(f"accelerator init failed ({exc}); falling back to CPU")
        jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform
        degraded = "accelerator init failed; CPU backend"
    log(f"platform: {platform}")
    if platform != "tpu" and kernel in ("fdmt", "hybrid"):
        # interpret-mode Pallas is far too slow; the XLA fdmt fallback is
        # fine but gather is the honest portable kernel
        kernel = "gather"
    elif platform == "tpu" and kernel == "gather":
        # never run the gather kernel on TPU (see module docstring)
        log("BENCH_KERNEL=gather crashes the TPU worker at bench sizes; "
            "using hybrid")
        kernel = "hybrid"

    # kernel fallback chain; gather stays CPU-only (see module docstring)
    chain = [kernel]
    if platform == "tpu":
        chain += [k for k in ("hybrid", "fdmt", "pallas") if k != kernel]

    attempts = [(nchan, nsamp)]
    if preset == "full":
        attempts.append((nchan, nsamp // 4))
    table = array = device_array = None
    measured_kernel = kernel
    upload_s = None
    headline_timing = None
    for i, (nc, ns) in enumerate(attempts):
        # rebuild at each size so the injected pulse and the full DM span
        # survive the reduction (slicing would lose both)
        sub = make_data(nc, ns) if i > 0 or array is None else array
        try:
            device_array, upload_s = upload(sub)
            for j, kern in enumerate(chain):
                try:
                    table, jax_tps, jax_time, headline_timing = \
                        measure_kernel(device_array, kern, stabilize=True)
                    measured_kernel = kern
                    if j > 0:
                        degraded = (f"kernel={chain[0]} failed; "
                                    f"fell back to kernel={kern}")
                    break
                except Exception as exc:
                    if j + 1 == len(chain):
                        raise
                    log(f"kernel={kern} failed at ({nc}x{ns}): {exc!r}; "
                        f"trying {chain[j + 1]}")
            nchan, nsamp, array = nc, ns, sub
            if i > 0:
                degraded = f"TPU failure at full size; reduced to {ns} samples"
            break
        except Exception as exc:  # TPU worker crash / wedged tunnel
            log(f"jax path failed at ({nc}x{ns}): {exc!r}")
            table = None
    if table is None:
        # a post-init backend switch is a no-op in jax (backends are
        # memoized), so the only reliable CPU fallback is a fresh process
        if os.environ.get("BENCH_NO_SUBFALLBACK"):
            raise SystemExit("bench failed and sub-fallback is disabled")
        log("falling back to CPU backend in a fresh process ...")
        import subprocess

        env = dict(os.environ, BENCH_PRESET="quick", BENCH_KERNEL="gather",
                   BENCH_NO_SUBFALLBACK="1", BENCH_DEGRADED="1")
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu'); "
             "import bench; bench.main()"],
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
            capture_output=True, text=True, timeout=1800)
        sys.stderr.write(proc.stderr)
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        out = json.loads(line)
        out["degraded"] = "TPU unavailable; CPU backend, quick shapes"
        print(json.dumps(out), flush=True)
        return

    # secondary metrics + in-place verification of the hybrid's claim:
    # its best row must be byte-equal to a full exact Pallas sweep
    # (which round 1 established as bit-identical-vs-NumPy hit detection)
    secondary = []
    exact_hit_match = None
    if measured_kernel == "hybrid" and platform == "tpu":
        try:
            t2, tps2, dt2, _ = measure_kernel(device_array, "pallas")
            best_h, best_p = table.argbest("snr"), t2.argbest("snr")
            exact_hit_match = {
                "argbest_equal": best_h == best_p,
                "dm_byte_equal": bool(table["DM"][best_h]
                                      == t2["DM"][best_p]),
                "rebin_equal": int(table["rebin"][best_h])
                               == int(t2["rebin"][best_p]),
                "peak_equal": int(table["peak"][best_h])
                              == int(t2["peak"][best_p]),
                # the two paths add the same floats in the same order but
                # score through different-shaped reductions (16-row vs
                # 512-row planes), so snr agrees to f32 reduction order,
                # not byte-for-byte; assert the tolerance and report the
                # actual relative gap
                "snr_close": bool(abs(table["snr"][best_h]
                                      - t2["snr"][best_p])
                                  <= 1e-5 * abs(t2["snr"][best_p])),
                "snr_rel_diff": float(abs(table["snr"][best_h]
                                          - t2["snr"][best_p])
                                      / abs(t2["snr"][best_p])),
                "rescored_rows": int(np.count_nonzero(table["exact"])),
            }
            log(f"exact_hit_match: {exact_hit_match}")
            # the verification GATES the headline: any failed field marks
            # the artifact degraded (a silently-false boolean in the JSON
            # would ship an exactness regression as a green benchmark)
            failed = [k for k, v in exact_hit_match.items()
                      if isinstance(v, bool) and not v]
            if failed:
                msg = (f"exact_hit_match FAILED on {failed}: the hybrid's "
                       "best row does not match the exact sweep")
                degraded = "; ".join(filter(None, [degraded, msg]))
            secondary.append({
                "kernel": "pallas (full exact sweep)",
                "trials_per_sec": round(tps2, 1),
                "full_sweep_s": round(dt2, 3),
                "best_dm": float(t2["DM"][t2.argbest()]),
            })
        except Exception as exc:
            log(f"secondary pallas metric skipped: {exc!r}")
        if exact_hit_match is None:
            # the gate only gates if it actually ran: an exact sweep that
            # crashed must not let the hybrid headline ship unverified
            degraded = "; ".join(filter(None, [
                degraded, "exact_hit_match verification DID NOT RUN "
                          "(exact pallas sweep failed)"]))
        try:
            t3, tps3, dt3, _ = measure_kernel(device_array, "fdmt")
            secondary.append({
                "kernel": "fdmt (coarse sweep alone)",
                "trials_per_sec": round(tps3, 1),
                "full_sweep_s": round(dt3, 3),
                "best_dm": float(t3["DM"][t3.argbest()]),
            })
        except Exception as exc:
            log(f"secondary fdmt metric skipped: {exc!r}")
    elif measured_kernel == "fdmt" and platform == "tpu":
        try:
            t2, tps2, dt2, _ = measure_kernel(device_array, "pallas")
            secondary.append({
                "kernel": "pallas (bit-exact hit detection)",
                "trials_per_sec": round(tps2, 1),
                "full_sweep_s": round(dt2, 3),
                "best_dm": float(t2["DM"][t2.argbest()]),
            })
        except Exception as exc:
            log(f"secondary pallas metric skipped: {exc!r}")

    numpy_tps, linearity = measure_numpy_baseline(array, nsamp)

    result = {
        "metric": f"DM-trials/sec, {nchan}-chan x {nsamp}-sample filterbank, "
                  f"DM {DMMIN:.0f}-{DMMAX:.0f} ({table.nrows} trials), "
                  f"backend=jax ({platform})",
        "value": round(jax_tps, 2),
        "unit": "DM-trials/sec",
        "vs_baseline": round(jax_tps / numpy_tps, 2),
        "baseline": {
            "what": "single-core NumPy (reference semantics, efficient "
                    "roll-and-accumulate form), measured directly at the "
                    "full benchmark size (no nsamples extrapolation)",
            "dm_trials_per_sec": round(numpy_tps, 4),
            "linearity_check": round(linearity, 3),
        },
        "platform": platform,
        "kernel": measured_kernel,
        "best_dm": float(table["DM"][table.argbest()]),
        "injected_dm": INJECT_DM,
    }
    if headline_timing is not None:
        result["timing"] = headline_timing
        if not headline_timing.get("stable", True):
            # the stated variance bound was not reached within
            # MAX_REPEATS: the headline is whatever the tunnel allowed —
            # flag it rather than stamping it as a clean measurement
            degraded = "; ".join(filter(None, [
                degraded,
                f"timing unstable: cluster spread "
                f"{headline_timing['cluster_spread']:.1%} exceeds the "
                f"{SPREAD_BOUND:.0%} bound after "
                f"{len(headline_timing['times_s'])} repeats"]))
    if upload_s is not None:
        result["upload_s"] = round(upload_s, 1)
    if exact_hit_match is not None:
        result["exact_hit_match"] = exact_hit_match
    if secondary:
        result["secondary"] = secondary
    if os.environ.get("BENCH_DEGRADED"):
        degraded = degraded or "degraded run"
    if degraded:
        result["degraded"] = degraded
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
