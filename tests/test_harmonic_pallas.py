"""Identity of the fused Pallas harmonic-stack kernel vs the XLA chain
(ISSUE 17): host eager, under jit, and on the (4,2)/(2,4) CPU meshes.

The contract (see ``ops/harmonic_pallas.py``): discrete fields — the
winning harmonic depth and the peak's frequency bin — match the XLA
``normalize_power -> score_normalized_power`` chain EXACTLY; score
floats agree at tight ``allclose`` tolerance (XLA may fuse the
median-normalise divide differently between the two programs, a
data-dependent last-ulp row scale).  The same contract the autotuner's
:func:`~pulsarutils_tpu.tuning.autotune.harmonic_packs_match` harness
gates before caching a Pallas win.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from pulsarutils_tpu.ops.harmonic_pallas import (  # noqa: E402
    score_power_pallas,
    spectral_search_pallas,
)
from pulsarutils_tpu.ops.periodicity import (  # noqa: E402
    _spectral_chunk,
    normalize_power,
    power_spectrum,
    score_normalized_power,
    spectral_search,
)
from pulsarutils_tpu.parallel.mesh import (  # noqa: E402
    make_mesh,
    shard_map_compat,
)
from pulsarutils_tpu.precision import STRATEGIES  # noqa: E402

TSAMP = 1e-3
KEYS = ("freq", "power", "nharm", "log_sf", "sigma")

POLICIES = [None, "f32_compensated", "bf16_operand_f32_accum"]


def _plane(rows=16, t=4096, seed=11):
    """Noise plane with one strong tone (harmonics populated) and one
    weak tone — exercises different winning depths across rows."""
    rng = np.random.default_rng(seed)
    plane = rng.standard_normal((rows, t)).astype(np.float32)
    tt = np.arange(t) * TSAMP
    f0 = 200 / (t * TSAMP)  # exact bin
    plane[2] += 1.5 * np.square(np.sin(np.pi * f0 * tt))  # pulse train
    plane[7] += 0.4 * np.sin(2 * np.pi * f0 * tt)
    return plane


def _reference(power, t, policy):
    norm = normalize_power(power, xp=jnp)
    return score_normalized_power(norm, t, TSAMP, xp=jnp, policy=policy)


def _score_rtol(policy):
    if policy is None:
        return 1e-5
    return max(1e-5, STRATEGIES[policy].score_rtol * 1e-2)


def _assert_identity(got, want, policy, t=4096):
    np.testing.assert_array_equal(np.asarray(got["nharm"]),
                                  np.asarray(want["nharm"]))
    # discrete contract: the peak names the same BIN; the frequency
    # float itself may differ by one ulp across compiled programs
    # (jit rewrites arange/(t*tsamp) as a reciprocal multiply)
    scale = t * TSAMP
    np.testing.assert_array_equal(
        np.rint(np.asarray(got["freq"], dtype=np.float64) * scale),
        np.rint(np.asarray(want["freq"], dtype=np.float64) * scale))
    np.testing.assert_allclose(np.asarray(got["freq"]),
                               np.asarray(want["freq"]), rtol=1e-6)
    rtol = _score_rtol(policy)
    for col in ("power", "log_sf", "sigma"):
        np.testing.assert_allclose(np.asarray(got[col]),
                                   np.asarray(want[col]), rtol=rtol,
                                   atol=1e-6, err_msg=col)


@pytest.mark.parametrize("policy", POLICIES)
def test_host_identity(policy):
    plane = _plane()
    t = plane.shape[-1]
    power = power_spectrum(jnp.asarray(plane), xp=jnp)
    got = score_power_pallas(power, t, TSAMP, policy=policy,
                             interpret=True)
    want = _reference(power, t, policy)
    _assert_identity(got, want, policy)


def test_row_padding_identity():
    # 13 rows: one full 8-row block + a padded block whose benign
    # ones-rows must not perturb the real rows
    plane = _plane(rows=13, seed=23)
    t = plane.shape[-1]
    power = power_spectrum(jnp.asarray(plane), xp=jnp)
    got = score_power_pallas(power, t, TSAMP, interpret=True)
    want = _reference(power, t, None)
    _assert_identity(got, want, None)
    assert np.asarray(got["freq"]).shape == (13,)


@pytest.mark.parametrize("policy", [None, "f32_compensated"])
def test_jit_identity(policy):
    plane = _plane(seed=31)
    t = plane.shape[-1]

    @jax.jit
    def run(p):
        spec = score_power_pallas(power_spectrum(p, xp=jnp), t, TSAMP,
                                  policy=policy, interpret=True)
        return tuple(spec[k] for k in KEYS)

    got = dict(zip(KEYS, run(jnp.asarray(plane))))
    want = spectral_search(jnp.asarray(plane), TSAMP, xp=jnp,
                           policy=policy)
    _assert_identity(got, want, policy)


def test_band_limits_identity():
    plane = _plane(seed=47)
    t = plane.shape[-1]
    power = power_spectrum(jnp.asarray(plane), xp=jnp)
    fmin, fmax = 20.0, 220.0
    got = score_power_pallas(power, t, TSAMP, fmin=fmin, fmax=fmax,
                             interpret=True)
    norm = normalize_power(power, xp=jnp)
    want = score_normalized_power(norm, t, TSAMP, fmin=fmin, fmax=fmax,
                                  xp=jnp)
    _assert_identity(got, want, None)


def test_max_harmonics_truncates_depths():
    plane = _plane(seed=53)
    t = plane.shape[-1]
    power = power_spectrum(jnp.asarray(plane), xp=jnp)
    got = score_power_pallas(power, t, TSAMP, max_harmonics=4,
                             interpret=True)
    norm = normalize_power(power, xp=jnp)
    want = score_normalized_power(norm, t, TSAMP, max_harmonics=4,
                                  xp=jnp)
    _assert_identity(got, want, None)
    assert int(np.asarray(got["nharm"]).max()) <= 4


@pytest.mark.parametrize("shape", [(4, 2), (2, 4)])
@pytest.mark.parametrize("policy", [None, "f32_compensated"])
def test_mesh_identity(shape, policy):
    # per-row scoring shards cleanly over rows; the Pallas kernel runs
    # per shard (check_vma off: pallas_call outputs carry no vma)
    plane = _plane(rows=16, seed=61)
    t = plane.shape[-1]
    mesh = make_mesh(shape, ("dm", "chan"))

    def local(p):
        spec = score_power_pallas(power_spectrum(p, xp=jnp), t, TSAMP,
                                  policy=policy, interpret=True)
        return tuple(spec[k] for k in KEYS)

    fn = shard_map_compat(
        local, mesh=mesh, in_specs=(P("dm", None),),
        out_specs=tuple(P("dm") for _ in KEYS), check_vma=False)
    got = dict(zip(KEYS, jax.jit(fn)(jnp.asarray(plane))))
    want = spectral_search(jnp.asarray(plane), TSAMP, xp=jnp,
                           policy=policy)
    _assert_identity(got, want, policy)


def test_spectral_search_pallas_full_chain():
    plane = _plane(seed=71)
    got = spectral_search_pallas(plane, TSAMP)
    want = spectral_search(jnp.asarray(plane), TSAMP, xp=jnp)
    _assert_identity(got, want, None)


def test_spectral_chunk_pallas_kernel_spec():
    # the production dispatch seam: kernel="pallas" returns the host
    # dict contract (_SPEC_KEYS, int32 nharm) matching kernel="xla"
    plane = _plane(seed=83)
    xla = _spectral_chunk(jnp.asarray(plane), TSAMP, 16, None, None, jnp,
                          kernel="xla")
    pal = _spectral_chunk(jnp.asarray(plane), TSAMP, 16, None, None, jnp,
                          kernel="pallas")
    assert pal["nharm"].dtype == np.int32
    _assert_identity(pal, xla, None)


def test_spectral_chunk_auto_resolves_static_xla(monkeypatch):
    # PUTPU_AUTOTUNE=off: "auto" must be the static "xla" — no pallas
    # dispatch, byte-identical to the explicit spelling
    monkeypatch.setenv("PUTPU_AUTOTUNE", "off")
    from pulsarutils_tpu.tuning.autotune import resolve_harmonic_kernel

    assert resolve_harmonic_kernel(16, 4096, TSAMP) == "xla"
    plane = _plane(seed=97)
    auto = _spectral_chunk(jnp.asarray(plane), TSAMP, 16, None, None, jnp,
                           kernel="auto")
    xla = _spectral_chunk(jnp.asarray(plane), TSAMP, 16, None, None, jnp,
                          kernel="xla")
    for k in KEYS:
        np.testing.assert_array_equal(auto[k], xla[k], err_msg=k)


def test_bf16_policy_needs_jax_path():
    with pytest.raises(ValueError, match="bfloat16"):
        score_normalized_power(np.ones((2, 64)), 64, TSAMP, xp=np,
                               policy="bf16_operand_f32_accum")
