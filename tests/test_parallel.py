"""Sharded sweep + ring streaming on the 8-virtual-device CPU mesh.

The "fake cluster" tests of SURVEY §4: same results as the single-device
path, through real shard_map/psum/ppermute programs.
"""
import numpy as np
import pytest

from pulsarutils_tpu import dedispersion_search, simulate_test_data
from pulsarutils_tpu.ops.dedisperse import dedisperse_batch_numpy
from pulsarutils_tpu.ops.plan import dedispersion_plan, dedispersion_shifts_batch
from pulsarutils_tpu.parallel.mesh import (
    balanced_2d_mesh,
    make_mesh,
    pad_to_multiple,
)
from pulsarutils_tpu.parallel.sharded import sharded_dedispersion_search
from pulsarutils_tpu.parallel.stream import (
    iter_chunk_starts,
    plan_chunks,
    ring_dedisperse,
    stream_search,
)


@pytest.fixture(scope="module")
def sim():
    return simulate_test_data(150, rng=77)


def test_make_mesh_shapes():
    import jax

    mesh = make_mesh()
    assert mesh.shape["dm"] == len(jax.devices())
    mesh2 = make_mesh((4, 2))
    assert mesh2.shape == {"dm": 4, "chan": 2}
    mesh3 = make_mesh((-1, 2))
    assert mesh3.shape["dm"] == len(jax.devices()) // 2
    with pytest.raises(ValueError):
        make_mesh((64, 2))


def test_pad_to_multiple():
    x = np.arange(10).reshape(5, 2)
    padded, n = pad_to_multiple(x, 0, 4, mode="edge")
    assert padded.shape == (8, 2) and n == 5
    assert np.all(padded[5:] == x[-1])
    same, n2 = pad_to_multiple(x, 0, 5)
    assert same is x and n2 == 5


def test_sharded_matches_single_device(sim):
    array, header = sim
    args = (array, 100, 200., header["fbottom"], header["bandwidth"],
            header["tsamp"])
    t_ref = dedispersion_search(*args, backend="jax")
    for shape in [(8, 1), (4, 2), (2, 4), (1, 8)]:
        mesh = make_mesh(shape)
        t_sh = sharded_dedispersion_search(*args, mesh=mesh)
        assert t_sh.argbest() == t_ref.argbest(), shape
        assert np.allclose(t_sh["snr"], t_ref["snr"], rtol=1e-4), shape
        assert np.array_equal(t_sh["rebin"], t_ref["rebin"]), shape
    assert np.isclose(t_ref["DM"][t_ref.argbest()], 150, atol=1)


def test_sharded_plane_capture(sim):
    array, header = sim
    mesh = balanced_2d_mesh()
    t_sh, plane = sharded_dedispersion_search(
        array, 100, 200., header["fbottom"], header["bandwidth"],
        header["tsamp"], mesh=mesh, capture_plane=True)
    _, plane_ref = dedispersion_search(
        array, 100, 200., header["fbottom"], header["bandwidth"],
        header["tsamp"], backend="jax", capture_plane=True)
    assert np.allclose(np.asarray(plane), plane_ref, atol=1e-3)


def test_sharded_with_uneven_sizes():
    # trial count and channel count not divisible by the mesh axes
    array, header = simulate_test_data(120, nchan=100, nsamples=512, rng=3)
    mesh = make_mesh((4, 2))
    t_sh = sharded_dedispersion_search(
        array, 100, 140., header["fbottom"], header["bandwidth"],
        header["tsamp"], mesh=mesh)
    t_ref = dedispersion_search(
        array, 100, 140., header["fbottom"], header["bandwidth"],
        header["tsamp"], backend="numpy")
    assert t_sh.nrows == t_ref.nrows
    assert t_sh.argbest() == t_ref.argbest()
    assert np.isclose(t_sh["DM"][t_sh.argbest()], 120, atol=1)


def test_ring_dedisperse_matches_global(sim):
    array, header = sim
    mesh = make_mesh((8,), ("time",))
    dms = dedispersion_plan(array.shape[0], 100, 200., header["fbottom"],
                            header["bandwidth"], header["tsamp"])[:16]
    plane_ring = np.asarray(ring_dedisperse(
        array, dms, header["fbottom"], header["bandwidth"], header["tsamp"],
        mesh))
    shifts = dedispersion_shifts_batch(dms, array.shape[0],
                                       header["fbottom"],
                                       header["bandwidth"], header["tsamp"])
    plane_ref = dedisperse_batch_numpy(array, shifts)
    assert plane_ring.shape == plane_ref.shape
    assert np.allclose(plane_ring, plane_ref, rtol=1e-4, atol=1e-3)


def test_ring_multihop_span_larger_than_slice():
    # span (~229 samples at DM 150) far exceeds the per-device slice of 32:
    # the ring must take multiple hops and still match the global result
    array, header = simulate_test_data(150, nchan=16, nsamples=256, rng=4)
    mesh = make_mesh((8,), ("time",))
    dms = np.array([140.0, 150.0, 160.0])
    plane_ring = np.asarray(ring_dedisperse(
        array, dms, header["fbottom"], header["bandwidth"], header["tsamp"],
        mesh))
    shifts = dedispersion_shifts_batch(dms, 16, header["fbottom"],
                                       header["bandwidth"], header["tsamp"])
    plane_ref = dedisperse_batch_numpy(array, shifts)
    assert np.allclose(plane_ring, plane_ref, rtol=1e-4, atol=1e-3)


def test_ring_rejects_span_larger_than_sequence():
    array, header = simulate_test_data(150, nchan=32, nsamples=256, rng=4)
    mesh = make_mesh((8,), ("time",))
    # huge DM -> intra-band span exceeds the whole chunk
    with pytest.raises(ValueError, match="exceeds the sequence length"):
        ring_dedisperse(array, [3000.0], header["fbottom"],
                        header["bandwidth"], header["tsamp"], mesh)


def test_plan_chunks_physics():
    plan = plan_chunks(nsamples=1_000_000, sample_time=0.0005, dmmin=300,
                       dmmax=400, start_freq=1200., stop_freq=1400.,
                       foff=200. / 1024)
    from pulsarutils_tpu.ops.plan import delta_delay, dm_broadening
    expected_delay = delta_delay(400, 1200., 1400.)
    base = max(int(expected_delay / 0.0005) * 2, 128)
    # step is rounded UP to the 1024-sample tile so the TPU transform
    # never zero-pads (which would disable the noise certificate); the
    # physics guarantee (chunk >= 2x band-crossing delay) is preserved
    assert plan.step == -(-base // 1024) * 1024
    assert plan.step >= base
    assert plan.hop == plan.step // 2
    # resampling targets dm_broadening(dmmin)/10
    dt = dm_broadening(300, 1200., 200. / 1024)
    assert plan.resample == int(np.rint(max(dt / 10, 0.0005) / 0.0005))


def test_iter_chunk_starts_overlap_and_tail():
    from pulsarutils_tpu.parallel.stream import ChunkPlan
    plan = ChunkPlan(step=100, hop=50, resample=1, sample_time=1.0)
    starts = list(iter_chunk_starts(320, plan))
    # last start yielding >= 50 samples is 270; 300 leaves only 20
    assert starts == [0, 50, 100, 150, 200, 250]
    # tmin skips early chunks
    starts_t = list(iter_chunk_starts(320, plan, tmin=120, sample_time=1.0))
    assert starts_t == [150, 200, 250]
    # a final half-chunk fragment wholly contained in the previous
    # full-length chunk is skipped (it re-searches covered data at a
    # fresh compile shape — round 5)
    assert list(iter_chunk_starts(300, plan)) == [0, 50, 100, 150, 200]
    # ... but kept when it is the ONLY chunk covering its span
    assert list(iter_chunk_starts(50, plan)) == [0]
    assert list(iter_chunk_starts(300, plan, tmin=250,
                                  sample_time=1.0)) == [250]


def test_stream_search_finds_pulse_in_right_chunk():
    # long series with one pulse; 50% overlap chunking must localise it
    rng = np.random.default_rng(5)
    nchan, nsamples = 32, 4096
    array = np.abs(rng.normal(0, 0.5, (nchan, nsamples)))
    array[:, 2500] += 2.0
    from pulsarutils_tpu.models.simulate import disperse_array
    array = disperse_array(array, 150, 1200., 200., 0.0005)

    step, hop = 1024, 512
    chunks = [(s, array[:, s:s + step]) for s in range(0, nsamples - hop, hop)
              if array[:, s:s + step].shape[1] == step]
    results, hits = stream_search(chunks, 100, 200., 1200., 200., 0.0005,
                                  snr_threshold=6.0)
    assert len(hits) >= 1
    hit_starts = [h[0] for h in hits]
    assert any(s <= 2500 < s + step for s in hit_starts)
    # at least one hit (the chunk fully containing the pulse) nails the DM;
    # overlapping neighbours see a wrapped pulse and may be slightly off
    assert any(np.isclose(best["DM"], 150, atol=2) for _, _, best in hits)


def test_sharded_search_pallas_kernel_matches_numpy():
    """Per-shard Pallas kernel inside shard_map (interpret mode on the
    virtual CPU mesh) must reproduce the NumPy reference hits."""
    from pulsarutils_tpu.ops.search import dedispersion_search

    array, header = simulate_test_data(150, nchan=32, nsamples=1024, rng=3)
    args = (100, 200., header["fbottom"], header["bandwidth"],
            header["tsamp"])
    mesh = make_mesh((4, 2), ("dm", "chan"))
    t_ref = dedispersion_search(array, *args, backend="numpy")
    t_pl = sharded_dedispersion_search(array, *args, mesh=mesh,
                                       kernel="pallas")
    assert t_pl.argbest() == t_ref.argbest()
    np.testing.assert_allclose(np.asarray(t_pl["snr"]),
                               np.asarray(t_ref["snr"]), rtol=2e-3,
                               atol=2e-3)


class TestMultihost:
    """Single-process degradation of the multi-host helpers (the real
    multi-process path shares every code line except jax.distributed
    bring-up, which needs actual multiple hosts)."""

    def test_initialize_single_process_is_safe_and_idempotent(self):
        from pulsarutils_tpu.parallel import multihost

        assert multihost.initialize() is False
        assert multihost.initialize() is False  # cached, no re-init

    def test_pod_mesh_on_fake_cluster(self):
        from pulsarutils_tpu.parallel import multihost
        from pulsarutils_tpu.parallel.sharded import (
            sharded_dedispersion_search,
        )

        mesh = multihost.pod_mesh()
        assert set(mesh.axis_names) == {"dm", "chan"}
        assert mesh.devices.size == 8  # conftest's virtual CPU devices
        array, header = simulate_test_data(150, nchan=16, nsamples=512,
                                           rng=21)
        t = sharded_dedispersion_search(
            array, 100, 200., header["fbottom"], header["bandwidth"],
            header["tsamp"], mesh=mesh)
        assert abs(float(t["DM"][t.argbest()]) - 150) < 2

    def test_process_local_slice_partitions_exactly(self):
        from pulsarutils_tpu.parallel.multihost import process_local_slice

        n, p = 103, 4
        spans = [process_local_slice(n, p, i) for i in range(p)]
        assert spans[0][0] == 0 and spans[-1][1] == n
        for (a, b), (c, d) in zip(spans, spans[1:]):
            assert b == c


class TestShardedFdmt:
    """DM-sliced sharded FDMT (parallel/sharded_fdmt.py)."""

    def test_matches_single_device_fdmt(self):
        from pulsarutils_tpu.models.simulate import simulate_test_data
        from pulsarutils_tpu.ops.search import dedispersion_search
        from pulsarutils_tpu.parallel.mesh import make_mesh
        from pulsarutils_tpu.parallel.sharded_fdmt import sharded_fdmt_search

        array, header = simulate_test_data(150, nchan=64, nsamples=4096,
                                           rng=31)
        args = (100, 200.0, header["fbottom"], header["bandwidth"],
                header["tsamp"])
        mesh = make_mesh((8,), ("dm",))
        t_sh = sharded_fdmt_search(array, *args, mesh=mesh)
        t_ref = dedispersion_search(array, *args, backend="jax",
                                    kernel="fdmt")
        assert t_sh.nrows == t_ref.nrows
        assert np.array_equal(t_sh["DM"], t_ref["DM"])
        # every device slice must reproduce the single-device transform's
        # scores: same tracks, same summation order, merely delay-pruned
        assert np.allclose(t_sh["snr"], t_ref["snr"], rtol=1e-4, atol=1e-4)
        assert np.array_equal(t_sh["rebin"], t_ref["rebin"])
        assert t_sh.argbest() == t_ref.argbest()
        assert np.isclose(t_sh["DM"][t_sh.argbest()], 150, atol=1.5)

    def test_odd_device_counts_and_narrow_ranges(self):
        from pulsarutils_tpu.models.simulate import simulate_test_data
        from pulsarutils_tpu.parallel.mesh import make_mesh
        from pulsarutils_tpu.parallel.sharded_fdmt import (
            sharded_fdmt_search,
            slice_delay_range,
        )

        # uneven split arithmetic
        slices = slice_delay_range(10, 20, 4)
        assert slices[0][0] == 10 and slices[-1][1] == 20
        assert all(lo <= hi for lo, hi in slices)
        assert sum(hi - lo + 1 for lo, hi in slices) == 11
        with pytest.raises(ValueError, match="cannot fill"):
            slice_delay_range(5, 6, 8)

        # a range that does not divide evenly across devices still works
        array, header = simulate_test_data(150, nchan=32, nsamples=2048,
                                           rng=32)
        mesh = make_mesh((8,), ("dm",))
        t_sh = sharded_fdmt_search(array, 130, 170.0, header["fbottom"],
                                   header["bandwidth"], header["tsamp"],
                                   mesh=mesh)
        assert abs(float(t_sh["DM"][t_sh.argbest()]) - 150) <= 2.0

    def test_pallas_traced_tables_interpret_mode(self):
        # the traced-table merge kernel (runtime schedules riding
        # scalar-prefetch, shared static k_tiles bound) must agree with
        # the XLA merge — exercised in interpret mode so CPU CI covers
        # the path that otherwise first runs on real TPU hardware
        from pulsarutils_tpu.models.simulate import simulate_test_data
        from pulsarutils_tpu.parallel.mesh import make_mesh
        from pulsarutils_tpu.parallel.sharded_fdmt import sharded_fdmt_search

        array, header = simulate_test_data(150, nchan=16, nsamples=1024,
                                           rng=33)
        args = (120, 180.0, header["fbottom"], header["bandwidth"],
                header["tsamp"])
        mesh = make_mesh((4,), ("dm",))
        t_xla = sharded_fdmt_search(array, *args, mesh=mesh,
                                    use_pallas=False)
        t_pl = sharded_fdmt_search(array, *args, mesh=mesh,
                                   use_pallas=True)
        assert np.allclose(t_pl["snr"], t_xla["snr"], rtol=1e-5, atol=1e-5)
        assert t_pl.argbest() == t_xla.argbest()

    def test_sharded_hybrid_matches_numpy_hits(self):
        # multi-device hybrid: coarse sharded FDMT + sharded exact
        # rescore must land on the NumPy reference's argbest row
        from pulsarutils_tpu.models.simulate import simulate_test_data
        from pulsarutils_tpu.ops.search import dedispersion_search
        from pulsarutils_tpu.parallel.mesh import make_mesh
        from pulsarutils_tpu.parallel.sharded_fdmt import (
            sharded_hybrid_search,
        )

        array, header = simulate_test_data(150, nchan=64, nsamples=4096,
                                           signal=2.0, noise=0.4, rng=51)
        args = (100, 200.0, header["fbottom"], header["bandwidth"],
                header["tsamp"])
        mesh = make_mesh((4, 2), ("dm", "chan"))
        t_h = sharded_hybrid_search(array, *args, mesh=mesh)
        t_np = dedispersion_search(array, *args, backend="numpy")
        assert t_h.nrows == t_np.nrows
        best = t_np.argbest("snr")
        assert t_h.argbest("snr") == best
        assert bool(t_h["exact"][best])
        assert t_h["DM"][best] == t_np["DM"][best]
        assert t_h["rebin"][best] == t_np["rebin"][best]
        assert np.isclose(t_h["snr"][best], t_np["snr"][best], rtol=1e-3)
