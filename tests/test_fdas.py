"""Fourier-domain acceleration/jerk search (ISSUE 16): z/w-response
template accuracy against quadrature and the time-domain stretch
oracle, the grid-cap telemetry, fdas host/jit/mesh cell-for-cell
identity, the measured accel-backend autotuner pair, and the
jerk-axis plumbing through the driver, service intake and fleet."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
from pulsarutils_tpu.models.simulate import simulate_accel_pulsar_data
from pulsarutils_tpu.obs.metrics import REGISTRY
from pulsarutils_tpu.ops import zresponse
from pulsarutils_tpu.ops.zresponse import (MAX_HALF_WIDTH, Z_SMALL,
                                           bank_for_trials, fresnel,
                                           z_response, zw_response)
from pulsarutils_tpu.periodicity.accel import (C_M_S, accel_grid,
                                               accel_search, jerk_grid,
                                               trial_product)
from pulsarutils_tpu.periodicity.driver import periodicity_search
from pulsarutils_tpu.periodicity.fdas import fdas_search
from pulsarutils_tpu.tuning import autotune
from pulsarutils_tpu.tuning.cache import TuneCache

TSAMP = 0.0005
NSAMPLES = 16384
NDM = 6
#: the injected tone sits exactly on Fourier bin K0 (~350 Hz) — high
#: enough that the accel/jerk grids below are non-degenerate (z ~ 19,
#: w ~ 40 bins at the grid edges), low enough that the stretch
#: backend's resampling scalloping stays small
K0 = int(round(0.175 * NSAMPLES))
F0 = K0 / (NSAMPLES * TSAMP)
ACCELS = np.linspace(-2.0e5, 2.0e5, 9)
JERKS = np.linspace(-5.0e4, 5.0e4, 5)
#: synthetic_accel_plane injects at DM row ndm // 3
INJ_DM, INJ_A, INJ_J = NDM // 3, 6, 3
KW = dict(jerks=JERKS, max_harmonics=1, fmax=1.25 * F0, topk=8)


def _counter(name, **labels):
    for rec in REGISTRY.snapshot():
        if rec["name"] == name and rec.get("labels", {}) == labels:
            return rec["value"]
    return 0


@pytest.fixture(scope="module")
def plane():
    return autotune.synthetic_accel_plane(
        NDM, NSAMPLES, TSAMP, ACCELS[INJ_A], jerk=JERKS[INJ_J])


@pytest.fixture(scope="module")
def host_tables(plane):
    """(time_stretch, fdas) host-float64 reference tables of the same
    injected plane — the cross-backend oracle pair."""
    t_stretch = accel_search(plane, TSAMP, ACCELS, xp=np, **KW)
    t_fdas = fdas_search(plane, TSAMP, ACCELS, xp=np, **KW)
    return t_stretch, t_fdas


# ---------------------------------------------------------------------------
# Fresnel integrals (no scipy in this repo: series + asymptotic branch)
# ---------------------------------------------------------------------------

_trapz = getattr(np, "trapezoid", np.trapz)


def _fresnel_reference(x, n=400_001):
    t = np.linspace(0.0, float(x), n)
    arg = 0.5 * np.pi * t * t
    return _trapz(np.cos(arg), t), _trapz(np.sin(arg), t)


class TestFresnel:
    def test_accuracy_against_quadrature(self):
        # straddle the series/asymptotic split (3.2) on purpose
        for x in (0.3, 1.7, 3.19, 3.2, 3.21, 5.0, 8.0):
            c_ref, s_ref = _fresnel_reference(x)
            c, s = fresnel(x)
            assert c == pytest.approx(c_ref, abs=1e-6), x
            assert s == pytest.approx(s_ref, abs=1e-6), x

    def test_odd_symmetry_and_large_x_limit(self):
        x = np.array([-6.0, -2.0, -0.5, 0.0, 0.5, 2.0, 6.0])
        c, s = fresnel(x)
        np.testing.assert_allclose(c, -c[::-1], atol=1e-15)
        np.testing.assert_allclose(s, -s[::-1], atol=1e-15)
        assert c[3] == 0.0 and s[3] == 0.0
        # C, S -> 1/2 with an O(1/x) oscillatory tail
        c_inf, s_inf = fresnel(500.0)
        assert abs(c_inf - 0.5) < 1.0 / (np.pi * 500.0)
        assert abs(s_inf - 0.5) < 1.0 / (np.pi * 500.0)


# ---------------------------------------------------------------------------
# z/w responses: closed form vs sampled chirp, branch seams, the bank
# ---------------------------------------------------------------------------

class TestResponses:
    def test_speed_of_light_pinned_to_accel_module(self):
        # the ops layer cannot import upward, so the constant is
        # duplicated — this pin is the documented substitute
        assert zresponse._C_M_S == C_M_S

    def test_zero_drift_response_is_a_delta(self):
        q = np.arange(-4, 5, dtype=np.float64)
        a = z_response(0.0, q)
        assert abs(a[4]) == pytest.approx(1.0, abs=1e-12)
        off = np.abs(np.delete(a, 4))
        assert off.max() < 1e-12          # sinc is exactly 0 at ints

    def test_closed_form_matches_sampled_chirp(self):
        # the w=0 Fresnel closed form against the numerical FFT path
        # (the doc'd seam property), spanning BOTH closed-form regimes
        # and the small-|z| series branch
        q = np.arange(-20, 21)
        for z in (5.0e-4, 2.0e-3, 5.0, 37.3):
            a_closed = z_response(z, q.astype(np.float64))
            a_chirp = zw_response(z, 0.0, q)
            np.testing.assert_allclose(a_closed, a_chirp, atol=5e-4,
                                       err_msg=f"z={z}")

    def test_small_z_branch_is_continuous(self):
        q = np.arange(-10, 11, dtype=np.float64)
        below = z_response(Z_SMALL * 0.999, q)
        above = z_response(Z_SMALL * 1.001, q)
        np.testing.assert_allclose(below, above, atol=1e-4)
        # and the negative-z conjugate symmetry across the seam too
        np.testing.assert_allclose(z_response(-Z_SMALL * 1.001, q),
                                   np.conj(z_response(Z_SMALL * 1.001,
                                                      -q)), atol=1e-12)

    def test_zw_response_rejects_fractional_bins(self):
        with pytest.raises(ValueError, match="integer"):
            zw_response(3.0, 10.0, np.array([0.5]))

    def test_bank_zero_trial_is_delta_row(self):
        tab = bank_for_trials((0.0,), (0.0,), 64, TSAMP, NSAMPLES)
        row = tab["bank"][tab["zero_index"]]
        h = tab["half_width"]
        assert np.argmax(np.abs(row)) == h
        assert abs(row[h]) == pytest.approx(1.0, abs=1e-12)
        np.testing.assert_array_equal(tab["centers"], [0])
        # gather origins are the spectrum bins themselves
        np.testing.assert_array_equal(tab["gidx"][0], np.arange(64))

    def test_bank_half_width_cap_warns(self):
        with pytest.warns(UserWarning, match="half-width"):
            tab = bank_for_trials((5.0e6,), (0.0,), 8193, TSAMP,
                                  NSAMPLES)
        assert tab["half_width"] == MAX_HALF_WIDTH


# ---------------------------------------------------------------------------
# trial grids: physics spacing, the warn+count cap, accel-major order
# ---------------------------------------------------------------------------

class TestGrids:
    def test_jerk_grid_properties(self):
        g = jerk_grid(1.0e5, TSAMP, NSAMPLES)
        assert g[0] == -1.0e5 and g[-1] == 1.0e5
        assert 0.0 in g and g.size % 2 == 1
        np.testing.assert_allclose(g, -g[::-1])
        assert jerk_grid(0.0, TSAMP, NSAMPLES).tolist() == [0.0]
        assert jerk_grid(-1.0, TSAMP, NSAMPLES).tolist() == [0.0]

    def test_grid_caps_warn_and_count(self):
        # the no-silent-caps satellite: a binding max_trials is a
        # warning plus a putpu_period_grid_capped_total tick per axis
        a0 = _counter("putpu_period_grid_capped_total", axis="accel")
        with pytest.warns(UserWarning, match="max_trials"):
            g = accel_grid(1.0e9, 0.001, 1 << 16, max_trials=11)
        assert g.size == 11 and 0.0 in g
        assert _counter("putpu_period_grid_capped_total",
                        axis="accel") == a0 + 1
        j0 = _counter("putpu_period_grid_capped_total", axis="jerk")
        with pytest.warns(UserWarning, match="max_trials"):
            gj = jerk_grid(1.0e9, 0.001, 1 << 16, max_trials=11)
        assert gj.size == 11 and 0.0 in gj
        assert _counter("putpu_period_grid_capped_total",
                        axis="jerk") == j0 + 1

    def test_trial_product_is_accel_major(self):
        ta, tj = trial_product(np.array([1.0, 2.0]),
                               np.array([10.0, 20.0, 30.0]))
        assert ta.tolist() == [1.0, 1.0, 1.0, 2.0, 2.0, 2.0]
        assert tj.tolist() == [10.0, 20.0, 30.0, 10.0, 20.0, 30.0]
        ta0, tj0 = trial_product(np.array([1.0, 2.0]), None)
        assert ta0.tolist() == [1.0, 2.0] and tj0.tolist() == [0.0, 0.0]


# ---------------------------------------------------------------------------
# the oracle: fdas vs time-domain stretch on the injected plane
# ---------------------------------------------------------------------------

class TestOracle:
    def test_both_backends_recover_the_injected_cell(self, host_tables):
        for name, tbl in zip(("time_stretch", "fdas"), host_tables):
            assert int(tbl["dm_index"][0]) == INJ_DM, name
            assert int(tbl["accel_index"][0]) == INJ_A, name
            assert int(tbl["jerk_index"][0]) == INJ_J, name
            assert abs(int(tbl["freq_bin"][0]) - K0) <= 1, name

    def test_cross_backend_tables_match(self, host_tables):
        # the autotuner's own equivalence contract, asserted directly:
        # discrete fields of the top cell exact, sigma to a few percent
        t_stretch, t_fdas = host_tables
        assert autotune.accel_tables_match(t_stretch, t_fdas)
        assert np.isclose(float(t_fdas["sigma"][0]),
                          float(t_stretch["sigma"][0]),
                          rtol=autotune.ACCEL_SIGMA_RTOL)

    def test_zero_trial_is_plain_spectral_scoring(self, plane):
        # accels=[0] means the delta template: the fdas correlation is
        # the raw spectrum and both formulations reduce to the same
        # spectral scoring, float64-exactly
        kw = dict(max_harmonics=4, fmin=4.0 / (NSAMPLES * TSAMP),
                  topk=8, xp=np)
        p32 = np.asarray(plane, dtype=np.float32)  # both paths see the
        t_f = fdas_search(p32, TSAMP, [0.0], **kw)  # same input values
        t_s = accel_search(p32, TSAMP, [0.0], **kw)
        for k in ("dm_index", "accel_index", "jerk_index", "freq_bin",
                  "nharm"):
            np.testing.assert_array_equal(t_f[k], t_s[k], err_msg=k)
        # the two host paths round intermediates differently at the
        # float32 level (the stretch path mirrors the device program's
        # dtype discipline) — discrete fields exact, floats to the
        # repo-wide float tolerance
        for k in ("freq", "power", "log_sf", "sigma"):
            np.testing.assert_allclose(t_f[k], t_s[k], rtol=1e-6,
                                       atol=1e-6, err_msg=k)

    def test_fdas_metrics_tick(self, plane):
        t0 = _counter("putpu_fdas_trials_total")
        b0 = _counter("putpu_fdas_bank_entries_total")
        fdas_search(plane[:2], TSAMP, np.array([0.0, ACCELS[INJ_A]]),
                    max_harmonics=1, fmax=1.25 * F0, topk=4, xp=np)
        assert _counter("putpu_fdas_trials_total") == t0 + 4
        assert _counter("putpu_fdas_bank_entries_total") > b0


# ---------------------------------------------------------------------------
# execution-path identity: host / jit / (4,2) and (2,4) meshes
# ---------------------------------------------------------------------------

def _assert_tables_identical(tables, ref):
    for name, tbl in tables.items():
        for k in ("dm_index", "accel_index", "jerk_index", "freq_bin",
                  "nharm"):
            np.testing.assert_array_equal(
                tbl[k], ref[k], err_msg=f"{name} diverges on {k}")
        np.testing.assert_allclose(tbl["sigma"], ref["sigma"],
                                   rtol=5e-3, atol=5e-3, err_msg=name)


class TestPathIdentity:
    def test_fdas_host_jit_mesh_tables_identical(self, plane,
                                                 host_tables):
        from pulsarutils_tpu.parallel.mesh import make_mesh

        _, t_np = host_tables
        t_jit = fdas_search(plane, TSAMP, ACCELS, xp=jnp, **KW)
        tables = {"np": t_np}
        for shape in [(4, 2), (2, 4)]:
            mesh = make_mesh(shape, ("dm", "chan"))
            tables[f"mesh{shape}"] = fdas_search(
                plane, TSAMP, ACCELS, xp=jnp, mesh=mesh, **KW)
        _assert_tables_identical(tables, t_jit)

    def test_stretch_jerk_host_jit_mesh_identical(self, plane,
                                                  host_tables):
        from pulsarutils_tpu.parallel.mesh import make_mesh

        t_np, _ = host_tables
        t_jit = accel_search(plane, TSAMP, ACCELS, xp=jnp, **KW)
        mesh = make_mesh((4, 2), ("dm", "chan"))
        t_mesh = accel_search(plane, TSAMP, ACCELS, xp=jnp, mesh=mesh,
                              **KW)
        _assert_tables_identical({"np": t_np, "mesh": t_mesh}, t_jit)


# ---------------------------------------------------------------------------
# the measured accel-backend pair
# ---------------------------------------------------------------------------

def _match_table(sigma=30.0, accel_index=6, jerk_index=3, freq=350.0):
    return {"dm_index": np.array([2]), "accel_index":
            np.array([accel_index]), "jerk_index": np.array([jerk_index]),
            "nharm": np.array([1]), "freq": np.array([freq]),
            "sigma": np.array([sigma])}


class TestBackendTuning:
    @pytest.fixture(autouse=True)
    def _hermetic_tuner(self, monkeypatch):
        monkeypatch.delenv("PUTPU_AUTOTUNE", raising=False)
        monkeypatch.delenv("PUTPU_AUTOTUNE_MIN", raising=False)
        prev = autotune.set_tuner(
            autotune.KernelTuner(cache=TuneCache(None)))
        yield
        autotune.set_tuner(prev)

    def test_accel_tables_match_rules(self):
        ref = _match_table()
        assert not autotune.accel_tables_match(None, ref)
        assert not autotune.accel_tables_match(ref, None)
        empty = {k: v[:0] for k, v in ref.items()}
        assert not autotune.accel_tables_match(ref, empty)
        assert autotune.accel_tables_match(ref, _match_table(sigma=31.0))
        assert not autotune.accel_tables_match(
            ref, _match_table(accel_index=5))
        assert not autotune.accel_tables_match(
            ref, _match_table(jerk_index=2))
        assert not autotune.accel_tables_match(ref,
                                               _match_table(sigma=45.0))
        assert not autotune.accel_tables_match(ref,
                                               _match_table(freq=351.0))

    def test_below_floor_resolves_to_time_stretch(self):
        # the default 2^25-element floor: every tier-1-scale geometry
        # resolves statically with zero measurements
        mark = autotune.decision_seq()
        got = autotune.resolve_accel_backend(
            NDM, NSAMPLES, TSAMP, ACCELS, jerks=JERKS, max_harmonics=1,
            fmax=1.25 * F0)
        assert got == "time_stretch"
        (dec,) = autotune.decisions_since(mark)
        assert dec["source"] == "static" and "floor" in dec["reason"]

    def test_forced_floor_measures_the_pair_once(self):
        autotune.set_tuner(autotune.KernelTuner(
            cache=TuneCache(None), mode="on", min_elements=0, reps=1))
        mark = autotune.decision_seq()
        kw = dict(jerks=JERKS, max_harmonics=1, fmax=1.25 * F0)
        got = autotune.resolve_accel_backend(NDM, NSAMPLES, TSAMP,
                                             ACCELS, **kw)
        assert got in ("time_stretch", "fdas")
        # the decision ledger is process-global, so a background thread
        # from an earlier test can land an unrelated (non-accel) measured
        # decision in our window while the floor-0 tuner is installed —
        # assert only over the "-accel|" namespace this test contracts
        def accel_decisions(since):
            return [d for d in autotune.decisions_since(since)
                    if "-accel|" in d["key"]]

        (dec,) = accel_decisions(mark)
        assert dec["kernel"] == got and dec["source"] == "measured"
        # second resolve at the same geometry: memory hit, no decision
        mark = autotune.decision_seq()
        assert autotune.resolve_accel_backend(NDM, NSAMPLES, TSAMP,
                                              ACCELS, **kw) == got
        assert accel_decisions(mark) == []

    def test_resolve_equiv_override_gates_candidates(self):
        # the generic harness: a caller-supplied equivalence matcher
        # replaces hits_match and an inequivalent-but-faster candidate
        # is rejected
        def measurer(kernel, run, reps):
            return {"a": 0.4, "b": 0.001}[kernel]

        tuner = autotune.KernelTuner(cache=TuneCache(None), mode="on",
                                     min_elements=0, measurer=measurer)
        runners = {"a": lambda: {"tag": "a"}, "b": lambda: {"tag": "b"}}
        got = tuner.resolve(backend="cpu", nchan=4, nsamples=4, ndm=4,
                            dtype="float32", candidates=["a", "b"],
                            static="a", runner_factory=lambda: runners,
                            equiv=lambda ref, cand:
                                cand["tag"] == ref["tag"])
        assert got == "a"


# ---------------------------------------------------------------------------
# end to end: the jerk-enabled sweep through the driver, resume, fleet
# ---------------------------------------------------------------------------

E2E_TSAMP, E2E_NSAMPLES, E2E_NCHAN = 0.0005, 16384, 32
E2E_DM = 150.0
E2E_F0 = 492 / (E2E_NSAMPLES * E2E_TSAMP)
E2E_ACCEL, E2E_ACCEL_MAX = 4.5e5, 9.0e5
#: ~48 Fourier bins of quadratic drift at E2E_F0 — the zero-jerk trial
#: demonstrably smears it, and the accel span is narrow enough that no
#: (accel, 0) cell can linearly compensate the cubic track (the
#: accel/jerk degeneracy: a wide accel grid offers a quadratic that
#: fits the cubic to within a fraction of a cycle).  The injected jerk
#: sits exactly on grid index 3 of linspace(-E2E_JERK_MAX,
#: E2E_JERK_MAX, 5)
E2E_JERK, E2E_JERK_MAX = 4.4e5, 8.8e5
E2E_JOB = dict(dmmin=130.0, dmmax=170.0, accel_max=E2E_ACCEL_MAX,
               n_accel=5, jerk_max=E2E_JERK_MAX, n_jerk=5,
               sigma_threshold=8.0, chunk_length=4096 * E2E_TSAMP,
               snr_threshold=8.0, progress=False)


@pytest.fixture(scope="module")
def jerk_pulsar_file(tmp_path_factory):
    """Binary pulsar with line-of-sight jerk: phase(t) = f0 (t +
    a t^2 / 2c + j t^3 / 6c)."""
    arr, hdr = simulate_accel_pulsar_data(
        freq=E2E_F0, dm=E2E_DM, accel=E2E_ACCEL, jerk=E2E_JERK,
        tsamp=E2E_TSAMP, nsamples=E2E_NSAMPLES, nchan=E2E_NCHAN, rng=17)
    path = tmp_path_factory.mktemp("jerkpsr") / "jerky.fil"
    write_simulated_filterbank(str(path), arr, hdr, descending=True)
    return str(path)


@pytest.fixture(scope="module")
def jerk_run(jerk_pulsar_file, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("jerk_direct"))
    res = periodicity_search(jerk_pulsar_file, output_dir=out, **E2E_JOB)
    assert res["complete"]
    return res


class TestJerkEndToEnd:
    def test_sweep_recovers_injected_jerk_cell(self, jerk_run):
        assert len(jerk_run["jerks"]) == 5
        assert jerk_run["accel_backend"] in ("time_stretch", "fdas")
        cands = jerk_run["candidates"]
        assert cands, "no candidates above threshold"
        best = cands[0]
        assert abs(best["dm"] - E2E_DM) < 5.0
        assert best["accel"] == E2E_ACCEL      # exact grid cell
        assert best["jerk"] == E2E_JERK
        assert abs(best["freq_bin"] - 492) <= 1
        assert best["sigma"] > 15.0
        # the jerk axis demonstrably mattered: the best zero-jerk cell
        # leaves ~24 bins of quadratic smear on the table
        tbl = jerk_run["table"]
        zero = [s for s, j in zip(tbl["sigma"], tbl["jerk"]) if j == 0.0]
        assert not zero or max(zero) < best["sigma"]

    def test_resume_rewrites_identical_candidates(self, jerk_run,
                                                  jerk_pulsar_file):
        # PR 15 resume semantics with the jerk axis on: the second run
        # restores the snapshot + ledger and re-emits the candidates
        # artifact with identical contents (array for array)
        def arrays(path):
            with np.load(path, allow_pickle=False) as d:
                return {k: d[k].tobytes() for k in d.files}

        first = arrays(jerk_run["candidates_path"])
        out = os.path.dirname(jerk_run["candidates_path"])
        res2 = periodicity_search(jerk_pulsar_file, output_dir=out,
                                  **E2E_JOB)
        assert res2["complete"]
        assert res2["fingerprint"] == jerk_run["fingerprint"]
        assert res2["candidates_path"] == jerk_run["candidates_path"]
        second = arrays(res2["candidates_path"])
        assert set(second) == set(first)
        for k in first:
            assert second[k] == first[k], f"{k} bytes differ on resume"

    def test_jerkless_fingerprint_unchanged(self, jerk_pulsar_file,
                                            tmp_path):
        # the driver-fingerprint rule: jerk_max=0 must not enter the
        # fingerprint extra, so pre-jerk ledgers/artifacts keep their
        # names and remain resumable
        from pulsarutils_tpu.pipeline.search_pipeline import plan_survey

        base = plan_survey(jerk_pulsar_file, dmmin=130.0, dmmax=170.0,
                           snr_threshold=8.0,
                           chunk_length=4096 * E2E_TSAMP,
                           fingerprint_extra={"workload": "periodicity",
                                              "accel_max":
                                              E2E_ACCEL_MAX})
        res = periodicity_search(jerk_pulsar_file, 130.0, 170.0,
                                 accel_max=E2E_ACCEL_MAX, n_accel=3,
                                 jerk_max=0.0, sigma_threshold=8.0,
                                 chunk_length=4096 * E2E_TSAMP,
                                 snr_threshold=8.0, progress=False,
                                 output_dir=str(tmp_path))
        assert res["fingerprint"] == base["fingerprint"]

    def test_fleet_lease_carries_jerk_keys(self, jerk_pulsar_file,
                                           jerk_run, tmp_path):
        from pulsarutils_tpu.fleet.coordinator import FleetCoordinator

        spec = {"fname": jerk_pulsar_file, "dmmin": 130.0,
                "dmmax": 170.0, "workload": "periodicity",
                "accel_max": E2E_ACCEL_MAX, "n_accel": 5,
                "jerk_max": E2E_JERK_MAX, "n_jerk": 5,
                "snr_threshold": 8.0,
                "chunk_length": 4096 * E2E_TSAMP}
        with FleetCoordinator(str(tmp_path), auto_sweep=False) as coord:
            units = coord.add_job(spec)
            assert len(units) == 1
            rec = coord._files[os.path.abspath(jerk_pulsar_file)]
            # the coordinator plans the jerk job under the driver's
            # fingerprint: unit completions read the ledger the
            # worker's periodicity_search actually writes
            assert rec["fingerprint"] == jerk_run["fingerprint"]
            reg = coord.register({"healthz_url": None})
            leases = coord.lease({"worker": reg["worker"]})["leases"]
            cfg = leases[0]["config"]
            assert cfg["jerk_max"] == E2E_JERK_MAX
            assert cfg["n_jerk"] == 5
            # jerk knobs on a single-pulse config: rejected at intake
            with pytest.raises(ValueError, match="periodicity"):
                coord.add_survey([jerk_pulsar_file], dmmin=1.0,
                                 dmmax=2.0, jerk_max=10.0)
            with pytest.raises(ValueError, match="accel_backend"):
                coord.add_survey([jerk_pulsar_file], dmmin=1.0,
                                 dmmax=2.0, workload="periodicity",
                                 accel_backend="warp")

    def test_validate_spec_jerk_rules(self, jerk_pulsar_file):
        from pulsarutils_tpu.beams.service import validate_spec

        ok = validate_spec({"fname": jerk_pulsar_file, "dmmin": 1,
                            "dmmax": 2, "workload": "periodicity",
                            "accel_max": 10.0, "jerk_max": 5.0,
                            "n_jerk": 5, "accel_backend": "fdas"})
        assert ok["jerk_max"] == 5.0 and ok["accel_backend"] == "fdas"
        with pytest.raises(ValueError, match="periodicity"):
            validate_spec({"fname": jerk_pulsar_file, "dmmin": 1,
                           "dmmax": 2, "jerk_max": 5.0})
        with pytest.raises(ValueError, match="periodicity"):
            validate_spec({"fname": jerk_pulsar_file, "dmmin": 1,
                           "dmmax": 2, "accel_backend": "fdas"})
        with pytest.raises(ValueError, match="jerk_max"):
            validate_spec({"fname": jerk_pulsar_file, "dmmin": 1,
                           "dmmax": 2, "workload": "periodicity",
                           "jerk_max": -1.0})
        with pytest.raises(ValueError, match="accel_backend"):
            validate_spec({"fname": jerk_pulsar_file, "dmmin": 1,
                           "dmmax": 2, "workload": "periodicity",
                           "accel_backend": "warp"})

    def test_driver_rejects_unknown_backend(self, jerk_pulsar_file,
                                            tmp_path):
        with pytest.raises(ValueError, match="accel_backend"):
            periodicity_search(jerk_pulsar_file, 130.0, 170.0,
                               accel_backend="warp",
                               output_dir=str(tmp_path))

    def test_cli_exposes_jerk_and_backend_flags(self):
        from pulsarutils_tpu.cli.period_main import build_parser

        opts = build_parser().parse_args(
            ["f.fil", "--jerk-max", "4.4e5", "--n-jerk", "5",
             "--accel-backend", "fdas"])
        assert opts.jerk_max == 4.4e5 and opts.n_jerk == 5
        assert opts.accel_backend == "fdas"
        with pytest.raises(SystemExit):
            build_parser().parse_args(["f.fil", "--accel-backend",
                                       "warp"])


# ---------------------------------------------------------------------------
# report surfacing
# ---------------------------------------------------------------------------

def test_report_carries_jerk_and_backend():
    from pulsarutils_tpu.obs.report import build_report, render_markdown

    summary = {"n_dm": 4, "n_accel": 3, "n_jerk": 5,
               "accel_backend": "fdas", "nout": 128, "rebin": 2,
               "t_obs_s": 12.8, "raw_candidates": 1, "kept": 1,
               "rejected": {}, "canary": None,
               "candidates": [{"freq": 60.0, "dm": 150.0, "accel": 9e5,
                               "jerk": 2.2e5, "sigma": 30.0, "nharm": 4,
                               "h": 99.0}]}
    md = render_markdown(build_report(meta={"root": "x"},
                                      periodicity=summary))
    assert ("4 DM x 3 acceleration trials x 5 jerk trials "
            "(fdas backend)") in md
    assert "jerk (m/s^3)" in md
    # a jerk-less summary keeps the exact pre-jerk table and line
    old = dict(summary)
    del old["n_jerk"], old["accel_backend"]
    md_old = render_markdown(build_report(meta={"root": "x"},
                                          periodicity=old))
    assert "4 DM x 3 acceleration trials over a" in md_old
    assert "jerk (m/s^3)" not in md_old
