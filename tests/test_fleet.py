"""Fleet orchestrator tests (ISSUE 9).

Tier-1 pins: the wire-protocol/config whitelist, coordinator sharding +
ledger-backed resume, lease expiry -> steal -> duplicate-completion
idempotency, DEGRADED-worker lease starvation (and recovery), the
killed-worker (SIGKILL mid-lease) resume byte-identity, graceful drain,
the ``chunks=``/``cancel_cb=`` driver seams, the sorted/merging ledger,
and the ``/fleet/`` HTTP surface.  The full subprocess chaos classes
(killed + wedged worker over the drill survey) are ``slow``-marked.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pulsarutils_tpu.fleet import protocol
from pulsarutils_tpu.fleet.coordinator import FleetCoordinator
from pulsarutils_tpu.fleet.worker import FleetWorker
from pulsarutils_tpu.io.candidates import CandidateStore
from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
from pulsarutils_tpu.models.simulate import disperse_array
from pulsarutils_tpu.obs import metrics as obs_metrics
from pulsarutils_tpu.obs.health import HealthEngine
from pulsarutils_tpu.obs.server import start_obs_server
from pulsarutils_tpu.pipeline.search_pipeline import (plan_survey,
                                                      search_by_chunks)

TSAMP = 0.0005
NCHAN = 64
#: 24576 samples at chunk_length 8192*TSAMP -> exactly chunks [0, 8192]
NSAMPLES = 24576
CONFIG = dict(dmmin=100, dmmax=200, chunk_length=8192 * TSAMP,
              snr_threshold=6.5)


def write_file(path, seed=0, pulse=False):
    rng = np.random.default_rng(seed)
    arr = np.abs(rng.normal(0, 0.5, (NCHAN, NSAMPLES))) + 20.0
    if pulse:
        arr[:, (3 * NSAMPLES) // 4] += 4.0
        arr = disperse_array(arr, 150.0, 1200., 200., TSAMP)
    header = {"bandwidth": 200., "fbottom": 1200., "nchans": NCHAN,
              "nsamples": NSAMPLES, "tsamp": TSAMP,
              "foff": 200. / NCHAN}
    write_simulated_filterbank(str(path), arr, header, descending=True)
    return str(path)


def reference_run(fnames, outdir):
    for fname in fnames:
        search_by_chunks(fname, output_dir=str(outdir), make_plots=False,
                         progress=False, **CONFIG)


def snapshot_dir(outdir):
    """{name: bytes-or-npz-members} over ledgers + candidates (the
    chaos-drill comparison rule: npz compared member-wise)."""
    out = {}
    for path in sorted(glob.glob(os.path.join(str(outdir), "*"))):
        name = os.path.basename(path)
        if name.startswith("progress_") and name.endswith(".json"):
            with open(path, "rb") as f:
                out[name] = f.read()
        elif name.endswith(".npz"):
            with np.load(path, allow_pickle=False) as z:
                out[name] = {k: (str(z[k].dtype), z[k].shape,
                                 z[k].tobytes()) for k in z.files}
    return out


def mark_chunks_done(outdir, fingerprint, chunks):
    """Simulate a worker's ledger writes without paying a search."""
    store = CandidateStore(str(outdir), fingerprint)
    for c in chunks:
        store.mark_done(c)


def counter_value(name):
    return obs_metrics.counter(name).value


# ---------------------------------------------------------------------------
# protocol + planning
# ---------------------------------------------------------------------------

def test_search_config_whitelist():
    cfg = protocol.clean_search_config(dict(CONFIG, kernel="hybrid"))
    assert cfg["dmmin"] == 100 and cfg["kernel"] == "hybrid"
    with pytest.raises(ValueError, match="output_dir"):
        protocol.clean_search_config({"output_dir": "/tmp/x"})
    with pytest.raises(ValueError, match="dmax"):
        protocol.clean_search_config({"dmax": 200})  # typo must not pass


def test_plan_survey_matches_driver_fingerprint(tmp_path):
    fname = write_file(tmp_path / "a.fil", seed=3)
    sp = plan_survey(fname, **CONFIG)
    assert sp["chunk_starts"] == [0, 8192]
    _, store = search_by_chunks(fname, output_dir=str(tmp_path / "out"),
                                make_plots=False, progress=False,
                                max_chunks=1, **CONFIG)
    # the coordinator's fingerprint IS the driver's — same ledger
    assert store.fingerprint == sp["fingerprint"]
    assert store.done_chunks == sp["chunk_starts"][:1]


def test_coordinator_shards_and_skips_ledger_done(tmp_path):
    fname = write_file(tmp_path / "a.fil", seed=4)
    out = tmp_path / "fleet"
    with FleetCoordinator(str(out), auto_sweep=False) as coordinator:
        ids = coordinator.add_survey([fname], **CONFIG)
        assert len(ids) == 2  # chunks_per_unit=1 over [0, 8192]
        fingerprint = plan_survey(fname, **CONFIG)["fingerprint"]
    # chunk 0 already done in the ledger: only 8192 gets sharded
    mark_chunks_done(out, fingerprint, [0])
    with FleetCoordinator(str(out), auto_sweep=False) as c2:
        ids = c2.add_survey([fname], **CONFIG)
        assert len(ids) == 1
        assert c2.progress_doc()["chunks_done"] == 1


def test_lease_complete_lifecycle_resolved_by_ledger(tmp_path):
    fname = write_file(tmp_path / "a.fil", seed=5)
    out = tmp_path / "fleet"
    with FleetCoordinator(str(out), auto_sweep=False) as coordinator:
        coordinator.add_survey([fname], **CONFIG)
        fingerprint = coordinator.progress_doc()["files"][0]["fingerprint"]
        w = coordinator.register({"healthz_url": None})["worker"]
        resp = coordinator.lease({"worker": w, "max_units": 2})
        assert len(resp["leases"]) == 2
        lease = resp["leases"][0]
        assert lease["config"]["dmmin"] == 100
        assert lease["output_dir"] == str(out)
        # completing WITHOUT ledger backing requeues, never resolves
        resp2 = coordinator.complete({"worker": w, "lease": lease["lease"],
                                      "unit": lease["unit"],
                                      "error": None})
        assert resp2["unit_done"] is False
        assert resp2["requeued"] == lease["chunks"]
        # now the ledger actually records the chunks: complete resolves
        release = coordinator.lease({"worker": w, "max_units": 1})
        assert len(release["leases"]) == 1
        got = release["leases"][0]
        mark_chunks_done(out, fingerprint, got["chunks"])
        resp3 = coordinator.complete({"worker": w, "lease": got["lease"],
                                      "unit": got["unit"], "error": None})
        assert resp3["unit_done"] is True


def test_lease_expiry_steal_duplicate_completion_idempotent(tmp_path):
    fname = write_file(tmp_path / "a.fil", seed=6)
    out = tmp_path / "fleet"
    before = {k: counter_value(f"putpu_fleet_{k}_total")
              for k in ("leases_expired", "duplicate_completions",
                        "units_requeued")}
    with FleetCoordinator(str(out), auto_sweep=False,
                          lease_ttl_s=5.0) as coordinator:
        coordinator.add_survey([fname], **CONFIG)
        fingerprint = coordinator.progress_doc()["files"][0]["fingerprint"]
        w1 = coordinator.register({})["worker"]
        w2 = coordinator.register({})["worker"]
        lease1 = coordinator.lease({"worker": w1,
                                    "max_units": 1})["leases"][0]
        # TTL passes with w1 silent: the sweep requeues via the ledger
        swept = coordinator.sweep(now=time.monotonic() + 10.0)
        assert swept["expired"] == [lease1["lease"]]
        assert counter_value("putpu_fleet_leases_expired_total") \
            == before["leases_expired"] + 1
        # w2 steals the unit and finishes it
        lease2 = coordinator.lease({"worker": w2,
                                    "max_units": 1})["leases"][0]
        assert lease2["unit"] == lease1["unit"]
        assert lease2["chunks"] == lease1["chunks"]
        mark_chunks_done(out, fingerprint, lease2["chunks"])
        done = coordinator.complete({"worker": w2, "lease": lease2["lease"],
                                     "unit": lease2["unit"], "error": None})
        assert done["unit_done"] is True
        ledger = snapshot_dir(out)[f"progress_{fingerprint}.json"]
        # the straggler's late completion: counted, idempotent, no
        # requeue, ledger untouched
        late = coordinator.complete({"worker": w1, "lease": lease1["lease"],
                                     "unit": lease1["unit"], "error": None})
        assert late["unit_done"] is True
        assert late["requeued"] == []
        assert counter_value("putpu_fleet_duplicate_completions_total") \
            == before["duplicate_completions"] + 1
        assert snapshot_dir(out)[f"progress_{fingerprint}.json"] == ledger


def test_degraded_worker_lease_starvation_and_recovery(tmp_path):
    fname = write_file(tmp_path / "a.fil", seed=7)
    sick_engine = HealthEngine()
    sick_engine.update(0, quarantined=True)        # -> DEGRADED
    assert sick_engine.verdict == "DEGRADED"
    ok_engine = HealthEngine()
    with start_obs_server(0, health=sick_engine) as sick_srv, \
            start_obs_server(0, health=ok_engine) as ok_srv, \
            FleetCoordinator(str(tmp_path / "fleet"), auto_sweep=False,
                             file_affinity=False) as coordinator:
        coordinator.add_survey([fname], **CONFIG)
        sick = coordinator.register(
            {"healthz_url":
             f"http://127.0.0.1:{sick_srv.port}/healthz"})["worker"]
        ok = coordinator.register(
            {"healthz_url":
             f"http://127.0.0.1:{ok_srv.port}/healthz"})["worker"]
        probed = coordinator.sweep()["probed"]
        assert probed == {sick: "DEGRADED", ok: "OK"}
        denied = coordinator.lease({"worker": sick, "max_units": 1})
        assert denied["leases"] == [] and denied["denied"] == "DEGRADED"
        granted = coordinator.lease({"worker": ok, "max_units": 1})
        assert len(granted["leases"]) == 1
        workers = {w["worker"]: w for w in
                   coordinator.workers_doc()["workers"]}
        assert workers[sick]["verdict"] == "DEGRADED"
        # the condition decays (recover_after clean updates): the next
        # probe re-qualifies the worker for leases
        sick_engine.update(1)
        sick_engine.update(2)
        assert sick_engine.verdict == "OK"
        coordinator.sweep()
        regranted = coordinator.lease({"worker": sick, "max_units": 1})
        assert len(regranted["leases"]) == 1


def test_dead_worker_probe_revokes_and_requeues(tmp_path):
    fname = write_file(tmp_path / "a.fil", seed=8)
    with FleetCoordinator(str(tmp_path / "fleet"), auto_sweep=False,
                          dead_after=2) as coordinator:
        coordinator.add_survey([fname], **CONFIG)
        # a healthz URL nothing listens on: every probe fails
        dead = coordinator.register(
            {"healthz_url": "http://127.0.0.1:9/healthz"})["worker"]
        lease = coordinator.lease({"worker": dead,
                                   "max_units": 1})["leases"][0]
        assert coordinator.sweep()["revoked"] == []    # 1 failure: not yet
        revoked = coordinator.sweep()["revoked"]       # 2nd: declared dead
        assert revoked == [lease["lease"]]
        doc = coordinator.workers_doc()["workers"][0]
        assert doc["alive"] is False
        # the unit is back in the queue for a live worker
        alive = coordinator.register({})["worker"]
        again = coordinator.lease({"worker": alive,
                                   "max_units": 1})["leases"]
        assert [le["unit"] for le in again] == [lease["unit"]]


def test_two_worker_fleet_byte_identical_to_single_process(tmp_path):
    """The tentpole contract: a 2-worker fleet run over a 2-file survey
    produces byte-identical candidates and per-file ledgers vs the
    single-process run (real HTTP wire, real searches)."""
    fnames = [write_file(tmp_path / "a.fil", seed=0, pulse=True),
              write_file(tmp_path / "b.fil", seed=1)]
    reference_run(fnames, tmp_path / "single")

    out = tmp_path / "fleet"
    with FleetCoordinator(str(out), lease_ttl_s=120.0,
                          probe_interval_s=0.5) as coordinator:
        with start_obs_server(0, fleet=coordinator) as srv:
            url = f"http://127.0.0.1:{srv.port}"
            coordinator.add_survey(fnames, **CONFIG)
            workers = [FleetWorker(url, http_port=None)
                       for _ in range(2)]
            threads = [threading.Thread(target=w.run,
                                        kwargs={"max_idle_s": 60.0})
                       for w in workers]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300.0)
            assert coordinator.survey_done
            assert sum(w.units_done for w in workers) == 4
    assert snapshot_dir(tmp_path / "single") == snapshot_dir(out)


def test_killed_worker_sigkill_mid_lease_byte_identity(tmp_path):
    """SIGKILL a real worker process while it holds a lease (wedged at
    the fleet fault seam, pre-search): the lease expires, the chunks
    requeue off the ledger, a healthy worker finishes, and the outputs
    are byte-identical to the single-process run."""
    from pulsarutils_tpu.faults.inject import FaultPlan, FaultSpec

    fname = write_file(tmp_path / "a.fil", seed=0, pulse=True)
    reference_run([fname], tmp_path / "single")

    out = tmp_path / "fleet"
    coordinator = FleetCoordinator(str(out), lease_ttl_s=4.0,
                                   probe_interval_s=0.3)
    srv = start_obs_server(0, fleet=coordinator)
    url = f"http://127.0.0.1:{srv.port}"
    coordinator.add_survey([fname], **CONFIG)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""),
               PUTPU_FAULT_PLAN=FaultPlan(
                   [FaultSpec(site="fleet", kind="hang", seconds=300.0,
                              times=1)]).to_json())
    victim = subprocess.Popen(
        [sys.executable, "-m", "pulsarutils_tpu.cli.fleet_main",
         "worker", "--coordinator", url, "--worker-id", "victim",
         "--max-idle", "60"],
        env=env, cwd=repo, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 120.0
        while time.time() < deadline \
                and not coordinator.leases_doc()["leases"]:
            time.sleep(0.2)
        assert coordinator.leases_doc()["leases"], \
            "victim never obtained a lease"
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)
        rescuer = FleetWorker(url, http_port=None)
        rescuer.run(max_idle_s=60.0)
        assert coordinator.survey_done
        stats = coordinator.progress_doc()["stats"]
        assert stats["expired"] + stats["revoked"] >= 1
    finally:
        if victim.poll() is None:
            victim.kill()
        srv.close()
        coordinator.close()
    assert snapshot_dir(tmp_path / "single") == snapshot_dir(out)


def test_worker_graceful_drain_returns_unstarted_leases(tmp_path):
    """Drain before run(): the worker registers, leases nothing more,
    releases unstarted leases mid-batch, and counts the drain."""
    fname = write_file(tmp_path / "a.fil", seed=9)
    out = tmp_path / "fleet"
    before = counter_value("putpu_fleet_drains_total")
    with FleetCoordinator(str(out), auto_sweep=False) as coordinator:
        with start_obs_server(0, fleet=coordinator) as srv:
            url = f"http://127.0.0.1:{srv.port}"
            coordinator.add_survey([fname], **CONFIG)
            worker = FleetWorker(url, http_port=None, max_units=2)
            orig_run_unit = worker._run_unit

            def drain_after_first(lease):
                result = orig_run_unit(lease)
                worker.drain()    # eviction notice mid-batch
                return result

            worker._run_unit = drain_after_first
            worker.run()
            assert worker.drained is True
            assert worker.units_done == 1
            assert counter_value("putpu_fleet_drains_total") == before + 1
            progress = coordinator.progress_doc()
            # first unit completed + ledger-backed; second was released
            # back (requeued) untouched — nothing is leased anymore
            assert progress["chunks_done"] == 1
            assert progress["units"] == {"done": 1, "pending": 1}
            assert coordinator.leases_doc()["leases"] == []
            # cooperative returns never burn the poison-chunk budget:
            # a preemptible fleet draining daily must not fail units
            assert all(u.attempts == 0
                       for u in coordinator._units.values())
            # the drained worker gets nothing further
            denied = coordinator.lease({"worker": worker.worker_id,
                                        "max_units": 1})
            assert denied["denied"] == "draining"
            # a fresh worker finishes the survey exactly
            finisher = FleetWorker(url, http_port=None)
            finisher.run(max_idle_s=30.0)
            assert coordinator.survey_done


def test_chunks_and_cancel_cb_driver_seams(tmp_path):
    fname = write_file(tmp_path / "a.fil", seed=10)
    out = str(tmp_path / "out")
    _, store = search_by_chunks(fname, output_dir=out, make_plots=False,
                                progress=False, chunks=[8192], **CONFIG)
    assert store.done_chunks == [8192]     # only the leased chunk
    _, store2 = search_by_chunks(fname, output_dir=out, make_plots=False,
                                 progress=False,
                                 cancel_cb=lambda: True, **CONFIG)
    assert store2.done_chunks == [8192]    # cancelled before chunk 0


def test_mark_done_sorted_and_merging(tmp_path):
    # two sessions over ONE ledger, interleaved out of order (the
    # fleet's steal edge): the final file equals a single ascending
    # session's bytes
    a = CandidateStore(str(tmp_path), "f" * 16)
    b = CandidateStore(str(tmp_path), "f" * 16)
    a.mark_done(16384)
    b.mark_done(0)          # merges a's 16384 from disk
    a.mark_done(8192)       # merges b's 0 from disk
    with open(a._ledger_path, "rb") as f:
        merged = f.read()
    ref = CandidateStore(str(tmp_path / "ref"), "f" * 16)
    for c in (0, 8192, 16384):
        ref.mark_done(c)
    with open(ref._ledger_path, "rb") as f:
        assert f.read() == merged


def test_fleet_http_surface(tmp_path):
    fname = write_file(tmp_path / "a.fil", seed=11)
    with FleetCoordinator(str(tmp_path / "fleet"),
                          auto_sweep=False) as coordinator:
        with start_obs_server(0, fleet=coordinator) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            coordinator.add_survey([fname], **CONFIG)
            reg = protocol.post_json(base + "/fleet/register",
                                     {"healthz_url": None})
            assert reg["protocol_version"] == protocol.PROTOCOL_VERSION
            lease = protocol.post_json(
                base + "/fleet/lease",
                {"worker": reg["worker"], "max_units": 1})["leases"][0]
            # completion over the wire, carrying a metrics snapshot the
            # aggregated /fleet/metrics page must re-serve
            mark_chunks_done(tmp_path / "fleet",
                             coordinator.progress_doc()["files"][0]
                             ["fingerprint"], lease["chunks"])
            protocol.post_json(base + "/fleet/complete", {
                "worker": reg["worker"], "lease": lease["lease"],
                "unit": lease["unit"], "error": None,
                "metrics": [{"name": "putpu_chunks_total",
                             "type": "counter", "labels": {},
                             "value": 1}],
                "health": {"status": "OK", "reasons": []}})
            for path in ("/fleet/workers", "/fleet/leases",
                         "/fleet/progress"):
                with urllib.request.urlopen(base + path,
                                            timeout=10.0) as resp:
                    assert resp.status == 200
                    json.loads(resp.read().decode())
            with urllib.request.urlopen(base + "/fleet/metrics",
                                        timeout=10.0) as resp:
                text = resp.read().decode()
            assert ('putpu_chunks_total{worker="%s"} 1'
                    % reg["worker"]) in text
            # protocol violations are 400s with the reason in the body
            status, body = _post_raw(base + "/fleet/lease",
                                     {"worker": "nope"})
            assert status == 400 and "unknown worker" in body
            # bad unit id on complete is a 400 too, not a 500
            status, body = _post_raw(
                base + "/fleet/complete",
                {"worker": reg["worker"], "lease": "L99",
                 "unit": "u99", "error": None})
            assert status == 400 and "unknown unit" in body


def _post_raw(url, doc):
    req = urllib.request.Request(
        url, method="POST", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode()


def test_fleet_endpoints_404_unwired():
    with start_obs_server(0) as srv:
        base = f"http://127.0.0.1:{srv.port}"
        for path in ("/fleet/progress", "/fleet/workers"):
            try:
                urllib.request.urlopen(base + path, timeout=10.0)
                status = 200
            except urllib.error.HTTPError as exc:
                status = exc.code
            assert status == 404
        assert _post_raw(base + "/fleet/lease", {"worker": "w"})[0] == 404


def test_add_job_service_spec_handoff(tmp_path):
    fname = write_file(tmp_path / "a.fil", seed=12)
    with FleetCoordinator(str(tmp_path / "fleet"),
                          auto_sweep=False) as coordinator:
        ids = coordinator.add_job({"fname": fname, "dmmin": 100,
                                   "dmmax": 200, "snr_threshold": 6.5})
        assert len(ids) >= 1
        with pytest.raises(ValueError, match="missing keys"):
            coordinator.add_job({"fname": fname})
        with pytest.raises(ValueError, match="canary_rate"):
            coordinator.add_job({"fname": fname, "dmmin": 100,
                                 "dmmax": 200, "canary_rate": 0.5})
        # one fleet run, one fingerprint per file
        with pytest.raises(ValueError, match="different search config"):
            coordinator.add_survey([fname], dmmin=100, dmmax=300)


def test_fleet_report_section(tmp_path):
    from pulsarutils_tpu.obs.report import render_markdown, write_report

    fname = write_file(tmp_path / "a.fil", seed=13)
    with FleetCoordinator(str(tmp_path / "fleet"),
                          auto_sweep=False) as coordinator:
        coordinator.add_survey([fname], **CONFIG)
        summary = coordinator.summary()
    write_report(str(tmp_path / "report"), meta={"root": "fleet"},
                 fleet=summary)
    with open(str(tmp_path / "report") + ".json") as f:
        rec = json.load(f)
    md = render_markdown(rec)
    assert "## Fleet" in md
    assert "0/2 chunks completed across the fleet" in md
    # absence stated when no coordinator was involved
    write_report(str(tmp_path / "r2"), meta={"root": "solo"})
    with open(str(tmp_path / "r2") + ".json") as f:
        assert "no fleet coordinator" in render_markdown(json.load(f))


def test_worker_reregisters_after_coordinator_restart(tmp_path):
    """A coordinator restart loses its in-memory worker table; a
    long-lived worker must re-register on the 'unknown worker' 400
    instead of spinning as a zombie."""
    fname = write_file(tmp_path / "a.fil", seed=14)
    first = FleetCoordinator(str(tmp_path / "old"), auto_sweep=False)
    with start_obs_server(0, fleet=first) as srv:
        url = f"http://127.0.0.1:{srv.port}"
        worker = FleetWorker(url, http_port=None, poll_s=0.1)
        thread = threading.Thread(
            target=worker.run, kwargs={"max_idle_s": 60.0})
        thread.start()      # registers with `first`, polls an empty queue
        deadline = time.time() + 30.0
        while time.time() < deadline and worker.worker_id is None:
            time.sleep(0.05)
        assert worker.worker_id is not None
        # "restart": a fresh coordinator (empty worker table) takes
        # over the same surface mid-poll
        second = FleetCoordinator(str(tmp_path / "fleet"),
                                  auto_sweep=False)
        second.add_survey([fname], **CONFIG)
        srv.fleet = second
        thread.join(timeout=120.0)
        assert not thread.is_alive()
        assert worker.units_done == 2 and second.survey_done
        second.close()
    first.close()


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_chaos_drill_killed_and_wedged_workers():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import chaos_drill

    result = chaos_drill.run_fleet_drill(log=lambda *a: None)
    assert result["all_ok"], json.dumps(result, indent=1)
