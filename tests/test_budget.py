"""Round-6 budget accountant + overlapped-persist parity.

Covers the streaming wall-clock budget layer
(:class:`pulsarutils_tpu.utils.logging_utils.BudgetAccountant`): bucket
sums + ``unattributed`` reconcile with measured wall, dispatch/readback
counters match a known streaming run, a forced shape-drift retrace is
detected and reported — and the overlapped persist executor yields a
byte-identical ledger and candidate set versus the serial loop,
including across an interrupt/resume.
"""
import json
import os
import time

import numpy as np
import pytest

from pulsarutils_tpu.utils.logging_utils import (BudgetAccountant,
                                                 budget_bucket,
                                                 budget_count,
                                                 measure_device_rtt)


def test_buckets_plus_unattributed_sum_to_wall():
    acct = BudgetAccountant()
    with acct.chunk("c0"):
        with acct.bucket("read"):
            time.sleep(0.02)
        with acct.bucket("search"):
            time.sleep(0.03)
            with acct.bucket("search/sub"):
                time.sleep(0.01)
        time.sleep(0.02)  # deliberately unattributed
    rec = acct.chunks[0]
    top = sum(v for k, v in rec["buckets"].items() if "/" not in k)
    assert rec["wall_s"] == pytest.approx(top + rec["unattributed_s"],
                                          abs=1e-3)
    # the residual sleep is found, not silently absorbed
    assert rec["unattributed_s"] >= 0.015
    # nested bucket counts toward its parent's span, not the top level
    assert rec["buckets"]["search"] >= rec["buckets"]["search/sub"]
    j = acct.to_json()
    # wall_s, each bucket and unattributed_s are rounded independently
    # (3-4 decimals), so the reconstructed sum drifts by up to half a
    # quantum per term — tolerance covers the rounding, not real leaks
    n_terms = sum("/" not in k for k in j["buckets_s"]) + 2
    assert j["wall_s"] == pytest.approx(
        sum(j["buckets_s"][k] for k in j["buckets_s"] if "/" not in k)
        + j["unattributed_s"], abs=1e-3 * n_terms)
    assert 0 < j["attributed_pct"] < 100


def test_counters_and_async_accounting():
    acct = BudgetAccountant(rtt_s=0.001)
    with acct.chunk(0):
        budget_count("dispatches")
        budget_count("readbacks", 2)
        with budget_bucket("search"):
            pass
    acct.add_async("persist", 0.5)
    assert acct.chunks[0]["counters"] == {"dispatches": 1, "readbacks": 2}
    j = acct.to_json()
    assert j["counters"] == {"dispatches": 1, "readbacks": 2}
    assert j["trips"] == 3
    assert j["trips_x_rtt_s"] == pytest.approx(0.003)
    assert j["async_s"]["persist"] == pytest.approx(0.5)
    # async work must NOT leak into any chunk's serial budget
    assert "persist" not in acct.chunks[0]["buckets"]


def test_budget_bucket_is_noop_without_active_chunk():
    # kernel code calls these unconditionally; outside a chunk context
    # they must not raise and must not create a chunk record
    acct = BudgetAccountant()
    with budget_bucket("search/dispatch"):
        pass
    budget_count("dispatches")
    assert acct.chunks == []


def test_forced_shape_drift_retrace_is_detected():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    f = jax.jit(lambda x: x * 2 + 1)
    acct = BudgetAccountant()
    with acct.chunk(0):
        np.asarray(f(jnp.ones((4, 8))))   # first compile: expected
    with acct.chunk(1):
        np.asarray(f(jnp.ones((4, 8))))   # cache hit: no compile
    with acct.chunk(2):
        np.asarray(f(jnp.ones((4, 16))))  # shape drift: retrace
    assert acct.chunks[0]["counters"].get("compiles", 0) >= 1
    assert "retrace" not in acct.chunks[0]  # chunk 0 compiles are normal
    assert acct.chunks[1]["counters"].get("compiles", 0) == 0
    assert "retrace" not in acct.chunks[1]
    assert acct.chunks[2]["counters"].get("compiles", 0) >= 1
    assert acct.chunks[2]["retrace"] is True
    assert acct.chunks[2]["counters"]["compile_s"] > 0


def test_measure_device_rtt():
    rtt = measure_device_rtt(n=3)
    assert rtt is None or 0 < rtt < 60


@pytest.fixture(scope="module")
def pulse_file(tmp_path_factory):
    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.models.simulate import disperse_array

    tmp = tmp_path_factory.mktemp("budget")
    rng = np.random.default_rng(3)
    nchan, nsamples = 64, 16384
    array = np.abs(rng.normal(0, 0.5, (nchan, nsamples))) + 20.0
    array[:, 9000] += 4.0
    array = disperse_array(array, 150, 1200., 200., 0.0005)
    header = {"bandwidth": 200., "fbottom": 1200., "nchans": nchan,
              "nsamples": nsamples, "tsamp": 0.0005, "foff": 200. / nchan}
    path = str(tmp / "pulse.fil")
    write_simulated_filterbank(path, array, header, descending=True)
    return path


def test_streaming_run_counters_and_budget(pulse_file, tmp_path):
    from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks

    acct = BudgetAccountant()
    hits, store = search_by_chunks(
        pulse_file, dmmin=100, dmmax=200, backend="jax",
        output_dir=str(tmp_path), make_plots=False, resume=False,
        progress=False, snr_threshold=1e9, budget=acct)
    assert not hits  # threshold excludes everything: a pure no-hit stream
    assert len(acct.chunks) >= 2
    for rec in acct.chunks:
        # the known per-chunk device-op schedule of the jax gather path
        # with no hits: upload-force readback + clean dispatch + clean
        # force readback + search dispatch + search readback
        assert rec["counters"]["dispatches"] == 2, rec
        assert rec["counters"]["readbacks"] == 3, rec
        # budget reconciles per chunk
        top = sum(v for k, v in rec["buckets"].items() if "/" not in k)
        assert rec["wall_s"] == pytest.approx(
            top + rec["unattributed_s"], abs=2e-3)
        for key in ("read", "upload_wait", "clean", "search"):
            assert key in rec["buckets"], rec
        assert "search/dispatch" in rec["buckets"]
        assert "search/readback" in rec["buckets"]
    # interior chunks reuse one executable: no retrace flags (the final
    # chunk may be ragged — a different shape legitimately recompiles,
    # and the accountant is REQUIRED to flag exactly that)
    assert not any(rec.get("retrace") for rec in acct.chunks[1:-1])
    j = acct.to_json()
    assert j["attributed_pct"] > 90.0
    assert j["counters"]["dispatches"] == 2 * len(acct.chunks)


def _run_stream(path, outdir, overlap, **kw):
    from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks

    return search_by_chunks(
        path, dmmin=100, dmmax=200, backend="jax", output_dir=str(outdir),
        make_plots=False, progress=False, overlap_persist=overlap, **kw)


def _ledger_bytes(outdir):
    (name,) = [n for n in os.listdir(outdir) if n.startswith("progress_")]
    with open(os.path.join(outdir, name), "rb") as f:
        return name, f.read()


def test_overlapped_persist_parity_with_serial(pulse_file, tmp_path):
    # byte-identical ledger + identical candidate set vs the serial loop
    hits_s, store_s = _run_stream(pulse_file, tmp_path / "serial", False)
    hits_o, store_o = _run_stream(pulse_file, tmp_path / "overlap", True)
    assert [(h[0], h[1]) for h in hits_s] == [(h[0], h[1]) for h in hits_o]

    name_s, bytes_s = _ledger_bytes(str(tmp_path / "serial"))
    name_o, bytes_o = _ledger_bytes(str(tmp_path / "overlap"))
    assert name_s == name_o          # same fingerprint
    assert bytes_s == bytes_o        # same done-order, byte for byte

    cands_s = sorted(store_s.candidates())
    cands_o = sorted(store_o.candidates())
    assert cands_s == cands_o and cands_s
    for (root, lo, hi) in cands_s:
        info_s, table_s = store_s.load_candidate(root, lo, hi)
        info_o, table_o = store_o.load_candidate(root, lo, hi)
        np.testing.assert_array_equal(info_s.allprofs, info_o.allprofs)
        assert info_s.dm == info_o.dm and info_s.snr == info_o.snr
        for col in table_s.colnames:
            np.testing.assert_array_equal(np.asarray(table_s[col]),
                                          np.asarray(table_o[col]))


def test_overlapped_persist_resume_after_interrupt(pulse_file, tmp_path):
    # interrupt with the overlapped loop, resume, and end in exactly the
    # state a serial uninterrupted run produces
    out = tmp_path / "resumed"
    hits1, store1 = _run_stream(pulse_file, out, True, max_chunks=2)
    assert len(store1.done_chunks) == 2
    hits2, store2 = _run_stream(pulse_file, out, True)

    ref_out = tmp_path / "oneshot"
    hits_ref, store_ref = _run_stream(pulse_file, ref_out, False)
    assert store2.done_chunks == store_ref.done_chunks
    assert ([(h[0], h[1]) for h in hits2]
            == [(h[0], h[1]) for h in hits_ref])
    assert sorted(store2.candidates()) == sorted(store_ref.candidates())


def test_stream_search_budget_and_retrace_flag():
    # parallel/stream.stream_search: per-chunk budgets + the checked
    # one-executable contract (a ragged final chunk IS a retrace)
    jax = pytest.importorskip("jax")

    rng = np.random.default_rng(0)
    from pulsarutils_tpu.parallel.stream import stream_search

    chunks = [(0, rng.normal(size=(16, 512)).astype(np.float32)),
              (256, rng.normal(size=(16, 512)).astype(np.float32)),
              (512, rng.normal(size=(16, 384)).astype(np.float32))]
    acct = BudgetAccountant()
    results, hits = stream_search(chunks, 100, 200, 1200., 200., 0.0005,
                                  backend="jax", budget=acct)
    assert len(results) == 3
    assert len(acct.chunks) == 3
    assert all("search" in rec["buckets"] for rec in acct.chunks)
    assert "retrace" not in acct.chunks[1]        # same shape: cache hit
    assert acct.chunks[2].get("retrace") is True  # ragged final chunk


def test_budget_json_logged(pulse_file, tmp_path, caplog):
    import logging

    from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks

    with caplog.at_level(logging.INFO, logger="pulsarutils_tpu"):
        search_by_chunks(pulse_file, dmmin=100, dmmax=200, backend="jax",
                         output_dir=str(tmp_path), make_plots=False,
                         resume=False, progress=False)
    budget_lines = [r.getMessage() for r in caplog.records
                    if r.getMessage().startswith("BUDGET_JSON ")]
    assert len(budget_lines) == 1
    j = json.loads(budget_lines[0][len("BUDGET_JSON "):])
    if j.get("per_chunk_truncated"):
        assert len(j["per_chunk"]) == 32 < j["chunks"]
    else:
        assert j["chunks"] == len(j["per_chunk"])
    assert set(j["counters"]) >= {"dispatches", "readbacks"}
    assert j["attributed_pct"] > 50.0
