"""ISSUE 18 — candidate lifecycle observability: per-candidate lineage
docs, the end-to-end latency SLO, and alert fan-out with delivery
telemetry.  Tier-1 throughout: tiny surveys, in-process webhook sinks,
ephemeral ports.
"""
import glob
import http.server
import json
import os
import threading
import time

import numpy as np
import pytest

from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
from pulsarutils_tpu.models.simulate import disperse_array
from pulsarutils_tpu.obs import metrics as obs_metrics
from pulsarutils_tpu.obs.health import OK, HealthEngine
from pulsarutils_tpu.obs.lineage import (LINEAGE_SCHEMA_VERSION,
                                         LineageRecorder)
from pulsarutils_tpu.obs.push import AlertBroker, Subscriber
from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks

TSAMP = 0.0005
NCHAN = 64
#: 16384 samples at chunk_length 8192*TSAMP -> chunks [0, 8192];
#: the pulse sits in chunk 8192
NSAMPLES = 16384
PULSE_T = 12000
CHUNK_LEN_S = 8192 * TSAMP
SEARCH_KW = dict(dmmin=100, dmmax=200, backend="jax",
                 chunk_length=CHUNK_LEN_S, make_plots=False,
                 progress=False, snr_threshold=6.5)


def _counter(name, **labels):
    for rec in obs_metrics.REGISTRY.snapshot():
        if rec["name"] == name and rec["labels"] == labels:
            return rec.get("value", rec.get("count", 0))
    return 0


# ---------------------------------------------------------------------------
# in-process webhook sinks
# ---------------------------------------------------------------------------

class _Sink:
    """Local webhook endpoint collecting every POSTed alert doc."""

    def __init__(self, hang_s=0.0):
        received = self.received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                if hang_s:
                    # wedged subscriber: accept, then never answer
                    # within any sane client timeout
                    time.sleep(hang_s)
                n = int(self.headers.get("Content-Length") or 0)
                received.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), Handler)
        self._httpd.daemon_threads = True
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}/hook"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


@pytest.fixture()
def sink():
    s = _Sink()
    yield s
    s.close()


# ---------------------------------------------------------------------------
# survey fixtures + byte-snapshot helper
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def survey_file(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("lineage")
    rng = np.random.default_rng(0)
    arr = np.abs(rng.normal(0, 0.5, (NCHAN, NSAMPLES))) + 20.0
    arr[:, PULSE_T] += 4.0
    arr = disperse_array(arr, 150.0, 1200., 200., TSAMP)
    header = {"bandwidth": 200., "fbottom": 1200., "nchans": NCHAN,
              "nsamples": NSAMPLES, "tsamp": TSAMP,
              "foff": 200. / NCHAN}
    path = str(tmp / "survey.fil")
    write_simulated_filterbank(path, arr, header, descending=True)
    return path


def _snapshot(outdir, fingerprint):
    """Ledger bytes + npz member bytes — the byte-identity comparison
    set (lineage docs and dead-letter journals are extra files by
    design and excluded)."""
    with open(os.path.join(outdir, f"progress_{fingerprint}.json"),
              "rb") as f:
        ledger = f.read()
    cands = {}
    for path in sorted(glob.glob(os.path.join(outdir, "*.npz"))):
        with np.load(path, allow_pickle=False) as data:
            cands[os.path.basename(path)] = {
                k: data[k].tobytes() for k in data.files}
    return ledger, cands


@pytest.fixture(scope="module")
def baseline(survey_file, tmp_path_factory):
    """One lineage/push-off reference run; (snapshot, fingerprint)."""
    out = str(tmp_path_factory.mktemp("baseline"))
    hits, store = search_by_chunks(survey_file, output_dir=out,
                                   resume=True, **SEARCH_KW)
    assert len(hits) >= 1
    return _snapshot(out, store.fingerprint), store.fingerprint


# ---------------------------------------------------------------------------
# Subscriber / AlertBroker units
# ---------------------------------------------------------------------------

def test_subscriber_validation_and_filters():
    sub = Subscriber.coerce("http://h:1/hook")
    assert sub.name == "h:1/hook"
    with pytest.raises(ValueError):
        Subscriber.coerce("ftp://nope")
    with pytest.raises(ValueError):
        Subscriber.coerce({"min_snr": 9.0})  # no url
    with pytest.raises(ValueError):
        Subscriber.coerce({"url": "http://h/x", "bogus": 1})
    filt = Subscriber("http://h/x", min_snr=8.0, min_dm=100.0,
                      max_dm=200.0)
    assert filt.wants({"snr": 9.0, "dm": 150.0})
    assert not filt.wants({"snr": 7.0, "dm": 150.0})
    assert not filt.wants({"snr": 9.0, "dm": 250.0})
    # a missing field passes the predicate: never silently drop an
    # alert for lacking a value the filter would have tested
    assert filt.wants({"snr": 9.0})


def test_broker_delivers_and_filters(sink):
    deliveries = []
    with AlertBroker([sink.url,
                      {"url": sink.url, "name": "picky",
                       "min_snr": 100.0}]) as broker:
        assert broker.publish({"kind": "candidate", "snr": 9.0},
                              on_delivered=lambda s, lat:
                              deliveries.append(s))
        deadline = time.monotonic() + 10.0
        while not sink.received and time.monotonic() < deadline:
            time.sleep(0.02)
    stats = broker.stats()      # post-close: drained and settled
    assert len(sink.received) == 1
    assert stats["delivered"] == 1 and stats["filtered"] == 1
    assert stats["dead_lettered"] == 0
    # a filtered-out subscriber NEVER receives (the bench forces 0.0
    # on this) and the delivery hook names who did
    assert deliveries == ["127.0.0.1:%d/hook"
                          % int(sink.url.rsplit(":", 1)[1].split("/")[0])]


def test_broker_wedged_subscriber_drop_oldest_bounded(tmp_path):
    """queue_max=1 + a hung webhook: enqueues never block, the oldest
    alert is dropped (counted + dead-lettered), health degrades, and
    close() is bounded and resolves the condition."""
    hung = _Sink(hang_s=30.0)
    dead = str(tmp_path / "dead.jsonl")
    health = HealthEngine()
    try:
        broker = AlertBroker([hung.url], queue_max=1, timeout_s=0.3,
                             retries=0, dead_letter_path=dead,
                             health=health)
        t0 = time.monotonic()
        for i in range(3):
            assert broker.publish({"kind": "candidate", "seq": i})
        assert time.monotonic() - t0 < 1.0  # publish never blocks
        stats = broker.close(timeout_s=2.0)
        assert time.monotonic() - t0 < 15.0  # bounded shutdown
    finally:
        hung.close()
    assert stats["dropped"] >= 1
    assert _counter("putpu_push_dropped_total") >= 1
    with open(dead) as f:
        records = [json.loads(ln) for ln in f]
    assert any(r["reason"] == "dropped_oldest" for r in records)
    # every published alert is accounted for: delivered is 0 here, so
    # dropped + journaled-at-close covers all three
    assert len(records) + stats["delivered"] >= 3
    # the push condition degraded while wedged, and close() resolved it
    events = [(i["kind"], i["event"])
              for i in health.snapshot()["incidents"]]
    assert ("push", "raised") in events
    assert health.verdict == OK
    assert broker.publish({"kind": "late"}) is False  # closed


# ---------------------------------------------------------------------------
# LineageRecorder units
# ---------------------------------------------------------------------------

def test_lineage_recorder_doc_monotone_and_idempotent():
    lr = LineageRecorder(fingerprint="fp0", source="search_by_chunks")
    lr.mark(0, "read")
    first = lr._marks[0]["read"]
    lr.mark(0, "read")  # idempotent: retries keep the first stamp
    assert lr._marks[0]["read"] == first
    lr.mark(0, "dispatch")
    lr.mark(0, "ready")
    cl = lr.candidate(0, 8192, name="x_0-8192", dm=150.0, snr=9.0,
                      width=0.001)
    written = []
    lr.persisted(cl, writer=written.append)
    lr.delivered(cl, subscriber="hook-a")
    lr.delivered(cl, subscriber="hook-b")
    doc = written[-1]
    assert doc["schema_version"] == LINEAGE_SCHEMA_VERSION
    assert doc["fingerprint"] == "fp0" and doc["chunk"] == 0
    assert doc["candidate"] == "x_0-8192" and doc["dm"] == 150.0
    assert len(doc["trace_id"]) == 16
    st = doc["stages"]
    assert st["read"] <= st["dispatch"] <= st["ready"] <= st["sift"] \
        <= st["persist"]
    assert st["alert"] >= st["sift"]
    # the alert stamp is first-delivery-wins; both subscribers recorded
    assert doc["delivered_to"] == ["hook-a", "hook-b"]
    # delivery after persist re-wrote the doc (3 writes total: persist,
    # then one per delivery)
    assert len(written) == 3
    summary = lr.summary()
    assert summary["candidates"] == 1
    assert summary["latency"]["n"] == 1
    assert set(summary["stages"]) >= {"read", "dispatch", "sift",
                                      "persist", "alert"}
    # discarded chunks leave no marks behind
    lr.mark(8192, "read")
    lr.discard(8192)
    assert 8192 not in lr._marks


# ---------------------------------------------------------------------------
# search_by_chunks integration
# ---------------------------------------------------------------------------

def test_lineage_false_and_empty_push_take_the_off_path(
        survey_file, baseline, tmp_path):
    """The CLI spelling of "off" — ``lineage=False`` (store_true flag
    not given) and an empty ``push`` list — must take the pre-PR code
    path, not call ``.mark`` on a bool (regression: test_cli_search)."""
    (ref_ledger, ref_cands), ref_fp = baseline
    out = str(tmp_path / "cli_off")
    hits, store = search_by_chunks(survey_file, output_dir=out,
                                   resume=True, lineage=False, push=[],
                                   **SEARCH_KW)
    assert len(hits) >= 1
    assert store.fingerprint == ref_fp
    assert _snapshot(out, ref_fp) == (ref_ledger, ref_cands)
    assert not glob.glob(os.path.join(out, "*.lineage.json"))


def test_search_armed_byte_identical_and_docs_complete(
        survey_file, baseline, tmp_path, sink):
    """The tentpole pin: lineage+push armed produces byte-identical
    candidates and ledger vs the off run, every persisted hit carries a
    lineage doc with monotone stages, and the sink receives exactly the
    science detections."""
    (ref_ledger, ref_cands), ref_fp = baseline
    docs_before = _counter("putpu_lineage_docs_total")
    out = str(tmp_path / "armed")
    hits, store = search_by_chunks(
        survey_file, output_dir=out, resume=True, lineage=True,
        push=[sink.url], **SEARCH_KW)
    assert store.fingerprint == ref_fp  # host-local knobs: same config
    ledger, cands = _snapshot(out, store.fingerprint)
    assert ledger == ref_ledger
    assert cands == ref_cands
    # every persisted hit has its lineage doc beside the npz pair
    assert len(hits) >= 1
    for istart, iend, info, _tab in hits:
        matches = glob.glob(os.path.join(
            out, f"*_{istart}-{iend}.lineage.json"))
        assert len(matches) == 1, \
            f"no lineage doc for hit {istart}-{iend}"
        with open(matches[0]) as f:
            doc = json.load(f)
        assert doc["schema_version"] == LINEAGE_SCHEMA_VERSION
        assert doc["fingerprint"] == store.fingerprint
        assert doc["chunk"] == istart and doc["iend"] == iend
        assert doc["snr"] == pytest.approx(info.snr)
        st = doc["stages"]
        order = [st[k] for k in ("read", "dispatch", "ready", "sift",
                                 "persist")]
        assert order == sorted(order), f"non-monotone stages: {st}"
    assert _counter("putpu_lineage_docs_total") \
        >= docs_before + len(hits)
    # the latency histogram (the SLO's series) observed every hit
    assert _counter("putpu_candidate_latency_seconds") >= len(hits)
    # the sink got exactly the science hits, chunk-for-chunk
    deadline = time.monotonic() + 10.0
    while len(sink.received) < len(hits) \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    assert sorted(a["chunk"] for a in sink.received) \
        == sorted(h[0] for h in hits)
    for alert in sink.received:
        assert alert["kind"] == "candidate"
        assert alert["fingerprint"] == store.fingerprint


def test_search_wedged_subscriber_never_stalls_driver(
        survey_file, baseline, tmp_path):
    """A hung webhook (accepts, never answers): the survey finishes in
    bounded time with byte-identical science outputs; undelivered
    alerts land in the dead-letter journal.  The broker is caller-owned
    here so its close is deterministic in the test; the armed test
    above exercises the driver-owned close path."""
    (ref_ledger, ref_cands), ref_fp = baseline
    hung = _Sink(hang_s=60.0)
    out = str(tmp_path / "wedged")
    dead = str(tmp_path / "dead.jsonl")
    broker = AlertBroker([hung.url], timeout_s=0.3, retries=0,
                         dead_letter_path=dead)
    t0 = time.monotonic()
    try:
        hits, store = search_by_chunks(
            survey_file, output_dir=out, resume=True,
            push=broker, **SEARCH_KW)
        wall = time.monotonic() - t0
        stats = broker.close(timeout_s=2.0)
    finally:
        hung.close()
    assert wall < 60.0, f"driver stalled {wall:.0f}s on a dead webhook"
    ledger, cands = _snapshot(out, store.fingerprint)
    assert ledger == ref_ledger and cands == ref_cands
    assert len(hits) >= 1
    # every alert the wedge swallowed is accounted for
    assert stats["published"] == len(hits)
    assert stats["delivered"] == 0
    assert os.path.exists(dead)
    with open(dead) as f:
        assert sum(1 for _ in f) >= 1


def test_canary_detections_never_pushed(tmp_path, sink):
    """Canary-topped chunks are tagged before the publish site: a
    noise-only survey under rate-1.0 injection recovers canaries but
    pushes NOTHING."""
    from pulsarutils_tpu.obs.canary import CanaryController

    rng = np.random.default_rng(3)
    arr = np.abs(rng.normal(0, 0.5, (NCHAN, NSAMPLES))) + 20.0
    header = {"bandwidth": 200., "fbottom": 1200., "nchans": NCHAN,
              "nsamples": NSAMPLES, "tsamp": TSAMP,
              "foff": 200. / NCHAN}
    path = str(tmp_path / "noise.fil")
    write_simulated_filterbank(path, arr, header, descending=True)
    canary = CanaryController(rate=1.0, dm=150.0, snr=15.0, seed=7)
    hits, _store = search_by_chunks(
        path, output_dir=str(tmp_path / "out"), resume=True,
        canary=canary, push=[sink.url], lineage=True, **SEARCH_KW)
    assert canary.summary()["recovered"] >= 1
    assert hits == []
    time.sleep(0.5)  # give a (wrong) delivery every chance to land
    assert sink.received == []


def test_delayed_persist_feeds_latency_histogram(survey_file, tmp_path,
                                                 monkeypatch):
    """A slow persist is visible end-to-end: the candidate-latency
    histogram (the SLO's series) observes the injected delay."""
    from pulsarutils_tpu.io.candidates import CandidateStore

    real = CandidateStore.save_candidate

    def slow(self, *a, **kw):
        time.sleep(0.25)
        return real(self, *a, **kw)

    monkeypatch.setattr(CandidateStore, "save_candidate", slow)
    reg_count0 = _counter("putpu_candidate_latency_seconds")
    lr = LineageRecorder(source="search_by_chunks")
    hits, _store = search_by_chunks(
        survey_file, output_dir=str(tmp_path / "slow"), resume=True,
        lineage=lr, **SEARCH_KW)
    assert len(hits) >= 1
    summary = lr.summary()
    assert summary["candidates"] == len(hits)
    assert summary["latency"]["max"] >= 0.25
    assert summary["stages"]["persist"]["max"] >= 0.25
    assert _counter("putpu_candidate_latency_seconds") >= reg_count0


# ---------------------------------------------------------------------------
# the candidate-latency SLO
# ---------------------------------------------------------------------------

def test_candidate_latency_slo_fires_and_resolves():
    from pulsarutils_tpu.obs.slo import SLOEngine, SLOSpec, default_slos

    base = {s.name: s for s in default_slos()}["candidate-latency-p95"]
    assert base.series == "putpu_candidate_latency_seconds"
    assert base.field == "p95" and base.op == "<="

    class _FakeSeries:
        def __init__(self, points):
            self._points = points

        def points(self, last=None):
            return list(self._points)

    spec = SLOSpec(base.name, objective=base.objective, kind=base.kind,
                   series=base.series, field=base.field,
                   bound=base.bound, op=base.op,
                   windows=((2.0, 4.0, 2.0, "page"),),
                   budget_window_s=10.0)
    health = HealthEngine()
    engine = SLOEngine([spec], health=health)
    slow = [{"t": 1000.0 + i,
             "series": {base.series: {"p95": base.bound * 4}}}
            for i in range(6)]
    alerts = engine.evaluate(_FakeSeries(slow), now=1005.0)
    assert [a.slo for a in alerts] == ["candidate-latency-p95"]
    assert "slo:candidate-latency-p95" in health.reasons()
    fast = slow + [{"t": 1006.0 + i,
                    "series": {base.series: {"p95": 0.5}}}
                   for i in range(6)]
    assert engine.evaluate(_FakeSeries(fast), now=1011.0) == []
    assert health.verdict == OK


# ---------------------------------------------------------------------------
# stream_search wiring
# ---------------------------------------------------------------------------

def _stream_chunks(seed=2, n=2):
    rng = np.random.default_rng(seed)
    chunks = []
    for i in range(n):
        arr = np.abs(rng.normal(0, 0.5, (NCHAN, 4096))) + 20.0
        if i == 1:
            arr[:, 2000] += 4.0
            arr = disperse_array(arr, 150.0, 1200., 200., TSAMP)
        chunks.append((i * 4096, arr))
    return chunks


def test_stream_search_lineage_and_push(sink):
    from pulsarutils_tpu.parallel.stream import stream_search

    lr = LineageRecorder(source="stream_search")
    results, hits = stream_search(
        _stream_chunks(), 100, 200, 1200., 200., TSAMP, backend="jax",
        snr_threshold=6.5, lineage=lr, push=[sink.url])
    assert len(hits) >= 1
    summary = lr.summary()
    assert summary["candidates"] == len(hits)
    # stream has no persist store: the emit point is persist-complete,
    # so latency is still measured (dispatch -> emit)
    assert summary["latency"]["n"] == len(hits)
    deadline = time.monotonic() + 10.0
    while len(sink.received) < len(hits) \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    assert sorted(a["chunk"] for a in sink.received) \
        == sorted(h[0] for h in hits)


def _stream_hit_key(hit):
    istart, _table, best = hit
    return (istart, float(best["DM"]), float(best["snr"]))


def test_stream_search_wedged_subscriber_bounded():
    from pulsarutils_tpu.parallel.stream import stream_search

    chunks = _stream_chunks()
    ref_results, ref_hits = stream_search(
        chunks, 100, 200, 1200., 200., TSAMP, backend="jax",
        snr_threshold=6.5)
    hung = _Sink(hang_s=60.0)
    t0 = time.monotonic()
    try:
        results, hits = stream_search(
            chunks, 100, 200, 1200., 200., TSAMP, backend="jax",
            snr_threshold=6.5, push=[hung.url])
    finally:
        hung.close()
    assert time.monotonic() - t0 < 60.0
    # science results untouched by the wedge
    assert [_stream_hit_key(h) for h in hits] \
        == [_stream_hit_key(h) for h in ref_hits]
    assert len(hits) >= 1


# ---------------------------------------------------------------------------
# /metrics manifest HELP + warn_unknown (satellite a)
# ---------------------------------------------------------------------------

def test_metrics_scrape_serves_manifest_help_and_warns_unknown(caplog):
    import logging

    from pulsarutils_tpu.obs import names as obs_names
    from pulsarutils_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("putpu_hits_total").inc(2)
    # an undeclared name created straight on a registry bypasses the
    # facade's creation-time warning — the scrape must catch it
    obs_names._warned.discard("putpu_totally_undeclared_total")
    reg.counter("putpu_totally_undeclared_total").inc()
    with caplog.at_level(logging.WARNING, logger="pulsarutils_tpu"):
        text = reg.prometheus_text(manifest_help=True)
        text2 = reg.prometheus_text(manifest_help=True)
    assert ("# HELP putpu_hits_total "
            + obs_names.METRIC_NAMES["putpu_hits_total"]) in text
    assert "putpu_totally_undeclared_total 1" in text
    warnings = [r for r in caplog.records
                if "putpu_totally_undeclared_total" in r.getMessage()]
    assert len(warnings) == 1  # once per name, not per scrape
    assert text == text2


def test_subscribe_endpoint_roundtrip(sink):
    import urllib.error
    import urllib.request

    from pulsarutils_tpu.obs.server import start_obs_server

    with AlertBroker([]) as broker:
        with start_obs_server(0, push=broker) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            req = urllib.request.Request(
                base + "/subscribe",
                data=json.dumps({"url": sink.url,
                                 "min_snr": 7.0}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 201
                doc = json.loads(resp.read())
            assert doc["min_snr"] == 7.0
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(urllib.request.Request(
                    base + "/subscribe", data=b'{"nope": 1}'))
            assert err.value.code == 400
            with urllib.request.urlopen(base + "/subscribers") as resp:
                listed = json.loads(resp.read())
            assert len(listed["subscribers"]) == 1
            # the runtime subscriber actually receives
            broker.publish({"kind": "candidate", "snr": 9.0})
            deadline = time.monotonic() + 10.0
            while not sink.received and time.monotonic() < deadline:
                time.sleep(0.02)
            assert len(sink.received) == 1


def test_subscribe_without_broker_is_404():
    import urllib.error
    import urllib.request

    from pulsarutils_tpu.obs.server import start_obs_server

    with start_obs_server(0) as srv:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/subscribe",
                data=b"{}"))
        assert err.value.code == 404


# ---------------------------------------------------------------------------
# time-series JSONL spill under sustained load (satellite c)
# ---------------------------------------------------------------------------

def test_timeseries_spill_bounded_growth_and_ring_consistency(tmp_path):
    from pulsarutils_tpu.obs.timeseries import TimeSeriesSampler

    reg = obs_metrics.MetricsRegistry()
    spill = str(tmp_path / "history.jsonl")
    sampler = TimeSeriesSampler(registry=reg, interval_s=1.0,
                                capacity=8, spill_path=spill)
    c = reg.counter("putpu_chunks_total")
    for i in range(50):
        c.inc()
        sampler.sample(now=1000.0 + i)
    # bounded growth: exactly one JSONL line per sample, no
    # amplification however long the run
    with open(spill) as f:
        lines = [json.loads(ln) for ln in f]
    assert len(lines) == 50
    # ring eviction vs spill consistency: the in-memory ring is exactly
    # the spill's tail
    ring = sampler.points()
    assert len(ring) == 8
    assert [p["t"] for p in ring] == [p["t"] for p in lines[-8:]]
    assert [p["series"]["putpu_chunks_total"]["total"] for p in ring] \
        == [p["series"]["putpu_chunks_total"]["total"]
            for p in lines[-8:]]


def test_history_endpoint_paging_at_ring_boundary(tmp_path):
    import urllib.request

    from pulsarutils_tpu.obs.server import start_obs_server
    from pulsarutils_tpu.obs.timeseries import TimeSeriesSampler

    reg = obs_metrics.MetricsRegistry()
    sampler = TimeSeriesSampler(registry=reg, interval_s=1.0,
                                capacity=4,
                                spill_path=str(tmp_path / "h.jsonl"))
    reg.counter("putpu_chunks_total").inc()
    for i in range(9):
        sampler.sample(now=2000.0 + i)
    with start_obs_server(0, timeseries=sampler) as srv:
        base = f"http://127.0.0.1:{srv.port}/metrics/history"

        def fetch(query=""):
            with urllib.request.urlopen(base + query) as resp:
                return json.loads(resp.read())["samples"]

        # last= at the ring boundary, inside it, and past it: the ring
        # is the source of truth, never the spill
        assert [p["t"] for p in fetch()] == [2005.0, 2006.0, 2007.0,
                                             2008.0]
        assert [p["t"] for p in fetch("?last=4")] \
            == [2005.0, 2006.0, 2007.0, 2008.0]
        assert [p["t"] for p in fetch("?last=2")] == [2007.0, 2008.0]
        assert [p["t"] for p in fetch("?last=99")] \
            == [2005.0, 2006.0, 2007.0, 2008.0]
        assert fetch("?last=0") == []


# ---------------------------------------------------------------------------
# report sections
# ---------------------------------------------------------------------------

def test_report_lineage_and_push_sections():
    from pulsarutils_tpu.obs.report import build_report, render_markdown

    lr = LineageRecorder(source="search_by_chunks")
    lr.mark(0, "read")
    lr.mark(0, "dispatch")
    lr.mark(0, "ready")
    cl = lr.candidate(0, 8192, snr=9.0)
    lr.persisted(cl)
    rec = build_report(meta={"root": "t"}, lineage=lr.summary(),
                       push={"subscribers": 1, "published": 3,
                             "delivered": 2, "filtered": 1,
                             "dropped": 0, "dead_lettered": 0,
                             "queued": 0})
    md = render_markdown(rec)
    assert "## Candidate latency" in md
    assert "Per-stage waterfall" in md and "| persist |" in md
    assert "**2 delivered**" in md
    # absence stated, never silently missing
    md_off = render_markdown(build_report(meta={"root": "t"}))
    assert "Lineage recording was off" in md_off
    assert "Alert push was off" in md_off


# ---------------------------------------------------------------------------
# fleet: worker knobs, coordinator rollup, merged candidate spans
# ---------------------------------------------------------------------------

def test_fleet_worker_lineage_push_rollup_and_candidate_spans(
        tmp_path, sink):
    from pulsarutils_tpu.fleet.coordinator import FleetCoordinator
    from pulsarutils_tpu.fleet.worker import FleetWorker
    from pulsarutils_tpu.obs.collector import TraceCollector
    from pulsarutils_tpu.obs.server import start_obs_server

    rng = np.random.default_rng(0)
    arr = np.abs(rng.normal(0, 0.5, (NCHAN, NSAMPLES))) + 20.0
    arr[:, PULSE_T] += 4.0
    arr = disperse_array(arr, 150.0, 1200., 200., TSAMP)
    header = {"bandwidth": 200., "fbottom": 1200., "nchans": NCHAN,
              "nsamples": NSAMPLES, "tsamp": TSAMP,
              "foff": 200. / NCHAN}
    fname = str(tmp_path / "a.fil")
    write_simulated_filterbank(fname, arr, header, descending=True)

    out = tmp_path / "fleet"
    collector = TraceCollector()
    with FleetCoordinator(str(out), lease_ttl_s=120.0,
                          probe_interval_s=0.5,
                          collector=collector) as coordinator:
        with start_obs_server(0, fleet=coordinator) as srv:
            url = f"http://127.0.0.1:{srv.port}"
            coordinator.add_survey([fname], **{
                k: v for k, v in SEARCH_KW.items()
                if k in ("dmmin", "dmmax", "chunk_length",
                         "snr_threshold")})
            worker = FleetWorker(url, http_port=None, trace=True,
                                 lineage=True, push=[sink.url])
            worker.run(max_idle_s=60.0)
            assert coordinator.survey_done
            summary = coordinator.summary()
    # the delivery rollup rode the completion's metrics snapshot
    assert summary["push"]["putpu_push_delivered_total"] >= 1
    # the lineage doc landed beside the fleet-written candidate
    docs = glob.glob(os.path.join(str(out), "*.lineage.json"))
    assert len(docs) >= 1
    with open(docs[0]) as f:
        doc = json.load(f)
    # the merged trace has the candidate span INSIDE the unit's
    # distributed trace: same trace_id as the lease stamped
    chrome = collector.to_chrome()
    cand_spans = [ev for ev in chrome["traceEvents"]
                  if ev.get("name") == "candidate"
                  and ev.get("ph") == "b"]
    assert cand_spans, "no candidate span reached the collector"
    assert any((ev.get("args") or {}).get("trace_id")
               == doc["trace_id"] for ev in cand_spans)
    unit_ids = {(ev.get("args") or {}).get("trace_id")
                for ev in chrome["traceEvents"]
                if ev.get("name") == "unit"}
    assert doc["trace_id"] in unit_ids
    # the alert reached the webhook from the fleet path too
    assert any(a.get("chunk") == doc["chunk"] for a in sink.received)


def test_coordinator_summary_push_rollup_absent_when_off(tmp_path):
    from pulsarutils_tpu.fleet.coordinator import FleetCoordinator

    with FleetCoordinator(str(tmp_path / "c")) as coordinator:
        assert "push" not in coordinator.summary()


# ---------------------------------------------------------------------------
# trace_merge filters (satellite b)
# ---------------------------------------------------------------------------

def _fake_trace(path, events):
    doc = {"traceEvents": events,
           "putpu": {"epoch_unix": 1000.0, "clock_offset_s": 0.0}}
    with open(path, "w") as f:
        json.dump(doc, f)
    return str(path)


def test_trace_merge_candidate_and_trace_id_filters(tmp_path, capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "trace_merge", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "trace_merge.py"))
    tm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tm)

    coord = _fake_trace(tmp_path / "coord.json", [
        {"name": "clock_sync", "ph": "X", "pid": 1, "tid": 1, "ts": 0.0,
         "dur": 1.0},
        {"name": "unit", "ph": "X", "pid": 1, "tid": 1, "ts": 10.0,
         "dur": 50.0, "args": {"trace_id": "aaa111"}},
        {"name": "unit", "ph": "X", "pid": 1, "tid": 1, "ts": 70.0,
         "dur": 50.0, "args": {"trace_id": "bbb222"}}])
    worker = _fake_trace(tmp_path / "worker.json", [
        {"name": "candidate", "ph": "b", "cat": "async", "id": 1,
         "pid": 1, "tid": 2, "ts": 20.0,
         "args": {"chunk": 8192, "trace_id": "aaa111"}},
        {"name": "candidate", "ph": "e", "cat": "async", "id": 1,
         "pid": 1, "tid": 2, "ts": 30.0},
        {"name": "chunk", "ph": "X", "pid": 1, "tid": 2, "ts": 15.0,
         "dur": 40.0, "args": {"trace_id": "bbb222"}}])

    out = str(tmp_path / "merged.json")
    assert tm.main([out, coord, worker, "--candidate", "8192"]) == 0
    with open(out) as f:
        doc = json.load(f)
    names = [ev["name"] for ev in doc["traceEvents"]
             if ev.get("ph") not in ("M",)]
    # kept: the clock anchor, the aaa111 unit, the candidate b/e pair;
    # dropped: the bbb222 unit and chunk spans
    assert names.count("candidate") == 2
    assert names.count("unit") == 1
    assert "chunk" not in names
    assert "clock_sync" in names

    out2 = str(tmp_path / "merged2.json")
    assert tm.main([out2, coord, worker, "--trace-id", "bbb222"]) == 0
    with open(out2) as f:
        doc2 = json.load(f)
    names2 = [ev["name"] for ev in doc2["traceEvents"]
              if ev.get("ph") not in ("M",)]
    assert "chunk" in names2 and "candidate" not in names2

    # an unknown candidate chunk is an error, not an empty file
    assert tm.main([str(tmp_path / "x.json"), coord, worker,
                    "--candidate", "424242"]) == 1
