"""OOM-resilient dispatch (ISSUE 12): preflight memory budgeting, the
degradation ladder, and admission control.

The contracts under test:

* classification — ``RESOURCE_EXHAUSTED`` is recognised (and the PR 4
  transient faults are NOT), the ``kind="oom"`` injection raises the
  real production shape;
* byte identity — every ladder rung re-dispatches byte-identical work:
  the direct sweep's split trial passes (roll + gather), the mesh
  hybrid's un-fuse, the beam batch halving (packed + float), end to
  end through ``search_by_chunks`` / ``stream_search``;
* containment — a persistent floor OOM quarantines the chunk as
  ``oom_floor`` (exact resume, clean audit) instead of killing the
  survey, and the health verdict walks DEGRADED/CRITICAL -> OK;
* admission — the service caps co-batches to the memory budget, a
  fleet worker's ``too_large`` release makes the coordinator re-shard
  the unit smaller (over the real HTTP wire), and fleet wire calls
  survive transient transport failures.
"""

import os

import numpy as np
import pytest

from pulsarutils_tpu.faults.inject import FaultPlan, FaultSpec
from pulsarutils_tpu.faults import inject as fault_inject
from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
from pulsarutils_tpu.models.simulate import disperse_array
from pulsarutils_tpu.obs.metrics import REGISTRY
from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks
from pulsarutils_tpu.resilience import ladder
from pulsarutils_tpu.resilience import memory_budget as membudget

pytestmark = pytest.mark.chaos

TSAMP = 0.0005
NCHAN = 64
NSAMPLES = 32768
CHUNK_LEN_S = 8192 * TSAMP          # -> step 16384, hop 8192
PULSE_T = 20000                     # noise chunk: 0; hit chunks: 8192, 16384
SEARCH_KW = dict(dmmin=100, dmmax=200, backend="jax",
                 chunk_length=CHUNK_LEN_S, make_plots=False,
                 progress=False, snr_threshold=6.5)
GEOM = (1200.0, 200.0, TSAMP)       # start_freq, bandwidth, tsamp


def _csum(name):
    """Counter total across every label set."""
    return sum(r["value"] for r in REGISTRY.snapshot()
               if r["name"] == name)


@pytest.fixture(autouse=True)
def _fresh_ladder():
    """Every test starts (and leaves) the global ladder undegraded —
    a failed assertion must not leak a degraded level into later
    tests or other modules."""
    ladder.reset()
    yield
    ladder.reset()


@pytest.fixture(scope="module")
def survey_file(tmp_path_factory):
    from pulsarutils_tpu.pipeline.spectral_stats import get_bad_chans

    tmp = tmp_path_factory.mktemp("resilience")
    rng = np.random.default_rng(0)
    array = np.abs(rng.normal(0, 0.5, (NCHAN, NSAMPLES))) + 20.0
    array[:, PULSE_T] += 4.0
    array = disperse_array(array, 150, 1200., 200., TSAMP)
    sim_header = {"bandwidth": 200., "fbottom": 1200., "nchans": NCHAN,
                  "nsamples": NSAMPLES, "tsamp": TSAMP,
                  "foff": 200. / NCHAN}
    path = str(tmp / "survey.fil")
    write_simulated_filterbank(path, array, sim_header, descending=True)
    get_bad_chans(path)
    return path


def _snapshot(outdir, fingerprint):
    with open(os.path.join(outdir, f"progress_{fingerprint}.json"),
              "rb") as f:
        ledger = f.read()
    cands = {}
    for name in sorted(os.listdir(outdir)):
        if name.endswith(".npz"):
            with np.load(os.path.join(outdir, name),
                         allow_pickle=False) as d:
                cands[name] = {k: d[k].tobytes() for k in d.files}
    return ledger, cands


# ---------------------------------------------------------------------------
# classification + injection shape
# ---------------------------------------------------------------------------

def test_is_resource_exhausted_classifier():
    class XlaRuntimeError(RuntimeError):
        pass

    assert ladder.is_resource_exhausted(
        XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying "
                        "to allocate 17179869184 bytes."))
    assert ladder.is_resource_exhausted(MemoryError())
    assert ladder.is_resource_exhausted(
        RuntimeError("Resource exhausted: ran out of HBM"))
    # PR 4's transient faults stay with the retry path
    assert not ladder.is_resource_exhausted(
        RuntimeError("FAULTPLAN: injected dispatch error (chunk=0)"))
    # deterministic configuration errors are never OOM, whatever the text
    assert not ladder.is_resource_exhausted(
        ValueError("Out of memory-shaped but a config error"))


def test_inject_oom_kind_is_production_shaped():
    plan = FaultPlan([FaultSpec(site="dispatch", kind="oom", times=1),
                      FaultSpec(site="host", kind="oom", times=1)])
    with plan.armed():
        with pytest.raises(Exception) as exc_info:
            fault_inject.fire("dispatch", chunk=0)
        exc = exc_info.value
        assert type(exc).__name__ == "XlaRuntimeError"
        assert "RESOURCE_EXHAUSTED" in str(exc)
        assert ladder.is_resource_exhausted(exc)
        # the ladder-floor seam raises host memory exhaustion
        with pytest.raises(MemoryError):
            fault_inject.fire("host", chunk=0)
    assert plan.fired() == 2


# ---------------------------------------------------------------------------
# the ladder's level plumbing
# ---------------------------------------------------------------------------

def test_direct_plan_levels_and_maxing():
    assert ladder.direct_plan("roll", nblocks=8) == 1
    assert not ladder.unfuse_engaged()
    ladder.descend("split_dm")
    assert ladder.direct_plan("roll", nblocks=8) == 2
    assert ladder.unfuse_engaged()
    ladder.descend("split_dm")
    assert ladder.direct_plan("gather", nblocks=8) == 4
    assert not ladder.direct_maxed("gather", nblocks=8)
    ladder.descend("split_dm")
    assert ladder.direct_plan("gather", nblocks=8) == 8
    assert ladder.direct_maxed("gather", nblocks=8)
    # the pass count floors at one block per dispatch
    ladder.descend("split_dm")
    assert ladder.direct_plan("roll", nblocks=8) == 8
    ladder.reset()
    assert ladder.direct_plan("roll", nblocks=8) == 1


# ---------------------------------------------------------------------------
# estimator + calibration
# ---------------------------------------------------------------------------

def test_estimate_direct_terms_scale():
    one = membudget.estimate_direct(64, 4096, 128)
    assert set(one) == {"operand", "workspace", "scoring", "outputs",
                        "total"}
    assert one["total"] == sum(v for k, v in one.items() if k != "total")
    # the batch axis multiplies the operand only (lax.map serialises
    # per-beam bodies)
    four = membudget.estimate_direct(64, 4096, 128, batch=4)
    assert four["operand"] == 4 * one["operand"]
    assert four["workspace"] == one["workspace"]
    # a packed operand adds the raw frames on top of the float view
    packed = membudget.estimate_direct(64, 4096, 128, packed_nbits=2)
    assert packed["operand"] == one["operand"] + 64 * 4096 * 2 // 8
    # plane capture dominates the output side
    cap = membudget.estimate_direct(64, 4096, 128, capture_plane=True)
    assert cap["outputs"] > one["outputs"]
    # splitting trial passes shrinks the per-dispatch outputs
    split = membudget.estimate_direct(64, 4096, 128, capture_plane=True,
                                      dm_passes=4)
    assert split["outputs"] < cap["outputs"]


def test_budget_env_override(monkeypatch):
    monkeypatch.setenv(membudget.MEM_LIMIT_ENV, "123456789")
    assert membudget.device_budget_bytes() == 123456789
    assert membudget.headroom_bytes() is not None
    monkeypatch.delenv(membudget.MEM_LIMIT_ENV)
    # CPU's live-array fallback reports no limit: budget unknown
    assert membudget.device_budget_bytes() is None
    assert membudget.headroom_bytes() is None


def test_calibration_roundtrip_beside_tune_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("PUTPU_TUNE_CACHE",
                       str(tmp_path / "a" / "tune_cache.json"))
    path = membudget.calibration_path()
    assert os.path.dirname(path) == str(tmp_path / "a")
    assert membudget.calibration_offset("k") == 1.0
    membudget.record_calibration("k", estimated=100.0, measured=50.0)
    assert membudget.calibration_offset("k") == pytest.approx(0.5)
    assert membudget.calibrated("k", 200.0) == pytest.approx(100.0)
    assert os.path.exists(path)
    # EWMA folding: a later outlier moves the offset 30%, not all the way
    membudget.record_calibration("k", estimated=100.0, measured=150.0)
    assert membudget.calibration_offset("k") \
        == pytest.approx(0.7 * 0.5 + 0.3 * 1.5)
    # a torn calibration file degrades to the raw model, never fails
    monkeypatch.setenv("PUTPU_TUNE_CACHE",
                       str(tmp_path / "b" / "tune_cache.json"))
    os.makedirs(tmp_path / "b")
    with open(membudget.calibration_path(), "w") as f:
        f.write("{torn")
    assert membudget.calibration_offset("k") == 1.0


def test_preflight_splits_before_dispatch(monkeypatch, rng):
    """A dispatch whose estimate exceeds PUTPU_MEM_LIMIT splits before
    compiling — and the split table is byte-identical to the
    unconstrained one."""
    from pulsarutils_tpu.ops.search import dedispersion_search

    data = np.abs(rng.normal(0, 1, (64, 4096))).astype(np.float32) + 5
    kw = dict(dmmin=100, dmmax=300, start_freq=1200., bandwidth=200.,
              sample_time=TSAMP, backend="jax", kernel="roll")
    t_free = dedispersion_search(data, **kw)
    ladder.reset()
    monkeypatch.setenv(membudget.MEM_LIMIT_ENV, "100000")  # ~100 kB
    before = _csum("putpu_oom_splits_total")
    t_tight = dedispersion_search(data, **kw)
    assert _csum("putpu_oom_splits_total") > before
    assert ladder.level() > 0
    for col in t_free.colnames:
        assert np.array_equal(np.asarray(t_free[col]),
                              np.asarray(t_tight[col])), col


@pytest.mark.parametrize("kernel", ["roll", "gather"])
def test_direct_sweep_split_byte_identity(kernel, rng):
    """The split_dm rung: every degradation level's table equals the
    level-0 table byte for byte, both formulations."""
    from pulsarutils_tpu.ops.search import dedispersion_search

    data = np.abs(rng.normal(0, 1, (64, 4096))).astype(np.float32) + 5
    kw = dict(dmmin=100, dmmax=300, start_freq=1200., bandwidth=200.,
              sample_time=TSAMP, backend="jax", kernel=kernel)
    t0 = dedispersion_search(data, **kw)
    for _ in range(3):
        ladder.descend("split_dm")
        t = dedispersion_search(data, **kw)
        for col in t0.colnames:
            assert np.array_equal(np.asarray(t0[col]),
                                  np.asarray(t[col])), \
                f"{col} diverged at ladder level {ladder.level()}"


# ---------------------------------------------------------------------------
# end to end: search_by_chunks / stream_search / mesh hybrid / beams
# ---------------------------------------------------------------------------

def test_search_by_chunks_transient_oom_byte_identical(survey_file,
                                                       tmp_path):
    """One injected RESOURCE_EXHAUSTED: the ladder descends, the run
    recovers, and candidates + ledger match the clean run byte for
    byte; health flags memory_pressure and decays back to OK."""
    from pulsarutils_tpu.obs.health import HealthEngine

    _, store = search_by_chunks(survey_file,
                                output_dir=str(tmp_path / "clean"),
                                **SEARCH_KW)
    base = _snapshot(str(tmp_path / "clean"), store.fingerprint)

    plan = FaultPlan([FaultSpec(site="dispatch", kind="oom", chunks=(0,),
                                times=1)])
    engine = HealthEngine()
    before = _csum("putpu_oom_events_total")
    with plan.armed():
        search_by_chunks(survey_file, output_dir=str(tmp_path / "oom"),
                         health=engine, **SEARCH_KW)
    assert plan.fired() == 1
    assert _csum("putpu_oom_events_total") > before
    assert _snapshot(str(tmp_path / "oom"), store.fingerprint) == base
    kinds = [t["to"] for t in engine.transitions]
    assert "DEGRADED" in kinds and engine.verdict == "OK"
    assert any("memory_pressure" in t["reasons"]
               for t in engine.transitions)


def test_oom_floor_quarantines_and_resumes_exactly(survey_file, tmp_path):
    """Persistent floor OOM on one chunk: quarantined as oom_floor,
    audit clean, resume searches nothing again, verdict CRITICAL -> OK."""
    from pulsarutils_tpu.faults.audit import audit_run
    from pulsarutils_tpu.obs.health import HealthEngine

    plan = FaultPlan([
        FaultSpec(site="dispatch", kind="oom", chunks=(0,), times=None),
        FaultSpec(site="host", kind="oom", chunks=(0,), times=None)])
    engine = HealthEngine()
    before = _csum("putpu_oom_floor_total")
    with plan.armed():
        hits, store = search_by_chunks(survey_file,
                                       output_dir=str(tmp_path),
                                       health=engine, **SEARCH_KW)
    assert _csum("putpu_oom_floor_total") == before + 1
    assert store.quarantined_chunks.get("0") == "oom_floor"
    assert any(lo <= PULSE_T < hi for lo, hi, _, _ in hits), \
        "the clean chunks must still find the pulse"
    report = audit_run(str(tmp_path), store.fingerprint, root="survey")
    assert report["ok"], report["issues"]
    worst = [t["to"] for t in engine.transitions]
    assert "CRITICAL" in worst and engine.verdict == "OK"
    # exact resume: the quarantined chunk is done-with-reason, so a
    # resumed session has nothing left to search
    with plan.armed():  # would fire again if chunk 0 were re-dispatched
        fired_before = plan.fired()
        search_by_chunks(survey_file, output_dir=str(tmp_path),
                         **SEARCH_KW)
    assert plan.fired() == fired_before


def test_stream_search_oom_byte_identical(rng):
    from pulsarutils_tpu.parallel.stream import stream_search

    chunks = [(s, np.abs(rng.normal(0, 1, (32, 2048))
                         ).astype(np.float32) + 5)
              for s in (0, 1024, 2048)]
    res0, _ = stream_search(list(chunks), 100, 200, *GEOM)
    plan = FaultPlan([FaultSpec(site="dispatch", kind="oom", chunks=(0,),
                                times=1)])
    with plan.armed():
        res1, _ = stream_search(list(chunks), 100, 200, *GEOM)
    assert plan.fired() == 1
    assert len(res0) == len(res1)
    for (i0, t0), (i1, t1) in zip(res0, res1):
        assert i0 == i1
        for col in t0.colnames:
            assert np.array_equal(np.asarray(t0[col]),
                                  np.asarray(t1[col])), col


def test_mesh_fused_hybrid_oom_unfuses_bitwise():
    """The unfuse rung: an OOM at the fused mesh dispatch drops to the
    two-stage composition, whose result is pinned bit-identical."""
    import jax

    from pulsarutils_tpu.models.simulate import simulate_test_data
    from pulsarutils_tpu.parallel.mesh import make_mesh
    from pulsarutils_tpu.parallel.sharded_fdmt import sharded_hybrid_search

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    array, header = simulate_test_data(150, nchan=64, nsamples=4096,
                                       signal=2.0, noise=0.4, rng=51)
    args = (100, 200.0, header["fbottom"], header["bandwidth"],
            header["tsamp"])
    mesh = make_mesh((4, 2), ("dm", "chan"))
    t_clean = sharded_hybrid_search(array, *args, mesh=mesh)
    plan = FaultPlan([FaultSpec(site="mesh", kind="oom", times=1)])
    before = _csum("putpu_oom_events_total")
    with plan.armed():
        t_oom = sharded_hybrid_search(array, *args, mesh=mesh)
    assert plan.fired() == 1
    assert _csum("putpu_oom_events_total") > before
    assert ladder.unfuse_engaged()
    for col in t_clean.colnames:
        assert np.array_equal(np.asarray(t_clean[col]),
                              np.asarray(t_oom[col])), col
    assert t_clean.meta == t_oom.meta
    # the engaged level keeps later chunks on the two-stage path —
    # still identical (fused == unfused is the PR 2 contract)
    t_next = sharded_hybrid_search(array, *args, mesh=mesh)
    assert np.array_equal(np.asarray(t_clean["snr"]),
                          np.asarray(t_next["snr"]))


@pytest.mark.parametrize("kernel", ["roll", "gather"])
@pytest.mark.parametrize("packed", [False, True])
def test_beam_batcher_oom_splits_byte_identical(kernel, packed, rng):
    """The halve_batch rung (satellite): a forced mid-batch OOM splits
    N beams into two half-batches whose per-beam tables are
    byte-identical to the unsplit dispatch — both formulations, packed
    and float inputs."""
    from pulsarutils_tpu.beams.batcher import BeamBatcher
    from pulsarutils_tpu.io.lowbit import pack_numpy

    nchan, nsamps, nbits = 32, 2048, 2
    dms = np.linspace(100.0, 200.0, 16)
    if packed:
        def beam(seed):
            codes = np.random.default_rng(seed).integers(
                0, 1 << nbits, (nchan, nsamps))
            return np.stack([pack_numpy(codes[::-1, t], nbits)
                             for t in range(nsamps)])
        batcher = BeamBatcher(nchan, nsamps, dms, *GEOM, kernel=kernel,
                              packed=(nbits, True))
    else:
        def beam(seed):
            return np.abs(np.random.default_rng(seed).normal(
                0, 1, (nchan, nsamps))).astype(np.float32) + 5
        batcher = BeamBatcher(nchan, nsamps, dms, *GEOM, kernel=kernel)
    blocks = [beam(s) for s in range(4)]
    unsplit = batcher.search(blocks)
    plan = FaultPlan([FaultSpec(site="beams", kind="oom", times=1)])
    before = _csum("putpu_oom_ladder_steps_total")
    with plan.armed():
        split = batcher.search(blocks)
    ladder.reset()
    assert plan.fired() == 1
    assert _csum("putpu_oom_ladder_steps_total") > before
    assert len(split) == len(unsplit) == 4
    for tu, ts in zip(unsplit, split):
        for col in tu.colnames:
            assert np.array_equal(np.asarray(tu[col]),
                                  np.asarray(ts[col])), col


def test_beam_batcher_preflight_cap(monkeypatch, rng):
    """Admission preflight: with a tiny budget the batcher splits the
    dispatch up front (no OOM needed), results unchanged."""
    from pulsarutils_tpu.beams.batcher import BeamBatcher

    nchan, nsamps = 32, 2048
    dms = np.linspace(100.0, 200.0, 16)
    batcher = BeamBatcher(nchan, nsamps, dms, *GEOM, kernel="roll")
    blocks = [np.abs(rng.normal(0, 1, (nchan, nsamps))
                     ).astype(np.float32) + 5 for _ in range(3)]
    free = batcher.search(blocks)
    monkeypatch.setenv(membudget.MEM_LIMIT_ENV, "1000000")
    assert batcher.max_batch() == 1
    capped = batcher.search(blocks)
    for tf, tc in zip(free, capped):
        for col in tf.colnames:
            assert np.array_equal(np.asarray(tf[col]),
                                  np.asarray(tc[col])), col


# ---------------------------------------------------------------------------
# health + report surfacing
# ---------------------------------------------------------------------------

def test_health_engine_oom_conditions():
    from pulsarutils_tpu.obs.health import HealthEngine

    engine = HealthEngine(recover_after=2)
    assert engine.update(0, oom_events=1) == "DEGRADED"
    assert "memory_pressure" in engine.reasons()
    assert engine.update(1, oom_floor=True) == "CRITICAL"
    assert "oom_floor" in engine.reasons()
    engine.update(2)
    assert engine.update(3) == "OK", "conditions must decay on clean chunks"


def test_report_memory_pressure_section():
    from pulsarutils_tpu.obs.report import build_report, render_markdown

    rec = build_report(meta={"root": "x"}, metrics=[
        {"name": "putpu_oom_events_total", "type": "counter",
         "labels": {"surface": "direct_sweep"}, "value": 3}])
    md = render_markdown(rec)
    assert "## Memory pressure" in md
    assert "oom_events_total{surface=direct_sweep}" in md
    # absence stated
    md_clean = render_markdown(build_report(meta={"root": "x"},
                                            metrics=[]))
    assert "No memory pressure" in md_clean


# ---------------------------------------------------------------------------
# service admission control
# ---------------------------------------------------------------------------

def test_service_admission_caps_cobatch(tmp_path, monkeypatch):
    """Two same-geometry tenants under a tiny memory budget: both jobs
    are accepted and finish, but each runs in its own capped batch
    (batch_group of 1) instead of being co-batched into an OOM."""
    import time as _time

    from pulsarutils_tpu.beams.service import SurveyService

    rng = np.random.default_rng(3)
    paths = []
    for i in range(2):
        array = np.abs(rng.normal(0, 0.5, (32, 8192))) + 20.0
        array[:, 4000] += 4.0
        array = disperse_array(array, 150, 1200., 200., TSAMP)
        header = {"bandwidth": 200., "fbottom": 1200., "nchans": 32,
                  "nsamples": 8192, "tsamp": TSAMP, "foff": 200. / 32}
        p = str(tmp_path / f"beam{i}.fil")
        write_simulated_filterbank(p, array, header, descending=True)
        paths.append(p)
    monkeypatch.setenv(membudget.MEM_LIMIT_ENV, "1000000")
    before = _csum("putpu_oom_admission_capped_total")
    with SurveyService(str(tmp_path / "out"),
                       batch_window_s=0.3) as service:
        ids = [service.submit({"fname": p, "dmmin": 100.0,
                               "dmmax": 200.0, "snr_threshold": 6.5})
               for p in paths]
        deadline = _time.time() + 120
        while _time.time() < deadline:
            docs = [service.get(j) for j in ids]
            if all(d["state"] in ("done", "failed") for d in docs):
                break
            _time.sleep(0.2)
    docs = [d for d in docs]
    assert [d["state"] for d in docs] == ["done", "done"], docs
    assert all(len(d["batch_group"]) == 1 for d in docs), \
        "admission control must cap the co-batch at the budgeted width"
    assert _csum("putpu_oom_admission_capped_total") > before


# ---------------------------------------------------------------------------
# fleet: wire retries, budget-sized leases, too_large re-shard
# ---------------------------------------------------------------------------

def test_post_json_retry_counts_and_gives_up(monkeypatch):
    from pulsarutils_tpu.fleet import protocol

    calls = {"n": 0}

    def flaky(url, doc, timeout=10.0):
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionResetError("peer reset")
        return {"ok": True}

    monkeypatch.setattr(protocol, "post_json", flaky)
    before = _csum("putpu_fleet_wire_retries_total")
    assert protocol.post_json_retry("http://x", {}, backoff_s=0.0,
                                    jitter_s=0.0) == {"ok": True}
    assert calls["n"] == 3
    assert _csum("putpu_fleet_wire_retries_total") == before + 2

    # an HTTP status error is an answer, not weather: no retry
    def rejected(url, doc, timeout=10.0):
        calls["n"] += 1
        raise ValueError("http://x -> HTTP 400: bad")

    calls["n"] = 0
    monkeypatch.setattr(protocol, "post_json", rejected)
    with pytest.raises(ValueError):
        protocol.post_json_retry("http://x", {}, backoff_s=0.0)
    assert calls["n"] == 1

    # a persistently dead link propagates the transport error
    def dead(url, doc, timeout=10.0):
        raise ConnectionRefusedError("nope")

    monkeypatch.setattr(protocol, "post_json", dead)
    with pytest.raises(ConnectionRefusedError):
        protocol.post_json_retry("http://x", {}, retries=1,
                                 backoff_s=0.0, jitter_s=0.0)


def test_fleet_too_large_release_reshards_over_http(survey_file,
                                                    tmp_path):
    """Over the real wire: a register carries the worker's memory
    budget, an over-budget worker's too_large release makes the
    coordinator split the unit smaller (without draining the worker),
    budget-sized grants re-shard at grant time, and a real worker then
    finishes the survey byte-identical to the single-process run."""
    from pulsarutils_tpu.fleet.coordinator import FleetCoordinator
    from pulsarutils_tpu.fleet.protocol import post_json
    from pulsarutils_tpu.fleet.worker import FleetWorker
    from pulsarutils_tpu.obs.server import start_obs_server

    _, store = search_by_chunks(survey_file,
                                output_dir=str(tmp_path / "single"),
                                **SEARCH_KW)
    base = _snapshot(str(tmp_path / "single"), store.fingerprint)

    outdir = str(tmp_path / "fleet")
    coordinator = FleetCoordinator(outdir, chunks_per_unit=3,
                                   auto_sweep=False)
    server = start_obs_server(0, fleet=coordinator)
    url = f"http://127.0.0.1:{server.port}"
    try:
        config = {k: v for k, v in SEARCH_KW.items()
                  if k not in ("make_plots", "progress")}
        coordinator.add_survey([survey_file], **config)
        chunk_est = coordinator._files[
            os.path.abspath(survey_file)]["chunk_est_bytes"]
        assert chunk_est > 0

        # a worker with no budget gets the whole 3-chunk unit...
        post_json(url + "/fleet/register",
                  {"healthz_url": None, "worker": "big"})
        resp = post_json(url + "/fleet/lease",
                         {"worker": "big", "max_units": 1})
        (lease,) = resp["leases"]
        assert len(lease["chunks"]) == 3
        # ...and releases it too_large: the coordinator re-shards it
        before = _csum("putpu_fleet_units_resharded_total")
        post_json(url + "/fleet/release",
                  {"worker": "big", "leases": [lease["lease"]],
                   "reason": "too_large"})
        assert _csum("putpu_fleet_units_resharded_total") == before + 1
        sizes = sorted(len(u["chunks"]) for u in (
            unit.doc() for unit in coordinator._units.values())
            if u["state"] == "pending")
        assert sizes == [1, 2], \
            "the 3-chunk unit must be re-sharded into smaller units"
        # too_large does NOT drain the worker: it can still lease
        resp = post_json(url + "/fleet/lease",
                         {"worker": "big", "max_units": 1})
        assert resp["denied"] is None and resp["leases"]
        post_json(url + "/fleet/release",
                  {"worker": "big",
                   "leases": [le["lease"] for le in resp["leases"]],
                   "reason": "handover"})

        # a budget-reporting worker's grants are sized at grant time
        post_json(url + "/fleet/register",
                  {"healthz_url": None, "worker": "small",
                   "mem_budget_bytes": int(chunk_est * 1.5)})
        doc = coordinator.workers_doc()
        small = next(w for w in doc["workers"]
                     if w["worker"] == "small")
        assert small["mem_budget_bytes"] == int(chunk_est * 1.5)
        resp = post_json(url + "/fleet/lease",
                         {"worker": "small", "max_units": 1})
        (lease,) = resp["leases"]
        assert len(lease["chunks"]) == 1, \
            "the lease must be sized to the reported budget"
        post_json(url + "/fleet/release",
                  {"worker": "small", "leases": [lease["lease"]],
                   "reason": "handover"})

        # a 2-worker fleet — with a transient OOM landing mid-survey —
        # finishes the re-sharded survey byte-identical to the
        # single-process run (the acceptance contract: the worker's
        # own degradation ladder recovers, no steal, no divergence)
        import threading

        plan = FaultPlan([FaultSpec(site="dispatch", kind="oom",
                                    chunks=(0,), times=1)])
        workers = [FleetWorker(url, http_port=None) for _ in range(2)]
        with plan.armed():
            threads = [threading.Thread(target=w.run,
                                        kwargs={"max_idle_s": 30})
                       for w in workers]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
        assert plan.fired() == 1
        assert coordinator.survey_done
        assert _snapshot(outdir, store.fingerprint) == base
    finally:
        server.close()
        coordinator.close()


def test_register_rejects_bogus_budget(tmp_path):
    from pulsarutils_tpu.fleet.coordinator import FleetCoordinator

    coordinator = FleetCoordinator(str(tmp_path), auto_sweep=False)
    try:
        with pytest.raises(ValueError, match="mem_budget_bytes"):
            coordinator.register({"healthz_url": None,
                                  "mem_budget_bytes": -5})
    finally:
        coordinator.close()
