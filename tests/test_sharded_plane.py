"""ShardedPlane: device-resident DM-sharded plane + shard-local products.

Round-3 verdict item 1: the mesh path must not be a capability subset —
plane capture, per-row periodicity spectra, the per-row H curve and the
figure's plane image all work without gathering the plane.
"""
import numpy as np
import pytest

from pulsarutils_tpu.ops.plan import dedispersion_shifts
from pulsarutils_tpu.ops.search import dedispersion_search
from pulsarutils_tpu.parallel.mesh import make_mesh
from pulsarutils_tpu.parallel.sharded import sharded_dedispersion_search
from pulsarutils_tpu.parallel.sharded_fdmt import (
    sharded_fdmt_search,
    sharded_hybrid_search,
    slice_delay_range,
)


@pytest.fixture(scope="module")
def pulse_data():
    rng = np.random.default_rng(7)
    nchan, t = 64, 2048
    data = rng.normal(size=(nchan, t)).astype(np.float32)
    shifts = dedispersion_shifts(nchan, 150.0, 1400.0, 300.0, 1e-3)
    for c in range(nchan):
        data[c, (500 + int(round(shifts[c]))) % t] += 12.0
    return data


ARGS = (100, 200, 1400.0, 300.0, 1e-3)


@pytest.fixture(scope="module")
def fdmt_capture(pulse_data):
    mesh = make_mesh((4, 2), ("dm", "chan"))
    table, plane = sharded_fdmt_search(pulse_data, *ARGS, mesh=mesh,
                                       capture_plane=True)
    return table, plane


def test_sharded_fdmt_plane_matches_single_device(pulse_data, fdmt_capture):
    table, plane = fdmt_capture
    t0, plane0 = dedispersion_search(pulse_data, *ARGS, backend="jax",
                                     kernel="fdmt", capture_plane=True)
    plane0 = np.asarray(plane0)
    assert plane.shape == plane0.shape
    np.testing.assert_allclose(plane.to_host(), plane0, atol=1e-3)
    # scalar row fetch (the argbest-profile path) without a full gather
    np.testing.assert_allclose(plane.row(5), plane0[5], atol=1e-3)
    np.testing.assert_allclose(plane[table.argbest()],
                               plane0[t0.argbest()], atol=1e-3)
    with pytest.raises(TypeError):
        plane[1:3]


def test_spectral_scores_match_host(fdmt_capture):
    """Shard-local periodicity stage 1 == the host spectral search on the
    same rows (row-local computation, sharding changes nothing)."""
    from pulsarutils_tpu.ops.periodicity import spectral_search

    _, plane = fdmt_capture
    spec = plane.spectral_scores(1e-3, fmin=2.0)
    host = spectral_search(plane.to_host(), 1e-3, fmin=2.0)
    np.testing.assert_allclose(spec["freq"], host["freq"], rtol=1e-5)
    np.testing.assert_allclose(spec["power"], host["power"], rtol=1e-3)
    np.testing.assert_array_equal(spec["nharm"], host["nharm"])
    np.testing.assert_allclose(spec["sigma"], host["sigma"], rtol=1e-3)


def test_h_curve_per_shard_semantics(fdmt_capture):
    """The H curve equals the host computation applied per device shard
    (digitisation stats are per-shard — documented in sharded_plane)."""
    from pulsarutils_tpu.ops.rebin import quick_resample
    from pulsarutils_tpu.ops.robust import digitize, h_test_batch

    table, plane = fdmt_capture
    window = 2
    h, m = plane.h_curve(window=window)
    assert h.shape == (len(table["DM"]),)

    # reproduce shard-locally on host: same padded row blocks per device
    full = np.asarray(plane._plane)  # padded global plane
    n_dev = plane.mesh.shape[plane.axis]
    rows_max = full.shape[0] // n_dev
    t_r = full.shape[1] // window
    nmax = max(1, t_r // 10)
    h_ref = np.empty(full.shape[0])
    for d in range(n_dev):
        shard = quick_resample(full[d * rows_max:(d + 1) * rows_max], window)
        counts = np.maximum(digitize(shard), 0)
        hd, _ = h_test_batch(counts, nmax=nmax)
        h_ref[d * rows_max:(d + 1) * rows_max] = hd
    np.testing.assert_allclose(h, h_ref[plane.row_index], rtol=1e-4)


def test_decimated_image(fdmt_capture):
    _, plane = fdmt_capture
    img, factor = plane.decimated(max_bins=256)
    assert factor == plane.shape[1] // 256
    host = plane.to_host()
    ref = host[:, :256 * factor].reshape(host.shape[0], 256, factor).sum(2)
    np.testing.assert_allclose(img, ref, atol=1e-2)
    # no decimation needed when the plane is already small
    img1, f1 = plane.decimated(max_bins=1 << 20)
    assert f1 == 1 and img1.shape == plane.shape


def test_hybrid_capture_plan_grid(pulse_data):
    """Hybrid capture returns the coarse plane remapped to the plan grid
    (same convention as the single-device hybrid's capture)."""
    from pulsarutils_tpu.ops.search import nearest_rows

    mesh = make_mesh((4, 2), ("dm", "chan"))
    table, plane = sharded_hybrid_search(pulse_data, *ARGS, mesh=mesh,
                                        capture_plane=True)
    assert plane.shape[0] == len(table["DM"])
    # the captured plane is the coarse plane remapped to the plan grid:
    # reproduce the mapping on the host-gathered single-device coarse
    # plane over the SAME [dmmin, dmmax] coarse grid.  (The single-device
    # hybrid's own capture derives its coarse grid from min/max of the
    # plan grid instead, which can differ by one boundary row — both map
    # each plan row to its nearest coarse row.)
    t0, plane0 = dedispersion_search(pulse_data, *ARGS, backend="jax",
                                     kernel="fdmt", capture_plane=True)
    idx = nearest_rows(np.asarray(t0["DM"]), np.asarray(table["DM"]))
    np.testing.assert_allclose(plane.to_host(), np.asarray(plane0)[idx],
                               atol=1e-3)
    t1 = dedispersion_search(pulse_data, *ARGS, backend="jax",
                             kernel="hybrid")
    b = table.argbest()
    assert bool(table["exact"][b])
    assert np.isclose(table["DM"][b], t1["DM"][t1.argbest()])


def test_exact_sweep_plane_handle(pulse_data):
    """plane_handle=True on the exact sharded sweep: device-resident
    handle equals the host-gathered capture."""
    mesh = make_mesh((4, 2), ("dm", "chan"))
    t_host, plane_host = sharded_dedispersion_search(
        pulse_data, *ARGS, mesh=mesh, capture_plane=True)
    t_dev, handle = sharded_dedispersion_search(
        pulse_data, *ARGS, mesh=mesh, capture_plane=True,
        plane_handle=True)
    np.testing.assert_allclose(handle.to_host(), plane_host, atol=1e-4)
    np.testing.assert_array_equal(t_host["snr"], t_dev["snr"])


def test_period_search_plane_accepts_handle(pulse_data, fdmt_capture):
    """period_search_plane on the handle == on the gathered plane."""
    from pulsarutils_tpu.ops.periodicity import period_search_plane

    _, plane = fdmt_capture
    t = plane.shape[1]
    kw = dict(fmin=4.0 / (t * 1e-3), refine_top=1)
    res_mesh = period_search_plane(plane, 1e-3, **kw)
    res_host = period_search_plane(plane.to_host(), 1e-3, **kw)
    assert res_mesh["best_dm_index"] == res_host["best_dm_index"]
    # the handle's spectral stage runs float32 on device vs the host's
    # float64: the refine grid centre shifts by ~1e-7 relative, so the
    # refined H/sigma agree to ~1%, not bit-exactly
    np.testing.assert_allclose(res_mesh["best_freq"], res_host["best_freq"],
                               rtol=1e-5)
    np.testing.assert_allclose(res_mesh["best_sigma"],
                               res_host["best_sigma"], rtol=2e-2)


def test_diagnostic_figure_from_handle(pulse_data, fdmt_capture, tmp_path):
    """The 7-panel figure renders from the sharded handle (H curve and
    plane image shard-local) and backs the panels with the right data."""
    from pulsarutils_tpu.pipeline.diagnostics import plot_diagnostics
    from pulsarutils_tpu.pipeline.pulse_info import PulseInfo

    pytest.importorskip("matplotlib")
    table, plane = fdmt_capture
    info = PulseInfo(allprofs=pulse_data, start_freq=1400.0,
                     bandwidth=300.0, nbin=pulse_data.shape[1],
                     nchan=pulse_data.shape[0], t0=0.0,
                     pulse_freq=1.0 / (pulse_data.shape[1] * 1e-3))
    out = plot_diagnostics(info, table, plane,
                           outname=str(tmp_path / "mesh_diag.jpg"))
    import os

    assert os.path.getsize(out) > 0


def test_slice_delay_range_still_exact():
    """Regression guard: the capture refactor must not disturb the
    slice/stitch bookkeeping the row_index is built from."""
    slices = slice_delay_range(10, 30, 4)
    assert slices[0][0] == 10 and slices[-1][1] == 30
    covered = [n for lo, hi in slices for n in range(lo, hi + 1)]
    assert covered == list(range(10, 31))
