"""Cleaning ops: scipy-parity of smoothers, flagging, renormalisation,
FFT zap — NumPy and JAX paths."""
import numpy as np
import pytest
from scipy.ndimage import gaussian_filter1d, uniform_filter1d

from pulsarutils_tpu.models.simulate import inject_rfi, simulate_test_data
from pulsarutils_tpu.ops.clean_ops import (
    fft_zap_time,
    gaussian_filter_1d,
    get_noisier_channels,
    measure_channel_variability,
    renormalize_data,
    uniform_filter_1d,
)


def test_gaussian_filter_matches_scipy():
    rng = np.random.default_rng(0)
    x = rng.normal(size=500)
    for sigma in (1, 5, 21):
        ours = gaussian_filter_1d(x, sigma)
        scipys = gaussian_filter1d(x, sigma, mode="reflect")
        assert np.allclose(ours, scipys, atol=1e-8)


def test_gaussian_filter_radius_longer_than_array():
    x = np.random.default_rng(1).normal(size=50)
    ours = gaussian_filter_1d(x, 30)
    scipys = gaussian_filter1d(x, 30, mode="reflect")
    assert np.allclose(ours, scipys, atol=1e-8)


def test_uniform_filter_matches_scipy():
    rng = np.random.default_rng(2)
    x = rng.normal(size=300)
    for size in (1, 2, 4, 8, 16):
        ours = uniform_filter_1d(x, size)
        scipys = uniform_filter1d(x, size, mode="reflect")
        assert np.allclose(ours, scipys, atol=1e-10)


def test_smoothers_jax_match_numpy():
    import jax.numpy as jnp

    x = np.random.default_rng(3).normal(size=256)
    g_np = gaussian_filter_1d(x, 7)
    g_j = gaussian_filter_1d(jnp.asarray(x), 7, xp=jnp)
    assert np.allclose(np.asarray(g_j), g_np, atol=1e-5)
    u_np = uniform_filter_1d(x, 8)
    u_j = uniform_filter_1d(jnp.asarray(x), 8, xp=jnp)
    assert np.allclose(np.asarray(u_j), u_np, atol=1e-5)


@pytest.fixture()
def rfi_data():
    array, header = simulate_test_data(150, nchan=64, nsamples=2048, rng=4)
    bad = (7, 23, 42)
    contaminated = inject_rfi(array, bad_channels=bad, rng=5)
    return contaminated, bad


def test_get_noisier_channels_finds_injected(rfi_data):
    contaminated, bad = rfi_data
    mask = get_noisier_channels(contaminated)
    assert set(np.flatnonzero(mask)) >= set(bad)
    assert mask.sum() <= len(bad) + 3  # few false positives


def test_measure_channel_variability_finds_injected(rfi_data):
    contaminated, bad = rfi_data
    mask = measure_channel_variability(contaminated)
    assert set(np.flatnonzero(mask)) >= set(bad)


def test_measure_channel_variability_with_prior_mask(rfi_data):
    contaminated, bad = rfi_data
    prior = np.zeros(contaminated.shape[0], dtype=bool)
    prior[bad[0]] = True
    mask = measure_channel_variability(contaminated, prior)
    assert mask[bad[0]]  # prior survives
    assert set(np.flatnonzero(mask)) >= set(bad)


def test_renormalize_zeroes_bad_and_flattens(rfi_data):
    contaminated, bad = rfi_data
    mask = np.zeros(contaminated.shape[0], dtype=bool)
    mask[list(bad)] = True
    out = renormalize_data(contaminated, badchans_mask=mask)
    assert not np.any(out[list(bad), :])
    # good channels are fractional deviations around zero
    good = np.setdiff1d(np.arange(64), bad)
    assert abs(out[good].mean()) < 0.05


def test_renormalize_removes_baseline_drift():
    array, _ = simulate_test_data(0, nchan=32, nsamples=4096, signal=0.0,
                                  rng=6)
    drift = 1 + 0.5 * np.sin(np.linspace(0, 4 * np.pi, 4096))
    drifted = array * drift[None, :]
    out = renormalize_data(drifted)
    lc = out.mean(0)
    # baseline strongly flattened: the +-50% drift is reduced >5x (the
    # sigma-81 Gaussian can't perfectly track a period-2048 sinusoid, so a
    # few-percent residual is expected and matches the reference behaviour)
    from pulsarutils_tpu.ops.clean_ops import gaussian_filter_1d as gf
    assert np.abs(gf(lc, 50)).max() < 0.1


def test_renormalize_cut_outliers_all_windows():
    array, _ = simulate_test_data(0, nchan=32, nsamples=4096, signal=0.0,
                                  noise=0.1, rng=7)
    # broadband spike wide enough for small windows only
    array[:, 1000:1002] += 50.0
    out = renormalize_data(array, cut_outliers=True)
    assert not np.any(out[:, 1000:1002])


def test_renormalize_jax_matches_numpy(rfi_data):
    import jax.numpy as jnp

    contaminated, bad = rfi_data
    mask = np.zeros(contaminated.shape[0], dtype=bool)
    mask[list(bad)] = True
    out_np = renormalize_data(contaminated, badchans_mask=mask,
                              cut_outliers=True)
    out_j = renormalize_data(jnp.asarray(contaminated),
                             badchans_mask=jnp.asarray(mask),
                             cut_outliers=True, xp=jnp)
    assert np.allclose(np.asarray(out_j), out_np, atol=1e-4)


def test_renormalize_jit_compiles(rfi_data):
    import jax
    import jax.numpy as jnp

    contaminated, bad = rfi_data
    mask = np.zeros(contaminated.shape[0], dtype=bool)

    fn = jax.jit(lambda a, m: renormalize_data(a, badchans_mask=m, xp=jnp))
    out = fn(jnp.asarray(contaminated), jnp.asarray(mask))
    ref = renormalize_data(contaminated, badchans_mask=mask)
    assert np.allclose(np.asarray(out), ref, atol=1e-4)


def test_fft_zap_removes_periodic_rfi():
    rng = np.random.default_rng(8)
    array, header = simulate_test_data(150, nchan=32, nsamples=4096,
                                       rng=9)
    t = np.arange(4096)
    mains = 2.0 * np.sin(2 * np.pi * t / 64)  # strong periodic broadband
    contaminated = array + mains[None, :]
    cleaned, zap = fft_zap_time(contaminated)
    assert zap.sum() >= 1
    k = 4096 // 64
    assert zap[k]  # the injected tone's bin is zapped
    # the tone is gone: power at that frequency drops by >100x
    power = np.abs(np.fft.rfft(cleaned.mean(0)))
    power_dirty = np.abs(np.fft.rfft(contaminated.mean(0)))
    assert power[k] < power_dirty[k] / 100


def test_fft_zap_jax_matches_numpy():
    import jax.numpy as jnp

    rng = np.random.default_rng(10)
    array = rng.normal(size=(16, 1024))
    array += np.sin(2 * np.pi * np.arange(1024) / 32)[None, :] * 3
    c_np, z_np = fft_zap_time(array)
    c_j, z_j = fft_zap_time(jnp.asarray(array), xp=jnp)
    assert np.array_equal(np.asarray(z_j), z_np)
    assert np.allclose(np.asarray(c_j), c_np, atol=1e-3)


def test_zero_dm_filter_removes_broadband_keeps_dispersed():
    import jax.numpy as jnp

    from pulsarutils_tpu.models.simulate import disperse_array
    from pulsarutils_tpu.ops.clean_ops import zero_dm_filter

    rng = np.random.default_rng(29)
    nchan, t = 32, 2048
    noise = rng.normal(0, 0.1, (nchan, t))
    # broadband un-dispersed spike + a dispersed pulse
    rfi = np.zeros((nchan, t))
    rfi[:, 500] = 10.0
    pulse = np.zeros((nchan, t))
    pulse[:, 1200] = 5.0
    pulse = disperse_array(pulse, 150, 1200.0, 200.0, 0.0005)
    data = noise + rfi + pulse

    out = zero_dm_filter(data)
    # the un-dispersed spike column is cancelled to noise level
    assert np.abs(out[:, 500]).max() < 1.0
    # the dispersed pulse loses only ~1/nchan of its power
    peak_per_chan = out[pulse > 4.0]
    assert (peak_per_chan > 4.0).all()

    # bad channels pass through untouched; jax path matches numpy
    mask = np.zeros(nchan, dtype=bool)
    mask[3] = True
    out_m = zero_dm_filter(data, badchans_mask=mask)
    assert np.array_equal(out_m[3], data[3])
    out_j = np.asarray(zero_dm_filter(jnp.asarray(data.astype(np.float32)),
                                      badchans_mask=jnp.asarray(mask),
                                      xp=jnp))
    assert np.allclose(out_j, out_m, atol=1e-3)
