import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
