import numpy as np
import pytest


@pytest.fixture(scope="session", autouse=True)
def _hermetic_tune_cache(tmp_path_factory):
    """Point the kernel-autotune cache at a per-session temp file: a
    developer's ~/.cache tune entries must never steer test kernel
    selection (byte-identity comparisons would diverge per machine),
    and tests must never write the user's cache."""
    import os

    prev = os.environ.get("PUTPU_TUNE_CACHE")
    os.environ["PUTPU_TUNE_CACHE"] = str(
        tmp_path_factory.mktemp("tune") / "tune_cache.json")
    yield
    if prev is None:
        os.environ.pop("PUTPU_TUNE_CACHE", None)
    else:
        os.environ["PUTPU_TUNE_CACHE"] = prev


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
