"""Dedispersion kernel semantics: roll conventions, NumPy vs JAX parity."""
import numpy as np

from pulsarutils_tpu.ops.dedisperse import (
    apply_dm_shifts_to_data,
    dedisperse,
    dedisperse_batch_numpy,
    dedisperse_block_chunked_jax,
    dedisperse_block_jax,
    roll_and_sum,
)
from pulsarutils_tpu.ops.plan import (
    dedispersion_shifts,
    dedispersion_shifts_batch,
    normalize_shifts,
)
from pulsarutils_tpu.models.simulate import disperse_array


def test_roll_and_sum_doctest():
    array = np.arange(10)
    sum_array = np.zeros(10)
    assert np.allclose(roll_and_sum(array, sum_array, 3), np.roll(array, 3))
    sum_array = np.zeros(10)
    assert sum_array is roll_and_sum(array, sum_array, 3)


def test_roll_and_sum_out_of_range_n():
    # the slice-add form must agree with np.roll for negative and
    # wrapped-past-length shifts, and accumulate (not overwrite)
    array = np.arange(11.0)
    for n in (-3, -11, 0, 11, 14, 25):
        acc = np.ones(11)
        roll_and_sum(array, acc, n)
        assert np.allclose(acc, 1.0 + np.roll(array, n)), n


def test_batch_numpy_out_param():
    rng = np.random.default_rng(7)
    data = rng.normal(size=(8, 100))  # non-power-of-two T exercises wraps
    dms = np.linspace(50, 150, 5)
    shifts = dedispersion_shifts_batch(dms, 8, 1200., 200., 0.0005)
    out = np.full((5, 100), 1e9)  # stale contents must be overwritten
    got = dedisperse_batch_numpy(data, shifts, out=out)
    assert got is out
    assert np.allclose(out, dedisperse_batch_numpy(data, shifts))


def test_dedisperse_undoes_simulated_dispersion():
    rng = np.random.default_rng(1)
    nchan, t = 16, 256
    clean = np.zeros((nchan, t))
    clean[:, 100] = 5.0
    shifts = dedispersion_shifts(nchan, 120, 1200., 200., 0.0005)
    dispersed = disperse_array(clean, 120, 1200., 200., 0.0005)
    dd = dedisperse(dispersed, shifts)
    assert np.argmax(dd) == 100
    assert np.isclose(dd[100], 5.0 * nchan)


def test_dedisperse_matches_explicit_rolls():
    rng = np.random.default_rng(2)
    data = rng.normal(size=(8, 64))
    shifts = np.array([3, -5, 0, 17, 64, 65, -64, -1], dtype=float)
    # direct: dedisperse rolls each channel by -shift (normalised) and sums
    expected = sum(np.roll(data[i], -int(shifts[i])) for i in range(8))
    got = dedisperse(data, shifts)
    assert np.allclose(got, expected)


def test_batch_numpy_matches_single():
    rng = np.random.default_rng(3)
    data = rng.normal(size=(16, 128))
    dms = np.linspace(50, 150, 11)
    shifts = dedispersion_shifts_batch(dms, 16, 1200., 200., 0.0005)
    plane = dedisperse_batch_numpy(data, shifts)
    for i in [0, 5, 10]:
        assert np.allclose(plane[i], dedisperse(data, shifts[i]))


def test_jax_block_matches_numpy():
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    data = rng.normal(size=(16, 128)).astype(np.float32)
    dms = np.linspace(50, 150, 12)
    shifts = dedispersion_shifts_batch(dms, 16, 1200., 200., 0.0005)
    plane_np = dedisperse_batch_numpy(data.astype(np.float64), shifts)

    offsets = normalize_shifts(shifts, 128)
    plane_j = dedisperse_block_jax(jnp.asarray(data), jnp.asarray(offsets))
    assert np.allclose(np.asarray(plane_j), plane_np, atol=1e-4)

    plane_j2 = dedisperse_block_chunked_jax(
        jnp.asarray(data), jnp.asarray(offsets), chan_block=4)
    assert np.allclose(np.asarray(plane_j2), plane_np, atol=1e-4)


def test_apply_dm_shifts_to_data():
    rng = np.random.default_rng(5)
    data = rng.normal(size=(6, 32))
    shifts = np.array([1., 2., -3., 0., 31., 33.])
    out = apply_dm_shifts_to_data(data, shifts)
    for i in range(6):
        assert np.allclose(out[i], np.roll(data[i], -int(round(shifts[i]))))


def test_apply_dm_shifts_jax_matches():
    import jax.numpy as jnp

    rng = np.random.default_rng(6)
    data = rng.normal(size=(6, 32)).astype(np.float32)
    shifts = np.array([1., 2., -3., 0., 31., 33.])
    out_np = apply_dm_shifts_to_data(data, shifts)
    out_j = apply_dm_shifts_to_data(jnp.asarray(data), jnp.asarray(shifts),
                                    xp=jnp)
    assert np.allclose(np.asarray(out_j), out_np)
