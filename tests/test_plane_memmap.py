"""capture_plane="memmap": disk-backed plane spill (VERDICT r3 #7).

Parity with the reference's memmap capture
(``/root/reference/pulsarutils/dedispersion.py:215-218``): the plane
lands on disk, host RAM holds one superblock at a time, and downstream
consumers (diagnostics, the plane period search) operate on the memmap
exactly as on an in-memory plane.
"""

import os

import numpy as np
import pytest

from pulsarutils_tpu.ops.search import (
    PALLAS_SUPERBLOCK,
    dedispersion_search,
    plane_memmap,
)

GARGS = (1200.0, 200.0, 0.0005)


def make_data(nchan=32, t=2048, seed=0):
    rng = np.random.default_rng(seed)
    return (np.abs(rng.standard_normal((nchan, t))) * 0.5).astype(np.float32)


def test_plane_memmap_helper(tmp_path):
    mm = plane_memmap(8, 64, directory=str(tmp_path))
    assert isinstance(mm, np.memmap) and mm.shape == (8, 64)
    mm[:] = 7.0
    mm.flush()
    # a valid .npy: reopenable without this package
    back = np.load(mm.filename, mmap_mode="r")
    assert back.shape == (8, 64) and float(back[3, 3]) == 7.0
    os.unlink(mm.filename)


def test_numpy_backend_memmap_matches_dense(tmp_path, monkeypatch):
    monkeypatch.setenv("PUTPU_PLANE_DIR", str(tmp_path))
    data = make_data()
    table, dense = dedispersion_search(data, 100.0, 200.0, *GARGS,
                                       capture_plane=True)
    table_m, mm = dedispersion_search(data, 100.0, 200.0, *GARGS,
                                      capture_plane="memmap")
    assert isinstance(mm, np.memmap)
    assert os.path.dirname(mm.filename) == str(tmp_path)
    np.testing.assert_allclose(np.asarray(mm), dense, rtol=1e-6, atol=1e-5)
    np.testing.assert_array_equal(table_m["snr"], table["snr"])
    os.unlink(mm.filename)


def test_pallas_path_memmap_matches_dense(tmp_path, monkeypatch):
    monkeypatch.setenv("PUTPU_PLANE_DIR", str(tmp_path))
    data = make_data(nchan=16, t=1024)
    table, dense = dedispersion_search(data, 100.0, 160.0, *GARGS,
                                       backend="jax", kernel="pallas",
                                       capture_plane=True)
    table_m, mm = dedispersion_search(data, 100.0, 160.0, *GARGS,
                                      backend="jax", kernel="pallas",
                                      capture_plane="memmap")
    assert isinstance(mm, np.memmap)
    np.testing.assert_array_equal(np.asarray(mm), np.asarray(dense))
    np.testing.assert_array_equal(table_m["snr"], table["snr"])
    os.unlink(mm.filename)


def test_memmap_spans_superblocks(tmp_path, monkeypatch):
    """More trials than one superblock: every block lands in the file."""
    monkeypatch.setenv("PUTPU_PLANE_DIR", str(tmp_path))
    monkeypatch.setattr("pulsarutils_tpu.ops.search.PALLAS_SUPERBLOCK", 8)
    data = make_data(nchan=16, t=1024)
    table, mm = dedispersion_search(data, 100.0, 200.0, *GARGS,
                                    backend="jax", kernel="pallas",
                                    capture_plane="memmap")
    assert PALLAS_SUPERBLOCK == 512  # module constant untouched for real
    assert table.nrows > 8 and mm.shape[0] == table.nrows
    # no row left unwritten (all-zero rows would betray a skipped block)
    assert (np.abs(np.asarray(mm)).sum(axis=1) > 0).all()
    os.unlink(mm.filename)


def test_downstream_consumers_accept_memmap(tmp_path, monkeypatch):
    """The period search (and any np-consuming diagnostic) runs on the
    memmap plane unchanged — the reference's show-at-any-size property."""
    monkeypatch.setenv("PUTPU_PLANE_DIR", str(tmp_path))
    from pulsarutils_tpu.ops.periodicity import period_search_plane

    data = make_data(nchan=16, t=2048, seed=3)
    _, mm = dedispersion_search(data, 100.0, 160.0, *GARGS,
                                capture_plane="memmap")
    res = period_search_plane(np.asarray(mm), GARGS[2],
                              fmin=4.0 / (mm.shape[1] * GARGS[2]))
    assert np.isfinite(res["best_sigma"])
    os.unlink(mm.filename)


@pytest.mark.parametrize("kwargs", [
    dict(backend="jax", kernel="fdmt"),
    dict(backend="jax", kernel="hybrid"),
    dict(backend="jax", kernel="fourier"),
    dict(backend="jax", kernel="gather"),
])
def test_whole_plane_kernels_reject_memmap(kwargs):
    data = make_data(nchan=16, t=1024)
    with pytest.raises(ValueError, match="memmap"):
        dedispersion_search(data, 100.0, 160.0, *GARGS,
                            capture_plane="memmap", **kwargs)
