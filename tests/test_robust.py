"""Robust stats: MAD/medfilt vs scipy, H-test / Z^2_n sanity + jit parity."""
import numpy as np
import pytest
from scipy.signal import medfilt

from pulsarutils_tpu.ops.robust import (
    MAD_SCALE,
    digitize,
    h_test,
    h_test_batch,
    mad,
    median_filter_1d,
    ref_mad,
    z_n_test,
)


def test_mad_gaussian_estimates_sigma():
    rng = np.random.default_rng(0)
    x = rng.normal(0, 3.0, 100000)
    assert mad(x) == pytest.approx(3.0, rel=0.02)


def test_mad_matches_definition():
    x = np.array([1.0, 2.0, 3.0, 100.0])
    med = np.median(x)
    assert mad(x) == pytest.approx(np.median(np.abs(x - med)) / MAD_SCALE)


def test_mad_axis():
    x = np.arange(12.0).reshape(3, 4)
    per_row = mad(x, axis=1)
    assert per_row.shape == (3,)
    assert per_row[0] == pytest.approx(mad(x[0]))


def test_ref_mad_ignores_smooth_trend():
    rng = np.random.default_rng(1)
    t = np.linspace(0, 1, 10000)
    x = 100 * np.sin(2 * np.pi * t) + rng.normal(0, 0.5, t.size)
    # direct MAD is dominated by the trend; ref_mad recovers the noise
    assert ref_mad(x) == pytest.approx(0.5, rel=0.1)
    assert mad(x) > 10


def test_ref_mad_window_minimum():
    rng = np.random.default_rng(2)
    x = rng.normal(0, 1.0, 4000)
    x[2000:] += rng.normal(0, 20.0, 2000)  # second half much noisier
    windowed = ref_mad(x, window=500)
    assert windowed == pytest.approx(1.0, rel=0.25)


def test_median_filter_matches_scipy():
    rng = np.random.default_rng(3)
    x = rng.normal(size=101)
    for size in (3, 5, 11):
        assert np.allclose(median_filter_1d(x, size), medfilt(x, size))


def test_median_filter_jax_matches():
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    x = rng.normal(size=64)
    out = median_filter_1d(jnp.asarray(x), 11, xp=jnp)
    assert np.allclose(np.asarray(out), medfilt(x, 11), atol=1e-6)


def _pulsed_profile(nbin=64, counts=5000, width=0.05, rng=None):
    rng = np.random.default_rng(rng)
    phases = rng.normal(0.3, width, counts) % 1.0
    prof, _ = np.histogram(phases, bins=nbin, range=(0, 1))
    return prof


def test_h_test_detects_pulse():
    prof = _pulsed_profile(rng=5)
    h, m = h_test(prof)
    assert h > 50  # decisively periodic
    flat = np.full(64, 5000 // 64)
    h_flat, _ = h_test(flat)
    assert h_flat < 10


def test_h_test_flat_noise_calibration():
    # for pure Poisson noise H should be small on average (E[H] ~ 2.5)
    rng = np.random.default_rng(6)
    hs = []
    for _ in range(50):
        prof = rng.poisson(100, 64)
        hs.append(h_test(prof)[0])
    assert np.mean(hs) < 10


def test_h_test_batch_matches_scalar():
    rng = np.random.default_rng(7)
    profs = np.stack([_pulsed_profile(rng=10 + i) for i in range(4)] +
                     [rng.poisson(100, 64)])
    h_b, m_b = h_test_batch(profs)
    for i in range(profs.shape[0]):
        h_s, m_s = h_test(profs[i])
        assert h_b[i] == pytest.approx(h_s)
        assert m_b[i] == m_s


def test_h_test_jax_matches_numpy():
    import jax.numpy as jnp

    prof = _pulsed_profile(rng=8)
    h_np, m_np = h_test(prof)
    h_j, m_j = h_test(jnp.asarray(prof), xp=jnp)
    assert float(h_j) == pytest.approx(float(h_np), rel=1e-4)
    assert int(m_j) == m_np


def test_z_n_test_positive_and_increasing_info():
    prof = _pulsed_profile(rng=9)
    z2 = z_n_test(prof, 2)
    z8 = z_n_test(prof, 8)
    assert z2 > 0
    assert z8 >= z2  # harmonics only add power


def test_digitize():
    rng = np.random.default_rng(10)
    x = rng.normal(100, 5, (8, 256))
    d = digitize(x)
    assert d.dtype == np.int32
    assert d.min() == 0
    # median maps to 0, +1 MAD-sigma maps to ~3
    assert np.median(d) == 0
    ints = np.arange(10)
    assert digitize(ints) is ints  # integer passthrough


def test_digitize_jax():
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    x = rng.normal(0, 1, (4, 64))
    d_np = digitize(x)
    d_j = digitize(jnp.asarray(x), xp=jnp)
    assert np.array_equal(np.asarray(d_j), d_np)


def test_digitize_integer_passthrough_jax():
    import jax.numpy as jnp

    ints = jnp.arange(10)
    out = digitize(ints, xp=jnp)
    assert np.array_equal(np.asarray(out), np.arange(10))


def test_z_n_test_rejects_unresolvable_harmonics():
    prof = np.ones(16)
    with pytest.raises(ValueError, match="harmonics"):
        z_n_test(prof, 10)
