"""Candidate sifting: duplicate detections collapse to one candidate."""
import numpy as np

from pulsarutils_tpu.pipeline.sift import sift_candidates, sift_hits


def test_sift_candidates_groups_by_radius():
    cands = [
        {"time": 1.00, "dm": 150.0, "snr": 8.0},
        {"time": 1.01, "dm": 151.0, "snr": 12.0},  # same event, higher S/N
        {"time": 5.00, "dm": 150.5, "snr": 7.0},   # same DM, far in time
        {"time": 1.00, "dm": 400.0, "snr": 9.0},   # same time, far in DM
    ]
    kept = sift_candidates(cands, time_radius=0.1, dm_radius=5.0)
    assert len(kept) == 3
    assert kept[0]["snr"] == 12.0 and kept[0]["n_members"] == 2
    assert sorted(k["snr"] for k in kept) == [7.0, 9.0, 12.0]


def test_sift_per_group_dm_radius():
    # a single high-DM candidate must NOT inflate the merge radius of
    # low-DM groups: two distinct low-DM events 8 DM units apart stay
    # separate even with a DM-2000 candidate in the list (the old global
    # radius 0.02 * 2000 + 1 = 41 would wrongly merge them)
    cands = [
        {"time": 1.00, "dm": 100.0, "snr": 9.0},
        {"time": 1.01, "dm": 108.0, "snr": 8.0},   # distinct low-DM event
        {"time": 50.0, "dm": 2000.0, "snr": 12.0},
    ]
    kept = sift_candidates(cands, time_radius=0.1)
    assert len(kept) == 3
    # but trial-grid neighbours of one event still merge
    cands[1]["dm"] = 101.5
    kept = sift_candidates(cands, time_radius=0.1)
    assert len(kept) == 2
    assert kept[1]["n_members"] == 2


def test_sift_candidates_descending_snr_and_empty():
    assert sift_candidates([], 1.0, 1.0) == []
    cands = [{"time": t, "dm": 100.0, "snr": s}
             for t, s in [(0.0, 5.0), (10.0, 9.0), (20.0, 7.0)]]
    kept = sift_candidates(cands, time_radius=1.0, dm_radius=1.0)
    assert [k["snr"] for k in kept] == [9.0, 7.0, 5.0]


def test_sift_hits_collapses_overlap_duplicates(tmp_path):
    # a single strong pulse is detected in both 50%-overlapping chunks
    # that contain it; sifting must merge them into one candidate at the
    # right arrival time and DM
    from pulsarutils_tpu.models.simulate import simulate_test_data
    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks

    array, header = simulate_test_data(150, nchan=64, nsamples=16384,
                                       signal=2.0, noise=0.4, rng=5)
    path = str(tmp_path / "pulse.fil")
    write_simulated_filterbank(path, array, header)
    hits, _ = search_by_chunks(path, dmmin=100, dmmax=200, backend="numpy",
                               make_plots=False, resume=False,
                               progress=False,
                               output_dir=str(tmp_path / "out"))
    assert len(hits) >= 2  # duplicate detections from the overlap

    sifted = sift_hits(hits)
    assert len(sifted) == 1
    best = sifted[0]
    assert best["n_members"] == len(hits)
    assert abs(best["dm"] - 150) <= 2.0
    # pulse injected at nsamples // 2
    t_true = (16384 // 2) * header["tsamp"]
    assert abs(best["time"] - t_true) <= 0.05


def test_sift_keeps_distinct_pulses_within_one_chunk_span():
    # two REAL pulses minutes apart (well within one survey chunk span)
    # must stay separate candidates when arrival times are exact — the
    # round-5 rehearsal lost a pulse to the old chunk-scale radius
    from pulsarutils_tpu.pipeline.sift import sift_candidates, sift_hits

    span = 524.0  # survey chunk span, seconds
    cands = []
    for t, dm, snr in ((3035.96, 394.9, 27.1), (3035.96, 394.9, 27.0),
                       (3590.62, 394.2, 21.1), (3590.62, 394.2, 21.0)):
        cands.append({"time": t, "dm": dm, "snr": snr, "width": 2e-3,
                      "span": span, "time_approx": False})
    # exact-time default radius: width-scale, so the two pulses survive
    radius = max(0.5, 4.0 * max(c["width"] for c in cands))
    kept = sift_candidates(cands, radius)
    assert len(kept) == 2
    times = sorted(round(k["time"], 2) for k in kept)
    assert times == [3035.96, 3590.62]
    assert all(k["n_members"] == 2 for k in kept)

    # and sift_hits picks that radius when no hit is time-approximate
    class _T:
        colnames = ("DM", "snr", "rebin", "peak")

        def __init__(self, dm, snr, peak):
            self._r = {"DM": dm, "snr": snr, "rebin": 2, "peak": peak}

        def best_row(self):
            return self._r

        def __getitem__(self, k):
            return self._r[k]

    class _I:
        nbin = 524288
        pulse_freq = 1.0 / 524.288  # tsamp 1e-3

        def __init__(self, t0):
            self.t0 = t0

    hits = [(0, 10, _I(3000.0), _T(394.9, 27.1, 35960)),
            (5, 15, _I(3000.0), _T(394.2, 21.1, 590620))]
    sifted = sift_hits(hits)
    assert len(sifted) == 2


def test_pucands_lists_and_exports(tmp_path):
    # end to end: search -> store -> PUcands listing + CSV export
    import csv
    import os

    from pulsarutils_tpu.models.simulate import simulate_test_data
    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.pipeline.search_pipeline import search_by_chunks
    from pulsarutils_tpu.cli.cands_main import main as cands_main

    array, header = simulate_test_data(150, nchan=64, nsamples=16384,
                                       signal=2.0, noise=0.4, rng=5)
    path = str(tmp_path / "pulse.fil")
    write_simulated_filterbank(path, array, header)
    out = str(tmp_path / "out")
    hits, _ = search_by_chunks(path, dmmin=100, dmmax=200, backend="numpy",
                               make_plots=False, resume=False,
                               progress=False, output_dir=out)
    assert hits

    csv_path = str(tmp_path / "cands.csv")
    assert cands_main([out, "--csv", csv_path]) == 0
    with open(csv_path) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 1  # sifted to the single injected pulse
    assert abs(float(rows[0]["dm"]) - 150) <= 2.0
    assert int(rows[0]["n_members"]) == len(hits)

    # CSV rows carry the source-file root
    assert rows[0]["file"] == "pulse"

    # raw listing + S/N floor
    assert cands_main([out, "--no-sift", "--min-snr", "1e9"]) == 0

    # a nonexistent directory is an error, not a silently created dir
    missing = str(tmp_path / "nope")
    assert cands_main([missing]) == 1
    assert not os.path.exists(missing)


def test_sift_per_pair_width_radius():
    # round 6 (ADVICE r5): one wide rebin=8 candidate must not inflate
    # the merge radius of unrelated narrow pulses.  Two narrow pulses
    # 2 s apart stay separate (pair radius = 0.5 s floor) even though
    # the wide candidate's width would have set a 16 s GLOBAL radius —
    # while the wide pulse still absorbs its own duplicate 3 s away.
    cands = [
        {"time": 100.0, "dm": 150.0, "snr": 9.0, "width": 2e-3},
        {"time": 102.0, "dm": 150.5, "snr": 8.0, "width": 2e-3},
        {"time": 500.0, "dm": 300.0, "snr": 12.0, "width": 4.0},
        {"time": 503.0, "dm": 300.5, "snr": 11.0, "width": 4.0},
    ]
    kept = sift_candidates(cands, time_radius="pair-width")
    assert len(kept) == 3
    times = sorted(round(k["time"], 1) for k in kept)
    assert times == [100.0, 102.0, 500.0]
    wide = [k for k in kept if k["time"] == 500.0][0]
    assert wide["n_members"] == 2

    # the old global radius (4 x widest = 16 s) would have merged the
    # two narrow pulses into one
    kept_global = sift_candidates(cands, time_radius=16.0)
    assert len(kept_global) == 2

    # candidates without widths fall back to the 0.5 s floor
    bare = [{"time": 0.0, "dm": 10.0, "snr": 5.0},
            {"time": 0.4, "dm": 10.0, "snr": 4.0}]
    assert len(sift_candidates(bare, time_radius="pair-width")) == 1
