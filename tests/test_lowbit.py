"""1/2/4-bit filterbank support: native C unpacker vs numpy oracle,
file round trips, and DM recovery through a quantised file."""
import numpy as np
import pytest

from pulsarutils_tpu.io import lowbit
from pulsarutils_tpu.io.sigproc import FilterbankReader, write_filterbank


@pytest.mark.parametrize("nbits", [1, 2, 4])
def test_pack_unpack_numpy_round_trip(nbits, rng):
    maxval = (1 << nbits) - 1
    values = rng.integers(0, maxval + 1, size=512).astype(np.float32)
    packed = lowbit.pack_numpy(values, nbits)
    assert packed.dtype == np.uint8
    assert packed.size == values.size * nbits // 8
    out = lowbit.unpack_numpy(packed, nbits)
    assert np.array_equal(out, values)


@pytest.mark.parametrize("nbits", [1, 2, 4])
def test_native_matches_numpy(nbits, rng):
    if not lowbit.native_available():
        pytest.skip("native unpacker did not build")
    values = rng.integers(0, (1 << nbits), size=4096).astype(np.float32)
    p_np = lowbit.pack_numpy(values, nbits)
    p_c = lowbit.pack(values, nbits)
    assert np.array_equal(p_np, p_c)
    assert np.array_equal(lowbit.unpack_numpy(p_c, nbits),
                          lowbit.unpack(p_c, nbits))


def test_pack_clips_out_of_range():
    vals = np.array([-3.0, 0.0, 1.4, 1.6, 99.0, 3.0, 2.0, 1.0],
                    dtype=np.float32)
    out = lowbit.unpack_numpy(lowbit.pack_numpy(vals, 2), 2)
    assert np.array_equal(out, [0, 0, 1, 2, 3, 3, 2, 1])


@pytest.mark.parametrize("nbits", [1, 2, 4])
def test_filterbank_lowbit_round_trip(tmp_path, rng, nbits):
    nchan, nsamp = 16, 64
    maxval = (1 << nbits) - 1
    data = rng.integers(0, maxval + 1, size=(nchan, nsamp)).astype(float)
    path = str(tmp_path / f"lb{nbits}.fil")
    write_filterbank(path, data, tsamp=1e-3, fch1=1400.0, foff=-1.0,
                     nbits=nbits)
    r = FilterbankReader(path)
    assert r.header["nbits"] == nbits
    assert r.nsamples == nsamp
    block = r.read_block(0, nsamp)
    assert np.array_equal(block, data)
    # partial read from an offset
    assert np.array_equal(r.read_block(10, 7), data[:, 10:17])


def test_search_through_2bit_file(tmp_path):
    # quantise a simulated dispersed pulse to 2 bits and recover the DM
    from pulsarutils_tpu.models.simulate import simulate_test_data
    from pulsarutils_tpu.io.sigproc import write_simulated_filterbank
    from pulsarutils_tpu.ops.search import dedispersion_search

    array, header = simulate_test_data(150, nchan=64, nsamples=4096,
                                       signal=3.0, noise=0.4, rng=11)
    # scale to use the 0..3 range
    q = np.clip(np.rint(array / array.max() * 3), 0, 3)
    path = str(tmp_path / "q2.fil")
    write_simulated_filterbank(path, q, header, nbits=2)
    r = FilterbankReader(path)
    block = r.read_block(0, r.nsamples, band_ascending=True)
    table = dedispersion_search(block, 100, 200.0, header["fbottom"],
                                header["bandwidth"], header["tsamp"],
                                backend="numpy")
    assert abs(table.best_row()["DM"] - 150) <= 2.0


def test_native_pack_half_values_match_numpy():
    # exact halves round half-to-even in BOTH paths (np.rint semantics)
    if not lowbit.native_available():
        pytest.skip("native unpacker did not build")
    vals = np.array([0.5, 1.5, 2.5, 3.5, -0.5, 0.0, 1.0, 2.0],
                    dtype=np.float32)
    assert np.array_equal(lowbit.pack(vals, 2), lowbit.pack_numpy(vals, 2))
    assert np.array_equal(lowbit.pack(vals, 4), lowbit.pack_numpy(vals, 4))
    assert np.array_equal(lowbit.pack(vals, 1), lowbit.pack_numpy(vals, 1))
